"""Whole-graph fusion — one XLA program per predictor (ROADMAP item 5).

Every non-trivial inference graph used to interpret node-by-node: an
N-node MODEL/TRANSFORMER/COMBINER chain paid N eager unit dispatches, N
pad-bucket decisions and N device->host->device round-trips per request.
This module is the compiler pass that closes that gap, in the spirit of
full-program compilation (arxiv 1810.09868): walk a validated
:class:`~seldon_core_tpu.graph.spec.PredictorSpec`, decide *fusion
eligibility* per subtree, and emit ONE jitted callable per (graph,
input-shape bucket):

  * MODEL/TRANSFORMER chains compose functionally — intermediates stay
    in device registers/HBM, never round-tripping through host memory;
  * COMBINER fan-outs become stacked in-program reductions XLA is free
    to fuse;
  * host ROUTERs lower to ``lax.switch`` with the autopilot's predicted
    per-branch costs (arxiv 2008.01040) threaded in as **runtime
    arguments**, so cost-aware branch demotion — previously a
    host-ROUTER-only feature (graph/interpreter.py ``_autopilot_branch``)
    — now runs *inside* the compiled program without retracing when the
    learned costs move;
  * the request tensor's device buffer is donated to the program on
    TPU/GPU backends (``SELDON_TPU_FUSE_DONATE``), so even the program's
    input does not survive as a second copy.

Three layers consume this pass:

  * **Full fusion** (``runtime/engine.py``): a fully-eligible graph
    serves through :class:`FusedGraph` (engine mode ``fused``) — a
    drop-in :class:`~seldon_core_tpu.graph.compiled.CompiledGraph`
    with the cost-threaded program, per-shape AOT warmup through the
    same compile-cache plumbing, and a per-node *phase decomposition*
    stamped onto the fused dispatch hotrecord (utils/hotrecord.py) so
    one record still explains where the program's time goes.
  * **Partial fusion** (``graph/interpreter.py``): graphs with a
    remote/rest-bound leaf, a ``quorum``/``fallback`` degradation
    policy, or an impure unit keep those subtrees on the host
    interpreter, while every *maximal eligible subtree* (>= 2 nodes)
    collapses into a :class:`FusedSubtreeRuntime` — the interpreter
    recursion stops at the fused root and pays one device dispatch for
    the whole subtree.
  * **Kill switch**: ``SELDON_TPU_GRAPH_FUSE=0`` disables the pass
    entirely and restores the pre-fusion dispatch bit-for-bit (the
    interpreter for any graph the interpreter served; the legacy
    compiled executor elsewhere).

Equivalence is pinned against the interpreter per graph shape
(tests/test_graph_fusion.py): per-unit PRNG keys derive from the unit's
NAME exactly as the interpreter derives them (``unit_rngs`` crc32 fold —
the PR-8 sharding discipline), so fusion is never a numerics change.
"""

from __future__ import annotations

import logging
import math
import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from seldon_core_tpu.messages import Meta, SeldonMessage
from seldon_core_tpu.graph.compiled import (
    NOT_ROUTED,
    CompiledGraph,
    _set_state,
)
from seldon_core_tpu.graph.interpreter import (
    effective_type,
    methods_for,
    pythonize_tags,
)
from seldon_core_tpu.graph.spec import (
    ComponentBinding,
    GraphSpecError,
    PredictiveUnit,
    PredictorSpec,
    UnitImplementation,
    UnitMethod,
    UnitType,
)
from seldon_core_tpu.graph.units import UNIT_REGISTRY, normalize_output

__all__ = [
    "fuse_enabled",
    "FUSE_ANNOTATION",
    "FusionPlan",
    "plan_fusion",
    "FusedGraph",
    "FusedSubtreeRuntime",
    "build_partial_fusion",
]

logger = logging.getLogger(__name__)

#: predictor annotation opting one deployment out of fusion without the
#: process-wide env kill switch (spec.annotations / metadata.annotations)
FUSE_ANNOTATION = "seldon.io/graph-fuse"


def fuse_enabled() -> bool:
    """Kill switch: ``SELDON_TPU_GRAPH_FUSE=0`` disables the fusion pass
    and restores the pre-fusion dispatch bit-for-bit (host interpreter
    for any graph the interpreter served; the legacy compiled executor
    for fully in-process pure graphs)."""
    return os.environ.get("SELDON_TPU_GRAPH_FUSE", "1") != "0"


def _donate_enabled() -> bool:
    """Buffer donation for the request tensor: on by default where XLA
    honours it (TPU/GPU), off on CPU (donation is a no-op there and jax
    warns).  ``SELDON_TPU_FUSE_DONATE=1|0`` overrides either way."""
    env = os.environ.get("SELDON_TPU_FUSE_DONATE", "")
    if env:
        return env != "0"
    try:
        return jax.default_backend() in ("tpu", "gpu", "cuda", "rocm")
    except Exception:  # noqa: BLE001 - no backend: no donation
        return False


# ---------------------------------------------------------------------------
# Fusion planning
# ---------------------------------------------------------------------------


@dataclass
class FusionPlan:
    """Per-node eligibility + the maximal fused subtrees of one graph.

    ``reasons`` names why a node itself blocks fusion (a node with no
    reason of its own can still sit outside every fused root because a
    descendant blocks it).  ``fused_roots`` are the maximal subtrees the
    pass will compile (>= 2 nodes each).  ``fused_nodes`` counts the
    subtrees' static size; ``fused_dispatches`` counts the unit
    dispatches the interpreter would pay PER REQUEST for those subtrees
    (a ROUTER executes itself plus ONE branch, so only the cheapest
    branch is guaranteed — the conservative figure), which makes
    ``hops_eliminated`` the per-request N->1 saving, not the static
    node count."""

    n_nodes: int = 0
    reasons: Dict[str, str] = field(default_factory=dict)
    fused_roots: List[str] = field(default_factory=list)
    fused_nodes: int = 0
    fused_dispatches: int = 0
    full: bool = False

    @property
    def hops_eliminated(self) -> int:
        return max(self.fused_dispatches - len(self.fused_roots), 0)

    def summary(self) -> Dict[str, Any]:
        """The ``/stats`` engine-block face of the plan."""
        return {
            "full": self.full,
            "nodes": self.n_nodes,
            "fused_nodes": self.fused_nodes,
            "fused_roots": list(self.fused_roots),
            "hops_eliminated": self.hops_eliminated,
            "blocked": dict(self.reasons),
        }


def _node_block_reason(
    node: PredictiveUnit,
    comp_map: Dict[str, ComponentBinding],
    skip: frozenset,
) -> Optional[str]:
    """Why THIS node cannot enter a fused program (None = eligible).

    Eligibility reads CLASS-level facts only (``pure`` is a class
    attribute; plain user-object classes always get the impure
    ``as_unit`` adapter) — units are never instantiated here, so
    planning a graph of heavy-``__init__`` units costs nothing and the
    one real construction stays in ``build_units``.  A unit whose
    constructor then fails at build time falls back to the interpreter
    (``build_partial_fusion`` catches and logs)."""
    from seldon_core_tpu.graph.units import Unit, resolve_unit_class

    if node.name in skip:
        return "external node runtime supplied"
    if node.quorum is not None:
        return "quorum degradation policy is host-mode only"
    if node.fallback is not None:
        return "fallback degradation policy is host-mode only"
    if node.implementation is not UnitImplementation.UNKNOWN_IMPLEMENTATION:
        cls = UNIT_REGISTRY.get(node.implementation.value)
        if cls is None:
            return f"no registered unit for {node.implementation.value}"
    else:
        binding = comp_map.get(node.name)
        if binding is None:
            return "no implementation, binding, or runtime"
        if binding.runtime != "inprocess":
            return f"remote {binding.runtime} binding"
        try:
            cls = resolve_unit_class(binding.class_path)
        except ValueError as e:
            return f"unresolvable unit class: {e}"
    is_unit = isinstance(cls, type) and issubclass(cls, Unit)
    if not is_unit and not hasattr(cls, "pure"):
        # reference-style plain user object: bound through the as_unit
        # adapter, which is host-mode only (units.instantiate_bound_unit)
        return (
            f"user-object class {getattr(cls, '__name__', cls)!r} "
            f"serves host-mode only"
        )
    if not getattr(cls, "pure", False):
        return f"impure unit {getattr(cls, '__name__', cls)}"
    return None


def _per_request_dispatches(node: PredictiveUnit) -> int:
    """Unit dispatches the interpreter pays for ONE request through
    this subtree: a ROUTER runs itself + exactly one branch (the
    cheapest branch is the guaranteed floor), everything else runs
    itself + all children."""
    if not node.children:
        return 1
    if UnitMethod.ROUTE in methods_for(node):
        return 1 + min(
            _per_request_dispatches(c) for c in node.children
        )
    return 1 + sum(_per_request_dispatches(c) for c in node.children)


def plan_fusion(
    predictor: PredictorSpec, skip: Optional[set] = None
) -> FusionPlan:
    """Walk the validated spec and mark every maximal fusible subtree.

    A subtree is fusible iff the root and every descendant is an
    in-process *pure* unit with no declared degradation policy
    (``quorum``/``fallback`` stay on the interpreter, which is the only
    layer that can absorb a mid-graph call failure).  ``skip`` names
    nodes whose runtime the caller supplies externally (test harnesses,
    pooled remote clients) — those pin their subtree to the host path.
    The predictor annotation ``seldon.io/graph-fuse: "false"`` opts the
    whole deployment out."""
    plan = FusionPlan(n_nodes=sum(1 for _ in predictor.graph.walk()))
    if str(predictor.annotations.get(FUSE_ANNOTATION, "")).lower() in (
        "false", "0", "off",
    ):
        plan.reasons[predictor.graph.name] = (
            f"predictor annotation {FUSE_ANNOTATION}=false"
        )
        return plan
    comp_map = predictor.component_map()
    skip_f = frozenset(skip or ())
    fusible: Dict[str, bool] = {}

    def visit(node: PredictiveUnit) -> bool:
        reason = _node_block_reason(node, comp_map, skip_f)
        if reason is not None:
            plan.reasons[node.name] = reason
        ok = reason is None
        for c in node.children:
            ok = visit(c) and ok
        fusible[node.name] = ok
        return ok

    plan.full = visit(predictor.graph)

    def collect_roots(node: PredictiveUnit) -> None:
        n_sub = sum(1 for _ in node.walk())
        if fusible[node.name] and n_sub >= 2:
            plan.fused_roots.append(node.name)
            plan.fused_nodes += n_sub
            plan.fused_dispatches += _per_request_dispatches(node)
            return  # maximal: don't descend into a fused subtree
        for c in node.children:
            collect_roots(c)

    collect_roots(predictor.graph)
    return plan


# ---------------------------------------------------------------------------
# The fused executor
# ---------------------------------------------------------------------------


class FusedGraph(CompiledGraph):
    """A :class:`CompiledGraph` whose program threads the autopilot's
    per-branch cost predictions and the request's demotion budget in as
    runtime arguments:

        (states, X, costs, budget) -> (Y, states', raw_routing,
                                       routing, tags)

    ``costs`` maps each router name to a float32 ``[n_children]`` vector
    of predicted branch walls (NaN = no prediction); ``budget`` is the
    margin-scaled remaining deadline (``+inf`` = no deadline / autopilot
    off).  Inside the program each router's raw choice is demoted to the
    cheapest predicted-to-fit branch exactly when the host interpreter
    would demote it (graph/interpreter.py ``_autopilot_branch``): a raw
    branch predicted over budget moves to the min-cost alternative
    predicted within budget; unknown (NaN) predictions neither trigger
    nor receive demotion.  With no deadline, no predictions, or the
    autopilot kill switch off, the program is the identity of the plain
    compiled program — never a numerics change.

    ``raw_routing`` carries the router's own (pre-demotion) choice for
    host-side range validation; ``routing`` carries the branch that
    actually executed, which is what lands in ``meta.routing`` so the
    feedback pass trains the branch that served (interpreter parity).
    """

    #: perf/autopilot executable-key program name; the engine's full
    #: graph keeps "predict" so autopilot seed priors and observatory
    #: rows stay continuous with the legacy compiled mode
    def __init__(
        self,
        predictor: PredictorSpec,
        rng=None,
        mesh=None,
        key_name: str = "predict",
        require_plan: bool = True,
    ):
        if require_plan:
            plan = plan_fusion(predictor)
            if not plan.full:
                blocked = "; ".join(
                    f"{n}: {r}" for n, r in sorted(plan.reasons.items())
                ) or "ineligible subtree"
                raise GraphSpecError(
                    f"graph {predictor.graph.name!r} is not fully "
                    f"fuse-eligible ({blocked})"
                )
            self.plan = plan
        else:
            self.plan = plan_fusion(predictor)
        super().__init__(predictor, rng=rng, mesh=mesh)
        self._key_name = key_name
        fused_fn = self._build_fused(predictor.graph)
        all_routers = self._all_routers

        def run(states, X, costs, budget):
            y, states2, raw, eff, tags = fused_fn(states, X, costs, budget)
            raw = {
                r: raw.get(r, jnp.int32(NOT_ROUTED)) for r in all_routers
            }
            eff = {
                r: eff.get(r, jnp.int32(NOT_ROUTED)) for r in all_routers
            }
            return y, states2, raw, eff, tags

        self._donate = _donate_enabled()
        #: buffer donation: X (argnum 1) is consumed by the program so
        #: the request tensor never exists twice on device; states stay
        #: undonated (they are re-read when the write-back is vetoed)
        self._jit_fused = (
            jax.jit(run, donate_argnums=(1,)) if self._donate
            else jax.jit(run)
        )
        #: the UNJITTED program: the phase-decomposition capture pass
        #: must re-trace the Python builder (a jitted eval_shape reuses
        #: the cached jaxpr and the capture hooks never fire)
        self._fused_run = run
        #: approximate per-node share of the fused program's cost —
        #: computed lazily at first AOT build (``_phase_weights``),
        #: stamped onto every fused dispatch hotrecord
        self.phases: Optional[Dict[str, float]] = None
        self._phases_done = False
        #: trace-time capture sink for per-node input avals (see
        #: ``_phase_weights``); None outside the capture pass
        self._capture: Optional[List[Tuple[str, str, tuple, Any]]] = None

    # -- trace-time builder -------------------------------------------------

    def _cap(self, name: str, method: str, arr) -> None:
        if self._capture is not None:
            self._capture.append(
                (name, method, tuple(arr.shape), arr.dtype)
            )

    def _build_fused(self, node: PredictiveUnit):
        from seldon_core_tpu.graph.compiled import _routers_in

        unit = self.units[node.name]
        methods = methods_for(node)
        is_model = effective_type(node) is UnitType.MODEL
        child_fns = [self._build_fused(c) for c in node.children]
        name = node.name
        static_tags = dict(unit.static_tags or {})
        n_children = len(node.children)

        def fn(states, X, costs, budget):
            raw_routing: Dict[str, Any] = {}
            routing: Dict[str, Any] = {}
            tags: Dict[str, Any] = dict(static_tags)
            y = X
            if UnitMethod.TRANSFORM_INPUT in methods:
                m = unit.predict if is_model else unit.transform_input
                self._cap(name, "predict" if is_model else "transform_input", y)
                out = m(states.get(name), y)
                y, new_state, t = normalize_output(out, states.get(name))
                states = _set_state(states, name, new_state)
                tags.update(t)

            if node.children:
                if UnitMethod.ROUTE in methods:
                    self._cap(name, "route", y)
                    out = unit.route(states.get(name), y)
                    branch, new_state, _ = normalize_output(
                        out, states.get(name)
                    )
                    states = _set_state(states, name, new_state)
                    raw_branch = jnp.asarray(branch, dtype=jnp.int32)
                    clamped = jnp.clip(raw_branch, 0, n_children - 1)
                    # in-program branch demotion: the compiled analogue
                    # of interpreter._autopilot_branch, driven entirely
                    # by the runtime cost/budget arguments so learned
                    # predictions never retrigger a compile
                    c = costs.get(name)
                    if c is not None:
                        pred = c[clamped]
                        need = pred > budget  # NaN pred -> False: keep
                        cand = jnp.where(
                            jnp.isnan(c) | (c > budget), jnp.inf, c
                        )
                        cand = cand.at[clamped].set(jnp.inf)
                        alt = jnp.argmin(cand).astype(jnp.int32)
                        has_alt = cand[alt] < jnp.inf
                        eff = jnp.where(need & has_alt, alt, clamped)
                    else:
                        eff = clamped
                    sub_routers = sorted(
                        {r for ch in node.children for r in _routers_in(ch)}
                    )

                    def make_branch(cf):
                        def bf(operand):
                            states_, x_ = operand
                            yc, s2, r, er, t = cf(states_, x_, costs, budget)
                            full_r = {
                                rn: r.get(rn, jnp.int32(NOT_ROUTED))
                                for rn in sub_routers
                            }
                            full_er = {
                                rn: er.get(rn, jnp.int32(NOT_ROUTED))
                                for rn in sub_routers
                            }
                            return yc, s2, full_r, full_er, t

                        return bf

                    try:
                        y, states, child_r, child_er, child_tags = (
                            jax.lax.switch(
                                eff,
                                [make_branch(cf) for cf in child_fns],
                                (states, y),
                            )
                        )
                    except TypeError as e:
                        if "structure" in str(e) or "pytree" in str(e):
                            raise GraphSpecError(
                                f"router {name!r}: children return "
                                f"mismatched structures (shapes/tags must "
                                f"agree across branches for compiled "
                                f"routing): {e}"
                            ) from e
                        raise GraphSpecError(
                            f"in subgraph of {name!r}: {e}"
                        ) from e
                    raw_routing[name] = raw_branch
                    routing[name] = eff
                    raw_routing.update(child_r)
                    routing.update(child_er)
                    tags.update(child_tags)
                else:
                    ys = []
                    for cf in child_fns:
                        yc, states, r, er, t = cf(states, y, costs, budget)
                        ys.append(yc)
                        raw_routing.update(r)
                        routing.update(er)
                        tags.update(t)
                    if UnitMethod.AGGREGATE in methods:
                        stacked = jnp.stack(ys, axis=0)
                        self._cap(name, "aggregate", stacked)
                        out = unit.aggregate(states.get(name), stacked)
                        y, new_state, t = normalize_output(
                            out, states.get(name)
                        )
                        states = _set_state(states, name, new_state)
                        tags.update(t)
                    elif len(ys) == 1:
                        y = ys[0]
                    else:
                        raise GraphSpecError(
                            f"node {name!r} has {len(ys)} children but no "
                            f"AGGREGATE method to merge them"
                        )

            if UnitMethod.TRANSFORM_OUTPUT in methods:
                self._cap(name, "transform_output", y)
                out = unit.transform_output(states.get(name), y)
                y, new_state, t = normalize_output(out, states.get(name))
                states = _set_state(states, name, new_state)
                tags.update(t)
            return y, states, raw_routing, routing, tags

        return fn

    # -- runtime cost arguments --------------------------------------------

    def _cost_args(
        self, rows: int, budget_s: Optional[float]
    ) -> Tuple[Dict[str, Any], Any]:
        """The per-request (costs, budget) argument pair.  Shapes are
        static per graph — only VALUES change per request, so learned
        predictions moving never retraces the program."""
        from seldon_core_tpu.runtime.autopilot import (
            autopilot_enabled,
            branch_cost_vector,
            shed_margin,
        )

        active = (
            budget_s is not None
            and budget_s > 0
            and autopilot_enabled()
            and bool(self._router_children)
        )
        costs: Dict[str, Any] = {}
        for r, n in self._router_children.items():
            if active:
                vec = branch_cost_vector(r, n, rows)
                costs[r] = jnp.asarray(
                    [math.nan if v is None else float(v) for v in vec],
                    dtype=jnp.float32,
                )
            else:
                costs[r] = jnp.full((n,), math.nan, dtype=jnp.float32)
        budget = jnp.float32(
            budget_s * shed_margin() if active else math.inf
        )
        return costs, budget

    # -- execution ----------------------------------------------------------

    def executable_key(self, X) -> str:
        from seldon_core_tpu.utils.perf import executable_key

        dtype = getattr(X, "dtype", None)
        if dtype is None:
            dtype = np.asarray(X).dtype
        return executable_key(self._key_name, np.shape(X), dtype)

    def _ensure_executable(self, X, costs=None, budget=None):
        """Per-shape AOT build of the FUSED program through the same
        compile-cache/observatory plumbing the plain compiled executor
        uses (graph/compiled.py ``_aot_build``); also computes the
        per-node phase decomposition once, off the hot path."""
        from seldon_core_tpu.utils.perf import OBSERVATORY

        if not OBSERVATORY.enabled:
            return "", None
        if costs is None or budget is None:
            costs, budget = self._cost_args(1, None)
        key = self.executable_key(X)
        executable = self._aot_build(
            key, self._jit_fused, (self.states, X, costs, budget)
        )
        if not self._phases_done:
            self._phases_done = True
            self.phases = self._phase_weights(X)
        if self.phases is not None:
            OBSERVATORY.note_phases(key, self.phases)
        return key, executable

    def _phase_weights(self, X) -> Optional[Dict[str, float]]:
        """Approximate per-node share of the fused program's FLOPs: one
        capture trace records every unit method's input aval, then each
        node's isolated program is lowered for its ``cost_analysis()``
        FLOPs.  Degrades to a uniform split when the backend yields no
        features; None only for single-node graphs (nothing to
        decompose).  Runs once per graph at first AOT build — never on
        the dispatch path."""
        from seldon_core_tpu.utils.perf import extract_cost_features

        names = list(self.units)
        if len(names) < 2:
            return None
        uniform = {n: round(1.0 / len(names), 4) for n in names}
        if len(names) > 16:
            return uniform  # decomposition capped: lowering N programs
        try:
            self._capture = []
            costs, budget = self._cost_args(1, None)
            # a FRESH wrapper per call: jax caches traces by function
            # identity, and both the jit lowering and any earlier pass
            # have already traced `_fused_run` — a cached trace would
            # skip the Python builder and the capture hooks with it
            jax.eval_shape(
                lambda s, x, c, b: self._fused_run(s, x, c, b),
                self.states, jnp.asarray(X), costs, budget,
            )
            captured, self._capture = self._capture, None
            flops: Dict[str, float] = {}
            for name, method, shape, dtype in captured:
                unit = self.units[name]
                state = self.states.get(name)
                m = getattr(unit, method)

                def iso(s, x, _m=m):
                    return normalize_output(_m(s, x), s)[0]

                cost = (
                    jax.jit(iso)
                    .lower(state, jax.ShapeDtypeStruct(shape, dtype))
                    .cost_analysis()
                )
                feats = extract_cost_features(cost) or {}
                flops[name] = flops.get(name, 0.0) + feats.get("flops", 0.0)
            total = sum(flops.values())
            if total <= 0:
                return uniform
            return {
                n: round(flops.get(n, 0.0) / total, 4) for n in names
            }
        except Exception:  # noqa: BLE001 - decomposition is best-effort
            self._capture = None
            return uniform

    def predict_arrays(
        self,
        X,
        update_states=True,
        budget_s: Optional[float] = None,
        rows: Optional[int] = None,
    ):
        """Run the fused program; returns ``(Y, routing, tags)`` exactly
        like the plain compiled executor, with ``routing`` carrying the
        branches that actually executed (post-demotion) and demotion
        stamped into ``tags`` the way the interpreter stamps it."""
        from seldon_core_tpu.runtime.autopilot import (
            AUTOPILOT,
            branch_key,
        )
        from seldon_core_tpu.utils.telemetry import RECORDER

        X_in = X  # pre-device original: the donation-safe retry source
        X = jnp.asarray(X)
        if rows is None:
            shape = np.shape(X)
            rows = int(shape[0]) if len(shape) >= 2 else 1
        costs, budget = self._cost_args(rows, budget_s)
        key, executable = self._ensure_executable(X, costs, budget)
        t0 = time.perf_counter()
        if executable is not None:
            try:
                y, new_states, raw, eff, tags = executable(
                    self.states, X, costs, budget
                )
            except Exception:  # noqa: BLE001 - aval drift: jit path serves
                with self._aot_lock:
                    self._aot[key] = None
                # the failed donating executable may already have
                # consumed X's device buffer — re-materialize from the
                # caller's original (every serving path hands numpy in)
                y, new_states, raw, eff, tags = self._jit_fused(
                    self.states, jnp.asarray(X_in), costs, budget
                )
        else:
            y, new_states, raw, eff, tags = self._jit_fused(
                self.states, X, costs, budget
            )
        raw_py = {k: int(v) for k, v in raw.items() if int(v) != NOT_ROUTED}
        for r, v in raw_py.items():
            if v < 0 or v >= self._router_children[r]:
                raise GraphSpecError(
                    f"router {r!r} chose branch {v} but has "
                    f"{self._router_children[r]} children (broadcast "
                    f"routing is host-mode only)"
                )
        routing_py = {
            k: int(v) for k, v in eff.items() if int(v) != NOT_ROUTED
        }
        demoted = {
            r: b for r, b in routing_py.items() if raw_py.get(r, b) != b
        }
        if demoted:
            tags = dict(tags)
            for r, b in demoted.items():
                # interpreter-parity bookkeeping: the decision counter,
                # an audit-grade span event, and the reroute tag — the
                # demotion happened on device, the record happens here
                RECORDER.record_autopilot_decision("route")
                from seldon_core_tpu.utils.tracing import TRACER

                TRACER.event(
                    "autopilot_reroute", node=r,
                    from_branch=int(raw_py[r]), to_branch=int(b),
                    in_program=True,
                )
                tags[f"seldon.autopilot.reroute.{r}"] = int(b)
        if callable(update_states):
            jax.block_until_ready(new_states)
            do_update = update_states()
        else:
            do_update = update_states
        if do_update:
            self.states = new_states
        # per-branch latency learning, interpreter-parity: the branch
        # that served this request's shape bucket observes the fused
        # wall (learning is never gated by the autopilot kill switch)
        if routing_py:
            wall = time.perf_counter() - t0
            for r, b in routing_py.items():
                AUTOPILOT.observe(branch_key(r, b, rows), wall)
        return y, routing_py, tags

    # -- SeldonMessage API --------------------------------------------------

    def predict(
        self, msg: SeldonMessage, budget_s: Optional[float] = None
    ) -> SeldonMessage:
        from seldon_core_tpu.messages import Status

        # hand predict_arrays a HOST array: its donation-failure retry
        # re-materializes from the caller's original, which must not be
        # the device buffer the failed executable already consumed
        y, routing, tags = self.predict_arrays(
            np.atleast_2d(np.asarray(msg.array())), budget_s=budget_s
        )
        leaf_names = self._output_names(self.predictor.graph, routing)
        resp = msg.with_array(y, names=leaf_names)
        resp.meta = Meta(
            puid=msg.meta.puid,
            tags={**msg.meta.tags, **pythonize_tags(tags)},
            routing={**msg.meta.routing, **routing},
            requestPath=dict(msg.meta.requestPath),
        )
        resp.status = Status()
        return resp


# ---------------------------------------------------------------------------
# Partial fusion: fused subtrees inside the host interpreter
# ---------------------------------------------------------------------------


def _subtree_spec(
    predictor: PredictorSpec, root: PredictiveUnit
) -> PredictorSpec:
    """A PredictorSpec scoped to one subtree.  Unit PRNG keys derive
    from unit NAMES (graph/interpreter.py ``unit_rngs``), so the
    sub-spec's units initialise bit-identically to the same units inside
    the full graph — subsetting is a pure topology change."""
    names = {u.name for u in root.walk()}
    return PredictorSpec(
        name=f"{predictor.name}/{root.name}",
        graph=root,
        components=[c for c in predictor.components if c.name in names],
        annotations=dict(predictor.annotations),
    )


class FusedSubtreeRuntime:
    """One fused subtree executed as a single device dispatch from
    inside the host interpreter (graph/interpreter.py stops its
    recursion at this node).  Emits ONE fused dispatch hotrecord with
    the per-node phase decomposition — N interpreter hops become one
    record, not zero."""

    def __init__(
        self, predictor: PredictorSpec, root: PredictiveUnit, rng=None
    ):
        self.root = root
        self.graph = FusedGraph(
            _subtree_spec(predictor, root),
            rng=rng,
            key_name=f"fused:{root.name}",
            require_plan=False,
        )

    async def run(self, msg: SeldonMessage) -> SeldonMessage:
        from seldon_core_tpu.runtime.resilience import remaining_s
        from seldon_core_tpu.utils.hotrecord import SPINE

        # the HOST copy is what predict_arrays re-materializes from on a
        # failed donating executable, and what the telemetry record
        # holds: the donated device buffer is dead after dispatch, and a
        # drainer folding a deleted jax array would silently lose every
        # quality/trace fold on exactly the backends donation targets
        X = np.atleast_2d(np.asarray(msg.array()))
        rows = int(X.shape[0])
        wants = SPINE.dispatch_wants()
        t0 = time.perf_counter()
        start_s = time.time()
        try:
            y, routing, tags = self.graph.predict_arrays(
                X, budget_s=remaining_s(), rows=rows
            )
        except GraphSpecError:
            raise
        except (TypeError, ValueError) as e:
            # trace-time shape/type rejection: the interpreter's eager
            # path would surface the unit's own error inline; the fused
            # path names the subtree so the 400 stays actionable
            raise GraphSpecError(
                f"fused subtree {self.root.name!r} rejected input of "
                f"shape {tuple(X.shape)}: {e}"
            ) from e
        y = np.asarray(y)
        if wants.any:
            SPINE.record_dispatch(
                wants,
                executable=self.graph.executable_key(X),
                seconds=time.perf_counter() - t0,
                start_s=start_s,
                rows=rows,
                real_rows=rows,
                method="fused",
                quality_node=self.root.name,
                X=X,
                Y=y,
                phases=self.graph.phases,
            )
        resp = msg.with_array(
            y, names=self.graph._output_names(self.root, routing)
        )
        resp.meta = Meta(
            puid=msg.meta.puid,
            tags={**msg.meta.tags, **pythonize_tags(tags)},
            routing={**msg.meta.routing, **routing},
            requestPath=dict(msg.meta.requestPath),
        )
        return resp

    async def feedback(self, feedback) -> None:
        routing = (
            feedback.response.meta.routing
            if feedback.response is not None
            else {}
        )
        X = None
        if feedback.request is not None and feedback.request.data is not None:
            X = feedback.request.array()
        truth = feedback.truth_array()
        self.graph.feedback_arrays(X, routing, feedback.reward, truth)


def build_partial_fusion(
    predictor: PredictorSpec,
    skip: Optional[set] = None,
    rng=None,
) -> Tuple[Dict[str, FusedSubtreeRuntime], FusionPlan]:
    """Plan + build the fused subtree runtimes for a host-mode graph.
    Returns ``({root_name: runtime}, plan)``; an empty dict means the
    interpreter serves every node (nothing eligible, or the kill switch
    off — callers check :func:`fuse_enabled` themselves)."""
    plan = plan_fusion(predictor, skip=skip)
    fused: Dict[str, FusedSubtreeRuntime] = {}
    for root_name in plan.fused_roots:
        root = predictor.graph.find(root_name)
        try:
            fused[root_name] = FusedSubtreeRuntime(predictor, root, rng=rng)
        except Exception:  # noqa: BLE001 - a subtree that fails to trace
            # keeps the interpreter path; fusion is an optimization, the
            # interpreter is the always-available fallback
            logger.exception(
                "partial fusion of subtree %r failed; interpreter keeps it",
                root_name,
            )
            plan.reasons[root_name] = "fused build failed (see logs)"
            plan.fused_nodes -= sum(1 for _ in root.walk())
            plan.fused_dispatches -= _per_request_dispatches(root)
            plan.fused_roots = [
                r for r in plan.fused_roots if r != root_name
            ]
    return fused, plan
