"""Deployment defaulting + validation — the operator's spec-rewriting pass.

Mirrors the reference operator's ``defaulting``/``validate`` steps
(cluster-manager SeldonDeploymentOperatorImpl.java:346-441): assign each
remote graph node a cluster-unique service port from a base, back-fill
``PredictiveUnit.endpoint`` from its component binding, inject the standard
unit env/config (unit id, predictor id, deployment id, typed parameters as
JSON), and reject structurally invalid graphs before anything materialises.

TPU-native differences: an ``inprocess`` binding gets no port — the node is
compiled into the engine's XLA program, so its "endpoint" is the in-memory
unit registry.  Port assignment only happens for ``rest``/``grpc`` bindings.
"""

from __future__ import annotations

import json
from typing import List

from seldon_core_tpu.graph.spec import (
    ComponentBinding,
    Endpoint,
    EndpointType,
    GraphSpecError,
    PredictiveUnit,
    PredictorSpec,
    SeldonDeploymentSpec,
    UnitImplementation,
    UnitMethod,
    UnitType,
)

PU_PORT_BASE = 9000  # cluster-manager application.properties:7 pu-container-port-base

# env names the reference injects into every unit container
# (SeldonDeploymentOperatorImpl.java:260-279)
ENV_SERVICE_PORT = "PREDICTIVE_UNIT_SERVICE_PORT"
ENV_PARAMETERS = "PREDICTIVE_UNIT_PARAMETERS"
ENV_UNIT_ID = "PREDICTIVE_UNIT_ID"
ENV_PREDICTOR_ID = "PREDICTOR_ID"
ENV_DEPLOYMENT_ID = "SELDON_DEPLOYMENT_ID"


def defaulting(spec: SeldonDeploymentSpec) -> SeldonDeploymentSpec:
    """Rewrite the spec in place (and return it) with ports/endpoints/env."""
    port_counter = PU_PORT_BASE
    for predictor in spec.predictors:
        comp_map = predictor.component_map()
        for unit in predictor.graph.walk():
            binding = comp_map.get(unit.name)
            if binding is None:
                continue  # hardcoded-impl units have no binding
            # -- port assignment (remote runtimes only) ---------------------
            if binding.runtime in ("rest", "grpc") and not binding.port:
                binding.port = port_counter
                port_counter += 1
            if not binding.host and binding.runtime in ("rest", "grpc"):
                binding.host = "localhost"
            # -- endpoint back-fill ----------------------------------------
            if binding.runtime == "inprocess":
                unit.endpoint = None  # compiled into the engine program
            else:
                ep_type = (
                    EndpointType.GRPC if binding.runtime == "grpc" else EndpointType.REST
                )
                if unit.endpoint is None:
                    unit.endpoint = Endpoint(type=ep_type)
                unit.endpoint.service_host = binding.host
                unit.endpoint.service_port = binding.port
                unit.endpoint.type = ep_type
            # -- parameter propagation: unit params flow to the binding ----
            if unit.parameters and not binding.parameters:
                binding.parameters = list(unit.parameters)
            # -- standard env ----------------------------------------------
            binding.env.setdefault(ENV_SERVICE_PORT, str(binding.port))
            binding.env.setdefault(
                ENV_PARAMETERS,
                json.dumps([p.to_json_dict() for p in binding.parameters]),
            )
            binding.env.setdefault(ENV_UNIT_ID, unit.name)
            binding.env.setdefault(ENV_PREDICTOR_ID, predictor.name)
            binding.env.setdefault(ENV_DEPLOYMENT_ID, spec.name)
    return spec


def _check_unit(unit: PredictiveUnit, comp_names: set, errors: List[str]) -> None:
    has_impl = unit.implementation is not UnitImplementation.UNKNOWN_IMPLEMENTATION
    has_methods = unit.methods is not None
    has_type = unit.type is not None
    # every unit must define what it does (SeldonDeploymentOperatorImpl.java:422-430)
    if not (has_impl or has_methods or has_type):
        errors.append(
            f"unit {unit.name!r} must declare type, implementation, or methods"
        )
    # non-hardcoded units must resolve to a component binding
    # (SeldonDeploymentOperatorImpl.java:390-413)
    if not has_impl and unit.name not in comp_names:
        errors.append(
            f"unit {unit.name!r} has no hardcoded implementation and no matching "
            f"component binding"
        )
    # built-in structural constraints
    if unit.implementation is UnitImplementation.RANDOM_ABTEST:
        if len(unit.children) != 2:
            errors.append(
                f"RANDOM_ABTEST unit {unit.name!r} needs exactly 2 children, "
                f"has {len(unit.children)}"
            )
        if not any(p.name == "ratioA" for p in unit.parameters):
            errors.append(f"RANDOM_ABTEST unit {unit.name!r} needs a 'ratioA' parameter")
    if unit.implementation is UnitImplementation.AVERAGE_COMBINER and not unit.children:
        errors.append(f"AVERAGE_COMBINER unit {unit.name!r} needs children to combine")
    if unit.type is UnitType.COMBINER and not unit.children:
        errors.append(f"COMBINER unit {unit.name!r} needs children to combine")
    if unit.type is UnitType.ROUTER and not unit.children:
        errors.append(f"ROUTER unit {unit.name!r} needs children to route to")
    # degradation declarations (resilience layer): structural sanity
    if unit.quorum is not None:
        combinerish = (
            unit.type is UnitType.COMBINER
            or unit.implementation is UnitImplementation.AVERAGE_COMBINER
            or (unit.methods is not None and UnitMethod.AGGREGATE in unit.methods)
        )
        if not combinerish:
            errors.append(
                f"unit {unit.name!r} declares a quorum but has no AGGREGATE "
                f"method (quorum only applies to combiners)"
            )
        if not (1 <= unit.quorum <= len(unit.children)):
            errors.append(
                f"unit {unit.name!r}: quorum {unit.quorum} out of range for "
                f"{len(unit.children)} children"
            )
    if unit.fallback is not None:
        routerish = (
            unit.type is UnitType.ROUTER
            or unit.implementation
            in (UnitImplementation.SIMPLE_ROUTER, UnitImplementation.RANDOM_ABTEST)
            or (unit.methods is not None and UnitMethod.ROUTE in unit.methods)
        )
        if not routerish:
            errors.append(
                f"unit {unit.name!r} declares a fallback branch but has no "
                f"ROUTE method (fallback only applies to routers)"
            )
        if not (0 <= unit.fallback < len(unit.children)):
            errors.append(
                f"unit {unit.name!r}: fallback branch {unit.fallback} out of "
                f"range for {len(unit.children)} children"
            )
    for child in unit.children:
        _check_unit(child, comp_names, errors)


def validate(spec: SeldonDeploymentSpec) -> None:
    """Raise GraphSpecError listing every violation (reference validate,
    SeldonDeploymentOperatorImpl.java:432-441)."""
    errors: List[str] = []
    if not spec.predictors:
        errors.append("deployment has no predictors")
    seen_predictors = set()
    for predictor in spec.predictors:
        if predictor.name in seen_predictors:
            errors.append(f"duplicate predictor name {predictor.name!r}")
        seen_predictors.add(predictor.name)
        names = [u.name for u in predictor.graph.walk()]
        dupes = {n for n in names if names.count(n) > 1}
        if dupes:
            errors.append(
                f"predictor {predictor.name!r}: duplicate unit names {sorted(dupes)}"
            )
        comp_names = set(predictor.component_map())
        _check_unit(predictor.graph, comp_names, errors)
        for binding in predictor.components:
            if binding.runtime == "inprocess" and not binding.class_path:
                errors.append(
                    f"inprocess binding {binding.name!r} needs a class_path "
                    f"(module:Class or registered unit name)"
                )
    if errors:
        raise GraphSpecError("; ".join(errors))


def default_and_validate(spec: SeldonDeploymentSpec) -> SeldonDeploymentSpec:
    defaulting(spec)
    validate(spec)
    return spec
