"""Graph sharding — one engine process per graph node.

The reference materializes one pod PER GRAPH NODE (PAPER.md §1: every
PredictiveUnit gets its own microservice Deployment, wired by the engine's
internal dispatch).  This repo collapsed that into a single engine
process — the right call for latency, the wrong one for scale-out, where
a hot MODEL leaf should grow replicas independently of its siblings.
This module wins the reference topology back at process granularity:

* :func:`shardable_nodes` — the MODEL leaves with inprocess bindings.
  Only those shard: the engine's cross-process surface speaks ``POST
  /predict`` (engines compose as MODEL leaves since PR 3), while routers/
  combiners/transformers are per-request control flow that stays in the
  root engine.
* :func:`node_subspec` — a standalone single-node SeldonDeployment for
  one leaf, servable by ``engine_main --node`` / ``ENGINE_GRAPH_NODE``.
* :func:`shard_predictor` — the root engine's rewritten spec: sharded
  leaves' bindings become ``rest`` endpoints at the node engines, so the
  existing remote-dispatch client (runtime/client.py — pooled sessions,
  retries, breakers, deadline propagation, traceparent) wires the mesh
  with zero new transport code.

The operator half lives in operator/manifests.py: annotating a
SeldonDeployment with ``seldon.io/shard-graph: "true"`` renders one
engine Deployment+Service per shardable node plus the rewritten root.
"""

from __future__ import annotations

import copy
from typing import Dict, List, Optional, Tuple

from seldon_core_tpu.graph.interpreter import effective_type
from seldon_core_tpu.graph.spec import (
    ComponentBinding,
    GraphSpecError,
    PredictiveUnit,
    PredictorSpec,
    SeldonDeploymentSpec,
    UnitType,
)

__all__ = [
    "shardable_nodes",
    "node_subspec",
    "shard_predictor",
]


def shardable_nodes(predictor: PredictorSpec) -> List[PredictiveUnit]:
    """MODEL leaves with an inprocess binding — the nodes that can run as
    standalone engine processes behind ``POST /predict``."""
    comp_map = predictor.component_map()
    out = []
    for unit in predictor.graph.walk():
        if unit.children:
            continue
        if effective_type(unit) is not UnitType.MODEL:
            continue
        binding = comp_map.get(unit.name)
        if binding is not None and binding.runtime == "inprocess":
            out.append(unit)
    return out


def node_subspec(spec: SeldonDeploymentSpec, node_name: str,
                 predictor_name: Optional[str] = None) -> SeldonDeploymentSpec:
    """A standalone deployment serving ONE node of ``spec``'s graph — what
    an ``ENGINE_GRAPH_NODE=<name>`` engine process boots.  The node keeps
    its name, parameters and binding, so its compiled unit (and therefore
    its predictions) are identical to the collapsed in-engine form."""
    predictor = spec.predictor(predictor_name)
    unit = predictor.graph.find(node_name)
    if unit is None:
        raise GraphSpecError(
            f"graph node {node_name!r} not found in predictor "
            f"{predictor.name!r}"
        )
    if unit.children:
        raise GraphSpecError(
            f"graph node {node_name!r} has children — only leaves shard "
            f"into node engines"
        )
    binding = predictor.component_map().get(node_name)
    if binding is None or binding.runtime != "inprocess":
        raise GraphSpecError(
            f"graph node {node_name!r} has no inprocess binding to serve"
        )
    node_unit = copy.deepcopy(unit)
    annotations = dict(spec.annotations)
    # the subspec is a plain single-node deployment; carrying the shard
    # marker forward would re-shard it on the next materialization pass
    annotations.pop("seldon.io/shard-graph", None)
    # predictor-qualified name: a canary pair sharing a leaf name must
    # materialize DISTINCT node Deployments/Services per predictor, or
    # the second `kubectl apply` silently rewires the first predictor's
    # traffic onto the other's node engine
    return SeldonDeploymentSpec(
        name=f"{spec.name}-{predictor.name}-{node_name}",
        predictors=[
            PredictorSpec(
                name=predictor.name,
                graph=node_unit,
                components=[copy.deepcopy(binding)],
                replicas=predictor.replicas,
                annotations=dict(predictor.annotations),
            )
        ],
        annotations=annotations,
    )


def shard_predictor(
    spec: SeldonDeploymentSpec,
    endpoints: Dict[str, Tuple[str, int]],
    predictor_name: Optional[str] = None,
) -> SeldonDeploymentSpec:
    """The root engine's spec with each node in ``endpoints`` rewritten to
    a ``rest`` binding at ``(host, port)`` — the node engine materialized
    by :func:`node_subspec`.  The root then serves the graph in host mode
    through the resilient remote-dispatch client (per-node breakers,
    shared retry budget, deadline propagation ride along for free).

    ``endpoints`` keys must be shardable nodes; anything else is a
    config error surfaced at materialization, not at first request."""
    out = copy.deepcopy(spec)
    predictor = out.predictor(predictor_name)
    legal = {u.name for u in shardable_nodes(predictor)}
    comp_map = predictor.component_map()
    for node_name, (host, port) in endpoints.items():
        if node_name not in legal:
            raise GraphSpecError(
                f"node {node_name!r} is not shardable (must be a MODEL "
                f"leaf with an inprocess binding)"
            )
        old = comp_map[node_name]
        predictor.components[predictor.components.index(old)] = \
            ComponentBinding(
                name=node_name,
                runtime="rest",
                host=host,
                port=int(port),
                image=old.image,
            )
    return out
