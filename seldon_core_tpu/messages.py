"""Core data-plane messages for the TPU inference-graph framework.

Re-designs the reference wire contract (reference: proto/prediction.proto:12-69)
as Python dataclasses whose tensor payloads are *device-resident arrays* rather
than `repeated double` protos: the array is materialised to JSON/proto only at
a network edge, so an in-process graph hop moves zero bytes host-side.

Semantics mirrored from the reference:
  * ``SeldonMessage{status, meta, data_oneof{data|binData|strData}}``
    (proto/prediction.proto:12-22)
  * ``DefaultData{names, tensor|ndarray}`` oneof — and the rule that a
    response preserves the *kind* of the request payload
    (engine PredictorUtils.java:127-166 ``updateData``)
  * ``Tensor{shape:int32[], values:double[]}`` flattened row-major
    (proto/prediction.proto:31-34)
  * ``Meta{puid, tags, routing}`` — tags merge across graph nodes, routing
    records the branch each ROUTER chose (proto/prediction.proto:19-24,
    engine PredictiveUnitBean.java:252-264)
  * ``Feedback{request, response, reward, truth}`` (proto/prediction.proto:55-60)
  * puid: 130-bit random, base32, lowercase (engine PredictionService.java:52-58)

JSON field names are camelCase, matching the reference's protobuf JsonFormat
output, so clients of the reference can talk to this framework unchanged.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Iterable, Mapping, Optional, Sequence, Union

import numpy as np

__all__ = [
    "Status",
    "Meta",
    "DefaultData",
    "SeldonMessage",
    "SeldonMessageList",
    "Feedback",
    "SeldonMessageError",
    "DispatchTimeoutError",
    "DeadlineExceededError",
    "LoadShedError",
    "new_puid",
    "prediction_delta",
]

ArrayLike = Any  # np.ndarray | jax.Array | nested lists


class SeldonMessageError(ValueError):
    """Malformed message payload (maps to a FAILURE Status at the edge).

    ``http_code`` drives the FAILURE status code; subclasses override it
    for non-client-fault failures (e.g. dispatch deadline -> 504)."""

    http_code = 400


class DispatchTimeoutError(SeldonMessageError):
    """Device dispatch exceeded the engine deadline — the per-node budget
    the reference enforced with 5 s gRPC deadlines
    (engine InternalPredictionService.java:77)."""

    http_code = 504


class DeadlineExceededError(SeldonMessageError):
    """The request-level deadline budget (``Seldon-Deadline-Ms`` header /
    gRPC deadline, runtime/resilience.py) ran out before the call could
    complete.  Distinct from ``DispatchTimeoutError``: that is the engine's
    own per-dispatch ceiling; this is the budget the CALLER set, decremented
    across every node hop and retry so timeouts never stack."""

    http_code = 504


class LoadShedError(SeldonMessageError):
    """Predictive load shed (runtime/autopilot.py): the autopilot's
    predicted queue + dispatch latency exceeded the request's remaining
    deadline budget, so the engine refused the request *before* burning
    device time on an answer the caller could never use.  503 at the
    edge — retryable downstream (runtime/resilience.py classifies 503
    transient), so a shed composes with the circuit breakers and the
    global retry budget instead of bypassing them."""

    http_code = 503


# ---------------------------------------------------------------------------
# puid
# ---------------------------------------------------------------------------

_BASE32 = "abcdefghijklmnopqrstuvwxyz234567"
# byte -> base32 char of its low 5 bits (uniform: 256 = 8 * 32)
_B32_TABLE = bytes(ord(_BASE32[b & 31]) for b in range(256))


_PUID_LOCAL = threading.local()
_PUID_BATCH = 26 * 1024  # one urandom read per 1024 ids

# a forked child inherits the parent's buffer and would replay the same ids
os.register_at_fork(after_in_child=lambda: _PUID_LOCAL.__dict__.clear())


def new_puid() -> str:
    """130-bit random id, base32 lowercase — same shape as the reference's
    ``PuidGenerator`` (engine PredictionService.java:52-58): 26 chars of
    [a-z2-7] = 130 uniform bits.  Implemented as bytes.translate over the
    low 5 bits of 26 random bytes; entropy is drawn from os.urandom in
    per-thread 26 KiB blocks because a syscall per id (~40us) dominated the
    request hot path — same buffering a JVM SecureRandom does internally."""
    loc = _PUID_LOCAL
    pos = getattr(loc, "pos", _PUID_BATCH)
    if pos >= _PUID_BATCH:
        loc.buf = os.urandom(_PUID_BATCH)
        pos = 0
    chunk = loc.buf[pos : pos + 26]
    loc.pos = pos + 26
    return chunk.translate(_B32_TABLE).decode("ascii")


# ---------------------------------------------------------------------------
# Status
# ---------------------------------------------------------------------------


@dataclass
class Status:
    """Mirrors ``Status{code, info, reason, status}`` (proto/prediction.proto:24-29)."""

    code: int = 200
    info: str = ""
    reason: str = ""
    status: str = "SUCCESS"  # SUCCESS | FAILURE

    @staticmethod
    def failure(info: str, code: int = 400, reason: str = "") -> "Status":
        return Status(code=code, info=info, reason=reason, status="FAILURE")

    def to_json_dict(self) -> dict:
        out: dict = {"code": self.code, "status": self.status}
        if self.info:
            out["info"] = self.info
        if self.reason:
            out["reason"] = self.reason
        return out

    @staticmethod
    def from_json_dict(d: Mapping[str, Any]) -> "Status":
        try:
            return Status(
                code=int(d.get("code", 0) or 0),
                info=str(d.get("info", "") or ""),
                reason=str(d.get("reason", "") or ""),
                status=str(d.get("status", "SUCCESS") or "SUCCESS"),
            )
        except (TypeError, ValueError, AttributeError) as e:
            raise SeldonMessageError(f"malformed status: {e}") from e


# ---------------------------------------------------------------------------
# Meta
# ---------------------------------------------------------------------------


@dataclass
class Meta:
    """Request metadata carried across every graph hop.

    ``routing`` maps node-name -> child index chosen by that ROUTER (-1 means
    broadcast); the feedback pass replays it so only the branch that served a
    request gets trained (engine PredictiveUnitBean.java:141-149).
    ``tags`` accumulate across nodes (later writers win on key conflict,
    engine PredictiveUnitBean.java:252-264).
    """

    puid: str = ""
    tags: dict = field(default_factory=dict)
    routing: dict = field(default_factory=dict)  # node name -> int branch
    requestPath: dict = field(default_factory=dict)  # node name -> impl id

    def merged_with(self, other: "Meta") -> "Meta":
        """Merge semantics of the reference engine: child meta merged into
        parent, other's entries winning (PredictiveUnitBean.java:252-264)."""
        return Meta(
            puid=other.puid or self.puid,
            tags={**self.tags, **other.tags},
            routing={**self.routing, **other.routing},
            requestPath={**self.requestPath, **other.requestPath},
        )

    def to_json_dict(self) -> dict:
        out: dict = {"puid": self.puid}
        if self.tags:
            out["tags"] = dict(self.tags)
        if self.routing:
            out["routing"] = {k: int(v) for k, v in self.routing.items()}
        if self.requestPath:
            out["requestPath"] = dict(self.requestPath)
        return out

    @staticmethod
    def from_json_dict(d: Mapping[str, Any]) -> "Meta":
        try:
            return Meta(
                puid=str(d.get("puid", "") or ""),
                tags=dict(d.get("tags", {}) or {}),
                routing={k: int(v) for k, v in (d.get("routing", {}) or {}).items()},
                requestPath=dict(d.get("requestPath", {}) or {}),
            )
        except (TypeError, ValueError, AttributeError) as e:
            raise SeldonMessageError(f"malformed meta: {e}") from e


# ---------------------------------------------------------------------------
# DefaultData — the tensor payload
# ---------------------------------------------------------------------------


def _to_numpy(arr: ArrayLike) -> np.ndarray:
    """Materialise to host numpy (only used at serialization edges)."""
    if isinstance(arr, np.ndarray):
        return arr
    # jax.Array exposes __array__; nested lists go through np.asarray too.
    return np.asarray(arr)


@dataclass
class DefaultData:
    """Named tensor payload.

    ``kind`` is "tensor" (flat values + shape) or "ndarray" (nested lists) —
    the JSON oneof of the reference (proto/prediction.proto:38-45).  The
    in-memory representation is always a single array (numpy or jax); ``kind``
    only controls the wire form, and is preserved request->response like the
    reference's ``updateData`` (engine PredictorUtils.java:127-166).
    """

    array: ArrayLike = None
    names: list = field(default_factory=list)
    kind: str = "tensor"  # "tensor" | "ndarray"

    # -- construction -------------------------------------------------------

    @staticmethod
    def from_array(
        arr: ArrayLike, names: Optional[Sequence[str]] = None, kind: str = "tensor"
    ) -> "DefaultData":
        return DefaultData(array=arr, names=list(names or []), kind=kind)

    # -- access -------------------------------------------------------------

    def numpy(self) -> np.ndarray:
        if self.array is None:
            raise SeldonMessageError("DefaultData has no array payload")
        return _to_numpy(self.array)

    @property
    def shape(self):
        return tuple(np.shape(self.array)) if self.array is not None else ()

    def with_array(self, arr: ArrayLike, names: Optional[Sequence[str]] = None) -> "DefaultData":
        """New payload keeping this payload's wire kind (and names unless
        overridden) — response-preserves-request-kind rule."""
        return DefaultData(
            array=arr,
            names=list(names) if names is not None else list(self.names),
            kind=self.kind,
        )

    # -- codecs -------------------------------------------------------------

    def to_json_dict(self) -> dict:
        out: dict = {}
        if self.names:
            out["names"] = list(self.names)
        a = self.numpy()
        if self.kind == "ndarray":
            out["ndarray"] = a.tolist()
        else:
            out["tensor"] = {
                "shape": [int(s) for s in a.shape],
                "values": a.reshape(-1).astype(np.float64).tolist(),
            }
        return out

    @staticmethod
    def from_json_dict(d: Mapping[str, Any], dtype=np.float64) -> "DefaultData":
        names = list(d.get("names", []) or [])
        if "tensor" in d:
            t = d["tensor"]
            if not isinstance(t, Mapping) or "values" not in t:
                raise SeldonMessageError("data.tensor must have 'shape' and 'values'")
            values = np.asarray(t.get("values", []), dtype=dtype)
            shape = [int(s) for s in t.get("shape", [values.size])]
            try:
                arr = values.reshape(shape)
            except ValueError as e:
                raise SeldonMessageError(f"tensor shape {shape} != #values {values.size}") from e
            return DefaultData(array=arr, names=names, kind="tensor")
        if "ndarray" in d:
            try:
                arr = np.asarray(d["ndarray"], dtype=dtype)
            except (ValueError, TypeError):
                # ragged / mixed-type ndarray: keep as object array (the
                # reference's ListValue permits heterogenous entries)
                arr = np.asarray(d["ndarray"], dtype=object)
            return DefaultData(array=arr, names=names, kind="ndarray")
        raise SeldonMessageError("data must contain 'tensor' or 'ndarray'")


# ---------------------------------------------------------------------------
# SeldonMessage
# ---------------------------------------------------------------------------


@dataclass
class SeldonMessage:
    """The unit of exchange on every graph hop (proto/prediction.proto:12-22).

    Exactly one of ``data`` / ``bin_data`` / ``str_data`` is set (the data
    oneof); all three may be None for metadata-only messages (e.g. feedback
    acks).
    """

    data: Optional[DefaultData] = None
    bin_data: Optional[bytes] = None
    str_data: Optional[str] = None
    meta: Meta = field(default_factory=Meta)
    status: Optional[Status] = None

    # -- construction helpers ----------------------------------------------

    @staticmethod
    def from_array(
        arr: ArrayLike,
        names: Optional[Sequence[str]] = None,
        kind: str = "tensor",
        meta: Optional[Meta] = None,
    ) -> "SeldonMessage":
        return SeldonMessage(
            data=DefaultData.from_array(arr, names, kind), meta=meta or Meta()
        )

    @staticmethod
    def failure(info: str, code: int = 400, meta: Optional[Meta] = None) -> "SeldonMessage":
        return SeldonMessage(status=Status.failure(info, code=code), meta=meta or Meta())

    # -- oneof accessors ----------------------------------------------------

    @property
    def data_kind(self) -> str:
        if self.data is not None:
            return "data"
        if self.bin_data is not None:
            return "binData"
        if self.str_data is not None:
            return "strData"
        return "empty"

    def array(self) -> np.ndarray:
        if self.data is None:
            raise SeldonMessageError("message has no DefaultData payload")
        return self.data.numpy()

    def names(self) -> list:
        return list(self.data.names) if self.data is not None else []

    def with_array(self, arr: ArrayLike, names: Optional[Sequence[str]] = None) -> "SeldonMessage":
        """Response builder: new array, preserved payload kind/meta."""
        if self.data is not None:
            new_data = self.data.with_array(arr, names)
        else:
            new_data = DefaultData.from_array(arr, names)
        return SeldonMessage(data=new_data, meta=self.meta, status=self.status)

    # -- codecs -------------------------------------------------------------

    def to_json_dict(self) -> dict:
        out: dict = {"meta": self.meta.to_json_dict()}
        if self.status is not None:
            out["status"] = self.status.to_json_dict()
        if self.data is not None:
            out["data"] = self.data.to_json_dict()
        elif self.bin_data is not None:
            import base64

            out["binData"] = base64.b64encode(self.bin_data).decode("ascii")
        elif self.str_data is not None:
            out["strData"] = self.str_data
        return out

    def to_json(self) -> str:
        fast = self._to_json_fast()
        if fast is not None:
            return fast
        return json.dumps(self.to_json_dict(), separators=(",", ":"))

    def _to_json_fast(self) -> Optional[str]:
        """Native-codec serialization (native/fastcodec.cpp): the numeric
        payload is formatted in C++ and spliced into the (tiny) envelope.
        None => caller uses the pure-Python path; the two paths emit
        JSON-equivalent documents."""
        if self.data is None or self.data.array is None:
            return None
        a = _to_numpy(self.data.array)
        if a.dtype == object or a.dtype.kind not in "fiub":
            return None
        if self.data.kind == "ndarray" and a.dtype.kind != "f":
            # python path emits ints/bools verbatim in ndarray form; the
            # native formatter only speaks doubles — don't change the wire
            return None
        if a.size < 32:
            return None  # ctypes fixed cost loses to json.dumps on tiny arrays
        try:
            from seldon_core_tpu.native.fastcodec import format_data_fragment
        except ImportError:  # pragma: no cover
            return None
        af = np.ascontiguousarray(a, dtype=np.float64)
        if not np.isfinite(af).all():
            return None  # python path emits NaN/Infinity literals
        frag = format_data_fragment(af, self.data.kind)
        if frag is None:
            return None
        out: dict = {"meta": self.meta.to_json_dict()}
        if self.status is not None:
            out["status"] = self.status.to_json_dict()
        data_obj: dict = {}
        if self.data.names:
            data_obj["names"] = list(self.data.names)
        data_obj["__payload__"] = 0
        out["data"] = data_obj
        s = json.dumps(out, separators=(",", ":"))
        # splice at the LAST occurrence: ours is inside "data", which is the
        # final member of `out`; an adversarial meta tag *key* named
        # __payload__ serializes earlier (string values can't match at all —
        # their quotes get escaped)
        marker = '"__payload__":0'
        idx = s.rfind(marker)
        return s[:idx] + frag.decode("ascii") + s[idx + len(marker):]

    @staticmethod
    def from_json_dict(d: Mapping[str, Any], dtype=np.float64) -> "SeldonMessage":
        if not isinstance(d, Mapping):
            raise SeldonMessageError("SeldonMessage JSON must be an object")
        # Protobuf JsonFormat treats explicit nulls as absent fields — do the same.
        meta = d.get("meta") or {}
        if not isinstance(meta, Mapping):
            raise SeldonMessageError("meta must be an object")
        status = d.get("status")
        if status is not None and not isinstance(status, Mapping):
            raise SeldonMessageError("status must be an object")
        msg = SeldonMessage(
            meta=Meta.from_json_dict(meta),
            status=Status.from_json_dict(status) if status is not None else None,
        )
        if d.get("data") is not None:
            data = d["data"]
            if not isinstance(data, Mapping):
                raise SeldonMessageError("data must be an object")
            msg.data = DefaultData.from_json_dict(data, dtype=dtype)
        elif d.get("binData") is not None:
            import base64
            import binascii

            try:
                msg.bin_data = base64.b64decode(d["binData"], validate=True)
            except (binascii.Error, TypeError, ValueError) as e:
                raise SeldonMessageError(f"binData is not valid base64: {e}") from e
        elif d.get("strData") is not None:
            msg.str_data = str(d["strData"])
        return msg

    @staticmethod
    def from_json(s: Union[str, bytes], dtype=np.float64) -> "SeldonMessage":
        fast = SeldonMessage._from_json_fast(s, dtype)
        if fast is not None:
            return fast
        try:
            d = json.loads(s)
        except json.JSONDecodeError as e:
            raise SeldonMessageError(f"invalid JSON: {e}") from e
        return SeldonMessage.from_json_dict(d, dtype=dtype)

    @staticmethod
    def _from_json_fast(s: Union[str, bytes], dtype) -> Optional["SeldonMessage"]:
        """Native-codec parse (native/fastcodec.cpp).  The C++ side hands back
        the message envelope (numeric payload removed) plus the payload as a
        contiguous buffer; anything it declines — including invalid JSON —
        returns None so the pure-Python parser owns error behaviour."""
        try:
            from seldon_core_tpu.native.fastcodec import parse_message_fast
        except ImportError:  # pragma: no cover
            return None
        fast = parse_message_fast(s)
        if fast is None:
            return None
        envelope, kind, arr = fast
        data_env = envelope.pop("data", None)
        msg = SeldonMessage.from_json_dict(envelope, dtype=dtype)
        if kind is not None:
            if np.dtype(dtype) != arr.dtype:
                arr = arr.astype(dtype)
            names = list((data_env or {}).get("names", []) or [])
            msg.data = DefaultData(array=arr, names=names, kind=kind)
        elif data_env is not None:
            # a data object with no payload member fails like the python path
            raise SeldonMessageError("data must contain 'tensor' or 'ndarray'")
        return msg


# ---------------------------------------------------------------------------
# SeldonMessageList / Feedback
# ---------------------------------------------------------------------------


@dataclass
class SeldonMessageList:
    """COMBINER input: one message per child branch (proto/prediction.proto:51-53)."""

    messages: list = field(default_factory=list)

    def to_json_dict(self) -> dict:
        return {"seldonMessages": [m.to_json_dict() for m in self.messages]}

    @staticmethod
    def from_json_dict(d: Mapping[str, Any], dtype=np.float64) -> "SeldonMessageList":
        if not isinstance(d, Mapping):
            raise SeldonMessageError("SeldonMessageList JSON must be an object")
        return SeldonMessageList(
            messages=[
                SeldonMessage.from_json_dict(m, dtype=dtype)
                for m in d.get("seldonMessages", []) or []
            ]
        )

    @staticmethod
    def from_json(s: Union[str, bytes], dtype=np.float64) -> "SeldonMessageList":
        try:
            d = json.loads(s)
        except json.JSONDecodeError as e:
            raise SeldonMessageError(f"invalid JSON: {e}") from e
        return SeldonMessageList.from_json_dict(d, dtype=dtype)

    def to_json(self) -> str:
        return json.dumps(self.to_json_dict(), separators=(",", ":"))


@dataclass
class Feedback:
    """Online-learning signal (proto/prediction.proto:55-60): the original
    request/response pair plus a scalar reward and optional ground truth."""

    request: Optional[SeldonMessage] = None
    response: Optional[SeldonMessage] = None
    reward: float = 0.0
    truth: Optional[SeldonMessage] = None

    def puid(self) -> str:
        """Correlation id of this feedback: the served response's puid
        when present, else the original request's (a reward-only feedback
        has no response payload but still belongs to a request).  The ONE
        rule every lane shares — engine, gateway, unit apps, node
        clients."""
        if self.response is not None and self.response.meta.puid:
            return self.response.meta.puid
        if self.request is not None and self.request.meta.puid:
            return self.request.meta.puid
        return ""

    def prediction_array(self) -> Optional[np.ndarray]:
        """The served prediction tensor (``response.data``) as numpy, or
        None — the ONE truth-vs-prediction plumbing rule every feedback
        consumer shares (engine quality accounting, gateway ingress,
        unit runtimes)."""
        if self.response is not None and self.response.data is not None:
            return np.asarray(self.response.array())
        return None

    def truth_array(self) -> Optional[np.ndarray]:
        """The ground-truth tensor (``truth.data``) as numpy, or None."""
        if self.truth is not None and self.truth.data is not None:
            return np.asarray(self.truth.array())
        return None

    def to_json_dict(self) -> dict:
        out: dict = {"reward": float(self.reward)}
        if self.request is not None:
            out["request"] = self.request.to_json_dict()
        if self.response is not None:
            out["response"] = self.response.to_json_dict()
        if self.truth is not None:
            out["truth"] = self.truth.to_json_dict()
        return out

    @staticmethod
    def from_json_dict(d: Mapping[str, Any], dtype=np.float64) -> "Feedback":
        if not isinstance(d, Mapping):
            raise SeldonMessageError("Feedback JSON must be an object")

        def _msg(key: str) -> Optional[SeldonMessage]:
            v = d.get(key)
            return SeldonMessage.from_json_dict(v, dtype=dtype) if v is not None else None

        try:
            reward = float(d.get("reward", 0.0) or 0.0)
        except (TypeError, ValueError) as e:
            raise SeldonMessageError(f"malformed reward: {e}") from e
        return Feedback(
            request=_msg("request"),
            response=_msg("response"),
            reward=reward,
            truth=_msg("truth"),
        )

    @staticmethod
    def from_json(s: Union[str, bytes], dtype=np.float64) -> "Feedback":
        try:
            d = json.loads(s)
        except json.JSONDecodeError as e:
            raise SeldonMessageError(f"invalid JSON: {e}") from e
        return Feedback.from_json_dict(d, dtype=dtype)

    def to_json(self) -> str:
        return json.dumps(self.to_json_dict(), separators=(",", ":"))


def prediction_delta(live: Optional["SeldonMessage"],
                     other: Optional["SeldonMessage"],
                     atol: float = 1e-6) -> dict:
    """Compare two prediction messages — the ONE disagreement rule the
    shadow mirror (gateway/shadow.py), the firehose replayer
    (runtime/replay.py) and their tests share, so "divergence" means the
    same thing whether a candidate is vetted offline or mirrored live.

    Returns ``{"comparable": bool, "disagree": float, "mean_abs_delta":
    float|None}``:

      * classification shape ([rows, >1 classes] on both sides):
        ``disagree`` = fraction of rows whose argmax differs — a score
        wiggle below the decision boundary is NOT a disagreement;
      * everything else numeric: ``disagree`` = fraction of elements
        differing by more than ``atol``;
      * error/shape/kind mismatches: ``comparable=False`` with
        ``disagree`` pinned to 1.0 (a candidate that errors or changes
        the output contract disagrees maximally by definition).
    """
    full = {"comparable": False, "disagree": 1.0, "mean_abs_delta": None}

    def _failed(m: Optional["SeldonMessage"]) -> bool:
        return (m is None
                or (m.status is not None and m.status.status == "FAILURE"))

    if _failed(live) and _failed(other):
        # both sides failed: they AGREE (an error-for-error candidate is
        # not diverging, it is faithfully reproducing the baseline)
        return {"comparable": False, "disagree": 0.0,
                "mean_abs_delta": None}
    if _failed(live) or _failed(other):
        return full
    if live.data_kind != other.data_kind:
        return full
    if live.data_kind != "data":
        # non-tensor payloads (strData/binData): byte-equality is the
        # only defensible comparison
        same = (live.str_data == other.str_data
                and live.bin_data == other.bin_data)
        return {"comparable": True, "disagree": 0.0 if same else 1.0,
                "mean_abs_delta": None}
    a = np.asarray(live.array(), dtype=np.float64)
    b = np.asarray(other.array(), dtype=np.float64)
    if a.shape != b.shape or a.size == 0:
        return full
    mean_abs = float(np.mean(np.abs(a - b)))
    if a.ndim == 2 and a.shape[1] > 1:
        disagree = float(np.mean(
            np.argmax(a, axis=1) != np.argmax(b, axis=1)
        ))
    else:
        disagree = float(np.mean(np.abs(a - b) > atol))
    return {"comparable": True, "disagree": disagree,
            "mean_abs_delta": round(mean_abs, 9)}
