"""Int8 post-training quantization for the serving path.

v5e's MXU runs int8 matmuls at ~2x its bf16 rate, and int8 weights halve
HBM traffic — the classic serving trade.  This module provides:

  * ``quantize_weight``  — symmetric per-output-channel int8 weights with
    f32 scales (no zero points: keeps the MXU path a plain integer dot).
  * ``quant_matmul``     — dynamic per-row activation quantization, int8 x
    int8 -> int32 dot on the MXU, dequantized with row * column scales.
  * ``QuantizedMLP``     — drop-in for the dense-MLP forward
    (models/mnist.py layout): quantize once at load, serve int8.

Accuracy contract: dynamic symmetric int8 keeps softmax argmax stable for
well-scaled classifier MLPs (tests pin >=95% argmax agreement vs f32 on
random UNTRAINED models — the worst case; trained heads agree higher); it is a SERVING path — training stays in bf16/f32.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

__all__ = ["quantize_weight", "quant_matmul", "quantize_mlp_params",
           "QuantizedMLP"]


def quantize_weight(w) -> Tuple[jax.Array, jax.Array]:
    """w [in, out] -> (w_q int8 [in, out], scales f32 [out]).

    Symmetric per-output-channel: scale = absmax / 127."""
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0)  # [out]
    scales = jnp.maximum(absmax, 1e-12) / 127.0
    w_q = jnp.clip(jnp.round(w.astype(jnp.float32) / scales), -127, 127)
    return w_q.astype(jnp.int8), scales


def quant_matmul(x, w_q, w_scales):
    """x [B, in] (float) @ int8 weights -> f32 [B, out].

    Activations quantize dynamically per row (symmetric absmax); the dot
    runs int8 x int8 -> int32 on the MXU; dequantization multiplies the
    row scale back with the per-channel weight scale."""
    x32 = x.astype(jnp.float32)
    row_absmax = jnp.max(jnp.abs(x32), axis=1, keepdims=True)  # [B, 1]
    row_scales = jnp.maximum(row_absmax, 1e-12) / 127.0
    x_q = jnp.clip(jnp.round(x32 / row_scales), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        x_q, w_q, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [B, out] int32
    return acc.astype(jnp.float32) * row_scales * w_scales[None, :]


def quantize_mlp_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """models/mnist.py mlp layout {w0,b0,...,wL,bL} -> quantized variant
    {w0_q, w0_s, b0, ...}.  Biases stay f32."""
    out: Dict[str, Any] = {}
    n_layers = len(params) // 2
    for i in range(n_layers):
        w_q, s = quantize_weight(params[f"w{i}"])
        out[f"w{i}_q"] = w_q
        out[f"w{i}_s"] = s
        out[f"b{i}"] = params[f"b{i}"].astype(jnp.float32)
    return out


class QuantizedMLP:
    """Int8 forward for the dense-MLP layout: relu hidden layers, f32
    softmax head — mirrors models/mnist.py mlp_apply numerics modulo
    quantization error."""

    @staticmethod
    def apply(qparams: Dict[str, Any], x) -> jax.Array:
        n_layers = len(qparams) // 3
        h = x
        for i in range(n_layers):
            h = quant_matmul(h, qparams[f"w{i}_q"], qparams[f"w{i}_s"])
            h = h + qparams[f"b{i}"]
            if i < n_layers - 1:
                h = jnp.maximum(h, 0.0)
        return jax.nn.softmax(h, axis=-1)
