"""Int8 post-training quantization for the serving path.

Int8 weights halve HBM traffic — and serving matmuls at decode batch
sizes are weight-bandwidth-bound, so weight bytes are the lever.  This
module provides:

  * ``quantize_weight``  — symmetric per-output-channel int8 weights with
    f32 scales (no zero points), computed in host numpy at LOAD time.
  * ``dequant_matmul``   — weight-only int8 ("W8A16"): XLA fuses the
    convert+scale into the dot's weight read, so weights stream at int8
    size with bf16 compute.  THE serving path (measured 1.3x faster than
    bf16 on v5e decode shapes; activations never quantized).
  * ``quant_matmul``     — the classic W8A8 formulation (dynamic per-row
    activation quantization, int8 x int8 -> int32 on the MXU).  Kept for
    reference/completeness: at serving batch sizes the activation
    quantization overhead EXCEEDS the int8 MXU rate's return (measured
    ~1.9x slower than bf16 at B=32) — it pays off only when both operands
    are large.
  * ``QuantizedMLP``     — drop-in for the dense-MLP forward
    (models/mnist.py layout): quantize once at load, serve int8.

Accuracy contract: weight-only rounding keeps softmax argmax stable for
classifier MLPs (tests pin >=95% argmax agreement vs f32 on random
UNTRAINED models — the worst case; trained heads agree higher); it is a
SERVING path — training stays in bf16/f32.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

__all__ = ["quantize_weight", "quant_matmul", "dequant_matmul",
           "quantize_mlp_params", "QuantizedMLP", "quantize_lm_params",
           "lm_matmul", "LM_QUANT_NAMES"]


def quantize_weight(w) -> Tuple[jax.Array, jax.Array]:
    """w [in, out] -> (w_q int8 [in, out], scales f32 [out]).

    Symmetric per-output-channel: scale = absmax / 127.

    Computed in host numpy deliberately: quantization is a one-time LOAD
    transform, and doing it eagerly on-device fires a burst of tiny XLA
    compiles per layer (slow everywhere, and abusive to remote-compile
    services); the serving-path dots (dequant_matmul) stay on-device."""
    import numpy as np

    w_np = np.asarray(w, dtype=np.float32)  # device -> host once
    absmax = np.abs(w_np).max(axis=0)  # [out]
    scales = np.maximum(absmax, 1e-12) / 127.0
    w_q = np.clip(np.round(w_np / scales), -127, 127).astype(np.int8)
    return jnp.asarray(w_q), jnp.asarray(scales.astype(np.float32))


def quant_matmul(x, w_q, w_scales):
    """x [..., in] (float) @ int8 weights -> f32 [..., out].

    Activations quantize dynamically per row (symmetric absmax over the
    contracted axis); the dot runs int8 x int8 -> int32 on the MXU;
    dequantization multiplies the row scale back with the per-channel
    weight scale.  Leading dims are flattened for the dot and restored."""
    lead = x.shape[:-1]
    x32 = x.reshape((-1, x.shape[-1])).astype(jnp.float32)
    row_absmax = jnp.max(jnp.abs(x32), axis=1, keepdims=True)  # [B, 1]
    row_scales = jnp.maximum(row_absmax, 1e-12) / 127.0
    x_q = jnp.clip(jnp.round(x32 / row_scales), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        x_q, w_q, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )  # [B, out] int32
    y = acc.astype(jnp.float32) * row_scales * w_scales[None, :]
    return y.reshape(lead + (w_q.shape[1],))


def dequant_matmul(x, w_q, w_scales, out_dtype=None):
    """Weight-only int8 ("W8A16"): x [..., in] @ dequantized int8 weights.

    XLA fuses the int8->bf16 convert and per-channel scale into the dot's
    weight-operand read, so the weights STREAM at int8 size and no bf16
    copy materialises.  Measured on v5e decode shapes
    ([32,1024]x[1024,4096], chained 16k-rep scan): **1.3x faster than the
    bf16 matmul**, while the dynamic-activation W8A8 path (quant_matmul)
    is ~1.9x SLOWER there — per-row activation quantization costs more
    than the int8 MXU rate returns at serving batch sizes.  Accuracy:
    only weight rounding error (no activation quantization at all) —
    int8 values are EXACT in bf16, and the f32 per-channel scales apply
    to the f32-accumulated OUTPUT (cheaper than scaling the [in, out]
    weights and avoids a second bf16 rounding)."""
    ct = x.dtype if x.dtype in (jnp.bfloat16, jnp.float16) else jnp.bfloat16
    # trailing-axis broadcast (no [None, :]): a rank-1 x yields a rank-1
    # [out] result, matching the plain-matmul path for any input rank
    y = jax.lax.dot_general(
        x.astype(ct), w_q.astype(ct), (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * w_scales
    return y.astype(out_dtype) if out_dtype is not None else y


def quantize_mlp_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """models/mnist.py mlp layout {w0,b0,...,wL,bL} -> quantized variant
    {w0_q, w0_s, b0, ...}.  Biases stay f32."""
    out: Dict[str, Any] = {}
    n_layers = len(params) // 2
    for i in range(n_layers):
        w_q, s = quantize_weight(params[f"w{i}"])
        out[f"w{i}_q"] = w_q
        out[f"w{i}_s"] = s
        out[f"b{i}"] = params[f"b{i}"].astype(jnp.float32)
    return out


# transformer-layer weights that quantize (models/transformer.py layout);
# embed/unembed and norm scales stay in the model dtype — the embedding
# gather has no matmul to win back, and the tied unembed head is the
# quality-critical projection
LM_QUANT_NAMES = ("wqkv", "wo", "w1", "w2")


def quantize_lm_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """models/transformer.py ``lm_init`` tree -> int8 serving variant.

    Each layer weight ``w`` in LM_QUANT_NAMES becomes ``w_q`` (int8) +
    ``w_s`` (f32 per-output-channel scales); everything else passes
    through.  Serving matmuls stream the whole weight set per step, so
    halving weight bytes is the lever; the weight-only dequant_matmul
    formulation serves them (the module docstring records the measured
    trade-offs).  Serving-only: int8 weights are not differentiable —
    training stays bf16."""
    out: Dict[str, Any] = {}
    for key, val in params.items():
        if not (isinstance(val, dict) and "wqkv" in val):
            out[key] = val
            continue
        lp: Dict[str, Any] = {}
        for name, w in val.items():
            if name in LM_QUANT_NAMES:
                w_q, s = quantize_weight(w)
                lp[f"{name}_q"] = w_q
                lp[f"{name}_s"] = s
            else:
                lp[name] = w
        out[key] = lp
    return out


def lm_matmul(lp: Dict[str, Any], name: str, h, out_dtype=None):
    """``h @ lp[name]`` dispatching on quantization: layers carrying
    ``{name}_q``/``{name}_s`` (quantize_lm_params) take the weight-only
    int8 path (dequant_matmul — the MEASURED-faster serving formulation),
    else the plain dense matmul.  ``out_dtype`` casts the result
    (quantized paths accumulate f32; attention wants the model dtype
    back)."""
    if f"{name}_q" in lp:
        return dequant_matmul(h, lp[f"{name}_q"], lp[f"{name}_s"],
                              out_dtype=out_dtype)
    y = h @ lp[name]
    if out_dtype is not None and y.dtype != out_dtype:
        y = y.astype(out_dtype)
    return y


class QuantizedMLP:
    """Int8 forward for the dense-MLP layout: relu hidden layers, f32
    softmax head — mirrors models/mnist.py mlp_apply numerics modulo
    (weight-only) quantization error.  Uses dequant_matmul: faster than
    both bf16 and the W8A8 formulation at serving batches, and strictly
    more accurate than W8A8 (activations are never quantized)."""

    @staticmethod
    def apply(qparams: Dict[str, Any], x) -> jax.Array:
        n_layers = len(qparams) // 3
        h = x
        for i in range(n_layers):
            h = dequant_matmul(h, qparams[f"w{i}_q"], qparams[f"w{i}_s"])
            h = h + qparams[f"b{i}"]  # stored f32 at load (quantize_mlp_params)
            if i < n_layers - 1:
                h = jnp.maximum(h, 0.0)
        return jax.nn.softmax(h, axis=-1)
