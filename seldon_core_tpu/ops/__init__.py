"""Pallas TPU kernels for the serving hot path."""

from seldon_core_tpu.ops.fused_mlp import (  # noqa: F401
    fused_mlp_softmax,
    pallas_supported,
)
from seldon_core_tpu.ops.flash_attention import flash_attention  # noqa: F401
from seldon_core_tpu.ops.quant import (  # noqa: F401
    dequant_matmul,
    QuantizedMLP,
    quant_matmul,
    quantize_weight,
)
