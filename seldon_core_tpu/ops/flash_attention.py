"""Flash-attention Pallas kernel — block-wise online-softmax attention.

Single-chip sibling of the ring attention layer (parallel/ring_attention.py
handles the cross-chip sp axis; this kernel handles the within-chip block
loop).  Plain XLA attention materialises the [S, S] score matrix in HBM;
this kernel tiles Q and K/V into VMEM blocks and accumulates the softmax
online (running max ``m``, normaliser ``l``, weighted sum ``acc``), so HBM
traffic is O(S*D) and the score matrix never exists.

Layout: grid (B*H, S/bq, S/bk) — the K-block axis is innermost, so the
(m, l, acc) VMEM scratch carries across K steps of one Q block; the m/l
stats live in one native [bq, 128] lane tile (values broadcast across the
128 lanes — lane-sliced [:, :1] reads recover them).  Causality is
applied by global-position masking.

``flash_attention`` raises ValueError when its constraints don't hold
(S % 128, head dim <= 256); callers fall back to the XLA path.

Training: the op carries a custom VJP (flash-attention backward — recompute
p from the saved per-row log-sum-exp, never materialise [S, S] in HBM).
dQ runs on a (heads, q-block, k-block) grid accumulating over K blocks;
dK/dV run on a (heads, k-block, q-block) grid accumulating over Q blocks —
two passes instead of atomics, the standard TPU formulation.  Gradients
match the XLA attention VJP to ~1e-5 in f32 (tests/test_flash_attention.py).

Block sizes auto-select LARGE — bq up to 512, bk up to 1024 (divisibility
permitting): per-grid-step overhead (~1 us) dominates the per-block dot
at moderate S long before the MXU does, and a wider q block also divides
total K/V streaming by bq/128.  The round-3 kernel (bq=128, bk<=512,
[bq, bk] broadcast stats) measured 1.4x XLA at S=8192 but 1.7x SLOWER at
S=2048, which set ``FLASH_AUTO_MIN_S``; the current shape is re-measured
by bench.py's ``flash_vs_xla_x`` keys each round and the auto threshold
follows those measurements.  ``attention="flash"`` forces the kernel at
any length.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["flash_attention"]

_BLOCK = 128
_NEG_INF = -1e30


#: stats scratch lane width — one native VPU tile, independent of bk (the
#: round-3 kernel kept [bq, bk] broadcast stats, which at bk=512 burned
#: VPU time rebroadcasting [bq, 512] tiles every block step)
_STATS_LANES = 128


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
                  *, causal: bool, scale: float, n_k: int,
                  bq: int = _BLOCK, bk: int = _BLOCK):
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # causal: blocks strictly above the diagonal are fully masked — skip
    # their dots entirely (halves the causal FLOPs; XLA's fused attention
    # cannot skip, it masks after materialising the scores)
    @pl.when(jnp.logical_or(not causal, ik * bk <= iq * bq + (bq - 1)))
    def _compute():
        q = q_ref[0]  # [bq, D]
        k = k_ref[0]  # [bk, D]
        v = v_ref[0]  # [bk, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bk]
        if causal:
            qpos = iq * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0
            )
            kpos = ik * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1
            )
            s = jnp.where(qpos >= kpos, s, _NEG_INF)

        m_prev = m_ref[:][:, :1]                        # [bq, 1]
        l_prev = l_ref[:][:, :1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_cur)                 # [bq, 1]
        p = jnp.exp(s - m_cur)                          # [bq, bk] (bcast sub)
        l_cur = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:] = jnp.broadcast_to(m_cur, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_cur, l_ref.shape)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ik == n_k - 1)
    def _done():
        # fully-masked rows (can't happen causally, but keep the guard
        # for masked variants) divide by at least 1
        l_fin = l_ref[:][:, :1]
        o_ref[0] = (
            acc_ref[:] / jnp.maximum(l_fin, 1e-30)
        ).astype(o_ref.dtype)
        # per-row log-sum-exp of the scaled scores, saved for the backward
        # pass (p is recomputed there as exp(s - lse))
        lse_ref[0] = m_ref[:][:, :1] + jnp.log(
            jnp.maximum(l_fin, 1e-30)
        )


def _validate(q, k, v):
    if k.shape != v.shape:
        raise ValueError(f"k/v shapes differ: {k.shape} {v.shape}")
    if q.ndim != 4 or k.ndim != 4:
        raise ValueError(f"expected [B, H, S, D], got {q.shape} {k.shape}")
    B, H, S, D = q.shape
    KV = k.shape[1]
    if k.shape[0] != B or k.shape[2] != S or k.shape[3] != D:
        raise ValueError(f"q/k shapes differ: {q.shape} {k.shape}")
    if KV == 0 or H % KV != 0:
        raise ValueError(f"query heads {H} not a multiple of kv heads {KV}")
    if S % _BLOCK != 0:
        raise ValueError(f"seq len {S} not divisible by {_BLOCK}")
    if D > 256:
        raise ValueError(f"head dim {D} > 256")
    return B, H, S, D


def _fwd_impl(q, k, v, causal: bool, interpret: bool):
    """Returns (out [B,H,S,D], lse [B*H,S,1] f32).

    Grouped K/V (GQA, k/v [B, KV, S, D] with KV < H) is native: the K/V
    BlockSpec index maps fold the query-head -> kv-head mapping, so K/V
    stream from HBM at their stored (grouped) size — no head repeat."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, S, D = _validate(q, k, v)
    KV = k.shape[1]
    g = H // KV
    # large blocks are the moderate-S lever: per-grid-step overhead (~1 us)
    # dominates the tiny per-block dot long before the MXU does, and a
    # wider q block also divides total K/V streaming by bq/128.  VMEM holds
    # the [bq, bk] f32 score tile + [bk, D] K/V tiles comfortably at
    # 512x1024xD<=256 (~6 MB with double buffering, of ~16 MB)
    bq = max(b for b in (512, 256, _BLOCK) if S % b == 0)
    bk = max(b for b in (1024, 512, 256, _BLOCK) if S % b == 0)
    n_k = S // bk
    scale = float(1.0 / (D ** 0.5))

    grid = (B * H, S // bq, n_k)
    qblk = lambda idx: pl.BlockSpec(  # noqa: E731
        (1, bq, D), idx, memory_space=pltpu.VMEM
    )
    kblk = lambda idx: pl.BlockSpec(  # noqa: E731
        (1, bk, D), idx, memory_space=pltpu.VMEM
    )

    if KV == H:
        # MHA: keep the identity map LITERAL — the computed form below is
        # algebraically b but defeats the pipeliner's sequential-block
        # prefetch (measured: up to 4x slower at S=8192)
        def kv_index(b):
            return b
    else:
        def kv_index(b):
            # merged q row b = bi * H + h; its kv row = bi * KV + h // g
            return (b // H) * KV + (b % H) // g

    out, lse = pl.pallas_call(
        functools.partial(
            _flash_kernel, causal=causal, scale=scale, n_k=n_k,
            bq=bq, bk=bk,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, S, 1), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            qblk(lambda b, i, j: (b, i, 0)),  # Q: follows the q-block axis
            kblk(lambda b, i, j: (kv_index(b), j, 0)),   # K (grouped)
            kblk(lambda b, i, j: (kv_index(b), j, 0)),   # V
        ],
        out_specs=(
            qblk(lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((bq, _STATS_LANES), jnp.float32),  # m (lane-bcast)
            pltpu.VMEM((bq, _STATS_LANES), jnp.float32),  # l
            pltpu.VMEM((bq, D), jnp.float32),             # acc
        ],
        interpret=interpret,
    )(q.reshape(B * H, S, D), k.reshape(B * KV, S, D),
      v.reshape(B * KV, S, D))
    return out.reshape(B, H, S, D), lse


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dsum_ref, dq_ref,
                   dq_acc, *, causal: bool, scale: float, n_k: int):
    """grid (B*H, n_q, n_k): K innermost, dq accumulates across K blocks."""
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    @pl.when(jnp.logical_or(not causal, ik <= iq))
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            qpos = iq * _BLOCK + jax.lax.broadcasted_iota(
                jnp.int32, (_BLOCK, _BLOCK), 0
            )
            kpos = ik * _BLOCK + jax.lax.broadcasted_iota(
                jnp.int32, (_BLOCK, _BLOCK), 1
            )
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0])                      # [bq, bk]
        dp = jax.lax.dot_general(                        # dO V^T  [bq, bk]
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - dsum_ref[0])                      # [bq, bk] f32
        dq_acc[:] += jax.lax.dot_general(                # dS K    [bq, D]
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(ik == n_k - 1)
    def _done():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dsum_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc,
                    *, causal: bool, scale: float, n_q: int):
    """grid (B*H, n_k, n_q): Q innermost, dk/dv accumulate across Q blocks."""
    from jax.experimental import pallas as pl

    ik = pl.program_id(1)
    iq = pl.program_id(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(jnp.logical_or(not causal, iq >= ik))
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            qpos = iq * _BLOCK + jax.lax.broadcasted_iota(
                jnp.int32, (_BLOCK, _BLOCK), 0
            )
            kpos = ik * _BLOCK + jax.lax.broadcasted_iota(
                jnp.int32, (_BLOCK, _BLOCK), 1
            )
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0])                      # [bq, bk]
        dv_acc[:] += jax.lax.dot_general(                # P^T dO  [bk, D]
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(                        # dO V^T  [bq, bk]
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - dsum_ref[0])
        dk_acc[:] += jax.lax.dot_general(                # dS^T Q  [bk, D]
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(iq == n_q - 1)
    def _done():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_impl(q, k, v, o, lse, do, causal: bool, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, S, D = q.shape
    n = S // _BLOCK
    scale = float(1.0 / (D ** 0.5))

    def merge(t):
        return t.reshape(B * H, S, D)

    qf, kf, vf, dof = merge(q), merge(k), merge(v), merge(do)
    # D_i = rowsum(dO * O): O(S*D) elementwise, XLA fuses it fine
    dsum = jnp.sum(
        dof.astype(jnp.float32) * merge(o).astype(jnp.float32),
        axis=-1, keepdims=True,
    )  # [B*H, S, 1]

    blk = lambda idx: pl.BlockSpec(  # noqa: E731
        (1, _BLOCK, D), idx, memory_space=pltpu.VMEM
    )
    row = lambda idx: pl.BlockSpec(  # noqa: E731
        (1, _BLOCK, 1), idx, memory_space=pltpu.VMEM
    )

    qside = lambda b, i, j: (b, i, 0)  # noqa: E731
    kside = lambda b, i, j: (b, j, 0)  # noqa: E731

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, causal=causal, scale=scale, n_k=n
        ),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        grid=(B * H, n, n),
        in_specs=[
            blk(qside), blk(kside), blk(kside), blk(qside),
            row(qside), row(qside),
        ],
        out_specs=blk(qside),
        scratch_shapes=[pltpu.VMEM((_BLOCK, D), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, dsum)

    # swapped grid: program_id(1) walks K blocks, program_id(2) walks Q
    qside2 = lambda b, j, i: (b, i, 0)  # noqa: E731
    kside2 = lambda b, j, i: (b, j, 0)  # noqa: E731
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, causal=causal, scale=scale, n_q=n
        ),
        out_shape=(
            jax.ShapeDtypeStruct((B * H, S, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, S, D), v.dtype),
        ),
        grid=(B * H, n, n),
        in_specs=[
            blk(qside2), blk(kside2), blk(kside2), blk(qside2),
            row(qside2), row(qside2),
        ],
        out_specs=(blk(kside2), blk(kside2)),
        scratch_shapes=[
            pltpu.VMEM((_BLOCK, D), jnp.float32),
            pltpu.VMEM((_BLOCK, D), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, dof, lse, dsum)

    unmerge = lambda t: t.reshape(B, H, S, D)  # noqa: E731
    return unmerge(dq), unmerge(dk), unmerge(dv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    interpret: bool = False,
) -> jax.Array:
    """q [B, H, S, D], k/v [B, KV, S, D] (KV divides H; KV < H = grouped-
    query attention) -> [B, H, S, D] attention output.

    Differentiable (custom flash VJP; the GQA backward group-sums the
    repeated-head dK/dV).  Constraints (ValueError otherwise, caller falls
    back to XLA): S divisible by 128, D <= 256, H a multiple of KV."""
    out, _ = _fwd_impl(q, k, v, causal, interpret)
    return out


def _flash_fwd(q, k, v, causal, interpret):
    out, lse = _fwd_impl(q, k, v, causal, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, interpret, res, do):
    q, k, v, o, lse = res
    H, KV = q.shape[1], k.shape[1]
    if KV == H:
        return _bwd_impl(q, k, v, o, lse, do, causal, interpret)
    # GQA backward: run the MHA kernels over head-repeated K/V, then sum
    # each group's dK/dV (the adjoint of the head-share).  Training-only
    # cost — the forward serving path never materialises repeated K/V.
    g = H // KV
    krep = jnp.repeat(k, g, axis=1)
    vrep = jnp.repeat(v, g, axis=1)
    dq, dk_rep, dv_rep = _bwd_impl(q, krep, vrep, o, lse, do, causal,
                                   interpret)
    B, _, S, D = k.shape
    dk = dk_rep.reshape(B, KV, g, S, D).sum(axis=2).astype(k.dtype)
    dv = dv_rep.reshape(B, KV, g, S, D).sum(axis=2).astype(v.dtype)
    return dq, dk, dv


flash_attention.defvjp(_flash_fwd, _flash_bwd)
