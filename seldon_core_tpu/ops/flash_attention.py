"""Flash-attention Pallas kernel — block-wise online-softmax attention.

Single-chip sibling of the ring attention layer (parallel/ring_attention.py
handles the cross-chip sp axis; this kernel handles the within-chip block
loop).  Plain XLA attention materialises the [S, S] score matrix in HBM;
this kernel tiles Q and K/V into VMEM blocks and accumulates the softmax
online (running max ``m``, normaliser ``l``, weighted sum ``acc``), so HBM
traffic is O(S*D) and the score matrix never exists.

Layout: grid (B*H, S/bq, S/bk) — the K-block axis is innermost, so the
(m, l, acc) VMEM scratch carries across K steps of one Q block; stats are
kept lane-broadcast ([bq, bk] blocks with bq = bk = 128) to stay on the
VPU's native tiles.  Causality is applied by global-position masking.

``flash_attention`` raises ValueError when its constraints don't hold
(S % 128, head dim <= 256); callers fall back to the XLA path.  Serving
integration: ``models/transformer.TransformerLM.predict`` uses it when
``ops.fused_mlp.pallas_supported()``; the training path keeps plain XLA
attention (this kernel defines no custom VJP).

Measured on v5e (chained-dependency timing, bf16, causal): 8.8x faster
than the XLA einsum+softmax attention at S=2048/H=8/D=128, 2.5x at
S=8192, 3.3x at S=16384 — the [S, S] HBM materialisation XLA pays grows
quadratically while this kernel's HBM traffic stays O(S*D).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["flash_attention"]

_BLOCK = 128
_NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
                  *, causal: bool, scale: float, n_k: int):
    from jax.experimental import pallas as pl

    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # causal: blocks strictly above the diagonal are fully masked — skip
    # their dots entirely (halves the causal FLOPs; XLA's fused attention
    # cannot skip, it masks after materialising the scores)
    @pl.when(jnp.logical_or(not causal, ik <= iq))
    def _compute():
        q = q_ref[0]  # [bq, D]
        k = k_ref[0]  # [bk, D]
        v = v_ref[0]  # [bk, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bk]
        if causal:
            qpos = iq * _BLOCK + jax.lax.broadcasted_iota(
                jnp.int32, (_BLOCK, _BLOCK), 0
            )
            kpos = ik * _BLOCK + jax.lax.broadcasted_iota(
                jnp.int32, (_BLOCK, _BLOCK), 1
            )
            s = jnp.where(qpos >= kpos, s, _NEG_INF)

        m_prev = m_ref[:]                               # [bq, bk] lane-bcast
        l_prev = l_ref[:]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_cur)                 # [bq, bk] lane-bcast
        p = jnp.exp(s - m_cur)                          # m_cur same per lane
        l_cur = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:] = m_cur
        l_ref[:] = l_cur
        acc_ref[:] = acc_ref[:] * alpha[:, :1] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ik == n_k - 1)
    def _done():
        # fully-masked rows (can't happen causally, but keep the guard
        # for masked variants) divide by at least 1
        o_ref[0] = (
            acc_ref[:] / jnp.maximum(l_ref[:, :1], 1e-30)
        ).astype(o_ref.dtype)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    interpret: bool = False,
) -> jax.Array:
    """[B, H, S, D] q/k/v -> [B, H, S, D] attention output.

    Constraints (ValueError otherwise, caller falls back to XLA):
    S divisible by 128, D <= 256, q/k/v same shape."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if q.shape != k.shape or q.shape != v.shape:
        raise ValueError(f"q/k/v shapes differ: {q.shape} {k.shape} {v.shape}")
    if q.ndim != 4:
        raise ValueError(f"expected [B, H, S, D], got {q.shape}")
    B, H, S, D = q.shape
    if S % _BLOCK != 0:
        raise ValueError(f"seq len {S} not divisible by {_BLOCK}")
    if D > 256:
        raise ValueError(f"head dim {D} > 256")
    n_q = S // _BLOCK
    n_k = S // _BLOCK
    scale = float(1.0 / (D ** 0.5))

    def merge(t):
        return t.reshape(B * H, S, D)

    qf, kf, vf = merge(q), merge(k), merge(v)
    grid = (B * H, n_q, n_k)
    blk = lambda idx: pl.BlockSpec(  # noqa: E731
        (1, _BLOCK, D), idx, memory_space=pltpu.VMEM
    )
    out = pl.pallas_call(
        functools.partial(
            _flash_kernel, causal=causal, scale=scale, n_k=n_k
        ),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        grid=grid,
        in_specs=[
            blk(lambda b, i, j: (b, i, 0)),   # Q: follows the q-block axis
            blk(lambda b, i, j: (b, j, 0)),   # K: follows the k-block axis
            blk(lambda b, i, j: (b, j, 0)),   # V
        ],
        out_specs=blk(lambda b, i, j: (b, i, 0)),
        scratch_shapes=[
            pltpu.VMEM((_BLOCK, _BLOCK), jnp.float32),  # m (lane-broadcast)
            pltpu.VMEM((_BLOCK, _BLOCK), jnp.float32),  # l
            pltpu.VMEM((_BLOCK, D), jnp.float32),       # acc
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(B, H, S, D)
