"""Fused MLP-forward Pallas kernel — the serving flagship's hot op.

The reference's "model math" is a TF session per microservice
(examples/models/deep_mnist/DeepMnist.py:1-17); the TPU-native equivalent
keeps the whole forward in one kernel:

    probs = softmax(relu(x @ w0 + b0) ... @ wL + bL)

Under plain XLA each layer's activation round-trips HBM between fused
regions; this kernel tiles the batch, keeps every weight and intermediate in
VMEM, and runs matmul -> bias -> relu -> ... -> softmax per batch tile with
zero HBM traffic for intermediates.  Weights are bf16 (MXU-native), the
final logits and softmax accumulate in f32.

Weight VMEM budget: all layers must fit (~16 MB/core); serving MLPs
(784x512x512x10 bf16 ~= 1.3 MB) are far under it.  ``fused_mlp_softmax``
checks the budget and shape constraints and raises ``ValueError`` when the
kernel doesn't apply — callers fall back to the XLA path
(models/mnist.py mlp_apply).

``pallas_supported()`` probes the runtime once (compiles a trivial kernel);
serving code uses it to pick the kernel path at unit-construction time, so
the decision is static under jit.
"""

from __future__ import annotations

import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp

__all__ = ["fused_mlp_softmax", "pallas_supported"]

_VMEM_BUDGET_BYTES = 12 * 1024 * 1024  # leave headroom under ~16 MB/core


def _layer_params(params: Dict[str, Any]):
    n_layers = len(params) // 2
    return [(params[f"w{i}"], params[f"b{i}"]) for i in range(n_layers)]


def _mlp_kernel(*refs, n_layers: int):
    """refs = (x_ref, w0, b0, w1, b1, ..., out_ref).  One batch tile: all
    layers + softmax computed entirely in VMEM."""
    x_ref = refs[0]
    out_ref = refs[-1]
    h = x_ref[:]
    for i in range(n_layers):
        w_ref, b_ref = refs[1 + 2 * i], refs[2 + 2 * i]
        w = w_ref[:]
        h = jnp.dot(
            h.astype(w.dtype), w, preferred_element_type=jnp.float32
        ) + b_ref[:].astype(jnp.float32)
        if i < n_layers - 1:
            h = jnp.maximum(h, 0.0)
    # softmax in f32 (numerically-stable shift)
    h = h - jnp.max(h, axis=-1, keepdims=True)
    e = jnp.exp(h)
    out_ref[:] = e / jnp.sum(e, axis=-1, keepdims=True)


def fused_mlp_softmax(
    params: Dict[str, Any],
    x: jax.Array,
    *,
    block_b: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """softmax(mlp(x)) fused in one Pallas kernel.

    params: flat dict {w0,b0,...,wL,bL} (models/mnist.py mlp_init layout);
    x: [B, in_dim] float array.  Returns [B, out_dim] float32 probabilities.
    Raises ValueError when the kernel's constraints don't hold (caller falls
    back to XLA)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    layers = _layer_params(params)
    if not layers:
        raise ValueError("empty params")
    if x.ndim != 2:
        raise ValueError(f"x must be [B, D], got {x.shape}")
    in_dim = layers[0][0].shape[0]
    out_dim = layers[-1][0].shape[1]
    if x.shape[1] != in_dim:
        raise ValueError(f"x dim {x.shape[1]} != w0 in_dim {in_dim}")
    weight_bytes = sum(w.size * w.dtype.itemsize + b.size * b.dtype.itemsize
                       for w, b in layers)
    # x tile + widest activation tile, f32
    act_bytes = 4 * block_b * (in_dim + max(w.shape[1] for w, _ in layers))
    if weight_bytes + act_bytes > _VMEM_BUDGET_BYTES:
        raise ValueError(
            f"fused MLP needs ~{(weight_bytes + act_bytes) >> 20} MiB VMEM "
            f"(budget {_VMEM_BUDGET_BYTES >> 20} MiB)"
        )

    B = x.shape[0]
    block_b = min(block_b, max(B, 1))
    grid = (pl.cdiv(B, block_b),)

    # x is tiled over the batch grid; weights/biases are whole-array blocks
    # (the same VMEM-resident block every step — Mosaic hoists the copies)
    in_specs = [
        pl.BlockSpec((block_b, in_dim), lambda i: (i, 0),
                     memory_space=pltpu.VMEM)
    ]
    flat_inputs = [x]
    for w, b in layers:
        in_specs.append(pl.BlockSpec(w.shape, lambda i: (0, 0),
                                     memory_space=pltpu.VMEM))
        # biases as [1, D] — TPU VMEM wants >=2D tiles
        in_specs.append(pl.BlockSpec((1, b.shape[0]), lambda i: (0, 0),
                                     memory_space=pltpu.VMEM))
        flat_inputs += [w, b.reshape(1, -1)]

    fn = pl.pallas_call(
        functools.partial(_mlp_kernel, n_layers=len(layers)),
        out_shape=jax.ShapeDtypeStruct((B, out_dim), jnp.float32),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((block_b, out_dim), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )
    return fn(*flat_inputs)


_PALLAS_PROBE: "bool | None" = None


def pallas_supported() -> bool:
    """True when the default backend compiles+runs a trivial Pallas TPU
    kernel.  Probed once per process — but NEVER probed (or cached) inside
    a jit trace, where the float() readback would raise and pin a spurious
    False for the whole process; under a trace we answer from the backend
    platform instead."""
    global _PALLAS_PROBE
    if _PALLAS_PROBE is not None:
        return _PALLAS_PROBE
    try:
        from jax._src.core import trace_state_clean
    except ImportError:  # pragma: no cover - private-API drift
        trace_state_clean = None
    if trace_state_clean is not None and not trace_state_clean():
        import jax

        return jax.default_backend() == "tpu"  # uncached best answer
    _PALLAS_PROBE = _pallas_probe()
    return _PALLAS_PROBE


def _pallas_probe() -> bool:
    try:
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def k(i_ref, o_ref):
            o_ref[:] = i_ref[:] * 2.0

        x = jnp.ones((8, 128), jnp.float32)
        y = pl.pallas_call(
            k,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        )(x)
        return bool(abs(float(y[0, 0]) - 2.0) < 1e-6)
    except Exception:  # noqa: BLE001 - any lowering/runtime failure => no
        return False
