"""Flash-decode Pallas kernel — single-token cached attention in ONE pass.

The cached decode step's attention (models/generate.py:_attend_cached) is
the serving hot loop: every generated token streams the whole KV cache
from HBM.  This kernel fuses the dot -> mask/softmax -> dot chain
(classic flash-decoding): K/V blocks stream through VMEM once, the
softmax runs online (running max ``m``, normaliser ``l``, weighted
accumulator ``acc``), and the [*, L] score row never exists in HBM.

GQA-native like ops/flash_attention.py: q arrives as [B, KV, G, hd]
(the group's query heads folded onto the sublane axis), K/V at their
stored grouped size [B, KV, L, hd].  The valid-length mask (positions >=
n_valid are preallocated-but-unwritten cache slots) rides a prefetched
scalar.

STATUS — correct but NOT wired into serving, and now SUPERSEDED: round 3
measured this kernel ~1.6-2.3x SLOWER per decode step than the
grouped-XLA formulation (the (B*KV, L/128) grid serializes tiny [G, 128]
dots where XLA runs a few large batched ones).  Round 4 then found the
real decode bottleneck was never the attention math at all but cache
mutation inside the scan (dus + layout copies), fixed by the two-tier
cache in models/generate.py — after which the isolated XLA attention
read streams at ~70-80% of measured HBM bandwidth (scripts/
probe_layout.py), leaving a fused decode kernel little to win.  The op
stays for the kernel-correctness suite and as a starting point should a
batch-blocked variant ever be worth measuring again.

Constraints (ValueError): L divisible by 128, hd <= 256.  NOTE:
``models/generate.py:init_cache`` allocates EXACT lengths (it does NOT
round up to this kernel's block — see its comment), so wiring this op
into serving would also require length padding at the call sites.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["flash_decode", "flash_decode_supported"]

_BLOCK = 128
_NEG_INF = -1e30


def _decode_kernel(nv_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
                   acc_ref, *, n_k: int, scale: float):
    from jax.experimental import pallas as pl

    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0]  # [G, hd]
    k = k_ref[0]  # [BLK, hd]
    v = v_ref[0]  # [BLK, hd]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [G, BLK]
    pos = j * _BLOCK + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(pos < nv_ref[0], s, _NEG_INF)

    m_prev = m_ref[:]  # [G, BLK] lane-broadcast stats
    l_prev = l_ref[:]
    m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_cur)
    p = jnp.exp(s - m_cur)
    l_ref[:] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
    m_ref[:] = m_cur
    acc_ref[:] = acc_ref[:] * alpha[:, :1] + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(j == n_k - 1)
    def _done():
        o_ref[0] = (
            acc_ref[:] / jnp.maximum(l_ref[:, :1], 1e-30)
        ).astype(o_ref.dtype)


def flash_decode(q, k, v, n_valid, interpret: bool = False) -> jax.Array:
    """q [B, KV, G, hd] x cache k/v [B, KV, L, hd] -> [B, KV, G, hd].

    ``n_valid``: scalar int — cache positions >= n_valid are masked.
    Numerics match models/generate.py:_attend_cached (f32 online softmax
    over stored-dtype K/V reads)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if q.ndim != 4 or k.ndim != 4 or k.shape != v.shape:
        raise ValueError(f"bad shapes: q{q.shape} k{k.shape} v{v.shape}")
    B, KV, G, hd = q.shape
    L = k.shape[2]
    if k.shape[0] != B or k.shape[1] != KV or k.shape[3] != hd:
        raise ValueError(f"q/k mismatch: q{q.shape} k{k.shape}")
    if L % _BLOCK != 0:
        raise ValueError(f"cache len {L} not divisible by {_BLOCK}")
    if hd > 256:
        raise ValueError(f"head dim {hd} > 256")
    n_k = L // _BLOCK
    scale = float(1.0 / (hd ** 0.5))
    nv = jnp.asarray(n_valid, jnp.int32).reshape((1,))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B * KV, n_k),
        in_specs=[
            pl.BlockSpec((1, G, hd), lambda b, j, nv_ref: (b, 0, 0)),
            pl.BlockSpec((1, _BLOCK, hd), lambda b, j, nv_ref: (b, j, 0)),
            pl.BlockSpec((1, _BLOCK, hd), lambda b, j, nv_ref: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, G, hd), lambda b, j, nv_ref: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((G, _BLOCK), jnp.float32),  # m (lane-broadcast)
            pltpu.VMEM((G, _BLOCK), jnp.float32),  # l
            pltpu.VMEM((G, hd), jnp.float32),      # acc
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, n_k=n_k, scale=scale),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B * KV, G, hd), k.dtype),
        interpret=interpret,
    )(nv, q.reshape(B * KV, G, hd), k.reshape(B * KV, L, hd),
      v.reshape(B * KV, L, hd))
    return out.reshape(B, KV, G, hd)


@functools.lru_cache(maxsize=1)
def flash_decode_supported() -> bool:
    """One-time runtime probe (static under jit) — mirrors
    ops/fused_mlp.pallas_supported for the decode kernel.

    Probes via an explicit AOT lower+compile: the first call often happens
    INSIDE another function's trace, where an eager pallas_call only
    records a jaxpr and the backend's can't-lower error would surface
    later, from the caller's compile — AOT compilation forces it here."""
    try:
        q = jax.ShapeDtypeStruct((1, 1, 1, 128), jnp.float32)
        kv = jax.ShapeDtypeStruct((1, 1, 128, 128), jnp.float32)
        jax.jit(
            lambda q_, k_, nv: flash_decode(q_, k_, k_, nv)
        ).lower(q, kv, jax.ShapeDtypeStruct((), jnp.int32)).compile()
        return True
    except Exception:  # noqa: BLE001 - any backend/lowering failure
        return False
