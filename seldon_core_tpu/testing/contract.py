"""Contract-based request generation — per-model schema fuzzing.

The reference drives every example model from a ``contract.json`` describing
feature distributions, then fires randomly generated batches at the service
(wrappers/testing/tester.py:42-66, util/api_tester/api-tester.py:24-120).
Same contract schema here::

    {"features": [{"name": "x", "dtype": "FLOAT", "ftype": "continuous",
                   "range": [0, 1], "repeat": 784}],
     "targets":  [{"name": "class", "dtype": "FLOAT", "ftype": "continuous",
                   "range": [0, 1], "repeat": 10}]}

Feature kinds: ``continuous`` (uniform in range; "inf" bounds -> normal /
lognormal tails like the reference) and ``categorical`` (uniform over
``values``).  ``repeat`` expands one definition into k columns; ``shape``
generates an [n, *shape] block.  ``validate_response`` checks a response
against the ``targets`` section — shape, dtype, and range.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from seldon_core_tpu.messages import SeldonMessage

__all__ = ["Contract", "ContractError", "generate_batch", "validate_response"]


class ContractError(ValueError):
    pass


def _gen_continuous(rng, lo, hi, shape) -> np.ndarray:
    if lo == "inf" and hi == "inf":
        return rng.normal(size=shape)
    if lo == "inf":
        return float(hi) - rng.lognormal(size=shape)
    if hi == "inf":
        return float(lo) + rng.lognormal(size=shape)
    return rng.uniform(float(lo), float(hi), size=shape)


@dataclass
class Contract:
    features: List[dict]
    targets: List[dict] = field(default_factory=list)

    @staticmethod
    def from_json(s) -> "Contract":
        try:
            d = json.loads(s) if isinstance(s, (str, bytes)) else dict(s)
        except json.JSONDecodeError as e:
            raise ContractError(f"invalid contract JSON: {e}") from e
        if "features" not in d:
            raise ContractError("contract needs a 'features' section")
        return Contract(
            features=list(d["features"]), targets=list(d.get("targets", []))
        )

    @staticmethod
    def from_file(path: str) -> "Contract":
        with open(path) as f:
            return Contract.from_json(f.read())


def _columns_for(defs: List[dict], n: int, rng) -> Tuple[np.ndarray, List[str]]:
    blocks, names = [], []
    for fd in defs:
        if "name" not in fd:
            raise ContractError("feature definition missing 'name'")
        ftype = fd.get("ftype", "continuous")
        repeat = int(fd.get("repeat", 1))
        if ftype == "continuous":
            if "shape" in fd:
                shape = [n] + [int(s) for s in fd["shape"]]
            else:
                shape = [n, repeat]
            lo, hi = fd.get("range", ["inf", "inf"])
            block = np.around(_gen_continuous(rng, lo, hi, shape), decimals=3)
            if fd.get("dtype") == "INT":
                block = np.floor(block + 0.5)
            block = block.reshape(n, -1)
        elif ftype == "categorical":
            values = fd.get("values")
            if not values:
                raise ContractError(f"categorical feature {fd['name']!r} needs 'values'")
            idx = rng.integers(0, len(values), size=(n, repeat))
            block = np.asarray(values, dtype=object)[idx]
        else:
            raise ContractError(f"unknown ftype {ftype!r}")
        blocks.append(block)
        cols = block.shape[1]
        names.extend(
            [fd["name"]] if cols == 1 else [f"{fd['name']}:{i}" for i in range(cols)]
        )
    return np.concatenate(blocks, axis=1), names


def generate_batch(
    contract: Contract, n: int, seed: Optional[int] = None
) -> SeldonMessage:
    """Random request batch drawn from the contract's feature distributions
    (tester.py generate_batch)."""
    rng = np.random.default_rng(seed)
    data, names = _columns_for(contract.features, n, rng)
    try:
        arr = data.astype(np.float64)
        kind = "tensor"
    except (ValueError, TypeError):
        arr = data  # mixed categorical: ndarray wire form
        kind = "ndarray"
    return SeldonMessage.from_array(arr, names=names, kind=kind)


def validate_response(contract: Contract, resp: SeldonMessage) -> List[str]:
    """Check a response against the contract's targets; returns a list of
    violations (empty == conforming)."""
    problems: List[str] = []
    if resp.status is not None and resp.status.status == "FAILURE":
        return [f"FAILURE status: {resp.status.info}"]
    if not contract.targets:
        return problems
    try:
        arr = np.asarray(resp.array(), dtype=np.float64)
    except Exception as e:
        return [f"response has no numeric payload: {e}"]
    want_cols = sum(
        int(np.prod(td["shape"])) if "shape" in td else int(td.get("repeat", 1))
        for td in contract.targets
    )
    got_cols = int(np.prod(arr.shape[1:])) if arr.ndim > 1 else arr.shape[0]
    if got_cols != want_cols:
        problems.append(f"target width {got_cols} != contract {want_cols}")
    col = 0
    flat = arr.reshape(arr.shape[0], -1) if arr.ndim > 1 else arr.reshape(1, -1)
    for td in contract.targets:
        width = int(np.prod(td["shape"])) if "shape" in td else int(td.get("repeat", 1))
        block = flat[:, col : col + width]
        col += width
        rng_spec = td.get("range")
        if rng_spec and block.size:
            lo, hi = rng_spec
            if lo != "inf" and block.min() < float(lo) - 1e-9:
                problems.append(
                    f"target {td.get('name')!r} below range: {block.min()} < {lo}"
                )
            if hi != "inf" and block.max() > float(hi) + 1e-9:
                problems.append(
                    f"target {td.get('name')!r} above range: {block.max()} > {hi}"
                )
    return problems
