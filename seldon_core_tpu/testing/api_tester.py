"""Contract API tester CLI — fire generated batches at a running service.

Parity with ``util/api_tester/api-tester.py:24-120``::

    python -m seldon_core_tpu.testing.api_tester contract.json 127.0.0.1 8000 \
        --api rest --endpoint predict -n 8 --ndarray \
        [--oauth-key k --oauth-secret s]   # via gateway
    python -m seldon_core_tpu.testing.api_tester contract.json 127.0.0.1 5001 \
        --api grpc

Sends ``n`` randomly generated rows, validates the response against the
contract's targets, prints one JSON result line, exit code 0/1.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time
from typing import Optional

import numpy as np

from seldon_core_tpu.messages import Feedback, SeldonMessage
from seldon_core_tpu.testing.contract import Contract, generate_batch, validate_response

__all__ = ["run_test", "main"]


async def _rest_call(host, port, path, payload, token=None):
    import aiohttp

    headers = {"Authorization": f"Bearer {token}"} if token else {}
    async with aiohttp.ClientSession() as session:
        async with session.post(
            f"http://{host}:{port}{path}", data=payload, headers=headers
        ) as r:
            return r.status, await r.text()


async def _rest_token(host, port, key, secret):
    import aiohttp

    async with aiohttp.ClientSession() as session:
        async with session.post(
            f"http://{host}:{port}/oauth/token", auth=aiohttp.BasicAuth(key, secret)
        ) as r:
            if r.status != 200:
                raise RuntimeError(f"token request failed: HTTP {r.status}")
            return (await r.json())["access_token"]


async def _grpc_call(host, port, msg: SeldonMessage, token=None) -> SeldonMessage:
    import grpc

    from seldon_core_tpu import protoconv
    from seldon_core_tpu.proto_gen import prediction_pb2 as pb

    async with grpc.aio.insecure_channel(f"{host}:{port}") as ch:
        stub = ch.unary_unary(
            "/seldon.protos.Seldon/Predict",
            request_serializer=pb.SeldonMessage.SerializeToString,
            response_deserializer=pb.SeldonMessage.FromString,
        )
        metadata = (("oauth_token", token),) if token else None
        resp = await stub(protoconv.msg_to_proto(msg), metadata=metadata, timeout=30)
        return protoconv.msg_from_proto(resp)


async def run_test(
    contract: Contract,
    host: str,
    port: int,
    api: str = "rest",
    endpoint: str = "predict",
    n: int = 1,
    tensor: bool = True,
    oauth_key: Optional[str] = None,
    oauth_secret: Optional[str] = None,
    seed: Optional[int] = None,
) -> dict:
    msg = generate_batch(contract, n, seed=seed)
    if not tensor and msg.data is not None:
        msg.data.kind = "ndarray"
    token = None
    if oauth_key:
        token = await _rest_token(host, port, oauth_key, oauth_secret or "")
    t0 = time.perf_counter()
    if api == "grpc":
        resp = await _grpc_call(host, port, msg, token)
        status_code = 200
    else:
        if endpoint == "send-feedback":
            payload = Feedback(request=msg, reward=1.0).to_json()
            path = "/api/v0.1/feedback"
        else:
            payload = msg.to_json()
            path = "/api/v0.1/predictions"
        status_code, body = await _rest_call(host, port, path, payload, token)
        resp = SeldonMessage.from_json(body)
    elapsed_ms = (time.perf_counter() - t0) * 1e3
    problems = [] if endpoint == "send-feedback" else validate_response(contract, resp)
    if status_code != 200:
        problems.append(f"HTTP {status_code}")
    return {
        "ok": not problems,
        "problems": problems,
        "latency_ms": round(elapsed_ms, 2),
        "rows": n,
        "response_meta": resp.meta.to_json_dict(),
    }


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description="contract-based API tester")
    parser.add_argument("contract")
    parser.add_argument("host")
    parser.add_argument("port", type=int)
    parser.add_argument("--api", choices=["rest", "grpc"], default="rest")
    parser.add_argument("--endpoint", choices=["predict", "send-feedback"],
                        default="predict")
    parser.add_argument("-n", "--batch-size", type=int, default=1)
    parser.add_argument("--ndarray", action="store_true",
                        help="send ndarray wire form instead of tensor")
    parser.add_argument("--oauth-key", default=None)
    parser.add_argument("--oauth-secret", default=None)
    parser.add_argument("--seed", type=int, default=None)
    args = parser.parse_args(argv)

    contract = Contract.from_file(args.contract)
    result = asyncio.run(
        run_test(
            contract, args.host, args.port, api=args.api, endpoint=args.endpoint,
            n=args.batch_size, tensor=not args.ndarray,
            oauth_key=args.oauth_key, oauth_secret=args.oauth_secret,
            seed=args.seed,
        )
    )
    print(json.dumps(result))
    sys.exit(0 if result["ok"] else 1)


if __name__ == "__main__":
    main()
