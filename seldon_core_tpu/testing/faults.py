"""Deterministic fault injection for the inference graph.

``FaultyNodeRuntime`` wraps any ``NodeRuntime`` (in-process or remote) and
injects seeded delays, errors, client-style timeouts, and malformed
responses per method — the chaos harness the resilience layer's contracts
are tested against (tests/test_chaos.py).  Determinism is the whole point:
every injection decision comes from one ``random.Random(seed)`` stream per
wrapper, so a failing chaos scenario replays exactly.

Usage::

    faulty = FaultyNodeRuntime(
        inner,
        FaultSpec(error_rate=1.0),                 # every method
        seed=7,
    )
    faulty = FaultyNodeRuntime(
        inner,
        {"predict": FaultSpec(delay_s=0.2, error_rate=0.3)},  # per method
    )

Wire it into a graph via ``GraphExecutor``/``EngineService``
``extra_runtimes`` — the engine then exercises real degradation paths
(combiner quorum, router fallback, retries, breakers) with zero network
setup.
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass
from typing import Dict, List, Mapping, Optional, Union

from seldon_core_tpu.graph.interpreter import NodeRuntime
from seldon_core_tpu.messages import Feedback, SeldonMessage

__all__ = [
    "FaultSpec",
    "FaultyNodeRuntime",
    "FaultyEngine",
    "InjectedFault",
    "PartitionedStore",
    "drive_tenant",
    "kill_engine",
]


class InjectedFault(Exception):
    """Raised by an injected error/timeout.  Defined standalone (imported
    as a ``RemoteCallError`` peer at the call site) so the chaos suite can
    assert the failure came from the harness, not the system under test."""


@dataclass
class FaultSpec:
    """Per-method fault probabilities, evaluated in order: delay always
    applies, then timeout / error / malformed draw one uniform sample
    (mutually exclusive per call)."""

    delay_s: float = 0.0        # added latency on every call
    error_rate: float = 0.0     # P(raise InjectedFault-as-RemoteCallError)
    timeout_rate: float = 0.0   # P(raise asyncio.TimeoutError — client view)
    malformed_rate: float = 0.0  # P(return a payload-free garbage message)

    @property
    def total_failure_rate(self) -> float:
        return self.error_rate + self.timeout_rate + self.malformed_rate


class FaultyNodeRuntime(NodeRuntime):
    """A NodeRuntime wrapper injecting faults BEFORE delegating.

    ``faults`` is either one ``FaultSpec`` (applied to every method) or a
    mapping ``method-name -> FaultSpec`` (methods absent from the mapping
    pass through untouched).  Calls are counted per method
    (``self.calls``) so tests can assert how often the system under test
    actually reached the node (retry counts, breaker fail-fast)."""

    def __init__(
        self,
        inner: NodeRuntime,
        faults: Union[FaultSpec, Mapping[str, FaultSpec]],
        seed: int = 0,
    ):
        self.inner = inner
        self.node = getattr(inner, "node", None)
        self._faults = faults
        self._rng = random.Random(seed)
        self.calls: Dict[str, int] = {}
        self.injected: Dict[str, int] = {}

    def _spec_for(self, method: str) -> Optional[FaultSpec]:
        if isinstance(self._faults, FaultSpec):
            return self._faults
        return self._faults.get(method)

    def _name(self) -> str:
        node = self.node
        return getattr(node, "name", None) or "faulty-node"

    async def _maybe_fault(self, method: str) -> bool:
        """Apply the method's fault spec; True means "return a malformed
        response instead of delegating"."""
        self.calls[method] = self.calls.get(method, 0) + 1
        spec = self._spec_for(method)
        if spec is None:
            return False
        if spec.delay_s > 0:
            await asyncio.sleep(spec.delay_s)
        r = self._rng.random()
        if r < spec.error_rate:
            self.injected[method] = self.injected.get(method, 0) + 1
            from seldon_core_tpu.runtime.client import RemoteCallError

            # raised AS a RemoteCallError so the system under test treats
            # it exactly like a real remote failure (degradable, breaker-
            # countable); InjectedFault mixin marks the provenance
            class _Injected(InjectedFault, RemoteCallError):
                pass

            raise _Injected(self._name(), method, "injected fault")
        r -= spec.error_rate
        if r < spec.timeout_rate:
            self.injected[method] = self.injected.get(method, 0) + 1
            raise asyncio.TimeoutError(f"injected timeout: {self._name()}.{method}")
        r -= spec.timeout_rate
        if r < spec.malformed_rate:
            self.injected[method] = self.injected.get(method, 0) + 1
            return True
        return False

    # -- NodeRuntime API ----------------------------------------------------

    async def predict(self, msg: SeldonMessage) -> SeldonMessage:
        if await self._maybe_fault("predict"):
            return SeldonMessage(str_data="\x00not-a-tensor")
        return await self.inner.predict(msg)

    async def transform_input(self, msg: SeldonMessage) -> SeldonMessage:
        if await self._maybe_fault("transform_input"):
            return SeldonMessage(str_data="\x00not-a-tensor")
        return await self.inner.transform_input(msg)

    async def transform_output(self, msg: SeldonMessage) -> SeldonMessage:
        if await self._maybe_fault("transform_output"):
            return SeldonMessage(str_data="\x00not-a-tensor")
        return await self.inner.transform_output(msg)

    async def route(self, msg: SeldonMessage) -> int:
        if await self._maybe_fault("route"):
            from seldon_core_tpu.runtime.client import RemoteCallError

            raise RemoteCallError(self._name(), "route", "injected bad branch")
        return await self.inner.route(msg)

    async def aggregate(self, msgs: List[SeldonMessage]) -> SeldonMessage:
        if await self._maybe_fault("aggregate"):
            return SeldonMessage(str_data="\x00not-a-tensor")
        return await self.inner.aggregate(msgs)

    async def send_feedback(self, feedback: Feedback, branch: int) -> None:
        if await self._maybe_fault("send_feedback"):
            return None
        return await self.inner.send_feedback(feedback, branch)

    async def close(self) -> None:
        closer = getattr(self.inner, "close", None)
        if closer is not None:
            await closer()


class FaultyEngine:
    """An ``EngineService`` wrapper injecting faults at the ENGINE edge —
    the replica-set counterpart of :class:`FaultyNodeRuntime` (which wraps
    graph-node hops).  A gateway replica set built over
    ``[engine, FaultyEngine(engine2, delay_s=...)]`` exercises the
    power-of-two-choices balancer against a deterministically slow or
    failing replica (scripts/scale_demo.py, tests/test_replica_balancer.py).

    Same determinism contract as the node wrapper: one seeded RNG stream,
    per-method call counts in ``self.calls``."""

    def __init__(self, inner, faults: Union[FaultSpec, Mapping[str, FaultSpec]],
                 seed: int = 0):
        self.inner = inner
        self._faults = faults
        self._rng = random.Random(seed)
        self.calls: Dict[str, int] = {}
        self.injected: Dict[str, int] = {}

    def _spec_for(self, method: str) -> Optional[FaultSpec]:
        if isinstance(self._faults, FaultSpec):
            return self._faults
        return self._faults.get(method)

    async def _maybe_fault(self, method: str) -> bool:
        """Delay always applies; one uniform draw decides error vs
        malformed (timeouts collapse into errors at this edge — the
        gateway sees a failure message either way).  True = respond with
        a FAILURE message instead of delegating."""
        self.calls[method] = self.calls.get(method, 0) + 1
        spec = self._spec_for(method)
        if spec is None:
            return False
        if spec.delay_s > 0:
            await asyncio.sleep(spec.delay_s)
        if self._rng.random() < spec.total_failure_rate:
            self.injected[method] = self.injected.get(method, 0) + 1
            return True
        return False

    async def predict(self, msg: SeldonMessage) -> SeldonMessage:
        if await self._maybe_fault("predict"):
            return SeldonMessage.failure("injected engine fault", code=503)
        return await self.inner.predict(msg)

    async def send_feedback(self, feedback: Feedback) -> SeldonMessage:
        if await self._maybe_fault("send_feedback"):
            return SeldonMessage.failure("injected engine fault", code=503)
        return await self.inner.send_feedback(feedback)

    def __getattr__(self, name):
        # stats/ready/open_breakers/predict_json/... delegate untouched so
        # the wrapper stays a drop-in EngineService wherever one is used
        return getattr(self.inner, name)


class ThrottledEngine:
    """An ``EngineService`` wrapper with FIXED capacity: at most
    ``concurrency`` predicts in service, each taking ``delay_s`` — a
    deterministic stand-in for a saturated device, so overload tests
    (tests/test_chaos.py fairness arm, scripts/overload_demo.py) have a
    real bottleneck to fight over.  Excess callers queue FIFO on the
    semaphore, which is exactly the starvation the QoS layer exists to
    prevent."""

    def __init__(self, inner, concurrency: int = 8,
                 delay_s: float = 0.04):
        self.inner = inner
        self.delay_s = float(delay_s)
        self._sem = asyncio.Semaphore(int(concurrency))
        self.served = 0

    async def predict(self, msg):
        async with self._sem:
            await asyncio.sleep(self.delay_s)
            self.served += 1
            return await self.inner.predict(msg)

    async def send_feedback(self, feedback):
        return await self.inner.send_feedback(feedback)

    def __getattr__(self, name):
        return getattr(self.inner, name)


async def drive_tenant(
    gateway,
    tenant: str,
    n: int,
    *,
    tier: Optional[str] = None,
    concurrency: int = 1,
    n_features: int = 4,
    msg_factory=None,
):
    """Fire ``n`` predicts at an in-process gateway AS one tenant —
    the overload-fairness harness (tests/test_chaos.py hog/victim arms,
    scripts/overload_demo.py, ``bench.py --fairness-gate``).

    Returns ``(latencies_s, outcomes)``: per-request wall seconds and
    the response status code (200 for SUCCESS).  ``concurrency`` > 1
    models a greedy caller that keeps that many requests permanently in
    flight; 1 models a polite sequential client."""
    import time as _time

    import numpy as np

    from seldon_core_tpu.messages import SeldonMessage
    from seldon_core_tpu.runtime.qos import qos_scope

    if msg_factory is None:
        def msg_factory():
            return SeldonMessage.from_array(
                np.zeros((1, n_features), dtype=np.float64))

    latencies: List[float] = []
    outcomes: List[int] = []
    sem = asyncio.Semaphore(max(int(concurrency), 1))

    async def one():
        async with sem:
            t0 = _time.perf_counter()
            with qos_scope(tenant, tier):
                resp = await gateway.predict(msg_factory())
            latencies.append(_time.perf_counter() - t0)
            st = resp.status
            outcomes.append(
                200 if st is None or st.status == "SUCCESS"
                else (st.code or 500))

    await asyncio.gather(*(one() for _ in range(int(n))))
    return latencies, outcomes


def kill_engine(proc, sig: Optional[int] = None) -> None:
    """Kill an engine subprocess mid-request / mid-stream.

    ``proc`` is anything with ``.pid`` (``subprocess.Popen``,
    ``asyncio.subprocess.Process``); ``sig`` defaults to SIGKILL — the
    interesting case, since SIGTERM triggers the engine's own graceful
    drain and the mesh never sees an abrupt death.  The call returns
    immediately (no wait); chaos harnesses assert on the FLEET's
    recovery, not the corpse's exit code."""
    import os as _os
    import signal as _signal

    if sig is None:
        sig = getattr(_signal, "SIGKILL", _signal.SIGTERM)
    _os.kill(proc.pid, sig)


class PartitionedStore:
    """A deployment-store wrapper that partitions / lags sqlite traffic,
    deterministically scriptable — the chaos harness for the federation
    layer (a coordinator whose store vanishes must demote itself and keep
    serving ingress; see gateway/federation.py).

    Modes, settable at any time mid-test:

    * ``store.partition()``       — every call raises ``InjectedFault``
    * ``store.heal()``            — calls pass through again
    * ``store.lag(seconds)``      — every call sleeps first (sync sleep:
      the store API is sync; callers on an event loop feel it as a stall,
      which is exactly what a slow disk does to them)
    * ``store.fail_next(n)``      — the next ``n`` calls raise, then heal
      (deterministic flap, no RNG involved)

    Reads and writes can be partitioned independently via
    ``partition(reads=..., writes=...)`` — a read-only partition models a
    replica that can renew its lease (write) but not list peers, and vice
    versa.  Method classification: anything starting with a mutating verb
    is a write, the rest are reads (``_WRITE_PREFIXES``)."""

    _WRITE_PREFIXES = ("set_", "register", "unregister", "issue_",
                       "acquire_", "release_", "heartbeat_", "drop_",
                       "fenced_", "delete", "revoke")

    def __init__(self, inner):
        self.inner = inner
        self._read_down = False
        self._write_down = False
        self._lag_s = 0.0
        self._fail_next = 0
        self.calls: Dict[str, int] = {}
        self.faults_injected = 0

    # -- the control surface (test-side) ---------------------------------

    def partition(self, *, reads: bool = True, writes: bool = True) -> None:
        self._read_down = bool(reads)
        self._write_down = bool(writes)

    def heal(self) -> None:
        self._read_down = self._write_down = False
        self._lag_s = 0.0
        self._fail_next = 0

    def lag(self, seconds: float) -> None:
        self._lag_s = max(float(seconds), 0.0)

    def fail_next(self, n: int = 1) -> None:
        self._fail_next = max(int(n), 0)

    # -- the data path (system-under-test side) --------------------------

    def _gate(self, name: str) -> None:
        self.calls[name] = self.calls.get(name, 0) + 1
        if self._lag_s:
            import time as _time

            _time.sleep(self._lag_s)
        if self._fail_next > 0:
            self._fail_next -= 1
            self.faults_injected += 1
            raise InjectedFault(f"store fault injected on {name}")
        is_write = name.startswith(self._WRITE_PREFIXES)
        if (is_write and self._write_down) or \
                (not is_write and self._read_down):
            self.faults_injected += 1
            raise InjectedFault(
                f"store partitioned ({'write' if is_write else 'read'} "
                f"path down) on {name}")

    def __getattr__(self, name):
        attr = getattr(self.inner, name)
        if not callable(attr) or name.startswith("_"):
            return attr

        def gated(*args, **kwargs):
            self._gate(name)
            return attr(*args, **kwargs)

        return gated
