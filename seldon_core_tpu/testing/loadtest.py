"""Async load rig — the locust-equivalent (util/loadtester/scripts/
predict_rest_locust.py, helm-charts/seldon-core-loadtesting).

K closed-loop clients fire contract-generated requests at a REST or gRPC
endpoint for a fixed duration; reports qps + latency percentiles as one JSON
line (the shape ``docs/benchmarking.md`` tabulates)::

    python -m seldon_core_tpu.testing.loadtest contract.json 127.0.0.1 8000 \
        --clients 64 --duration 10 [--api grpc] [--batch-size 1]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time
from typing import Optional

import numpy as np

from seldon_core_tpu.testing.contract import Contract, generate_batch

__all__ = ["run_load", "main"]


async def run_load(
    contract: Contract,
    host: str,
    port: int,
    api: str = "rest",
    clients: int = 16,
    duration_s: float = 10.0,
    batch_size: int = 1,
    oauth_key: Optional[str] = None,
    oauth_secret: Optional[str] = None,
) -> dict:
    payload_msg = generate_batch(contract, batch_size, seed=0)
    stop_at = time.perf_counter() + duration_s
    latencies: list = []
    failures = 0

    token = None
    if oauth_key:
        from seldon_core_tpu.testing.api_tester import _rest_token

        token = await _rest_token(host, port, oauth_key, oauth_secret or "")

    if api == "grpc":
        import grpc

        from seldon_core_tpu import protoconv
        from seldon_core_tpu.proto_gen import prediction_pb2 as pb

        channel = grpc.aio.insecure_channel(f"{host}:{port}")
        stub = channel.unary_unary(
            "/seldon.protos.Seldon/Predict",
            request_serializer=pb.SeldonMessage.SerializeToString,
            response_deserializer=pb.SeldonMessage.FromString,
        )
        proto_req = protoconv.msg_to_proto(payload_msg)
        metadata = (("oauth_token", token),) if token else None

        async def one_request():
            await stub(proto_req, metadata=metadata, timeout=30)

    else:
        import aiohttp

        headers = {"Authorization": f"Bearer {token}"} if token else {}
        session = aiohttp.ClientSession(headers=headers)
        payload = payload_msg.to_json()
        url = f"http://{host}:{port}/api/v0.1/predictions"

        async def one_request():
            async with session.post(url, data=payload) as r:
                await r.read()
                if r.status != 200:
                    raise RuntimeError(f"HTTP {r.status}")

    async def client():
        nonlocal failures
        while time.perf_counter() < stop_at:
            t0 = time.perf_counter()
            try:
                await one_request()
                latencies.append(time.perf_counter() - t0)
            except Exception:
                failures += 1

    try:
        await asyncio.gather(*[client() for _ in range(clients)])
    finally:
        if api == "grpc":
            await channel.close()
        else:
            await session.close()

    lat = np.asarray(latencies)
    pct = (
        {
            f"p{p}_ms": round(float(np.percentile(lat, p)) * 1e3, 2)
            for p in (50, 75, 90, 95, 99)
        }
        if len(lat)
        else {}
    )
    return {
        "requests": len(latencies),
        "failures": failures,
        "qps": round(len(latencies) / duration_s, 1),
        "clients": clients,
        "duration_s": duration_s,
        **pct,
    }


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description="async load tester")
    parser.add_argument("contract")
    parser.add_argument("host")
    parser.add_argument("port", type=int)
    parser.add_argument("--api", choices=["rest", "grpc"], default="rest")
    parser.add_argument("--clients", type=int, default=16)
    parser.add_argument("--duration", type=float, default=10.0)
    parser.add_argument("--batch-size", type=int, default=1)
    parser.add_argument("--oauth-key", default=None)
    parser.add_argument("--oauth-secret", default=None)
    args = parser.parse_args(argv)
    result = asyncio.run(
        run_load(
            Contract.from_file(args.contract), args.host, args.port,
            api=args.api, clients=args.clients, duration_s=args.duration,
            batch_size=args.batch_size, oauth_key=args.oauth_key,
            oauth_secret=args.oauth_secret,
        )
    )
    print(json.dumps(result))


if __name__ == "__main__":
    main()
