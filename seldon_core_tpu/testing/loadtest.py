"""Async load rig — the locust-equivalent (util/loadtester/scripts/
predict_rest_locust.py, helm-charts/seldon-core-loadtesting).

K closed-loop clients fire contract-generated requests at a REST or gRPC
endpoint for a fixed duration; reports qps + latency percentiles as one JSON
line (the shape ``docs/benchmarking.md`` tabulates)::

    python -m seldon_core_tpu.testing.loadtest contract.json 127.0.0.1 8000 \
        --clients 64 --duration 10 [--api grpc] [--batch-size 1]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import time
from typing import Optional

import numpy as np

from seldon_core_tpu.testing.contract import Contract, generate_batch

__all__ = ["run_load", "run_load_native", "main"]


# ---------------------------------------------------------------------------
# Native load generator (native/loadgen.cpp) — plays the role of the
# reference's DEDICATED loadtest nodes (docs/benchmarking.md drives the
# engine from 3 separate locust machines).  On this single-core host a
# Python client would charge its own per-request cost to the same CPU the
# server runs on; the native client costs ~2 us/request, so the measured
# number is the server's.
# ---------------------------------------------------------------------------

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def loadgen_binary() -> Optional[str]:
    """Path to the compiled native load generator, building it on first use;
    None if no toolchain (callers fall back to the Python rig)."""
    import subprocess

    src = os.path.join(_REPO_ROOT, "native", "loadgen.cpp")
    binary = os.path.join(_REPO_ROOT, "native", "loadgen")
    if not os.path.exists(src):
        return binary if os.path.exists(binary) else None
    if os.path.exists(binary) and os.path.getmtime(binary) >= os.path.getmtime(src):
        return binary
    try:
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-o", binary, src],
            check=True, capture_output=True,
        )
    except (OSError, subprocess.CalledProcessError):
        return None
    return binary


def _rounded_payload(contract: Contract, batch_size: int,
                     decimals: Optional[int]):
    # the reference's locust rig sends round(random(), 2)
    # (util/loadtester/scripts/predict_rest_locust.py:129) — full-precision
    # random doubles would make the payload ~2.4x larger than anything the
    # reference's benchmark ever parsed
    payload_msg = generate_batch(contract, batch_size, seed=0)
    if decimals is not None:
        try:
            arr = np.round(np.asarray(payload_msg.array(), np.float64), decimals)
            payload_msg = payload_msg.with_array(arr)
        except Exception:
            pass  # non-numeric contract: send as generated
    return payload_msg


async def run_load_native(
    contract: Contract,
    host: str,
    port: int,
    api: str = "rest",
    clients: int = 16,
    duration_s: float = 10.0,
    warmup_s: float = 2.0,
    batch_size: int = 1,
    decimals: Optional[int] = 2,
    conns: Optional[int] = None,
    oauth_key: Optional[str] = None,
    oauth_secret: Optional[str] = None,
) -> dict:
    """Drive the endpoint with the native closed-loop client.  Same report
    shape as :func:`run_load`.  ``conns`` caps gRPC connection count (REST is
    one connection per client, locust-style).  With ``oauth_key`` a token is
    fetched once and embedded in every request (the reference locust scripts
    authenticate the same way, once per worker)."""
    import json as _json
    import tempfile

    token = None
    if oauth_key:
        from seldon_core_tpu.testing.api_tester import _rest_token

        token = await _rest_token(host, port, oauth_key, oauth_secret or "")

    binary = loadgen_binary()
    if binary is None:
        # no toolchain: approximate the native client's warmup phase with a
        # short unmeasured Python-rig run, and flag the substitution so the
        # two rigs' numbers are never silently conflated
        if warmup_s > 0:
            await run_load(
                contract, host, port, api=api, clients=clients,
                duration_s=warmup_s, batch_size=batch_size, fast=True,
                decimals=decimals, oauth_key=oauth_key,
                oauth_secret=oauth_secret,
            )
        report = await run_load(
            contract, host, port, api=api, clients=clients,
            duration_s=duration_s, batch_size=batch_size, fast=True,
            decimals=decimals, oauth_key=oauth_key,
            oauth_secret=oauth_secret,
        )
        report["impl"] = "python-fallback"
        return report
    payload_msg = _rounded_payload(contract, batch_size, decimals)
    with tempfile.TemporaryDirectory() as td:
        req_path = os.path.join(td, "request.bin")
        argv = [
            binary, "--host", host, "--port", str(port), "--api", api,
            "--clients", str(clients), "--duration", str(duration_s),
            "--warmup", str(warmup_s), "--request-file", req_path,
        ]
        if api == "grpc":
            from seldon_core_tpu import protoconv
            from seldon_core_tpu.native.hpackcodec import encode_headers

            proto = protoconv.msg_to_proto(payload_msg).SerializeToString()
            import struct

            with open(req_path, "wb") as f:  # gRPC message frame
                f.write(b"\x00" + struct.pack(">I", len(proto)) + proto)
            hdr_path = os.path.join(td, "headers.bin")
            headers = [
                (b":method", b"POST"),
                (b":scheme", b"http"),
                (b":path", b"/seldon.protos.Seldon/Predict"),
                (b"content-type", b"application/grpc"),
                (b"te", b"trailers"),
            ]
            if token:
                headers.append((b"oauth_token", token.encode()))
            with open(hdr_path, "wb") as f:
                f.write(encode_headers(headers))
            argv += ["--headers-file", hdr_path]
            if conns is not None:
                argv += ["--conns", str(conns)]
        else:
            body = payload_msg.to_json().encode()
            auth = f"Authorization: Bearer {token}\r\n" if token else ""
            with open(req_path, "wb") as f:
                f.write(
                    (
                        f"POST /api/v0.1/predictions HTTP/1.1\r\nHost: {host}\r\n"
                        f"Content-Type: application/json\r\n{auth}"
                        f"Content-Length: {len(body)}\r\n\r\n"
                    ).encode() + body
                )
        proc = await asyncio.create_subprocess_exec(
            *argv, stdout=asyncio.subprocess.PIPE,
        )
        out, _ = await proc.communicate()
    if proc.returncode != 0:
        raise RuntimeError(f"loadgen exited {proc.returncode}")
    return _json.loads(out)


async def run_load(
    contract: Contract,
    host: str,
    port: int,
    api: str = "rest",
    clients: int = 16,
    duration_s: float = 10.0,
    batch_size: int = 1,
    oauth_key: Optional[str] = None,
    oauth_secret: Optional[str] = None,
    fast: bool = False,
    decimals: Optional[int] = 2,
) -> dict:
    payload_msg = _rounded_payload(contract, batch_size, decimals)
    stop_at = time.perf_counter() + duration_s
    latencies: list = []
    failures = 0

    token = None
    if oauth_key:
        from seldon_core_tpu.testing.api_tester import _rest_token

        token = await _rest_token(host, port, oauth_key, oauth_secret or "")

    if api == "rest" and fast:
        # locust FastHttpUser analogue: raw keepalive HTTP/1.1 connections,
        # one per client, minimal parsing — the aiohttp client costs ~3x as
        # much CPU per request, which matters when clients and server share
        # cores (docs/benchmarking.md methodology note)
        body = payload_msg.to_json().encode()
        auth = f"Authorization: Bearer {token}\r\n" if token else ""
        request = (
            f"POST /api/v0.1/predictions HTTP/1.1\r\nHost: {host}\r\n"
            f"Content-Type: application/json\r\n{auth}"
            f"Content-Length: {len(body)}\r\n\r\n"
        ).encode() + body

        async def client():
            nonlocal failures
            reader = writer = None
            while time.perf_counter() < stop_at:
                if writer is None:
                    try:
                        reader, writer = await asyncio.open_connection(
                            host, port
                        )
                    except OSError:
                        failures += 1
                        await asyncio.sleep(0.05)  # connect storm relief
                        continue
                t0 = time.perf_counter()
                try:
                    writer.write(request)
                    head = await reader.readuntil(b"\r\n\r\n")
                    lower = head.lower()
                    j = lower.find(b"content-length:")
                    clen = int(lower[j + 15: lower.find(b"\r", j)])
                    await reader.readexactly(clen)
                except (OSError, asyncio.IncompleteReadError, ValueError):
                    # transient: count it, reconnect, keep loading (the
                    # aiohttp lane behaves the same way)
                    failures += 1
                    writer.close()
                    reader = writer = None
                    continue
                if head[9:12] == b"200":
                    latencies.append(time.perf_counter() - t0)
                else:
                    failures += 1
            if writer is not None:
                writer.close()

        t_start = time.perf_counter()
        await asyncio.gather(*[client() for _ in range(clients)])
        wall = time.perf_counter() - t_start
        return _report(latencies, failures, wall, clients, duration_s)

    if api == "grpc" and fast:
        # wire-level gRPC client (runtime/grpcfast.py): multiplexed streams
        # over a few connections — the stock grpc.aio stub costs ~10x the
        # CPU per unary call
        from seldon_core_tpu import protoconv
        from seldon_core_tpu.runtime.grpcfast import (
            FastGrpcChannel,
            GrpcCallError,
        )

        wire = protoconv.msg_to_proto(payload_msg).SerializeToString()
        path = b"/seldon.protos.Seldon/Predict"
        n_conns = max(1, min(4, clients // 64))
        channels = []
        for _ in range(n_conns):
            channels.append(await FastGrpcChannel().connect(host, port))
        locks = [asyncio.Lock() for _ in range(n_conns)]

        async def client(i):
            nonlocal failures
            slot = i % n_conns
            while time.perf_counter() < stop_at:
                ch = channels[slot]
                t0 = time.perf_counter()
                try:
                    # same 30s deadline as the stock-lane stub calls
                    await asyncio.wait_for(ch.call(path, wire), 30)
                    latencies.append(time.perf_counter() - t0)
                except (GrpcCallError, OSError, asyncio.TimeoutError):
                    failures += 1
                    async with locks[slot]:  # one reconnect per dead conn
                        conn = channels[slot]._conn
                        if (conn is None or conn.transport is None
                                or conn.transport.is_closing()):
                            try:
                                channels[slot] = (
                                    await FastGrpcChannel().connect(host, port)
                                )
                            except OSError:
                                await asyncio.sleep(0.05)

        t_start = time.perf_counter()
        await asyncio.gather(*[client(i) for i in range(clients)])
        wall = time.perf_counter() - t_start
        for ch in channels:
            await ch.close()
        return _report(latencies, failures, wall, clients, duration_s)

    if api == "grpc":
        import grpc

        from seldon_core_tpu import protoconv
        from seldon_core_tpu.proto_gen import prediction_pb2 as pb

        channel = grpc.aio.insecure_channel(f"{host}:{port}")
        stub = channel.unary_unary(
            "/seldon.protos.Seldon/Predict",
            request_serializer=pb.SeldonMessage.SerializeToString,
            response_deserializer=pb.SeldonMessage.FromString,
        )
        proto_req = protoconv.msg_to_proto(payload_msg)
        metadata = (("oauth_token", token),) if token else None

        async def one_request():
            await stub(proto_req, metadata=metadata, timeout=30)

    else:
        import aiohttp

        headers = {"Authorization": f"Bearer {token}"} if token else {}
        session = aiohttp.ClientSession(headers=headers)
        payload = payload_msg.to_json()
        url = f"http://{host}:{port}/api/v0.1/predictions"

        async def one_request():
            async with session.post(url, data=payload) as r:
                await r.read()
                if r.status != 200:
                    raise RuntimeError(f"HTTP {r.status}")

    async def client():
        nonlocal failures
        while time.perf_counter() < stop_at:
            t0 = time.perf_counter()
            try:
                await one_request()
                latencies.append(time.perf_counter() - t0)
            except Exception:
                failures += 1

    t_start = time.perf_counter()
    try:
        await asyncio.gather(*[client() for _ in range(clients)])
    finally:
        wall = time.perf_counter() - t_start
        if api == "grpc":
            await channel.close()
        else:
            await session.close()

    return _report(latencies, failures, wall, clients, duration_s)


def _report(latencies, failures, wall, clients, duration_s) -> dict:
    lat = np.asarray(latencies)
    pct = (
        {
            f"p{p}_ms": round(float(np.percentile(lat, p)) * 1e3, 2)
            for p in (50, 75, 90, 95, 99)
        }
        if len(lat)
        else {}
    )
    return {
        "requests": len(latencies),
        "failures": failures,
        "qps": round(len(latencies) / max(wall, 1e-9), 1),
        "clients": clients,
        "duration_s": duration_s,
        **pct,
    }


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description="async load tester")
    parser.add_argument("contract")
    parser.add_argument("host")
    parser.add_argument("port", type=int)
    parser.add_argument("--api", choices=["rest", "grpc"], default="rest")
    parser.add_argument(
        "--fast", action="store_true",
        help="REST: raw keepalive connections (locust FastHttpUser analogue)",
    )
    parser.add_argument(
        "--native", action="store_true",
        help="drive with the native C++ closed-loop client (native/loadgen)",
    )
    parser.add_argument(
        "--decimals", type=int, default=2,
        help="round generated features (reference locust: 2); -1 = full precision",
    )
    parser.add_argument("--clients", type=int, default=16)
    parser.add_argument("--duration", type=float, default=10.0)
    parser.add_argument("--batch-size", type=int, default=1)
    parser.add_argument("--oauth-key", default=None)
    parser.add_argument("--oauth-secret", default=None)
    args = parser.parse_args(argv)
    if args.native:
        result = asyncio.run(
            run_load_native(
                Contract.from_file(args.contract), args.host, args.port,
                api=args.api, clients=args.clients, duration_s=args.duration,
                batch_size=args.batch_size,
                decimals=None if args.decimals < 0 else args.decimals,
                oauth_key=args.oauth_key, oauth_secret=args.oauth_secret,
            )
        )
    else:
        result = asyncio.run(
            run_load(
                Contract.from_file(args.contract), args.host, args.port,
                api=args.api, clients=args.clients, duration_s=args.duration,
                batch_size=args.batch_size, oauth_key=args.oauth_key,
                oauth_secret=args.oauth_secret, fast=args.fast,
                decimals=None if args.decimals < 0 else args.decimals,
            )
        )
    print(json.dumps(result))


if __name__ == "__main__":
    main()
