"""Testing/tooling layer: contract-based request generation + fuzz tester,
async load rig (the reference's wrappers/testing/tester.py, util/api_tester,
util/loadtester)."""

from seldon_core_tpu.testing.contract import (  # noqa: F401
    Contract,
    generate_batch,
    validate_response,
)
