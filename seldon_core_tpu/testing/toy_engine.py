"""A minimal, killable engine process for mesh-kill chaos drills.

Real engines (runtime/engine_main.py) need a compiled graph and a JAX
platform; chaos drills need the opposite — a process cheap enough to
spawn per test that speaks just enough of the engine wire surface to
carry live traffic, and that can be SIGKILLed mid-stream without
ceremony (testing/faults.py ``kill_engine``).  It serves:

  POST /api/v0.1/predictions       unary SUCCESS echo
  POST /api/v0.1/generate/stream   SSE token stream continuing the
                                   arithmetic run of the prompt: token
                                   k is ``prompt[-1] + k``.  A resumed
                                   stream (prompt = original + emitted
                                   so far, reduced ``max_new`` — the
                                   gateway's re-prefill contract)
                                   therefore continues the SAME run, so
                                   a client can verify exactly-once
                                   cumulative output across a failover
                                   by checking for one consecutive
                                   sequence.
  GET  /stats                      {"boot_id": ...} for restart-epoch
                                   detection in the balancer scrape

With ``ENGINE_ADVERTISE_URL`` + ``GATEWAY_STATE_PATH`` set it
heartbeats an engine liveness lease through the shared sqlite store
exactly like engine_main does — a SIGKILL lapses the lease and the
balancer marks the corpse dead within one TTL.

    python -m seldon_core_tpu.testing.toy_engine --port 8000
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import secrets

__all__ = ["main", "serve"]


async def serve(port: int, token_sleep_s: float = 0.05,
                host: str = "127.0.0.1") -> None:
    from aiohttp import web

    boot_id = secrets.token_hex(8)

    async def predictions(request):
        if request.content_type == "application/x-seldon-tensor":
            # decline the binary wire lane like an engine build without
            # it: the gateway negotiates down to JSON permanently
            return web.Response(status=415, text="json only")
        await request.read()  # drain
        return web.json_response({"meta": {}, "data": {"ndarray": [[0.5]]}})

    async def generate_stream(request):
        doc = json.loads(await request.text())
        prompt = doc["data"]["ndarray"][0]
        max_new = max(1, int(doc.get("max_new", 4)))
        resp = web.StreamResponse(
            status=200, headers={"Content-Type": "text/event-stream"})
        await resp.prepare(request)
        last = float(prompt[-1])
        for k in range(1, max_new + 1):
            await asyncio.sleep(token_sleep_s)
            await resp.write(
                b'data: {"tokens": [[%s]]}\n\n'
                % repr(last + k).encode())
        await resp.write(b'data: {"done": true}\n\n')
        return resp

    async def stats(request):
        return web.json_response({"boot_id": boot_id, "toy": True})

    app = web.Application()
    app.router.add_post("/api/v0.1/predictions", predictions)
    app.router.add_post("/api/v0.1/generate/stream", generate_stream)
    app.router.add_get("/stats", stats)
    runner = web.AppRunner(app)
    await runner.setup()
    await web.TCPSite(runner, host, port).start()
    print(f"toy-engine listening :{port} boot={boot_id}", flush=True)

    heartbeat_task = None
    advertise = os.environ.get("ENGINE_ADVERTISE_URL", "").strip()
    state_path = os.environ.get("GATEWAY_STATE_PATH", "").strip()
    if advertise and state_path:
        from seldon_core_tpu.gateway.federation import lease_ttl_s
        from seldon_core_tpu.gateway.state import SqliteDeploymentStore

        store = SqliteDeploymentStore(state_path)
        ttl = lease_ttl_s()

        async def _heartbeat() -> None:
            while True:
                try:
                    store.heartbeat_engine(advertise, boot_id, ttl)
                except Exception as e:  # noqa: BLE001 — liveness is best-effort
                    print(f"toy-engine heartbeat failed: {e}", flush=True)
                await asyncio.sleep(ttl / 3.0)

        heartbeat_task = asyncio.get_running_loop().create_task(_heartbeat())

    try:
        await asyncio.Event().wait()  # serve until killed — that's the point
    finally:
        if heartbeat_task is not None:
            heartbeat_task.cancel()
        await runner.cleanup()


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(
        description="toy engine for mesh-kill chaos drills")
    parser.add_argument("--port", type=int, required=True)
    parser.add_argument("--token-sleep-s", type=float, default=0.05)
    parser.add_argument("--host", default="127.0.0.1")
    args = parser.parse_args(argv)
    asyncio.run(serve(args.port, args.token_sleep_s, args.host))


if __name__ == "__main__":
    main()
