"""Shadow traffic mirroring — live requests duplicated to a shadow
predictor, fire-and-forget.

The reference platform's shadow pattern routes a *copy* of production
traffic to a non-serving predictor so a candidate model sees real inputs
without ever answering a user.  Here the gateway owns it: after a live
predict completes, a sampled fraction of requests is re-dispatched to the
deployment's shadow predictor on a background task and the pair of
answers is diffed — prediction disagreement (``messages.prediction_delta``,
the same rule the firehose replayer uses), latency delta, and error delta
accumulate per deployment and surface on ``GET /shadow`` plus the
``seldon_tpu_shadow_*`` metric families.

Hard invariants (the whole point of the design):

  * **Never on the response path.**  The live handler pays one RNG draw
    and, for the sampled fraction, one ``loop.create_task`` — the mirror
    dispatch, the shadow predictor's latency, and the diff all happen
    after the live response has left the building.  A hung shadow
    predictor cannot slow a user by construction.
  * **Concurrency- and budget-capped.**  At most ``max_concurrency``
    mirrors in flight per deployment and a token-bucket rate cap
    (``budget_per_s``, burst 2x) — a traffic spike mirrors *less*, never
    amplifies 2x into the backend.  Capped requests are counted
    (``outcome="capped"``), not queued.
  * **Deadline-clamped.**  Each mirror runs under its own fresh deadline
    (``deadline_ms``) — it does NOT inherit the live request's spent
    budget (which is typically exhausted by the time the mirror runs),
    and a wedged shadow predictor fails at the clamp, not never.

Configuration rides the deployment spec: a predictor annotated
``seldon.io/shadow: "true"`` is excluded from the live weighted split and
becomes the mirror target; deployment-level annotations
``seldon.io/shadow-sample`` / ``-deadline-ms`` / ``-max-concurrency`` /
``-budget-per-s`` tune the caps.  ``SELDON_TPU_SHADOW=0`` kills the whole
subsystem (no sampling, no tasks — today's behavior).
"""

from __future__ import annotations

import asyncio
import os
import random
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

from seldon_core_tpu.messages import SeldonMessage, prediction_delta
from seldon_core_tpu.runtime.resilience import DEADLINE_VAR, deadline_scope
from seldon_core_tpu.utils.telemetry import RECORDER, Reservoir

__all__ = [
    "ShadowConfig",
    "ShadowMirror",
    "shadow_enabled",
    "shadow_config_from_spec",
    "SHADOW_ANNOTATION",
]

SHADOW_ANNOTATION = "seldon.io/shadow"


def shadow_enabled() -> bool:
    """``SELDON_TPU_SHADOW=0`` restores the pre-mirroring gateway —
    checked per request so a flip needs no restart."""
    return os.environ.get("SELDON_TPU_SHADOW", "1").strip() != "0"


def _ann_float(annotations: dict, key: str, default: float) -> float:
    try:
        return float(annotations.get(key, "") or default)
    except (TypeError, ValueError):
        return default


@dataclass
class ShadowConfig:
    """Mirror policy for one deployment."""

    predictor: str               #: shadow predictor name (weight-0 live)
    sample: float = 0.1          #: mirrored fraction of live predicts
    max_concurrency: int = 8     #: in-flight mirror cap
    budget_per_s: float = 50.0   #: token-bucket rate cap (burst 2x)
    deadline_ms: float = 2000.0  #: per-mirror deadline clamp

    def to_json_dict(self) -> dict:
        return {
            "predictor": self.predictor,
            "sample": self.sample,
            "max_concurrency": self.max_concurrency,
            "budget_per_s": self.budget_per_s,
            "deadline_ms": self.deadline_ms,
        }

    @staticmethod
    def from_json_dict(d: dict) -> "ShadowConfig":
        return ShadowConfig(
            predictor=str(d["predictor"]),
            sample=float(d.get("sample", 0.1)),
            max_concurrency=int(d.get("max_concurrency", 8)),
            budget_per_s=float(d.get("budget_per_s", 50.0)),
            deadline_ms=float(d.get("deadline_ms", 2000.0)),
        )


def shadow_config_from_spec(spec) -> Optional[ShadowConfig]:
    """The spec-level shadow contract: the FIRST predictor annotated
    ``seldon.io/shadow: "true"`` becomes the mirror target (weight 0 in
    the live split — apife/state enforce that); deployment annotations
    tune the caps.  None when no predictor opts in."""
    target = None
    for p in spec.predictors:
        flag = str(p.annotations.get(SHADOW_ANNOTATION, "")).strip().lower()
        if flag in ("true", "1", "yes"):
            target = p.name
            break
    if target is None:
        return None
    ann = spec.annotations
    sample = _ann_float(ann, "seldon.io/shadow-sample", 0.1)
    return ShadowConfig(
        predictor=target,
        sample=min(max(sample, 0.0), 1.0),
        max_concurrency=max(
            int(_ann_float(ann, "seldon.io/shadow-max-concurrency", 8)), 1
        ),
        budget_per_s=max(
            _ann_float(ann, "seldon.io/shadow-budget-per-s", 50.0), 0.1
        ),
        deadline_ms=max(
            _ann_float(ann, "seldon.io/shadow-deadline-ms", 2000.0), 1.0
        ),
    )


@dataclass
class _DeploymentShadow:
    """Per-deployment mirror state: caps plus the divergence picture."""

    config: ShadowConfig
    inflight: int = 0
    tokens: float = 0.0
    tokens_at: float = field(default_factory=time.monotonic)
    mirrored: int = 0
    sampled_out: int = 0
    capped: int = 0
    live_errors: int = 0      # over mirrored requests only — comparable
    shadow_errors: int = 0
    disagreement: Reservoir = field(default_factory=Reservoir)
    latency_delta_ms: Reservoir = field(default_factory=Reservoir)
    shadow_latency_ms: Reservoir = field(default_factory=Reservoir)
    last_error: str = ""

    def take_token(self, now: float) -> bool:
        burst = 2.0 * self.config.budget_per_s
        self.tokens = min(
            burst, self.tokens + (now - self.tokens_at) * self.config.budget_per_s
        )
        self.tokens_at = now
        if self.tokens < 1.0:
            return False
        self.tokens -= 1.0
        return True

    def document_row(self) -> dict:
        mirrored = self.mirrored
        dis = self.disagreement.snapshot()
        return {
            "config": self.config.to_json_dict(),
            "mirrored": mirrored,
            "sampled_out": self.sampled_out,
            "capped": self.capped,
            "inflight": self.inflight,
            "disagreement": {
                "count": dis["count"],
                "mean": dis["mean"],
                "p50": dis["p50"],
                "p95": dis["p95"],
            },
            "latency_delta_ms": self.latency_delta_ms.snapshot(),
            "shadow_latency_ms": self.shadow_latency_ms.snapshot(),
            "error_delta": {
                "live": self.live_errors,
                "shadow": self.shadow_errors,
                "live_rate": round(self.live_errors / mirrored, 6)
                if mirrored else 0.0,
                "shadow_rate": round(self.shadow_errors / mirrored, 6)
                if mirrored else 0.0,
            },
            "last_error": self.last_error,
        }


def _is_error(resp: Optional[SeldonMessage]) -> bool:
    return (resp is None
            or (resp.status is not None and resp.status.status == "FAILURE"))


class ShadowMirror:
    """Gateway-owned mirror engine.  ``dispatch`` is supplied by the
    gateway: ``async dispatch(reg, predictor_name, msg) -> SeldonMessage``
    — it reuses the real pick/lane machinery so the shadow predictor's
    replica set, breakers and lanes behave exactly as they would for live
    traffic."""

    def __init__(self, dispatch: Callable, seed: int = 0):
        self._dispatch = dispatch
        self._rng = random.Random(seed)
        self._by_deployment: Dict[str, _DeploymentShadow] = {}
        self._tasks: set = set()

    # -- configuration ---------------------------------------------------

    def state_for(self, deployment: str,
                  config: Optional[ShadowConfig]) -> Optional[_DeploymentShadow]:
        """Lazily (re)build per-deployment state; a re-registration that
        changed the shadow target/config resets the divergence windows —
        they described the OLD candidate."""
        if config is None:
            self._by_deployment.pop(deployment, None)
            return None
        ds = self._by_deployment.get(deployment)
        if ds is None or ds.config != config:
            ds = _DeploymentShadow(config=config)
            # the bucket starts FULL: the first sampled request after a
            # (re)configuration must mirror, not bootstrap the refill
            ds.tokens = 2.0 * config.budget_per_s
            self._by_deployment[deployment] = ds
        return ds

    # -- the live-path hook ----------------------------------------------

    def maybe_mirror(self, reg, live_predictor: str, msg: SeldonMessage,
                     live_resp: SeldonMessage,
                     live_latency_s: float) -> bool:
        """Called by the gateway AFTER the live response exists.  Costs
        one RNG draw on the unsampled path.  Returns True when a mirror
        task was scheduled."""
        config = getattr(reg, "shadow", None)
        if config is None or not shadow_enabled():
            return False
        if live_predictor == config.predictor:
            return False  # never mirror the shadow's own traffic
        ds = self.state_for(reg.deployment_id, config)
        if self._rng.random() >= config.sample:
            ds.sampled_out += 1
            RECORDER.record_shadow("sampled_out")
            return False
        now = time.monotonic()
        if ds.inflight >= config.max_concurrency or not ds.take_token(now):
            ds.capped += 1
            RECORDER.record_shadow("capped")
            return False
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return False  # no loop (sync tests drive predict() directly)
        ds.inflight += 1
        task = loop.create_task(
            self._mirror(ds, reg, msg, live_resp, live_latency_s)
        )
        # keep a strong ref until done (asyncio only holds weak ones)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return True

    async def _mirror(self, ds: _DeploymentShadow, reg, msg: SeldonMessage,
                      live_resp: SeldonMessage,
                      live_latency_s: float) -> None:
        t0 = time.perf_counter()
        shadow_resp: Optional[SeldonMessage] = None
        try:
            # drop the live request's (spent) deadline before clamping to
            # the mirror's own budget — deadline_scope tightens only, so
            # an inherited exhausted budget would 504 every mirror
            token = DEADLINE_VAR.set(None)
            try:
                with deadline_scope(ds.config.deadline_ms / 1e3):
                    # wait_for enforces the clamp even against targets
                    # that ignore the deadline contextvar (a wedged
                    # in-process stub, a lane without propagation) — and
                    # cancels the hung coroutine instead of leaking it
                    shadow_resp = await asyncio.wait_for(
                        self._dispatch(reg, ds.config.predictor, msg),
                        timeout=ds.config.deadline_ms / 1e3,
                    )
            finally:
                DEADLINE_VAR.reset(token)
        except asyncio.TimeoutError:
            ds.last_error = (
                f"shadow deadline exceeded ({ds.config.deadline_ms:.0f} ms)"
            )
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — the mirror NEVER raises
            ds.last_error = f"{type(e).__name__}: {e}"
        finally:
            ds.inflight -= 1
        shadow_latency_s = time.perf_counter() - t0
        ds.mirrored += 1
        if _is_error(live_resp):
            ds.live_errors += 1
        if _is_error(shadow_resp):
            ds.shadow_errors += 1
            if shadow_resp is not None and shadow_resp.status is not None:
                ds.last_error = shadow_resp.status.info or ds.last_error
            RECORDER.record_shadow("shadow_error")
        else:
            RECORDER.record_shadow("mirrored")
        # the disagree figure is recorded UNCONDITIONALLY: an
        # incomparable pair is either matched failures (disagree 0.0 —
        # faithfully reproducing the baseline's error) or a contract
        # break (shape/kind mismatch, one-sided failure → 1.0) — a
        # candidate that changes the output contract must read as
        # maximal divergence, not fall out of the window
        disagreement = prediction_delta(live_resp, shadow_resp)["disagree"]
        ds.shadow_latency_ms.observe(shadow_latency_s * 1e3)
        ds.latency_delta_ms.observe((shadow_latency_s - live_latency_s) * 1e3)
        ds.disagreement.observe(disagreement)
        RECORDER.observe_shadow(disagreement, shadow_latency_s)

    def prune(self, live_deployments) -> None:
        """Drop divergence state of deployments no longer registered —
        rides the gateway's existing prune gate (apife._prune_stale_sets)
        so an unregistered deployment's windows don't outlive it."""
        live = set(live_deployments)
        for dep in [d for d in self._by_deployment if d not in live]:
            del self._by_deployment[dep]

    # -- surfaces ---------------------------------------------------------

    async def drain(self, timeout_s: float = 5.0) -> None:
        """Wait for in-flight mirrors (tests / orderly shutdown)."""
        pending = [t for t in self._tasks if not t.done()]
        if pending:
            await asyncio.wait(pending, timeout=timeout_s)

    def cancel_all(self) -> None:
        """Cancel in-flight mirrors — gateway shutdown.  Mirrors are
        fire-and-forget by contract (nothing awaits their results), so
        dying with the gateway is the correct teardown."""
        for t in list(self._tasks):
            t.cancel()

    def disagreement_rate(self, deployment: str) -> Optional[float]:
        """Rolling mean live-vs-shadow disagreement — the signal the
        rollout controller gates stages on.  None before any mirror
        completed (no evidence is not zero divergence)."""
        ds = self._by_deployment.get(deployment)
        if ds is None or len(ds.disagreement) == 0:
            return None
        return float(ds.disagreement.snapshot()["mean"])

    def document(self) -> dict:
        """The ``GET /shadow`` body."""
        return {
            "enabled": shadow_enabled(),
            "deployments": {
                dep: ds.document_row()
                for dep, ds in sorted(self._by_deployment.items())
            },
        }

    def snapshot(self) -> dict:
        """Compact block for the gateway's ``/stats``."""
        return {
            "enabled": shadow_enabled(),
            "deployments": {
                dep: {
                    "predictor": ds.config.predictor,
                    "sample": ds.config.sample,
                    "mirrored": ds.mirrored,
                    "capped": ds.capped,
                    "inflight": ds.inflight,
                    "mean_disagreement": round(
                        ds.disagreement.snapshot()["mean"], 6
                    ) if len(ds.disagreement) else None,
                }
                for dep, ds in sorted(self._by_deployment.items())
            },
        }
