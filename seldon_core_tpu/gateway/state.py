"""Shared gateway state — tokens and registrations that survive replicas.

The reference gateway keeps OAuth tokens in Redis so any apife replica can
validate a token issued by another (api-frontend
config/RedisConfig.java, TokenStore wiring); deployment registrations
arrive via the cluster-manager and live in each replica's memory.

This module is that role without an external broker: a single sqlite file
(on a shared volume) in WAL mode holds both tables, and
:class:`SqliteDeploymentStore` is a drop-in for
:class:`~seldon_core_tpu.gateway.apife.DeploymentStore` — same methods,
same AuthError semantics, same TTL — so ``ApiGateway`` works unchanged
with N replicas pointed at one ``GATEWAY_STATE_PATH``.

Registrations persisted here reference engines by URL (remote dispatch);
in-process EngineService objects are inherently per-replica and stay with
the in-memory store.
"""

from __future__ import annotations

import json
import secrets
import sqlite3
import threading
import time
from typing import Dict, List

from seldon_core_tpu.gateway.apife import (
    TOKEN_TTL_S,
    AuthError,
    _Registration,
)
from seldon_core_tpu.gateway.shadow import (
    ShadowConfig,
    shadow_config_from_spec,
)
from seldon_core_tpu.graph.spec import SeldonDeploymentSpec

__all__ = ["SqliteDeploymentStore"]

_SCHEMA = """
CREATE TABLE IF NOT EXISTS registrations (
    oauth_key TEXT PRIMARY KEY,
    deployment_id TEXT NOT NULL,
    oauth_secret TEXT NOT NULL,
    engines_json TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS tokens (
    token TEXT PRIMARY KEY,
    oauth_key TEXT NOT NULL,
    expiry REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS tokens_by_key ON tokens(oauth_key);
CREATE TABLE IF NOT EXISTS meta (
    k TEXT PRIMARY KEY,
    v INTEGER NOT NULL
);
"""

# bumped inside the same transaction as the registration write, so every
# gateway replica sharing the file observes other replicas' changes too
_BUMP_REVISION = (
    "INSERT INTO meta VALUES ('revision', 1) "
    "ON CONFLICT(k) DO UPDATE SET v = v + 1"
)


class SqliteDeploymentStore:
    """DeploymentStore drop-in over a shared sqlite file."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._conn = sqlite3.connect(path, check_same_thread=False)
        with self._lock:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute("PRAGMA synchronous=NORMAL")
            self._conn.executescript(_SCHEMA)
            self._conn.commit()

    # -- registrations -----------------------------------------------------

    def register(self, spec: SeldonDeploymentSpec,
                 engines: Dict[str, object]) -> None:
        """``engines``: predictor name -> engine base URL, or a LIST of
        endpoint specs (a replica set the gateway balances over with
        power-of-two-choices — gateway/balancer.py).  Shared state can
        only carry references another replica can dial, so in-process
        engines are rejected in either form."""
        shadow = shadow_config_from_spec(spec)
        weighted = []
        for p in spec.predictors:
            if p.name in engines:
                engine = engines[p.name]
                if isinstance(engine, (list, tuple)):
                    if not engine or not all(
                        isinstance(u, str) for u in engine
                    ):
                        raise TypeError(
                            "a replica set must be a non-empty list of "
                            "endpoint spec strings"
                        )
                    engine = [str(u) for u in engine]
                elif not isinstance(engine, str):
                    raise TypeError(
                        "SqliteDeploymentStore carries engine URLs; "
                        "in-process engines are per-replica "
                        "(use the in-memory DeploymentStore)"
                    )
                # same shadow contract as the in-memory store: an
                # annotated shadow predictor serves weight-0 live traffic
                weight = (
                    0 if shadow is not None and p.name == shadow.predictor
                    else max(int(p.replicas), 0)
                )
                weighted.append((p.name, weight, engine))
        if not weighted:
            raise ValueError(
                f"no engines supplied for deployment {spec.name!r}"
            )
        if shadow is not None and shadow.predictor not in (
            w[0] for w in weighted
        ):
            shadow = None
        key = spec.oauth_key or spec.name
        # wrapped form carries the shadow policy alongside the engines;
        # the reader accepts the bare-list form older rows persisted
        doc = {
            "engines": weighted,
            "shadow": None if shadow is None else shadow.to_json_dict(),
        }
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO registrations VALUES (?, ?, ?, ?)",
                (key, spec.name, spec.oauth_secret, json.dumps(doc)),
            )
            self._conn.execute(_BUMP_REVISION)
            self._conn.commit()

    def set_weights(self, deployment_id: str, weights) -> None:
        """Reassign one deployment's live traffic split in place — the
        rollout controller's lever, same semantics as the in-memory
        store's ``set_weights`` (unknown predictors are a typed error);
        the revision bump propagates the change to every gateway replica
        sharing the file."""
        with self._lock:
            row = self._conn.execute(
                "SELECT oauth_key, engines_json FROM registrations "
                "WHERE deployment_id = ?",
                (deployment_id,),
            ).fetchone()
            if row is None:
                raise KeyError(
                    f"deployment not registered: {deployment_id!r}"
                )
            key, engines_json = row
            doc = json.loads(engines_json)
            engines = doc["engines"] if isinstance(doc, dict) else doc
            known = {e[0] for e in engines}
            unknown = set(weights) - known
            if unknown:
                raise KeyError(
                    f"unknown predictors for {deployment_id!r}: "
                    f"{sorted(unknown)}"
                )
            engines = [
                [name, max(int(weights.get(name, w)), 0), engine]
                for name, w, engine in engines
            ]
            if isinstance(doc, dict):
                doc["engines"] = engines
            else:
                doc = engines
            self._conn.execute(
                "UPDATE registrations SET engines_json = ? "
                "WHERE oauth_key = ?",
                (json.dumps(doc), key),
            )
            self._conn.execute(_BUMP_REVISION)
            self._conn.commit()

    def unregister(self, oauth_key: str) -> None:
        with self._lock:
            self._conn.execute(
                "DELETE FROM registrations WHERE oauth_key = ?", (oauth_key,)
            )
            self._conn.execute(
                "DELETE FROM tokens WHERE oauth_key = ?", (oauth_key,)
            )
            self._conn.execute(_BUMP_REVISION)
            self._conn.commit()

    def revision(self) -> int:
        """Monotone registration-change counter shared through the sqlite
        file — bumps on every register/unregister by ANY gateway replica,
        including same-deployment re-registrations (the gateway's prune
        gate reads this instead of diffing deployment IDs)."""
        with self._lock:
            row = self._conn.execute(
                "SELECT v FROM meta WHERE k = 'revision'"
            ).fetchone()
        return int(row[0]) if row else 0

    def _registration(self, oauth_key: str):
        with self._lock:
            row = self._conn.execute(
                "SELECT deployment_id, oauth_secret, engines_json "
                "FROM registrations WHERE oauth_key = ?",
                (oauth_key,),
            ).fetchone()
        if row is None:
            return None
        doc = json.loads(row[2])
        if isinstance(doc, dict):
            engines, shadow = doc["engines"], doc.get("shadow")
        else:  # bare-list rows persisted before the shadow field existed
            engines, shadow = doc, None
        return _Registration(
            deployment_id=row[0],
            oauth_key=oauth_key,
            oauth_secret=row[1],
            engines=[tuple(e) for e in engines],
            shadow=(
                None if shadow is None
                else ShadowConfig.from_json_dict(shadow)
            ),
        )

    # -- auth --------------------------------------------------------------

    def issue_token(self, oauth_key: str, oauth_secret: str) -> str:
        reg = self._registration(oauth_key)
        if reg is None or (reg.oauth_secret
                           and reg.oauth_secret != oauth_secret):
            raise AuthError("invalid client credentials")
        token = secrets.token_urlsafe(24)
        now = time.time()
        with self._lock:
            # expired rows are evicted on the write path (the same lazy
            # policy the in-memory store uses)
            self._conn.execute("DELETE FROM tokens WHERE expiry <= ?", (now,))
            self._conn.execute(
                "INSERT INTO tokens VALUES (?, ?, ?)",
                (token, oauth_key, now + TOKEN_TTL_S),
            )
            self._conn.commit()
        return token

    def principal_for_token(self, token: str) -> _Registration:
        with self._lock:
            row = self._conn.execute(
                "SELECT oauth_key, expiry FROM tokens WHERE token = ?",
                (token,),
            ).fetchone()
        if row is None:
            raise AuthError("invalid token")
        key, expiry = row
        if time.time() > expiry:
            with self._lock:
                self._conn.execute(
                    "DELETE FROM tokens WHERE token = ?", (token,)
                )
                self._conn.commit()
            raise AuthError("token expired")
        reg = self._registration(key)
        if reg is None:
            raise AuthError("client no longer registered")
        return reg

    def deployments(self) -> List[str]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT deployment_id FROM registrations ORDER BY oauth_key"
            ).fetchall()
        return [r[0] for r in rows]

    # ApiGateway._resolve peeks at _by_key when auth is disabled; present
    # the same mapping view lazily
    @property
    def _by_key(self) -> Dict[str, _Registration]:
        with self._lock:
            keys = [r[0] for r in self._conn.execute(
                "SELECT oauth_key FROM registrations"
            ).fetchall()]
        return {k: self._registration(k) for k in keys}

    def close(self) -> None:
        with self._lock:
            self._conn.close()
