"""Shared gateway state — tokens and registrations that survive replicas.

The reference gateway keeps OAuth tokens in Redis so any apife replica can
validate a token issued by another (api-frontend
config/RedisConfig.java, TokenStore wiring); deployment registrations
arrive via the cluster-manager and live in each replica's memory.

This module is that role without an external broker: a single sqlite file
(on a shared volume) in WAL mode holds both tables, and
:class:`SqliteDeploymentStore` is a drop-in for
:class:`~seldon_core_tpu.gateway.apife.DeploymentStore` — same methods,
same AuthError semantics, same TTL — so ``ApiGateway`` works unchanged
with N replicas pointed at one ``GATEWAY_STATE_PATH``.

Registrations persisted here reference engines by URL (remote dispatch);
in-process EngineService objects are inherently per-replica and stay with
the in-memory store.
"""

from __future__ import annotations

import contextlib
import json
import secrets
import sqlite3
import threading
import time
from typing import Dict, List, Optional, Tuple

from seldon_core_tpu.gateway.apife import (
    TOKEN_TTL_S,
    AuthError,
    _Registration,
)
from seldon_core_tpu.gateway.shadow import (
    ShadowConfig,
    shadow_config_from_spec,
)
from seldon_core_tpu.graph.spec import SeldonDeploymentSpec

__all__ = ["SqliteDeploymentStore", "StaleFenceError"]

_SCHEMA = """
CREATE TABLE IF NOT EXISTS registrations (
    oauth_key TEXT PRIMARY KEY,
    deployment_id TEXT NOT NULL,
    oauth_secret TEXT NOT NULL,
    engines_json TEXT NOT NULL
);
CREATE TABLE IF NOT EXISTS tokens (
    token TEXT PRIMARY KEY,
    oauth_key TEXT NOT NULL,
    expiry REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS tokens_by_key ON tokens(oauth_key);
CREATE TABLE IF NOT EXISTS meta (
    k TEXT PRIMARY KEY,
    v INTEGER NOT NULL
);
CREATE TABLE IF NOT EXISTS leases (
    name TEXT PRIMARY KEY,
    holder TEXT NOT NULL,
    token INTEGER NOT NULL,
    expires REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS engine_leases (
    url TEXT PRIMARY KEY,
    boot_id TEXT NOT NULL,
    expires REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS gateway_peers (
    replica_id TEXT PRIMARY KEY,
    base_url TEXT NOT NULL,
    expires REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS burn_deltas (
    replica_id TEXT NOT NULL,
    scope TEXT NOT NULL,
    window TEXT NOT NULL,
    total INTEGER NOT NULL,
    slow INTEGER NOT NULL,
    errors INTEGER NOT NULL,
    throttled INTEGER NOT NULL,
    shed INTEGER NOT NULL,
    updated REAL NOT NULL,
    PRIMARY KEY (replica_id, scope, window)
);
"""

#: how many times a write transaction retries when another gateway
#: replica holds the sqlite write lock, and the base of the backoff
#: (full jitter on top; total worst-case wait ~= 2s, far beyond any
#: real contention window for a WAL-mode file on a shared volume)
_BUSY_RETRIES = 6
_BUSY_BACKOFF_S = 0.03


class StaleFenceError(RuntimeError):
    """A fenced write carried a fencing token that is no longer the
    lease's current token — the caller lost the lease (paused past its
    TTL, another replica took over) and MUST NOT mutate shared state."""

# bumped inside the same transaction as the registration write, so every
# gateway replica sharing the file observes other replicas' changes too
_BUMP_REVISION = (
    "INSERT INTO meta VALUES ('revision', 1) "
    "ON CONFLICT(k) DO UPDATE SET v = v + 1"
)


class SqliteDeploymentStore:
    """DeploymentStore drop-in over a shared sqlite file."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        # isolation_level=None -> autocommit: transactions are explicit
        # (BEGIN IMMEDIATE in _write) so a multi-statement writer holds
        # the write lock for exactly its own span and nothing implicit
        # lingers between calls
        self._conn = sqlite3.connect(
            path, check_same_thread=False, isolation_level=None)
        with self._lock:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute("PRAGMA synchronous=NORMAL")
            # first line of defense against a sibling replica's write
            # lock; the _write retry loop is the second
            self._conn.execute("PRAGMA busy_timeout=200")
            self._conn.executescript(_SCHEMA)

    @contextlib.contextmanager
    def _write(self):
        """One IMMEDIATE write transaction with SQLITE_BUSY retry.

        BEGIN IMMEDIATE takes the write lock up front, so two gateway
        replicas racing ``set_weights``/``register`` serialize at BEGIN
        instead of failing mid-transaction on the first write.  A busy
        BEGIN (the other replica holds the lock past busy_timeout) is
        retried with linear backoff + full jitter rather than surfacing
        a raw OperationalError to the caller."""
        with self._lock:
            last: Optional[Exception] = None
            for attempt in range(_BUSY_RETRIES):
                try:
                    self._conn.execute("BEGIN IMMEDIATE")
                except sqlite3.OperationalError as e:
                    msg = str(e).lower()
                    if "locked" not in msg and "busy" not in msg:
                        raise
                    last = e
                    time.sleep(_BUSY_BACKOFF_S * (attempt + 1)
                               * (0.5 + secrets.randbelow(512) / 1024))
                    continue
                try:
                    yield self._conn
                except BaseException:
                    self._conn.execute("ROLLBACK")
                    raise
                self._conn.execute("COMMIT")
                return
            raise last  # type: ignore[misc]

    # -- registrations -----------------------------------------------------

    def register(self, spec: SeldonDeploymentSpec,
                 engines: Dict[str, object]) -> None:
        """``engines``: predictor name -> engine base URL, or a LIST of
        endpoint specs (a replica set the gateway balances over with
        power-of-two-choices — gateway/balancer.py).  Shared state can
        only carry references another replica can dial, so in-process
        engines are rejected in either form."""
        shadow = shadow_config_from_spec(spec)
        weighted = []
        for p in spec.predictors:
            if p.name in engines:
                engine = engines[p.name]
                if isinstance(engine, (list, tuple)):
                    if not engine or not all(
                        isinstance(u, str) for u in engine
                    ):
                        raise TypeError(
                            "a replica set must be a non-empty list of "
                            "endpoint spec strings"
                        )
                    engine = [str(u) for u in engine]
                elif not isinstance(engine, str):
                    raise TypeError(
                        "SqliteDeploymentStore carries engine URLs; "
                        "in-process engines are per-replica "
                        "(use the in-memory DeploymentStore)"
                    )
                # same shadow contract as the in-memory store: an
                # annotated shadow predictor serves weight-0 live traffic
                weight = (
                    0 if shadow is not None and p.name == shadow.predictor
                    else max(int(p.replicas), 0)
                )
                weighted.append((p.name, weight, engine))
        if not weighted:
            raise ValueError(
                f"no engines supplied for deployment {spec.name!r}"
            )
        if shadow is not None and shadow.predictor not in (
            w[0] for w in weighted
        ):
            shadow = None
        key = spec.oauth_key or spec.name
        # wrapped form carries the shadow policy alongside the engines;
        # the reader accepts the bare-list form older rows persisted
        doc = {
            "engines": weighted,
            "shadow": None if shadow is None else shadow.to_json_dict(),
        }
        with self._write() as conn:
            conn.execute(
                "INSERT OR REPLACE INTO registrations VALUES (?, ?, ?, ?)",
                (key, spec.name, spec.oauth_secret, json.dumps(doc)),
            )
            conn.execute(_BUMP_REVISION)

    @staticmethod
    def _set_weights_in(conn, deployment_id: str, weights) -> None:
        """The set_weights body, run inside an already-open write
        transaction (shared by the plain and fenced entry points)."""
        row = conn.execute(
            "SELECT oauth_key, engines_json FROM registrations "
            "WHERE deployment_id = ?",
            (deployment_id,),
        ).fetchone()
        if row is None:
            raise KeyError(
                f"deployment not registered: {deployment_id!r}"
            )
        key, engines_json = row
        doc = json.loads(engines_json)
        engines = doc["engines"] if isinstance(doc, dict) else doc
        known = {e[0] for e in engines}
        unknown = set(weights) - known
        if unknown:
            raise KeyError(
                f"unknown predictors for {deployment_id!r}: "
                f"{sorted(unknown)}"
            )
        engines = [
            [name, max(int(weights.get(name, w)), 0), engine]
            for name, w, engine in engines
        ]
        if isinstance(doc, dict):
            doc["engines"] = engines
        else:
            doc = engines
        conn.execute(
            "UPDATE registrations SET engines_json = ? "
            "WHERE oauth_key = ?",
            (json.dumps(doc), key),
        )
        conn.execute(_BUMP_REVISION)

    def set_weights(self, deployment_id: str, weights) -> None:
        """Reassign one deployment's live traffic split in place — the
        rollout controller's lever, same semantics as the in-memory
        store's ``set_weights`` (unknown predictors are a typed error);
        the revision bump propagates the change to every gateway replica
        sharing the file."""
        with self._write() as conn:
            self._set_weights_in(conn, deployment_id, weights)

    def fenced_set_weights(self, deployment_id: str, weights, *,
                           lease: str, holder: str, token: int) -> None:
        """``set_weights`` guarded by a fencing check INSIDE the same
        write transaction: the caller must still be the named lease's
        current holder at its current token.  An ex-coordinator that was
        paused past its TTL (GC stall, SIGSTOP) and resumed with a stale
        token gets :class:`StaleFenceError` instead of clobbering the
        new coordinator's traffic split."""
        with self._write() as conn:
            row = conn.execute(
                "SELECT holder, token, expires FROM leases WHERE name = ?",
                (lease,),
            ).fetchone()
            if (row is None or row[0] != holder
                    or int(row[1]) != int(token)
                    or float(row[2]) <= time.time()):
                raise StaleFenceError(
                    f"lease {lease!r}: fencing token {token} for "
                    f"{holder!r} is stale (current: {row!r})"
                )
            self._set_weights_in(conn, deployment_id, weights)

    def weights(self, deployment_id: str) -> Dict[str, int]:
        """The live traffic split by predictor name (read side of
        ``set_weights`` — same contract as the in-memory store's)."""
        with self._lock:
            row = self._conn.execute(
                "SELECT engines_json FROM registrations "
                "WHERE deployment_id = ?",
                (deployment_id,),
            ).fetchone()
        if row is None:
            raise KeyError(f"deployment not registered: {deployment_id!r}")
        doc = json.loads(row[0])
        engines = doc["engines"] if isinstance(doc, dict) else doc
        return {e[0]: int(e[1]) for e in engines}

    def unregister(self, oauth_key: str) -> None:
        with self._write() as conn:
            conn.execute(
                "DELETE FROM registrations WHERE oauth_key = ?", (oauth_key,)
            )
            conn.execute(
                "DELETE FROM tokens WHERE oauth_key = ?", (oauth_key,)
            )
            conn.execute(_BUMP_REVISION)

    def revision(self) -> int:
        """Monotone registration-change counter shared through the sqlite
        file — bumps on every register/unregister by ANY gateway replica,
        including same-deployment re-registrations (the gateway's prune
        gate reads this instead of diffing deployment IDs)."""
        with self._lock:
            row = self._conn.execute(
                "SELECT v FROM meta WHERE k = 'revision'"
            ).fetchone()
        return int(row[0]) if row else 0

    def _registration(self, oauth_key: str):
        with self._lock:
            row = self._conn.execute(
                "SELECT deployment_id, oauth_secret, engines_json "
                "FROM registrations WHERE oauth_key = ?",
                (oauth_key,),
            ).fetchone()
        if row is None:
            return None
        doc = json.loads(row[2])
        if isinstance(doc, dict):
            engines, shadow = doc["engines"], doc.get("shadow")
        else:  # bare-list rows persisted before the shadow field existed
            engines, shadow = doc, None
        return _Registration(
            deployment_id=row[0],
            oauth_key=oauth_key,
            oauth_secret=row[1],
            engines=[tuple(e) for e in engines],
            shadow=(
                None if shadow is None
                else ShadowConfig.from_json_dict(shadow)
            ),
        )

    # -- auth --------------------------------------------------------------

    def issue_token(self, oauth_key: str, oauth_secret: str) -> str:
        reg = self._registration(oauth_key)
        if reg is None or (reg.oauth_secret
                           and reg.oauth_secret != oauth_secret):
            raise AuthError("invalid client credentials")
        token = secrets.token_urlsafe(24)
        now = time.time()
        with self._write() as conn:
            # expired rows are evicted on the write path (the same lazy
            # policy the in-memory store uses)
            conn.execute("DELETE FROM tokens WHERE expiry <= ?", (now,))
            conn.execute(
                "INSERT INTO tokens VALUES (?, ?, ?)",
                (token, oauth_key, now + TOKEN_TTL_S),
            )
        return token

    def principal_for_token(self, token: str) -> _Registration:
        with self._lock:
            row = self._conn.execute(
                "SELECT oauth_key, expiry FROM tokens WHERE token = ?",
                (token,),
            ).fetchone()
        if row is None:
            raise AuthError("invalid token")
        key, expiry = row
        if time.time() > expiry:
            with self._write() as conn:
                conn.execute(
                    "DELETE FROM tokens WHERE token = ?", (token,)
                )
            raise AuthError("token expired")
        reg = self._registration(key)
        if reg is None:
            raise AuthError("client no longer registered")
        return reg

    def deployments(self) -> List[str]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT deployment_id FROM registrations ORDER BY oauth_key"
            ).fetchall()
        return [r[0] for r in rows]

    def active_token_count(self) -> int:
        """Unexpired issued tokens — the /stats ``active_tokens`` gauge
        (ApiGateway.stats reads this off whichever store it was built
        with; the sqlite store counts live rows, mirroring the in-memory
        store's lazy-eviction semantics)."""
        with self._lock:
            row = self._conn.execute(
                "SELECT COUNT(*) FROM tokens WHERE expiry > ?",
                (time.time(),),
            ).fetchone()
        return int(row[0])

    # ApiGateway._resolve peeks at _by_key when auth is disabled; present
    # the same mapping view lazily
    @property
    def _by_key(self) -> Dict[str, _Registration]:
        with self._lock:
            keys = [r[0] for r in self._conn.execute(
                "SELECT oauth_key FROM registrations"
            ).fetchall()]
        return {k: self._registration(k) for k in keys}

    # -- coordinator leases (gateway/federation.py) ------------------------

    def acquire_lease(self, name: str, holder: str,
                      ttl_s: float) -> Optional[int]:
        """Claim or renew the named lease; returns the fencing token if
        ``holder`` now holds it, None if another live holder does.

        The token is a monotone integer that bumps on every CHANGE of
        tenure (fresh claim, takeover of an expired lease) and stays
        fixed across renewals by the same holder — so any write fenced
        on an old token is rejectable forever, while a healthy
        coordinator's heartbeat doesn't invalidate its own writes."""
        now = time.time()
        with self._write() as conn:
            row = conn.execute(
                "SELECT holder, token, expires FROM leases WHERE name = ?",
                (name,),
            ).fetchone()
            if row is None:
                conn.execute(
                    "INSERT INTO leases VALUES (?, ?, 1, ?)",
                    (name, holder, now + ttl_s),
                )
                return 1
            cur_holder, cur_token, expires = row
            if cur_holder == holder and float(expires) > now:
                conn.execute(
                    "UPDATE leases SET expires = ? WHERE name = ?",
                    (now + ttl_s, name),
                )
                return int(cur_token)
            if float(expires) <= now:
                # expired — ANY caller may take over; tenure changes, so
                # the token bumps even if the holder name is the same
                # (a restarted process must not inherit its dead
                # predecessor's fence)
                conn.execute(
                    "UPDATE leases SET holder = ?, token = token + 1, "
                    "expires = ? WHERE name = ?",
                    (holder, now + ttl_s, name),
                )
                return int(cur_token) + 1
            return None

    def release_lease(self, name: str, holder: str, token: int) -> None:
        """Voluntary release (graceful shutdown) — a no-op unless the
        caller still holds the lease at its current token."""
        with self._write() as conn:
            conn.execute(
                "DELETE FROM leases WHERE name = ? AND holder = ? "
                "AND token = ?",
                (name, holder, int(token)),
            )

    def lease(self, name: str) -> Optional[dict]:
        with self._lock:
            row = self._conn.execute(
                "SELECT holder, token, expires FROM leases WHERE name = ?",
                (name,),
            ).fetchone()
        if row is None:
            return None
        return {"holder": row[0], "token": int(row[1]),
                "expires": float(row[2])}

    # -- engine liveness leases (runtime/engine_main.py heartbeats,
    #    gateway/balancer.py reads) ----------------------------------------

    def heartbeat_engine(self, url: str, boot_id: str,
                         ttl_s: float) -> None:
        with self._write() as conn:
            conn.execute(
                "INSERT INTO engine_leases VALUES (?, ?, ?) "
                "ON CONFLICT(url) DO UPDATE SET boot_id = excluded.boot_id, "
                "expires = excluded.expires",
                (url, boot_id, time.time() + ttl_s),
            )

    def drop_engine(self, url: str) -> None:
        """Graceful deregistration: the engine's lease disappears
        immediately instead of lapsing a TTL later."""
        with self._write() as conn:
            conn.execute(
                "DELETE FROM engine_leases WHERE url = ?", (url,)
            )

    def live_engines(self) -> Dict[str, Tuple[str, float]]:
        """url -> (boot_id, expires) for every UNEXPIRED engine lease.
        An engine that ever heartbeated and is absent here is dead (or
        drained) as far as the balancer is concerned."""
        now = time.time()
        with self._lock:
            rows = self._conn.execute(
                "SELECT url, boot_id, expires FROM engine_leases "
                "WHERE expires > ?",
                (now,),
            ).fetchall()
        return {r[0]: (r[1], float(r[2])) for r in rows}

    def engine_leases(self) -> Dict[str, Tuple[str, float]]:
        """ALL engine leases, lapsed included — url -> (boot_id,
        expires); the balancer distinguishes "lease lapsed" (dead) from
        "never leased" (liveness unknown, fall back to scrape health)."""
        with self._lock:
            rows = self._conn.execute(
                "SELECT url, boot_id, expires FROM engine_leases"
            ).fetchall()
        return {r[0]: (r[1], float(r[2])) for r in rows}

    # -- gateway peer directory (the /fleet federation surface) ------------

    def heartbeat_peer(self, replica_id: str, base_url: str,
                       ttl_s: float) -> None:
        with self._write() as conn:
            conn.execute(
                "INSERT INTO gateway_peers VALUES (?, ?, ?) "
                "ON CONFLICT(replica_id) DO UPDATE SET "
                "base_url = excluded.base_url, expires = excluded.expires",
                (replica_id, base_url, time.time() + ttl_s),
            )

    def drop_peer(self, replica_id: str) -> None:
        with self._write() as conn:
            conn.execute(
                "DELETE FROM gateway_peers WHERE replica_id = ?",
                (replica_id,),
            )

    def peers(self, exclude: Optional[str] = None) -> List[Tuple[str, str]]:
        """Unexpired gateway replicas as (replica_id, base_url)."""
        now = time.time()
        with self._lock:
            rows = self._conn.execute(
                "SELECT replica_id, base_url FROM gateway_peers "
                "WHERE expires > ? ORDER BY replica_id",
                (now,),
            ).fetchall()
        return [(r[0], r[1]) for r in rows if r[0] != exclude]

    # -- federated SLO/QoS burn deltas (fleet-truth accounting) ------------

    def publish_burn(self, replica_id: str, rows) -> None:
        """Upsert one replica's burn deltas in ONE write transaction
        (same BEGIN IMMEDIATE + busy-retry discipline as every other
        shared-state write).  Each row is ``(scope, window, total, slow,
        errors, throttled, shed)`` — absolute current-window counts, so
        a replica's LAST publish stays meaningful after it dies (the
        fold keeps reading it until the window ages it out: no burn
        amnesia on failover)."""
        now = time.time()
        with self._write() as conn:
            for scope, window, total, slow, errors, throttled, shed in rows:
                conn.execute(
                    "INSERT INTO burn_deltas VALUES "
                    "(?, ?, ?, ?, ?, ?, ?, ?, ?) "
                    "ON CONFLICT(replica_id, scope, window) DO UPDATE SET "
                    "total = excluded.total, slow = excluded.slow, "
                    "errors = excluded.errors, "
                    "throttled = excluded.throttled, "
                    "shed = excluded.shed, updated = excluded.updated",
                    (replica_id, str(scope), str(window), int(total),
                     int(slow), int(errors), int(throttled), int(shed),
                     now),
                )

    def burn_rows(self, max_age_s: Optional[float] = None) -> List[Dict]:
        """Every replica's last published deltas (optionally bounded by
        age) — the fold side of fleet-truth burn.  Dead replicas' rows
        are INCLUDED by design; the per-window age mask in the fold is
        what retires them."""
        now = time.time()
        with self._lock:
            rows = self._conn.execute(
                "SELECT replica_id, scope, window, total, slow, errors, "
                "throttled, shed, updated FROM burn_deltas "
                "ORDER BY replica_id, scope, window",
            ).fetchall()
        out: List[Dict] = []
        for r in rows:
            if max_age_s is not None and now - r[8] > max_age_s:
                continue
            out.append({
                "replica_id": r[0], "scope": r[1], "window": r[2],
                "total": r[3], "slow": r[4], "errors": r[5],
                "throttled": r[6], "shed": r[7], "updated": r[8],
            })
        return out

    def close(self) -> None:
        with self._lock:
            self._conn.close()
