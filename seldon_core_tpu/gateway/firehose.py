"""Request/response firehose — the Kafka publish path of the reference
gateway (api-frontend kafka/KafkaRequestResponseProducer.java:30-62: topic =
deployment id, key = puid, fire-and-forget with MAX_BLOCK_MS=20 so logging
can never stall serving).

Here the sink is pluggable: an append-only JSONL file per deployment by
default (one line per RequestResponse, key fields first so consumers can
stream-grep), or any callable sink.  Writes happen on a background task fed
by a bounded queue; when the queue is full events are DROPPED, never
blocking the serving path — the same trade the reference makes."""

from __future__ import annotations

import asyncio
import json
import os
import time
from typing import Callable, Optional

from seldon_core_tpu.messages import SeldonMessage

__all__ = ["Firehose"]


def _default_base_dir() -> str:
    return os.environ.get(
        "SELDON_TPU_FIREHOSE_DIR", os.path.expanduser("~/.seldon_tpu_firehose")
    )


class Firehose:
    def __init__(
        self,
        base_dir: Optional[str] = None,
        sink: Optional[Callable[[str, dict], None]] = None,
        max_queue: int = 4096,
    ):
        self.base_dir = base_dir or _default_base_dir()
        self.sink = sink
        self.dropped = 0
        self._queue: asyncio.Queue = asyncio.Queue(maxsize=max_queue)
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.get_running_loop().create_task(self._drain())

    async def stop(self) -> None:
        if self._task is not None:
            await self._queue.join()
            self._task.cancel()
            self._task = None

    def snapshot(self) -> dict:
        """Backpressure picture for ``/stats`` — queue depth vs bound and
        the lifetime drop count."""
        return {
            "queued": self._queue.qsize(),
            "max_queue": self._queue.maxsize,
            "dropped": self.dropped,
        }

    def publish(
        self, deployment: str, request: SeldonMessage,
        response: SeldonMessage, tenant: Optional[str] = None,
        tier: Optional[str] = None,
    ) -> None:
        """Fire-and-forget; drops when the queue is full (never blocks).
        ``tenant``/``tier`` (runtime/qos.py) land as top-level fields so
        a grep over the JSONL attributes traffic per tenant; absent for
        pre-tenancy producers — consumers must tolerate both."""
        event = {
            "puid": response.meta.puid or request.meta.puid,
            "deployment": deployment,
            "ts": time.time(),
            "request": request.to_json_dict(),
            "response": response.to_json_dict(),
        }
        if tenant is not None:
            event["tenant"] = tenant
        if tier is not None:
            event["tier"] = tier
        try:
            self._queue.put_nowait(event)
        except asyncio.QueueFull:
            self.dropped += 1

    def publish_event(self, deployment: str, kind: str, **fields) -> None:
        """Control-plane event on the same firehose (fire-and-forget,
        same drop-when-full trade): rollout stage shifts and rollbacks
        (operator/rollouts.py) land next to the request stream they
        acted on, so one grep over the JSONL reconstructs WHY traffic
        moved.  ``kind`` becomes the line's ``event`` field; request/
        response stay absent so stream consumers keyed on them skip
        these lines cleanly."""
        event = {
            "puid": "",
            "deployment": deployment,
            "ts": time.time(),
            "event": kind,
            **fields,
        }
        try:
            self._queue.put_nowait(event)
        except asyncio.QueueFull:
            self.dropped += 1

    async def _drain(self) -> None:
        while True:
            event = await self._queue.get()
            try:
                if self.sink is not None:
                    self.sink(event["deployment"], event)
                else:
                    os.makedirs(self.base_dir, exist_ok=True)
                    path = os.path.join(self.base_dir, f"{event['deployment']}.jsonl")
                    with open(path, "a") as f:
                        f.write(json.dumps(event, separators=(",", ":")) + "\n")
            except Exception:
                self.dropped += 1
            finally:
                self._queue.task_done()


def main(argv=None) -> None:
    """Consumer CLI — the reference's Kafka reader example
    (kafka/tests/src/read_predictions.py:22-30): stream a deployment's
    request/response log, one summarised line per event.

        python -m seldon_core_tpu.gateway.firehose <deployment> [--follow]
    """
    import argparse
    import sys
    import time as _time

    parser = argparse.ArgumentParser(description="firehose consumer")
    parser.add_argument("deployment", help="deployment id (topic)")
    parser.add_argument("--dir", default=None, help="firehose base dir")
    parser.add_argument("--follow", action="store_true", help="tail -f mode")
    parser.add_argument("--raw", action="store_true", help="print full JSONL")
    args = parser.parse_args(argv)
    base = args.dir or _default_base_dir()
    path = os.path.join(base, f"{args.deployment}.jsonl")
    if not os.path.exists(path) and not args.follow:
        raise SystemExit(f"no firehose log at {path}")

    def emit(line: str) -> None:
        line = line.strip()
        if not line:
            return
        if args.raw:
            sys.stdout.write(line + "\n")
            return
        try:
            ev = json.loads(line)
        except json.JSONDecodeError:
            return
        status = ((ev.get("response") or {}).get("status") or {})
        sys.stdout.write(
            f"{ev.get('ts', 0):.3f} puid={ev.get('puid', '')} "
            f"status={status.get('status', 'SUCCESS')}\n"
        )

    pos = 0
    while True:
        if os.path.exists(path):
            if os.path.getsize(path) < pos:
                pos = 0  # truncated/rotated: restart from the top
            with open(path) as f:
                f.seek(pos)
                while True:
                    line_start = f.tell()
                    line = f.readline()
                    if not line:
                        break
                    if not line.endswith("\n"):
                        # producer mid-write: hold the fragment back and
                        # re-read the whole line once it is terminated
                        pos = line_start
                        break
                    emit(line)
                    pos = f.tell()
        if not args.follow:
            break
        _time.sleep(1.0)
        sys.stdout.flush()


if __name__ == "__main__":
    main()
