"""Gateway process entrypoint — the reference's apife pod boot.

Env contract (rendered by operator/bundle.py, mirroring the apife chart
values):

  GATEWAY_REST_PORT / GATEWAY_GRPC_PORT   listen ports (8080 / 5000)
  GATEWAY_OAUTH_ENABLED                   "0" disables auth (open gateway;
                                          tenant identity then comes from
                                          the Seldon-Tenant header alone)

Multi-tenant QoS (runtime/qos.py; docs/operations.md "Surviving
overload"): requests carry Seldon-Tenant / Seldon-Tier headers, and the
SELDON_TPU_TENANT_* / SELDON_TPU_GW_FAIR_INFLIGHT env knobs turn on
per-tenant token buckets and weighted-fair admission; the brownout
ladder (SELDON_TPU_BROWNOUT_*) sheds lower tiers under overload.
  GATEWAY_STATE_PATH                      sqlite file for replica-shared
                                          tokens/registrations (the
                                          reference's Redis role,
                                          gateway/state.py); empty =
                                          per-process in-memory store
  GATEWAY_SPEC_DIR                        directory of SeldonDeployment
                                          JSONs to register, polled like
                                          the operator's watch_dir
  GATEWAY_ENGINE_URL_TEMPLATE             engine base URL per deployment,
                                          default "http://{name}:8000"
                                          ({name} = deployment Service;
                                          {predictor} and {replica} are
                                          also substituted)
  GATEWAY_ENGINE_REPLICAS                 N>1 expands a {replica}-bearing
                                          template into an N-endpoint
                                          replica set per predictor
                                          (power-of-two-choices balancing,
                                          gateway/balancer.py)
  GATEWAY_ENGINE_URL_MAP                  per-predictor overrides; a JSON
                                          LIST value registers a replica
                                          set, and endpoint specs may
                                          carry a "+uds:/path" suffix for
                                          the zero-copy co-located lane
                                          (runtime/udsrelay.py)

    python -m seldon_core_tpu.gateway.gateway_main [--spec-dir DIR]
"""

from __future__ import annotations

import argparse
import asyncio
import glob
import json
import os

from seldon_core_tpu.gateway.apife import ApiGateway, DeploymentStore
from seldon_core_tpu.gateway.firehose import Firehose
from seldon_core_tpu.graph.spec import GraphSpecError, SeldonDeploymentSpec

__all__ = ["main"]


def _build_store():
    path = os.environ.get("GATEWAY_STATE_PATH", "").strip()
    if path:
        from seldon_core_tpu.gateway.state import SqliteDeploymentStore

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        return SqliteDeploymentStore(path)
    return DeploymentStore()


def _engine_url_map() -> dict:
    """Explicit per-predictor overrides: '{"<deployment>/<predictor>":
    url-or-list}' — topologies where predictor engines don't follow one
    URL pattern (canary pairs on distinct ports, split-cluster serving).
    A LIST value registers a replica set the gateway balances over.
    Parsed once at boot; a malformed value is a fatal config error with a
    clear message, not a crash-loop in the poll tick."""
    raw_map = os.environ.get("GATEWAY_ENGINE_URL_MAP", "").strip()
    if not raw_map:
        return {}
    try:
        out = {}
        for k, v in json.loads(raw_map).items():
            if isinstance(v, list):
                if not v or not all(isinstance(u, str) for u in v):
                    raise ValueError(
                        f"{k!r}: a replica list must be non-empty strings"
                    )
                out[str(k)] = [str(u) for u in v]
            else:
                out[str(k)] = str(v)
        return out
    except (json.JSONDecodeError, AttributeError, ValueError) as e:
        raise SystemExit(
            f"GATEWAY_ENGINE_URL_MAP is not a JSON object of "
            f"'deployment/predictor' -> url (or list of urls): {e}"
        ) from e


def _engine_url_template() -> str:
    """Validated once at boot: a template with placeholders other than
    {name}/{predictor}/{replica} is a fatal config error with a clear
    message — NOT a KeyError escaping from the poll loop on the first
    matching spec."""
    template = os.environ.get(
        "GATEWAY_ENGINE_URL_TEMPLATE", "http://{name}:8000"
    )
    try:
        template.format(name="x", predictor="y", replica=0)
    except (KeyError, IndexError, ValueError) as e:
        raise SystemExit(
            f"GATEWAY_ENGINE_URL_TEMPLATE {template!r} is invalid: only "
            f"{{name}}, {{predictor}} and {{replica}} placeholders are "
            f"supported ({e})"
        ) from e
    return template


def _engine_replicas() -> int:
    """``GATEWAY_ENGINE_REPLICAS``: endpoints per predictor rendered from
    a {replica}-bearing template (validated at boot, same policy as the
    template itself)."""
    raw = os.environ.get("GATEWAY_ENGINE_REPLICAS", "").strip()
    if not raw:
        return 1
    try:
        n = int(raw)
    except ValueError as e:
        raise SystemExit(
            f"GATEWAY_ENGINE_REPLICAS {raw!r} is not an integer"
        ) from e
    if n < 1:
        raise SystemExit(f"GATEWAY_ENGINE_REPLICAS must be >= 1, got {n}")
    return n


def _check_replica_template(replicas: int, template: str) -> int:
    """Same fatal-at-boot policy as every other misconfig here: a replica
    count the template can't render would otherwise register
    single-endpoint sets and the scale-out would silently not exist."""
    if replicas > 1 and "{replica}" not in template:
        raise SystemExit(
            f"GATEWAY_ENGINE_REPLICAS={replicas} needs a {{replica}} "
            f"placeholder in GATEWAY_ENGINE_URL_TEMPLATE (got {template!r})"
        )
    return replicas


def _render_endpoints(template: str, name: str, predictor: str,
                      replicas: int):
    """One URL, or — when a {replica} template meets replicas>1 — a
    replica-set list the gateway p2c-balances over."""
    if replicas > 1 and "{replica}" in template:
        return [
            template.format(name=name, predictor=predictor, replica=i)
            for i in range(replicas)
        ]
    return template.format(name=name, predictor=predictor, replica=0)


def _register_specs(store, spec_dir: str, seen: dict, url_map: dict,
                    template: str, replicas: int = 1) -> None:
    for path in sorted(glob.glob(os.path.join(spec_dir, "*.json"))):
        mtime = os.path.getmtime(path)
        if seen.get(path) == mtime:
            continue
        try:
            with open(path) as f:
                spec = SeldonDeploymentSpec.from_json_dict(json.load(f))
            # {predictor} in the template routes each predictor to its own
            # engine Service — the canary topology (one engine pod per
            # predictor, replica-weighted split in ApiGateway._pick_engine);
            # {replica} x GATEWAY_ENGINE_REPLICAS renders a replica SET
            # per predictor instead (p2c balancing within the predictor)
            engines = {
                p.name: url_map.get(
                    f"{spec.name}/{p.name}",
                    _render_endpoints(template, spec.name, p.name, replicas),
                )
                for p in spec.predictors
            }
            store.register(spec, engines)
            seen[path] = mtime
            print(f"registered {spec.name} -> "
                  f"{sorted(str(v) for v in engines.values())}",
                  flush=True)
        except (GraphSpecError, ValueError, OSError,
                json.JSONDecodeError) as e:
            print(f"skipping {path}: {e}", flush=True)
            seen[path] = mtime


async def serve(spec_dir: str = "", host: str = "0.0.0.0") -> None:
    from seldon_core_tpu.gateway.apife import make_gateway_app
    from seldon_core_tpu.runtime.grpc_server import make_gateway_grpc_server
    from seldon_core_tpu.runtime.rest import serve_app

    rest_port = int(os.environ.get("GATEWAY_REST_PORT", "8080"))
    grpc_port = int(os.environ.get("GATEWAY_GRPC_PORT", "5000"))
    store = _build_store()
    firehose_dir = os.environ.get("GATEWAY_FIREHOSE_DIR", "").strip()
    gateway = ApiGateway(
        store=store,
        firehose=Firehose(firehose_dir) if firehose_dir else None,
        require_auth=os.environ.get("GATEWAY_OAUTH_ENABLED", "1") != "0",
    )
    if gateway.firehose is not None:
        gateway.firehose.start()  # drain task needs the running loop
    # gateway federation (gateway/federation.py): with a shared sqlite
    # state file and SELDON_TPU_FEDERATION unset/1, this replica joins
    # the coordinator election + peer directory.  In-memory store or
    # SELDON_TPU_FEDERATION=0: no-op, single-gateway behavior bit-for-bit
    from seldon_core_tpu.gateway.federation import GatewayFederation

    advertise = os.environ.get("GATEWAY_ADVERTISE_URL", "").strip() or \
        f"http://127.0.0.1:{rest_port}"
    federation = GatewayFederation(store, base_url=advertise)
    # the burn publisher reads this replica's QoS throttle/shed totals
    # off the gateway's tenant governor (fleet-truth burn accounting)
    federation.governor = gateway.tenants
    gateway.federation = federation
    fed_stop = asyncio.Event()
    fed_task = None
    if federation.enabled:
        fed_task = asyncio.get_running_loop().create_task(
            federation.run(fed_stop))
        print(f"federation: replica={federation.replica_id} "
              f"ttl={federation.ttl_s:.1f}s advertise={advertise}",
              flush=True)
    seen: dict = {}
    url_map = _engine_url_map()
    template = _engine_url_template()  # fatal at boot if malformed
    replicas = _check_replica_template(_engine_replicas(), template)
    if spec_dir:
        _register_specs(store, spec_dir, seen, url_map, template, replicas)
    runner = await serve_app(make_gateway_app(gateway), host, rest_port)
    grpc_server = make_gateway_grpc_server(gateway, host, grpc_port)
    await grpc_server.start()
    print(
        f"gateway up: deployments={store.deployments()} "
        f"rest=:{rest_port} grpc=:{grpc_port}",
        flush=True,
    )

    import signal

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, stop.set)
        except (NotImplementedError, RuntimeError):
            pass
    while not stop.is_set():
        try:
            await asyncio.wait_for(stop.wait(), timeout=5.0)
        except asyncio.TimeoutError:
            if spec_dir:  # poll for new/changed deployment specs
                _register_specs(store, spec_dir, seen, url_map, template,
                                replicas)
    if fed_task is not None:
        fed_stop.set()
        await fed_task
        federation.resign()  # hand the lease over NOW, not at TTL expiry
    await grpc_server.stop(grace=5.0)
    await runner.cleanup()
    if gateway.firehose is not None:
        await gateway.firehose.stop()  # flush queued events before exit
    print("gateway stopped", flush=True)


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description="seldon_core_tpu gateway")
    parser.add_argument(
        "--spec-dir", default=os.environ.get("GATEWAY_SPEC_DIR", "")
    )
    parser.add_argument("--host", default="0.0.0.0")
    args = parser.parse_args(argv)
    asyncio.run(serve(args.spec_dir, args.host))


if __name__ == "__main__":
    main()
