"""Gateway process entrypoint — the reference's apife pod boot.

Env contract (rendered by operator/bundle.py, mirroring the apife chart
values):

  GATEWAY_REST_PORT / GATEWAY_GRPC_PORT   listen ports (8080 / 5000)
  GATEWAY_OAUTH_ENABLED                   "0" disables auth (single-tenant)
  GATEWAY_STATE_PATH                      sqlite file for replica-shared
                                          tokens/registrations (the
                                          reference's Redis role,
                                          gateway/state.py); empty =
                                          per-process in-memory store
  GATEWAY_SPEC_DIR                        directory of SeldonDeployment
                                          JSONs to register, polled like
                                          the operator's watch_dir
  GATEWAY_ENGINE_URL_TEMPLATE             engine base URL per deployment,
                                          default "http://{name}:8000"
                                          ({name} = deployment Service)

    python -m seldon_core_tpu.gateway.gateway_main [--spec-dir DIR]
"""

from __future__ import annotations

import argparse
import asyncio
import glob
import json
import os

from seldon_core_tpu.gateway.apife import ApiGateway, DeploymentStore
from seldon_core_tpu.gateway.firehose import Firehose
from seldon_core_tpu.graph.spec import GraphSpecError, SeldonDeploymentSpec

__all__ = ["main"]


def _build_store():
    path = os.environ.get("GATEWAY_STATE_PATH", "").strip()
    if path:
        from seldon_core_tpu.gateway.state import SqliteDeploymentStore

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        return SqliteDeploymentStore(path)
    return DeploymentStore()


def _engine_url_map() -> dict:
    """Explicit per-predictor overrides: '{"<deployment>/<predictor>": url}'
    — topologies where predictor engines don't follow one URL pattern
    (canary pairs on distinct ports, split-cluster serving).  Parsed once
    at boot; a malformed value is a fatal config error with a clear
    message, not a crash-loop in the poll tick."""
    raw_map = os.environ.get("GATEWAY_ENGINE_URL_MAP", "").strip()
    if not raw_map:
        return {}
    try:
        return {str(k): str(v) for k, v in json.loads(raw_map).items()}
    except (json.JSONDecodeError, AttributeError) as e:
        raise SystemExit(
            f"GATEWAY_ENGINE_URL_MAP is not a JSON object of "
            f"'deployment/predictor' -> url: {e}"
        ) from e


def _engine_url_template() -> str:
    """Validated once at boot: a template with placeholders other than
    {name}/{predictor} is a fatal config error with a clear message — NOT
    a KeyError escaping from the poll loop on the first matching spec."""
    template = os.environ.get(
        "GATEWAY_ENGINE_URL_TEMPLATE", "http://{name}:8000"
    )
    try:
        template.format(name="x", predictor="y")
    except (KeyError, IndexError, ValueError) as e:
        raise SystemExit(
            f"GATEWAY_ENGINE_URL_TEMPLATE {template!r} is invalid: only "
            f"{{name}} and {{predictor}} placeholders are supported ({e})"
        ) from e
    return template


def _register_specs(store, spec_dir: str, seen: dict, url_map: dict,
                    template: str) -> None:
    for path in sorted(glob.glob(os.path.join(spec_dir, "*.json"))):
        mtime = os.path.getmtime(path)
        if seen.get(path) == mtime:
            continue
        try:
            with open(path) as f:
                spec = SeldonDeploymentSpec.from_json_dict(json.load(f))
            # {predictor} in the template routes each predictor to its own
            # engine Service — the canary topology (one engine pod per
            # predictor, replica-weighted split in ApiGateway._pick_engine)
            engines = {
                p.name: url_map.get(
                    f"{spec.name}/{p.name}",
                    template.format(name=spec.name, predictor=p.name),
                )
                for p in spec.predictors
            }
            store.register(spec, engines)
            seen[path] = mtime
            print(f"registered {spec.name} -> {sorted(engines.values())}",
                  flush=True)
        except (GraphSpecError, ValueError, OSError,
                json.JSONDecodeError) as e:
            print(f"skipping {path}: {e}", flush=True)
            seen[path] = mtime


async def serve(spec_dir: str = "", host: str = "0.0.0.0") -> None:
    from seldon_core_tpu.gateway.apife import make_gateway_app
    from seldon_core_tpu.runtime.grpc_server import make_gateway_grpc_server
    from seldon_core_tpu.runtime.rest import serve_app

    rest_port = int(os.environ.get("GATEWAY_REST_PORT", "8080"))
    grpc_port = int(os.environ.get("GATEWAY_GRPC_PORT", "5000"))
    store = _build_store()
    firehose_dir = os.environ.get("GATEWAY_FIREHOSE_DIR", "").strip()
    gateway = ApiGateway(
        store=store,
        firehose=Firehose(firehose_dir) if firehose_dir else None,
        require_auth=os.environ.get("GATEWAY_OAUTH_ENABLED", "1") != "0",
    )
    if gateway.firehose is not None:
        gateway.firehose.start()  # drain task needs the running loop
    seen: dict = {}
    url_map = _engine_url_map()
    template = _engine_url_template()  # fatal at boot if malformed
    if spec_dir:
        _register_specs(store, spec_dir, seen, url_map, template)
    runner = await serve_app(make_gateway_app(gateway), host, rest_port)
    grpc_server = make_gateway_grpc_server(gateway, host, grpc_port)
    await grpc_server.start()
    print(
        f"gateway up: deployments={store.deployments()} "
        f"rest=:{rest_port} grpc=:{grpc_port}",
        flush=True,
    )

    import signal

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, stop.set)
        except (NotImplementedError, RuntimeError):
            pass
    while not stop.is_set():
        try:
            await asyncio.wait_for(stop.wait(), timeout=5.0)
        except asyncio.TimeoutError:
            if spec_dir:  # poll for new/changed deployment specs
                _register_specs(store, spec_dir, seen, url_map, template)
    await grpc_server.stop(grace=5.0)
    await runner.cleanup()
    if gateway.firehose is not None:
        await gateway.firehose.stop()  # flush queued events before exit
    print("gateway stopped", flush=True)


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description="seldon_core_tpu gateway")
    parser.add_argument(
        "--spec-dir", default=os.environ.get("GATEWAY_SPEC_DIR", "")
    )
    parser.add_argument("--host", default="0.0.0.0")
    args = parser.parse_args(argv)
    asyncio.run(serve(args.spec_dir, args.host))


if __name__ == "__main__":
    main()
