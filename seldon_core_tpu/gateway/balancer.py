"""Engine replica sets + power-of-two-choices gateway balancing.

The reference scales a predictor by setting ``replicas`` on its engine
Deployment and letting the k8s Service round-robin over the pods; the
gateway never sees individual replicas.  Here the gateway DOES see them
(Podracer-style, arxiv 2104.06272: sheets of identical workers behind a
thin dispatcher) so it can balance on live load instead of blind
rotation:

* a :class:`ReplicaSet` holds N endpoints for one predictor — engine
  base URLs, ``uds:`` socket paths (runtime/udsrelay.py zero-copy lane),
  or in-process ``EngineService`` objects;
* :meth:`ReplicaSet.pick` is **power-of-two-choices**: sample two
  distinct replicas, score each as ``(outstanding requests) x (EWMA
  latency)`` — expected wait, not just queue depth — and take the lower.
  P2c gets within a constant factor of least-loaded at O(1) cost and,
  unlike full least-loaded, doesn't herd every gateway replica onto the
  same momentarily-idle engine;
* health is **passive**: the gateway's periodic ``GET /stats`` scrape
  (the surface every engine already serves) feeds per-replica engine-side
  inflight and circuit-breaker state.  A replica whose breaker is open,
  whose scrape failed, or whose scrape went stale is deprioritized by a
  score penalty — composing with the PR-2 breakers rather than
  duplicating their probing;
* every decision is auditable: the chosen replica and both candidates'
  scores ride the request span (gateway/apife.py stamps them), picks and
  gateway-side inflight land in the ``seldon_tpu_replica_*`` families,
  and hindsight **mispicks** (the pick finished slower than the losing
  candidate's EWMA at decision time) are counted so a broken score
  function shows up as a ratio, not an anecdote.

``SELDON_TPU_REPLICAS=0`` is the kill switch: every pick returns the
first endpoint with no sampling, no scoring and no metrics — bit-for-bit
today's single-engine path.
"""

from __future__ import annotations

import os
import random
import re
import time
from dataclasses import dataclass
from typing import List, Optional, Tuple

from seldon_core_tpu.runtime.autopilot import autopilot_enabled, pad_bucket
from seldon_core_tpu.utils.telemetry import RECORDER

__all__ = [
    "ReplicaEndpoint",
    "ReplicaSet",
    "PickDecision",
    "parse_endpoint_spec",
    "replicas_enabled",
    "uds_enabled",
]

#: EWMA smoothing for per-replica latency; small enough to remember a
#: slow spell for ~10 requests, large enough to converge fast after boot
_EWMA_ALPHA = 0.2
#: score floor so a no-sample-yet replica isn't infinitely attractive
_EWMA_FLOOR_MS = 0.1
#: additive score penalty for a degraded replica (breaker open / scrape
#: failed / scrape stale / fast-failing): it still serves when EVERY
#: candidate is degraded, but never beats a healthy one
_UNHEALTHY_PENALTY = 1e9
#: an idle, healthy endpoint whose last completed sample is older than
#: this prices at the floor so p2c sends it ONE probe, and a probe that
#: wildly disagrees with the stale EWMA RESEEDS it instead of blending.
#: Without this an endpoint whose first sample ate a one-off cost (jit
#: compile, cold page cache) can be starved FOREVER: p2c never re-picks
#: it, so its poisoned EWMA never gets a correcting sample.  Cost of the
#: escape hatch: at most one redirected request per window per idle
#: endpoint.  SELDON_TPU_REPROBE_S overrides; 0 disables
_REPROBE_AFTER_S = 0.1
#: blend-vs-reseed trust region: a fresh sample within this factor of
#: the stale EWMA still blends (low-traffic endpoints keep smoothing);
#: beyond it the history is judged wrong and replaced
_REPROBE_RESEED_X = 4.0


def reprobe_after_s() -> float:
    try:
        return float(os.environ.get(
            "SELDON_TPU_REPROBE_S", str(_REPROBE_AFTER_S)))
    except ValueError:
        return _REPROBE_AFTER_S
#: consecutive dispatch failures before a replica is degraded — without
#: this a replica that FAILS in microseconds drains its inflight
#: instantly, scores at the EWMA floor, and becomes a traffic black hole
#: (failures don't update the EWMA, so nothing else raises its score)
_FAIL_DEGRADE_AFTER = 3
#: how long the failure degradation lasts after the latest failure — the
#: passive half-open: after a quiet cooldown the replica gets sampled
#: again, and one success clears it (one more failure re-arms it)
_FAIL_DEGRADE_COOLDOWN_S = 5.0


def replicas_enabled() -> bool:
    """Kill switch: ``SELDON_TPU_REPLICAS=0`` restores the single-engine
    path (first registered endpoint, no p2c, no replica metrics)."""
    return os.environ.get("SELDON_TPU_REPLICAS", "1") != "0"


def uds_enabled() -> bool:
    """Kill switch: ``SELDON_TPU_UDS=0`` keeps every dispatch on TCP even
    when an endpoint advertises a ``uds:`` socket path."""
    return os.environ.get("SELDON_TPU_UDS", "1") != "0"


def _pm_note(reason: str, **attrs) -> None:
    """Out-of-band postmortem breadcrumb for fleet-health transitions
    (lease flips, breaker opens).  These events have no open request
    span, so they land as traceless synthetic exemplars — bounded, and
    inert when the recorder is disabled.  Never raises: replica health
    bookkeeping must not depend on the observability layer."""
    try:
        from seldon_core_tpu.utils.postmortem import POSTMORTEM

        POSTMORTEM.note("", reason, **attrs)
    except Exception:  # noqa: BLE001 - breadcrumbs are best-effort
        pass


def fleet_scrape_enabled() -> bool:
    """Should the scrape pass retain fleet documents (gateway/fleet.py)?
    Off with the federation kill switch (``SELDON_TPU_FLEET=0``) or
    explicitly via ``SELDON_TPU_FLEET_SCRAPE=0`` (health scraping keeps
    its original, lighter shape in both cases)."""
    if os.environ.get("SELDON_TPU_FLEET_SCRAPE", "1") == "0":
        return False
    from seldon_core_tpu.gateway.fleet import fleet_enabled

    return fleet_enabled()


def parse_endpoint_spec(spec: str) -> Tuple[Optional[str], Optional[str]]:
    """``(base_url, uds_path)`` from an endpoint spec string.

    Three forms (gateway_main env contract, docs/operations.md):

    * ``http://host:port``                   TCP only
    * ``uds:/path/to.sock``                  UDS only (no /stats scrape,
                                             no SSE proxy — hot path only)
    * ``http://host:port+uds:/path/to.sock`` TCP for scrape/stream, UDS
                                             for the predict/feedback hot
                                             path
    """
    spec = spec.strip()
    if "+uds:" in spec:
        base, _, uds = spec.partition("+uds:")
        return base.rstrip("/") or None, uds or None
    if spec.startswith("uds:"):
        return None, spec[len("uds:"):] or None
    return spec.rstrip("/") or None, None


class ReplicaEndpoint:
    """One engine replica as the gateway sees it: the dispatch target plus
    the live score inputs (gateway-side inflight, EWMA latency, scraped
    engine-side inflight + breaker state)."""

    __slots__ = (
        "target", "base_url", "uds_path", "name", "index", "set_name",
        "role", "inflight", "batcher_inflight", "ewma_ms", "shape_ms",
        "picks", "failures", "consec_failures", "fail_degraded_until",
        "scraped_inflight", "scraped_free_kv", "scrape_ts",
        "scrape_failed", "breaker_open", "fleet_docs",
        "boot_id", "epoch_resets", "lease_state",
        "last_sample_ts", "ewma_reseeds",
    )

    #: minimum samples before a shape bucket's own EWMA is trusted
    #: outright; below it the prediction blends toward the global EWMA
    SHAPE_MIN_SAMPLES = 5
    #: bounded per-shape table — pow2 buckets give ~20 keys max anyway
    SHAPE_MAX_BUCKETS = 32

    def __init__(self, target, index: int = 0, set_name: str = "default"):
        self.index = index
        self.set_name = set_name
        #: generation role in a disaggregated mesh
        #: (runtime/servingmesh.py): "prefill" / "decode" / "unified".
        #: Decode replicas only import KV handoffs — the gateway's picks
        #: exclude them from client traffic (phase-aware routing)
        self.role = "unified"
        if isinstance(target, str):
            spec = target
            # the +role: segment may sit anywhere among the spec's
            # + suffixes (e.g. url+role:decode+uds:/e.sock): extract the
            # segment, keep the rest — an order-sensitive parse would
            # silently swallow whatever follows it
            m = re.search(r"\+role:([a-zA-Z]+)", spec)
            if m:
                role = m.group(1).lower()
                if role in ("prefill", "decode", "unified"):
                    self.role = role
                spec = spec[:m.start()] + spec[m.end():]
            self.base_url, self.uds_path = parse_endpoint_spec(spec)
            self.target = target
            self.name = self.base_url or f"uds:{self.uds_path}"
        else:  # in-process EngineService-like object
            self.base_url = None
            self.uds_path = None
            self.target = target
            self.name = f"inprocess-{index}"
            role = getattr(target, "gen_role", "unified")
            if role in ("prefill", "decode", "unified"):
                self.role = role
        self.inflight = 0
        # the subset of ``inflight`` that rides the engine's MicroBatcher
        # (unary predicts) — the only part the scraped engine-side
        # ``inflight_dispatches`` figure can also contain
        self.batcher_inflight = 0
        self.ewma_ms = 0.0  # 0 = no successful sample yet
        #: free paged-KV blocks scraped off the /stats genserver block —
        #: the decode-capacity headroom signal (None = not a generator)
        self.scraped_free_kv: Optional[int] = None
        # per-request-shape latency models (autopilot cost-aware routing):
        # pad bucket (pow2 of row count) -> [ewma_ms, samples].  A 1-row
        # predict and a 512-row predict have wildly different walls; a
        # shape-blind EWMA averages them into a score that mispredicts
        # both.  SELDON_TPU_AUTOPILOT=0 restores the blind EWMA
        self.shape_ms: dict = {}
        self.picks = 0
        #: monotonic time of the last SUCCESSFUL completed sample; 0 =
        #: never sampled.  Drives the stale-EWMA re-probe (see
        #: _REPROBE_AFTER_S)
        self.last_sample_ts = 0.0
        #: times a re-probe sample replaced (not blended into) a stale
        #: EWMA that disagreed beyond the trust region
        self.ewma_reseeds = 0
        self.failures = 0
        self.consec_failures = 0
        self.fail_degraded_until = 0.0
        # passive health, fed by ReplicaSet.scrape_once
        self.scraped_inflight = 0
        self.scrape_ts = 0.0
        self.scrape_failed = False
        self.breaker_open = False
        #: fleet-observability document stash (gateway/fleet.py): the
        #: full /stats (+ /perf + /quality) docs the LAST scrape pass
        #: retained, with a monotonic timestamp — /fleet rollups and the
        #: seldon_tpu_fleet_* outlier gauges read from here so the
        #: aggregation adds zero polling of its own
        self.fleet_docs: Optional[dict] = None
        #: engine boot epoch, scraped off /stats (or carried by the
        #: engine's liveness lease).  A CHANGE at the same URL means the
        #: process restarted: every score input learned about the dead
        #: process (EWMA, shape models, failure streaks, scraped load)
        #: describes nobody and is reset instead of poisoning picks
        self.boot_id: Optional[str] = None
        self.epoch_resets = 0
        #: store-lease liveness (gateway/federation.py feed): None until
        #: the engine ever heartbeats a lease, then "live"/"dead".  A
        #: lapsed or dropped lease marks the replica dead within one
        #: lease TTL — faster than 3 failed scrapes
        self.lease_state: Optional[str] = None

    def observe_boot_id(self, boot_id: Optional[str]) -> None:
        """Record the engine's boot epoch; on a change at the same URL,
        reset every score input the previous process earned."""
        if not boot_id:
            return
        if self.boot_id is not None and boot_id != self.boot_id:
            self.ewma_ms = 0.0
            self.last_sample_ts = 0.0
            self.shape_ms = {}
            self.consec_failures = 0
            self.fail_degraded_until = 0.0
            self.scraped_inflight = 0
            self.breaker_open = False
            self.epoch_resets += 1
        self.boot_id = boot_id

    # -- health ----------------------------------------------------------

    def degraded(self, now: float, stale_after_s: float) -> bool:
        if self.lease_state == "dead":
            return True
        # fast-failure degradation applies to EVERY target kind — it is
        # the only health signal a uds-only or in-process endpoint has,
        # and the cooldown expiring is the passive half-open probe
        if now < self.fail_degraded_until:
            return True
        if isinstance(self.target, str):
            if self.breaker_open or self.scrape_failed:
                return True
            # staleness only counts once a scrape ever succeeded — sets
            # that never run the scraper (tests, in-bench single shots)
            # must not read as degraded
            return (
                self.scrape_ts > 0.0
                and now - self.scrape_ts > stale_after_s
            )
        # in-process: breaker state is readable directly, no scrape needed
        open_breakers = getattr(self.target, "open_breakers", None)
        return bool(open_breakers()) if callable(open_breakers) else False

    def predicted_ms(self, rows: Optional[int] = None) -> float:
        """Per-request latency prediction for a request of ``rows`` rows:
        the pad bucket's own EWMA once it has ``SHAPE_MIN_SAMPLES``,
        blended toward the shape-blind global EWMA below that, and the
        global EWMA when the shape is unknown or the autopilot is off —
        bit-for-bit the pre-autopilot score input in that case."""
        if rows is None or not autopilot_enabled():
            return self.ewma_ms
        model = self.shape_ms.get(pad_bucket(rows))
        if model is None or model[1] == 0:
            return self.ewma_ms
        ms, n = model
        if n >= self.SHAPE_MIN_SAMPLES or self.ewma_ms == 0.0:
            return ms
        w = n / self.SHAPE_MIN_SAMPLES
        return w * ms + (1.0 - w) * self.ewma_ms

    def score(self, now: float, stale_after_s: float,
              rows: Optional[int] = None) -> float:
        """Expected wait: (queued work) x (per-request cost).  Gateway-side
        inflight is authoritative for work THIS gateway queued; the scraped
        engine-side inflight adds load other gateways put there.  The
        per-request cost is shape-aware when the caller passes the request
        row count (autopilot cost-aware routing)."""
        ms = max(self.predicted_ms(rows), _EWMA_FLOOR_MS)
        degraded = self.degraded(now, stale_after_s)
        reprobe = reprobe_after_s()
        if (
            not degraded
            and reprobe > 0.0
            and self.inflight == 0
            and self.last_sample_ts > 0.0
            and now - self.last_sample_ts > reprobe
        ):
            # idle + healthy + no fresh sample: the EWMA is hearsay.
            # Price at the floor so p2c sends ONE probe (the inflight
            # gate stops a pile-on while the probe is out) — the
            # completion either confirms the history or reseeds it
            ms = _EWMA_FLOOR_MS
        s = (self.inflight + self.scraped_inflight + 1) * ms
        if degraded:
            s += _UNHEALTHY_PENALTY
        return s

    # -- dispatch accounting ---------------------------------------------

    def begin(self, batcher: bool = True) -> None:
        """``batcher=False`` for dispatches that do NOT enter the engine's
        MicroBatcher (streams, feedback acks) — they count as load but must
        not be subtracted from the scraped engine-side figure."""
        self.inflight += 1
        if batcher:
            self.batcher_inflight += 1
        RECORDER.set_replica_inflight(self.set_name, self.name, self.inflight)

    def complete(self, latency_s: float, ok: bool = True,
                 rows: Optional[int] = None) -> None:
        self.inflight = max(0, self.inflight - 1)
        self.batcher_inflight = max(0, self.batcher_inflight - 1)
        RECORDER.set_replica_inflight(self.set_name, self.name, self.inflight)
        if ok:
            ms = latency_s * 1e3
            now = time.monotonic()
            reprobe = reprobe_after_s()
            stale = (
                reprobe > 0.0
                and self.last_sample_ts > 0.0
                and now - self.last_sample_ts > reprobe
            )
            if self.ewma_ms == 0.0:
                self.ewma_ms = ms
            elif stale and not (
                self.ewma_ms / _REPROBE_RESEED_X
                <= ms
                <= self.ewma_ms * _REPROBE_RESEED_X
            ):
                # stale history that a fresh probe contradicts beyond
                # the trust region is judged WRONG, not smoothed: a
                # compile-poisoned 400ms first sample blended at
                # alpha=0.2 needs ~10 probes to converge, and p2c only
                # grants one probe per re-probe window — reseed instead
                self.ewma_ms = ms
                self.ewma_reseeds += 1
            else:
                self.ewma_ms = (
                    (1 - _EWMA_ALPHA) * self.ewma_ms + _EWMA_ALPHA * ms
                )
            self.last_sample_ts = now
            if rows is not None:
                bucket = pad_bucket(rows)
                model = self.shape_ms.get(bucket)
                if model is not None:
                    model[0] = (
                        (1 - _EWMA_ALPHA) * model[0] + _EWMA_ALPHA * ms
                    )
                    model[1] += 1
                elif len(self.shape_ms) < self.SHAPE_MAX_BUCKETS:
                    self.shape_ms[bucket] = [ms, 1]
            self.consec_failures = 0
            self.fail_degraded_until = 0.0
        else:
            self.failures += 1
            self.consec_failures += 1
            if self.consec_failures >= _FAIL_DEGRADE_AFTER:
                # a fast-failing replica would otherwise WIN every pick:
                # failures drain inflight instantly and never raise the
                # EWMA, pinning its score at the floor — degrade it for a
                # cooldown instead of letting it eat the traffic
                self.fail_degraded_until = (
                    time.monotonic() + _FAIL_DEGRADE_COOLDOWN_S
                )

    def release(self, batcher: bool = False) -> None:
        """End a dispatch WITHOUT a latency sample — long-lived streams
        and feedback acks: their wall time isn't comparable to a unary
        EWMA, but while they run they must count as load or p2c keeps
        stacking unary traffic onto a stream-saturated replica.
        ``batcher=True`` when closing a dispatch that was begun as
        batcher-bound (the neutral-accounting unary path)."""
        self.inflight = max(0, self.inflight - 1)
        if batcher:
            self.batcher_inflight = max(0, self.batcher_inflight - 1)
        RECORDER.set_replica_inflight(self.set_name, self.name, self.inflight)

    def snapshot(self) -> dict:
        return {
            "endpoint": self.name,
            "uds_path": self.uds_path,
            "role": self.role,
            "free_kv_blocks": self.scraped_free_kv,
            "inflight": self.inflight,
            "scraped_inflight": self.scraped_inflight,
            "ewma_ms": round(self.ewma_ms, 3),
            "ewma_reseeds": self.ewma_reseeds,
            "picks": self.picks,
            "failures": self.failures,
            "consec_failures": self.consec_failures,
            "fail_degraded": time.monotonic() < self.fail_degraded_until,
            "breaker_open": self.breaker_open,
            "scrape_failed": self.scrape_failed,
            "boot_id": self.boot_id,
            "epoch_resets": self.epoch_resets,
            "lease_state": self.lease_state,
        }


@dataclass
class PickDecision:
    """Why a replica was chosen — stamped onto the request span and used
    for hindsight mispick accounting at completion."""

    replica: str
    candidates: List[str]
    scores: List[float]
    #: losing candidate's EWMA at decision time (0 = no sample / solo pick)
    loser_ewma_ms: float = 0.0


class ReplicaSet:
    """N engine endpoints for one predictor + the p2c pick over them."""

    def __init__(self, targets, rng: Optional[random.Random] = None,
                 stale_after_s: Optional[float] = None,
                 name: str = "default"):
        if not targets:
            raise ValueError("ReplicaSet needs at least one endpoint")
        #: replica-set identity (deployment/predictor at the gateway) —
        #: the `set` label on the seldon_tpu_replica_* families, so
        #: imbalance is judged WITHIN a set, never across sets
        self.name = name
        self.endpoints = [
            ReplicaEndpoint(t, i, set_name=name)
            for i, t in enumerate(targets)
        ]
        self._rng = rng or random.Random(0)
        if stale_after_s is None:
            stale_after_s = 3.0 * scrape_interval_s()
        self.stale_after_s = float(stale_after_s)
        self.mispicks = 0

    def __len__(self) -> int:
        return len(self.endpoints)

    # -- the balancer ----------------------------------------------------

    def pick(
        self, eligible=None, rows: Optional[int] = None
    ) -> Tuple[ReplicaEndpoint, Optional[PickDecision]]:
        """Power-of-two-choices; ``decision`` is None exactly on the paths
        that predate replica sets (kill switch / single endpoint), so the
        span stays byte-identical there.  ``eligible`` narrows the p2c
        pool to endpoints a caller can actually use (e.g. streams need a
        TCP/in-process lane) so the pick — and its metrics — land on the
        endpoint that serves; an empty filtered pool falls back to the
        full set and the caller handles the capability miss.  ``rows``
        makes the score's latency term shape-aware (autopilot cost-aware
        routing): each candidate is priced for THIS request's pad bucket
        instead of its shape-blind EWMA."""
        if not replicas_enabled() or len(self.endpoints) == 1:
            return self.endpoints[0], None
        pool = self.endpoints
        if eligible is not None:
            pool = [ep for ep in pool if eligible(ep)] or self.endpoints
        now = time.monotonic()
        if len(pool) == 1:
            chosen = pool[0]
            chosen.picks += 1
            RECORDER.record_replica_pick(self.name, chosen.name)
            return chosen, PickDecision(
                replica=chosen.name, candidates=[chosen.name],
                scores=[round(
                    chosen.score(now, self.stale_after_s, rows), 4
                )],
                loser_ewma_ms=0.0,
            )
        i, j = self._rng.sample(range(len(pool)), 2)
        a, b = pool[i], pool[j]
        sa, sb = (
            a.score(now, self.stale_after_s, rows),
            b.score(now, self.stale_after_s, rows),
        )
        chosen, loser = (a, b) if sa <= sb else (b, a)
        chosen.picks += 1
        RECORDER.record_replica_pick(self.name, chosen.name)
        if rows is not None and autopilot_enabled():
            # count only picks a shape model actually informed — a pick
            # that fell back to the shape-blind EWMA on both candidates
            # is not a predictive decision
            bucket = pad_bucket(rows)
            if a.shape_ms.get(bucket) or b.shape_ms.get(bucket):
                RECORDER.record_autopilot_decision("p2c")
        return chosen, PickDecision(
            replica=chosen.name,
            candidates=[a.name, b.name],
            scores=[round(sa, 4), round(sb, 4)],
            # a degraded loser doesn't judge the pick: beating a sick
            # replica's historical EWMA is not a prediction error, and
            # counting it would pin the mispick ratio at 1.0 exactly
            # while the balancer steers correctly.  Hindsight uses the
            # same shape-aware prediction the pick scored with
            loser_ewma_ms=(
                0.0 if loser.degraded(now, self.stale_after_s)
                else loser.predicted_ms(rows)
            ),
        )

    def complete(self, endpoint: ReplicaEndpoint,
                 decision: Optional[PickDecision],
                 latency_s: float, ok: bool = True,
                 rows: Optional[int] = None) -> None:
        """Close one dispatch: update the endpoint's score inputs and judge
        the pick in hindsight (mispick = a successful request that ran
        longer than the losing candidate's EWMA at decision time — the
        loser would LIKELY have been faster)."""
        endpoint.complete(latency_s, ok=ok, rows=rows)
        if (
            ok
            and decision is not None
            and decision.loser_ewma_ms > 0.0
            and latency_s * 1e3 > decision.loser_ewma_ms
        ):
            self.mispicks += 1
            RECORDER.record_replica_mispick()

    # -- store-lease liveness (gateway/federation.py feed) ---------------

    def apply_leases(self, leases) -> None:
        """Fold the shared store's engine-lease table (url -> (boot_id,
        expires)) into endpoint health.  Only engines that EVER
        heartbeated participate — an endpoint with no lease row keeps
        scrape-based health untouched (mixed fleets, tests, engines
        started without a store).  A lapsed or dropped lease marks the
        replica dead within one lease TTL, long before three scrapes
        fail; the lease's boot_id doubles as an early epoch signal."""
        if not leases and not any(
            ep.lease_state is not None for ep in self.endpoints
        ):
            return
        now = time.time()
        for ep in self.endpoints:
            if ep.base_url is None:
                continue
            row = leases.get(ep.base_url) or leases.get(ep.base_url + "/")
            prev = ep.lease_state
            if row is None:
                # an engine that once held a lease and now has NO row
                # deregistered (graceful drain) — dead until it returns
                if ep.lease_state is not None:
                    ep.lease_state = "dead"
                    if prev == "live":
                        _pm_note("lease", endpoint=ep.base_url,
                                 transition="live->dead", cause="dropped")
                continue
            boot_id, expires = row
            if float(expires) > now:
                ep.lease_state = "live"
                ep.observe_boot_id(boot_id)
                if prev == "dead":
                    _pm_note("lease", endpoint=ep.base_url,
                             transition="dead->live")
            else:
                ep.lease_state = "dead"
                if prev == "live":
                    _pm_note("lease", endpoint=ep.base_url,
                             transition="live->dead", cause="lapsed")

    # -- passive health (the /stats scrape) ------------------------------

    async def scrape_once(self, session) -> int:
        """One scrape pass over the URL-backed endpoints: engine-side
        inflight dispatches + breaker state out of ``GET /stats``.
        Returns how many endpoints answered.  Never raises — a dead
        replica marks itself degraded, it must not kill the scrape loop.
        Endpoints scrape CONCURRENTLY so a pass is bounded by the 1 s
        per-endpoint timeout, not by how many replicas are down — N-1
        dead replicas scraped serially would age the healthy one past
        the staleness window and falsely degrade it."""
        import asyncio

        import aiohttp

        async def one(ep) -> int:
            try:
                timeout = aiohttp.ClientTimeout(total=1.0)
                async with session.get(
                    ep.base_url + "/stats", timeout=timeout
                ) as r:
                    doc = await r.json(content_type=None)
                if not isinstance(doc, dict):
                    raise ValueError("stats body is not an object")
                # boot epoch FIRST: a restarted engine at the same URL
                # resets the dead process's learned state before this
                # scrape's fresh readings land on top
                ep.observe_boot_id(doc.get("boot_id"))
                batch = (doc.get("telemetry") or {}).get("batch") or {}
                # subtract OWN batcher-bound inflight: the engine's
                # figure includes unary work THIS gateway queued, which
                # the score already counts live — double-counting a stale
                # snapshot of our own burst makes picks herd away from a
                # replica for a whole scrape interval after the burst
                # drained.  Only the batcher-bound subset is subtracted:
                # streams and feedback acks raise ep.inflight but never
                # appear in inflight_dispatches, and subtracting them
                # would erase OTHER gateways' real load from the signal
                ep.scraped_inflight = max(
                    0,
                    int(batch.get("inflight_dispatches", 0) or 0)
                    - ep.batcher_inflight,
                )
                breakers = (
                    (doc.get("resilience") or {}).get("breakers") or {}
                )
                was_open = ep.breaker_open
                ep.breaker_open = any(
                    (br or {}).get("state") not in (None, "closed")
                    for br in breakers.values()
                )
                if ep.breaker_open and not was_open:
                    _pm_note("breaker", endpoint=ep.base_url,
                             transition="closed->open")
                # free-KV-block headroom + role off the genserver block
                # (disaggregated mesh: the decode-capacity signal and
                # the role the endpoint actually serves)
                gs = doc.get("genserver")
                if isinstance(gs, dict):
                    kvb = gs.get("kv_blocks") or {}
                    try:
                        ep.scraped_free_kv = max(
                            0, int(kvb.get("total", 0))
                            - int(kvb.get("used", 0)))
                    except (TypeError, ValueError):
                        ep.scraped_free_kv = None
                    role = gs.get("role")
                    if role in ("prefill", "decode", "unified"):
                        ep.role = role
                # health is settled HERE — the optional fleet-document
                # fetches below must not delay the freshness stamp (two
                # hung 1 s GETs per pass would age scrape_ts past the
                # staleness window and falsely degrade a replica whose
                # /stats answered fine)
                ep.scrape_ts = time.monotonic()
                ep.scrape_failed = False
                # fleet observability rides the SAME pass: retain the
                # /stats doc and pull /perf + /quality alongside it
                # (concurrently — the pass stays bounded by ONE extra
                # timeout, not two) so /fleet rollups and the outlier
                # gauges need no polling of their own.  Failure here
                # must not mark the replica degraded.
                if fleet_scrape_enabled():
                    docs = {"stats": doc, "perf": None, "quality": None,
                            "postmortems": None, "ts": ep.scrape_ts}

                    async def _doc(path):
                        async with session.get(
                                ep.base_url + path, timeout=timeout
                        ) as r:
                            return await r.json(content_type=None)

                    # return_exceptions: one surface erroring (quality
                    # observatory disabled, transient 500) must not
                    # throw away the OTHER doc that fetched fine
                    perf, quality, postmortems = await asyncio.gather(
                        _doc("/perf"), _doc("/quality"),
                        _doc("/postmortems"),
                        return_exceptions=True,
                    )
                    if isinstance(perf, asyncio.CancelledError) or \
                            isinstance(quality, asyncio.CancelledError) or \
                            isinstance(postmortems, asyncio.CancelledError):
                        raise asyncio.CancelledError
                    if not isinstance(perf, BaseException):
                        docs["perf"] = perf
                    if not isinstance(quality, BaseException):
                        docs["quality"] = quality
                    if not isinstance(postmortems, BaseException):
                        docs["postmortems"] = postmortems
                    ep.fleet_docs = docs
                return 1
            except asyncio.CancelledError:
                raise
            except Exception:
                # passive health: ANY scrape problem just marks the
                # replica degraded — an exception type we didn't predict
                # must not differ in effect from one we did
                ep.scrape_failed = True
                return 0

        # in-process / uds-only endpoints have no scrape surface
        targets = [ep for ep in self.endpoints if ep.base_url is not None]
        if not targets:
            return 0
        return sum(await asyncio.gather(*(one(ep) for ep in targets)))

    def snapshot(self) -> dict:
        inflight = [ep.inflight for ep in self.endpoints]
        mean = sum(inflight) / max(len(inflight), 1)
        return {
            "endpoints": [ep.snapshot() for ep in self.endpoints],
            "mispicks": self.mispicks,
            # max/mean of the gateway-side inflight — the imbalance the
            # bench arm and the SeldonTPUReplicaImbalance alert judge
            "inflight_max_over_mean": round(
                (max(inflight) / mean) if mean > 0 else 1.0, 3
            ),
        }


def scrape_interval_s() -> float:
    """``SELDON_TPU_GW_SCRAPE_S`` — how often the gateway refreshes each
    replica's /stats-derived health (default 2 s; stale = 3 intervals)."""
    try:
        return float(os.environ.get("SELDON_TPU_GW_SCRAPE_S", "") or 2.0)
    except ValueError:
        return 2.0
