"""Gateway federation — N apife replicas over one shared sqlite store.

The reference architecture runs the api-frontend as a Deployment behind a
Service: every replica serves ingress statelessly, and anything stateful
(OAuth tokens) lives in Redis.  Our gateway grew singleton duties the
reference never had — rollout controllers, scale-ahead, shadow budget
accounting — which must run EXACTLY ONCE across the fleet or two replicas
fight over the same traffic split.

This module is the election that picks the one replica allowed to run
them.  It is deliberately boring: a single row in the shared sqlite file
(``leases`` table, gateway/state.py) holds ``(holder, token, expires)``;
every replica ticks ``acquire_lease`` at ttl/3, the holder renews, the
rest observe.  When the coordinator dies or stalls past the TTL, the next
ticker takes over and the **fencing token** bumps — any write the
ex-coordinator issues afterwards carries the old token and is rejected
inside the store's own write transaction (``fenced_set_weights``), the
classic lock-service fence (cf. Chubby; HashiCorp's leader election over
a session-bound KV key).

Failure semantics by design:

* ingress never depends on the lease — every replica serves requests the
  whole time, only singleton DUTIES move;
* QoS token buckets stay per-replica (a shed decision is
  latency-critical; sharing them through sqlite would put a disk write
  on the admission path), but SLO burn and throttle/shed ACCOUNTING
  federates off-path: every tick publishes this replica's window counts
  into the shared ``burn_deltas`` table and folds every peer's last
  counts into the process-global fleet-truth view
  (utils/quality.py ``FLEET_BURN``) that the brownout ladder and
  rollout burn gates judge — so a 3-replica mesh reacts to the fleet's
  burn, not a 1/3 slice.  ``SELDON_TPU_FLEET_BURN=0`` kills just this
  layer (per-replica burn bit-for-bit);
* a store outage demotes the replica (it cannot prove tenure, so it must
  not act as coordinator) but keeps serving ingress; the fleet-burn view
  goes stale and consumers fall back to their local rings.

Kill switch: ``SELDON_TPU_FEDERATION=0`` (or an in-memory store, which
has no lease API) makes every replica its own coordinator — bit-for-bit
the pre-federation single-gateway behavior.
"""

from __future__ import annotations

import asyncio
import os
import secrets
import time
from typing import Callable, List, Optional, Tuple

from seldon_core_tpu.utils.telemetry import RECORDER

__all__ = [
    "GatewayFederation",
    "federation_enabled",
    "lease_ttl_s",
    "COORDINATOR_LEASE",
]

#: the singleton-duty lease's row name in the shared ``leases`` table
COORDINATOR_LEASE = "coordinator"


def federation_enabled() -> bool:
    """``SELDON_TPU_FEDERATION=0`` restores single-gateway behavior."""
    return os.environ.get("SELDON_TPU_FEDERATION", "1") != "0"


def lease_ttl_s() -> float:
    """Coordinator + engine lease TTL (``SELDON_TPU_LEASE_TTL_S``,
    default 3s) — the upper bound on coordinator-failover time and on
    how long a dead engine keeps attracting picks before the balancer
    declares it via the lease (scrape fail-degrade needs 3 consecutive
    failures; the lease usually loses the race only when scrapes are
    faster than heartbeats)."""
    try:
        return max(float(os.environ.get("SELDON_TPU_LEASE_TTL_S", "3")), 0.2)
    except ValueError:
        return 3.0


class GatewayFederation:
    """One gateway replica's view of the federation.

    ``tick()`` is the whole protocol: claim-or-renew the coordinator
    lease, heartbeat this replica into the peer directory, notice
    transitions.  Everything else is read-side sugar (``is_coordinator``
    gates singleton duties; ``set_weights`` routes a coordinator's
    traffic-split writes through the fenced path; ``peers`` feeds the
    /fleet federation).

    Degrades to a no-op "always coordinator" when federation is off or
    the store has no lease API (the in-memory store) — callers never
    branch on the mode themselves."""

    def __init__(self, store, replica_id: Optional[str] = None, *,
                 ttl_s: Optional[float] = None,
                 base_url: Optional[str] = None,
                 clock: Callable[[], float] = time.time):
        self.store = store
        self.replica_id = (
            replica_id
            or os.environ.get("SELDON_TPU_GW_REPLICA_ID")
            or f"gw-{secrets.token_hex(4)}"
        )
        self.ttl_s = float(ttl_s if ttl_s is not None else lease_ttl_s())
        self.base_url = base_url
        self.clock = clock
        self.enabled = (
            federation_enabled() and hasattr(store, "acquire_lease")
        )
        self._token: Optional[int] = None
        self._store_error: Optional[str] = None
        self._last_tick = 0.0
        self._transitions = 0
        #: the gateway's TenantGovernor (set by gateway_main / tests) —
        #: source of the throttle/shed half of the burn delta
        self.governor = None
        self._burn_publishes = 0
        self._burn_folds = 0
        self._burn_errors = 0

    # -- the protocol ------------------------------------------------------

    def tick(self) -> bool:
        """Claim or renew the coordinator lease + heartbeat the peer row;
        returns whether this replica is the coordinator NOW."""
        if not self.enabled:
            return True
        was = self._token is not None
        try:
            token = self.store.acquire_lease(
                COORDINATOR_LEASE, self.replica_id, self.ttl_s)
            if self.base_url:
                self.store.heartbeat_peer(
                    self.replica_id, self.base_url, self.ttl_s)
            self._store_error = None
        except Exception as e:  # noqa: BLE001 — a partitioned store must
            # demote (tenure can't be proven) without crashing the loop
            token = None
            self._store_error = f"{type(e).__name__}: {e}"
            RECORDER.record_lease_transition("store_error")
        self._last_tick = self.clock()
        if token is not None and not was:
            RECORDER.record_lease_transition("acquired")
            self._transitions += 1
        elif token is None and was:
            RECORDER.record_lease_transition("lost")
            self._transitions += 1
        self._token = token
        # fleet-truth burn rides the same cadence (ttl/3): publish this
        # replica's deltas, fold every peer's — EVERY replica folds (the
        # view feeds local brownout/rollout decisions, not a singleton
        # duty), so it does not gate on the coordinator lease
        self._burn_tick()
        return token is not None

    # -- fleet-truth burn (federated SLO/QoS accounting) -------------------

    #: how far back one window's published counts stay credible: a dead
    #: replica's last delta keeps counting until the window it measured
    #: has fully aged out — failover cannot amnesia away burned budget
    #: "admission" is a synthetic window: per-tenant token-bucket
    #: admission totals (requests/throttled/shed) riding the same
    #: burn_deltas lane so /fleet can show fleet-wide per-tenant
    #: admission rates without a second store table
    _WINDOW_SPANS = {"5m": 300.0, "1h": 3600.0, "admission": 300.0}

    def _burn_tick(self) -> None:
        """Publish this replica's SLO window counts + QoS throttle/shed
        totals into the shared ``burn_deltas`` table, then fold EVERY
        replica's last published counts into the process-global
        :data:`~seldon_core_tpu.utils.quality.FLEET_BURN` view.  Rides
        ``tick()`` — off every request path.  No SLO configured means no
        SLO burn rows (exactly the local tracker's contract), but
        per-tenant ADMISSION rows (synthetic window ``"admission"``:
        total=requests, throttled/shed from the token buckets) still
        publish whenever a governor is live — admission truth does not
        require an SLO.  Store errors are counted and the stale view
        degrades consumers to their per-replica rings (fail-closed
        toward pre-fleet behaviour)."""
        from seldon_core_tpu.utils.quality import (
            QUALITY,
            fleet_burn_enabled,
        )

        if (not fleet_burn_enabled()
                or not hasattr(self.store, "publish_burn")):
            return
        gov = self.governor
        tenants_qos = gov.burn_totals() if gov is not None else {}
        if not QUALITY.slo.configured and not tenants_qos:
            return
        try:
            throttled = sum(
                v["throttled"] for v in tenants_qos.values())
            shed = sum(v["shed"] for v in tenants_qos.values())
            rows = []
            if QUALITY.slo.configured:
                for window, c in QUALITY.slo.window_counts().items():
                    rows.append(
                        ("_global", window, c["total"], c["slow"],
                         c["errors"], throttled, shed))
                for tenant, wins in (
                        QUALITY.tenant_window_counts().items()):
                    qos = tenants_qos.get(tenant, {})
                    for window, c in wins.items():
                        rows.append(
                            (tenant, window, c["total"], c["slow"],
                             c["errors"], qos.get("throttled", 0),
                             qos.get("shed", 0)))
            for tenant, qos in tenants_qos.items():
                rows.append((tenant, "admission",
                             qos.get("requests", 0), 0, 0,
                             qos.get("throttled", 0),
                             qos.get("shed", 0)))
            self.store.publish_burn(self.replica_id, rows)
            self._burn_publishes += 1
            self._burn_fold()
        except Exception:  # noqa: BLE001 — a sick store already demoted
            # us above; burn degrades to the per-replica view via
            # staleness, never by crashing the tick loop
            self._burn_errors += 1

    def _burn_fold(self) -> None:
        """Sum every replica's fresh-enough counts per (scope, window)
        and publish the aggregate — the SAME burn math as the local ring
        (``SloTracker.burn_entry``) over summed counts, so fleet and
        local views cannot diverge in formula, only in scope."""
        from seldon_core_tpu.utils.quality import (
            FLEET_BURN,
            QUALITY,
            SloTracker,
        )

        now = time.time()
        agg: dict = {}
        admission: dict = {}
        replicas = set()
        for r in self.store.burn_rows():
            span = self._WINDOW_SPANS.get(r["window"], 300.0)
            if now - r["updated"] > span:
                continue
            replicas.add(r["replica_id"])
            if r["window"] == "admission":
                # synthetic window: cumulative admission counts, no
                # burn-rate math — total carries the request counter
                adm = admission.setdefault(
                    r["scope"], {"requests": 0, "throttled": 0,
                                 "shed": 0})
                adm["requests"] += r["total"]
                adm["throttled"] += r["throttled"]
                adm["shed"] += r["shed"]
                continue
            a = agg.setdefault(
                (r["scope"], r["window"]), [0, 0, 0, 0, 0])
            a[0] += r["total"]
            a[1] += r["slow"]
            a[2] += r["errors"]
            a[3] += r["throttled"]
            a[4] += r["shed"]
        p99_ms = QUALITY.slo.p99_ms
        error_rate = QUALITY.slo.error_rate
        windows: dict = {}
        tenants: dict = {}
        for (scope, window), a in sorted(agg.items()):
            entry = SloTracker.burn_entry(
                a[0], a[1], a[2], p99_ms, error_rate)
            entry["throttled"] = a[3]
            entry["shed"] = a[4]
            if scope == "_global":
                windows[window] = entry
            else:
                tenants.setdefault(scope, {})[window] = entry
        for scope, adm in sorted(admission.items()):
            tenants.setdefault(scope, {})["admission"] = adm
        FLEET_BURN.publish({
            "replicas": sorted(replicas),
            "windows": windows,
            "tenants": tenants,
            "folded_at": round(now, 3),
            "folded_by": self.replica_id,
        })
        self._burn_folds += 1
        for window, entry in windows.items():
            RECORDER.set_fleet_burn(window, entry["burn_rate"])

    def resign(self) -> None:
        """Graceful shutdown: hand the lease over NOW instead of making
        the fleet wait out the TTL, and leave the peer directory."""
        if not self.enabled:
            return
        try:
            if self._token is not None:
                self.store.release_lease(
                    COORDINATOR_LEASE, self.replica_id, self._token)
                RECORDER.record_lease_transition("released")
                self._transitions += 1
            self.store.drop_peer(self.replica_id)
        except Exception:  # noqa: BLE001 — best effort on the way out
            pass
        self._token = None

    async def run(self, stop: Optional[asyncio.Event] = None) -> None:
        """Tick at ttl/3 (two missable heartbeats before the lease
        lapses) until ``stop`` is set."""
        interval = max(self.ttl_s / 3.0, 0.05)
        while stop is None or not stop.is_set():
            self.tick()
            if stop is None:
                await asyncio.sleep(interval)
            else:
                try:
                    await asyncio.wait_for(stop.wait(), interval)
                except asyncio.TimeoutError:
                    pass

    # -- read side ---------------------------------------------------------

    @property
    def is_coordinator(self) -> bool:
        return True if not self.enabled else self._token is not None

    @property
    def fencing_token(self) -> Optional[int]:
        return self._token

    def set_weights(self, deployment_id: str, weights) -> None:
        """The rollout controller's traffic-split lever, fenced: when
        federation is live the write proves tenure inside the store's
        own transaction; otherwise it is the plain store write."""
        if self.enabled and self._token is not None:
            self.store.fenced_set_weights(
                deployment_id, weights,
                lease=COORDINATOR_LEASE,
                holder=self.replica_id, token=self._token)
        else:
            self.store.set_weights(deployment_id, weights)

    def peers(self) -> List[Tuple[str, str]]:
        """Live sibling replicas as (replica_id, base_url) — the /fleet
        federation's fan-out list (this replica excluded)."""
        if not self.enabled:
            return []
        try:
            return list(self.store.peers(exclude=self.replica_id))
        except Exception:  # noqa: BLE001
            return []

    def engine_leases(self):
        """All engine leases (url -> (boot_id, expires)), {} when the
        store has none or is unreachable — the balancer's liveness feed."""
        if not self.enabled or not hasattr(self.store, "engine_leases"):
            return {}
        try:
            return dict(self.store.engine_leases())
        except Exception:  # noqa: BLE001
            return {}

    def snapshot(self) -> dict:
        """The /stats ``federation`` block."""
        doc = {
            "enabled": self.enabled,
            "replica_id": self.replica_id,
            "coordinator": self.is_coordinator,
            "lease_ttl_s": self.ttl_s,
            "transitions": self._transitions,
        }
        if self.enabled:
            doc["fencing_token"] = self._token
            doc["fleet_burn"] = {
                "publishes": self._burn_publishes,
                "folds": self._burn_folds,
                "errors": self._burn_errors,
            }
            doc["peers"] = [
                {"replica_id": rid, "url": url} for rid, url in self.peers()
            ]
            if self._store_error:
                doc["store_error"] = self._store_error
            try:
                lease = self.store.lease(COORDINATOR_LEASE)
            except Exception:  # noqa: BLE001
                lease = None
            if lease is not None:
                doc["lease"] = {
                    "holder": lease["holder"],
                    "token": lease["token"],
                    "expires_in_s": round(lease["expires"] - time.time(), 3),
                }
        return doc
