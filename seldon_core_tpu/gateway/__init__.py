"""Ingress gateway: auth, deployment routing, canary traffic split,
request/response firehose."""

from seldon_core_tpu.gateway.apife import ApiGateway, DeploymentStore  # noqa: F401
from seldon_core_tpu.gateway.firehose import Firehose  # noqa: F401
