"""API gateway — the reference's ``api-frontend`` ("apife") re-designed.

Responsibilities mirrored from §2.3 of the survey:
  * OAuth2-style client-credentials auth: each deployment registers an
    (oauth_key, oauth_secret) pair (seldon_deployment.proto:39-40); POST
    /oauth/token with HTTP basic auth issues a bearer token; the principal
    (client id == deployment) selects the target graph
    (api-frontend RestClientController.java:126-177,
    InMemoryClientDetailsService.java).  Tokens live in an in-memory store
    with expiry (the reference used Redis).
  * Prediction routing: principal -> deployment -> predictor.  With several
    predictors the gateway splits traffic by replica weight — the TPU-native
    form of the reference's canary pattern (2 predictors, replica-weighted
    k8s service routing, docs/crd/readme.md).
  * Request/response firehose publish, fire-and-forget (gateway/firehose.py).
  * Ingress metrics (seldon_api_ingress_server_requests_*).

Targets are in-process ``EngineService``s (the common case: gateway and
engines share the host) or remote engine base URLs.
"""

from __future__ import annotations

import asyncio
import base64
import secrets
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple, Union

import numpy as np

from seldon_core_tpu.gateway.firehose import Firehose
from seldon_core_tpu.graph.spec import SeldonDeploymentSpec
from seldon_core_tpu.messages import Feedback, SeldonMessage, SeldonMessageError
# importing the spine at module load wires the global TRACER's ring sink
# BEFORE the gateway serves its first request — a gateway-only process
# must not flip span routing mid-serving when someone first polls
# /overhead (the ingress hop's request spans are its fused records)
from seldon_core_tpu.utils.hotrecord import SPINE
from seldon_core_tpu.runtime.resilience import (
    DEADLINE_HEADER,
    deadline_header_value,
    deadline_ms_header,
    maybe_deadline_scope,
    remaining_s,
)
from seldon_core_tpu.utils.metrics import MetricsRegistry

__all__ = ["ApiGateway", "DeploymentStore", "AuthError"]

TOKEN_TTL_S = 3600.0


class AuthError(Exception):
    pass


@dataclass
class _Registration:
    deployment_id: str
    oauth_key: str
    oauth_secret: str
    engines: List  # [(predictor_name, weight, EngineService | base_url)]


class DeploymentStore:
    """client-id -> deployment registry + token store — the reference's
    DeploymentStore + InMemoryClientDetailsService + Redis token store
    (api-frontend deployments/DeploymentStore.java:33-80)."""

    def __init__(self):
        self._by_key: Dict[str, _Registration] = {}
        self._tokens: Dict[str, Tuple[str, float]] = {}  # token -> (key, expiry)

    def register(
        self,
        spec: SeldonDeploymentSpec,
        engines: Dict[str, object],
    ) -> None:
        """``engines``: predictor name -> EngineService (or URL)."""
        weighted = []
        for p in spec.predictors:
            if p.name in engines:
                weighted.append((p.name, max(int(p.replicas), 0), engines[p.name]))
        if not weighted:
            raise ValueError(f"no engines supplied for deployment {spec.name!r}")
        key = spec.oauth_key or spec.name
        self._by_key[key] = _Registration(
            deployment_id=spec.name,
            oauth_key=key,
            oauth_secret=spec.oauth_secret,
            engines=weighted,
        )

    def unregister(self, oauth_key: str) -> None:
        self._by_key.pop(oauth_key, None)
        self._tokens = {
            t: (k, exp) for t, (k, exp) in self._tokens.items() if k != oauth_key
        }

    # -- auth ---------------------------------------------------------------

    def issue_token(self, oauth_key: str, oauth_secret: str) -> str:
        reg = self._by_key.get(oauth_key)
        if reg is None or (reg.oauth_secret and reg.oauth_secret != oauth_secret):
            raise AuthError("invalid client credentials")
        if len(self._tokens) > 4096:
            # clients that fetch a fresh token per session would otherwise
            # grow the store without bound (expiry eviction is lazy)
            now = time.time()
            self._tokens = {
                t: (k, exp) for t, (k, exp) in self._tokens.items() if exp > now
            }
        token = secrets.token_urlsafe(24)
        self._tokens[token] = (oauth_key, time.time() + TOKEN_TTL_S)
        return token

    def principal_for_token(self, token: str) -> _Registration:
        entry = self._tokens.get(token)
        if entry is None:
            raise AuthError("invalid token")
        key, expiry = entry
        if time.time() > expiry:
            self._tokens.pop(token, None)
            raise AuthError("token expired")
        reg = self._by_key.get(key)
        if reg is None:
            raise AuthError("client no longer registered")
        return reg

    def deployments(self) -> List[str]:
        return [r.deployment_id for r in self._by_key.values()]

    def active_token_count(self) -> int:
        """Unexpired issued tokens (expiry eviction is lazy, so this
        counts live entries, not strictly valid ones)."""
        return len(self._tokens)


class ApiGateway:
    def __init__(
        self,
        store: Optional[DeploymentStore] = None,
        firehose: Optional[Firehose] = None,
        require_auth: bool = True,
        seed: int = 0,
    ):
        self.store = store or DeploymentStore()
        self.firehose = firehose
        self.require_auth = require_auth
        self.metrics = MetricsRegistry(deployment_name="gateway")
        self._rng = np.random.default_rng(seed)
        self._session = None  # lazy shared aiohttp session (remote engines)
        # feedback ingress accounting: engines may live in other
        # processes, so the gateway keeps its own view of the reward
        # stream it routed (surfaced in /stats; the process-global
        # seldon_tpu_feedback_* families are fed engine-side where
        # truth-vs-prediction agreement is computed)
        self.feedback_count = 0
        self.feedback_reward_sum = 0.0
        self.feedback_truth_count = 0

    # -- principal resolution ----------------------------------------------

    def _resolve(self, token: Optional[str]) -> _Registration:
        if token:
            return self.store.principal_for_token(token)
        if self.require_auth:
            raise AuthError("missing bearer token")
        regs = list(self.store._by_key.values())
        if len(regs) != 1:
            raise AuthError("auth disabled but no unique deployment registered")
        return regs[0]

    def _pick_engine(self, reg: _Registration, predictor: Optional[str] = None):
        """Replica-weighted predictor choice (canary traffic split)."""
        if predictor is not None:
            for name, _, engine in reg.engines:
                if name == predictor:
                    return name, engine
        names = [e[0] for e in reg.engines]
        weights = np.asarray([e[1] for e in reg.engines], dtype=np.float64)
        if weights.sum() <= 0:
            weights = np.ones_like(weights)
        idx = int(self._rng.choice(len(names), p=weights / weights.sum()))
        return reg.engines[idx][0], reg.engines[idx][2]

    # -- data plane ---------------------------------------------------------

    async def predict(
        self, msg: SeldonMessage, token: Optional[str] = None
    ) -> SeldonMessage:
        from seldon_core_tpu.utils.tracing import TRACER

        reg = self._resolve(token)
        with self.metrics.time_ingress("predictions", "POST") as code:
            predictor_name, engine = self._pick_engine(reg)
            # the ingress span roots the request tree (or joins the
            # caller's trace when it sent a traceparent); the engine hop —
            # in-process or HTTP — becomes its child
            with TRACER.span(
                msg.meta.puid, "gateway", kind="request", method="predict",
                deployment=reg.deployment_id, predictor=predictor_name,
            ):
                resp = await self._dispatch_predict(engine, msg)
            # record which predictor served (canary observability; feedback
            # routes back to the same predictor)
            resp.meta.requestPath.setdefault("predictor", predictor_name)
            if resp.status is not None and resp.status.status == "FAILURE":
                code["code"] = str(resp.status.code or 500)
        if self.firehose is not None:
            self.firehose.publish(reg.deployment_id, msg, resp)
        return resp

    async def send_feedback(
        self, feedback: Feedback, token: Optional[str] = None
    ) -> SeldonMessage:
        from seldon_core_tpu.utils.tracing import TRACER

        reg = self._resolve(token)
        with self.metrics.time_ingress("feedback", "POST"):
            predictor = None
            if feedback.response is not None:
                predictor = feedback.response.meta.requestPath.get("predictor")
            fb_puid = feedback.puid()
            _, engine = self._pick_engine(reg, predictor)
            self.feedback_count += 1
            self.feedback_reward_sum += float(feedback.reward)
            if feedback.truth is not None:
                self.feedback_truth_count += 1
            with TRACER.span(
                fb_puid, "gateway", kind="request", method="feedback",
                deployment=reg.deployment_id,
            ):
                return await self._dispatch_feedback(engine, feedback)

    async def _dispatch_predict(self, engine, msg: SeldonMessage) -> SeldonMessage:
        if hasattr(engine, "predict"):  # in-process EngineService
            return await engine.predict(msg)
        return await self._http_post(str(engine) + "/api/v0.1/predictions", msg.to_json())

    async def _dispatch_feedback(self, engine, fb: Feedback) -> SeldonMessage:
        if hasattr(engine, "send_feedback"):
            return await engine.send_feedback(fb)
        return await self._http_post(str(engine) + "/api/v0.1/feedback", fb.to_json())

    def _get_session(self):
        """Shared pooled session; timeouts are PER REQUEST (a session-level
        total would make unary calls and long-lived SSE proxies poison each
        other's deadline)."""
        import aiohttp

        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        return self._session

    async def _http_post(self, url: str, payload: str) -> SeldonMessage:
        import aiohttp

        # one pooled session per gateway + 3 retries, mirroring apife's
        # pooling client with HttpRetryHandler (InternalPredictionService.
        # java:60-72, HttpRetryHandler.java:34-45).  Retries fire only on
        # connection-establishment failures — once bytes may have reached the
        # engine, re-POSTing could double-apply feedback training
        session = self._get_session()
        last = "unreachable"
        for _ in range(3):
            # deadline propagation (runtime/resilience.py): recomputed per
            # attempt — the caller's REMAINING budget clamps this hop's
            # timeout and rides to the engine as milliseconds, so a
            # deadline set AT the gateway is honored end-to-end instead of
            # resetting per hop (or per connect-retry)
            total = 20.0
            headers = {}
            rem = remaining_s()
            if rem is not None:
                if rem <= 0:
                    return SeldonMessage.failure(
                        "request deadline exhausted at gateway", code=504
                    )
                total = min(total, rem)
                headers[DEADLINE_HEADER] = deadline_header_value()
            # trace context rides to the remote engine alongside the
            # deadline, so its spans join the gateway's tree
            from seldon_core_tpu.utils.tracing import (
                TRACEPARENT_HEADER,
                traceparent_header_value,
            )

            tp = traceparent_header_value()
            if tp is not None:
                headers[TRACEPARENT_HEADER] = tp
            headers = headers or None
            timeout = aiohttp.ClientTimeout(total=total)
            try:
                async with session.post(
                    url, data=payload, timeout=timeout, headers=headers
                ) as r:
                    return SeldonMessage.from_json(await r.text())
            except aiohttp.ClientConnectorError as e:
                last = str(e)
                await asyncio.sleep(0.05)
            except (aiohttp.ClientError, asyncio.TimeoutError) as e:
                return SeldonMessage.failure(f"engine error: {e}", code=503)
        return SeldonMessage.failure(f"engine unreachable: {last}", code=503)

    def stats(self) -> dict:
        """Zero-dependency JSON snapshot for ``GET /stats`` — ingress
        latency percentiles, routing table, firehose backpressure, and the
        process-level flight-recorder telemetry (engines sharing this
        process report their batcher/generation internals here too)."""
        from seldon_core_tpu.utils.telemetry import RECORDER

        return {
            "gateway": {
                "require_auth": self.require_auth,
                "deployments": self.store.deployments(),
                "active_tokens": self.store.active_token_count(),
            },
            "feedback": {
                "count": self.feedback_count,
                "mean_reward": round(
                    self.feedback_reward_sum / self.feedback_count, 6
                ) if self.feedback_count else 0.0,
                "truth_provided": self.feedback_truth_count,
            },
            "firehose": (
                None if self.firehose is None else self.firehose.snapshot()
            ),
            "telemetry": RECORDER.snapshot(),
        }

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()


# ---------------------------------------------------------------------------
# HTTP app
# ---------------------------------------------------------------------------


def make_gateway_app(gateway: ApiGateway):
    """aiohttp app: /oauth/token, /api/v0.1/predictions, /api/v0.1/feedback,
    /ping, /prometheus, /stats — the apife REST surface."""
    from aiohttp import web

    from seldon_core_tpu.runtime.rest import _error_response, _msg_response, _payload_text
    from seldon_core_tpu.utils.metrics import CONTENT_TYPE_LATEST

    app = web.Application(client_max_size=256 * 1024 * 1024)

    def _bearer(request) -> Optional[str]:
        auth = request.headers.get("Authorization", "")
        if auth.startswith("Bearer "):
            return auth[len("Bearer ") :]
        return None

    async def token(request):
        auth = request.headers.get("Authorization", "")
        key = secret = None
        if auth.startswith("Basic "):
            try:
                decoded = base64.b64decode(auth[len("Basic ") :]).decode()
                key, _, secret = decoded.partition(":")
            except Exception:
                pass
        if key is None:
            form = await request.post()
            key = form.get("client_id")
            secret = form.get("client_secret", "")
        try:
            tok = gateway.store.issue_token(key or "", secret or "")
        except AuthError as e:
            return web.json_response({"error": str(e)}, status=401)
        return web.json_response(
            {"access_token": tok, "token_type": "bearer", "expires_in": int(TOKEN_TTL_S)}
        )

    async def predictions(request):
        try:
            msg = SeldonMessage.from_json(await _payload_text(request))
        except SeldonMessageError as e:
            return _error_response(str(e))
        from seldon_core_tpu.utils.tracing import (
            TRACEPARENT_HEADER,
            parse_traceparent,
            trace_scope,
        )

        try:
            # deadline set at the gateway governs the whole request tree;
            # an incoming traceparent makes the gateway span the caller's
            # child instead of a fresh root
            with trace_scope(
                parse_traceparent(request.headers.get(TRACEPARENT_HEADER))
            ), maybe_deadline_scope(
                deadline_ms_header(request.headers.get(DEADLINE_HEADER))
            ):
                resp = await gateway.predict(msg, _bearer(request))
        except AuthError as e:
            return _error_response(str(e), code=401)
        status = 200 if resp.status is None or resp.status.status == "SUCCESS" else (
            resp.status.code or 500
        )
        return _msg_response(resp, status=status)

    async def feedback(request):
        from seldon_core_tpu.utils.tracing import (
            TRACEPARENT_HEADER,
            parse_traceparent,
            trace_scope,
        )

        try:
            fb = Feedback.from_json(await _payload_text(request))
        except SeldonMessageError as e:
            return _error_response(str(e))
        try:
            # same adoption contract as the predictions route: a caller's
            # traceparent makes the feedback spans join its trace
            with trace_scope(
                parse_traceparent(request.headers.get(TRACEPARENT_HEADER))
            ), maybe_deadline_scope(
                deadline_ms_header(request.headers.get(DEADLINE_HEADER))
            ):
                ack = await gateway.send_feedback(fb, _bearer(request))
        except AuthError as e:
            return _error_response(str(e), code=401)
        return _msg_response(ack)

    async def generate_stream(request):
        """SSE token streaming through the ingress: auth + canary pick,
        then relay the engine's event stream (in-process engines stream
        directly; remote engines are proxied chunk-for-chunk)."""
        try:
            payload = await _payload_text(request)
            reg = gateway._resolve(_bearer(request))
        except AuthError as e:
            return _error_response(str(e), code=401)
        except SeldonMessageError as e:
            return _error_response(str(e))
        _, engine = gateway._pick_engine(reg)
        resp = web.StreamResponse(
            status=200,
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache"},
        )
        import json as _json

        if hasattr(engine, "generate_stream"):  # in-process EngineService
            try:
                text, chunk = engine.prepare_stream_request(payload)
            except SeldonMessageError as e:
                return _error_response(str(e))
            await resp.prepare(request)
            agen = engine.generate_stream(text, chunk=chunk)
            try:
                async for event in agen:
                    await resp.write(b"data: " + event.encode() + b"\n\n")
            except Exception as e:  # mid-stream: in-band terminal event,
                # same SSE failure contract as the engine lane (rest.py)
                await resp.write(
                    b'data: {"done": true, "error": %s}\n\n'
                    % _json.dumps(str(e)).encode()
                )
            finally:
                await agen.aclose()
            await resp.write_eof()
            return resp
        # remote engine: stream the upstream SSE bytes through unchanged
        import aiohttp

        try:
            async with gateway._get_session().post(
                str(engine) + "/api/v0.1/generate/stream", data=payload,
                timeout=aiohttp.ClientTimeout(total=None, sock_connect=20),
            ) as upstream:
                if upstream.status != 200:
                    return _error_response(
                        await upstream.text(), code=upstream.status
                    )
                await resp.prepare(request)
                async for chunk_bytes in upstream.content.iter_any():
                    await resp.write(chunk_bytes)
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            if not resp.prepared:
                return _error_response(f"engine unreachable: {e}", code=503)
            # upstream broke mid-stream: emit a terminal error event — the
            # SSE contract's in-band failure channel (headers already sent)
            await resp.write(
                b'data: {"done": true, "error": %s}\n\n'
                % _json.dumps(str(e)).encode()
            )
        await resp.write_eof()
        return resp

    async def ping(_):
        return web.Response(text="pong")

    async def ready(_):
        # readiness = a registered routing table (an empty gateway serves
        # nothing useful; the bundle's probe gates the Service on this) —
        # regardless of auth mode: an open gateway with no deployments can
        # still only 404.  No startup flap: gateway_main registers spec_dir
        # files BEFORE binding this server, so a red probe means the spec
        # source is genuinely empty, and readiness (not liveness) failing
        # just keeps the Service from routing here — the intended gate
        if gateway.store.deployments():
            return web.Response(text="ready")
        return web.Response(text="no deployments registered", status=503)

    async def prometheus(_):
        return web.Response(
            body=gateway.metrics.exposition(),
            headers={"Content-Type": CONTENT_TYPE_LATEST},
        )

    async def stats(_):
        return web.json_response(gateway.stats())

    async def overhead(_):
        # the ingress hop writes fused telemetry records too (its request
        # spans route through the per-thread ring): the gateway's
        # /overhead page reports this process's framework-time budget
        return web.json_response({
            "gateway": {"deployments": gateway.store.deployments()},
            **SPINE.overhead_document(),
        })

    app.router.add_post("/oauth/token", token)
    app.router.add_post("/api/v0.1/predictions", predictions)
    app.router.add_post("/api/v0.1/feedback", feedback)
    app.router.add_post("/api/v0.1/generate/stream", generate_stream)
    app.router.add_get("/ping", ping)
    app.router.add_get("/ready", ready)
    app.router.add_get("/prometheus", prometheus)
    app.router.add_get("/stats", stats)
    app.router.add_get("/overhead", overhead)

    async def _cleanup(_app):
        await gateway.close()  # pooled upstream session/connector

    app.on_cleanup.append(_cleanup)
    return app
