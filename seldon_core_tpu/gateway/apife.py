"""API gateway — the reference's ``api-frontend`` ("apife") re-designed.

Responsibilities mirrored from §2.3 of the survey:
  * OAuth2-style client-credentials auth: each deployment registers an
    (oauth_key, oauth_secret) pair (seldon_deployment.proto:39-40); POST
    /oauth/token with HTTP basic auth issues a bearer token; the principal
    (client id == deployment) selects the target graph
    (api-frontend RestClientController.java:126-177,
    InMemoryClientDetailsService.java).  Tokens live in an in-memory store
    with expiry (the reference used Redis).
  * Prediction routing: principal -> deployment -> predictor.  With several
    predictors the gateway splits traffic by replica weight — the TPU-native
    form of the reference's canary pattern (2 predictors, replica-weighted
    k8s service routing, docs/crd/readme.md).
  * Engine replica sets: each predictor may register N engine endpoints;
    within the weight-chosen predictor the gateway balances by
    power-of-two-choices over live inflight/EWMA-latency scores with
    passive /stats-scrape health (gateway/balancer.py).  The chosen
    replica and both candidates' scores ride the request span so every
    routing decision is auditable.
  * Request/response firehose publish, fire-and-forget (gateway/firehose.py).
  * Ingress metrics (seldon_api_ingress_server_requests_*).

Targets are in-process ``EngineService``s (the common case: gateway and
engines share the host), remote engine base URLs, ``uds:`` socket paths
(the runtime/udsrelay.py zero-copy lane for co-located engines), or lists
of any of those (a replica set).
"""

from __future__ import annotations

import asyncio
import base64
import random
import secrets
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple, Union

import numpy as np

from seldon_core_tpu.gateway.balancer import (
    PickDecision,
    ReplicaEndpoint,
    ReplicaSet,
    parse_endpoint_spec,
    replicas_enabled,
    scrape_interval_s,
    uds_enabled,
)

from seldon_core_tpu.gateway.firehose import Firehose
from seldon_core_tpu.gateway.shadow import (
    ShadowConfig,
    ShadowMirror,
    shadow_config_from_spec,
)
from seldon_core_tpu.graph.spec import SeldonDeploymentSpec
from seldon_core_tpu.messages import Feedback, SeldonMessage, SeldonMessageError
from seldon_core_tpu.runtime.brownout import BROWNOUT, BROWNOUT_INFO_PREFIX
from seldon_core_tpu.runtime.qos import (
    THROTTLE_INFO_PREFIX,
    TenantGovernor,
    current_tenant,
    current_tier,
    qos_scope,
    resolve_tenant,
    tenancy_enabled,
)
from seldon_core_tpu.runtime.udsrelay import OP_FEEDBACK, OP_PREDICT, OP_WIRE
from seldon_core_tpu.utils.telemetry import RECORDER, Reservoir
# importing the spine at module load wires the global TRACER's ring sink
# BEFORE the gateway serves its first request — a gateway-only process
# must not flip span routing mid-serving when someone first polls
# /overhead (the ingress hop's request spans are its fused records)
from seldon_core_tpu.utils.hotrecord import SPINE
from seldon_core_tpu.runtime.resilience import (
    DEADLINE_HEADER,
    IDEMPOTENT_METHODS,
    RetryBudget,
    deadline_header_value,
    deadline_ms_header,
    maybe_deadline_scope,
    remaining_s,
)
from seldon_core_tpu.utils.metrics import MetricsRegistry

__all__ = ["ApiGateway", "DeploymentStore", "AuthError"]

TOKEN_TTL_S = 3600.0


class AuthError(Exception):
    pass


def _not_decode(ep) -> bool:
    """Client traffic never lands on a decode-only replica — decode
    replicas serve KV handoffs from prefill peers, nothing else
    (runtime/servingmesh.py phase routing)."""
    return getattr(ep, "role", "unified") != "decode"


def _release_brownout_sink(sink) -> None:
    """Detach a gateway's firehose event sink from the global brownout
    controller — only if it is still the installed one (a later gateway
    may have taken over already)."""
    if sink is not None and BROWNOUT.event_sink is sink:
        BROWNOUT.event_sink = None


@dataclass
class _Registration:
    deployment_id: str
    oauth_key: str
    oauth_secret: str
    #: [(predictor_name, weight, engine)] where engine is an
    #: EngineService, an endpoint spec string (base URL / ``uds:`` path /
    #: ``url+uds:path``), or a LIST of those — a replica set
    engines: List
    #: mirror policy when one predictor is annotated seldon.io/shadow
    #: (gateway/shadow.py) — that predictor serves weight-0 live traffic
    #: and receives the sampled fire-and-forget copies instead
    shadow: Optional[ShadowConfig] = None


class _WireCoalescer:
    """Co-arriving binary predicts for ONE engine socket ride a single
    multi-tensor relay frame (runtime/wire.py MULTI): the first arrival
    opens a ``SELDON_TPU_WIRE_COALESCE_US`` window; everything that
    lands inside it (capped at ``SELDON_TPU_WIRE_COALESCE_MAX``) is
    packed into one ``OP_WIRE`` hop and de-coalesced positionally from
    the response's sub-frames — per-request hop cost amortizes exactly
    where the engine's MicroBatcher would have re-batched the rows
    anyway.  A window of 0 sends every frame solo.  Sub-request failures
    are per-slot typed frames; a transport failure fails the whole batch
    with the error every caller would have seen solo."""

    def __init__(self, client, window_s: float, max_n: int):
        self.client = client
        self.window_s = window_s
        self.max_n = max_n
        self._pending: list = []  # [(frame_bytes, future)]
        self._flush_task: Optional[asyncio.Task] = None
        # STRONG refs to in-flight flush/send tasks: the event loop only
        # holds tasks weakly, and a task whose last reference is dropped
        # mid-await is garbage-collected mid-flight (GeneratorExit) —
        # every waiter would then hang to its deadline
        self._tasks: set = set()

    def _track(self, task: asyncio.Task) -> asyncio.Task:
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        task.add_done_callback(
            lambda t: None if t.cancelled() else t.exception())
        return task

    async def call(self, frame: bytes) -> "tuple[bytes, int]":
        if self.window_s <= 0:
            return await self.client.call(OP_WIRE, frame)
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._pending.append((frame, fut))
        if len(self._pending) >= self.max_n:
            batch = self._take()
            self._track(loop.create_task(self._send(batch)))
        elif self._flush_task is None:
            self._flush_task = self._track(
                loop.create_task(self._delayed_flush()))
        return await fut

    def _take(self) -> list:
        batch, self._pending = self._pending, []
        if self._flush_task is not None:
            self._flush_task.cancel()
            self._flush_task = None
        return batch

    async def _delayed_flush(self) -> None:
        try:
            await asyncio.sleep(self.window_s)
        except asyncio.CancelledError:
            raise
        self._flush_task = None
        batch, self._pending = self._pending, []
        if batch:
            await self._send(batch)

    async def _send(self, batch: list) -> None:
        from seldon_core_tpu.runtime import wire as wirelib

        try:
            if len(batch) == 1:
                body, status = await self.client.call(OP_WIRE, batch[0][0])
                self._resolve(batch[0][1], (body, status))
                return
            RECORDER.record_wire_coalesced(len(batch))
            multi = wirelib.join_parts(
                wirelib.encode_multi([f for f, _fut in batch]))
            body, status = await self.client.call(OP_WIRE, multi)
            if status == 415:
                # peer doesn't speak OP_WIRE: hand every caller the 415
                # so each negotiates down to its JSON fallback
                for _f, fut in batch:
                    self._resolve(fut, (body, status))
                return
            try:
                frame = wirelib.decode_frame(body)
            except wirelib.WireError:
                # a NON-FRAME answer (e.g. a pre-wire relay's JSON
                # 'unknown relay op' 400): hand every caller the raw
                # body+status so each runs the solo path's JSON-parse /
                # negotiate-down logic — raising here would 502 the
                # whole batch and never trigger the fallback
                for _f, fut in batch:
                    self._resolve(fut, (body, status))
                return
            if not frame.is_multi or len(frame.subframes) != len(batch):
                # a frame, but not our batch — every caller gets the
                # typed 502 it would have gotten solo
                raise wirelib.WireError(
                    "coalesced response is not a %d-frame multi"
                    % len(batch)
                )
            for (_f, fut), sub in zip(batch, frame.subframes):
                # materialize each slot out of the shared buffer: the
                # response bytearray is one wire read, the slot copy is
                # what lets callers outlive it
                self._resolve(fut, (bytes(sub), status))
        except asyncio.CancelledError:
            # gateway shutdown cancelled the flush mid-call: every
            # waiter must fail FAST, not sit out its 20 s deadline
            self._fail_batch(batch, ConnectionError(
                "wire coalescer cancelled (gateway shutting down)"))
            raise
        except Exception as e:  # noqa: BLE001 - fan the failure out typed
            self._fail_batch(batch, e)
            # the exceptions ARE consumed (every caller awaits its
            # future) — but a caller that timed out already has a
            # cancelled future, and its slot's exception dies here
            return

    def shutdown(self) -> None:
        """Cancel in-flight flush/send tasks and fail everything still
        pending — callers get an immediate typed 503, not a 20 s hang."""
        for t in list(self._tasks):
            t.cancel()
        batch, self._pending = self._pending, []
        self._flush_task = None
        self._fail_batch(batch, ConnectionError(
            "wire coalescer closed (gateway shutting down)"))

    @staticmethod
    def _fail_batch(batch: list, exc: Exception) -> None:
        for _f, fut in batch:
            if not fut.done():
                fut.set_exception(exc)

    @staticmethod
    def _resolve(fut, result) -> None:
        if not fut.done():
            fut.set_result(result)


class DeploymentStore:
    """client-id -> deployment registry + token store — the reference's
    DeploymentStore + InMemoryClientDetailsService + Redis token store
    (api-frontend deployments/DeploymentStore.java:33-80)."""

    def __init__(self):
        self._by_key: Dict[str, _Registration] = {}
        self._tokens: Dict[str, Tuple[str, float]] = {}  # token -> (key, expiry)
        self._revision = 0

    def register(
        self,
        spec: SeldonDeploymentSpec,
        engines: Dict[str, object],
    ) -> None:
        """``engines``: predictor name -> EngineService (or URL)."""
        shadow = shadow_config_from_spec(spec)
        weighted = []
        for p in spec.predictors:
            if p.name in engines:
                # a shadow predictor never serves live traffic: weight 0
                # regardless of its replica count (replicas still size
                # its engines — it must absorb the mirrored fraction)
                weight = (
                    0 if shadow is not None and p.name == shadow.predictor
                    else max(int(p.replicas), 0)
                )
                weighted.append((p.name, weight, engines[p.name]))
        if not weighted:
            raise ValueError(f"no engines supplied for deployment {spec.name!r}")
        if shadow is not None and shadow.predictor not in (
            w[0] for w in weighted
        ):
            shadow = None  # annotated predictor has no engine: no mirror
        key = spec.oauth_key or spec.name
        self._by_key[key] = _Registration(
            deployment_id=spec.name,
            oauth_key=key,
            oauth_secret=spec.oauth_secret,
            engines=weighted,
            shadow=shadow,
        )
        self._revision += 1

    def set_weights(self, deployment_id: str,
                    weights: Dict[str, int]) -> None:
        """Reassign the live traffic split of one deployment in place —
        the rollout controller's single lever (operator/rollouts.py).
        Predictors absent from ``weights`` keep their weight; unknown
        predictor names are a typed error (a rollout must never silently
        shift 0% instead of 5%).  Bumps the revision so gateway caches
        notice."""
        reg = None
        for r in self._by_key.values():
            if r.deployment_id == deployment_id:
                reg = r
                break
        if reg is None:
            raise KeyError(f"deployment not registered: {deployment_id!r}")
        known = {name for name, _, _ in reg.engines}
        unknown = set(weights) - known
        if unknown:
            raise KeyError(
                f"unknown predictors for {deployment_id!r}: {sorted(unknown)}"
            )
        reg.engines = [
            (name, max(int(weights.get(name, w)), 0), engine)
            for name, w, engine in reg.engines
        ]
        self._revision += 1

    def weights(self, deployment_id: str) -> Dict[str, int]:
        """The live traffic split by predictor name — the read side of
        ``set_weights`` (a freshly elected coordinator's rollout
        controller resumes a predecessor's rollout from this instead of
        restarting at stage 0)."""
        for r in self._by_key.values():
            if r.deployment_id == deployment_id:
                return {name: w for name, w, _ in r.engines}
        raise KeyError(f"deployment not registered: {deployment_id!r}")

    def unregister(self, oauth_key: str) -> None:
        self._by_key.pop(oauth_key, None)
        self._tokens = {
            t: (k, exp) for t, (k, exp) in self._tokens.items() if k != oauth_key
        }
        self._revision += 1

    def revision(self) -> int:
        """Monotone registration-change counter: bumps on every register
        and unregister, including a re-registration of the SAME deployment
        (whose content may have changed) — the gateway's prune gate."""
        return self._revision

    # -- auth ---------------------------------------------------------------

    def issue_token(self, oauth_key: str, oauth_secret: str) -> str:
        reg = self._by_key.get(oauth_key)
        if reg is None or (reg.oauth_secret and reg.oauth_secret != oauth_secret):
            raise AuthError("invalid client credentials")
        if len(self._tokens) > 4096:
            # clients that fetch a fresh token per session would otherwise
            # grow the store without bound (expiry eviction is lazy)
            now = time.time()
            self._tokens = {
                t: (k, exp) for t, (k, exp) in self._tokens.items() if exp > now
            }
        token = secrets.token_urlsafe(24)
        self._tokens[token] = (oauth_key, time.time() + TOKEN_TTL_S)
        return token

    def principal_for_token(self, token: str) -> _Registration:
        entry = self._tokens.get(token)
        if entry is None:
            raise AuthError("invalid token")
        key, expiry = entry
        if time.time() > expiry:
            self._tokens.pop(token, None)
            raise AuthError("token expired")
        reg = self._by_key.get(key)
        if reg is None:
            raise AuthError("client no longer registered")
        return reg

    def deployments(self) -> List[str]:
        return [r.deployment_id for r in self._by_key.values()]

    def active_token_count(self) -> int:
        """Unexpired issued tokens (expiry eviction is lazy, so this
        counts live entries, not strictly valid ones)."""
        return len(self._tokens)


class ApiGateway:
    def __init__(
        self,
        store: Optional[DeploymentStore] = None,
        firehose: Optional[Firehose] = None,
        require_auth: bool = True,
        seed: int = 0,
    ):
        self.store = store or DeploymentStore()
        self.firehose = firehose
        self.require_auth = require_auth
        self.metrics = MetricsRegistry(deployment_name="gateway")
        self._rng = np.random.default_rng(seed)
        self._seed = seed
        self._session = None  # lazy shared aiohttp session (remote engines)
        # replica sets built lazily per (deployment, predictor) from the
        # registration's engines entry; rebuilt when a re-registration
        # changes the endpoint list.  The scrape task feeds their passive
        # health off the engines' /stats surfaces.
        self._replica_sets: Dict[Tuple[str, str], Tuple[tuple, ReplicaSet]] = {}
        self._uds_clients: Dict[str, object] = {}
        # binary wire lane (runtime/wire.py): one coalescer per engine
        # socket (co-arriving predicts ride ONE multi-tensor relay
        # frame), plus the TCP endpoints that declined binary (a 4xx
        # non-frame answer) so we stop offering it to them
        self._wire_coalescers: Dict[str, "_WireCoalescer"] = {}
        self._wire_json_only: set = set()
        self._scrape_task: Optional[asyncio.Task] = None
        self._pruned_for = None  # store-change marker at last prune
        # feedback ingress accounting: engines may live in other
        # processes, so the gateway keeps its own view of the reward
        # stream it routed (surfaced in /stats; the process-global
        # seldon_tpu_feedback_* families are fed engine-side where
        # truth-vs-prediction agreement is computed)
        self.feedback_count = 0
        self.feedback_reward_sum = 0.0
        self.feedback_truth_count = 0
        # shadow mirroring (gateway/shadow.py): sampled fire-and-forget
        # duplication of live predicts to a weight-0 shadow predictor,
        # dispatched through the same pick/lane machinery live uses
        self.shadow = ShadowMirror(self._shadow_dispatch, seed=seed)
        # per-(deployment, predictor) live traffic accounting — the
        # canary observability the rollout controller gates stages on
        # (requests/errors since boot + rolling latency); bounded by the
        # registration table, not by traffic
        self._traffic: Dict[Tuple[str, str], dict] = {}
        #: optional RolloutController (operator/rollouts.py) — attach to
        #: serve its status on GET /rollouts
        self.rollouts = None
        #: optional GatewayFederation (gateway/federation.py) — attached
        #: by gateway_main when N replicas share a sqlite store.  Feeds
        #: engine-lease liveness into the balancer, gates singleton
        #: duties, and federates /fleet across peers.  None = this
        #: replica is its own coordinator (single-gateway behavior)
        self.federation = None
        # hedged-recovery budget (runtime/resilience.py RetryBudget,
        # Finagle semantics): every successful predict deposits a
        # fraction of a token, every hedged re-dispatch withdraws one —
        # a fleet-wide outage can't stampede 2x traffic onto survivors
        self._hedge_budget = RetryBudget()
        #: inflight work re-homed after a replica death, by kind —
        #: the gateway-local mirror of seldon_tpu_failover_total
        self.failovers: Dict[str, int] = {}
        # multi-tenant fair admission (runtime/qos.py): per-tenant token
        # buckets + weighted fair queueing over dispatch slots, LRU-
        # bounded accounting.  Inert with default knobs (no rate limit,
        # fair queue off) — today's behaviour bit-for-bit
        self.tenants = TenantGovernor()
        #: the coordinated-profiling manifest (gateway/fleet.py): the
        #: latest window's per-source artifact paths; None until the
        #: first POST /profile/start
        self._profile_manifest = None
        # the fair queue's backlog is an overload signal for the
        # brownout ladder; the firehose carries its typed transitions
        self._brownout_key = f"gateway:{id(self)}"
        # late-bound through a weakref: a swapped-in governor (tests,
        # demos) keeps feeding the signal, and the registry never pins
        # a gateway that was dropped without close()
        import weakref

        _ref = weakref.ref(self)
        BROWNOUT.register_depth(
            self._brownout_key,
            lambda: (lambda g: 0 if g is None
                     else g.tenants.queue_depth())(_ref()),
        )
        weakref.finalize(self, BROWNOUT.unregister_depth,
                         self._brownout_key)
        self._brownout_sink = None
        if firehose is not None and BROWNOUT.event_sink is None:
            self._brownout_sink = (
                lambda kind, **fields: firehose.publish_event(
                    "_gateway", kind, **fields)
            )
            BROWNOUT.event_sink = self._brownout_sink
            # released on close() (and by finalize if close is skipped)
            # so a later gateway's firehose can take over instead of
            # transitions publishing to a closed queue forever
            weakref.finalize(self, _release_brownout_sink,
                             self._brownout_sink)

    # -- principal resolution ----------------------------------------------

    def _resolve(self, token: Optional[str]) -> _Registration:
        if token:
            return self.store.principal_for_token(token)
        if self.require_auth:
            raise AuthError("missing bearer token")
        regs = list(self.store._by_key.values())
        if len(regs) != 1:
            raise AuthError("auth disabled but no unique deployment registered")
        return regs[0]

    def _replica_set(self, reg: _Registration, predictor_name: str,
                     engine) -> ReplicaSet:
        """The (cached) ReplicaSet behind one predictor's engines entry.
        The fingerprint catches re-registrations that changed the endpoint
        list — the set (and its learned EWMA state) is rebuilt only then."""
        targets = (
            list(engine) if isinstance(engine, (list, tuple)) else [engine]
        )
        # the fingerprint holds the TARGETS themselves: strings compare
        # by value (same-URL re-registration keeps learned EWMA state),
        # objects by identity — and the strong reference means a freed
        # engine's address can never be recycled into a false cache hit
        # (id() alone allowed exactly that)
        fp = tuple(targets)
        key = (reg.deployment_id, predictor_name)
        cached = self._replica_sets.get(key)
        if cached is None or cached[0] != fp:
            rs = ReplicaSet(
                targets,
                # deterministic per (seed, deployment, predictor): str
                # seeding is hash-randomization-proof
                rng=random.Random(
                    f"{self._seed}:{reg.deployment_id}:{predictor_name}"
                ),
                name=f"{reg.deployment_id}/{predictor_name}",
            )
            self._replica_sets[key] = (fp, rs)
            cached = (fp, rs)
        return cached[1]

    def _pick_engine(
        self, reg: _Registration, predictor: Optional[str] = None,
        eligible=None, rows: Optional[int] = None,
    ) -> Tuple[str, ReplicaSet, ReplicaEndpoint, Optional[PickDecision]]:
        """Two-level choice: replica-weighted predictor split (canary,
        unchanged), then power-of-two-choices over THAT predictor's
        replica endpoints (gateway/balancer.py).  ``decision`` is None on
        the pre-replica-set paths (single endpoint / kill switch).
        ``eligible`` narrows the p2c pool (ReplicaSet.pick) to endpoints
        the caller's lane can use.  ``rows`` makes each candidate's score
        shape-aware (autopilot cost-aware routing)."""
        entry = None
        if predictor is not None:
            for name, _, engine in reg.engines:
                if name == predictor:
                    entry = (name, engine)
                    break
        if entry is None:
            names = [e[0] for e in reg.engines]
            weights = np.asarray(
                [e[1] for e in reg.engines], dtype=np.float64
            )
            if weights.sum() <= 0:
                # degenerate all-zero split: serve uniformly — but never
                # from the shadow predictor (weight-0 BY DESIGN) unless
                # it is the only predictor there is
                weights = np.ones_like(weights)
                if reg.shadow is not None and len(names) > 1:
                    for i, n in enumerate(names):
                        if n == reg.shadow.predictor:
                            weights[i] = 0.0
                if weights.sum() <= 0:
                    weights = np.ones_like(weights)
            idx = int(self._rng.choice(len(names), p=weights / weights.sum()))
            entry = (reg.engines[idx][0], reg.engines[idx][2])
        name, engine = entry
        rs = self._replica_set(reg, name, engine)
        # phase-aware routing (runtime/servingmesh.py): decode-role
        # replicas only import KV handoffs from prefill peers — client
        # traffic routes prefill-first, never to a decode replica
        if eligible is None:
            elig = _not_decode
        else:
            def elig(ep, _e=eligible):
                return _not_decode(ep) and _e(ep)
        endpoint, decision = rs.pick(elig, rows=rows)
        self._ensure_scraper(rs)
        return name, rs, endpoint, decision

    @staticmethod
    def _request_rows(msg: SeldonMessage) -> Optional[int]:
        """Row count of a predict payload — the shape signal the
        autopilot-blended p2c score prices candidates with.  None (the
        shape-blind legacy score) for non-tensor payloads.  The shared
        rule (runtime/autopilot.py message_rows) so gateway buckets
        match interpreter branch buckets."""
        from seldon_core_tpu.runtime.autopilot import message_rows

        return message_rows(msg)

    # -- data plane ---------------------------------------------------------

    @staticmethod
    def _replica_fault(resp: SeldonMessage) -> bool:
        """Did the REPLICA fail?  Only transport-shaped failures (bad
        gateway / unreachable / timeout) feed the balancer's failure
        degradation — an engine-side validation FAILURE for a malformed
        client payload says nothing about replica health, and blaming it
        would let one bad client cycle every healthy replica through the
        degraded state."""
        st = resp.status
        return (
            st is not None
            and st.status == "FAILURE"
            and (st.code or 0) in (502, 503, 504)
            # a predictive load shed (runtime/autopilot.py) is the engine
            # DECIDING, not the replica dying: blaming it would cycle
            # every correctly-shedding replica through fail-degradation
            # exactly under the tight-deadline bursts sheds exist for
            and not ApiGateway._is_autopilot_shed(resp)
        )

    @staticmethod
    def _is_autopilot_shed(resp: SeldonMessage) -> bool:
        """A predictive/policy shed — autopilot admission OR a brownout
        tier shed.  Both are the engine DECIDING, not dying: they count
        as load for routing but feed neither fail-degradation nor the
        latency EWMA."""
        from seldon_core_tpu.runtime.autopilot import SHED_INFO_PREFIX

        st = resp.status
        if st is None or (st.code or 0) != 503:
            return False
        info = str(st.info or "")
        return (info.startswith(SHED_INFO_PREFIX)
                or info.startswith(BROWNOUT_INFO_PREFIX))

    @staticmethod
    def _decision_attrs(decision: Optional[PickDecision]) -> dict:
        """The routing decision as span attrs — chosen replica plus both
        candidates' scores, so a misprediction is auditable straight off
        the trace (PR-3/6 plumbing).  Empty on the pre-replica paths."""
        if decision is None:
            return {}
        return {
            "replica": decision.replica,
            "p2c_candidates": ",".join(decision.candidates),
            "p2c_scores": ",".join(str(s) for s in decision.scores),
        }

    async def predict(
        self, msg: SeldonMessage, token: Optional[str] = None
    ) -> SeldonMessage:
        from seldon_core_tpu.utils.tracing import (
            TRACER,
            current_trace_context,
        )

        reg = self._resolve(token)
        # tenant identity (runtime/qos.py): the Seldon-Tenant header
        # (bound to the context by the HTTP lane), else the auth
        # principal, else "anon"; the tier header picks the lane
        tenant = resolve_tenant(
            current_tenant(), reg.oauth_key if token else None
        )
        tier = current_tier()
        with self.metrics.time_ingress("predictions", "POST") as code:
            BROWNOUT.maybe_tick()
            # fair admission FIRST: a hog's excess is refused before it
            # holds a queue slot, a deadline check, or a replica pick
            throttled = self.tenants.admit(tenant, tier)
            if throttled is not None:
                code["code"] = "429"
                return SeldonMessage.failure(
                    f"{THROTTLE_INFO_PREFIX}: tenant {tenant!r} over its "
                    f"{throttled} limit — retry later", code=429,
                )
            if BROWNOUT.sheds_tier(tier):
                # staged degradation: lower tiers answer a typed
                # retryable 503 while the ladder is engaged
                RECORDER.record_brownout_shed(tier)
                self.tenants.note_shed(tenant)
                code["code"] = "503"
                return SeldonMessage.failure(
                    f"{BROWNOUT_INFO_PREFIX}: {tier!r} tier shed at "
                    f"brownout stage {BROWNOUT.stage()} — retry later",
                    code=503,
                )
            # a request that arrives with its deadline already spent is
            # the CALLER's failure — answer before picking so it can't
            # feed any replica's failure degradation
            rem = remaining_s()
            if rem is not None and rem <= 0:
                code["code"] = "504"
                return SeldonMessage.failure(
                    "request deadline exhausted at gateway", code=504
                )
            # a hop clamped BELOW its normal timeout by the caller's
            # budget can fail because the budget was too small, not
            # because the replica is sick — such failures are accounted
            # neutrally (inflight released, no EWMA, no failure streak)
            # or one impatient client would cycle every healthy replica
            # through fail-degradation
            blameable = rem is None or rem >= 20.0
            rows = self._request_rows(msg)
            # the fair-queue slot covers pick + dispatch: a freed slot
            # always goes to the pending request with the smallest
            # virtual tag, so a hog's backlog cannot starve a
            # well-behaved tenant's next request (inert when
            # SELDON_TPU_GW_FAIR_INFLIGHT is unset)
            async with self.tenants.slot(tenant):
                predictor_name, rs, endpoint, decision = self._pick_engine(
                    reg, rows=rows
                )
                # the ingress span roots the request tree (or joins the
                # caller's trace when it sent a traceparent); the engine
                # hop — in-process, UDS or HTTP — becomes its child
                track = replicas_enabled()
                if track:
                    endpoint.begin()
                t0 = time.perf_counter()
                ok = False
                raised = True
                shed = False
                pm_trace_id = ""
                try:
                    with TRACER.span(
                        msg.meta.puid, "gateway", kind="request",
                        method="predict", deployment=reg.deployment_id,
                        predictor=predictor_name,
                        tenant=tenant, tier=tier,
                        **self._decision_attrs(decision),
                    ), qos_scope(tenant, tier):
                        # the RESOLVED identity (principal fallback
                        # included) binds the dispatch scope, so the
                        # remote lanes forward what the gateway resolved
                        # — the raw header is absent exactly for
                        # authenticated callers
                        resp = await self._dispatch_predict(endpoint, msg)
                        # verdict stamped while the ingress span is still
                        # OPEN: the postmortem retention policy judges the
                        # root span at fold time, and a replica fault or
                        # policy shed travels as a healthy-looking 200
                        # envelope the span would otherwise never show
                        shed = self._is_autopilot_shed(resp)
                        ok = not self._replica_fault(resp)
                        _ctx = current_trace_context()
                        if _ctx is not None:
                            pm_trace_id = _ctx.trace_id
                        if shed:
                            TRACER.annotate(shed=True, status=503)
                        elif not ok:
                            TRACER.annotate(
                                status=503, error="replica_fault")
                    raised = False
                finally:
                    if track:
                        if raised:
                            # the dispatch never returned — client hung up
                            # (CancelledError) or a gateway-side bug,
                            # neither of which says anything about REPLICA
                            # health: account neutrally or three impatient
                            # clients fail-degrade a healthy replica (real
                            # transport failures return a typed 503, they
                            # don't raise)
                            endpoint.release(batcher=True)
                        elif shed:
                            # predictive/policy shed: neutral accounting —
                            # not a failure streak (the replica is
                            # deciding, not dying) and not a latency
                            # sample (a ~1 ms refusal fed into the EWMA
                            # would make the shedding replica look FAST
                            # and herd more traffic onto it)
                            endpoint.release(batcher=True)
                        elif ok or blameable:
                            rs.complete(endpoint, decision,
                                        time.perf_counter() - t0, ok=ok,
                                        rows=rows)
                        else:
                            endpoint.release(batcher=True)
                if not raised:
                    if ok and not shed:
                        # fund the hedge budget off real successes so a
                        # fleet-wide outage can't stampede retries
                        self._hedge_budget.deposit()
                    elif not ok:
                        # the replica failed transport-style (dead
                        # process, lapsed lease, timeout): re-dispatch
                        # the idempotent predict ONCE to a peer replica
                        failed_resp = resp
                        resp = await self._maybe_hedge(
                            rs, endpoint, msg, rows, resp)
                        shed = self._is_autopilot_shed(resp)
                        if pm_trace_id:
                            # out-of-band: the ingress span already
                            # closed, so the hedge verdict joins the
                            # pending trace as a note and re-triggers
                            # the postmortem keep/drop decision
                            try:
                                from seldon_core_tpu.utils.postmortem \
                                    import POSTMORTEM
                                POSTMORTEM.note(
                                    pm_trace_id, "failover",
                                    lane="unary",
                                    recovered=(
                                        resp is not failed_resp
                                        and not
                                        self._replica_fault(resp)),
                                )
                            except Exception:  # noqa: BLE001
                                pass
            # record which predictor served (canary observability; feedback
            # routes back to the same predictor)
            resp.meta.requestPath.setdefault("predictor", predictor_name)
            live_error = (
                resp.status is not None and resp.status.status == "FAILURE"
            )
            if live_error:
                code["code"] = str(resp.status.code or 500)
            live_latency_s = time.perf_counter() - t0
            self._note_traffic(
                reg.deployment_id, predictor_name, live_latency_s, live_error
            )
            # per-tenant accounting: the governor's /stats row and the
            # quality observatory's per-tenant SLO ring (GET /quality).
            # An engine-side policy shed (autopilot/brownout 503) is
            # flow control, not a tenant error — same rule as the
            # global SLO feed (utils/metrics.py) — or a brownout would
            # latch the per-tenant burn at the cap during exactly the
            # event this view exists to attribute
            tenant_error = live_error and not shed
            self.tenants.note_result(tenant, live_latency_s, tenant_error)
            self._note_tenant_slo(tenant, live_latency_s, tenant_error)
            # shadow mirroring rides AFTER the live answer exists — one
            # RNG draw for the unsampled path, one create_task for the
            # sampled one; the mirror dispatch/diff never touches this
            # request's latency (gateway/shadow.py invariants)
            self.shadow.maybe_mirror(
                reg, predictor_name, msg, resp, live_latency_s
            )
        if self.firehose is not None:
            self.firehose.publish(reg.deployment_id, msg, resp,
                                  tenant=tenant, tier=tier)
        return resp

    async def _maybe_hedge(self, rs: ReplicaSet, failed: ReplicaEndpoint,
                           msg: SeldonMessage, rows: Optional[int],
                           resp: SeldonMessage) -> SeldonMessage:
        """Hedged recovery after a transport-shaped replica failure: one
        re-dispatch of the (idempotent) predict to a peer replica.

        Guard rails, in order: the federation kill switch (the hedge is
        part of the mesh-recovery layer — ``SELDON_TPU_FEDERATION=0``
        restores fail-to-caller bit-for-bit), idempotency (predict is in
        the resilience layer's IDEMPOTENT_METHODS — the dead engine may
        have half-executed it), a live peer to hedge to, remaining
        deadline, and the Finagle-style retry budget (funded by
        successes, so a fleet-wide outage degrades to the original
        failure instead of doubling traffic on survivors).  Returns the
        peer's response when it is not itself a replica fault, else the
        original failure."""
        from seldon_core_tpu.gateway.federation import federation_enabled

        if (
            not federation_enabled()
            or not replicas_enabled()
            or "predict" not in IDEMPOTENT_METHODS
            or len(rs) < 2
        ):
            return resp
        rem = remaining_s()
        if rem is not None and rem <= 0.05:
            return resp
        if not self._hedge_budget.withdraw():
            RECORDER.record_retry_budget_exhausted()
            return resp
        endpoint, decision = rs.pick(
            lambda ep, _f=failed: _not_decode(ep) and ep is not _f,
            rows=rows,
        )
        if endpoint is failed:
            # pick() falls back to the full pool when the filter empties
            # it — no live peer, nothing to hedge to
            return resp
        endpoint.begin()
        t0 = time.perf_counter()
        ok = False
        raised = True
        shed = False
        try:
            resp2 = await self._dispatch_predict(endpoint, msg)
            shed = self._is_autopilot_shed(resp2)
            ok = not self._replica_fault(resp2)
            raised = False
        finally:
            if raised or shed:
                endpoint.release(batcher=True)
            else:
                rs.complete(endpoint, decision,
                            time.perf_counter() - t0, ok=ok, rows=rows)
        if not ok:
            return resp  # peer no better: surface the ORIGINAL failure
        if not shed:
            self.failovers["unary"] = self.failovers.get("unary", 0) + 1
            RECORDER.record_failover("unary")
        return resp2

    @staticmethod
    def _note_tenant_slo(tenant: str, latency_s: float,
                         error: bool) -> None:
        from seldon_core_tpu.utils.quality import QUALITY

        QUALITY.record_tenant_request(tenant, latency_s, error=error)

    def _note_traffic(self, deployment: str, predictor: str,
                      latency_s: float, error: bool) -> None:
        key = (deployment, predictor)
        entry = self._traffic.get(key)
        if entry is None:
            entry = self._traffic[key] = {
                "count": 0, "errors": 0, "latency_ms": Reservoir(1024),
            }
        entry["count"] += 1
        if error:
            entry["errors"] += 1
        entry["latency_ms"].observe(latency_s * 1e3)

    def predictor_traffic(self, deployment: str,
                          predictor: str) -> Tuple[int, int]:
        """(requests, errors) served so far for one predictor — the
        error-rate signal the rollout controller diffs per stage."""
        entry = self._traffic.get((deployment, predictor))
        if entry is None:
            return (0, 0)
        return (entry["count"], entry["errors"])

    async def _shadow_dispatch(self, reg, predictor: str,
                               msg: SeldonMessage) -> SeldonMessage:
        """One mirrored hop to the shadow predictor, through the real
        replica-set pick + lane machinery.  Inflight-only accounting
        (release, not complete): mirrored traffic must keep the shadow
        set's load visible without letting mirror latencies feed routing
        EWMAs or failure streaks — the shadow predictor is under test,
        not under management."""
        _name, _rs, endpoint, _decision = self._pick_engine(reg, predictor)
        track = replicas_enabled()
        if track:
            endpoint.begin(batcher=False)
        try:
            return await self._dispatch_predict(endpoint, msg)
        finally:
            if track:
                endpoint.release()

    async def send_feedback(
        self, feedback: Feedback, token: Optional[str] = None
    ) -> SeldonMessage:
        from seldon_core_tpu.utils.tracing import TRACER

        reg = self._resolve(token)
        with self.metrics.time_ingress("feedback", "POST"):
            predictor = None
            if feedback.response is not None:
                predictor = feedback.response.meta.requestPath.get("predictor")
            fb_puid = feedback.puid()
            _, rs, endpoint, decision = self._pick_engine(reg, predictor)
            self.feedback_count += 1
            self.feedback_reward_sum += float(feedback.reward)
            if feedback.truth is not None:
                self.feedback_truth_count += 1
            # inflight-only accounting (release, not complete): a
            # feedback ack is a ~1 ms bookkeeping hop — folding it into
            # the EWMA that routes PREDICT traffic would drag a replica
            # with a steady feedback stream toward "fastest" regardless
            # of its real predict latency (same argument as streams)
            track = replicas_enabled()
            if track:
                endpoint.begin(batcher=False)
            try:
                with TRACER.span(
                    fb_puid, "gateway", kind="request", method="feedback",
                    deployment=reg.deployment_id,
                    **self._decision_attrs(decision),
                ):
                    return await self._dispatch_feedback(endpoint, feedback)
            finally:
                if track:
                    endpoint.release()

    def _uds_client(self, path: str):
        """Pooled relay client per socket path (runtime/udsrelay.py)."""
        client = self._uds_clients.get(path)
        if client is None or client.closed:
            from seldon_core_tpu.runtime.udsrelay import UdsRelayClient

            client = UdsRelayClient(path)
            self._uds_clients[path] = client
            # a re-dialed client is a NEW peer process: invalidate the
            # coalescer bound to the old one AND forget a stale json-only
            # negotiation — an engine restarted wire-enabled must not
            # stay pinned to the slow lane for the gateway's lifetime
            self._wire_coalescers.pop(path, None)
            self._wire_json_only.discard(path)
        return client

    def _wire_coalescer(self, path: str) -> _WireCoalescer:
        from seldon_core_tpu.runtime import wire as wirelib

        client = self._uds_client(path)
        co = self._wire_coalescers.get(path)
        window, max_n = wirelib.coalesce_window_s(), wirelib.coalesce_max()
        if (co is None or co.client is not client
                or co.window_s != window or co.max_n != max_n):
            co = _WireCoalescer(client, window, max_n)
            self._wire_coalescers[path] = co
        return co

    def _lane_for(self, endpoint: ReplicaEndpoint) -> str:
        if hasattr(endpoint.target, "predict"):
            return "inprocess"
        if endpoint.uds_path is not None and uds_enabled():
            return "uds"
        return "tcp"

    async def _dispatch(
        self, endpoint: ReplicaEndpoint, obj, method: str, relay_op: int,
        path: str,
    ) -> SeldonMessage:
        """One gateway->engine hop over whichever lane the endpoint
        advertises: in-process call, framed UDS relay, or HTTP POST.
        ``obj`` is the SeldonMessage/Feedback, ``method`` its in-process
        method name, ``relay_op``/``path`` the lane-specific addresses."""
        from seldon_core_tpu.runtime import wire as wirelib

        lane = self._lane_for(endpoint)
        RECORDER.record_lane_request(lane)
        if lane == "inprocess":
            return await getattr(endpoint.target, method)(obj)
        # the binary tensor lane (runtime/wire.py) carries unary predicts
        # with a numeric payload — no JSON composition, no JSON parse on
        # either side; feedback and non-tensor payloads stay on JSON
        wire_ok = (
            method == "predict"
            and wirelib.wire_enabled()
            and wirelib.frame_eligible(obj)
        )
        if lane == "uds":
            if wire_ok and endpoint.uds_path not in self._wire_json_only:
                return await self._wire_uds_call(endpoint.uds_path, obj)
            return await self._uds_call(
                endpoint.uds_path, relay_op, obj.to_json()
            )
        if endpoint.base_url is None:
            return SeldonMessage.failure(
                "endpoint has no TCP url and the UDS lane is disabled "
                "(SELDON_TPU_UDS=0)", code=503,
            )
        if wire_ok and endpoint.base_url not in self._wire_json_only:
            return await self._wire_http_post(endpoint.base_url, path, obj)
        return await self._http_post(
            endpoint.base_url + path, obj.to_json()
        )

    async def _dispatch_predict(
        self, endpoint: ReplicaEndpoint, msg: SeldonMessage
    ) -> SeldonMessage:
        return await self._dispatch(
            endpoint, msg, "predict", OP_PREDICT, "/api/v0.1/predictions"
        )

    async def _dispatch_feedback(
        self, endpoint: ReplicaEndpoint, fb: Feedback
    ) -> SeldonMessage:
        return await self._dispatch(
            endpoint, fb, "send_feedback", OP_FEEDBACK, "/api/v0.1/feedback"
        )

    async def _uds_call(self, path: str, op: int, payload: str) -> SeldonMessage:
        """One zero-copy relay round trip; transport failures surface the
        same 503 shape the TCP lane produces, and the caller's remaining
        deadline budget clamps the hop the same way _http_post's does (a
        wedged engine fails at the deadline, not never).  The request
        frame now carries the metadata sidecar (udsrelay.py
        current_relay_meta): deadline, traceparent and tenant/tier reach
        the engine like they do on the HTTP lane, so engine-side clamps,
        joined spans and tenant accounting survive the relay hop (the
        PR-8 scope gap).  The gateway-side clamp stays as the backstop."""
        from seldon_core_tpu.runtime.udsrelay import current_relay_meta

        total = 20.0
        rem = remaining_s()
        if rem is not None:
            if rem <= 0:
                return SeldonMessage.failure(
                    "request deadline exhausted at gateway", code=504
                )
            total = min(total, rem)
        try:
            body, _status = await asyncio.wait_for(
                self._uds_client(path).call(
                    op, payload.encode(), meta=current_relay_meta()
                ),
                timeout=total,
            )
            return SeldonMessage.from_json(body.decode("utf-8", "replace"))
        except asyncio.TimeoutError:
            return SeldonMessage.failure(
                f"engine timeout after {total:.1f}s on uds relay", code=504
            )
        except (ConnectionError, OSError) as e:
            return SeldonMessage.failure(
                f"engine unreachable: {e}", code=503
            )
        except SeldonMessageError as e:
            return SeldonMessage.failure(
                f"engine error: bad relay response: {e}", code=502
            )

    async def _wire_uds_call(self, path: str,
                             msg: SeldonMessage) -> SeldonMessage:
        """One binary predict over the framed relay — the zero-JSON hop.
        The request frame's sidecar carries puid/deadline/traceparent/
        tenant/tier (the relay-meta semantics, wire-native); co-arriving
        calls for the same socket coalesce into one multi-tensor frame
        and de-coalesce by slot, verified against the echoed puid.  The
        gateway-side deadline clamp stays as the backstop, exactly like
        ``_uds_call``."""
        from seldon_core_tpu.messages import new_puid
        from seldon_core_tpu.runtime import wire as wirelib

        total = 20.0
        rem = remaining_s()
        if rem is not None:
            if rem <= 0:
                return SeldonMessage.failure(
                    "request deadline exhausted at gateway", code=504
                )
            total = min(total, rem)
        if not msg.meta.puid:
            # the echo the de-coalescer is verified against
            msg.meta.puid = new_puid()
        frame = wirelib.join_parts(
            wirelib.frame_from_message(msg, sidecar=True))
        RECORDER.record_wire_request("dispatch-uds", "binary")
        try:
            body, _status = await asyncio.wait_for(
                self._wire_coalescer(path).call(frame), timeout=total,
            )
        except asyncio.TimeoutError:
            return SeldonMessage.failure(
                f"engine timeout after {total:.1f}s on uds relay", code=504
            )
        except (ConnectionError, OSError) as e:
            return SeldonMessage.failure(
                f"engine unreachable: {e}", code=503
            )
        except wirelib.WireError as e:
            return SeldonMessage.failure(
                f"engine error: bad wire response: {e}", code=502
            )
        if _status == 415:
            # the peer doesn't speak OP_WIRE (kill-switched engine):
            # negotiate down PERMANENTLY for this socket and serve the
            # request over JSON
            self._wire_json_only.add(path)
            return await self._uds_call(path, OP_PREDICT, msg.to_json())
        try:
            resp = wirelib.message_from_frame(wirelib.decode_frame(body))
        except wirelib.WireError:
            # not a frame: a relay-writer-level failure body is JSON
            try:
                parsed = SeldonMessage.from_json(
                    body.decode("utf-8", "replace"))
            except SeldonMessageError as e:
                return SeldonMessage.failure(
                    f"engine error: bad wire response: {e}", code=502
                )
            if (
                parsed.status is not None
                and "unknown relay op" in (parsed.status.info or "")
            ):
                # a PRE-WIRE engine build: its relay answers op 6 with
                # the unknown-op 400 — same negotiate-down as 415, or a
                # rolling upgrade would fail every predict to it forever
                self._wire_json_only.add(path)
                return await self._uds_call(
                    path, OP_PREDICT, msg.to_json())
            return parsed
        if resp.meta.puid and resp.meta.puid != msg.meta.puid:
            return SeldonMessage.failure(
                "coalesced wire response puid mismatch (got "
                f"{resp.meta.puid!r})", code=502,
            )
        return resp

    async def _wire_http_post(self, base_url: str, path: str,
                              msg: SeldonMessage) -> SeldonMessage:
        """Binary predict over the TCP lane: same pooled session and
        deadline clamp as ``_http_post``, body = one wire frame instead
        of JSON.  A peer that answers 4xx with a non-frame body doesn't
        speak the contract — it is remembered as json-only and this call
        (and every later one) rides the JSON path."""
        import aiohttp

        from seldon_core_tpu.runtime import wire as wirelib

        session = self._get_session()
        total = 20.0
        headers = {"Content-Type": wirelib.WIRE_CONTENT_TYPE}
        rem = remaining_s()
        if rem is not None:
            if rem <= 0:
                return SeldonMessage.failure(
                    "request deadline exhausted at gateway", code=504
                )
            total = min(total, rem)
            headers[DEADLINE_HEADER] = deadline_header_value()
        RECORDER.record_wire_request("dispatch-tcp", "binary")
        frame = wirelib.join_parts(
            wirelib.frame_from_message(msg, sidecar=True))
        try:
            async with session.post(
                base_url + path, data=frame,
                timeout=aiohttp.ClientTimeout(total=total), headers=headers,
            ) as r:
                raw = await r.read()
                if r.content_type == wirelib.WIRE_CONTENT_TYPE:
                    return wirelib.message_from_frame(
                        wirelib.decode_frame(raw))
                if r.status in (400, 404, 405, 415, 501):
                    # the peer declined the contract (older build or
                    # kill-switched): negotiate down PERMANENTLY and
                    # serve this request over JSON
                    self._wire_json_only.add(base_url)
                    return await self._http_post(
                        base_url + path, msg.to_json())
                return SeldonMessage.from_json(
                    raw.decode("utf-8", "replace"))
        except aiohttp.ClientConnectorError:
            # connection establishment failed before any bytes moved:
            # delegate to the JSON lane, which owns the connect-retry
            # choreography (3 attempts) — no double-apply risk
            return await self._http_post(base_url + path, msg.to_json())
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            return SeldonMessage.failure(f"engine error: {e}", code=503)
        except (wirelib.WireError, SeldonMessageError) as e:
            return SeldonMessage.failure(
                f"engine error: bad wire response: {e}", code=502
            )

    def _get_session(self):
        """Shared pooled session; timeouts are PER REQUEST (a session-level
        total would make unary calls and long-lived SSE proxies poison each
        other's deadline).  Pool geometry is an env contract instead of a
        library default: ``SELDON_TPU_GW_POOL`` caps concurrent upstream
        connections (aiohttp's default 100 starves >100-replica fan-outs),
        ``SELDON_TPU_GW_KEEPALIVE_S`` holds idle keep-alives (default 15 s
        — engines' drain window outlives it, so rolling restarts don't
        strand the pool on dead sockets)."""
        import os

        import aiohttp

        if self._session is None or self._session.closed:
            try:
                pool = int(os.environ.get("SELDON_TPU_GW_POOL", "") or 100)
            except ValueError:
                pool = 100
            try:
                keepalive = float(
                    os.environ.get("SELDON_TPU_GW_KEEPALIVE_S", "") or 15.0
                )
            except ValueError:
                keepalive = 15.0
            self._session = aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(
                    limit=pool, keepalive_timeout=keepalive
                )
            )
        return self._session

    def _ensure_scraper(self, rs: ReplicaSet) -> None:
        """Start the passive-health scrape loop once a URL-backed multi-
        replica set exists (in-process sets read health directly; solo
        sets have nothing to balance)."""
        if (
            self._scrape_task is not None
            or not replicas_enabled()
            or len(rs) < 2
            or not any(ep.base_url for ep in rs.endpoints)
        ):
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # no loop (sync tests): scores run on local state only
        self._scrape_task = loop.create_task(self._scrape_loop())

    def _prune_stale_sets(self) -> list:
        """Drop replica sets (and return the relay clients) of
        deployments no longer registered — without this an unregister
        leaves the cached set alive forever: in-process EngineServices
        pinned by the fingerprint's strong refs, URL sets perpetually
        scraped, relay connections pooled to sockets nothing routes to.

        Gated on the store actually changing: the full pass re-reads
        every registration (one query + JSON parse each on the sqlite
        store), which is pure waste on the every-2s scrape tick and every
        /stats poll of a stable topology.  The gate reads the store's
        revision counter — a re-registration of the SAME deployment (a
        predictor dropped, a uds path moved) bumps it, where a
        deployment-ID diff would miss the change and leave the stale set
        scraped forever.  Stores without a revision() fall back to the
        ID diff (they can at least prune on add/remove)."""
        rev = getattr(self.store, "revision", None)
        marker = (
            ("rev", rev()) if callable(rev)
            else ("ids", tuple(sorted(self.store.deployments())))
        )
        if marker == self._pruned_for:
            return []
        self._pruned_for = marker
        live_pairs = set()
        live_uds = set()
        for reg in list(self.store._by_key.values()):
            if reg is None:
                continue
            for name, _w, engine in reg.engines:
                live_pairs.add((reg.deployment_id, name))
                targets = (
                    engine if isinstance(engine, (list, tuple))
                    else [engine]
                )
                for t in targets:
                    if isinstance(t, str):
                        _base, uds = parse_endpoint_spec(t)
                        if uds:
                            live_uds.add(uds)
        for key in list(self._replica_sets):
            if key not in live_pairs:
                del self._replica_sets[key]
        for key in list(self._traffic):
            if key not in live_pairs:
                del self._traffic[key]
        self.shadow.prune({dep for dep, _ in live_pairs})
        stale_clients = [
            c for p, c in self._uds_clients.items() if p not in live_uds
        ]
        self._uds_clients = {
            p: c for p, c in self._uds_clients.items() if p in live_uds
        }
        return stale_clients

    async def _scrape_loop(self) -> None:
        interval = scrape_interval_s()
        while True:
            try:
                for client in self._prune_stale_sets():
                    await client.close()
                # engine-lease liveness rides the same tick: a lapsed
                # lease marks a replica dead within one TTL instead of
                # waiting out three failed scrapes (gateway/federation.py)
                leases = (
                    self.federation.engine_leases()
                    if self.federation is not None else None
                )
                for _fp, rs in list(self._replica_sets.values()):
                    if leases is not None:
                        rs.apply_leases(leases)
                    if len(rs) > 1:
                        await rs.scrape_once(self._get_session())
                # fleet outlier gauges refresh off the docs the pass
                # just stashed — zero extra polling (gateway/fleet.py)
                from seldon_core_tpu.gateway.fleet import (
                    refresh_outlier_gauges,
                )

                refresh_outlier_gauges(self)
            except Exception:
                # a malformed /stats body (proxy interposing, engine
                # mid-deploy) must not kill the loop: the task is never
                # restarted, so an escape here would freeze every
                # replica's health at its last value for the gateway's
                # lifetime
                pass
            await asyncio.sleep(interval)

    async def _http_post(self, url: str, payload: str) -> SeldonMessage:
        import aiohttp

        # one pooled session per gateway + 3 retries, mirroring apife's
        # pooling client with HttpRetryHandler (InternalPredictionService.
        # java:60-72, HttpRetryHandler.java:34-45).  Retries fire only on
        # connection-establishment failures — once bytes may have reached the
        # engine, re-POSTing could double-apply feedback training
        session = self._get_session()
        last = "unreachable"
        for _ in range(3):
            # deadline propagation (runtime/resilience.py): recomputed per
            # attempt — the caller's REMAINING budget clamps this hop's
            # timeout and rides to the engine as milliseconds, so a
            # deadline set AT the gateway is honored end-to-end instead of
            # resetting per hop (or per connect-retry)
            total = 20.0
            headers = {}
            rem = remaining_s()
            if rem is not None:
                if rem <= 0:
                    return SeldonMessage.failure(
                        "request deadline exhausted at gateway", code=504
                    )
                total = min(total, rem)
                headers[DEADLINE_HEADER] = deadline_header_value()
            # trace context rides to the remote engine alongside the
            # deadline, so its spans join the gateway's tree
            from seldon_core_tpu.utils.tracing import (
                TRACEPARENT_HEADER,
                traceparent_header_value,
            )

            tp = traceparent_header_value()
            if tp is not None:
                headers[TRACEPARENT_HEADER] = tp
            # tenant/tier ride to the remote engine so its admission
            # (brownout tier sheds, genserver lanes) and its spans see
            # the same identity the gateway resolved
            from seldon_core_tpu.runtime.qos import (
                TENANT_HEADER,
                TIER_HEADER,
                TIER_INTERACTIVE,
            )

            tenant = current_tenant()
            if tenant:
                headers[TENANT_HEADER] = tenant
            tier = current_tier()
            if tier != TIER_INTERACTIVE:
                headers[TIER_HEADER] = tier
            headers = headers or None
            timeout = aiohttp.ClientTimeout(total=total)
            try:
                async with session.post(
                    url, data=payload, timeout=timeout, headers=headers
                ) as r:
                    return SeldonMessage.from_json(await r.text())
            except aiohttp.ClientConnectorError as e:
                last = str(e)
                await asyncio.sleep(0.05)
            except (aiohttp.ClientError, asyncio.TimeoutError) as e:
                return SeldonMessage.failure(f"engine error: {e}", code=503)
        return SeldonMessage.failure(f"engine unreachable: {last}", code=503)

    def stats(self) -> dict:
        """Zero-dependency JSON snapshot for ``GET /stats`` — ingress
        latency percentiles, routing table, firehose backpressure, and the
        process-level flight-recorder telemetry (engines sharing this
        process report their batcher/generation internals here too)."""
        return {
            "gateway": {
                "require_auth": self.require_auth,
                "deployments": self.store.deployments(),
                "active_tokens": self.store.active_token_count(),
            },
            # per-predictor replica sets: endpoints, gateway-side
            # inflight/EWMA, picks, passive health, mispicks, imbalance.
            # Pruned here as well as in the scrape loop — gateways whose
            # sets are all in-process/uds-only never start the scraper,
            # and an unregistered deployment must not pin its engines
            "replicas": self._stats_replicas(),
            # per-predictor live traffic + the shadow mirror's compact
            # health block (full divergence table on GET /shadow) + the
            # attached rollout controller's state when one is wired
            "traffic": {
                f"{dep}/{pred}": {
                    "count": e["count"],
                    "errors": e["errors"],
                    "latency_ms": e["latency_ms"].snapshot(),
                }
                for (dep, pred), e in sorted(self._traffic.items())
            },
            "shadow": self.shadow.snapshot(),
            # per-tenant admission accounting (runtime/qos.py): bounded
            # rows (LRU past 256 tenants), token-bucket refusals, fair-
            # queue depth — plus the brownout ladder's stage/transitions
            "tenants": self.tenants.snapshot(),
            "brownout": BROWNOUT.snapshot(),
            "rollouts": (
                None if self.rollouts is None else self.rollouts.snapshot()
            ),
            # coordinator election + re-homed-work accounting: which
            # replica owns singleton duties, the fencing token, live
            # peers, and how much inflight work this replica recovered
            "federation": {
                **(
                    {} if self.federation is None
                    else self.federation.snapshot()
                ),
                "failovers": dict(self.failovers),
            },
            "feedback": {
                "count": self.feedback_count,
                "mean_reward": round(
                    self.feedback_reward_sum / self.feedback_count, 6
                ) if self.feedback_count else 0.0,
                "truth_provided": self.feedback_truth_count,
            },
            "firehose": (
                None if self.firehose is None else self.firehose.snapshot()
            ),
            "telemetry": RECORDER.snapshot(),
        }

    def _stats_replicas(self) -> dict:
        stale = self._prune_stale_sets()
        if stale:
            try:
                loop = asyncio.get_running_loop()
                for client in stale:
                    loop.create_task(client.close())
            except RuntimeError:
                pass  # sync caller: connections close with the gateway
        return {
            f"{dep}/{pred}": rs.snapshot()
            for (dep, pred), (_fp, rs) in sorted(
                self._replica_sets.items()
            )
        }

    async def close(self) -> None:
        BROWNOUT.unregister_depth(self._brownout_key)
        _release_brownout_sink(self._brownout_sink)
        if self.federation is not None:
            # hand the coordinator lease over NOW — the surviving
            # replicas must not wait out the TTL on a graceful exit
            self.federation.resign()
        self.shadow.cancel_all()
        if self._scrape_task is not None:
            self._scrape_task.cancel()
            self._scrape_task = None
        for co in self._wire_coalescers.values():
            co.shutdown()
        self._wire_coalescers = {}
        for client in self._uds_clients.values():
            await client.close()
        self._uds_clients = {}
        if self._session is not None and not self._session.closed:
            await self._session.close()


# ---------------------------------------------------------------------------
# HTTP app
# ---------------------------------------------------------------------------


def make_gateway_app(gateway: ApiGateway):
    """aiohttp app: /oauth/token, /api/v0.1/predictions, /api/v0.1/feedback,
    /ping, /prometheus, /stats — the apife REST surface."""
    from aiohttp import web

    from seldon_core_tpu.runtime.rest import _error_response, _msg_response, _payload_text
    from seldon_core_tpu.utils.metrics import CONTENT_TYPE_LATEST

    app = web.Application(client_max_size=256 * 1024 * 1024)

    def _bearer(request) -> Optional[str]:
        auth = request.headers.get("Authorization", "")
        if auth.startswith("Bearer "):
            return auth[len("Bearer ") :]
        return None

    async def token(request):
        auth = request.headers.get("Authorization", "")
        key = secret = None
        if auth.startswith("Basic "):
            try:
                decoded = base64.b64decode(auth[len("Basic ") :]).decode()
                key, _, secret = decoded.partition(":")
            except Exception:
                pass
        if key is None:
            form = await request.post()
            key = form.get("client_id")
            secret = form.get("client_secret", "")
        try:
            tok = gateway.store.issue_token(key or "", secret or "")
        except AuthError as e:
            return web.json_response({"error": str(e)}, status=401)
        return web.json_response(
            {"access_token": tok, "token_type": "bearer", "expires_in": int(TOKEN_TTL_S)}
        )

    from seldon_core_tpu.runtime.qos import (
        TENANT_HEADER,
        TIER_HEADER,
        bind_qos,
        qos_scope,
    )

    async def predictions(request):
        from seldon_core_tpu.runtime import wire as wirelib

        if (request.content_type or "") == wirelib.WIRE_CONTENT_TYPE:
            return await predictions_wire(request)
        try:
            msg = SeldonMessage.from_json(await _payload_text(request))
        except SeldonMessageError as e:
            return _error_response(str(e))
        from seldon_core_tpu.utils.tracing import (
            TRACEPARENT_HEADER,
            parse_traceparent,
            trace_scope,
        )

        RECORDER.record_wire_request("ingress", "json")
        from seldon_core_tpu.utils.costledger import (
            LEDGER,
            costledger_enabled,
        )
        if costledger_enabled():
            # tenant-attributed ingress bytes (utils/costledger.py);
            # deployment is unresolved this early, so gateway rows key
            # on the lane alone
            LEDGER.note_bytes(
                request.headers.get(TENANT_HEADER) or "", "",
                "gateway_json", int(request.content_length or 0))
        try:
            # deadline set at the gateway governs the whole request tree;
            # an incoming traceparent makes the gateway span the caller's
            # child instead of a fresh root; tenant/tier headers bind the
            # QoS identity the same way (runtime/qos.py)
            with trace_scope(
                parse_traceparent(request.headers.get(TRACEPARENT_HEADER))
            ), maybe_deadline_scope(
                deadline_ms_header(request.headers.get(DEADLINE_HEADER))
            ), qos_scope(
                request.headers.get(TENANT_HEADER),
                request.headers.get(TIER_HEADER),
            ):
                resp = await gateway.predict(msg, _bearer(request))
        except AuthError as e:
            return _error_response(str(e), code=401)
        status = 200 if resp.status is None or resp.status.status == "SUCCESS" else (
            resp.status.code or 500
        )
        return _msg_response(resp, status=status)

    async def predictions_wire(request):
        """Binary tensor ingress (``Content-Type:
        application/x-seldon-tensor``, runtime/wire.py): the client's
        frame parses into the routing layer with ONE frombuffer view —
        no JSON anywhere between the client socket and the engine's
        device dispatch when the engine hop rides the wire lane too.
        The frame sidecar carries deadline/trace/tenant/tier (HTTP
        headers still honored as the fallback); responses are framed
        from the engine's response tensor and answer with the same
        content type."""
        from seldon_core_tpu.runtime import wire as wirelib
        from seldon_core_tpu.utils.tracing import (
            TRACEPARENT_HEADER,
            parse_traceparent,
            trace_scope,
        )

        if not wirelib.wire_enabled():
            return _error_response(
                "binary wire lane disabled (SELDON_TPU_WIRE=0)", code=415
            )
        body = await request.read()
        RECORDER.record_wire_request("ingress", "binary")
        wirelib.account_copy(len(body))
        try:
            frame = wirelib.decode_frame(body)
            if frame.is_multi:
                raise wirelib.WireError(
                    "multi frames are a gateway->engine contract; "
                    "ingress takes single frames"
                )
            msg = wirelib.message_from_frame(frame)
        except wirelib.WireError as e:
            return _error_response(str(e), code=e.http_code)
        smeta = frame.meta
        from seldon_core_tpu.utils.costledger import (
            LEDGER,
            costledger_enabled,
        )
        if costledger_enabled():
            # tenant-attributed binary-ingress bytes: the frame sidecar
            # names the tenant; HTTP header is the fallback
            LEDGER.note_bytes(
                smeta.get("tenant")
                or request.headers.get(TENANT_HEADER) or "",
                "", "gateway_wire", len(body))
        dl_ms = smeta.get("deadline_ms")
        budget_s = (
            dl_ms / 1e3 if dl_ms else
            deadline_ms_header(request.headers.get(DEADLINE_HEADER))
        )
        try:
            with trace_scope(parse_traceparent(
                smeta.get("traceparent")
                or request.headers.get(TRACEPARENT_HEADER)
            )), maybe_deadline_scope(budget_s), qos_scope(
                smeta.get("tenant") or request.headers.get(TENANT_HEADER),
                smeta.get("tier") or request.headers.get(TIER_HEADER),
            ):
                resp = await gateway.predict(msg, _bearer(request))
        except AuthError as e:
            return _error_response(str(e), code=401)
        status = 200 if resp.status is None or resp.status.status == "SUCCESS" else (
            resp.status.code or 500
        )
        if resp.data is not None and not wirelib.frame_eligible(resp):
            # a non-tensor answer (object/ragged payload) can't frame —
            # degrade to JSON; the status code still tells the story
            return _msg_response(resp, status=status)
        parts = wirelib.frame_from_message(resp, response=True,
                                           sidecar=False)
        return web.Response(
            body=wirelib.join_parts(parts), status=status,
            content_type=wirelib.WIRE_CONTENT_TYPE,
        )

    async def feedback(request):
        from seldon_core_tpu.utils.tracing import (
            TRACEPARENT_HEADER,
            parse_traceparent,
            trace_scope,
        )

        try:
            fb = Feedback.from_json(await _payload_text(request))
        except SeldonMessageError as e:
            return _error_response(str(e))
        try:
            # same adoption contract as the predictions route: a caller's
            # traceparent makes the feedback spans join its trace
            with trace_scope(
                parse_traceparent(request.headers.get(TRACEPARENT_HEADER))
            ), maybe_deadline_scope(
                deadline_ms_header(request.headers.get(DEADLINE_HEADER))
            ), qos_scope(
                request.headers.get(TENANT_HEADER),
                request.headers.get(TIER_HEADER),
            ):
                ack = await gateway.send_feedback(fb, _bearer(request))
        except AuthError as e:
            return _error_response(str(e), code=401)
        return _msg_response(ack)

    async def generate_stream(request):
        """SSE token streaming through the ingress: auth + canary pick,
        then relay the engine's event stream (in-process engines stream
        directly; remote engines are proxied chunk-for-chunk)."""
        try:
            payload = await _payload_text(request)
            reg = gateway._resolve(_bearer(request))
        except AuthError as e:
            return _error_response(str(e), code=401)
        except SeldonMessageError as e:
            return _error_response(str(e))
        # QoS admission for streams: same tenant bucket + brownout tier
        # shed as unary predicts (the fair queue governs unary dispatch
        # slots only — a stream holds its slot for its whole lifetime).
        # The scope stays open across the stream so the genserver's tier
        # lane sees the request's tier at admission.
        from seldon_core_tpu.runtime.qos import parse_tier as _parse_tier

        tenant = resolve_tenant(
            request.headers.get(TENANT_HEADER),
            reg.oauth_key if _bearer(request) else None,
        )
        tier = _parse_tier(request.headers.get(TIER_HEADER))
        BROWNOUT.maybe_tick()
        throttled = gateway.tenants.admit(tenant, tier)
        if throttled is not None:
            return _error_response(
                f"{THROTTLE_INFO_PREFIX}: tenant {tenant!r} over its "
                f"{throttled} limit — retry later", code=429,
            )
        if BROWNOUT.sheds_tier(tier):
            RECORDER.record_brownout_shed(tier)
            gateway.tenants.note_shed(tenant)
            return _error_response(
                f"{BROWNOUT_INFO_PREFIX}: {tier!r} tier stream shed at "
                f"brownout stage {BROWNOUT.stage()}", code=503,
            )
        # aiohttp handlers run in their own task: binding the identity
        # task-locally keeps it visible through the whole stream
        # (genserver tier lane) with no scope to unwind
        bind_qos(tenant, tier)
        def _streamable(ep):
            return hasattr(ep.target, "generate_stream") or \
                ep.base_url is not None

        # streams stay on TCP (the relay lane is unary-only); the replica
        # pick still applies so streams balance across the set too.  A
        # mixed set may contain uds-only replicas (unary hot path only):
        # the eligibility filter keeps the p2c pool — and the pick
        # metrics — on endpoints that can actually serve the stream
        _, rs, endpoint, _decision = gateway._pick_engine(
            reg, eligible=_streamable
        )
        if not _streamable(endpoint):
            # only reachable on the no-decision paths (kill switch /
            # single endpoint), where pick() bypasses the filter and
            # records no pick metrics.  Re-home by SCORE, not raw
            # inflight — a breaker-open replica idles at inflight 0 and
            # would otherwise catch every re-homed stream
            capable = [ep for ep in rs.endpoints if _streamable(ep)]
            if not capable:
                return _error_response(
                    "streaming requires a TCP endpoint (every replica "
                    "is uds-only)", code=503,
                )
            now = time.monotonic()
            endpoint = min(
                capable, key=lambda ep: ep.score(now, rs.stale_after_s)
            )
        engine = endpoint.target
        if not hasattr(engine, "generate_stream"):
            engine = endpoint.base_url
        resp = web.StreamResponse(
            status=200,
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache"},
        )
        import json as _json

        # a live stream counts as load for the whole time it runs (or
        # p2c stacks unary traffic onto a stream-saturated replica), but
        # contributes no EWMA sample — stream wall time isn't comparable
        # to a unary latency
        track = replicas_enabled()
        if track:
            endpoint.begin(batcher=False)
        try:
            if hasattr(engine, "generate_stream"):  # in-process engine
                try:
                    text, chunk = engine.prepare_stream_request(payload)
                except SeldonMessageError as e:
                    return _error_response(str(e))
                agen = engine.generate_stream(text, chunk=chunk)
                # prime before the 200: genserver admission sheds raise
                # on the first __anext__ and must answer a typed
                # retryable 503, not an in-band frame on a 200 (same
                # contract as the engine REST lane)
                first = None
                try:
                    first = await agen.__anext__()
                except StopAsyncIteration:
                    pass
                except SeldonMessageError as e:
                    await agen.aclose()
                    return _error_response(str(e), code=e.http_code)
                await resp.prepare(request)
                try:
                    if first is not None:
                        await resp.write(
                            b"data: " + first.encode() + b"\n\n"
                        )
                    async for event in agen:
                        await resp.write(
                            b"data: " + event.encode() + b"\n\n"
                        )
                except Exception as e:  # mid-stream: in-band terminal
                    # event, same SSE failure contract as the engine lane
                    await resp.write(
                        b'data: {"done": true, "error": %s}\n\n'
                        % _json.dumps(str(e)).encode()
                    )
                finally:
                    await agen.aclose()
                await resp.write_eof()
                return resp
            # remote engine: proxy the upstream SSE stream.  With
            # federation on, the gateway parses the events it forwards —
            # accumulating each row's emitted tokens — so a mid-stream
            # engine death RE-HOMES the stream to a peer replica: the
            # peer re-prefills prompt + emitted-so-far (the genserver's
            # preempt/recompute contract) and decoding resumes where it
            # broke instead of 502ing the client
            import aiohttp

            from seldon_core_tpu.gateway.federation import (
                federation_enabled as _fed_on,
            )

            prompt = None
            doc0 = None
            max_new0 = None
            if _fed_on():
                try:
                    doc0 = _json.loads(payload)
                    arr = SeldonMessage.from_json(payload).data.array
                    prompt = np.asarray(arr, dtype=np.float64)
                    if prompt.ndim < 2:
                        prompt = prompt.reshape(1, -1)
                    if isinstance(doc0, dict) and \
                            doc0.get("max_new") is not None:
                        max_new0 = int(doc0["max_new"])
                except Exception:
                    # unparseable payload: no resume — plain proxy below
                    prompt = None
            if prompt is None:
                # resume unavailable (kill switch / non-tensor payload):
                # the pre-federation raw byte proxy, bit-for-bit
                try:
                    async with gateway._get_session().post(
                        str(engine) + "/api/v0.1/generate/stream",
                        data=payload,
                        # tenant/tier ride upstream so the remote
                        # engine's genserver schedules the stream on the
                        # right lane
                        headers={TENANT_HEADER: tenant, TIER_HEADER: tier},
                        timeout=aiohttp.ClientTimeout(
                            total=None, sock_connect=20
                        ),
                    ) as upstream:
                        if upstream.status != 200:
                            return _error_response(
                                await upstream.text(), code=upstream.status
                            )
                        await resp.prepare(request)
                        async for chunk_bytes in upstream.content.iter_any():
                            await resp.write(chunk_bytes)
                except (aiohttp.ClientError, asyncio.TimeoutError) as e:
                    if not resp.prepared:
                        return _error_response(
                            f"engine unreachable: {e}", code=503
                        )
                    # upstream broke mid-stream: emit a terminal error
                    # event — the SSE contract's in-band failure channel
                    await resp.write(
                        b'data: {"done": true, "error": %s}\n\n'
                        % _json.dumps(str(e)).encode()
                    )
                await resp.write_eof()
                return resp

            emitted: list = []  # [B, <=chunk] arrays, in emit order
            done_forwarded = False

            def _note_event(event: bytes) -> None:
                """Account one SSE event about to be forwarded: stash
                its token columns for a possible re-prefill, notice the
                terminal frame.  Unparseable events forward untouched."""
                nonlocal done_forwarded
                _, _, body = event.partition(b"data:")
                try:
                    obj = _json.loads(body)
                except ValueError:
                    return
                if not isinstance(obj, dict):
                    return
                if obj.get("done"):
                    done_forwarded = True
                    return
                toks = obj.get("tokens")
                if toks:
                    emitted.append(np.asarray(toks, dtype=np.float64))

            def _resume_payload() -> str:
                """The re-prefill request: prompt + every token already
                forwarded becomes the new prompt, and the token budget
                shrinks by what was served — the peer continues the
                SAME generation, it doesn't start a fresh one."""
                doc = dict(doc0) if isinstance(doc0, dict) else {}
                new_prompt = (
                    np.concatenate([prompt] + emitted, axis=1)
                    if emitted else prompt
                )
                doc["data"] = {"ndarray": new_prompt.tolist()}
                if max_new0 is not None:
                    served = sum(a.shape[1] for a in emitted)
                    doc["max_new"] = max(max_new0 - served, 1)
                return _json.dumps(doc)

            def _stream_peer(exclude):
                """Lowest-score remote streamable peer outside
                ``exclude`` — the re-home target (None = give up)."""
                capable = [
                    ep for ep in rs.endpoints
                    if ep not in exclude and ep.base_url is not None
                    and _streamable(ep) and _not_decode(ep)
                ]
                if not capable:
                    return None
                now = time.monotonic()
                return min(
                    capable,
                    key=lambda ep: ep.score(now, rs.stale_after_s),
                )

            attempts = 0
            failed_eps: list = []
            body = payload
            upstream_url = str(engine)
            while True:
                buf = b""
                try:
                    async with gateway._get_session().post(
                        upstream_url + "/api/v0.1/generate/stream",
                        data=body,
                        headers={TENANT_HEADER: tenant, TIER_HEADER: tier},
                        timeout=aiohttp.ClientTimeout(
                            total=None, sock_connect=20
                        ),
                    ) as upstream:
                        if upstream.status != 200:
                            if not resp.prepared and attempts == 0:
                                return _error_response(
                                    await upstream.text(),
                                    code=upstream.status,
                                )
                            raise RuntimeError(
                                f"upstream answered {upstream.status}"
                            )
                        if not resp.prepared:
                            await resp.prepare(request)
                        async for chunk_bytes in \
                                upstream.content.iter_any():
                            buf += chunk_bytes
                            # forward COMPLETE events only: a half-event
                            # from a dying engine must not reach the
                            # client (the resumed peer re-emits those
                            # tokens and they would double)
                            while b"\n\n" in buf:
                                event, _, buf = buf.partition(b"\n\n")
                                _note_event(event)
                                await resp.write(event + b"\n\n")
                    if not done_forwarded:
                        raise RuntimeError(
                            "upstream ended without a terminal event"
                        )
                    break
                except (aiohttp.ClientError, asyncio.TimeoutError,
                        RuntimeError) as e:
                    if done_forwarded:
                        break  # the stream had already finished cleanly
                    attempts += 1
                    peer = None
                    if attempts <= 2:
                        failed_eps.append(endpoint)
                        peer = _stream_peer(failed_eps)
                    if peer is None:
                        if not resp.prepared:
                            return _error_response(
                                f"engine unreachable: {e}", code=503
                            )
                        await resp.write(
                            b'data: {"done": true, "error": %s}\n\n'
                            % _json.dumps(str(e)).encode()
                        )
                        break
                    # re-home: the load accounting moves with the stream
                    if track:
                        endpoint.release()
                        peer.begin(batcher=False)
                    endpoint = peer
                    upstream_url = str(peer.base_url)
                    body = _resume_payload()
                    gateway.failovers["stream"] = (
                        gateway.failovers.get("stream", 0) + 1)
                    RECORDER.record_failover("stream")
                    # the stream lane opens no request span, so the
                    # re-home lands as a traceless (synthetic)
                    # postmortem exemplar rather than a trace join
                    try:
                        from seldon_core_tpu.utils.postmortem import (
                            POSTMORTEM)
                        POSTMORTEM.note(
                            "", "rehome", lane="stream",
                            deployment=reg.deployment_id,
                            attempts=attempts, error=str(e)[:200],
                        )
                    except Exception:  # noqa: BLE001
                        pass
            await resp.write_eof()
            return resp
        finally:
            if track:
                endpoint.release()

    async def ping(_):
        return web.Response(text="pong")

    async def ready(_):
        # readiness = a registered routing table (an empty gateway serves
        # nothing useful; the bundle's probe gates the Service on this) —
        # regardless of auth mode: an open gateway with no deployments can
        # still only 404.  No startup flap: gateway_main registers spec_dir
        # files BEFORE binding this server, so a red probe means the spec
        # source is genuinely empty, and readiness (not liveness) failing
        # just keeps the Service from routing here — the intended gate
        if gateway.store.deployments():
            return web.Response(text="ready")
        return web.Response(text="no deployments registered", status=503)

    async def prometheus(_):
        return web.Response(
            body=gateway.metrics.exposition(),
            headers={"Content-Type": CONTENT_TYPE_LATEST},
        )

    async def stats(_):
        return web.json_response(gateway.stats())

    async def shadow(_):
        # the shadow mirror's full divergence table: per-deployment
        # config, mirrored/capped counts, disagreement percentiles,
        # latency deltas, error deltas (gateway/shadow.py)
        return web.json_response(gateway.shadow.document())

    async def rollouts(_):
        # rollout status surface — present when a RolloutController
        # (operator/rollouts.py) is attached to this gateway
        if gateway.rollouts is None:
            return web.json_response(
                {"error": "no rollout controller attached"}, status=404
            )
        return web.json_response(gateway.rollouts.document())

    async def quality(_):
        # the process-global quality observatory (shared with in-process
        # engines): drift/feedback/SLO — including the per-tenant SLO
        # rings the gateway's predict accounting feeds
        from seldon_core_tpu.utils.quality import QUALITY

        return web.json_response(QUALITY.document())

    async def overhead(_):
        # the ingress hop writes fused telemetry records too (its request
        # spans route through the per-thread ring): the gateway's
        # /overhead page reports this process's framework-time budget
        return web.json_response({
            "gateway": {"deployments": gateway.store.deployments()},
            **SPINE.overhead_document(),
        })

    # -- the mesh-wide observability plane (gateway/fleet.py) ----------

    async def trace(request):
        # federated trace assembly: the named trace/puid fans out to
        # every registered replica + relay peer and answers ONE merged
        # causal tree; SELDON_TPU_FLEET=0 answers local spans only
        from seldon_core_tpu.gateway.fleet import (
            federated_trace_document,
        )

        doc = await federated_trace_document(
            gateway,
            trace_id=request.query.get("trace_id", ""),
            puid=request.query.get("puid", ""),
            limit=int(request.query.get("limit", "100") or 100),
        )
        return web.json_response(doc)

    async def trace_export(request):
        # the merged tree as Perfetto trace JSON, one process track per
        # participant (replica/role)
        from seldon_core_tpu.gateway.fleet import (
            federated_export_document,
        )

        doc = await federated_export_document(
            gateway,
            trace_id=request.query.get("trace_id", ""),
            puid=request.query.get("puid", ""),
            limit=int(request.query.get("limit", "1000") or 1000),
        )
        return web.json_response(doc)

    async def fleet(request):
        # per-deployment rollups of every replica's /stats + /perf +
        # /quality, with per-replica outlier deltas vs the set median.
        # With federation live the view fans out to every sibling
        # gateway replica too (?local=1 stops the recursion): one GET
        # answers for the whole gateway tier, whichever replica the
        # load balancer happened to route it to
        import aiohttp

        from seldon_core_tpu.gateway.fleet import fleet_document

        doc = await fleet_document(gateway)
        fed = gateway.federation
        if (fed is not None and fed.enabled
                and request.query.get("local") != "1"):
            doc["replica_id"] = fed.replica_id
            peer_docs = {}
            for rid, url in fed.peers():
                try:
                    async with gateway._get_session().get(
                        url.rstrip("/") + "/fleet?local=1",
                        timeout=aiohttp.ClientTimeout(total=2.0),
                    ) as r:
                        peer_docs[rid] = await r.json(content_type=None)
                except Exception as e:  # noqa: BLE001 — a dead peer is
                    # data, not a reason to fail the whole view
                    peer_docs[rid] = {"error": f"{type(e).__name__}: {e}"}
            if peer_docs:
                doc["gateway_peers"] = peer_docs
        return web.json_response(doc)

    async def corpus(_):
        # fleet-wide perf corpus: every replica's durable per-key
        # sketches merged into one training substrate (gateway/fleet.py)
        from seldon_core_tpu.gateway.fleet import corpus_document

        return web.json_response(await corpus_document(gateway))

    async def costs(_):
        # fleet-wide resource attribution: every replica's cost ledger
        # merged into one who-consumes-what table (gateway/fleet.py)
        from seldon_core_tpu.gateway.fleet import costs_document

        return web.json_response(await costs_document(gateway))

    async def postmortems(request):
        # worst-of-fleet exemplars: the gateway's own kept traces plus
        # every replica's, merged worst-first (gateway/fleet.py); ?puid=
        # chases a single exemplar across the fleet
        from seldon_core_tpu.gateway.fleet import postmortems_document

        return web.json_response(await postmortems_document(
            gateway, puid=request.query.get("puid", "")))

    async def profile_start(request):
        from seldon_core_tpu.gateway.fleet import profile_start as start

        try:
            body = await request.json()
        except Exception:  # noqa: BLE001 - empty body = defaults
            body = {}
        if not isinstance(body, dict):
            body = {}
        status, doc = await start(
            gateway,
            deployment=body.get("deployment"),
            duration_s=body.get("duration_s"),
        )
        return web.json_response(doc, status=status)

    async def profile_stop(_):
        from seldon_core_tpu.gateway.fleet import profile_stop as stop

        status, doc = await stop(gateway)
        return web.json_response(doc, status=status)

    async def profile_get(_):
        from seldon_core_tpu.gateway.fleet import profile_status

        return web.json_response(profile_status(gateway))

    app.router.add_post("/oauth/token", token)
    app.router.add_post("/api/v0.1/predictions", predictions)
    app.router.add_post("/api/v0.1/feedback", feedback)
    app.router.add_post("/api/v0.1/generate/stream", generate_stream)
    app.router.add_get("/ping", ping)
    app.router.add_get("/ready", ready)
    app.router.add_get("/prometheus", prometheus)
    app.router.add_get("/stats", stats)
    app.router.add_get("/shadow", shadow)
    app.router.add_get("/rollouts", rollouts)
    app.router.add_get("/quality", quality)
    app.router.add_get("/overhead", overhead)
    app.router.add_get("/trace", trace)
    app.router.add_get("/trace/export", trace_export)
    app.router.add_get("/fleet", fleet)
    app.router.add_get("/corpus", corpus)
    app.router.add_get("/costs", costs)
    app.router.add_get("/postmortems", postmortems)
    app.router.add_get("/profile", profile_get)
    app.router.add_post("/profile/start", profile_start)
    app.router.add_post("/profile/stop", profile_stop)

    async def _cleanup(_app):
        await gateway.close()  # pooled upstream session/connector

    app.on_cleanup.append(_cleanup)
    return app
