"""Mesh-wide observability plane — federated trace assembly, fleet
aggregation, coordinated profiling windows.

PR 12 made the data plane a true multi-process mesh (gateway → prefill
engine → OP_KVSTREAM handoff → decode engine; replica sets and sharded
node engines spread one predict over N processes) while every
observability surface stayed strictly per-process.  This module is the
single pane over the sheet of workers:

* **Federated trace assembly** — ``GET /trace?trace_id=`` on the
  gateway fans out to every replica the balancer's endpoint registry
  knows (HTTP for URL endpoints, the relay ``OP_TRACE`` frame for
  uds-only replicas and relay-spec decode peers, a direct call for
  in-process engines), merges the returned spans into ONE causal tree,
  and recomputes the critical path across process boundaries.  A
  subtree a remote ring already evicted answers a PARTIAL tree with an
  explicit marker and a per-source ``missing`` list — never a silent
  empty.  ``GET /trace/export`` renders the same merge as Perfetto
  trace JSON with one process track per participant (replica/role).
* **Fleet aggregation** — ``GET /fleet`` merges every replica's
  ``/stats`` + ``/perf`` + ``/quality`` into per-deployment rollups
  with per-replica deltas against the set median (MFU, dispatch p99,
  drift, free KV blocks, handoff outcomes) and per-replica staleness.
  The raw documents ride the EXISTING ``SELDON_TPU_GW_SCRAPE_S``
  scrape pass (gateway/balancer.py ``scrape_once`` stashes them next
  to the health fields it already parses — zero new polling loops);
  the ``seldon_tpu_fleet_*`` outlier gauges refresh on that same pass
  so one alert pages on "replica 3 is 2× slower than its siblings".
* **Coordinated profiling windows** — ``POST /profile/start`` opens a
  bounded ``jax.profiler`` window (utils/tracing.py
  ``profile_window_start``) on every engine of a deployment
  *simultaneously* and collects the artifact paths into one manifest;
  overlapping windows are refused (409), both at the gateway and by
  each engine's process-local profile lock.

Everything here is READ-PATH-ONLY: assembly, merging and outlier math
run at query time (or on the existing scrape tick), never on the
request hot path — ``make overhead-gate`` must not move.
``SELDON_TPU_FLEET=0`` kills federation: the gateway answers every
surface from local data only, bit-for-bit the PR-12 behaviour.
"""

from __future__ import annotations

import asyncio
import os
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

__all__ = [
    "fleet_enabled",
    "fleet_outlier_x",
    "FleetSource",
    "gather_sources",
    "federated_trace_document",
    "federated_export_document",
    "fleet_document",
    "corpus_document",
    "costs_document",
    "postmortems_document",
    "refresh_outlier_gauges",
    "extract_replica_row",
    "compute_outliers",
    "profile_start",
    "profile_stop",
    "profile_status",
]


def fleet_enabled() -> bool:
    """Kill switch: ``SELDON_TPU_FLEET=0`` disables every federated
    fan-out — gateway surfaces answer from local data only."""
    return os.environ.get("SELDON_TPU_FLEET", "1") != "0"


def fleet_outlier_x() -> float:
    """``SELDON_TPU_FLEET_OUTLIER_X`` — the worse-than-median ratio at
    which a replica is flagged an outlier on ``/fleet`` (default 1.5;
    the SeldonTPUReplicaOutlier alert pages at 2.0 sustained)."""
    try:
        return float(os.environ.get("SELDON_TPU_FLEET_OUTLIER_X", "") or 1.5)
    except ValueError:
        return 1.5


def _fleet_timeout_s() -> float:
    try:
        return float(os.environ.get("SELDON_TPU_FLEET_TIMEOUT_S", "") or 2.0)
    except ValueError:
        return 2.0


def _extra_peers() -> List[str]:
    """``SELDON_TPU_FLEET_PEERS`` — comma-separated extra federation
    targets outside the balancer registry (sharded node engines, decode
    peers registered nowhere): ``http://host:port``, ``uds:/path`` or
    ``tcp:host:port`` specs."""
    raw = os.environ.get("SELDON_TPU_FLEET_PEERS", "")
    return [p.strip() for p in raw.split(",") if p.strip()]


@dataclass
class FleetSource:
    """One federation target: how the gateway reaches a process that may
    hold spans / stats of a request that crossed the mesh."""

    name: str                 # replica endpoint name (or peer spec)
    set_name: str             # deployment/predictor ("_peers" for extras)
    role: str = "unified"
    lane: str = "http"        # "inprocess" | "http" | "relay"
    target: Any = None        # EngineService (inprocess lane)
    base_url: Optional[str] = None
    relay_spec: Optional[str] = None   # "uds:/path" | "tcp:host:port"
    endpoint: Any = None      # the balancer ReplicaEndpoint, if any


def gather_sources(gateway, deployment: Optional[str] = None
                   ) -> List[FleetSource]:
    """Every distinct process the gateway can federate over: the replica
    endpoints of every registered deployment (built through the same
    cached replica sets the data plane uses), the decode peers of
    in-process prefill coordinators, and ``SELDON_TPU_FLEET_PEERS``
    extras.  Deduplicated by reachable address/identity."""
    sources: List[FleetSource] = []
    seen: set = set()

    def add(src: FleetSource, key) -> None:
        if key in seen:
            return
        seen.add(key)
        sources.append(src)

    for reg in list(gateway.store._by_key.values()):
        if deployment is not None and reg.deployment_id != deployment:
            continue
        for pred_name, _w, engine in reg.engines:
            rs = gateway._replica_set(reg, pred_name, engine)
            set_name = f"{reg.deployment_id}/{pred_name}"
            for ep in rs.endpoints:
                if hasattr(ep.target, "predict"):
                    add(FleetSource(
                        name=ep.name, set_name=set_name, role=ep.role,
                        lane="inprocess", target=ep.target, endpoint=ep,
                    ), ("inprocess", id(ep.target)))
                elif ep.base_url is not None:
                    add(FleetSource(
                        name=ep.name, set_name=set_name, role=ep.role,
                        lane="http", base_url=ep.base_url, endpoint=ep,
                    ), ("http", ep.base_url))
                elif ep.uds_path is not None:
                    add(FleetSource(
                        name=ep.name, set_name=set_name, role=ep.role,
                        lane="relay", relay_spec=f"uds:{ep.uds_path}",
                        endpoint=ep,
                    ), ("relay", ep.uds_path))
            # an in-process prefill engine knows its decode peers by
            # relay spec — they may be registered nowhere else, yet a
            # disaggregated generation's decode spans live there
            for ep in rs.endpoints:
                gs = getattr(ep.target, "genserver", None)
                coord = getattr(gs, "coordinator", None)
                for peer in getattr(coord, "peers", None) or []:
                    add(FleetSource(
                        name=peer, set_name=set_name, role="decode",
                        lane="relay", relay_spec=peer,
                    ), ("relay", peer.split("uds:")[-1]))
    for spec in _extra_peers():
        if spec.startswith("http"):
            add(FleetSource(name=spec, set_name="_peers", lane="http",
                            base_url=spec.rstrip("/")),
                ("http", spec.rstrip("/")))
        else:
            add(FleetSource(name=spec, set_name="_peers", lane="relay",
                            relay_spec=spec),
                ("relay", spec.split("uds:")[-1]))
    return sources


# ---------------------------------------------------------------------------
# Federated trace assembly
# ---------------------------------------------------------------------------


async def _fetch_json(gateway, url: str) -> dict:
    import aiohttp

    timeout = aiohttp.ClientTimeout(total=_fleet_timeout_s())
    async with gateway._get_session().get(url, timeout=timeout) as r:
        if r.status != 200:
            raise RuntimeError(f"HTTP {r.status} from {url}")
        doc = await r.json(content_type=None)
    if not isinstance(doc, dict):
        raise RuntimeError(f"non-object body from {url}")
    return doc


async def _relay_trace(gateway, spec: str, query: dict) -> dict:
    """One OP_TRACE round trip to a relay-only peer.  ``uds:`` specs
    reuse the gateway's pooled relay clients; ``tcp:`` specs dial a
    transient client (read path — connection cost is acceptable and the
    cross-host case is rare)."""
    import json as _json

    from seldon_core_tpu.runtime.udsrelay import (
        OP_TRACE,
        make_relay_client,
    )

    payload = _json.dumps(query).encode()
    transient = None
    if spec.startswith("tcp:"):
        client = transient = make_relay_client(spec)
    else:
        path = spec[len("uds:"):] if spec.startswith("uds:") else spec
        client = gateway._uds_client(path)
    try:
        body, status = await asyncio.wait_for(
            client.call(OP_TRACE, payload), timeout=_fleet_timeout_s())
    finally:
        if transient is not None:
            await transient.close()
    if status != 200:
        raise RuntimeError(
            f"relay trace status {status}: "
            f"{body.decode('utf-8', 'replace')[:200]}")
    doc = _json.loads(body.decode("utf-8", "replace"))
    if not isinstance(doc, dict):
        raise RuntimeError("non-object relay trace body")
    return doc


async def _fetch_source_trace(gateway, src: FleetSource, trace_id: str,
                              puid: str, limit: int) -> List[dict]:
    """One source's span dicts for the query.  In-process engines share
    the gateway's global TRACER — their spans are already in the local
    result, so they contribute nothing new here (the dedup would drop
    them anyway); skipping the call keeps the fan-out lean."""
    if src.lane == "inprocess":
        return []
    query = {"trace_id": trace_id, "puid": puid, "limit": limit}
    if src.lane == "http":
        from urllib.parse import urlencode

        url = src.base_url + "/trace?" + urlencode(
            {k: v for k, v in query.items() if v})
        doc = await _fetch_json(gateway, url)
    else:
        doc = await _relay_trace(gateway, src.relay_spec, query)
    spans = doc.get("spans")
    return spans if isinstance(spans, list) else []


async def _federated_spans(gateway, trace_id: str, puid: str, limit: int):
    """(merged Span list, per-source report, span-id -> origin label).
    Local spans first, then every remote source concurrently; dedup by
    span id (spans without ids fall back to a content key).  The origin
    map remembers WHICH process actually returned each span so the
    Perfetto export can put it on that process's track."""
    from seldon_core_tpu.utils.tracing import (
        TRACER,
        _select_spans,
        span_from_json_dict,
    )

    local = _select_spans(TRACER, puid=puid, trace_id=trace_id,
                          limit=limit)
    merged: Dict[Any, Any] = {}
    origin: Dict[Any, str] = {}

    def key_of(s) -> Any:
        return s.span_id or (s.puid, s.name, s.kind, round(s.start_s, 6),
                             round(s.duration_ms, 3))

    for s in local:
        merged[key_of(s)] = s
    reports: List[dict] = [{
        "source": "gateway", "lane": "local", "role": "gateway",
        "spans": len(local), "error": None,
    }]
    if fleet_enabled():
        sources = gather_sources(gateway)

        async def one(src: FleetSource):
            try:
                dicts = await _fetch_source_trace(
                    gateway, src, trace_id, puid, limit)
                return src, [span_from_json_dict(d) for d in dicts], None
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 - reported per source
                return src, [], f"{type(e).__name__}: {e}"

        for src, spans, error in await asyncio.gather(
                *(one(s) for s in sources)):
            fresh = 0
            for s in spans:
                k = key_of(s)
                if k not in merged:
                    merged[k] = s
                    origin[k] = f"{src.name} ({src.role})"
                    fresh += 1
            reports.append({
                "source": src.name, "lane": src.lane, "role": src.role,
                "set": src.set_name, "spans": fresh, "error": error,
            })
    return (sorted(merged.values(), key=lambda s: s.start_s), reports,
            origin)


async def federated_trace_document(gateway, trace_id: str = "",
                                   puid: str = "",
                                   limit: int = 100) -> dict:
    """The gateway's ``GET /trace`` body: ONE assembled tree across
    every process a request touched, with the critical path recomputed
    over the merged span set.  Without a named query it reports the
    local recent spans only (fan-out for "everything recent" would be
    all cost, no join key)."""
    from seldon_core_tpu.utils.tracing import (
        TRACER,
        assembly_fields,
        trace_document,
    )

    if not (trace_id or puid):
        doc = trace_document(TRACER, limit=limit)
        doc["federated"] = False
        return doc
    spans, reports, _origin = await _federated_spans(
        gateway, trace_id, puid, limit)
    doc: Dict[str, Any] = {
        "enabled": TRACER.enabled,
        "sample": TRACER.sample,
        "federated": fleet_enabled(),
        "sources": reports,
        "spans": [s.to_json_dict() for s in spans],
    }
    # the assembly block (tree / critical path / phases / partial
    # markers) is the SAME code the engine-local /trace serves — the two
    # surfaces cannot drift (utils/tracing.py assembly_fields)
    doc.update(assembly_fields(spans))
    # a source that errored (or an engine whose ring evicted the
    # subtree) makes the result partial even when the local tree looks
    # self-consistent — the operator must know the view may be narrow
    source_missing = [
        {"source": r["source"], "reason": r["error"]}
        for r in reports if r.get("error")
    ]
    if source_missing:
        doc["partial"] = True
        doc["missing"] = list(doc["missing"]) + source_missing
    return doc


async def federated_export_document(gateway, trace_id: str = "",
                                    puid: str = "",
                                    limit: int = 1000) -> dict:
    """The gateway's ``GET /trace/export`` body: Perfetto trace JSON of
    the merged tree with ONE PROCESS TRACK PER PARTICIPANT — the
    gateway's spans on pid 0, each replica's on its own pid, named
    ``replica (role)`` so the federated tree renders legibly."""
    from seldon_core_tpu.utils.tracing import chrome_trace

    spans, reports, origin = await _federated_spans(
        gateway, trace_id, puid, limit)
    if not spans:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    base = min(s.start_s for s in spans)
    # per-process track assignment: a span a REMOTE source returned
    # renders on that source's track (the merge recorded its origin).
    # Locally-held spans need a heuristic — co-located engines share
    # the gateway's tracer, so the recorder's identity only survives in
    # what the scheduler stamped: its role attr on prefill/decode legs,
    # kv_import on the decode side, kv_handoff on the prefill side.
    by_source: Dict[str, List] = {}
    for s in spans:
        key = s.span_id or (s.puid, s.name, s.kind,
                            round(s.start_s, 6), round(s.duration_ms, 3))
        label = origin.get(key)
        if label is None:
            label = "gateway (local)"
            role = (s.attrs.get("role")
                    if isinstance(s.attrs, dict) else None)
            if s.kind in ("kv_import",) or (s.method == "decode"
                                            and role == "decode"):
                label = "decode replica"
            elif s.method == "prefill" or (role == "prefill"):
                label = "prefill replica"
            elif s.kind == "kv_handoff":
                label = "prefill replica"
        by_source.setdefault(label, []).append(s)
    events: List[dict] = []
    for pid, (label, group) in enumerate(sorted(by_source.items())):
        doc = chrome_trace(group, process_name=label, pid=pid,
                           base_s=base)
        events.extend(doc["traceEvents"])
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "sources": reports,
    }


# ---------------------------------------------------------------------------
# Fleet aggregation (GET /fleet)
# ---------------------------------------------------------------------------

#: finite ceiling on a worse-than-median ratio (a zero-MFU replica vs a
#: healthy median would otherwise be infinitely worse — unrenderable in
#: strict JSON and invisible to a max() over finite gauge values)
_RATIO_CAP = 1e6

#: outlier metrics: name -> direction ("high" = higher is worse)
_OUTLIER_METRICS = {
    "dispatch_p99_ms": "high",
    "ewma_ms": "high",
    "drift_max": "high",
    "mfu": "low",
    "free_kv_blocks": "low",
}


def _num(v) -> Optional[float]:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if f == f else None  # NaN -> None


def extract_replica_row(stats: Optional[dict], perf: Optional[dict],
                        quality: Optional[dict]) -> Dict[str, Any]:
    """Compact per-replica metrics off the three per-process documents.
    Defensive throughout: a replica mid-deploy may serve partial docs,
    and a missing figure must read as absent, not zero (zero would make
    it the 'best' replica on a lower-is-worse metric)."""
    row: Dict[str, Any] = {}
    stats = stats or {}
    perf = perf or {}
    quality = quality or {}
    tel = stats.get("telemetry") or {}
    batch = tel.get("batch") or {}
    row["inflight"] = _num(batch.get("inflight_dispatches"))
    req_lat = tel.get("request_latency_s") or {}
    counts = [
        (_num(v.get("count")), _num(v.get("p99")))
        for v in req_lat.values() if isinstance(v, dict)
    ]
    if counts:
        row["requests"] = sum(c for c, _ in counts if c)
        p99s = [p for _, p in counts if p]
        if p99s:
            row["request_p99_ms"] = round(max(p99s) * 1e3, 3)
    # dispatch latency + MFU off the /perf executable table
    execs = perf.get("executables") or []
    p99s, mfus = [], []
    weighted_p50, calls_total = 0.0, 0
    for e in execs:
        if not isinstance(e, dict):
            continue
        lat = e.get("latency_ms")
        if not isinstance(lat, dict):
            lat = {}
        calls = _num(e.get("calls")) or 0
        p99 = _num(lat.get("p99"))
        p50 = _num(lat.get("p50"))
        if p99 is not None:
            p99s.append(p99)
        if p50 is not None and calls:
            weighted_p50 += p50 * calls
            calls_total += calls
        mfu = _num(e.get("mfu"))
        if mfu is not None:
            mfus.append(mfu)
    if p99s:
        row["dispatch_p99_ms"] = round(max(p99s), 3)
    if calls_total:
        row["dispatch_p50_ms"] = round(weighted_p50 / calls_total, 3)
    if mfus:
        row["mfu"] = max(mfus)
    # drift: the worst live PSI/KS-ish score over nodes (either the
    # /quality document's rows or the compact /stats walk)
    drift_vals: List[float] = []

    def _drift_scan(node) -> None:
        if not isinstance(node, dict):
            return
        scores = node.get("scores")
        items = list(node.items()) + (
            list(scores.items()) if isinstance(scores, dict) else [])
        for k, v in items:
            if isinstance(k, str) and ("psi" in k or "drift" in k):
                f = _num(v)
                if f is not None:
                    drift_vals.append(f)

    nodes = quality.get("nodes")
    if isinstance(nodes, list):
        for n in nodes:
            _drift_scan(n)
    qsnap = stats.get("quality") or {}
    if isinstance(qsnap.get("nodes"), dict):
        for n in qsnap["nodes"].values():
            _drift_scan(n)
    if drift_vals:
        row["drift_max"] = round(max(drift_vals), 6)
    slo = (quality.get("slo") or {})
    if isinstance(slo, dict) and slo.get("burn_rates"):
        row["slo_burn"] = slo["burn_rates"]
    # generation lane: pool headroom, role, handoff flow
    gs = stats.get("genserver")
    if isinstance(gs, dict):
        row["role"] = gs.get("role")
        kvb = gs.get("kv_blocks") or {}
        total, used = _num(kvb.get("total")), _num(kvb.get("used"))
        if total is not None and used is not None:
            row["free_kv_blocks"] = int(total - used)
        disagg = gs.get("disagg")
        if isinstance(disagg, dict):
            row["handoffs"] = disagg.get("handoffs")
            row["handoff_ms_p50"] = disagg.get("handoff_ms_p50")
            row["chain_ewma_ms"] = disagg.get("chain_ewma_ms")
        imports = gs.get("imports")
        if isinstance(imports, dict):
            row["imports"] = imports
    return {k: v for k, v in row.items() if v is not None}


def compute_outliers(rows: Dict[str, Dict[str, Any]],
                     threshold: Optional[float] = None) -> dict:
    """Per-set outlier math: for each metric, the set median and each
    replica's worse-than-median ratio (>=1 always; direction folded in,
    so ``ratio=2.0`` uniformly reads "2x worse than the median
    sibling").  Returns ``{"median": {...}, "ratios": {replica:
    {metric: ratio}}, "outliers": [...]}``."""
    threshold = threshold if threshold is not None else fleet_outlier_x()
    medians: Dict[str, float] = {}
    ratios: Dict[str, Dict[str, float]] = {}
    outliers: List[dict] = []
    for metric, direction in _OUTLIER_METRICS.items():
        vals = sorted(
            v for v in (_num(r.get(metric)) for r in rows.values())
            if v is not None
        )
        if len(vals) < 2:
            continue
        n = len(vals)
        # true median (middle-two average for even n): with 2 replicas
        # an upper-middle convention would BE the outlier's own value
        # and the sick replica could never flag against itself
        median = (vals[n // 2] if n % 2
                  else (vals[n // 2 - 1] + vals[n // 2]) / 2.0)
        medians[metric] = round(median, 6)
        for replica, row in rows.items():
            v = _num(row.get(metric))
            if v is None:
                continue
            if direction == "high":
                ratio = v / median if median > 0 else (
                    1.0 if v <= 0 else _RATIO_CAP)
            else:
                ratio = median / v if v > 0 else (
                    1.0 if median <= 0 else _RATIO_CAP)
            # capped FINITE: an infinite ratio would serialize as the
            # bare `Infinity` literal (breaking strict JSON consumers of
            # /fleet) and fall out of the gauge max — the most extreme
            # outlier would be exactly the one that never pages
            ratio = round(min(max(ratio, 1.0), _RATIO_CAP), 3)
            ratios.setdefault(replica, {})[metric] = ratio
            if ratio >= threshold:
                outliers.append({
                    "replica": replica, "metric": metric,
                    "value": v, "median": median, "ratio": ratio,
                })
    outliers.sort(key=lambda o: -o["ratio"])
    return {"median": medians, "ratios": ratios, "outliers": outliers}


def _source_docs_cached(src: FleetSource) -> "tuple[Optional[dict], Optional[dict], Optional[dict], Optional[float]]":
    """(stats, perf, quality, age_s) from the scrape-stashed docs of a
    URL endpoint (balancer.scrape_once), or None when never scraped."""
    ep = src.endpoint
    docs = getattr(ep, "fleet_docs", None) if ep is not None else None
    if not docs:
        return None, None, None, None
    age = time.monotonic() - docs.get("ts", 0.0)
    return docs.get("stats"), docs.get("perf"), docs.get("quality"), age


async def _source_docs(gateway, src: FleetSource, max_age_s: float
                       ) -> "tuple[dict, float, Optional[str]]":
    """(row, staleness_s, error) for one source: in-process documents
    are assembled directly; URL endpoints serve from the scrape-stashed
    docs when fresh enough and are fetched on demand otherwise (query-
    time cost, never hot-path); relay-only endpoints have no document
    surface and report so."""
    if src.lane == "inprocess":
        t = src.target
        stats = t.stats() if hasattr(t, "stats") else None
        perf = (t.perf_document()
                if hasattr(t, "perf_document") else None)
        quality = (t.quality_document()
                   if hasattr(t, "quality_document") else None)
        row = extract_replica_row(stats, perf, quality)
        # co-located engines share the process-global observatories, so
        # perf/quality figures are identical across in-process rows —
        # flagged so the operator reads the per-replica distinction off
        # the gateway-side figures (ewma/picks/failures), which ARE
        # per-endpoint
        row["shared_process"] = True
        ep = src.endpoint
        if ep is not None:
            row.setdefault("ewma_ms", _num(ep.ewma_ms))
            row["picks"] = ep.picks
            row["failures"] = ep.failures
        return row, 0.0, None
    if src.lane == "relay":
        return {}, float("inf"), (
            "no document surface on the relay lane (uds-only endpoint "
            "— register an http://..+uds:/ spec for fleet rollups)")
    # a lapsed store lease (gateway/federation.py heartbeats) means the
    # stashed fleet_docs describe a DEAD process — serving their figures
    # as a live row would hide the death behind week-old numbers.  The
    # row says so explicitly and its staleness is pinned to at least the
    # lease TTL so the staleness gauge reads stale, not fresh
    if getattr(src.endpoint, "lease_state", None) == "dead":
        from seldon_core_tpu.gateway.federation import lease_ttl_s

        _s, _p, _q, age = _source_docs_cached(src)
        return ({"lease": "dead"}, max(age or 0.0, lease_ttl_s()),
                "engine lease lapsed")
    stats, perf, quality, age = _source_docs_cached(src)
    error = None
    if stats is None or age is None or age > max_age_s:
        try:
            stats, perf, quality = await asyncio.gather(
                _fetch_json(gateway, src.base_url + "/stats"),
                _fetch_json(gateway, src.base_url + "/perf"),
                _fetch_json(gateway, src.base_url + "/quality"),
            )
            age = 0.0
        except Exception as e:  # noqa: BLE001 - reported per replica
            error = f"{type(e).__name__}: {e}"
            if age is None:
                return {}, float("inf"), error
    row = extract_replica_row(stats, perf, quality)
    ep = src.endpoint
    if ep is not None:
        row.setdefault("ewma_ms", _num(ep.ewma_ms))
        row["picks"] = ep.picks
        row["failures"] = ep.failures
        if ep.lease_state is not None:
            row["lease"] = ep.lease_state
    return row, age or 0.0, error


async def fleet_document(gateway) -> dict:
    """The ``GET /fleet`` body.  With federation killed
    (``SELDON_TPU_FLEET=0``) only in-process replicas report — local
    data, no fan-out."""
    from seldon_core_tpu.gateway.balancer import scrape_interval_s
    from seldon_core_tpu.utils.telemetry import RECORDER

    enabled = fleet_enabled()
    max_age = 3.0 * scrape_interval_s()
    sources = gather_sources(gateway)
    if not enabled:
        sources = [s for s in sources if s.lane == "inprocess"]
    results = await asyncio.gather(
        *(_source_docs(gateway, s, max_age) for s in sources))
    deployments: Dict[str, Dict[str, Any]] = {}
    for src, (row, staleness, error) in zip(sources, results):
        dep = deployments.setdefault(src.set_name, {"replicas": {}})
        entry = {
            "role": src.role, "lane": src.lane,
            "staleness_s": (None if staleness == float("inf")
                            else round(staleness, 3)),
            **row,
        }
        if error:
            entry["error"] = error
        dep["replicas"][src.name] = entry
    threshold = fleet_outlier_x()
    for set_name, dep in deployments.items():
        rows = {
            name: r for name, r in dep["replicas"].items()
            # a dead-lease row carries no live metrics — feeding its
            # stale figures to the outlier math would skew the median
            if r.get("lease") != "dead"
            and ("error" not in r or r.get("staleness_s") is not None)
        }
        out = compute_outliers(rows, threshold)
        dep.update(out)
        totals: Dict[str, float] = {}
        for r in dep["replicas"].values():
            for k in ("requests", "picks", "failures"):
                v = _num(r.get(k))
                if v is not None:
                    totals[k] = totals.get(k, 0) + v
            for k, v in (r.get("handoffs") or {}).items():
                totals[f"handoffs_{k}"] = (
                    totals.get(f"handoffs_{k}", 0) + (_num(v) or 0))
        dep["totals"] = totals
        # publish the gauges from the same rollup the document shows
        _publish_set_gauges(RECORDER, set_name, dep)
    from seldon_core_tpu.utils.quality import FLEET_BURN

    return {
        "enabled": enabled,
        "outlier_threshold": threshold,
        "scrape_interval_s": scrape_interval_s(),
        # fleet-truth SLO/QoS burn: the aggregate every replica folds
        # from the shared store's burn_deltas (gateway/federation.py)
        "burn": FLEET_BURN.snapshot(),
        "deployments": deployments,
    }


def _publish_set_gauges(recorder, set_name: str, dep: dict) -> None:
    recorder.set_fleet_replicas(set_name, len(dep.get("replicas") or {}))
    for replica, metrics in (dep.get("ratios") or {}).items():
        worst = max(metrics.values(), default=1.0)
        recorder.set_fleet_outlier(set_name, replica, worst)
    for replica, row in (dep.get("replicas") or {}).items():
        st = row.get("staleness_s")
        if st is not None:
            recorder.set_fleet_staleness(set_name, replica, st)


def _merge_corpus_keys(merged: Dict[str, Dict[str, Any]],
                       doc: dict) -> int:
    """Fold one replica's ``/corpus`` key table into the fleet merge:
    quantiles combine as n-weighted means (each replica's sketch already
    summarizes its own sample ring — exact fleet quantiles would need
    the raw walls, which the compact rows deliberately do not carry),
    tier counts sum, recency takes the max."""
    folded = 0
    for row in doc.get("keys") or []:
        if not isinstance(row, dict):
            continue
        key, n = row.get("key"), row.get("n") or 0
        if not key or n <= 0:
            continue
        folded += 1
        ent = merged.get(key)
        if ent is None:
            merged[key] = {**row, "sources": 1}
            continue
        total = ent["n"] + n
        for f in ("p50_ms", "p90_ms", "p99_ms", "spread_ms", "last_ms"):
            a, b = _num(ent.get(f)), _num(row.get(f))
            if a is not None and b is not None:
                ent[f] = round((a * ent["n"] + b * n) / total, 4)
            elif b is not None:
                ent[f] = b
        tiers = dict(ent.get("tiers") or {})
        for t, c in (row.get("tiers") or {}).items():
            tiers[t] = tiers.get(t, 0) + (c or 0)
        ent["tiers"] = tiers
        ent["n"] = total
        ent["last_ts"] = max(_num(ent.get("last_ts")) or 0.0,
                             _num(row.get("last_ts")) or 0.0)
        ent["sources"] += 1
    return folded


async def corpus_document(gateway) -> dict:
    """The gateway's ``GET /corpus`` body: every replica's durable perf
    corpus merged into ONE fleet-wide key table — the training substrate
    for learned cost models (ROADMAP item 4) assembled across the whole
    fleet instead of read one process at a time.  In-process engines
    share the gateway's process-global corpus, so the local document
    covers them; URL replicas are fetched at query time (read path, never
    hot); with ``SELDON_TPU_FLEET=0`` the local document stands alone."""
    from seldon_core_tpu.utils.hotrecord import SPINE
    from seldon_core_tpu.utils.perfcorpus import CORPUS

    SPINE.drain()  # in-process engines' pending dispatches land first
    local = CORPUS.document()
    merged: Dict[str, Dict[str, Any]] = {}
    rows_total = int(local.get("rows_total") or 0)
    reports: List[dict] = [{
        "source": "gateway", "lane": "local",
        "keys": _merge_corpus_keys(merged, local), "error": None,
    }]
    if fleet_enabled():
        sources = [s for s in gather_sources(gateway)
                   if s.lane == "http"]

        async def one(src: FleetSource):
            try:
                doc = await _fetch_json(
                    gateway, src.base_url + "/corpus")
                return src, doc, None
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 - reported per source
                return src, None, f"{type(e).__name__}: {e}"

        for src, doc, error in await asyncio.gather(
                *(one(s) for s in sources)):
            folded = 0
            if doc is not None:
                folded = _merge_corpus_keys(merged, doc)
                rows_total += int(doc.get("rows_total") or 0)
            reports.append({
                "source": src.name, "lane": src.lane, "role": src.role,
                "set": src.set_name, "keys": folded, "error": error,
            })
    keys = sorted(merged.values(), key=lambda r: r["n"], reverse=True)
    return {
        "federated": fleet_enabled(),
        "sources": reports,
        "rows_total": rows_total,
        "key_count": len(keys),
        "keys": keys,
    }


async def costs_document(gateway) -> dict:
    """The gateway's ``GET /costs`` body: every replica's resource
    ledger merged into ONE fleet-wide attribution table (who is
    consuming the fleet — device-seconds, pad tax, KV-block-seconds,
    bytes per tenant x deployment, plus the summed accounting identity
    and capacity block).  In-process engines share the gateway's
    process-global ledger, so the local document covers them; URL
    replicas are fetched at query time (read path, never hot); with
    ``SELDON_TPU_FLEET=0`` the local document stands alone."""
    from seldon_core_tpu.utils.costledger import (
        LEDGER,
        merge_cost_documents,
    )
    from seldon_core_tpu.utils.hotrecord import SPINE

    SPINE.drain()  # in-process engines' pending flush/tick records first
    local = LEDGER.document()
    docs: List[dict] = [local]
    reports: List[dict] = [{
        "source": "gateway", "lane": "local",
        "tenants": len(local.get("tenants") or ()), "error": None,
    }]
    if fleet_enabled():
        sources = [s for s in gather_sources(gateway)
                   if s.lane == "http"]

        async def one(src: FleetSource):
            try:
                doc = await _fetch_json(
                    gateway, src.base_url + "/costs")
                return src, doc, None
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 - reported per source
                return src, None, f"{type(e).__name__}: {e}"

        for src, doc, error in await asyncio.gather(
                *(one(s) for s in sources)):
            if doc is not None:
                docs.append(doc)
            reports.append({
                "source": src.name, "lane": src.lane, "role": src.role,
                "set": src.set_name,
                "tenants": len((doc or {}).get("tenants") or ()),
                "error": error,
            })
    merged = merge_cost_documents(docs)
    merged["federated"] = fleet_enabled()
    merged["sources"] = reports
    merged["enabled"] = bool(local.get("enabled"))
    return merged


async def postmortems_document(gateway, puid: str = "") -> dict:
    """The gateway's ``GET /postmortems`` body: worst-of-fleet kept
    exemplars.  The summary view merges the gateway's own recorder
    (which also covers in-process engines — they share the
    process-global singleton) with the replica summaries the health
    scrape already stashed next to ``/perf`` and ``/quality``
    (``ep.fleet_docs`` — zero new polling loops).  ``?puid=`` chases ONE
    exemplar: the local recorder first, then each HTTP replica at query
    time (read path, never hot).  With ``SELDON_TPU_FLEET=0`` the local
    document stands alone."""
    from seldon_core_tpu.utils.hotrecord import SPINE
    from seldon_core_tpu.utils.postmortem import POSTMORTEM

    SPINE.drain()  # pending request spans complete their verdicts first
    if puid:
        local = POSTMORTEM.document(puid=puid)
        if local.get("found") or not fleet_enabled():
            local["source"] = "gateway" if local.get("found") else None
            return local
        from urllib.parse import quote

        sources = [s for s in gather_sources(gateway) if s.lane == "http"]

        async def chase(src: FleetSource):
            try:
                doc = await _fetch_json(
                    gateway, src.base_url + "/postmortems?puid="
                    + quote(puid, safe=""))
                return src, doc
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - absent source = not found
                return src, None

        for src, doc in await asyncio.gather(
                *(chase(s) for s in sources)):
            if isinstance(doc, dict) and doc.get("found"):
                doc["source"] = src.name
                return doc
        return {"found": False, "puid": puid, "postmortem": None,
                "source": None}
    local = POSTMORTEM.document()
    kept = [dict(s, source="gateway") for s in local.get("kept") or ()]
    synthetic = [dict(s, source="gateway")
                 for s in local.get("synthetic") or ()]
    counters = dict(local.get("counters") or {})
    reports: List[dict] = [{
        "source": "gateway", "lane": "local",
        "kept": len(kept), "stale_s": None, "error": None,
    }]
    if fleet_enabled():
        now = time.monotonic()
        for src in gather_sources(gateway):
            if src.lane != "http":
                continue
            docs = getattr(src.endpoint, "fleet_docs", None) \
                if src.endpoint is not None else None
            pm = (docs or {}).get("postmortems")
            stale_s = (round(now - docs["ts"], 3)
                       if docs and docs.get("ts") else None)
            if not isinstance(pm, dict):
                reports.append({
                    "source": src.name, "lane": src.lane, "role": src.role,
                    "set": src.set_name, "kept": 0, "stale_s": stale_s,
                    "error": "no scraped postmortem document",
                })
                continue
            folded = 0
            for key in ("kept", "synthetic"):
                dest = kept if key == "kept" else synthetic
                for s in pm.get(key) or ():
                    if isinstance(s, dict):
                        dest.append(dict(s, source=src.name))
                        folded += 1
            for name, val in (pm.get("counters") or {}).items():
                if isinstance(val, dict):
                    slot = counters.setdefault(name, {})
                    if isinstance(slot, dict):
                        for reason, n in val.items():
                            slot[reason] = slot.get(reason, 0) + int(n or 0)
                elif isinstance(val, (int, float)):
                    counters[name] = (counters.get(name) or 0) + val
            reports.append({
                "source": src.name, "lane": src.lane, "role": src.role,
                "set": src.set_name, "kept": folded, "stale_s": stale_s,
                "error": None,
            })
    # worst-of-fleet ordering: biggest explained excess first, then most
    # recent — same sort the per-process document uses
    kept.sort(key=lambda s: (-(s.get("excess_ms") or 0.0),
                             -(s.get("kept_at_s") or 0.0)))
    return {
        "federated": fleet_enabled(),
        "enabled": bool(local.get("enabled")),
        "sources": reports,
        "counters": counters,
        "kept_count": len(kept),
        "kept": kept,
        "synthetic": synthetic,
    }


def refresh_outlier_gauges(gateway) -> None:
    """Scrape-tick gauge refresh: recompute each URL replica set's
    outlier ratios from the docs the scrape pass just stashed — zero
    extra polling, so the SeldonTPUReplicaOutlier alert fires without
    anyone ever querying ``/fleet``.  In-process sets are covered at
    query time (they never run the scrape loop)."""
    if not fleet_enabled():
        return
    from seldon_core_tpu.utils.telemetry import RECORDER

    from seldon_core_tpu.gateway.federation import lease_ttl_s

    now = time.monotonic()
    for (dep, pred), (_fp, rs) in list(gateway._replica_sets.items()):
        rows: Dict[str, Dict[str, Any]] = {}
        stale: Dict[str, float] = {}
        for ep in rs.endpoints:
            docs = getattr(ep, "fleet_docs", None)
            if getattr(ep, "lease_state", None) == "dead":
                # lapsed lease: the stashed docs describe a dead process
                # — keep it out of the outlier median, but publish a
                # staleness of at least the lease TTL so the gauge (and
                # any alert on it) reads stale instead of silently fresh
                age = (now - docs.get("ts", now)) if docs else 0.0
                stale[ep.name] = round(max(age, lease_ttl_s()), 3)
                continue
            if not docs:
                continue
            row = extract_replica_row(
                docs.get("stats"), docs.get("perf"), docs.get("quality"))
            row.setdefault("ewma_ms", _num(ep.ewma_ms))
            rows[ep.name] = row
            stale[ep.name] = round(now - docs.get("ts", now), 3)
        if len(rows) < 2 and not (stale.keys() - rows.keys()):
            continue
        out = compute_outliers(rows)
        _publish_set_gauges(
            RECORDER, f"{dep}/{pred}",
            {"replicas": {n: {"staleness_s": stale.get(n)}
                          for n in stale},
             "ratios": out["ratios"]},
        )


# ---------------------------------------------------------------------------
# Coordinated profiling windows
# ---------------------------------------------------------------------------


def _profile_window_s() -> float:
    try:
        return float(
            os.environ.get("SELDON_TPU_PROFILE_WINDOW_S", "") or 5.0)
    except ValueError:
        return 5.0


def _profile_dir() -> str:
    import tempfile

    return os.environ.get("SELDON_TPU_PROFILE_DIR", "") or os.path.join(
        tempfile.gettempdir(), "seldon-tpu-profiles")


async def profile_start(gateway, deployment: Optional[str] = None,
                        duration_s: Optional[float] = None
                        ) -> "tuple[int, dict]":
    """Open ONE bounded profiling window across every engine of
    ``deployment`` (or every registered deployment) simultaneously.
    Returns ``(http_status, manifest)`` — 409 with the live manifest
    when a window is already open (overlap refused, never queued).

    Lanes: in-process engines share the gateway's device/process — one
    local ``profile_window_start`` covers them all; URL replicas get a
    ``POST /profile/start``; relay-only endpoints have no profile
    surface and are reported as skipped."""
    from seldon_core_tpu.utils.tracing import (
        ProfileBusyError,
        new_span_id,
        profile_window_start,
    )

    from seldon_core_tpu.utils.tracing import profile_window_status

    active = gateway._profile_manifest
    if active is not None and active.get("state") == "open":
        started = active.get("started_s", 0.0)
        dur = active.get("duration_s", 0.0)
        # expired = well past the bounded duration AND the local
        # process window has actually closed (the first start_trace can
        # take seconds — the wall clock alone must not declare a window
        # dead while its profiler demonstrably still runs)
        if (time.time() < started + dur + 5.0
                or profile_window_status()["active"]):
            return 409, {
                "error": "a coordinated profile window is already open "
                         "— stop it (POST /profile/stop) or wait for "
                         "its bounded duration to elapse",
                "manifest": active,
            }
        # an expired window nobody stopped: finalize it lazily
        await profile_stop(gateway)
    try:
        duration_s = float(duration_s or 0.0)
    except (TypeError, ValueError):
        duration_s = 0.0
    if duration_s <= 0.0:
        duration_s = _profile_window_s()
    wid = new_span_id()
    base = os.path.join(_profile_dir(), wid)
    # publish the manifest BEFORE the first await: the overlap check
    # above and this assignment run atomically on the event loop, so a
    # second concurrent POST /profile/start sees the open window and
    # answers 409 instead of racing past the check during the remote
    # fan-out and overwriting this manifest (losing its stop URLs)
    manifest: Dict[str, Any] = {
        "window": wid,
        "deployment": deployment,
        "state": "open",
        "started_s": time.time(),
        "duration_s": duration_s,
        "sources": [],
    }
    gateway._profile_manifest = manifest
    sources = gather_sources(gateway, deployment)
    if not fleet_enabled():
        sources = [s for s in sources if s.lane == "inprocess"]
    entries: List[dict] = manifest["sources"]
    local_done = False
    remote: List[FleetSource] = []
    for src in sources:
        if src.lane == "inprocess":
            if local_done:
                continue
            local_done = True
            try:
                res = profile_window_start(
                    os.path.join(base, "gateway-local"),
                    duration_s, window=wid)
                # the expiry clock runs from when the profiler actually
                # started — the first jax.profiler start can take
                # seconds, and stamping before it would let the very
                # next request judge this window already expired
                manifest["started_s"] = time.time()
                entries.append({
                    "source": "inprocess-engines", "lane": "inprocess",
                    "artifact": res["artifact"],
                })
            except ProfileBusyError as e:
                entries.append({
                    "source": "inprocess-engines", "lane": "inprocess",
                    "error": str(e),
                })
        elif src.lane == "http":
            remote.append(src)
        else:
            entries.append({
                "source": src.name, "lane": "relay", "skipped": True,
                "error": "no profile surface on the relay lane",
            })

    async def start_remote(src: FleetSource) -> dict:
        import json as _json

        import aiohttp

        body = _json.dumps({"duration_s": duration_s, "window": wid})
        try:
            timeout = aiohttp.ClientTimeout(total=_fleet_timeout_s())
            async with gateway._get_session().post(
                    src.base_url + "/profile/start", data=body,
                    timeout=timeout) as r:
                doc = await r.json(content_type=None)
                if r.status != 200:
                    return {"source": src.name, "lane": "http",
                            "error": (doc or {}).get(
                                "error", f"HTTP {r.status}")}
                # the stop fans out to THIS url — stashed so a replica
                # deregistered mid-window is still stopped
                return {"source": src.name, "lane": "http",
                        "role": src.role, "base_url": src.base_url,
                        "artifact": (doc or {}).get("artifact")}
        except Exception as e:  # noqa: BLE001 - reported per source
            return {"source": src.name, "lane": "http",
                    "error": f"{type(e).__name__}: {e}"}

    entries.extend(await asyncio.gather(*(start_remote(s)
                                          for s in remote)))
    return 200, manifest


async def profile_stop(gateway) -> "tuple[int, dict]":
    """Close the open window on every participant and finalize the
    manifest (idempotent: engines whose bounded timer already fired
    answer their LAST window)."""
    from seldon_core_tpu.utils.tracing import profile_window_stop

    manifest = gateway._profile_manifest
    if manifest is None:
        return 404, {"error": "no profile window has been opened"}
    if manifest.get("state") == "closed":
        return 200, manifest
    stops: List = []
    for entry in manifest["sources"]:
        if entry.get("error") or entry.get("skipped"):
            continue
        if entry["lane"] == "inprocess":
            try:
                profile_window_stop()
            except Exception as e:  # noqa: BLE001 - finalize best-effort
                entry["stop_error"] = f"{type(e).__name__}: {e}"
        elif entry["lane"] == "http":
            stops.append(entry)

    async def stop_remote(entry: dict) -> None:
        import aiohttp

        # the URL was stashed at start time, so a replica deregistered
        # mid-window still gets its stop (the bounded timer is only the
        # backstop, not the plan)
        url = entry.get("base_url")
        if not url:
            entry["stop_error"] = "no base_url stashed at start"
            return
        try:
            timeout = aiohttp.ClientTimeout(total=_fleet_timeout_s())
            async with gateway._get_session().post(
                    url + "/profile/stop", timeout=timeout) as r:
                await r.read()
        except Exception as e:  # noqa: BLE001
            entry["stop_error"] = f"{type(e).__name__}: {e}"

    await asyncio.gather(*(stop_remote(e) for e in stops))
    manifest["state"] = "closed"
    manifest["stopped_s"] = time.time()
    return 200, manifest


def profile_status(gateway) -> dict:
    """The ``GET /profile`` body: the latest manifest plus the local
    process window state."""
    from seldon_core_tpu.utils.tracing import profile_window_status

    return {
        "manifest": gateway._profile_manifest,
        "local": profile_window_status(),
    }
