// fastcodec — native wire marshalling for the TPU inference-graph framework.
//
// The reference spends its data-plane CPU in per-hop JSON marshalling (its
// engine vendors a 1.8k-line protobuf JsonFormat fork, engine/.../pb/
// JsonFormat.java, and the Python wrappers re-parse payloads with stock json,
// wrappers/python/microservice.py:35-120).  This library is the TPU build's
// equivalent of that layer plus the experimental zero-copy flatbuffers codec
// (fbs/prediction.fbs, wrappers/python/seldon_flatbuffers.py): a single-pass
// SeldonMessage JSON splitter that hands Python
//
//   * an "envelope": the original JSON with the numeric payload removed
//     (meta/status/names/binData/... byte spans copied verbatim, so exotic
//     metadata survives untouched), and
//   * the payload as a contiguous double buffer + shape,
//
// so the hot path never materialises Python lists.  A matching formatter
// emits the numeric payload fragment with shortest-roundtrip doubles.
// Anything the fast path can't represent (ragged/mixed ndarray, non-numeric
// entries, invalid JSON) returns SM_UNSUPPORTED and the caller falls back to
// the pure-Python codec — behaviour, not speed, is the contract.
//
// Exposed as a C ABI for ctypes (no pybind11 in this environment).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <cstdio>
#include <string>
#include <vector>

namespace {

enum Status : int {
  SM_OK = 0,
  SM_UNSUPPORTED = 1,  // valid-ish JSON the fast path doesn't model
  SM_INVALID = 2,      // malformed JSON
};

enum Kind : int {
  KIND_NONE = 0,
  KIND_TENSOR = 1,
  KIND_NDARRAY = 2,
};

struct Parse {
  int status = SM_INVALID;
  int kind = KIND_NONE;
  std::string envelope;
  std::vector<double> values;
  std::vector<long long> shape;
  std::string error;
};

struct Scanner {
  const char* p;
  const char* end;

  explicit Scanner(const char* buf, size_t len) : p(buf), end(buf + len) {}

  bool eof() const { return p >= end; }

  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p;
  }

  bool lit(char c) {
    ws();
    if (p < end && *p == c) { ++p; return true; }
    return false;
  }

  char peek() {
    ws();
    return p < end ? *p : '\0';
  }

  // Skip a JSON string (opening quote already consumed). False on error.
  bool skip_string_body() {
    while (p < end) {
      char c = *p++;
      if (c == '\\') { if (p < end) ++p; }
      else if (c == '"') return true;
    }
    return false;
  }

  // Parse a string into out (handles escapes). Quote not yet consumed.
  // had_escape (optional) reports whether any escape sequence appeared —
  // callers that re-emit the string verbatim must fall back in that case.
  bool parse_string(std::string& out, bool* had_escape = nullptr) {
    ws();
    if (p >= end || *p != '"') return false;
    ++p;
    out.clear();
    if (had_escape) *had_escape = false;
    while (p < end) {
      char c = *p++;
      if (c == '"') return true;
      if (c == '\\') {
        if (had_escape) *had_escape = true;
        if (p >= end) return false;
        char e = *p++;
        switch (e) {
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case '/': out += '/'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'u': {
            if (end - p < 4) return false;
            // keep the escape verbatim; envelope copies are verbatim anyway
            out += "\\u";
            out.append(p, 4);
            p += 4;
            break;
          }
          default: return false;
        }
      } else {
        out += c;
      }
    }
    return false;
  }

  // Skip any JSON value; on success the span [start, p) covers it.
  bool skip_value() {
    ws();
    if (p >= end) return false;
    char c = *p;
    if (c == '"') { ++p; return skip_string_body(); }
    if (c == '{' || c == '[') {
      char open = c, close = (c == '{') ? '}' : ']';
      int depth = 0;
      while (p < end) {
        char d = *p++;
        if (d == '"') { if (!skip_string_body()) return false; }
        else if (d == open) ++depth;
        else if (d == close) { if (--depth == 0) return true; }
      }
      return false;
    }
    // number / true / false / null
    const char* start = p;
    while (p < end && *p != ',' && *p != '}' && *p != ']' && *p != ' ' &&
           *p != '\t' && *p != '\n' && *p != '\r')
      ++p;
    return p > start;
  }

  // Fast double parse, STRICT JSON grammar (no leading +/., no "01", digits
  // required around '.') so the fast path never accepts text json.loads
  // rejects.  Falls back to strtod for long mantissas / extreme exponents.
  bool parse_number(double& out) {
    ws();
    const char* start = p;
    bool neg = false;
    if (p < end && *p == '-') { neg = true; ++p; }
    if (p >= end || *p < '0' || *p > '9') return false;  // int part mandatory
    if (*p == '0' && p + 1 < end && p[1] >= '0' && p[1] <= '9')
      return false;  // leading zeros are not JSON
    uint64_t mant = 0;
    int digits = 0, frac_digits = 0;
    bool any = false;
    while (p < end && *p >= '0' && *p <= '9') {
      if (digits < 18) { mant = mant * 10 + (uint64_t)(*p - '0'); ++digits; }
      else ++digits;  // overflow — strtod fallback below
      ++p; any = true;
    }
    if (p < end && *p == '.') {
      ++p;
      if (p >= end || *p < '0' || *p > '9') return false;  // "1." not JSON
      while (p < end && *p >= '0' && *p <= '9') {
        if (digits < 18) { mant = mant * 10 + (uint64_t)(*p - '0'); ++digits; ++frac_digits; }
        else { ++digits; ++frac_digits; }
        ++p; any = true;
      }
    }
    int exp10 = 0; bool has_exp = false;
    if (p < end && (*p == 'e' || *p == 'E')) {
      has_exp = true; ++p;
      bool eneg = false;
      if (p < end && (*p == '-' || *p == '+')) { eneg = (*p == '-'); ++p; }
      int ev = 0; bool edig = false;
      while (p < end && *p >= '0' && *p <= '9') { ev = ev * 10 + (*p - '0'); ++p; edig = true; }
      if (!edig) return false;
      exp10 = eneg ? -ev : ev;
    }
    if (!any) return false;
    int net_exp = exp10 - frac_digits;
    if (digits <= 15 && net_exp >= -22 && net_exp <= 22) {
      static const double pow10[] = {1e0,1e1,1e2,1e3,1e4,1e5,1e6,1e7,1e8,1e9,1e10,
                                     1e11,1e12,1e13,1e14,1e15,1e16,1e17,1e18,1e19,
                                     1e20,1e21,1e22};
      double v = (double)mant;
      v = net_exp >= 0 ? v * pow10[net_exp] : v / pow10[-net_exp];
      out = neg ? -v : v;
      return true;
    }
    (void)has_exp;
    char* endp = nullptr;
    std::string tmp(start, p - start);  // ensure NUL-terminated
    out = strtod(tmp.c_str(), &endp);
    return endp && *endp == '\0';
  }
};

// Parse a (possibly nested) numeric JSON array into flat values + shape.
// Rectangularity enforced; any non-number leaf => unsupported.
static int parse_ndarray(Scanner& s, std::vector<double>& vals,
                         std::vector<long long>& shape,
                         std::vector<int>& etypes, int depth) {
  if (!s.lit('[')) return SM_INVALID;
  if (depth >= 16) return SM_UNSUPPORTED;
  if ((int)shape.size() <= depth) { shape.push_back(-1); etypes.push_back(0); }
  long long count = 0;
  if (s.peek() == ']') { s.lit(']'); /* empty dim */ }
  else {
    for (;;) {
      char c = s.peek();
      if (c == '[') {
        // every element at a given depth must be the same kind across ALL
        // branches (rectangularity) — numpy would build an object array
        if (etypes[depth] == 1) return SM_UNSUPPORTED;
        etypes[depth] = 2;
        int rc = parse_ndarray(s, vals, shape, etypes, depth + 1);
        if (rc != SM_OK) return rc;
      } else if ((c >= '0' && c <= '9') || c == '-' || c == '.') {
        if (etypes[depth] == 2) return SM_UNSUPPORTED;
        etypes[depth] = 1;
        double v;
        if (!s.parse_number(v)) return SM_UNSUPPORTED;  // NaN/Infinity etc.
        vals.push_back(v);
      } else {
        // bools/strings/objects/NaN/garbage: python fallback decides
        return SM_UNSUPPORTED;
      }
      ++count;
      char d = s.peek();
      if (d == ',') { s.lit(','); continue; }
      if (d == ']') { s.lit(']'); break; }
      return SM_INVALID;
    }
  }
  if (shape[depth] == -1) shape[depth] = count;
  else if (shape[depth] != count) return SM_UNSUPPORTED;  // ragged
  return SM_OK;
}

// Parse "tensor":{"shape":[...],"values":[...]} payload.
static int parse_tensor(Scanner& s, std::vector<double>& vals,
                        std::vector<long long>& shape) {
  if (!s.lit('{')) return SM_INVALID;
  bool saw_values = false;
  if (s.peek() == '}') { s.lit('}'); return saw_values ? SM_OK : SM_UNSUPPORTED; }
  for (;;) {
    std::string key;
    bool key_escaped = false;
    if (!s.parse_string(key, &key_escaped)) return SM_INVALID;
    if (key_escaped) return SM_UNSUPPORTED;
    if (!s.lit(':')) return SM_INVALID;
    if (key == "shape") {
      if (!s.lit('[')) return SM_INVALID;
      if (s.peek() == ']') s.lit(']');
      else for (;;) {
        double v;
        if (!s.parse_number(v)) return SM_INVALID;
        shape.push_back((long long)v);
        char d = s.peek();
        if (d == ',') { s.lit(','); continue; }
        if (d == ']') { s.lit(']'); break; }
        return SM_INVALID;
      }
    } else if (key == "values") {
      if (!s.lit('[')) return SM_INVALID;
      saw_values = true;
      if (s.peek() == ']') s.lit(']');
      else for (;;) {
        char c = s.peek();
        if (!((c >= '0' && c <= '9') || c == '-' || c == '+' || c == '.'))
          return SM_UNSUPPORTED;
        double v;
        if (!s.parse_number(v)) return SM_INVALID;
        vals.push_back(v);
        char d = s.peek();
        if (d == ',') { s.lit(','); continue; }
        if (d == ']') { s.lit(']'); break; }
        return SM_INVALID;
      }
    } else {
      return SM_UNSUPPORTED;  // unknown tensor member
    }
    char d = s.peek();
    if (d == ',') { s.lit(','); continue; }
    if (d == '}') { s.lit('}'); break; }
    return SM_INVALID;
  }
  return saw_values ? SM_OK : SM_UNSUPPORTED;
}

// Parse the "data" object: payload members (ndarray/tensor) are extracted,
// everything else ("names", future members) is copied verbatim into env.
static int parse_data(Scanner& s, Parse& out, std::string& env) {
  if (!s.lit('{')) return SM_INVALID;
  env += '{';
  bool first = true;
  if (s.peek() == '}') { s.lit('}'); env += '}'; return SM_OK; }
  for (;;) {
    std::string key;
    bool key_escaped = false;
    if (!s.parse_string(key, &key_escaped)) return SM_INVALID;
    if (key_escaped) return SM_UNSUPPORTED;  // keys are re-emitted raw
    if (!s.lit(':')) return SM_INVALID;
    if (key == "ndarray") {
      if (out.kind != KIND_NONE) return SM_UNSUPPORTED;  // duplicate oneof
      if (s.peek() != '[') return SM_UNSUPPORTED;        // e.g. null
      out.kind = KIND_NDARRAY;
      std::vector<int> etypes;
      int rc = parse_ndarray(s, out.values, out.shape, etypes, 0);
      if (rc != SM_OK) return rc;
      // a trailing empty dim means an empty array — normalise shape product
      long long prod = 1;
      for (long long d : out.shape) prod *= d;
      if (prod != (long long)out.values.size()) return SM_UNSUPPORTED;
    } else if (key == "tensor") {
      if (out.kind != KIND_NONE) return SM_UNSUPPORTED;
      if (s.peek() != '{') return SM_UNSUPPORTED;
      out.kind = KIND_TENSOR;
      int rc = parse_tensor(s, out.values, out.shape);
      if (rc != SM_OK) return rc;
      if (out.shape.empty())
        out.shape.push_back((long long)out.values.size());
      long long prod = 1;
      for (long long d : out.shape) prod *= d;
      if (prod != (long long)out.values.size()) return SM_UNSUPPORTED;
    } else {
      const char* vstart = s.p;
      s.ws();
      vstart = s.p;
      if (!s.skip_value()) return SM_INVALID;
      if (!first) env += ',';
      env += '"'; env += key; env += "\":";
      env.append(vstart, s.p - vstart);
      first = false;
      // fallthrough to separator handling
      char d = s.peek();
      if (d == ',') { s.lit(','); continue; }
      if (d == '}') { s.lit('}'); break; }
      return SM_INVALID;
    }
    char d = s.peek();
    if (d == ',') { s.lit(','); continue; }
    if (d == '}') { s.lit('}'); break; }
    return SM_INVALID;
  }
  env += '}';
  return SM_OK;
}

static int parse_message(const char* buf, size_t len, Parse& out) {
  Scanner s(buf, len);
  if (!s.lit('{')) return SM_INVALID;
  std::string& env = out.envelope;
  env.reserve(128);
  env += '{';
  bool first = true;
  std::string data_env;
  bool has_data = false;
  if (s.peek() == '}') { s.lit('}'); }
  else for (;;) {
    std::string key;
    bool key_escaped = false;
    if (!s.parse_string(key, &key_escaped)) return SM_INVALID;
    if (key_escaped) return SM_UNSUPPORTED;  // keys are re-emitted raw
    if (!s.lit(':')) return SM_INVALID;
    if (key == "data") {
      // any duplicate top-level "data" (object-then-null, double object)
      // would need json.loads last-wins semantics — decline to Python
      if (has_data) return SM_UNSUPPORTED;
      if (s.peek() != '{') {
        // "data": null — treat as absent, like protobuf JsonFormat
        const char* vstart = s.p;
        if (!s.skip_value()) return SM_INVALID;
        std::string v(vstart, s.p - vstart);
        if (v != "null") return SM_UNSUPPORTED;
      } else {
        has_data = true;
        int rc = parse_data(s, out, data_env);
        if (rc != SM_OK) return rc;
      }
    } else {
      s.ws();
      const char* vstart = s.p;
      if (!s.skip_value()) return SM_INVALID;
      if (!first) env += ',';
      env += '"'; env += key; env += "\":";
      env.append(vstart, s.p - vstart);
      first = false;
    }
    char d = s.peek();
    if (d == ',') { s.lit(','); continue; }
    if (d == '}') { s.lit('}'); break; }
    return SM_INVALID;
  }
  s.ws();
  if (!s.eof()) return SM_INVALID;  // trailing garbage
  if (has_data) {
    if (!first) env += ',';
    env += "\"data\":";
    env += data_env;
  }
  env += '}';
  return SM_OK;
}

// ---------------------------------------------------------------------------
// Formatting: shortest-roundtrip double -> JSON text.
// ---------------------------------------------------------------------------

static int format_double(double v, char* buf /* >= 32 bytes */) {
  if (v == 0.0 && 1.0 / v < 0) {
    // python json.dumps(-0.0) keeps the sign; the integral path would drop it
    memcpy(buf, "-0.0", 4);
    return 4;
  }
  if (v == (double)(long long)v && v > -1e15 && v < 1e15) {
    // integral fast path, python-json style "N.0"
    long long i = (long long)v;
    int n = snprintf(buf, 32, "%lld.0", i);
    return n;
  }
  // %.17g always round-trips a double exactly; we trade a few wire bytes
  // (vs shortest-repr) for a single snprintf instead of a verify loop
  return snprintf(buf, 32, "%.17g", v);
}

}  // namespace

extern "C" {

// Single-call parse: fills a caller-provided view so the common path costs
// two FFI crossings (parse_view + free) instead of five getter calls.
struct SMView {
  int32_t status;
  int32_t kind;
  int32_t ndim;
  int32_t _pad;
  long long nvalues;
  long long envelope_len;
  const char* envelope;
  const double* values;
  const long long* shape;
};

Parse* sm_parse_view(const char* buf, long long len, SMView* view) {
  Parse* p = new (std::nothrow) Parse();
  if (!p) { if (view) view->status = SM_INVALID; return nullptr; }
  p->status = (buf && len >= 0) ? parse_message(buf, (size_t)len, *p) : SM_INVALID;
  if (p->status != SM_OK) {
    p->envelope.clear();
    p->values.clear();
    p->shape.clear();
  }
  if (view) {
    view->status = p->status;
    view->kind = p->kind;
    view->ndim = (int32_t)p->shape.size();
    view->nvalues = (long long)p->values.size();
    view->envelope_len = (long long)p->envelope.size();
    view->envelope = p->envelope.data();
    view->values = p->values.data();
    view->shape = p->shape.data();
  }
  return p;
}

Parse* sm_parse(const char* buf, long long len) {
  Parse* p = new (std::nothrow) Parse();
  if (!p) return nullptr;
  if (!buf || len < 0) { p->status = SM_INVALID; return p; }
  p->status = parse_message(buf, (size_t)len, *p);
  if (p->status != SM_OK) {
    p->envelope.clear();
    p->values.clear();
    p->shape.clear();
  }
  return p;
}

int sm_status(Parse* p) { return p ? p->status : SM_INVALID; }

const char* sm_envelope(Parse* p, long long* len) {
  if (!p) { if (len) *len = 0; return nullptr; }
  if (len) *len = (long long)p->envelope.size();
  return p->envelope.data();
}

int sm_kind(Parse* p) { return p ? p->kind : KIND_NONE; }

const double* sm_values(Parse* p, long long* n) {
  if (!p) { if (n) *n = 0; return nullptr; }
  if (n) *n = (long long)p->values.size();
  return p->values.data();
}

const long long* sm_shape(Parse* p, int* ndim) {
  if (!p) { if (ndim) *ndim = 0; return nullptr; }
  if (ndim) *ndim = (int)p->shape.size();
  return p->shape.data();
}

void sm_free(Parse* p) { delete p; }

// Empty-array nesting: mirror numpy .tolist() — full nesting down to the
// first zero-length dim, which renders as [] (e.g. (2,0) -> [[],[]],
// (0,5) -> [], (2,3,0) -> [[[],[],[]],[[],[],[]]]).
static void emit_empty_ndarray(std::string& out, const long long* shape,
                               int first_zero_dim, int d) {
  out += '[';
  if (d < first_zero_dim) {
    for (long long i = 0; i < shape[d]; ++i) {
      if (i) out += ',';
      emit_empty_ndarray(out, shape, first_zero_dim, d + 1);
    }
  }
  out += ']';
}

// Format a payload fragment from a flat double buffer:
//   kind==KIND_TENSOR  -> "tensor":{"shape":[..],"values":[..]}
//   kind==KIND_NDARRAY -> "ndarray":[[..],..] nested per shape
// Returns a malloc'd buffer (caller frees with sm_buf_free), len in out_len.
char* sm_format(const double* vals, const long long* shape, int ndim,
                int kind, long long* out_len) {
  if (!vals || !shape || ndim <= 0 || out_len == nullptr) return nullptr;
  long long total = 1;
  for (int i = 0; i < ndim; ++i) {
    if (shape[i] < 0) return nullptr;
    total *= shape[i];
  }
  std::string out;
  out.reserve((size_t)total * 8 + 64);
  char nb[32];
  if (kind == KIND_TENSOR) {
    out += "\"tensor\":{\"shape\":[";
    for (int i = 0; i < ndim; ++i) {
      if (i) out += ',';
      int n = snprintf(nb, sizeof nb, "%lld", shape[i]);
      out.append(nb, n);
    }
    out += "],\"values\":[";
    for (long long i = 0; i < total; ++i) {
      if (i) out += ',';
      out.append(nb, format_double(vals[i], nb));
    }
    out += "]}";
  } else if (kind == KIND_NDARRAY) {
    // nested arrays; divisor stack gives the index period of each dim close
    std::vector<long long> period(ndim);  // elements per sub-array at dim d
    long long acc = 1;
    for (int d = ndim - 1; d >= 0; --d) { acc *= shape[d]; period[d] = acc; }
    if (total == 0) {
      out += "\"ndarray\":";
      int z = 0;
      while (z < ndim && shape[z] != 0) ++z;
      emit_empty_ndarray(out, shape, z, 0);
    } else {
      out += "\"ndarray\":";
      for (long long i = 0; i < total; ++i) {
        for (int d = 0; d < ndim; ++d)
          if (i % period[d] == 0) out += '[';
        out.append(nb, format_double(vals[i], nb));
        for (int d = ndim - 1; d >= 0; --d)
          if ((i + 1) % period[d] == 0) out += ']';
        if (i + 1 < total) out += ',';
      }
    }
  } else {
    return nullptr;
  }
  char* buf = (char*)malloc(out.size());
  if (!buf) return nullptr;
  memcpy(buf, out.data(), out.size());
  *out_len = (long long)out.size();
  return buf;
}

void sm_buf_free(char* p) { free(p); }

}  // extern "C"
