// Native REST data plane — C++ HTTP termination + request batching for the
// serving engine.
//
// The reference engine spends its per-request budget inside Tomcat NIO +
// Jackson + a vendored protobuf JsonFormat fork (engine
// RestClientController.java, pb/JsonFormat.java); its throughput scales with
// the 16 cores of its benchmark pod.  This framework's Python engine
// (runtime/httpfast.py) already strips HTTP to an asyncio.Protocol, but on a
// single-core host ~190 us/request of interpreter work caps the lane at a
// few k req/s.  This module moves the ENTIRE per-request path out of Python:
//
//   IO thread (C++, no GIL): epoll loop -> HTTP/1.1 parse -> JSON numeric
//     parse (fastcodec.cpp) -> rows appended to a width-keyed batch ->
//     batch published when full / deadline / a dispatch slot is idle.
//   Python worker threads: dp_next_batch() blocks (GIL released) -> numpy
//     view of the stacked float64 rows -> ONE jitted XLA dispatch ->
//     dp_complete_batch(y).
//   Completing thread (C++, no GIL): per-request JSON responses composed
//     and handed to the IO thread for ordered, flow-controlled writes.
//
// Python's cost becomes one FFI round-trip per BATCH (<= 1/1024th of the
// request rate), so the serving ceiling is set by this file and the TPU,
// not the interpreter.  Requests the fast lane cannot express — feedback,
// admin GETs, form-encoded bodies, strData/binData/jsonData payloads,
// >2-D tensors, oversized row counts — are queued verbatim to Python
// (dp_next_misc / dp_respond_misc) and served by the full-semantics engine
// routes, preserving wire behaviour exactly.
//
// Response ordering per connection is FIFO by arrival (pipelining-safe),
// matching runtime/httpfast.py; keepalive, Connection: close, 404/405/411/
// 413/501 handling match the same contract.

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

// ---- fastcodec.cpp C ABI (compiled into the same shared object) -----------
extern "C" {
struct SMViewC {
  int32_t status;
  int32_t kind;
  int32_t ndim;
  int32_t _pad;
  long long nvalues;
  long long envelope_len;
  const char* envelope;
  const double* values;
  const long long* shape;
};
void* sm_parse_view(const char* buf, long long len, SMViewC* view);
void sm_free(void* p);
char* sm_format(const double* vals, const long long* shape, int ndim,
                int kind, long long* out_len);
void sm_buf_free(char* p);
}

namespace {

constexpr int SM_OK = 0;
constexpr int KIND_TENSOR = 1;
constexpr int KIND_NDARRAY = 2;

constexpr size_t MAX_HEAD = 64 * 1024;
constexpr size_t MAX_BODY = 256u * 1024 * 1024;  // matches rest.py
constexpr int MAX_CONN_OUTSTANDING = 128;        // matches httpfast backpressure
constexpr long long MAX_QUEUED_ROWS = 1 << 17;   // global 503 backstop

double now_s() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec * 1e-9;
}

// latency buckets — MUST match utils/metrics.py _BUCKETS (seconds)
constexpr double kBuckets[14] = {0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                                 0.05,   0.1,   0.25,   0.5,   1.0,  2.5,
                                 5.0,    10.0};

struct Stats {
  std::atomic<long long> n2xx{0}, n4xx{0}, n5xx{0};
  std::atomic<long long> sum_us{0};
  std::atomic<long long> hist[15]{};  // 14 buckets + +Inf
  void observe_ok(double secs) {
    n2xx.fetch_add(1, std::memory_order_relaxed);
    sum_us.fetch_add((long long)(secs * 1e6), std::memory_order_relaxed);
    int b = 0;
    while (b < 14 && secs > kBuckets[b]) b++;
    hist[b].fetch_add(1, std::memory_order_relaxed);
  }
};

// base32 [a-z2-7] puid, visually identical to messages.py new_puid()
struct PuidGen {
  uint64_t s;
  explicit PuidGen(uint64_t seed) : s(seed | 1) {}
  void fill(char* out26) {
    static const char alpha[] = "abcdefghijklmnopqrstuvwxyz234567";
    uint64_t x = 0;
    int have = 0;
    for (int i = 0; i < 26; i++) {
      if (have < 5) {
        s ^= s << 13; s ^= s >> 7; s ^= s << 17;  // xorshift64
        x = s;
        have = 64;
      }
      out26[i] = alpha[x & 31];
      x >>= 5;
      have -= 5;
    }
  }
};

struct ReqInfo {
  int conn_id;
  uint32_t conn_gen;
  uint64_t seq;       // per-conn response order (HTTP/1.1 lane)
  int kind;           // KIND_TENSOR / KIND_NDARRAY / KIND_PROTO
  long long rows;
  bool close_c = false;  // request asked Connection: close
  bool h2 = false;       // gRPC lane: respond by stream, not by seq
  uint32_t stream = 0;   // h2 stream id
  std::string meta;   // HTTP lane: verbatim client meta object ("" if absent)
  std::string puid;   // gRPC lane: client puid ("" -> generate)
  double t0;          // parse time, for the latency histogram
};

constexpr int KIND_PROTO = 100;  // gRPC tensor request (proto wire response)

struct Batch {
  long long id;
  long long width;
  std::vector<double> data;  // rows * width, row-major
  std::vector<ReqInfo> reqs;
  double t_first;
};

struct MiscReq {
  long long id;
  int conn_id;
  uint32_t conn_gen;
  uint64_t seq;
  bool close_c = false;
  bool h2 = false;       // gRPC misc: method="GRPC", body = message bytes
  uint32_t stream = 0;
  std::string method;  // "GET" / "POST"
  std::string path;    // without query
  std::string query;
  std::string ctype;
  std::string body;
};

struct H2State;  // defined in the gRPC lane section below

struct Conn {
  int fd = -1;
  uint32_t gen = 0;
  bool h2 = false;
  std::unique_ptr<H2State> h2s;
  std::string in;
  size_t scan_from = 0;
  ssize_t head_end = -1;
  long long clen = -1;
  bool head_parsed = false;
  std::string hmethod, hpath, hquery, hctype;
  bool hclose = false;
  uint64_t next_assign = 0;   // next seq to hand out
  uint64_t next_write = 0;    // next seq to be written
  std::map<uint64_t, std::string> done;  // seq -> full HTTP response
  uint64_t close_after = UINT64_MAX;     // write responses <= this, then close
  std::string out;
  size_t out_off = 0;
  bool want_write = false;
  bool paused = false;
};

struct Plane {
  int listen_fd = -1;
  int port = 0;
  int ep = -1;
  int evfd = -1;
  std::thread io_thread;
  std::atomic<bool> stop{false};

  long long max_batch;
  double max_wait_s;
  int depth;
  std::string names_frag;        // JSON: '"names":["a","b"],' or ""
  std::string proto_names_frag;  // proto: DefaultData.names fields wire bytes

  std::vector<std::unique_ptr<Conn>> conns;
  std::vector<int> free_conns;

  // batching state (guarded by mu)
  std::mutex mu;
  std::condition_variable cv_batch;
  std::condition_variable cv_misc;
  std::unordered_map<long long, std::unique_ptr<Batch>> accum;  // width -> batch
  std::deque<std::unique_ptr<Batch>> ready;
  std::unordered_map<long long, std::unique_ptr<Batch>> inflight;
  std::deque<std::unique_ptr<MiscReq>> misc_q;
  std::unordered_map<long long, std::unique_ptr<MiscReq>> misc_inflight;
  long long next_batch_id = 1;
  long long next_misc_id = 1;
  long long queued_rows = 0;
  int inflight_count = 0;

  // completions: responses composed off-thread, flushed by the IO thread
  struct Completion {
    int conn_id;
    uint32_t gen;
    bool h2;
    uint64_t seq;      // HTTP lane: response order slot
    uint32_t stream;   // gRPC lane: stream id
    int grpc_status;   // gRPC lane: 0 = data+OK trailers, else trailers-only
    std::string data;  // HTTP: full response; h2 ok: grpc message frame;
                       // h2 error: grpc-message text
  };
  std::mutex cmu;
  std::vector<Completion> completions;

  // gRPC listener (0 = lane disabled)
  int grpc_listen_fd = -1;
  int grpc_port = 0;

  // io-thread-local: conns needing a parse retry after backpressure resume
  std::vector<int> resume_parse;

  Stats stats;     // HTTP/1.1 fast lane
  Stats stats_h2;  // h2/gRPC fast lane — kept separate so /prometheus can
                   // attribute each surface to its own metric child
  PuidGen puid;

  Plane() : puid((uint64_t)now_s() * 1000003 ^ (uint64_t)(uintptr_t)this) {}
};

void set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

const char* status_text(int code) {
  switch (code) {
    case 200: return "OK";
    case 400: return "Bad Request";
    case 404: return "Not Found";
    case 405: return "Method Not Allowed";
    case 411: return "Length Required";
    case 413: return "Payload Too Large";
    case 500: return "Internal Server Error";
    case 501: return "Not Implemented";
    case 503: return "Service Unavailable";
    case 504: return "Gateway Timeout";
    default: return "X";
  }
}

std::string http_response(int code, const char* ctype, const char* body,
                          size_t body_len, bool close_conn) {
  char head[512];
  int n = snprintf(head, sizeof head,
                   "HTTP/1.1 %d %s\r\nContent-Length: %zu\r\n"
                   "Content-Type: %s\r\n%s\r\n",
                   code, status_text(code), body_len, ctype,
                   close_conn ? "Connection: close\r\n" : "");
  // snprintf returns the would-be length; clamp so an oversized
  // content-type truncates instead of reading past the buffer
  if (n < 0) n = 0;
  if ((size_t)n >= sizeof head) n = (int)sizeof head - 1;
  std::string out;
  out.reserve((size_t)n + body_len);
  out.append(head, (size_t)n);
  out.append(body, body_len);
  return out;
}

// Extract the top-level "meta" object span from the envelope JSON that
// fastcodec produced (compact, valid).  Returns "" if absent.
std::string extract_meta(const char* env, long long len) {
  // envelope is {"meta":{...},...} with our own serialization; find the key
  // at top level by scanning with brace/string awareness
  int depth = 0;
  bool in_str = false;
  for (long long i = 0; i < len; i++) {
    char c = env[i];
    if (in_str) {
      if (c == '\\') i++;
      else if (c == '"') in_str = false;
      continue;
    }
    if (c == '"') {
      if (depth == 1 && i + 7 <= len && memcmp(env + i, "\"meta\"", 6) == 0) {
        long long j = i + 6;
        while (j < len && (env[j] == ':' || env[j] == ' ')) j++;
        if (j < len && env[j] == '{') {
          int d2 = 0;
          bool s2 = false;
          for (long long k = j; k < len; k++) {
            char c2 = env[k];
            if (s2) {
              if (c2 == '\\') k++;
              else if (c2 == '"') s2 = false;
              continue;
            }
            if (c2 == '"') s2 = true;
            else if (c2 == '{') d2++;
            else if (c2 == '}') {
              if (--d2 == 0) return std::string(env + j, k - j + 1);
            }
          }
        }
        return "";
      }
      in_str = true;
      continue;
    }
    if (c == '{' || c == '[') depth++;
    else if (c == '}' || c == ']') depth--;
  }
  return "";
}

// meta for the response: client meta echoed with puid guaranteed present
std::string response_meta(Plane* pl, const std::string& client_meta) {
  char pbuf[26];
  if (client_meta.empty() || client_meta == "{}") {
    pl->puid.fill(pbuf);
    return std::string("{\"puid\":\"") + std::string(pbuf, 26) + "\"}";
  }
  if (client_meta.find("\"puid\"") != std::string::npos) return client_meta;
  pl->puid.fill(pbuf);
  std::string out;
  out.reserve(client_meta.size() + 40);
  out += "{\"puid\":\"";
  out.append(pbuf, 26);
  out += "\",";
  out.append(client_meta, 1, client_meta.size() - 1);
  return out;
}

void queue_completion(Plane* pl, const ReqInfo& r, std::string&& resp) {
  {
    std::lock_guard<std::mutex> lk(pl->cmu);
    pl->completions.push_back(Plane::Completion{
        r.conn_id, r.conn_gen, false, r.seq, 0, 0, std::move(resp)});
  }
  uint64_t one = 1;
  (void)!write(pl->evfd, &one, 8);
}

void queue_completion_h2(Plane* pl, int conn_id, uint32_t gen,
                         uint32_t stream, int grpc_status,
                         std::string&& data) {
  {
    std::lock_guard<std::mutex> lk(pl->cmu);
    pl->completions.push_back(Plane::Completion{
        conn_id, gen, true, 0, stream, grpc_status, std::move(data)});
  }
  uint64_t one = 1;
  (void)!write(pl->evfd, &one, 8);
}

// ---------------------------------------------------------------------------
// IO thread
// ---------------------------------------------------------------------------

struct EvTag {  // epoll user data: fd class + conn index
  enum { LISTEN = -1, EVENT = -2, LISTEN_GRPC = -3 };
};

void arm(Plane* pl, int fd, int idx, uint32_t events, int op) {
  struct epoll_event ev;
  ev.events = events;
  ev.data.u64 = (uint64_t)(uint32_t)idx;
  epoll_ctl(pl->ep, op, fd, &ev);
}

void conn_close(Plane* pl, int ci) {
  Conn& c = *pl->conns[ci];
  if (c.fd < 0) return;
  epoll_ctl(pl->ep, EPOLL_CTL_DEL, c.fd, nullptr);
  close(c.fd);
  c.fd = -1;
  c.gen++;  // invalidates in-flight completions for this conn
  c.in.clear();
  c.in.shrink_to_fit();
  c.done.clear();
  c.out.clear();
  c.out.shrink_to_fit();
  pl->free_conns.push_back(ci);
}

// move ready ordered responses into the write buffer; write; manage EPOLLOUT.
// IO-thread only; never re-entered from the parse path (respond_now just
// queues — the caller flushes once parsing is done).
void conn_flush(Plane* pl, int ci) {
  Conn& c = *pl->conns[ci];
  if (c.fd < 0) return;
  // HTTP lane: responses drain strictly in request order; the h2 lane
  // writes frames straight into c.out (streams are self-identifying)
  while (!c.h2 && c.next_write <= c.close_after) {
    auto it = c.done.find(c.next_write);
    if (it == c.done.end()) break;
    c.out += it->second;
    c.done.erase(it);
    c.next_write++;
  }
  if (c.h2 && c.out.size() - c.out_off > 256u * 1024 * 1024) {
    // h2 write-side backstop: a client that pipelines requests but never
    // reads responses would grow c.out without bound (the HTTP lane's
    // MAX_CONN_OUTSTANDING pause covers this for HTTP/1.1)
    conn_close(pl, ci);
    return;
  }
  while (c.out_off < c.out.size()) {
    ssize_t n = write(c.fd, c.out.data() + c.out_off, c.out.size() - c.out_off);
    if (n > 0) { c.out_off += (size_t)n; continue; }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      if (!c.want_write) {
        c.want_write = true;
        arm(pl, c.fd, ci, EPOLLIN | EPOLLOUT, EPOLL_CTL_MOD);
      }
      return;
    }
    conn_close(pl, ci);
    return;
  }
  c.out.clear();
  c.out_off = 0;
  if (c.want_write) {
    c.want_write = false;
    arm(pl, c.fd, ci, EPOLLIN, EPOLL_CTL_MOD);
  }
  if (!c.h2 && c.next_write > c.close_after) {
    conn_close(pl, ci);
    return;
  }
  // resume reading when the pipeline drains; buffered-but-unparsed bytes
  // are retried by the io loop (no recursion into the parse path here)
  if (c.paused && c.next_assign - c.next_write <= MAX_CONN_OUTSTANDING / 2) {
    c.paused = false;
    arm(pl, c.fd, ci, c.want_write ? (EPOLLIN | EPOLLOUT) : EPOLLIN,
        EPOLL_CTL_MOD);
    pl->resume_parse.push_back(ci);
  }
}

// queue an immediate (parse-error / overload) response; the caller flushes
void respond_now(Plane* pl, int ci, int code, const char* body, bool close_c) {
  Conn& c = *pl->conns[ci];
  uint64_t seq = c.next_assign++;
  c.done[seq] = http_response(code, "text/plain", body, strlen(body), close_c);
  if (close_c) c.close_after = seq;
  if (code >= 500) pl->stats.n5xx.fetch_add(1, std::memory_order_relaxed);
  else if (code >= 400) pl->stats.n4xx.fetch_add(1, std::memory_order_relaxed);
}

void flush_batch_locked(Plane* pl, long long width) {
  // queued_rows keeps counting ready batches — they still occupy memory and
  // the 503 backstop must see them; dp_next_batch decrements on hand-off
  auto it = pl->accum.find(width);
  if (it == pl->accum.end() || !it->second) return;
  std::unique_ptr<Batch> b = std::move(it->second);
  pl->accum.erase(it);
  pl->ready.push_back(std::move(b));
  pl->cv_batch.notify_one();
}

// Shared batch admission for both lanes: append `rows x width` doubles and
// the request's ReqInfo to the width-keyed accumulation, flushing on
// overflow/full.  Returns false when the global row backstop is hit (the
// caller answers 503 / RESOURCE_EXHAUSTED).
bool enqueue_rows(Plane* pl, ReqInfo&& r, const void* vals, long long rows,
                  long long width) {
  std::lock_guard<std::mutex> lk(pl->mu);
  if (pl->queued_rows + rows > MAX_QUEUED_ROWS) return false;
  {
    auto pre = pl->accum.find(width);
    if (pre != pl->accum.end() && pre->second &&
        (long long)(pre->second->data.size() / width) + rows > pl->max_batch)
      flush_batch_locked(pl, width);  // this request would overflow: flush
  }
  auto& slot = pl->accum[width];
  if (!slot) {
    slot.reset(new Batch());
    slot->id = pl->next_batch_id++;
    slot->width = width;
    slot->data.reserve((size_t)std::min<long long>(pl->max_batch, 4096) *
                       width);
    slot->t_first = now_s();
  }
  Batch& b = *slot;
  size_t off = b.data.size();
  b.data.resize(off + (size_t)(rows * width));
  memcpy(b.data.data() + off, vals, sizeof(double) * (size_t)(rows * width));
  b.reqs.push_back(std::move(r));
  pl->queued_rows += rows;
  if ((long long)(b.data.size() / width) >= pl->max_batch)
    flush_batch_locked(pl, width);
  return true;
}

// returns false if the request was NOT eligible for the fast lane
bool try_fast_predict(Plane* pl, int ci, const char* body, size_t blen,
                      bool close_c) {
  Conn& c = *pl->conns[ci];
  SMViewC v;
  void* p = sm_parse_view(body, (long long)blen, &v);
  bool ok = p && v.status == SM_OK &&
            (v.kind == KIND_TENSOR || v.kind == KIND_NDARRAY) &&
            v.ndim >= 1 && v.ndim <= 2 && v.nvalues > 0;
  long long rows = 0, width = 0;
  if (ok) {
    rows = v.ndim == 2 ? v.shape[0] : 1;
    width = v.ndim == 2 ? v.shape[1] : v.nvalues;
    ok = rows > 0 && width > 0 && rows <= pl->max_batch;
  }
  std::string meta;
  if (ok) {
    meta = extract_meta(v.envelope, v.envelope_len);
    // binData/strData/jsonData arrive with kind NONE (not ok); a non-object
    // meta can't appear here because extract_meta only matches "meta":{
  }
  if (!ok) {
    if (p) sm_free(p);
    return false;
  }
  ReqInfo r;
  r.conn_id = ci;
  r.conn_gen = c.gen;
  r.seq = c.next_assign++;
  r.kind = v.kind;
  r.rows = rows;
  r.close_c = close_c;
  r.meta = std::move(meta);
  r.t0 = now_s();
  uint64_t seq = r.seq;
  bool accepted = enqueue_rows(pl, std::move(r), v.values, rows, width);
  sm_free(p);
  if (!accepted) {
    // seq was already assigned: answer it, keeping per-conn order intact
    static const std::string overload =
        "{\"status\":{\"code\":503,\"status\":\"FAILURE\","
        "\"reason\":\"overloaded\"}}";
    Conn& cc = *pl->conns[ci];
    cc.done[seq] = http_response(503, "application/json", overload.data(),
                                 overload.size(), close_c);
    if (close_c) cc.close_after = seq;
    pl->stats.n5xx.fetch_add(1, std::memory_order_relaxed);
  }
  return true;
}

void to_misc(Plane* pl, int ci, bool close_c, std::string&& method,
             std::string&& path, std::string&& query, std::string&& ctype,
             std::string&& body) {
  Conn& c = *pl->conns[ci];
  auto m = std::make_unique<MiscReq>();
  m->conn_id = ci;
  m->conn_gen = c.gen;
  m->seq = c.next_assign++;
  m->close_c = close_c;
  m->method = std::move(method);
  m->path = std::move(path);
  m->query = std::move(query);
  m->ctype = std::move(ctype);
  m->body = std::move(body);
  std::lock_guard<std::mutex> lk(pl->mu);
  m->id = pl->next_misc_id++;
  pl->misc_q.push_back(std::move(m));
  pl->cv_misc.notify_one();
}

// case-insensitive header value inside [head, head+len), name lower-case
// with colon; anchored at line start
std::string header_value(const char* head, size_t len, const char* name) {
  size_t nlen = strlen(name);
  for (size_t i = 0; i + 2 + nlen <= len; i++) {
    if (head[i] != '\r' || head[i + 1] != '\n') continue;
    size_t j = 0;
    while (j < nlen && i + 2 + j < len &&
           (char)(head[i + 2 + j] | 0x20) == name[j])
      j++;
    if (j == nlen) {
      size_t s = i + 2 + nlen;
      size_t e = s;
      while (e < len && head[e] != '\r') e++;
      while (s < e && head[s] == ' ') s++;
      while (e > s && head[e - 1] == ' ') e--;
      return std::string(head + s, e - s);
    }
  }
  return "";
}

void handle_request(Plane* pl, int ci, const char* head, size_t head_len,
                    const char* body, size_t body_len) {
  Conn& c = *pl->conns[ci];
  // request line
  const char* line_end = (const char*)memchr(head, '\r', head_len);
  size_t ll = line_end ? (size_t)(line_end - head) : head_len;
  std::string method, target;
  {
    const char* sp1 = (const char*)memchr(head, ' ', ll);
    if (!sp1) { respond_now(pl, ci, 400, "malformed request line", true); return; }
    const char* sp2 = (const char*)memchr(sp1 + 1, ' ', ll - (sp1 + 1 - head));
    if (!sp2) { respond_now(pl, ci, 400, "malformed request line", true); return; }
    method.assign(head, sp1 - head);
    target.assign(sp1 + 1, sp2 - sp1 - 1);
  }
  std::string conn_hdr = header_value(head, head_len, "connection:");
  bool close_c = conn_hdr.find("close") != std::string::npos;
  std::string path = target, query;
  size_t qp = target.find('?');
  if (qp != std::string::npos) {
    path = target.substr(0, qp);
    query = target.substr(qp + 1);
  }
  std::string ctype = header_value(head, head_len, "content-type:");

  if (method == "POST" && path == "/api/v0.1/predictions" &&
      ctype.find("form") == std::string::npos) {
    if (try_fast_predict(pl, ci, body, body_len, close_c)) {
      if (close_c) c.close_after = c.next_assign - 1;
      goto backpressure;
    }
  }
  if (method != "GET" && method != "POST") {
    respond_now(pl, ci, 405, "method not allowed", close_c);
    return;
  }
  to_misc(pl, ci, close_c, std::move(method), std::move(path),
          std::move(query), std::move(ctype), std::string(body, body_len));
  if (close_c) c.close_after = c.next_assign - 1;

backpressure:
  if (!c.paused && c.next_assign - c.next_write > MAX_CONN_OUTSTANDING) {
    c.paused = true;
    if (c.fd >= 0)
      arm(pl, c.fd, ci, c.want_write ? EPOLLOUT : 0, EPOLL_CTL_MOD);
  }
}

void conn_parse(Plane* pl, int ci) {
  Conn& c = *pl->conns[ci];
  size_t consumed = 0;
  while (c.fd >= 0 && !c.paused) {
    if (c.head_parsed) {
      if (c.in.size() - consumed < (size_t)c.head_end + (size_t)c.clen) break;
      size_t bstart = consumed + (size_t)c.head_end;
      handle_request(pl, ci, c.in.data() + consumed, (size_t)c.head_end,
                     c.in.data() + bstart, (size_t)c.clen);
      consumed = bstart + (size_t)c.clen;
      c.head_parsed = false;
      c.head_end = -1;
      c.clen = -1;
      c.scan_from = 0;
      continue;
    }
    // scan for end of headers
    size_t from = consumed + (c.scan_from > 3 ? c.scan_from - 3 : 0);
    const char* found = nullptr;
    if (c.in.size() > from + 3) {
      for (size_t i = from; i + 4 <= c.in.size(); i++) {
        if (c.in[i] == '\r' && c.in[i + 1] == '\n' && c.in[i + 2] == '\r' &&
            c.in[i + 3] == '\n') {
          found = c.in.data() + i;
          break;
        }
      }
    }
    if (!found) {
      if (c.in.size() - consumed > MAX_HEAD) {
        respond_now(pl, ci, 413, "headers too large", true);
        break;
      }
      c.scan_from = c.in.size() - consumed;
      break;
    }
    size_t head_len = (size_t)(found - (c.in.data() + consumed)) + 4;
    const char* head = c.in.data() + consumed;
    // RFC 7230: Transfer-Encoding wins over Content-Length (smuggling guard)
    if (!header_value(head, head_len, "transfer-encoding:").empty()) {
      respond_now(pl, ci, 501, "chunked bodies not supported", true);
      break;
    }
    long long clen = 0;
    std::string clv = header_value(head, head_len, "content-length:");
    if (!clv.empty()) {
      for (char ch : clv) {
        if (ch < '0' || ch > '9') { clen = -1; break; }
        clen = clen * 10 + (ch - '0');
        if (clen > (long long)MAX_BODY) break;
      }
      if (clen < 0) {
        respond_now(pl, ci, 400, "bad content-length", true);
        break;
      }
      if (clen > (long long)MAX_BODY) {
        respond_now(pl, ci, 413, "body too large", true);
        break;
      }
    }
    c.head_end = (ssize_t)head_len;
    c.clen = clen;
    c.head_parsed = true;
  }
  if (consumed) {
    c.in.erase(0, consumed);
    if (!c.head_parsed) c.scan_from = 0;
  }
}

void h2_parse(Plane* pl, int ci);  // gRPC lane, defined below

void conn_data(Plane* pl, int ci) {
  Conn& c = *pl->conns[ci];
  char buf[65536];
  for (;;) {
    if (c.fd < 0) return;
    ssize_t r = read(c.fd, buf, sizeof buf);
    if (r > 0) {
      c.in.append(buf, (size_t)r);
      if ((size_t)r == sizeof buf && !c.paused) continue;
    } else if (r == 0) {
      conn_close(pl, ci);
      return;
    } else if (errno == EAGAIN || errno == EWOULDBLOCK) {
      // no more data
    } else {
      conn_close(pl, ci);
      return;
    }
    break;
  }
  if (c.h2) h2_parse(pl, ci);
  else conn_parse(pl, ci);
}

// ---------------------------------------------------------------------------
// gRPC lane: HTTP/2 + HPACK + protobuf tensor fast path.
//
// The HPACK decoder is a C++ port of this framework's own
// seldon_core_tpu/native/hpackcodec.py (RFC 7541: static+dynamic tables,
// Huffman via a bit trie built from the spec table); the proto scanner
// mirrors seldon_core_tpu/native/protowire.py exactly — any message shape
// the Python fast lane declines, this lane declines to the misc queue, so
// wire semantics never diverge between planes.
// ---------------------------------------------------------------------------

// RFC 7541 Appendix B Huffman code table (public spec data)
const uint32_t kHuffCodes[257] = {
    8184, 8388568, 268435426, 268435427, 268435428, 268435429, 268435430,
    268435431, 268435432, 16777194, 1073741820, 268435433, 268435434,
    1073741821, 268435435, 268435436, 268435437, 268435438, 268435439,
    268435440, 268435441, 268435442, 1073741822, 268435443, 268435444,
    268435445, 268435446, 268435447, 268435448, 268435449, 268435450,
    268435451, 20, 1016, 1017, 4090, 8185, 21, 248, 2042, 1018, 1019, 249,
    2043, 250, 22, 23, 24, 0, 1, 2, 25, 26, 27, 28, 29, 30, 31, 92, 251,
    32764, 32, 4091, 1020, 8186, 33, 93, 94, 95, 96, 97, 98, 99, 100, 101,
    102, 103, 104, 105, 106, 107, 108, 109, 110, 111, 112, 113, 114, 252,
    115, 253, 8187, 524272, 8188, 16380, 34, 32765, 3, 35, 4, 36, 5, 37, 38,
    39, 6, 116, 117, 40, 41, 42, 7, 43, 118, 44, 8, 9, 45, 119, 120, 121,
    122, 123, 32766, 2044, 16381, 8189, 268435452, 1048550, 4194258, 1048551,
    1048552, 4194259, 4194260, 4194261, 8388569, 4194262, 8388570, 8388571,
    8388572, 8388573, 8388574, 16777195, 8388575, 16777196, 16777197,
    4194263, 8388576, 16777198, 8388577, 8388578, 8388579, 8388580, 2097116,
    4194264, 8388581, 4194265, 8388582, 8388583, 16777199, 4194266, 2097117,
    1048553, 4194267, 4194268, 8388584, 8388585, 2097118, 8388586, 4194269,
    4194270, 16777200, 2097119, 4194271, 8388587, 8388588, 2097120, 2097121,
    4194272, 2097122, 8388589, 4194273, 8388590, 8388591, 1048554, 4194274,
    4194275, 4194276, 8388592, 4194277, 4194278, 8388593, 67108832, 67108833,
    1048555, 524273, 4194279, 8388594, 4194280, 33554412, 67108834, 67108835,
    67108836, 134217694, 134217695, 67108837, 16777201, 33554413, 524274,
    2097123, 67108838, 134217696, 134217697, 67108839, 134217698, 16777202,
    2097124, 2097125, 67108840, 67108841, 268435453, 134217699, 134217700,
    134217701, 1048556, 16777203, 1048557, 2097126, 4194281, 2097127,
    2097128, 8388595, 4194282, 4194283, 33554414, 33554415, 16777204,
    16777205, 67108842, 8388596, 67108843, 134217702, 67108844, 67108845,
    134217703, 134217704, 134217705, 134217706, 134217707, 268435454,
    134217708, 134217709, 134217710, 134217711, 134217712, 67108846,
    1073741823};
const uint8_t kHuffLens[257] = {
    13, 23, 28, 28, 28, 28, 28, 28, 28, 24, 30, 28, 28, 30, 28, 28, 28, 28,
    28, 28, 28, 28, 30, 28, 28, 28, 28, 28, 28, 28, 28, 28, 6,  10, 10, 12,
    13, 6,  8,  11, 10, 10, 8,  11, 8,  6,  6,  6,  5,  5,  5,  6,  6,  6,
    6,  6,  6,  6,  7,  8,  15, 6,  12, 10, 13, 6,  7,  7,  7,  7,  7,  7,
    7,  7,  7,  7,  7,  7,  7,  7,  7,  7,  7,  7,  7,  7,  7,  7,  8,  7,
    8,  13, 19, 13, 14, 6,  15, 5,  6,  5,  6,  5,  6,  6,  6,  5,  7,  7,
    6,  6,  6,  5,  6,  7,  6,  5,  5,  6,  7,  7,  7,  7,  7,  15, 11, 14,
    13, 28, 20, 22, 20, 20, 22, 22, 22, 23, 22, 23, 23, 23, 23, 23, 24, 23,
    24, 24, 22, 23, 24, 23, 23, 23, 23, 21, 22, 23, 22, 23, 23, 24, 22, 21,
    20, 22, 22, 23, 23, 21, 23, 22, 22, 24, 21, 22, 23, 23, 21, 21, 22, 21,
    23, 22, 23, 23, 20, 22, 22, 22, 23, 22, 22, 23, 26, 26, 20, 19, 22, 23,
    22, 25, 26, 26, 26, 27, 27, 26, 24, 25, 19, 21, 26, 27, 27, 26, 27, 24,
    21, 21, 26, 26, 28, 27, 27, 27, 20, 24, 20, 21, 22, 21, 21, 23, 22, 22,
    25, 25, 24, 24, 26, 23, 26, 27, 26, 26, 27, 27, 27, 27, 27, 28, 27, 27,
    27, 27, 27, 26, 30};

// Huffman decode trie: node pairs [zero_child, one_child], symbol per node.
struct HuffTrie {
  std::vector<int32_t> child;  // 2 per node, -1 = none
  std::vector<int16_t> sym;    // -1 = internal, 256 = EOS
  std::vector<bool> accept;    // all-ones-path states (legal padding ends)
  HuffTrie() {
    child.assign(2, -1);
    sym.assign(1, -1);
    for (int s = 0; s <= 256; s++) {
      uint32_t code = kHuffCodes[s];
      int len = kHuffLens[s];
      int n = 0;
      for (int i = len - 1; i >= 0; i--) {
        int bit = (code >> i) & 1;
        if (child[n * 2 + bit] < 0) {
          child[n * 2 + bit] = (int32_t)sym.size();
          child.push_back(-1);
          child.push_back(-1);
          sym.push_back(-1);
        }
        n = child[n * 2 + bit];
      }
      sym[n] = (int16_t)s;
    }
    accept.assign(sym.size(), false);
    accept[0] = true;
    int n = 0;
    for (;;) {
      n = child[n * 2 + 1];
      if (n < 0 || sym[n] >= 0) break;
      accept[n] = true;
    }
  }
};
const HuffTrie& huff_trie() {
  static HuffTrie t;
  return t;
}

bool huffman_decode(const uint8_t* data, size_t len, std::string& out) {
  const HuffTrie& t = huff_trie();
  int n = 0;
  for (size_t i = 0; i < len; i++) {
    for (int b = 7; b >= 0; b--) {
      int bit = (data[i] >> b) & 1;
      n = t.child[n * 2 + bit];
      if (n < 0) return false;
      int s = t.sym[n];
      if (s >= 0) {
        if (s == 256) return false;  // EOS in the body is an error
        out += (char)s;
        n = 0;
      }
    }
  }
  return t.accept[n];
}

struct HeaderPair {
  std::string name, value;
};

const HeaderPair kStaticTable[61] = {
    {":authority", ""}, {":method", "GET"}, {":method", "POST"},
    {":path", "/"}, {":path", "/index.html"}, {":scheme", "http"},
    {":scheme", "https"}, {":status", "200"}, {":status", "204"},
    {":status", "206"}, {":status", "304"}, {":status", "400"},
    {":status", "404"}, {":status", "500"}, {"accept-charset", ""},
    {"accept-encoding", "gzip, deflate"}, {"accept-language", ""},
    {"accept-ranges", ""}, {"accept", ""},
    {"access-control-allow-origin", ""}, {"age", ""}, {"allow", ""},
    {"authorization", ""}, {"cache-control", ""}, {"content-disposition", ""},
    {"content-encoding", ""}, {"content-language", ""}, {"content-length", ""},
    {"content-location", ""}, {"content-range", ""}, {"content-type", ""},
    {"cookie", ""}, {"date", ""}, {"etag", ""}, {"expect", ""},
    {"expires", ""}, {"from", ""}, {"host", ""}, {"if-match", ""},
    {"if-modified-since", ""}, {"if-none-match", ""}, {"if-range", ""},
    {"if-unmodified-since", ""}, {"last-modified", ""}, {"link", ""},
    {"location", ""}, {"max-forwards", ""}, {"proxy-authenticate", ""},
    {"proxy-authorization", ""}, {"range", ""}, {"referer", ""},
    {"refresh", ""}, {"retry-after", ""}, {"server", ""}, {"set-cookie", ""},
    {"strict-transport-security", ""}, {"transfer-encoding", ""},
    {"user-agent", ""}, {"vary", ""}, {"via", ""}, {"www-authenticate", ""}};

class HpackDec {
 public:
  explicit HpackDec(size_t max_table = 4096) : max_size_(max_table) {}

  // decode one header block; false on malformed (connection error)
  bool decode(const uint8_t* p, size_t len,
              std::vector<HeaderPair>& out) {
    size_t pos = 0;
    while (pos < len) {
      uint8_t b = p[pos];
      if (b & 0x80) {  // indexed
        uint64_t idx;
        if (!dec_int(p, len, pos, 7, idx) || idx == 0) return false;
        HeaderPair hp;
        if (!entry(idx, hp)) return false;
        out.push_back(std::move(hp));
      } else if ((b & 0xC0) == 0x40) {  // literal, incremental indexing
        uint64_t idx;
        if (!dec_int(p, len, pos, 6, idx)) return false;
        HeaderPair hp;
        if (!literal(p, len, pos, idx, hp)) return false;
        insert(hp);
        out.push_back(std::move(hp));
      } else if ((b & 0xE0) == 0x20) {  // dynamic table size update
        uint64_t sz;
        if (!dec_int(p, len, pos, 5, sz)) return false;
        if (sz > max_size_limit_) return false;
        max_size_ = (size_t)sz;
        evict();
      } else {  // literal without indexing / never indexed (4-bit prefix)
        uint64_t idx;
        if (!dec_int(p, len, pos, 4, idx)) return false;
        HeaderPair hp;
        if (!literal(p, len, pos, idx, hp)) return false;
        out.push_back(std::move(hp));
      }
    }
    return true;
  }

 private:
  std::deque<HeaderPair> dyn_;
  size_t dyn_size_ = 0;
  size_t max_size_;
  size_t max_size_limit_ = 4096;

  bool entry(uint64_t idx, HeaderPair& out) {
    if (idx >= 1 && idx <= 61) {
      out = kStaticTable[idx - 1];
      return true;
    }
    size_t d = (size_t)idx - 62;
    if (d >= dyn_.size()) return false;
    out = dyn_[d];
    return true;
  }

  void insert(const HeaderPair& hp) {
    size_t sz = hp.name.size() + hp.value.size() + 32;
    dyn_.push_front(hp);
    dyn_size_ += sz;
    evict();
  }

  void evict() {
    while (dyn_size_ > max_size_ && !dyn_.empty()) {
      dyn_size_ -= dyn_.back().name.size() + dyn_.back().value.size() + 32;
      dyn_.pop_back();
    }
  }

  static bool dec_int(const uint8_t* p, size_t len, size_t& pos, int prefix,
                      uint64_t& out) {
    if (pos >= len) return false;
    uint64_t mask = (1u << prefix) - 1;
    out = p[pos++] & mask;
    if (out < mask) return true;
    int shift = 0;
    for (;;) {
      if (pos >= len || shift > 35) return false;
      uint8_t b = p[pos++];
      out += (uint64_t)(b & 0x7F) << shift;
      shift += 7;
      if (!(b & 0x80)) return true;
    }
  }

  static bool dec_str(const uint8_t* p, size_t len, size_t& pos,
                      std::string& out) {
    if (pos >= len) return false;
    bool huff = p[pos] & 0x80;
    uint64_t n;
    if (!dec_int(p, len, pos, 7, n)) return false;
    if (pos + n > len) return false;
    if (huff) {
      if (!huffman_decode(p + pos, (size_t)n, out)) return false;
    } else {
      out.assign((const char*)p + pos, (size_t)n);
    }
    pos += (size_t)n;
    return true;
  }

  bool literal(const uint8_t* p, size_t len, size_t& pos, uint64_t name_idx,
               HeaderPair& out) {
    if (name_idx) {
      HeaderPair nm;
      if (!entry(name_idx, nm)) return false;
      out.name = std::move(nm.name);
    } else if (!dec_str(p, len, pos, out.name)) {
      return false;
    }
    return dec_str(p, len, pos, out.value);
  }
};

// --- protobuf tensor scan (mirrors native/protowire.py exactly) ------------

bool pw_varint(const uint8_t* p, size_t len, size_t& pos, uint64_t& out) {
  out = 0;
  int shift = 0;
  while (pos < len) {
    uint8_t b = p[pos++];
    out |= (uint64_t)(b & 0x7F) << shift;
    if (!(b & 0x80)) return true;
    shift += 7;
    if (shift > 63) return false;
  }
  return false;
}

bool pw_skip(const uint8_t* p, size_t len, size_t& pos, int wt) {
  uint64_t n;
  switch (wt) {
    case 0: return pw_varint(p, len, pos, n);
    case 1: pos += 8; return pos <= len;
    case 2:
      if (!pw_varint(p, len, pos, n) || pos + n > len) return false;
      pos += (size_t)n;
      return true;
    case 5: pos += 4; return pos <= len;
    default: return false;
  }
}

// SeldonMessage{meta{puid only}, data{names*, tensor{shape packed, values
// packed}}} -> rows/width/values-span/puid; anything else declines (misc
// lane = full protobuf semantics), exactly like protowire.parse_tensor_request
struct PwTensor {
  const uint8_t* values = nullptr;
  long long nvalues = 0;
  std::vector<long long> shape;
  std::string puid;
};

bool pw_scan_meta(const uint8_t* p, size_t len, std::string& puid) {
  size_t pos = 0;
  while (pos < len) {
    uint64_t key;
    if (!pw_varint(p, len, pos, key)) return false;
    if ((key >> 3) == 1 && (key & 7) == 2) {
      uint64_t n;
      if (!pw_varint(p, len, pos, n) || pos + n > len) return false;
      puid.assign((const char*)p + pos, (size_t)n);
      pos += (size_t)n;
    } else {
      return false;  // tags/routing/requestPath present -> full parser
    }
  }
  return true;
}

bool pw_scan_tensor(const uint8_t* p, size_t len, PwTensor& t) {
  size_t pos = 0;
  bool have_values = false;
  while (pos < len) {
    uint64_t key;
    if (!pw_varint(p, len, pos, key)) return false;
    int field = (int)(key >> 3), wt = (int)(key & 7);
    if (field == 1) {  // shape, packed (or repeated varint)
      if (wt == 2) {
        uint64_t n;
        if (!pw_varint(p, len, pos, n) || pos + n > len) return false;
        size_t sub_end = pos + (size_t)n;
        while (pos < sub_end) {
          uint64_t d;
          if (!pw_varint(p, sub_end, pos, d)) return false;
          t.shape.push_back((long long)d);
        }
      } else if (wt == 0) {
        uint64_t d;
        if (!pw_varint(p, len, pos, d)) return false;
        t.shape.push_back((long long)d);
      } else {
        return false;
      }
    } else if (field == 2) {  // values, packed doubles
      if (wt != 2 || have_values) return false;  // split packed -> merge
      uint64_t n;
      if (!pw_varint(p, len, pos, n) || pos + n > len || n % 8) return false;
      t.values = p + pos;
      t.nvalues = (long long)(n / 8);
      have_values = true;
      pos += (size_t)n;
    } else {
      if (!pw_skip(p, len, pos, wt)) return false;
    }
  }
  return have_values;
}

bool pw_parse_request(const uint8_t* p, size_t len, PwTensor& t) {
  size_t pos = 0;
  bool seen_meta = false, seen_data = false, have_tensor = false;
  while (pos < len) {
    uint64_t key;
    if (!pw_varint(p, len, pos, key)) return false;
    int field = (int)(key >> 3), wt = (int)(key & 7);
    if (field == 2 && wt == 2) {  // meta
      if (seen_meta) return false;  // repeated -> merge semantics
      seen_meta = true;
      uint64_t n;
      if (!pw_varint(p, len, pos, n) || pos + n > len) return false;
      if (!pw_scan_meta(p + pos, (size_t)n, t.puid)) return false;
      pos += (size_t)n;
    } else if (field == 3 && wt == 2) {  // data
      if (seen_data) return false;
      seen_data = true;
      uint64_t n;
      if (!pw_varint(p, len, pos, n) || pos + n > len) return false;
      const uint8_t* sub = p + pos;
      size_t slen = (size_t)n, spos = 0;
      pos += (size_t)n;
      while (spos < slen) {
        uint64_t skey;
        if (!pw_varint(sub, slen, spos, skey)) return false;
        int sf = (int)(skey >> 3), swt = (int)(skey & 7);
        if (sf == 2 && swt == 2) {  // tensor
          if (have_tensor) return false;
          uint64_t sn;
          if (!pw_varint(sub, slen, spos, sn) || spos + sn > slen)
            return false;
          if (!pw_scan_tensor(sub + spos, (size_t)sn, t)) return false;
          have_tensor = true;
          spos += (size_t)sn;
        } else if (sf == 1 && swt == 2) {  // names: ignored on input
          if (!pw_skip(sub, slen, spos, swt)) return false;
        } else {
          return false;  // ndarray and friends -> full parser
        }
      }
    } else if (field == 1 || field == 4 || field == 5) {
      return false;  // status / binData / strData
    } else {
      if (!pw_skip(p, len, pos, wt)) return false;
    }
  }
  if (!have_tensor) return false;
  if (t.shape.empty()) t.shape.push_back(t.nvalues);
  long long prod = 1;
  for (long long d : t.shape) {
    // overflow-guarded product: a crafted shape like [4, 2^62] must
    // decline (the Python lane's np.reshape raises), not wrap around
    if (d < 0 || (d > 0 && prod > (1LL << 40) / d)) return false;
    prod *= d;
  }
  return prod == t.nvalues && t.nvalues > 0;
}

void pw_append_varint(std::string& out, uint64_t v) {
  while (v >= 0x80) {
    out += (char)((v & 0x7F) | 0x80);
    v >>= 7;
  }
  out += (char)v;
}

void pw_append_len_field(std::string& out, int field,
                         const std::string& payload) {
  out += (char)((field << 3) | 2);
  pw_append_varint(out, payload.size());
  out += payload;
}

// SUCCESS SeldonMessage wire bytes — protowire.build_tensor_response port
std::string pw_build_response(const std::string& puid, const double* y,
                              long long rows, long long cols,
                              const std::string& names_frag) {
  std::string tensor;
  std::string shape_payload;
  pw_append_varint(shape_payload, (uint64_t)rows);
  pw_append_varint(shape_payload, (uint64_t)cols);
  pw_append_len_field(tensor, 1, shape_payload);
  std::string values((const char*)y, (size_t)(rows * cols) * 8);
  pw_append_len_field(tensor, 2, values);
  std::string data = names_frag;
  pw_append_len_field(data, 2, tensor);
  std::string meta;
  pw_append_len_field(meta, 1, puid);
  // Status{code=200, status=SUCCESS(0)}: zero enum omitted on the wire
  std::string status;
  status += (char)0x08;
  pw_append_varint(status, 200);
  std::string out;
  out.reserve(status.size() + meta.size() + data.size() + 16);
  pw_append_len_field(out, 1, status);
  pw_append_len_field(out, 2, meta);
  pw_append_len_field(out, 3, data);
  return out;
}

// --- HTTP/2 connection state ----------------------------------------------

constexpr uint8_t H2_DATA = 0, H2_HEADERS = 1, H2_RST = 3, H2_SETTINGS = 4,
                  H2_PING = 6, H2_GOAWAY = 7, H2_WINDOW_UPDATE = 8,
                  H2_CONTINUATION = 9;
constexpr uint8_t H2F_END_STREAM = 0x1, H2F_ACK = 0x1, H2F_END_HEADERS = 0x4,
                  H2F_PADDED = 0x8, H2F_PRIORITY = 0x20;
constexpr int64_t H2_DEFAULT_WINDOW = 65535;
constexpr int64_t H2_BIG_WINDOW = 0x7fffffff;
constexpr size_t H2_MAX_MESSAGE = 64u * 1024 * 1024;
const char kH2Preface[] = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";

struct H2State {
  bool preface_done = false;
  HpackDec hpack;
  struct Stream {
    std::string path;
    std::string body;
  };
  std::unordered_map<uint32_t, Stream> streams;
  size_t buffered = 0;  // sum of open-stream body bytes (backpressure cap)
  // fast-lane / misc work in flight, keyed by stream; false after RST
  std::unordered_map<uint32_t, bool> live;
  int64_t conn_send_window = H2_DEFAULT_WINDOW;
  int64_t peer_initial_window = H2_DEFAULT_WINDOW;
  std::unordered_map<uint32_t, int64_t> stream_windows;
  uint32_t peer_max_frame = 16384;
  uint64_t recv_since_update = 0;
  // flow-stalled sends: payload remainder + trailer per stream, FIFO
  struct Tx {
    uint32_t sid;
    std::string data;
    size_t off;
    std::string trailer;
  };
  std::deque<Tx> txq;
  // CONTINUATION accumulation
  bool in_headers = false;
  uint32_t headers_sid = 0;
  bool headers_end_stream = false;
  std::string headers_accum;
};

void h2_frame_header(std::string& out, uint32_t len, uint8_t type,
                     uint8_t flags, uint32_t sid) {
  out += (char)((len >> 16) & 0xff);
  out += (char)((len >> 8) & 0xff);
  out += (char)(len & 0xff);
  out += (char)type;
  out += (char)flags;
  out += (char)((sid >> 24) & 0x7f);
  out += (char)((sid >> 16) & 0xff);
  out += (char)((sid >> 8) & 0xff);
  out += (char)(sid & 0xff);
}

// response HEADERS / OK-trailers header blocks: static-table + literal
// encodings only (no dynamic-table state), constant for every response
const std::string& h2_resp_headers_block() {
  static const std::string block = [] {
    std::string b;
    b += (char)0x88;  // :status 200 (static index 8)
    // content-type: application/grpc — literal w/o indexing, name idx 31
    b += (char)0x0f;
    b += (char)0x10;
    const char* v = "application/grpc";
    b += (char)strlen(v);
    b += v;
    return b;
  }();
  return block;
}

std::string h2_trailers_block(int grpc_status, const std::string& msg) {
  std::string b;
  auto lit = [&](const char* name, const std::string& value) {
    b += (char)0x00;
    b += (char)strlen(name);
    b += name;
    // 7-bit prefixed length, no huffman
    if (value.size() < 127) {
      b += (char)value.size();
    } else {
      b += (char)0x7f;
      uint64_t v = value.size() - 127;
      while (v >= 0x80) { b += (char)((v & 0x7f) | 0x80); v >>= 7; }
      b += (char)v;
    }
    b += value;
  };
  lit("grpc-status", std::to_string(grpc_status));
  lit("grpc-message", msg.substr(0, 1024));
  return b;
}

void h2_fatal(Plane* pl, int ci, const char* reason) {
  Conn& c = *pl->conns[ci];
  if (c.fd >= 0) {
    std::string go;
    h2_frame_header(go, 8 + (uint32_t)strlen(reason), H2_GOAWAY, 0, 0);
    uint32_t last = 0;
    go += (char)((last >> 24) & 0x7f);
    go += (char)((last >> 16) & 0xff);
    go += (char)((last >> 8) & 0xff);
    go += (char)(last & 0xff);
    uint32_t err = 1;  // PROTOCOL_ERROR
    go += (char)((err >> 24) & 0xff);
    go += (char)((err >> 16) & 0xff);
    go += (char)((err >> 8) & 0xff);
    go += (char)(err & 0xff);
    go += reason;
    (void)!write(c.fd, go.data(), go.size());  // best effort
  }
  conn_close(pl, ci);
}

// append DATA frames for [payload+off ..) within window limits; returns new
// offset.  Trailer is sent once the payload fully drains.
size_t h2_pump_stream(Plane* pl, int ci, uint32_t sid,
                      const std::string& payload, size_t off,
                      const std::string& trailer) {
  Conn& c = *pl->conns[ci];
  H2State& h = *c.h2s;
  while (off < payload.size()) {
    auto itw = h.stream_windows.find(sid);
    int64_t sw = itw != h.stream_windows.end() ? itw->second
                                               : h.peer_initial_window;
    int64_t window = std::min(h.conn_send_window, sw);
    int64_t n = std::min<int64_t>(
        {(int64_t)(payload.size() - off), window, (int64_t)h.peer_max_frame});
    if (n <= 0) return off;  // stalled; resumes on WINDOW_UPDATE
    h2_frame_header(c.out, (uint32_t)n, H2_DATA, 0, sid);
    c.out.append(payload, off, (size_t)n);
    off += (size_t)n;
    h.conn_send_window -= n;
    h.stream_windows[sid] = sw - n;
  }
  c.out += trailer;
  h.stream_windows.erase(sid);
  return off;
}

void h2_pump_txq(Plane* pl, int ci) {
  Conn& c = *pl->conns[ci];
  H2State& h = *c.h2s;
  while (!h.txq.empty()) {
    H2State::Tx& tx = h.txq.front();
    tx.off = h2_pump_stream(pl, ci, tx.sid, tx.data, tx.off, tx.trailer);
    if (tx.off < tx.data.size()) return;  // still stalled
    h.txq.pop_front();
  }
}

// queue a complete gRPC response (HEADERS + DATA + trailers) on the conn
void h2_send_response(Plane* pl, int ci, uint32_t sid,
                      const std::string& grpc_payload) {
  Conn& c = *pl->conns[ci];
  H2State& h = *c.h2s;
  h2_frame_header(c.out, (uint32_t)h2_resp_headers_block().size(), H2_HEADERS,
                  H2F_END_HEADERS, sid);
  c.out += h2_resp_headers_block();
  std::string trailer;
  static const std::string ok_trailers = h2_trailers_block(0, "");
  h2_frame_header(trailer, (uint32_t)ok_trailers.size(), H2_HEADERS,
                  H2F_END_HEADERS | H2F_END_STREAM, sid);
  trailer += ok_trailers;
  if (!h.txq.empty()) {
    // keep per-conn FIFO so stalled streams don't reorder DATA
    h.txq.push_back({sid, grpc_payload, 0, std::move(trailer)});
    h2_pump_txq(pl, ci);
    return;
  }
  size_t off = h2_pump_stream(pl, ci, sid, grpc_payload, 0, trailer);
  if (off < grpc_payload.size())
    h.txq.push_back({sid, grpc_payload.substr(off), 0, std::move(trailer)});
}

void h2_trailers_only(Plane* pl, int ci, uint32_t sid, int grpc_status,
                      const std::string& msg) {
  Conn& c = *pl->conns[ci];
  // every error path ends the stream here: drop its send-window slot
  // (opened at dispatch) or the map grows by one entry per failed RPC
  c.h2s->stream_windows.erase(sid);
  std::string block;
  block += (char)0x88;  // :status 200
  block += (char)0x0f;
  block += (char)0x10;
  const char* v = "application/grpc";
  block += (char)strlen(v);
  block += v;
  block += h2_trailers_block(grpc_status, msg);
  h2_frame_header(c.out, (uint32_t)block.size(), H2_HEADERS,
                  H2F_END_HEADERS | H2F_END_STREAM, sid);
  c.out += block;
}

// dispatch one complete gRPC unary message (frame prefix already verified)
void h2_handle_message(Plane* pl, int ci, uint32_t sid,
                       const std::string& path, const uint8_t* msg,
                       size_t mlen, bool& want_flush) {
  Conn& c = *pl->conns[ci];
  H2State& h = *c.h2s;
  want_flush = true;
  // Model alias == Seldon service: an engine composes as a MODEL leaf of
  // a larger cross-process graph (grpc_server.make_engine_grpc_server)
  if (path == "/seldon.protos.Seldon/Predict" ||
      path == "/seldon.protos.Model/Predict") {
    PwTensor t;
    if (pw_parse_request(msg, mlen, t)) {
      long long rows = t.shape.size() >= 2 ? t.shape[0] : 1;
      long long width = t.shape.size() >= 2 ? t.nvalues / t.shape[0]
                                            : t.nvalues;
      // >2-D tensors flatten per leading dim like protowire's reshape
      if (rows > 0 && width > 0 && rows * width == t.nvalues &&
          rows <= pl->max_batch) {
        ReqInfo r;
        r.conn_id = ci;
        r.conn_gen = c.gen;
        r.seq = 0;
        r.kind = KIND_PROTO;
        r.rows = rows;
        r.h2 = true;
        r.stream = sid;
        r.puid = std::move(t.puid);
        r.t0 = now_s();
        // packed doubles are little-endian on the wire; memcpy inside
        // enqueue_rows is exact on this platform (x86/ARM LE)
        if (!enqueue_rows(pl, std::move(r), t.values, rows, width)) {
          h2_trailers_only(pl, ci, sid, 8 /* RESOURCE_EXHAUSTED */,
                           "overloaded");
          return;
        }
        h.live[sid] = true;
        // open the send window slot now so stream WINDOW_UPDATEs arriving
        // before the response (INITIAL_WINDOW_SIZE=0 clients) accumulate
        h.stream_windows.emplace(sid, h.peer_initial_window);
        return;
      }
    }
  }
  // misc lane: full protobuf/service semantics in Python
  auto m = std::make_unique<MiscReq>();
  m->conn_id = ci;
  m->conn_gen = c.gen;
  m->seq = 0;
  m->close_c = false;
  m->method = "GRPC";
  m->path = path;
  m->body.assign((const char*)msg, mlen);
  h.live[sid] = true;
  h.stream_windows.emplace(sid, h.peer_initial_window);
  m->h2 = true;
  m->stream = sid;
  std::lock_guard<std::mutex> lk(pl->mu);
  m->id = pl->next_misc_id++;
  pl->misc_q.push_back(std::move(m));
  pl->cv_misc.notify_one();
}

void h2_parse(Plane* pl, int ci) {
  Conn& c = *pl->conns[ci];
  H2State& h = *c.h2s;
  size_t consumed = 0;
  bool want_flush = false;
  while (c.fd >= 0) {
    if (!h.preface_done) {
      if (c.in.size() - consumed < 24) break;
      if (memcmp(c.in.data() + consumed, kH2Preface, 24) != 0) {
        h2_fatal(pl, ci, "bad preface");
        return;
      }
      consumed += 24;
      h.preface_done = true;
      continue;
    }
    if (c.in.size() - consumed < 9) break;
    const uint8_t* p = (const uint8_t*)c.in.data() + consumed;
    uint32_t len = (p[0] << 16) | (p[1] << 8) | p[2];
    if (len > (1u << 24) - 1 || len > 16u * 1024 * 1024) {
      h2_fatal(pl, ci, "frame too large");
      return;
    }
    if (c.in.size() - consumed < 9 + (size_t)len) break;
    uint8_t type = p[3], flags = p[4];
    uint32_t sid = ((p[5] & 0x7f) << 24) | (p[6] << 16) | (p[7] << 8) | p[8];
    const uint8_t* payload = p + 9;
    consumed += 9 + len;
    if (h.in_headers && type != H2_CONTINUATION) {
      h2_fatal(pl, ci, "expected CONTINUATION");
      return;
    }
    switch (type) {
      case H2_SETTINGS: {
        if (flags & H2F_ACK) break;
        if (len % 6) { h2_fatal(pl, ci, "bad SETTINGS"); return; }
        for (uint32_t i = 0; i + 6 <= len; i += 6) {
          uint16_t k = (payload[i] << 8) | payload[i + 1];
          uint32_t v = (payload[i + 2] << 24) | (payload[i + 3] << 16) |
                       (payload[i + 4] << 8) | payload[i + 5];
          if (k == 0x4) {  // INITIAL_WINDOW_SIZE
            int64_t delta = (int64_t)v - h.peer_initial_window;
            h.peer_initial_window = v;
            for (auto& kv : h.stream_windows) kv.second += delta;
          } else if (k == 0x5) {  // MAX_FRAME_SIZE
            if (v >= 16384 && v <= 16777215) h.peer_max_frame = v;
          }
        }
        h2_frame_header(c.out, 0, H2_SETTINGS, H2F_ACK, 0);
        h2_pump_txq(pl, ci);  // a raised INITIAL_WINDOW_SIZE unstalls
        want_flush = true;
        break;
      }
      case H2_PING:
        if (!(flags & H2F_ACK) && len == 8) {
          h2_frame_header(c.out, 8, H2_PING, H2F_ACK, 0);
          c.out.append((const char*)payload, 8);
          want_flush = true;
        }
        break;
      case H2_WINDOW_UPDATE: {
        if (len != 4) { h2_fatal(pl, ci, "bad WINDOW_UPDATE"); return; }
        uint32_t inc = ((payload[0] & 0x7f) << 24) | (payload[1] << 16) |
                       (payload[2] << 8) | payload[3];
        if (sid == 0) h.conn_send_window += inc;
        else {
          auto it = h.stream_windows.find(sid);
          if (it != h.stream_windows.end()) it->second += inc;
        }
        h2_pump_txq(pl, ci);
        want_flush = true;
        break;
      }
      case H2_HEADERS: {
        size_t off = 0;
        uint8_t pad = 0;
        if (flags & H2F_PADDED) { if (len < 1) { h2_fatal(pl, ci, "pad"); return; } pad = payload[off++]; }
        if (flags & H2F_PRIORITY) { off += 5; }
        if (off + pad > len) { h2_fatal(pl, ci, "pad"); return; }
        h.headers_sid = sid;
        h.headers_end_stream = flags & H2F_END_STREAM;
        h.headers_accum.assign((const char*)payload + off,
                               len - off - pad);
        if (flags & H2F_END_HEADERS) {
          std::vector<HeaderPair> headers;
          if (!h.hpack.decode((const uint8_t*)h.headers_accum.data(),
                              h.headers_accum.size(), headers)) {
            h2_fatal(pl, ci, "hpack error");
            return;
          }
          std::string path;
          for (auto& hp : headers)
            if (hp.name == ":path") { path = hp.value; break; }
          if (h.streams.size() >= 65536) {
            h2_fatal(pl, ci, "too many open streams");
            return;
          }
          h.streams[sid] = {std::move(path), {}};
          if (h.headers_end_stream) {
            h.streams.erase(sid);
            h2_trailers_only(pl, ci, sid, 13, "missing request body");
            want_flush = true;
          }
        } else {
          h.in_headers = true;
        }
        break;
      }
      case H2_CONTINUATION: {
        if (!h.in_headers || sid != h.headers_sid) {
          h2_fatal(pl, ci, "unexpected CONTINUATION");
          return;
        }
        h.headers_accum.append((const char*)payload, len);
        if (h.headers_accum.size() > 1u << 20) {
          h2_fatal(pl, ci, "headers too large");
          return;
        }
        if (flags & H2F_END_HEADERS) {
          h.in_headers = false;
          std::vector<HeaderPair> headers;
          if (!h.hpack.decode((const uint8_t*)h.headers_accum.data(),
                              h.headers_accum.size(), headers)) {
            h2_fatal(pl, ci, "hpack error");
            return;
          }
          std::string path;
          for (auto& hp : headers)
            if (hp.name == ":path") { path = hp.value; break; }
          h.streams[h.headers_sid] = {std::move(path), {}};
          if (h.headers_end_stream) {
            h.streams.erase(h.headers_sid);
            h2_trailers_only(pl, ci, h.headers_sid, 13,
                             "missing request body");
            want_flush = true;
          }
        }
        break;
      }
      case H2_DATA: {
        size_t off = 0;
        uint8_t pad = 0;
        if (flags & H2F_PADDED) { if (len < 1) { h2_fatal(pl, ci, "pad"); return; } pad = payload[off++]; }
        if (off + pad > len) { h2_fatal(pl, ci, "pad"); return; }
        h.recv_since_update += len;
        if (h.recv_since_update >= (1u << 20)) {
          h2_frame_header(c.out, 4, H2_WINDOW_UPDATE, 0, 0);
          uint32_t inc = (uint32_t)h.recv_since_update;
          c.out += (char)((inc >> 24) & 0x7f);
          c.out += (char)((inc >> 16) & 0xff);
          c.out += (char)((inc >> 8) & 0xff);
          c.out += (char)(inc & 0xff);
          h.recv_since_update = 0;
          want_flush = true;
        }
        auto it = h.streams.find(sid);
        if (it == h.streams.end()) break;  // unknown/aborted stream
        it->second.body.append((const char*)payload + off, len - off - pad);
        h.buffered += len - off - pad;
        if (it->second.body.size() > H2_MAX_MESSAGE + 5) {
          h.buffered -= it->second.body.size();
          h2_trailers_only(pl, ci, sid, 8, "message too large");
          h.streams.erase(it);
          want_flush = true;
          break;
        }
        if (h.buffered > 256u * 1024 * 1024) {
          // connection-level memory backstop: a client streaming unbounded
          // bodies across many open streams is killed, the same budget the
          // HTTP lane enforces per body (_MAX_BODY)
          h2_fatal(pl, ci, "connection buffer budget exceeded");
          return;
        }
        if (flags & H2F_END_STREAM) {
          std::string path = std::move(it->second.path);
          std::string body = std::move(it->second.body);
          h.buffered -= body.size();
          h.streams.erase(it);
          if (body.size() < 5 || body[0] != 0) {
            h2_trailers_only(pl, ci, sid, 13,
                             "compressed or malformed grpc frame");
            want_flush = true;
            break;
          }
          uint32_t mlen = ((uint8_t)body[1] << 24) | ((uint8_t)body[2] << 16) |
                          ((uint8_t)body[3] << 8) | (uint8_t)body[4];
          if (mlen != body.size() - 5) {
            h2_trailers_only(pl, ci, sid, 13, "grpc frame length mismatch");
            want_flush = true;
            break;
          }
          bool wf = false;
          h2_handle_message(pl, ci, sid, path,
                            (const uint8_t*)body.data() + 5, mlen, wf);
          want_flush = want_flush || wf;
        }
        break;
      }
      case H2_RST: {
        auto sit = h.streams.find(sid);
        if (sit != h.streams.end()) {
          h.buffered -= sit->second.body.size();
          h.streams.erase(sit);
        }
        h.stream_windows.erase(sid);
        auto it = h.live.find(sid);
        if (it != h.live.end()) it->second = false;  // drop the response
        // purge any flow-stalled response for the cancelled stream: the
        // client will never grant it window, and a stalled txq head would
        // head-of-line-block every later response on this connection
        for (auto tit = h.txq.begin(); tit != h.txq.end();) {
          if (tit->sid == sid) tit = h.txq.erase(tit);
          else ++tit;
        }
        h2_pump_txq(pl, ci);
        want_flush = true;
        break;
      }
      case H2_GOAWAY:
        conn_close(pl, ci);
        return;
      default:
        break;  // PRIORITY / PUSH_PROMISE / unknown: ignore
    }
  }
  if (c.fd >= 0 && consumed) c.in.erase(0, consumed);
  if (c.fd >= 0 && want_flush) conn_flush(pl, ci);
}

void drain_completions(Plane* pl) {
  uint64_t junk;
  (void)!read(pl->evfd, &junk, 8);
  std::vector<Plane::Completion> local;
  {
    std::lock_guard<std::mutex> lk(pl->cmu);
    local.swap(pl->completions);
  }
  // group flushes: mark conns dirty, flush each once
  std::vector<int> dirty;
  for (auto& item : local) {
    int ci = item.conn_id;
    if (ci < 0 || ci >= (int)pl->conns.size()) continue;
    Conn& c = *pl->conns[ci];
    if (c.fd < 0 || c.gen != item.gen) continue;  // conn died meanwhile
    if (item.h2) {
      H2State& h = *c.h2s;
      auto it = h.live.find(item.stream);
      bool alive = it == h.live.end() || it->second;  // RST'd -> drop
      if (it != h.live.end()) h.live.erase(it);
      if (!alive) {
        h.stream_windows.erase(item.stream);
        continue;
      }
      if (item.grpc_status == 0)
        h2_send_response(pl, ci, item.stream, item.data);
      else
        h2_trailers_only(pl, ci, item.stream, item.grpc_status, item.data);
    } else {
      c.done[item.seq] = std::move(item.data);
    }
    dirty.push_back(ci);
  }
  std::sort(dirty.begin(), dirty.end());
  dirty.erase(std::unique(dirty.begin(), dirty.end()), dirty.end());
  for (int ci : dirty) conn_flush(pl, ci);
}

void io_loop(Plane* pl) {
  std::vector<struct epoll_event> events(512);
  while (!pl->stop.load(std::memory_order_relaxed)) {
    // batch deadline: the oldest open accumulation decides the poll timeout
    int timeout_ms = 1000;
    {
      std::lock_guard<std::mutex> lk(pl->mu);
      if (!pl->accum.empty() && pl->inflight_count < pl->depth) {
        double oldest = 1e300;
        for (auto& kv : pl->accum)
          if (kv.second && kv.second->t_first < oldest)
            oldest = kv.second->t_first;
        double dl = oldest + pl->max_wait_s - now_s();
        timeout_ms = dl <= 0 ? 0 : (int)(dl * 1000) + 1;
      }
    }
    int n = epoll_wait(pl->ep, events.data(), (int)events.size(), timeout_ms);
    if (n < 0 && errno != EINTR) break;
    for (int e = 0; e < n; e++) {
      int idx = (int)(int32_t)events[e].data.u64;
      if (idx == EvTag::LISTEN || idx == EvTag::LISTEN_GRPC) {
        bool h2 = idx == EvTag::LISTEN_GRPC;
        int lfd = h2 ? pl->grpc_listen_fd : pl->listen_fd;
        for (;;) {
          int fd = accept4(lfd, nullptr, nullptr, SOCK_NONBLOCK);
          if (fd < 0) break;
          int one = 1;
          setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
          int ci;
          if (!pl->free_conns.empty()) {
            ci = pl->free_conns.back();
            pl->free_conns.pop_back();
          } else {
            ci = (int)pl->conns.size();
            pl->conns.emplace_back(new Conn());
          }
          Conn& c = *pl->conns[ci];
          c.fd = fd;
          c.h2 = h2;
          c.scan_from = 0;
          c.head_end = -1;
          c.clen = -1;
          c.head_parsed = false;
          c.next_assign = c.next_write = 0;
          c.close_after = UINT64_MAX;
          c.out_off = 0;
          c.want_write = false;
          c.paused = false;
          if (h2) {
            c.h2s.reset(new H2State());
            // server bootstrap: big receive windows so uploads never
            // stall on us (the same bootstrap grpcfast.py performs)
            std::string boot;
            h2_frame_header(boot, 12, H2_SETTINGS, 0, 0);
            auto put_setting = [&](uint16_t k, uint32_t v) {
              boot += (char)(k >> 8);
              boot += (char)(k & 0xff);
              boot += (char)((v >> 24) & 0xff);
              boot += (char)((v >> 16) & 0xff);
              boot += (char)((v >> 8) & 0xff);
              boot += (char)(v & 0xff);
            };
            put_setting(0x4, (uint32_t)H2_BIG_WINDOW);
            put_setting(0x3, 1u << 20);
            h2_frame_header(boot, 4, H2_WINDOW_UPDATE, 0, 0);
            uint32_t inc = (uint32_t)(H2_BIG_WINDOW - H2_DEFAULT_WINDOW);
            boot += (char)((inc >> 24) & 0x7f);
            boot += (char)((inc >> 16) & 0xff);
            boot += (char)((inc >> 8) & 0xff);
            boot += (char)(inc & 0xff);
            c.out += boot;
          } else {
            c.h2s.reset();
          }
          arm(pl, fd, ci, EPOLLIN, EPOLL_CTL_ADD);
          if (h2) conn_flush(pl, ci);
        }
        continue;
      }
      if (idx == EvTag::EVENT) {
        drain_completions(pl);
        continue;
      }
      if (idx < 0 || idx >= (int)pl->conns.size()) continue;
      Conn& c = *pl->conns[idx];
      if (c.fd < 0) continue;
      if (events[e].events & (EPOLLERR | EPOLLHUP)) {
        conn_close(pl, idx);
        continue;
      }
      if (events[e].events & EPOLLOUT) conn_flush(pl, idx);
      if (c.fd >= 0 && (events[e].events & EPOLLIN)) {
        conn_data(pl, idx);
        if (c.fd >= 0) conn_flush(pl, idx);  // parse-path responses
      }
    }
    if (!pl->resume_parse.empty()) {
      // connections that resumed from backpressure may hold complete
      // buffered requests that arrived while reading was paused
      std::vector<int> resumed;
      resumed.swap(pl->resume_parse);
      for (int ci : resumed) {
        if (pl->conns[ci]->fd < 0) continue;
        if (pl->conns[ci]->h2) h2_parse(pl, ci);
        else conn_parse(pl, ci);
        if (pl->conns[ci]->fd >= 0) conn_flush(pl, ci);
      }
    }
    // flush aged batches
    {
      std::lock_guard<std::mutex> lk(pl->mu);
      if (pl->inflight_count < pl->depth) {
        double now = now_s();
        std::vector<long long> due;
        for (auto& kv : pl->accum)
          if (kv.second && now - kv.second->t_first >= pl->max_wait_s)
            due.push_back(kv.first);
        for (long long w : due) flush_batch_locked(pl, w);
      }
    }
  }
  // shutdown: close everything
  for (size_t i = 0; i < pl->conns.size(); i++)
    if (pl->conns[i]->fd >= 0) conn_close(pl, (int)i);
  if (pl->listen_fd >= 0) close(pl->listen_fd);
  if (pl->grpc_listen_fd >= 0) close(pl->grpc_listen_fd);
  pl->cv_batch.notify_all();
  pl->cv_misc.notify_all();
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

struct DpBatchView {
  long long id;
  long long rows;
  long long width;
  const double* data;
};

struct DpMiscView {
  long long id;
  const char* method;
  long long method_len;
  const char* path;
  long long path_len;
  const char* query;
  long long query_len;
  const char* ctype;
  long long ctype_len;
  const char* body;
  long long body_len;
};

static int dp_listen(const char* host, int port, int* bound_port) {
  int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof addr);
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  if (inet_pton(AF_INET, host && *host ? host : "0.0.0.0", &addr.sin_addr) != 1)
    addr.sin_addr.s_addr = INADDR_ANY;
  if (bind(fd, (struct sockaddr*)&addr, sizeof addr) < 0 ||
      listen(fd, 4096) < 0) {
    close(fd);
    return -1;
  }
  socklen_t alen = sizeof addr;
  getsockname(fd, (struct sockaddr*)&addr, &alen);
  if (bound_port) *bound_port = ntohs(addr.sin_port);
  return fd;
}

// grpc_port: -1 disables the gRPC lane, 0 binds an ephemeral port
void* dp_start(const char* host, int port, int grpc_port, long long max_batch,
               double max_wait_ms, int depth, const char* names_frag,
               long long names_len, const char* proto_names,
               long long proto_names_len) {
  auto pl = std::make_unique<Plane>();
  pl->max_batch = max_batch > 0 ? max_batch : 1024;
  pl->max_wait_s = max_wait_ms > 0 ? max_wait_ms / 1e3 : 0.002;
  pl->depth = depth > 0 ? depth : 8;
  if (names_frag && names_len > 0) pl->names_frag.assign(names_frag, names_len);
  if (proto_names && proto_names_len > 0)
    pl->proto_names_frag.assign(proto_names, proto_names_len);

  pl->listen_fd = dp_listen(host, port, &pl->port);
  if (pl->listen_fd < 0) return nullptr;
  if (grpc_port >= 0) {
    pl->grpc_listen_fd = dp_listen(host, grpc_port, &pl->grpc_port);
    if (pl->grpc_listen_fd < 0) {
      close(pl->listen_fd);
      return nullptr;
    }
  }

  pl->ep = epoll_create1(0);
  pl->evfd = eventfd(0, EFD_NONBLOCK);
  arm(pl.get(), pl->listen_fd, EvTag::LISTEN, EPOLLIN, EPOLL_CTL_ADD);
  if (pl->grpc_listen_fd >= 0)
    arm(pl.get(), pl->grpc_listen_fd, EvTag::LISTEN_GRPC, EPOLLIN,
        EPOLL_CTL_ADD);
  arm(pl.get(), pl->evfd, EvTag::EVENT, EPOLLIN, EPOLL_CTL_ADD);
  Plane* raw = pl.release();
  raw->io_thread = std::thread(io_loop, raw);
  return raw;
}

int dp_port(void* h) { return h ? ((Plane*)h)->port : 0; }
int dp_grpc_port(void* h) { return h ? ((Plane*)h)->grpc_port : 0; }

int dp_next_batch(void* h, DpBatchView* out) {
  Plane* pl = (Plane*)h;
  std::unique_lock<std::mutex> lk(pl->mu);
  pl->cv_batch.wait(lk, [&] {
    return pl->stop.load(std::memory_order_relaxed) || !pl->ready.empty();
  });
  if (pl->ready.empty()) return 0;  // shutdown
  std::unique_ptr<Batch> b = std::move(pl->ready.front());
  pl->ready.pop_front();
  pl->queued_rows -= (long long)(b->data.size() / b->width);
  pl->inflight_count++;
  Batch* bp = b.get();
  pl->inflight[bp->id] = std::move(b);
  out->id = bp->id;
  out->width = bp->width;
  out->rows = (long long)(bp->data.size() / bp->width);
  out->data = bp->data.data();
  return 1;
}

static std::unique_ptr<Batch> take_inflight(Plane* pl, long long id) {
  std::lock_guard<std::mutex> lk(pl->mu);
  auto it = pl->inflight.find(id);
  if (it == pl->inflight.end()) return nullptr;
  std::unique_ptr<Batch> b = std::move(it->second);
  pl->inflight.erase(it);
  pl->inflight_count--;
  // a slot opened: if nothing else is ready, release the oldest accumulation
  if (pl->ready.empty() && !pl->accum.empty()) {
    long long oldest_w = -1;
    double oldest_t = 1e300;
    for (auto& kv : pl->accum)
      if (kv.second && kv.second->t_first < oldest_t) {
        oldest_t = kv.second->t_first;
        oldest_w = kv.first;
      }
    if (oldest_w >= 0) flush_batch_locked(pl, oldest_w);
  }
  return b;
}

int dp_complete_batch(void* h, long long id, const double* y, long long rows,
                      long long cols) {
  Plane* pl = (Plane*)h;
  std::unique_ptr<Batch> b = take_inflight(pl, id);
  if (!b) return -1;
  long long in_rows = (long long)(b->data.size() / b->width);
  if (rows != in_rows || cols <= 0 || !y) {
    // row-count mismatch is a server defect: fail every caller
    for (ReqInfo& r : b->reqs) {
      (r.h2 ? pl->stats_h2 : pl->stats)
          .n5xx.fetch_add(1, std::memory_order_relaxed);
      if (r.h2) {
        queue_completion_h2(pl, r.conn_id, r.conn_gen, r.stream,
                            13 /* INTERNAL */, "batch shape mismatch");
        continue;
      }
      std::string body =
          "{\"status\":{\"code\":500,\"status\":\"FAILURE\","
          "\"reason\":\"batch shape mismatch\"}}";
      queue_completion(pl, r,
                       http_response(500, "application/json", body.data(),
                                     body.size(), r.close_c));
    }
    return 0;
  }
  long long off = 0;
  double tdone = now_s();
  for (ReqInfo& r : b->reqs) {
    if (r.h2) {
      // gRPC lane: proto wire response + 5-byte message frame
      std::string puid = r.puid;
      if (puid.empty()) {
        char pbuf[26];
        pl->puid.fill(pbuf);
        puid.assign(pbuf, 26);
      }
      std::string proto = pw_build_response(
          puid, y + off * cols, r.rows, cols, pl->proto_names_frag);
      off += r.rows;
      std::string framed;
      framed.reserve(proto.size() + 5);
      framed += (char)0;
      framed += (char)((proto.size() >> 24) & 0xff);
      framed += (char)((proto.size() >> 16) & 0xff);
      framed += (char)((proto.size() >> 8) & 0xff);
      framed += (char)(proto.size() & 0xff);
      framed += proto;
      pl->stats_h2.observe_ok(tdone - r.t0);
      queue_completion_h2(pl, r.conn_id, r.conn_gen, r.stream, 0,
                          std::move(framed));
      continue;
    }
    long long shape[2] = {r.rows, cols};
    long long frag_len = 0;
    char* frag = sm_format(y + off * cols, shape, 2, r.kind, &frag_len);
    off += r.rows;
    if (!frag) {
      // never skip a seq: an unanswered slot would wedge the connection's
      // ordered response queue forever (conn_flush stops at a gap)
      std::string err =
          "{\"status\":{\"code\":500,\"status\":\"FAILURE\","
          "\"reason\":\"response format failed\"}}";
      pl->stats.n5xx.fetch_add(1, std::memory_order_relaxed);
      queue_completion(pl, r,
                       http_response(500, "application/json", err.data(),
                                     err.size(), r.close_c));
      continue;
    }
    std::string meta = response_meta(pl, r.meta);
    std::string body;
    body.reserve(meta.size() + pl->names_frag.size() + (size_t)frag_len + 96);
    body += "{\"meta\":";
    body += meta;
    body += ",\"status\":{\"code\":200,\"status\":\"SUCCESS\"},\"data\":{";
    body += pl->names_frag;
    body.append(frag, (size_t)frag_len);
    body += "}}";
    sm_buf_free(frag);
    pl->stats.observe_ok(tdone - r.t0);
    queue_completion(pl, r,
                     http_response(200, "application/json", body.data(),
                                   body.size(), r.close_c));
  }
  return 0;
}

int dp_fail_batch(void* h, long long id, int http_code, const char* body,
                  long long body_len) {
  Plane* pl = (Plane*)h;
  std::unique_ptr<Batch> b = take_inflight(pl, id);
  if (!b) return -1;
  std::string bs(body ? body : "", body ? (size_t)body_len : 0);
  if (bs.empty())
    bs = "{\"status\":{\"code\":500,\"status\":\"FAILURE\"}}";
  // gRPC status mapping for h2 callers in the same failed batch
  int grpc_status = http_code == 400 ? 3 /* INVALID_ARGUMENT */
                    : http_code == 503 ? 8 /* RESOURCE_EXHAUSTED */
                    : http_code == 504 ? 4 /* DEADLINE_EXCEEDED */
                                       : 13 /* INTERNAL */;
  for (ReqInfo& r : b->reqs) {
    Stats& st = r.h2 ? pl->stats_h2 : pl->stats;
    if (http_code >= 500) st.n5xx.fetch_add(1, std::memory_order_relaxed);
    else if (http_code >= 400) st.n4xx.fetch_add(1, std::memory_order_relaxed);
    if (r.h2) {
      // same diagnostic text the HTTP callers get (trimmed for grpc-message)
      queue_completion_h2(pl, r.conn_id, r.conn_gen, r.stream, grpc_status,
                          std::string(bs));
      continue;
    }
    queue_completion(pl, r,
                     http_response(http_code, "application/json", bs.data(),
                                   bs.size(), r.close_c));
  }
  return 0;
}

int dp_next_misc(void* h, DpMiscView* out) {
  Plane* pl = (Plane*)h;
  std::unique_lock<std::mutex> lk(pl->mu);
  pl->cv_misc.wait(lk, [&] {
    return pl->stop.load(std::memory_order_relaxed) || !pl->misc_q.empty();
  });
  if (pl->misc_q.empty()) return 0;  // shutdown
  std::unique_ptr<MiscReq> m = std::move(pl->misc_q.front());
  pl->misc_q.pop_front();
  MiscReq* mp = m.get();
  pl->misc_inflight[mp->id] = std::move(m);
  out->id = mp->id;
  out->method = mp->method.data();
  out->method_len = (long long)mp->method.size();
  out->path = mp->path.data();
  out->path_len = (long long)mp->path.size();
  out->query = mp->query.data();
  out->query_len = (long long)mp->query.size();
  out->ctype = mp->ctype.data();
  out->ctype_len = (long long)mp->ctype.size();
  out->body = mp->body.data();
  out->body_len = (long long)mp->body.size();
  return 1;
}

// gRPC misc response: status 0 sends payload + OK trailers, else
// trailers-only with `message`
int dp_respond_grpc(void* h, long long id, int grpc_status,
                    const char* message, long long message_len,
                    const char* payload, long long payload_len) {
  Plane* pl = (Plane*)h;
  std::unique_ptr<MiscReq> m;
  {
    std::lock_guard<std::mutex> lk(pl->mu);
    auto it = pl->misc_inflight.find(id);
    if (it == pl->misc_inflight.end()) return -1;
    m = std::move(it->second);
    pl->misc_inflight.erase(it);
  }
  if (!m->h2) return -1;
  if (grpc_status == 0)
    pl->stats_h2.n2xx.fetch_add(1, std::memory_order_relaxed);
  else
    pl->stats_h2.n5xx.fetch_add(1, std::memory_order_relaxed);
  std::string data;
  if (grpc_status == 0) {
    size_t n = payload ? (size_t)payload_len : 0;
    data.reserve(n + 5);
    data += (char)0;
    data += (char)((n >> 24) & 0xff);
    data += (char)((n >> 16) & 0xff);
    data += (char)((n >> 8) & 0xff);
    data += (char)(n & 0xff);
    data.append(payload ? payload : "", n);
  } else {
    data.assign(message ? message : "", message ? (size_t)message_len : 0);
  }
  queue_completion_h2(pl, m->conn_id, m->conn_gen, m->stream, grpc_status,
                      std::move(data));
  return 0;
}

int dp_respond_misc(void* h, long long id, int http_code, const char* ctype,
                    const char* body, long long body_len) {
  Plane* pl = (Plane*)h;
  std::unique_ptr<MiscReq> m;
  {
    std::lock_guard<std::mutex> lk(pl->mu);
    auto it = pl->misc_inflight.find(id);
    if (it == pl->misc_inflight.end()) return -1;
    m = std::move(it->second);
    pl->misc_inflight.erase(it);
  }
  if (m->h2) return -1;  // gRPC misc must answer via dp_respond_grpc
  if (http_code >= 500) pl->stats.n5xx.fetch_add(1, std::memory_order_relaxed);
  else if (http_code >= 400) pl->stats.n4xx.fetch_add(1, std::memory_order_relaxed);
  else pl->stats.n2xx.fetch_add(1, std::memory_order_relaxed);
  ReqInfo r;
  r.conn_id = m->conn_id;
  r.conn_gen = m->conn_gen;
  r.seq = m->seq;
  queue_completion(
      pl, r,
      http_response(http_code, ctype && *ctype ? ctype : "application/json",
                    body ? body : "", body ? (size_t)body_len : 0,
                    m->close_c));
  return 0;
}

// Two 19-slot blocks, one per fast lane:
//   out[0..18]  HTTP/1.1: 2xx/4xx/5xx, latency sum (us), 15 hist buckets
//   out[19..37] h2/gRPC:  same layout
// Keeping the lanes separate lets /prometheus attribute REST vs gRPC
// traffic to distinct metric children (parity with the Python lanes).
void dp_stats(void* h, long long* out) {
  Plane* pl = (Plane*)h;
  Stats* lanes[2] = {&pl->stats, &pl->stats_h2};
  for (int l = 0; l < 2; l++) {
    long long* o = out + 19 * l;
    Stats& s = *lanes[l];
    o[0] = s.n2xx.load(std::memory_order_relaxed);
    o[1] = s.n4xx.load(std::memory_order_relaxed);
    o[2] = s.n5xx.load(std::memory_order_relaxed);
    o[3] = s.sum_us.load(std::memory_order_relaxed);
    for (int i = 0; i < 15; i++)
      o[4 + i] = s.hist[i].load(std::memory_order_relaxed);
  }
}

// Two-phase shutdown: dp_shutdown stops IO and wakes blocked workers but
// keeps the Plane alive so threads mid-call (dp_next_* / dp_complete_* /
// dp_respond_misc) stay memory-safe; dp_destroy frees it once the caller
// has joined its worker threads.
void dp_shutdown(void* h) {
  Plane* pl = (Plane*)h;
  pl->stop.store(true, std::memory_order_relaxed);
  uint64_t one = 1;
  (void)!write(pl->evfd, &one, 8);
  pl->cv_batch.notify_all();
  pl->cv_misc.notify_all();
  if (pl->io_thread.joinable()) pl->io_thread.join();
}

void dp_destroy(void* h) {
  Plane* pl = (Plane*)h;
  close(pl->ep);
  close(pl->evfd);
  delete pl;
}

void dp_stop(void* h) {  // single-phase convenience for single-threaded use
  dp_shutdown(h);
  dp_destroy(h);
}

}  // extern "C"
