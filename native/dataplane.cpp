// Native REST data plane — C++ HTTP termination + request batching for the
// serving engine.
//
// The reference engine spends its per-request budget inside Tomcat NIO +
// Jackson + a vendored protobuf JsonFormat fork (engine
// RestClientController.java, pb/JsonFormat.java); its throughput scales with
// the 16 cores of its benchmark pod.  This framework's Python engine
// (runtime/httpfast.py) already strips HTTP to an asyncio.Protocol, but on a
// single-core host ~190 us/request of interpreter work caps the lane at a
// few k req/s.  This module moves the ENTIRE per-request path out of Python:
//
//   IO thread (C++, no GIL): epoll loop -> HTTP/1.1 parse -> JSON numeric
//     parse (fastcodec.cpp) -> rows appended to a width-keyed batch ->
//     batch published when full / deadline / a dispatch slot is idle.
//   Python worker threads: dp_next_batch() blocks (GIL released) -> numpy
//     view of the stacked float64 rows -> ONE jitted XLA dispatch ->
//     dp_complete_batch(y).
//   Completing thread (C++, no GIL): per-request JSON responses composed
//     and handed to the IO thread for ordered, flow-controlled writes.
//
// Python's cost becomes one FFI round-trip per BATCH (<= 1/1024th of the
// request rate), so the serving ceiling is set by this file and the TPU,
// not the interpreter.  Requests the fast lane cannot express — feedback,
// admin GETs, form-encoded bodies, strData/binData/jsonData payloads,
// >2-D tensors, oversized row counts — are queued verbatim to Python
// (dp_next_misc / dp_respond_misc) and served by the full-semantics engine
// routes, preserving wire behaviour exactly.
//
// Response ordering per connection is FIFO by arrival (pipelining-safe),
// matching runtime/httpfast.py; keepalive, Connection: close, 404/405/411/
// 413/501 handling match the same contract.

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

// ---- fastcodec.cpp C ABI (compiled into the same shared object) -----------
extern "C" {
struct SMViewC {
  int32_t status;
  int32_t kind;
  int32_t ndim;
  int32_t _pad;
  long long nvalues;
  long long envelope_len;
  const char* envelope;
  const double* values;
  const long long* shape;
};
void* sm_parse_view(const char* buf, long long len, SMViewC* view);
void sm_free(void* p);
char* sm_format(const double* vals, const long long* shape, int ndim,
                int kind, long long* out_len);
void sm_buf_free(char* p);
}

namespace {

constexpr int SM_OK = 0;
constexpr int KIND_TENSOR = 1;
constexpr int KIND_NDARRAY = 2;

constexpr size_t MAX_HEAD = 64 * 1024;
constexpr size_t MAX_BODY = 256u * 1024 * 1024;  // matches rest.py
constexpr int MAX_CONN_OUTSTANDING = 128;        // matches httpfast backpressure
constexpr long long MAX_QUEUED_ROWS = 1 << 17;   // global 503 backstop

double now_s() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec * 1e-9;
}

// latency buckets — MUST match utils/metrics.py _BUCKETS (seconds)
constexpr double kBuckets[14] = {0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                                 0.05,   0.1,   0.25,   0.5,   1.0,  2.5,
                                 5.0,    10.0};

struct Stats {
  std::atomic<long long> n2xx{0}, n4xx{0}, n5xx{0};
  std::atomic<long long> sum_us{0};
  std::atomic<long long> hist[15]{};  // 14 buckets + +Inf
  void observe_ok(double secs) {
    n2xx.fetch_add(1, std::memory_order_relaxed);
    sum_us.fetch_add((long long)(secs * 1e6), std::memory_order_relaxed);
    int b = 0;
    while (b < 14 && secs > kBuckets[b]) b++;
    hist[b].fetch_add(1, std::memory_order_relaxed);
  }
};

// base32 [a-z2-7] puid, visually identical to messages.py new_puid()
struct PuidGen {
  uint64_t s;
  explicit PuidGen(uint64_t seed) : s(seed | 1) {}
  void fill(char* out26) {
    static const char alpha[] = "abcdefghijklmnopqrstuvwxyz234567";
    uint64_t x = 0;
    int have = 0;
    for (int i = 0; i < 26; i++) {
      if (have < 5) {
        s ^= s << 13; s ^= s >> 7; s ^= s << 17;  // xorshift64
        x = s;
        have = 64;
      }
      out26[i] = alpha[x & 31];
      x >>= 5;
      have -= 5;
    }
  }
};

struct ReqInfo {
  int conn_id;
  uint32_t conn_gen;
  uint64_t seq;       // per-conn response order
  int kind;           // KIND_TENSOR / KIND_NDARRAY
  long long rows;
  bool close_c = false;  // request asked Connection: close
  std::string meta;   // verbatim client meta object ("" if absent)
  double t0;          // parse time, for the latency histogram
};

struct Batch {
  long long id;
  long long width;
  std::vector<double> data;  // rows * width, row-major
  std::vector<ReqInfo> reqs;
  double t_first;
};

struct MiscReq {
  long long id;
  int conn_id;
  uint32_t conn_gen;
  uint64_t seq;
  bool close_c = false;
  std::string method;  // "GET" / "POST"
  std::string path;    // without query
  std::string query;
  std::string ctype;
  std::string body;
};

struct Conn {
  int fd = -1;
  uint32_t gen = 0;
  std::string in;
  size_t scan_from = 0;
  ssize_t head_end = -1;
  long long clen = -1;
  bool head_parsed = false;
  std::string hmethod, hpath, hquery, hctype;
  bool hclose = false;
  uint64_t next_assign = 0;   // next seq to hand out
  uint64_t next_write = 0;    // next seq to be written
  std::map<uint64_t, std::string> done;  // seq -> full HTTP response
  uint64_t close_after = UINT64_MAX;     // write responses <= this, then close
  std::string out;
  size_t out_off = 0;
  bool want_write = false;
  bool paused = false;
};

struct Plane {
  int listen_fd = -1;
  int port = 0;
  int ep = -1;
  int evfd = -1;
  std::thread io_thread;
  std::atomic<bool> stop{false};

  long long max_batch;
  double max_wait_s;
  int depth;
  std::string names_frag;  // '"names":["a","b"],' or ""

  std::vector<std::unique_ptr<Conn>> conns;
  std::vector<int> free_conns;

  // batching state (guarded by mu)
  std::mutex mu;
  std::condition_variable cv_batch;
  std::condition_variable cv_misc;
  std::unordered_map<long long, std::unique_ptr<Batch>> accum;  // width -> batch
  std::deque<std::unique_ptr<Batch>> ready;
  std::unordered_map<long long, std::unique_ptr<Batch>> inflight;
  std::deque<std::unique_ptr<MiscReq>> misc_q;
  std::unordered_map<long long, std::unique_ptr<MiscReq>> misc_inflight;
  long long next_batch_id = 1;
  long long next_misc_id = 1;
  long long queued_rows = 0;
  int inflight_count = 0;

  // completions: responses composed off-thread, flushed by the IO thread
  std::mutex cmu;
  std::vector<std::pair<std::pair<int, uint32_t>,
                        std::pair<uint64_t, std::string>>> completions;

  // io-thread-local: conns needing a parse retry after backpressure resume
  std::vector<int> resume_parse;

  Stats stats;
  PuidGen puid;

  Plane() : puid((uint64_t)now_s() * 1000003 ^ (uint64_t)(uintptr_t)this) {}
};

void set_nonblock(int fd) {
  int fl = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, fl | O_NONBLOCK);
}

const char* status_text(int code) {
  switch (code) {
    case 200: return "OK";
    case 400: return "Bad Request";
    case 404: return "Not Found";
    case 405: return "Method Not Allowed";
    case 411: return "Length Required";
    case 413: return "Payload Too Large";
    case 500: return "Internal Server Error";
    case 501: return "Not Implemented";
    case 503: return "Service Unavailable";
    case 504: return "Gateway Timeout";
    default: return "X";
  }
}

std::string http_response(int code, const char* ctype, const char* body,
                          size_t body_len, bool close_conn) {
  char head[512];
  int n = snprintf(head, sizeof head,
                   "HTTP/1.1 %d %s\r\nContent-Length: %zu\r\n"
                   "Content-Type: %s\r\n%s\r\n",
                   code, status_text(code), body_len, ctype,
                   close_conn ? "Connection: close\r\n" : "");
  // snprintf returns the would-be length; clamp so an oversized
  // content-type truncates instead of reading past the buffer
  if (n < 0) n = 0;
  if ((size_t)n >= sizeof head) n = (int)sizeof head - 1;
  std::string out;
  out.reserve((size_t)n + body_len);
  out.append(head, (size_t)n);
  out.append(body, body_len);
  return out;
}

// Extract the top-level "meta" object span from the envelope JSON that
// fastcodec produced (compact, valid).  Returns "" if absent.
std::string extract_meta(const char* env, long long len) {
  // envelope is {"meta":{...},...} with our own serialization; find the key
  // at top level by scanning with brace/string awareness
  int depth = 0;
  bool in_str = false;
  for (long long i = 0; i < len; i++) {
    char c = env[i];
    if (in_str) {
      if (c == '\\') i++;
      else if (c == '"') in_str = false;
      continue;
    }
    if (c == '"') {
      if (depth == 1 && i + 7 <= len && memcmp(env + i, "\"meta\"", 6) == 0) {
        long long j = i + 6;
        while (j < len && (env[j] == ':' || env[j] == ' ')) j++;
        if (j < len && env[j] == '{') {
          int d2 = 0;
          bool s2 = false;
          for (long long k = j; k < len; k++) {
            char c2 = env[k];
            if (s2) {
              if (c2 == '\\') k++;
              else if (c2 == '"') s2 = false;
              continue;
            }
            if (c2 == '"') s2 = true;
            else if (c2 == '{') d2++;
            else if (c2 == '}') {
              if (--d2 == 0) return std::string(env + j, k - j + 1);
            }
          }
        }
        return "";
      }
      in_str = true;
      continue;
    }
    if (c == '{' || c == '[') depth++;
    else if (c == '}' || c == ']') depth--;
  }
  return "";
}

// meta for the response: client meta echoed with puid guaranteed present
std::string response_meta(Plane* pl, const std::string& client_meta) {
  char pbuf[26];
  if (client_meta.empty() || client_meta == "{}") {
    pl->puid.fill(pbuf);
    return std::string("{\"puid\":\"") + std::string(pbuf, 26) + "\"}";
  }
  if (client_meta.find("\"puid\"") != std::string::npos) return client_meta;
  pl->puid.fill(pbuf);
  std::string out;
  out.reserve(client_meta.size() + 40);
  out += "{\"puid\":\"";
  out.append(pbuf, 26);
  out += "\",";
  out.append(client_meta, 1, client_meta.size() - 1);
  return out;
}

void queue_completion(Plane* pl, const ReqInfo& r, std::string&& resp) {
  {
    std::lock_guard<std::mutex> lk(pl->cmu);
    pl->completions.emplace_back(
        std::make_pair(r.conn_id, r.conn_gen),
        std::make_pair(r.seq, std::move(resp)));
  }
  uint64_t one = 1;
  (void)!write(pl->evfd, &one, 8);
}

// ---------------------------------------------------------------------------
// IO thread
// ---------------------------------------------------------------------------

struct EvTag {  // epoll user data: fd class + conn index
  enum { LISTEN = -1, EVENT = -2 };
};

void arm(Plane* pl, int fd, int idx, uint32_t events, int op) {
  struct epoll_event ev;
  ev.events = events;
  ev.data.u64 = (uint64_t)(uint32_t)idx;
  epoll_ctl(pl->ep, op, fd, &ev);
}

void conn_close(Plane* pl, int ci) {
  Conn& c = *pl->conns[ci];
  if (c.fd < 0) return;
  epoll_ctl(pl->ep, EPOLL_CTL_DEL, c.fd, nullptr);
  close(c.fd);
  c.fd = -1;
  c.gen++;  // invalidates in-flight completions for this conn
  c.in.clear();
  c.in.shrink_to_fit();
  c.done.clear();
  c.out.clear();
  c.out.shrink_to_fit();
  pl->free_conns.push_back(ci);
}

// move ready ordered responses into the write buffer; write; manage EPOLLOUT.
// IO-thread only; never re-entered from the parse path (respond_now just
// queues — the caller flushes once parsing is done).
void conn_flush(Plane* pl, int ci) {
  Conn& c = *pl->conns[ci];
  if (c.fd < 0) return;
  while (c.next_write <= c.close_after) {
    auto it = c.done.find(c.next_write);
    if (it == c.done.end()) break;
    c.out += it->second;
    c.done.erase(it);
    c.next_write++;
  }
  while (c.out_off < c.out.size()) {
    ssize_t n = write(c.fd, c.out.data() + c.out_off, c.out.size() - c.out_off);
    if (n > 0) { c.out_off += (size_t)n; continue; }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      if (!c.want_write) {
        c.want_write = true;
        arm(pl, c.fd, ci, EPOLLIN | EPOLLOUT, EPOLL_CTL_MOD);
      }
      return;
    }
    conn_close(pl, ci);
    return;
  }
  c.out.clear();
  c.out_off = 0;
  if (c.want_write) {
    c.want_write = false;
    arm(pl, c.fd, ci, EPOLLIN, EPOLL_CTL_MOD);
  }
  if (c.next_write > c.close_after) {
    conn_close(pl, ci);
    return;
  }
  // resume reading when the pipeline drains; buffered-but-unparsed bytes
  // are retried by the io loop (no recursion into the parse path here)
  if (c.paused && c.next_assign - c.next_write <= MAX_CONN_OUTSTANDING / 2) {
    c.paused = false;
    arm(pl, c.fd, ci, c.want_write ? (EPOLLIN | EPOLLOUT) : EPOLLIN,
        EPOLL_CTL_MOD);
    pl->resume_parse.push_back(ci);
  }
}

// queue an immediate (parse-error / overload) response; the caller flushes
void respond_now(Plane* pl, int ci, int code, const char* body, bool close_c) {
  Conn& c = *pl->conns[ci];
  uint64_t seq = c.next_assign++;
  c.done[seq] = http_response(code, "text/plain", body, strlen(body), close_c);
  if (close_c) c.close_after = seq;
  if (code >= 500) pl->stats.n5xx.fetch_add(1, std::memory_order_relaxed);
  else if (code >= 400) pl->stats.n4xx.fetch_add(1, std::memory_order_relaxed);
}

void flush_batch_locked(Plane* pl, long long width) {
  // queued_rows keeps counting ready batches — they still occupy memory and
  // the 503 backstop must see them; dp_next_batch decrements on hand-off
  auto it = pl->accum.find(width);
  if (it == pl->accum.end() || !it->second) return;
  std::unique_ptr<Batch> b = std::move(it->second);
  pl->accum.erase(it);
  pl->ready.push_back(std::move(b));
  pl->cv_batch.notify_one();
}

// returns false if the request was NOT eligible for the fast lane
bool try_fast_predict(Plane* pl, int ci, const char* body, size_t blen,
                      bool close_c) {
  Conn& c = *pl->conns[ci];
  SMViewC v;
  void* p = sm_parse_view(body, (long long)blen, &v);
  bool ok = p && v.status == SM_OK &&
            (v.kind == KIND_TENSOR || v.kind == KIND_NDARRAY) &&
            v.ndim >= 1 && v.ndim <= 2 && v.nvalues > 0;
  long long rows = 0, width = 0;
  if (ok) {
    rows = v.ndim == 2 ? v.shape[0] : 1;
    width = v.ndim == 2 ? v.shape[1] : v.nvalues;
    ok = rows > 0 && width > 0 && rows <= pl->max_batch;
  }
  std::string meta;
  if (ok) {
    meta = extract_meta(v.envelope, v.envelope_len);
    // binData/strData/jsonData arrive with kind NONE (not ok); a non-object
    // meta can't appear here because extract_meta only matches "meta":{
  }
  if (!ok) {
    if (p) sm_free(p);
    return false;
  }
  std::unique_lock<std::mutex> lk(pl->mu);
  if (pl->queued_rows + rows > MAX_QUEUED_ROWS) {
    lk.unlock();
    sm_free(p);
    respond_now(pl, ci, 503,
                "{\"status\":{\"code\":503,\"status\":\"FAILURE\","
                "\"reason\":\"overloaded\"}}", close_c);
    return true;  // consumed (with a 503), not misc-lane material
  }
  {
    auto pre = pl->accum.find(width);
    if (pre != pl->accum.end() && pre->second &&
        (long long)(pre->second->data.size() / width) + rows > pl->max_batch)
      flush_batch_locked(pl, width);  // this request would overflow: flush
  }
  auto& slot = pl->accum[width];
  if (!slot) {
    slot.reset(new Batch());
    slot->id = pl->next_batch_id++;
    slot->width = width;
    slot->data.reserve((size_t)std::min<long long>(pl->max_batch, 4096) *
                       width);
    slot->t_first = now_s();
  }
  Batch& b = *slot;
  size_t off = b.data.size();
  b.data.resize(off + (size_t)rows * width);
  memcpy(b.data.data() + off, v.values, sizeof(double) * rows * width);
  ReqInfo r;
  r.conn_id = ci;
  r.conn_gen = c.gen;
  r.seq = c.next_assign++;
  r.kind = v.kind;
  r.rows = rows;
  r.close_c = close_c;
  r.meta = std::move(meta);
  r.t0 = now_s();
  b.reqs.push_back(std::move(r));
  pl->queued_rows += rows;
  if ((long long)(b.data.size() / width) >= pl->max_batch)
    flush_batch_locked(pl, width);
  lk.unlock();
  sm_free(p);
  return true;
}

void to_misc(Plane* pl, int ci, bool close_c, std::string&& method,
             std::string&& path, std::string&& query, std::string&& ctype,
             std::string&& body) {
  Conn& c = *pl->conns[ci];
  auto m = std::make_unique<MiscReq>();
  m->conn_id = ci;
  m->conn_gen = c.gen;
  m->seq = c.next_assign++;
  m->close_c = close_c;
  m->method = std::move(method);
  m->path = std::move(path);
  m->query = std::move(query);
  m->ctype = std::move(ctype);
  m->body = std::move(body);
  std::lock_guard<std::mutex> lk(pl->mu);
  m->id = pl->next_misc_id++;
  pl->misc_q.push_back(std::move(m));
  pl->cv_misc.notify_one();
}

// case-insensitive header value inside [head, head+len), name lower-case
// with colon; anchored at line start
std::string header_value(const char* head, size_t len, const char* name) {
  size_t nlen = strlen(name);
  for (size_t i = 0; i + 2 + nlen <= len; i++) {
    if (head[i] != '\r' || head[i + 1] != '\n') continue;
    size_t j = 0;
    while (j < nlen && i + 2 + j < len &&
           (char)(head[i + 2 + j] | 0x20) == name[j])
      j++;
    if (j == nlen) {
      size_t s = i + 2 + nlen;
      size_t e = s;
      while (e < len && head[e] != '\r') e++;
      while (s < e && head[s] == ' ') s++;
      while (e > s && head[e - 1] == ' ') e--;
      return std::string(head + s, e - s);
    }
  }
  return "";
}

void handle_request(Plane* pl, int ci, const char* head, size_t head_len,
                    const char* body, size_t body_len) {
  Conn& c = *pl->conns[ci];
  // request line
  const char* line_end = (const char*)memchr(head, '\r', head_len);
  size_t ll = line_end ? (size_t)(line_end - head) : head_len;
  std::string method, target;
  {
    const char* sp1 = (const char*)memchr(head, ' ', ll);
    if (!sp1) { respond_now(pl, ci, 400, "malformed request line", true); return; }
    const char* sp2 = (const char*)memchr(sp1 + 1, ' ', ll - (sp1 + 1 - head));
    if (!sp2) { respond_now(pl, ci, 400, "malformed request line", true); return; }
    method.assign(head, sp1 - head);
    target.assign(sp1 + 1, sp2 - sp1 - 1);
  }
  std::string conn_hdr = header_value(head, head_len, "connection:");
  bool close_c = conn_hdr.find("close") != std::string::npos;
  std::string path = target, query;
  size_t qp = target.find('?');
  if (qp != std::string::npos) {
    path = target.substr(0, qp);
    query = target.substr(qp + 1);
  }
  std::string ctype = header_value(head, head_len, "content-type:");

  if (method == "POST" && path == "/api/v0.1/predictions" &&
      ctype.find("form") == std::string::npos) {
    if (try_fast_predict(pl, ci, body, body_len, close_c)) {
      if (close_c) c.close_after = c.next_assign - 1;
      goto backpressure;
    }
  }
  if (method != "GET" && method != "POST") {
    respond_now(pl, ci, 405, "method not allowed", close_c);
    return;
  }
  to_misc(pl, ci, close_c, std::move(method), std::move(path),
          std::move(query), std::move(ctype), std::string(body, body_len));
  if (close_c) c.close_after = c.next_assign - 1;

backpressure:
  if (!c.paused && c.next_assign - c.next_write > MAX_CONN_OUTSTANDING) {
    c.paused = true;
    if (c.fd >= 0)
      arm(pl, c.fd, ci, c.want_write ? EPOLLOUT : 0, EPOLL_CTL_MOD);
  }
}

void conn_parse(Plane* pl, int ci) {
  Conn& c = *pl->conns[ci];
  size_t consumed = 0;
  while (c.fd >= 0 && !c.paused) {
    if (c.head_parsed) {
      if (c.in.size() - consumed < (size_t)c.head_end + (size_t)c.clen) break;
      size_t bstart = consumed + (size_t)c.head_end;
      handle_request(pl, ci, c.in.data() + consumed, (size_t)c.head_end,
                     c.in.data() + bstart, (size_t)c.clen);
      consumed = bstart + (size_t)c.clen;
      c.head_parsed = false;
      c.head_end = -1;
      c.clen = -1;
      c.scan_from = 0;
      continue;
    }
    // scan for end of headers
    size_t from = consumed + (c.scan_from > 3 ? c.scan_from - 3 : 0);
    const char* found = nullptr;
    if (c.in.size() > from + 3) {
      for (size_t i = from; i + 4 <= c.in.size(); i++) {
        if (c.in[i] == '\r' && c.in[i + 1] == '\n' && c.in[i + 2] == '\r' &&
            c.in[i + 3] == '\n') {
          found = c.in.data() + i;
          break;
        }
      }
    }
    if (!found) {
      if (c.in.size() - consumed > MAX_HEAD) {
        respond_now(pl, ci, 413, "headers too large", true);
        break;
      }
      c.scan_from = c.in.size() - consumed;
      break;
    }
    size_t head_len = (size_t)(found - (c.in.data() + consumed)) + 4;
    const char* head = c.in.data() + consumed;
    // RFC 7230: Transfer-Encoding wins over Content-Length (smuggling guard)
    if (!header_value(head, head_len, "transfer-encoding:").empty()) {
      respond_now(pl, ci, 501, "chunked bodies not supported", true);
      break;
    }
    long long clen = 0;
    std::string clv = header_value(head, head_len, "content-length:");
    if (!clv.empty()) {
      for (char ch : clv) {
        if (ch < '0' || ch > '9') { clen = -1; break; }
        clen = clen * 10 + (ch - '0');
        if (clen > (long long)MAX_BODY) break;
      }
      if (clen < 0) {
        respond_now(pl, ci, 400, "bad content-length", true);
        break;
      }
      if (clen > (long long)MAX_BODY) {
        respond_now(pl, ci, 413, "body too large", true);
        break;
      }
    }
    c.head_end = (ssize_t)head_len;
    c.clen = clen;
    c.head_parsed = true;
  }
  if (consumed) {
    c.in.erase(0, consumed);
    if (!c.head_parsed) c.scan_from = 0;
  }
}

void conn_data(Plane* pl, int ci) {
  Conn& c = *pl->conns[ci];
  char buf[65536];
  for (;;) {
    if (c.fd < 0) return;
    ssize_t r = read(c.fd, buf, sizeof buf);
    if (r > 0) {
      c.in.append(buf, (size_t)r);
      if ((size_t)r == sizeof buf && !c.paused) continue;
    } else if (r == 0) {
      conn_close(pl, ci);
      return;
    } else if (errno == EAGAIN || errno == EWOULDBLOCK) {
      // no more data
    } else {
      conn_close(pl, ci);
      return;
    }
    break;
  }
  conn_parse(pl, ci);
}

void drain_completions(Plane* pl) {
  uint64_t junk;
  (void)!read(pl->evfd, &junk, 8);
  std::vector<std::pair<std::pair<int, uint32_t>,
                        std::pair<uint64_t, std::string>>> local;
  {
    std::lock_guard<std::mutex> lk(pl->cmu);
    local.swap(pl->completions);
  }
  // group flushes: mark conns dirty, flush each once
  std::vector<int> dirty;
  for (auto& item : local) {
    int ci = item.first.first;
    uint32_t gen = item.first.second;
    if (ci < 0 || ci >= (int)pl->conns.size()) continue;
    Conn& c = *pl->conns[ci];
    if (c.fd < 0 || c.gen != gen) continue;  // conn died meanwhile
    c.done[item.second.first] = std::move(item.second.second);
    dirty.push_back(ci);
  }
  std::sort(dirty.begin(), dirty.end());
  dirty.erase(std::unique(dirty.begin(), dirty.end()), dirty.end());
  for (int ci : dirty) conn_flush(pl, ci);
}

void io_loop(Plane* pl) {
  std::vector<struct epoll_event> events(512);
  while (!pl->stop.load(std::memory_order_relaxed)) {
    // batch deadline: the oldest open accumulation decides the poll timeout
    int timeout_ms = 1000;
    {
      std::lock_guard<std::mutex> lk(pl->mu);
      if (!pl->accum.empty() && pl->inflight_count < pl->depth) {
        double oldest = 1e300;
        for (auto& kv : pl->accum)
          if (kv.second && kv.second->t_first < oldest)
            oldest = kv.second->t_first;
        double dl = oldest + pl->max_wait_s - now_s();
        timeout_ms = dl <= 0 ? 0 : (int)(dl * 1000) + 1;
      }
    }
    int n = epoll_wait(pl->ep, events.data(), (int)events.size(), timeout_ms);
    if (n < 0 && errno != EINTR) break;
    for (int e = 0; e < n; e++) {
      int idx = (int)(int32_t)events[e].data.u64;
      if (idx == EvTag::LISTEN) {
        for (;;) {
          int fd = accept4(pl->listen_fd, nullptr, nullptr, SOCK_NONBLOCK);
          if (fd < 0) break;
          int one = 1;
          setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
          int ci;
          if (!pl->free_conns.empty()) {
            ci = pl->free_conns.back();
            pl->free_conns.pop_back();
          } else {
            ci = (int)pl->conns.size();
            pl->conns.emplace_back(new Conn());
          }
          Conn& c = *pl->conns[ci];
          c.fd = fd;
          c.scan_from = 0;
          c.head_end = -1;
          c.clen = -1;
          c.head_parsed = false;
          c.next_assign = c.next_write = 0;
          c.close_after = UINT64_MAX;
          c.out_off = 0;
          c.want_write = false;
          c.paused = false;
          arm(pl, fd, ci, EPOLLIN, EPOLL_CTL_ADD);
        }
        continue;
      }
      if (idx == EvTag::EVENT) {
        drain_completions(pl);
        continue;
      }
      if (idx < 0 || idx >= (int)pl->conns.size()) continue;
      Conn& c = *pl->conns[idx];
      if (c.fd < 0) continue;
      if (events[e].events & (EPOLLERR | EPOLLHUP)) {
        conn_close(pl, idx);
        continue;
      }
      if (events[e].events & EPOLLOUT) conn_flush(pl, idx);
      if (c.fd >= 0 && (events[e].events & EPOLLIN)) {
        conn_data(pl, idx);
        if (c.fd >= 0) conn_flush(pl, idx);  // parse-path responses
      }
    }
    if (!pl->resume_parse.empty()) {
      // connections that resumed from backpressure may hold complete
      // buffered requests that arrived while reading was paused
      std::vector<int> resumed;
      resumed.swap(pl->resume_parse);
      for (int ci : resumed) {
        if (pl->conns[ci]->fd < 0) continue;
        conn_parse(pl, ci);
        if (pl->conns[ci]->fd >= 0) conn_flush(pl, ci);
      }
    }
    // flush aged batches
    {
      std::lock_guard<std::mutex> lk(pl->mu);
      if (pl->inflight_count < pl->depth) {
        double now = now_s();
        std::vector<long long> due;
        for (auto& kv : pl->accum)
          if (kv.second && now - kv.second->t_first >= pl->max_wait_s)
            due.push_back(kv.first);
        for (long long w : due) flush_batch_locked(pl, w);
      }
    }
  }
  // shutdown: close everything
  for (size_t i = 0; i < pl->conns.size(); i++)
    if (pl->conns[i]->fd >= 0) conn_close(pl, (int)i);
  if (pl->listen_fd >= 0) close(pl->listen_fd);
  pl->cv_batch.notify_all();
  pl->cv_misc.notify_all();
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

struct DpBatchView {
  long long id;
  long long rows;
  long long width;
  const double* data;
};

struct DpMiscView {
  long long id;
  const char* method;
  long long method_len;
  const char* path;
  long long path_len;
  const char* query;
  long long query_len;
  const char* ctype;
  long long ctype_len;
  const char* body;
  long long body_len;
};

void* dp_start(const char* host, int port, long long max_batch,
               double max_wait_ms, int depth, const char* names_frag,
               long long names_len) {
  auto pl = std::make_unique<Plane>();
  pl->max_batch = max_batch > 0 ? max_batch : 1024;
  pl->max_wait_s = max_wait_ms > 0 ? max_wait_ms / 1e3 : 0.002;
  pl->depth = depth > 0 ? depth : 8;
  if (names_frag && names_len > 0) pl->names_frag.assign(names_frag, names_len);

  pl->listen_fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (pl->listen_fd < 0) return nullptr;
  int one = 1;
  setsockopt(pl->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof addr);
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  if (inet_pton(AF_INET, host && *host ? host : "0.0.0.0", &addr.sin_addr) != 1)
    addr.sin_addr.s_addr = INADDR_ANY;
  if (bind(pl->listen_fd, (struct sockaddr*)&addr, sizeof addr) < 0 ||
      listen(pl->listen_fd, 4096) < 0) {
    close(pl->listen_fd);
    return nullptr;
  }
  socklen_t alen = sizeof addr;
  getsockname(pl->listen_fd, (struct sockaddr*)&addr, &alen);
  pl->port = ntohs(addr.sin_port);

  pl->ep = epoll_create1(0);
  pl->evfd = eventfd(0, EFD_NONBLOCK);
  arm(pl.get(), pl->listen_fd, EvTag::LISTEN, EPOLLIN, EPOLL_CTL_ADD);
  arm(pl.get(), pl->evfd, EvTag::EVENT, EPOLLIN, EPOLL_CTL_ADD);
  Plane* raw = pl.release();
  raw->io_thread = std::thread(io_loop, raw);
  return raw;
}

int dp_port(void* h) { return h ? ((Plane*)h)->port : 0; }

int dp_next_batch(void* h, DpBatchView* out) {
  Plane* pl = (Plane*)h;
  std::unique_lock<std::mutex> lk(pl->mu);
  pl->cv_batch.wait(lk, [&] {
    return pl->stop.load(std::memory_order_relaxed) || !pl->ready.empty();
  });
  if (pl->ready.empty()) return 0;  // shutdown
  std::unique_ptr<Batch> b = std::move(pl->ready.front());
  pl->ready.pop_front();
  pl->queued_rows -= (long long)(b->data.size() / b->width);
  pl->inflight_count++;
  Batch* bp = b.get();
  pl->inflight[bp->id] = std::move(b);
  out->id = bp->id;
  out->width = bp->width;
  out->rows = (long long)(bp->data.size() / bp->width);
  out->data = bp->data.data();
  return 1;
}

static std::unique_ptr<Batch> take_inflight(Plane* pl, long long id) {
  std::lock_guard<std::mutex> lk(pl->mu);
  auto it = pl->inflight.find(id);
  if (it == pl->inflight.end()) return nullptr;
  std::unique_ptr<Batch> b = std::move(it->second);
  pl->inflight.erase(it);
  pl->inflight_count--;
  // a slot opened: if nothing else is ready, release the oldest accumulation
  if (pl->ready.empty() && !pl->accum.empty()) {
    long long oldest_w = -1;
    double oldest_t = 1e300;
    for (auto& kv : pl->accum)
      if (kv.second && kv.second->t_first < oldest_t) {
        oldest_t = kv.second->t_first;
        oldest_w = kv.first;
      }
    if (oldest_w >= 0) flush_batch_locked(pl, oldest_w);
  }
  return b;
}

int dp_complete_batch(void* h, long long id, const double* y, long long rows,
                      long long cols) {
  Plane* pl = (Plane*)h;
  std::unique_ptr<Batch> b = take_inflight(pl, id);
  if (!b) return -1;
  long long in_rows = (long long)(b->data.size() / b->width);
  if (rows != in_rows || cols <= 0 || !y) {
    // row-count mismatch is a server defect: fail every caller
    for (ReqInfo& r : b->reqs) {
      std::string body =
          "{\"status\":{\"code\":500,\"status\":\"FAILURE\","
          "\"reason\":\"batch shape mismatch\"}}";
      queue_completion(pl, r,
                       http_response(500, "application/json", body.data(),
                                     body.size(), r.close_c));
      pl->stats.n5xx.fetch_add(1, std::memory_order_relaxed);
    }
    return 0;
  }
  long long off = 0;
  double tdone = now_s();
  for (ReqInfo& r : b->reqs) {
    long long shape[2] = {r.rows, cols};
    long long frag_len = 0;
    char* frag = sm_format(y + off * cols, shape, 2, r.kind, &frag_len);
    off += r.rows;
    if (!frag) {
      // never skip a seq: an unanswered slot would wedge the connection's
      // ordered response queue forever (conn_flush stops at a gap)
      std::string err =
          "{\"status\":{\"code\":500,\"status\":\"FAILURE\","
          "\"reason\":\"response format failed\"}}";
      pl->stats.n5xx.fetch_add(1, std::memory_order_relaxed);
      queue_completion(pl, r,
                       http_response(500, "application/json", err.data(),
                                     err.size(), r.close_c));
      continue;
    }
    std::string meta = response_meta(pl, r.meta);
    std::string body;
    body.reserve(meta.size() + pl->names_frag.size() + (size_t)frag_len + 96);
    body += "{\"meta\":";
    body += meta;
    body += ",\"status\":{\"code\":200,\"status\":\"SUCCESS\"},\"data\":{";
    body += pl->names_frag;
    body.append(frag, (size_t)frag_len);
    body += "}}";
    sm_buf_free(frag);
    pl->stats.observe_ok(tdone - r.t0);
    queue_completion(pl, r,
                     http_response(200, "application/json", body.data(),
                                   body.size(), r.close_c));
  }
  return 0;
}

int dp_fail_batch(void* h, long long id, int http_code, const char* body,
                  long long body_len) {
  Plane* pl = (Plane*)h;
  std::unique_ptr<Batch> b = take_inflight(pl, id);
  if (!b) return -1;
  std::string bs(body ? body : "", body ? (size_t)body_len : 0);
  if (bs.empty())
    bs = "{\"status\":{\"code\":500,\"status\":\"FAILURE\"}}";
  for (ReqInfo& r : b->reqs) {
    if (http_code >= 500) pl->stats.n5xx.fetch_add(1, std::memory_order_relaxed);
    else if (http_code >= 400) pl->stats.n4xx.fetch_add(1, std::memory_order_relaxed);
    queue_completion(pl, r,
                     http_response(http_code, "application/json", bs.data(),
                                   bs.size(), r.close_c));
  }
  return 0;
}

int dp_next_misc(void* h, DpMiscView* out) {
  Plane* pl = (Plane*)h;
  std::unique_lock<std::mutex> lk(pl->mu);
  pl->cv_misc.wait(lk, [&] {
    return pl->stop.load(std::memory_order_relaxed) || !pl->misc_q.empty();
  });
  if (pl->misc_q.empty()) return 0;  // shutdown
  std::unique_ptr<MiscReq> m = std::move(pl->misc_q.front());
  pl->misc_q.pop_front();
  MiscReq* mp = m.get();
  pl->misc_inflight[mp->id] = std::move(m);
  out->id = mp->id;
  out->method = mp->method.data();
  out->method_len = (long long)mp->method.size();
  out->path = mp->path.data();
  out->path_len = (long long)mp->path.size();
  out->query = mp->query.data();
  out->query_len = (long long)mp->query.size();
  out->ctype = mp->ctype.data();
  out->ctype_len = (long long)mp->ctype.size();
  out->body = mp->body.data();
  out->body_len = (long long)mp->body.size();
  return 1;
}

int dp_respond_misc(void* h, long long id, int http_code, const char* ctype,
                    const char* body, long long body_len) {
  Plane* pl = (Plane*)h;
  std::unique_ptr<MiscReq> m;
  {
    std::lock_guard<std::mutex> lk(pl->mu);
    auto it = pl->misc_inflight.find(id);
    if (it == pl->misc_inflight.end()) return -1;
    m = std::move(it->second);
    pl->misc_inflight.erase(it);
  }
  if (http_code >= 500) pl->stats.n5xx.fetch_add(1, std::memory_order_relaxed);
  else if (http_code >= 400) pl->stats.n4xx.fetch_add(1, std::memory_order_relaxed);
  else pl->stats.n2xx.fetch_add(1, std::memory_order_relaxed);
  ReqInfo r;
  r.conn_id = m->conn_id;
  r.conn_gen = m->conn_gen;
  r.seq = m->seq;
  queue_completion(
      pl, r,
      http_response(http_code, ctype && *ctype ? ctype : "application/json",
                    body ? body : "", body ? (size_t)body_len : 0,
                    m->close_c));
  return 0;
}

// out[0..2] = 2xx/4xx/5xx counts, out[3] = latency sum (us, fast lane),
// out[4..18] = 15 histogram buckets (14 finite + +Inf)
void dp_stats(void* h, long long* out) {
  Plane* pl = (Plane*)h;
  out[0] = pl->stats.n2xx.load(std::memory_order_relaxed);
  out[1] = pl->stats.n4xx.load(std::memory_order_relaxed);
  out[2] = pl->stats.n5xx.load(std::memory_order_relaxed);
  out[3] = pl->stats.sum_us.load(std::memory_order_relaxed);
  for (int i = 0; i < 15; i++)
    out[4 + i] = pl->stats.hist[i].load(std::memory_order_relaxed);
}

// Two-phase shutdown: dp_shutdown stops IO and wakes blocked workers but
// keeps the Plane alive so threads mid-call (dp_next_* / dp_complete_* /
// dp_respond_misc) stay memory-safe; dp_destroy frees it once the caller
// has joined its worker threads.
void dp_shutdown(void* h) {
  Plane* pl = (Plane*)h;
  pl->stop.store(true, std::memory_order_relaxed);
  uint64_t one = 1;
  (void)!write(pl->evfd, &one, 8);
  pl->cv_batch.notify_all();
  pl->cv_misc.notify_all();
  if (pl->io_thread.joinable()) pl->io_thread.join();
}

void dp_destroy(void* h) {
  Plane* pl = (Plane*)h;
  close(pl->ep);
  close(pl->evfd);
  delete pl;
}

void dp_stop(void* h) {  // single-phase convenience for single-threaded use
  dp_shutdown(h);
  dp_destroy(h);
}

}  // extern "C"
