// loadgen — closed-loop load generator for the engine's socketed data planes.
//
// The reference's benchmark methodology drives the engine with locust workers
// on THREE dedicated client nodes (docs/benchmarking.md:20-36); on this
// single-core host the client and server timeshare one CPU, so a Python
// client would charge its own per-request cost against the server's budget.
// This native client plays the role of the reference's dedicated loadtest
// nodes: ~2 us/request of client-side work, leaving the core to the server.
//
//   REST mode: HTTP/1.1 keepalive, one connection per client, each client a
//     closed loop (request -> full response -> next request) — the exact
//     behaviour of locust FastHttpUser (util/loadtester/scripts/
//     predict_rest_locust.py).
//   GRPC mode: HTTP/2 gRPC unary (RFC 7540 framing), K clients multiplexed
//     over a few connections — the behaviour of the reference's grpc locust
//     script (predict_grpc_locust.py) modulo multiplexing.
//
// Request bytes are prepared by the Python rig (testing/loadtest.py): REST
// gets the verbatim HTTP/1.1 request; gRPC gets a replayable HPACK header
// block (static/literal-only, no dynamic-table state) plus the framed
// message body.  Single thread, epoll, level-triggered.
//
// Usage:
//   loadgen --host H --port P --api rest|grpc --clients N [--conns C]
//           --duration S --warmup S --request-file F [--headers-file F]
// Prints one JSON line: {"requests":..,"failures":..,"qps":..,"p50_ms":..,...}

#include <algorithm>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

namespace {

double now_s() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec + ts.tv_nsec * 1e-9;
}

std::vector<uint8_t> read_file(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) { fprintf(stderr, "loadgen: cannot open %s\n", path); exit(2); }
  fseek(f, 0, SEEK_END);
  long n = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::vector<uint8_t> buf(n);
  if (n && fread(buf.data(), 1, n, f) != (size_t)n) {
    fprintf(stderr, "loadgen: short read %s\n", path); exit(2);
  }
  fclose(f);
  return buf;
}

struct Stats {
  std::vector<float> lat_ms;
  uint64_t failures = 0;
  void reset() { lat_ms.clear(); failures = 0; }
};

double pct(std::vector<float>& v, double p) {
  if (v.empty()) return 0.0;
  size_t k = (size_t)(p / 100.0 * (v.size() - 1) + 0.5);
  std::nth_element(v.begin(), v.begin() + k, v.end());
  return v[k];
}

// Resolve a host name to an IPv4 dotted literal ("localhost" -> "127.0.0.1");
// returns the input unchanged if it already is one, empty string on failure.
std::string resolve_ipv4(const char* host) {
  struct in_addr probe;
  if (inet_pton(AF_INET, host, &probe) == 1) return host;
  struct addrinfo hints, *res = nullptr;
  memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  if (getaddrinfo(host, nullptr, &hints, &res) != 0 || !res) return "";
  char buf[INET_ADDRSTRLEN];
  inet_ntop(AF_INET, &((struct sockaddr_in*)res->ai_addr)->sin_addr, buf,
            sizeof(buf));
  freeaddrinfo(res);
  return buf;
}

int connect_nb(const char* host, int port) {
  int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) { close(fd); return -1; }
  int r = connect(fd, (struct sockaddr*)&addr, sizeof(addr));
  if (r < 0 && errno != EINPROGRESS) { close(fd); return -1; }
  return fd;
}

// ---------------------------------------------------------------------------
// REST: HTTP/1.1 keepalive closed loop
// ---------------------------------------------------------------------------

struct RestConn {
  int fd = -1;
  enum { CONNECTING, WRITING, READING } state = CONNECTING;
  size_t wr_off = 0;
  std::vector<uint8_t> in;
  size_t scan_from = 0;   // resume point for the header-terminator scan
  ssize_t head_end = -1;  // offset just past \r\n\r\n once found
  long clen = -1;
  double t0 = 0;
  int backoff_until_idx = 0;  // reconnect pacing, in loop iterations
};

// Case-insensitive search for "content-length:" in [buf, buf+len); returns
// the value or -1.  Our servers emit "Content-Length" but RFC 7230 says
// field names are case-insensitive, so don't assume.
long find_clen(const uint8_t* buf, size_t len) {
  static const char* k = "content-length:";
  const size_t klen = 15;
  for (size_t i = 0; i + klen <= len; i++) {
    size_t j = 0;
    while (j < klen && (buf[i + j] | 0x20) == (uint8_t)k[j]) j++;
    if (j == klen) {
      long v = 0; size_t p = i + klen;
      while (p < len && buf[p] == ' ') p++;
      bool any = false;
      while (p < len && buf[p] >= '0' && buf[p] <= '9') {
        v = v * 10 + (buf[p] - '0'); p++; any = true;
      }
      return any ? v : -1;
    }
  }
  return -1;
}

int run_rest(const char* host, int port, int clients, double warmup_s,
             double duration_s, const std::vector<uint8_t>& request,
             Stats& stats) {
  int ep = epoll_create1(0);
  std::vector<RestConn> conns(clients);
  auto arm = [&](int i, uint32_t events, int op) {
    struct epoll_event ev; ev.events = events; ev.data.u32 = i;
    epoll_ctl(ep, op, conns[i].fd, &ev);
  };
  int closed_count = 0;  // slots with fd < 0 awaiting a reconnect attempt
  auto open_conn = [&](int i) -> bool {
    conns[i].fd = connect_nb(host, port);
    if (conns[i].fd < 0) { closed_count++; return false; }
    conns[i].state = RestConn::CONNECTING;
    conns[i].wr_off = 0;
    conns[i].in.clear();
    conns[i].scan_from = 0; conns[i].head_end = -1; conns[i].clen = -1;
    arm(i, EPOLLOUT, EPOLL_CTL_ADD);
    return true;
  };
  for (int i = 0; i < clients; i++) open_conn(i);

  const double t_start = now_s();
  const double t_measure = t_start + warmup_s;
  const double t_stop = t_measure + duration_s;
  bool measuring = warmup_s <= 0;
  std::vector<struct epoll_event> events(1024);
  std::vector<int> dead;  // conns to reopen this iteration

  auto fail_conn = [&](int i) {
    if (measuring && now_s() < t_stop) stats.failures++;
    if (conns[i].fd >= 0) { close(conns[i].fd); conns[i].fd = -1; }
    dead.push_back(i);
  };

  // start (or continue) writing the request on conn i; returns false on error
  auto pump_write = [&](int i) -> bool {
    RestConn& c = conns[i];
    while (c.wr_off < request.size()) {
      ssize_t n = write(c.fd, request.data() + c.wr_off,
                        request.size() - c.wr_off);
      if (n > 0) { c.wr_off += n; continue; }
      if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        arm(i, EPOLLOUT | EPOLLIN, EPOLL_CTL_MOD);
        return true;
      }
      return false;
    }
    c.state = RestConn::READING;
    arm(i, EPOLLIN, EPOLL_CTL_MOD);
    return true;
  };

  while (true) {
    double t = now_s();
    if (!measuring && t >= t_measure) { stats.reset(); measuring = true; }
    if (t >= t_stop) break;
    int timeout_ms = (int)((t_stop - t) * 1000) + 1;
    int n = epoll_wait(ep, events.data(), events.size(), std::min(timeout_ms, 100));
    dead.clear();
    for (int e = 0; e < n; e++) {
      int i = events[e].data.u32;
      RestConn& c = conns[i];
      if (c.fd < 0) continue;
      if (events[e].events & (EPOLLERR | EPOLLHUP)) { fail_conn(i); continue; }
      if (c.state == RestConn::CONNECTING) {
        int err = 0; socklen_t el = sizeof(err);
        getsockopt(c.fd, SOL_SOCKET, SO_ERROR, &err, &el);
        if (err != 0) { fail_conn(i); continue; }
        c.state = RestConn::WRITING;
        c.t0 = now_s();
        if (!pump_write(i)) { fail_conn(i); continue; }
        continue;
      }
      if ((events[e].events & EPOLLOUT) && c.state == RestConn::WRITING) {
        if (!pump_write(i)) { fail_conn(i); continue; }
      }
      if (!(events[e].events & EPOLLIN)) continue;
      // READING (or residual EPOLLIN while writing — server never does that)
      char buf[65536];
      bool conn_dead = false;
      while (true) {
        ssize_t r = read(c.fd, buf, sizeof(buf));
        if (r > 0) {
          c.in.insert(c.in.end(), buf, buf + r);
          if (r == (ssize_t)sizeof(buf)) continue;
        } else if (r == 0) { conn_dead = true; }
        else if (errno != EAGAIN && errno != EWOULDBLOCK) { conn_dead = true; }
        break;
      }
      // parse as many complete responses as the buffer holds (the server
      // never pipelines unrequested data; normally exactly one)
      while (c.state == RestConn::READING) {
        if (c.head_end < 0) {
          if (c.in.size() >= 4) {
            const uint8_t* p = c.in.data();
            size_t from = c.scan_from > 3 ? c.scan_from - 3 : 0;
            for (size_t j = from; j + 4 <= c.in.size(); j++) {
              if (p[j] == '\r' && p[j+1] == '\n' && p[j+2] == '\r' &&
                  p[j+3] == '\n') { c.head_end = j + 4; break; }
            }
            c.scan_from = c.in.size();
          }
          if (c.head_end < 0) break;
          c.clen = find_clen(c.in.data(), c.head_end);
          if (c.clen < 0) c.clen = 0;
        }
        if (c.in.size() < (size_t)c.head_end + c.clen) break;
        // complete response
        bool ok = c.in.size() >= 12 && c.in[9] == '2';  // HTTP/1.1 2xx
        double tc = now_s();
        if (measuring) {
          if (ok) stats.lat_ms.push_back((float)((tc - c.t0) * 1e3));
          else stats.failures++;
        }
        size_t used = c.head_end + c.clen;
        c.in.erase(c.in.begin(), c.in.begin() + used);
        c.scan_from = 0; c.head_end = -1; c.clen = -1;
        // closed loop: fire the next request immediately
        c.state = RestConn::WRITING;
        c.wr_off = 0;
        c.t0 = tc;
        if (!pump_write(i)) { conn_dead = true; break; }
      }
      if (conn_dead) fail_conn(i);
    }
    for (int i : dead) open_conn(i);
    if (closed_count > 0) {
      // slots whose (re)connect itself failed: retry them each iteration
      closed_count = 0;
      for (int i = 0; i < clients; i++)
        if (conns[i].fd < 0) open_conn(i);
    }
  }
  for (auto& c : conns) if (c.fd >= 0) close(c.fd);
  close(ep);
  return 0;
}

// ---------------------------------------------------------------------------
// GRPC: HTTP/2 unary, multiplexed closed-loop clients
// ---------------------------------------------------------------------------

const uint8_t kPreface[] = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";
enum { F_DATA = 0, F_HEADERS = 1, F_RST = 3, F_SETTINGS = 4, F_PING = 6,
       F_GOAWAY = 7, F_WINDOW_UPDATE = 8, F_CONTINUATION = 9 };
enum { FLAG_END_STREAM = 1, FLAG_ACK = 1, FLAG_END_HEADERS = 4 };

void put_frame_header(std::vector<uint8_t>& out, uint32_t len, uint8_t type,
                      uint8_t flags, uint32_t sid) {
  out.push_back((len >> 16) & 0xff);
  out.push_back((len >> 8) & 0xff);
  out.push_back(len & 0xff);
  out.push_back(type);
  out.push_back(flags);
  out.push_back((sid >> 24) & 0x7f);
  out.push_back((sid >> 16) & 0xff);
  out.push_back((sid >> 8) & 0xff);
  out.push_back(sid & 0xff);
}

struct Slot {  // one closed-loop client
  int conn = -1;
  uint32_t stream = 0;
  double t0 = 0;
  bool got_data = false;
  bool inflight = false;
};

struct GrpcConn {
  int fd = -1;
  bool connected = false;   // TCP established + preface sent
  std::vector<uint8_t> out;
  size_t out_off = 0;
  std::vector<uint8_t> in;
  size_t in_off = 0;        // parse cursor (compacted periodically)
  int64_t send_window = 65535;
  uint64_t recv_since_update = 0;
  uint32_t next_stream = 1;
  std::unordered_map<uint32_t, int> stream_slot;
  std::vector<int> parked;  // slots waiting for send window
  bool dead = false;
};

int run_grpc(const char* host, int port, int clients, int n_conns,
             double warmup_s, double duration_s,
             const std::vector<uint8_t>& header_block,
             const std::vector<uint8_t>& body, Stats& stats) {
  int ep = epoll_create1(0);
  std::vector<GrpcConn> conns(n_conns);
  std::vector<Slot> slots(clients);
  for (int s = 0; s < clients; s++) slots[s].conn = s % n_conns;

  auto arm = [&](int ci, uint32_t ev_mask, int op) {
    struct epoll_event ev; ev.events = ev_mask; ev.data.u32 = ci;
    epoll_ctl(ep, op, conns[ci].fd, &ev);
  };
  auto flush = [&](int ci) {
    GrpcConn& c = conns[ci];
    while (c.out_off < c.out.size()) {
      ssize_t n = write(c.fd, c.out.data() + c.out_off,
                        c.out.size() - c.out_off);
      if (n > 0) { c.out_off += n; continue; }
      if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        arm(ci, EPOLLOUT | EPOLLIN, EPOLL_CTL_MOD);
        return;
      }
      c.dead = true;
      return;
    }
    if (c.out_off == c.out.size() && !c.out.empty()) {
      c.out.clear(); c.out_off = 0;
      arm(ci, EPOLLIN, EPOLL_CTL_MOD);
    }
  };
  auto open_conn = [&](int ci) -> bool {
    GrpcConn& c = conns[ci];
    std::vector<int> keep = std::move(c.parked);  // survive reconnects
    c = GrpcConn();
    c.parked = std::move(keep);
    c.fd = connect_nb(host, port);
    if (c.fd < 0) return false;
    // connection bootstrap: preface, SETTINGS (huge receive window), and a
    // connection WINDOW_UPDATE opening the conn-level receive window — the
    // same bootstrap runtime/grpcfast.py's client does
    c.out.insert(c.out.end(), kPreface, kPreface + sizeof(kPreface) - 1);
    put_frame_header(c.out, 12, F_SETTINGS, 0, 0);
    auto put_setting = [&](uint16_t k, uint32_t v) {
      c.out.push_back(k >> 8); c.out.push_back(k & 0xff);
      c.out.push_back(v >> 24); c.out.push_back((v >> 16) & 0xff);
      c.out.push_back((v >> 8) & 0xff); c.out.push_back(v & 0xff);
    };
    put_setting(0x4, 0x7fffffff);          // INITIAL_WINDOW_SIZE
    put_setting(0x3, 1u << 20);            // MAX_CONCURRENT_STREAMS
    put_frame_header(c.out, 4, F_WINDOW_UPDATE, 0, 0);
    uint32_t inc = 0x7fffffff - 65535;
    c.out.push_back(inc >> 24); c.out.push_back((inc >> 16) & 0xff);
    c.out.push_back((inc >> 8) & 0xff); c.out.push_back(inc & 0xff);
    arm(ci, EPOLLOUT | EPOLLIN, EPOLL_CTL_ADD);
    return true;
  };
  for (int ci = 0; ci < n_conns; ci++) {
    if (!open_conn(ci)) { fprintf(stderr, "loadgen: connect failed\n"); return 2; }
  }

  const double t_start = now_s();
  const double t_measure = t_start + warmup_s;
  const double t_stop = t_measure + duration_s;
  bool measuring = warmup_s <= 0;

  auto fire = [&](int si) {
    Slot& s = slots[si];
    GrpcConn& c = conns[s.conn];
    if (c.dead || !c.connected) { c.parked.push_back(si); return; }
    if (c.send_window < (int64_t)body.size()) { c.parked.push_back(si); return; }
    s.stream = c.next_stream;
    c.next_stream += 2;
    s.t0 = now_s();
    s.got_data = false;
    s.inflight = true;
    c.stream_slot[s.stream] = si;
    put_frame_header(c.out, header_block.size(), F_HEADERS, FLAG_END_HEADERS,
                     s.stream);
    c.out.insert(c.out.end(), header_block.begin(), header_block.end());
    put_frame_header(c.out, body.size(), F_DATA, FLAG_END_STREAM, s.stream);
    c.out.insert(c.out.end(), body.begin(), body.end());
    c.send_window -= body.size();
  };

  auto complete = [&](int ci, uint32_t sid, bool rst) {
    GrpcConn& c = conns[ci];
    auto it = c.stream_slot.find(sid);
    if (it == c.stream_slot.end()) return;
    int si = it->second;
    c.stream_slot.erase(it);
    Slot& s = slots[si];
    s.inflight = false;
    double t = now_s();
    if (measuring && t < t_stop) {
      if (!rst && s.got_data)
        stats.lat_ms.push_back((float)((t - s.t0) * 1e3));
      else
        stats.failures++;
    }
    if (t < t_stop) fire(si);
  };

  auto kill_conn = [&](int ci) {
    GrpcConn& c = conns[ci];
    if (c.fd >= 0) { close(c.fd); c.fd = -1; }
    std::vector<int> orphans;
    for (auto& kv : c.stream_slot) orphans.push_back(kv.second);
    c.stream_slot.clear();
    if (measuring) stats.failures += orphans.size();
    for (int si : orphans) {
      slots[si].inflight = false;
      c.parked.push_back(si);  // refired once the conn is back up
    }
    open_conn(ci);  // on failure the main loop retries each iteration
  };

  // process one complete frame at [p, p+9+len); returns frame length or -1
  auto handle = [&](int ci) {
    GrpcConn& c = conns[ci];
    while (true) {
      size_t avail = c.in.size() - c.in_off;
      if (avail < 9) break;
      const uint8_t* p = c.in.data() + c.in_off;
      uint32_t len = (p[0] << 16) | (p[1] << 8) | p[2];
      if (avail < 9 + len) break;
      uint8_t type = p[3], flags = p[4];
      uint32_t sid = ((p[5] & 0x7f) << 24) | (p[6] << 16) | (p[7] << 8) | p[8];
      const uint8_t* payload = p + 9;
      switch (type) {
        case F_DATA: {
          auto it = c.stream_slot.find(sid);
          if (it != c.stream_slot.end() && len > 0)
            slots[it->second].got_data = true;
          c.recv_since_update += len;
          if (c.recv_since_update >= (1u << 20)) {
            put_frame_header(c.out, 4, F_WINDOW_UPDATE, 0, 0);
            uint32_t inc = (uint32_t)c.recv_since_update;
            c.out.push_back(inc >> 24); c.out.push_back((inc >> 16) & 0xff);
            c.out.push_back((inc >> 8) & 0xff); c.out.push_back(inc & 0xff);
            c.recv_since_update = 0;
          }
          if (flags & FLAG_END_STREAM) complete(ci, sid, false);
          break;
        }
        case F_HEADERS:
        case F_CONTINUATION:
          if (flags & FLAG_END_STREAM) complete(ci, sid, false);
          break;
        case F_RST:
          complete(ci, sid, true);
          break;
        case F_SETTINGS:
          if (!(flags & FLAG_ACK)) {
            // Ack.  We ignore INITIAL_WINDOW_SIZE deltas: request bodies are
            // < 64 KiB and sent whole with END_STREAM, so per-stream windows
            // never bind; the server (grpcfast) advertises huge windows.
            put_frame_header(c.out, 0, F_SETTINGS, FLAG_ACK, 0);
            c.connected = true;
            std::vector<int> parked; parked.swap(c.parked);
            for (int si : parked) fire(si);
          }
          break;
        case F_PING:
          if (!(flags & FLAG_ACK)) {
            put_frame_header(c.out, 8, F_PING, FLAG_ACK, 0);
            c.out.insert(c.out.end(), payload, payload + 8);
          }
          break;
        case F_WINDOW_UPDATE: {
          uint32_t inc = ((payload[0] & 0x7f) << 24) | (payload[1] << 16) |
                         (payload[2] << 8) | payload[3];
          if (sid == 0) {
            c.send_window += inc;
            std::vector<int> parked; parked.swap(c.parked);
            for (int si : parked) fire(si);
          }
          break;
        }
        case F_GOAWAY:
          c.dead = true;
          break;
        default:
          break;  // PUSH_PROMISE / PRIORITY / unknown: ignore
      }
      c.in_off += 9 + len;
    }
    if (c.in_off > (1u << 16)) {
      c.in.erase(c.in.begin(), c.in.begin() + c.in_off);
      c.in_off = 0;
    }
  };

  std::vector<struct epoll_event> events(256);
  bool fired = false;
  while (true) {
    double t = now_s();
    if (!measuring && t >= t_measure) { stats.reset(); measuring = true; }
    if (t >= t_stop) break;
    for (int ci = 0; ci < n_conns; ci++)  // conns whose reconnect failed
      if (conns[ci].fd < 0) open_conn(ci);
    int n = epoll_wait(ep, events.data(), events.size(), 50);
    for (int e = 0; e < n; e++) {
      int ci = events[e].data.u32;
      GrpcConn& c = conns[ci];
      if (c.fd < 0) continue;
      if (events[e].events & (EPOLLERR | EPOLLHUP)) { kill_conn(ci); continue; }
      if (events[e].events & EPOLLIN) {
        char buf[65536];
        while (true) {
          ssize_t r = read(c.fd, buf, sizeof(buf));
          if (r > 0) {
            c.in.insert(c.in.end(), buf, buf + r);
            if (r == (ssize_t)sizeof(buf)) continue;
          } else if (r == 0) { c.dead = true; }
          else if (errno != EAGAIN && errno != EWOULDBLOCK) { c.dead = true; }
          break;
        }
        handle(ci);
      }
      if (c.dead) { kill_conn(ci); continue; }
      if (!fired && c.connected) {
        // first connection became ready: launch every client slot
        fired = true;
        for (int si = 0; si < clients; si++)
          if (!slots[si].inflight) fire(si);
      }
      flush(ci);
      if (c.dead) kill_conn(ci);
    }
  }
  for (auto& c : conns) if (c.fd >= 0) close(c.fd);
  close(ep);
  return 0;
}

}  // namespace

int main(int argc, char** argv) {
  const char* host = "127.0.0.1";
  int port = 8000, clients = 64, conns = -1;
  double duration = 10.0, warmup = 2.0;
  const char* api = "rest";
  const char* request_file = nullptr;
  const char* headers_file = nullptr;
  for (int i = 1; i < argc - 1; i++) {
    if (!strcmp(argv[i], "--host")) host = argv[++i];
    else if (!strcmp(argv[i], "--port")) port = atoi(argv[++i]);
    else if (!strcmp(argv[i], "--api")) api = argv[++i];
    else if (!strcmp(argv[i], "--clients")) clients = atoi(argv[++i]);
    else if (!strcmp(argv[i], "--conns")) conns = atoi(argv[++i]);
    else if (!strcmp(argv[i], "--duration")) duration = atof(argv[++i]);
    else if (!strcmp(argv[i], "--warmup")) warmup = atof(argv[++i]);
    else if (!strcmp(argv[i], "--request-file")) request_file = argv[++i];
    else if (!strcmp(argv[i], "--headers-file")) headers_file = argv[++i];
  }
  if (!request_file) { fprintf(stderr, "loadgen: --request-file required\n"); return 2; }
  std::string ip = resolve_ipv4(host);
  if (ip.empty()) { fprintf(stderr, "loadgen: cannot resolve %s\n", host); return 2; }
  host = ip.c_str();
  std::vector<uint8_t> request = read_file(request_file);

  Stats stats;
  stats.lat_ms.reserve(1 << 21);
  double t0 = now_s();
  int rc;
  if (!strcmp(api, "grpc")) {
    if (!headers_file) { fprintf(stderr, "loadgen: --headers-file required\n"); return 2; }
    std::vector<uint8_t> headers = read_file(headers_file);
    if (conns <= 0) conns = std::max(1, std::min(4, clients / 64));
    rc = run_grpc(host, port, clients, conns, warmup, duration, headers,
                  request, stats);
  } else {
    rc = run_rest(host, port, clients, warmup, duration, request, stats);
  }
  if (rc != 0) return rc;
  double wall = now_s() - t0 - warmup;

  std::vector<float>& v = stats.lat_ms;
  double p50 = pct(v, 50), p75 = pct(v, 75), p90 = pct(v, 90),
         p95 = pct(v, 95), p99 = pct(v, 99);
  printf(
      "{\"requests\": %zu, \"failures\": %llu, \"qps\": %.1f, "
      "\"clients\": %d, \"duration_s\": %.1f, \"p50_ms\": %.2f, "
      "\"p75_ms\": %.2f, \"p90_ms\": %.2f, \"p95_ms\": %.2f, "
      "\"p99_ms\": %.2f}\n",
      v.size(), (unsigned long long)stats.failures,
      v.size() / std::max(wall, 1e-9), clients, duration, p50, p75, p90, p95,
      p99);
  return 0;
}
