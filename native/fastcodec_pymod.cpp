// CPython extension binding for the native SeldonMessage wire codec.
//
// The ctypes binding (seldon_core_tpu/native/fastcodec.py) costs ~15us per
// call in argument marshalling alone — more than the C++ parse itself for
// typical payloads.  This module exposes the same two entry points through
// the CPython C API so the per-call overhead is ~1us:
//
//   parse(bytes|str)  -> None | (envelope_bytes, kind:int, float64 ndarray|None)
//   format(ndarray_f64_contig, kind:int) -> bytes | None
//
// kind codes match fastcodec.cpp: 0 = no numeric payload, 1 = tensor,
// 2 = ndarray.  None always means "caller falls back to a slower path".
//
// Built standalone by seldon_core_tpu/native/fastcodec.py (g++ -shared with
// the CPython + numpy include dirs); fastcodec.cpp is included directly so
// the codec core stays in one file.

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#define NPY_NO_DEPRECATED_API NPY_1_7_API_VERSION
#include <numpy/arrayobject.h>

#include "fastcodec.cpp"

namespace {

PyObject* py_parse(PyObject*, PyObject* arg) {
  const char* buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_Check(arg)) {
    buf = PyBytes_AS_STRING(arg);
    len = PyBytes_GET_SIZE(arg);
  } else if (PyUnicode_Check(arg)) {
    buf = PyUnicode_AsUTF8AndSize(arg, &len);
    if (buf == nullptr) return nullptr;
  } else {
    Py_RETURN_NONE;
  }

  SMView view;
  Parse* p = sm_parse_view(buf, (long long)len, &view);
  if (p == nullptr) Py_RETURN_NONE;
  if (view.status != SM_OK || view.ndim > 32) {
    sm_free(p);
    Py_RETURN_NONE;
  }

  PyObject* env = view.envelope
                      ? PyBytes_FromStringAndSize((const char*)view.envelope,
                                                  (Py_ssize_t)view.envelope_len)
                      : PyBytes_FromStringAndSize("{}", 2);
  if (env == nullptr) {
    sm_free(p);
    return nullptr;
  }

  PyObject* result = nullptr;
  if (view.kind == KIND_NONE) {
    result = Py_BuildValue("(NiO)", env, (int)KIND_NONE, Py_None);
    if (result == nullptr) Py_DECREF(env);
  } else {
    npy_intp dims[32];
    long long prod = 1;
    for (int i = 0; i < view.ndim; ++i) {
      dims[i] = (npy_intp)view.shape[i];
      prod *= view.shape[i];
    }
    if (prod != view.nvalues) {  // defensive: never hand back a bad view
      Py_DECREF(env);
      sm_free(p);
      Py_RETURN_NONE;
    }
    PyObject* arr = PyArray_SimpleNew(view.ndim, dims, NPY_FLOAT64);
    if (arr == nullptr) {
      Py_DECREF(env);
      sm_free(p);
      return nullptr;
    }
    if (view.nvalues > 0) {
      memcpy(PyArray_DATA((PyArrayObject*)arr), view.values,
             (size_t)view.nvalues * sizeof(double));
    }
    result = Py_BuildValue("(NiN)", env, (int)view.kind, arr);
    if (result == nullptr) {
      Py_DECREF(env);
      Py_DECREF(arr);
    }
  }
  sm_free(p);
  return result;
}

PyObject* py_format(PyObject*, PyObject* args) {
  PyObject* obj = nullptr;
  int kind = KIND_NDARRAY;
  if (!PyArg_ParseTuple(args, "Oi", &obj, &kind)) return nullptr;
  if (!PyArray_Check(obj)) Py_RETURN_NONE;
  PyArrayObject* arr = (PyArrayObject*)obj;
  if (PyArray_TYPE(arr) != NPY_FLOAT64 ||
      !PyArray_IS_C_CONTIGUOUS(arr) || PyArray_NDIM(arr) < 1) {
    Py_RETURN_NONE;
  }
  int ndim = PyArray_NDIM(arr);
  long long shape[32];
  if (ndim > 32) Py_RETURN_NONE;
  for (int i = 0; i < ndim; ++i) shape[i] = (long long)PyArray_DIM(arr, i);
  long long out_len = 0;
  char* out = sm_format((const double*)PyArray_DATA(arr), shape, ndim, kind,
                        &out_len);
  if (out == nullptr) Py_RETURN_NONE;
  PyObject* bytes = PyBytes_FromStringAndSize(out, (Py_ssize_t)out_len);
  sm_buf_free(out);
  return bytes;
}

PyMethodDef kMethods[] = {
    {"parse", py_parse, METH_O,
     "parse(raw) -> None | (envelope_bytes, kind, float64 array|None)"},
    {"format", (PyCFunction)py_format, METH_VARARGS,
     "format(f64_c_contig_array, kind) -> bytes | None"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef kModule = {
    PyModuleDef_HEAD_INIT, "_fastcodec",
    "Native SeldonMessage wire codec (CPython binding)", -1, kMethods,
};

}  // namespace

PyMODINIT_FUNC PyInit__fastcodec(void) {
  import_array();
  return PyModule_Create(&kModule);
}
