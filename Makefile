proto:
	protoc -I proto --python_out=seldon_core_tpu/proto_gen proto/prediction.proto

test:
	python -m pytest tests/ -q

bench:
	python bench.py

.PHONY: proto test bench
