VERSION ?= latest
IMAGES = engine gateway operator loadtest

proto:
	protoc -I proto --python_out=seldon_core_tpu/proto_gen proto/prediction.proto proto/seldon_deployment.proto

native:
	g++ -O3 -std=c++17 -fPIC -shared -pthread -o native/libdataplane.so native/dataplane.cpp native/fastcodec.cpp
	g++ -O3 -std=c++17 -fPIC -shared -o native/libfastcodec.so native/fastcodec.cpp
	g++ -O2 -std=c++17 -o native/loadgen native/loadgen.cpp

test:
	python -m pytest tests/ -q

# deterministic fault-injection suite: combiner quorum, router fallback,
# breaker transitions, end-to-end deadlines, pause/drain (tests/test_chaos.py)
# + the mesh-kill lane (tests/test_mesh_kill.py): SIGKILL the coordinator
# gateway and one engine under live unary+SSE load — zero failed unary,
# >=99% streams complete, coordinator failover within one lease TTL
chaos:
	python -m pytest tests/ -q -m chaos

# causal-tracing demo: 3-node graph under fault injection, one traced
# request tree with retries/backoff, exported Perfetto-loadable artifact
# (trace_demo/trace.json) + critical-path summary (scripts/trace_demo.py)
trace-demo:
	python scripts/trace_demo.py --out trace_demo

# performance-observatory demo: a 3-node compiled ensemble under a batch
# mix, the GET /perf per-executable cost/MFU/roofline table dumped as an
# artifact (perf_demo/perf.json) + printed (scripts/perf_demo.py)
perf-demo:
	python scripts/perf_demo.py --out perf_demo

# prediction-quality demo: a 3-node graph served through a mid-run input
# distribution shift, the GET /quality drift/feedback/SLO table dumped as
# an artifact (quality_demo/quality.json) + printed
# (scripts/quality_demo.py)
quality-demo:
	python scripts/quality_demo.py --out quality_demo

# scale-out demo: gateway + 2 engine replicas, one deterministically slow
# (testing/faults.py FaultyEngine) — asserts the power-of-two-choices
# balancer steers away from it and that SELDON_TPU_REPLICAS=0 restores
# the single-engine path (scale_demo/scale.json artifact)
scale-demo:
	python scripts/scale_demo.py --out scale_demo

# autopilot demo: learned cost-model shed-before-dispatch — a heavy
# tight-deadline class is refused with typed 503s at admission (zero
# wasted device dispatches, measured exactly via the perf observatory's
# dispatched-row delta) and the serveable class's p99 improves; proves
# the SELDON_TPU_AUTOPILOT=0 kill switch restores the reactive path.
# Artifact autopilot_demo/autopilot.json + the GET /autopilot page
# (scripts/autopilot_demo.py; docs/operations.md "reading the
# /autopilot page")
autopilot-demo:
	python scripts/autopilot_demo.py --out autopilot_demo

# safe-rollout demo: shadow mirroring -> firehose replay vet -> staged
# canary under injected drift -> automatic rollback with zero failed
# live requests; proves both kill switches (SELDON_TPU_SHADOW=0,
# SELDON_TPU_ROLLOUTS=0).  Artifact canary_demo/rollout.json +
# shadow.json + replay.json (scripts/canary_demo.py; docs/operations.md
# "safe rollout" runbook)
canary-demo:
	python scripts/canary_demo.py --out canary_demo

# overload-survival demo: a 10x-share hog tenant vs a well-behaved
# victim over a fixed-capacity engine — fair admission holds the
# victim's p99, the brownout ladder engages and reverts in order, and
# the kill-switch arm (SELDON_TPU_BROWNOUT=0 + SELDON_TPU_TENANCY=0)
# shows the starvation this layer prevents.  Artifact
# overload_demo/overload.json (scripts/overload_demo.py;
# docs/operations.md "Surviving overload")
overload-demo:
	JAX_PLATFORMS=cpu python scripts/overload_demo.py --out overload_demo

# disaggregated-generation demo: 1 prefill + 2 decode CPU replicas,
# KV blocks streamed over the relay's OP_KVSTREAM lane — proves
# token-identity vs unified, handoffs visible in /stats + the firehose,
# the decode-direct typed 503, and the SELDON_TPU_DISAGG=0 kill switch.
# Artifact disagg_demo/disagg.json (scripts/disagg_demo.py;
# docs/operations.md "Disaggregated generation")
disagg-demo:
	JAX_PLATFORMS=cpu python scripts/disagg_demo.py --out disagg_demo

# fleet observability demo: a disaggregated generation traced END TO
# END through the gateway's federated /trace (one causal tree across
# gateway, prefill, KV-handoff and decode processes, critical path
# summing to the root), a +30ms FaultyEngine replica surfacing as the
# /fleet outlier, a coordinated profile window manifest with overlap
# refusal, and the SELDON_TPU_FLEET=0 kill-switch contrast.  Artifacts
# fleet_demo/fleet.json + trace_perfetto.json (scripts/fleet_demo.py;
# docs/operations.md "The fleet observability plane")
fleet-demo:
	JAX_PLATFORMS=cpu python scripts/fleet_demo.py --out fleet_demo

# cost-attribution demo: two tenants with skewed load through the
# micro-batcher AND the continuous-batching scheduler — the cost ledger
# (utils/costledger.py) must split each fenced device wall 3:2 with the
# pad tax following real shares, keep the accounting identity
# (accounted_fraction == 1.0), integrate KV-block-seconds, and the
# usage-weighted WFQ arm (SELDON_TPU_QOS_USAGE_WEIGHTED=1) must drain
# the cheap tenant ahead of the hog.  Artifact cost_demo/costs.json
# (scripts/cost_demo.py; docs/operations.md "Reading the /costs page")
cost-demo:
	JAX_PLATFORMS=cpu python scripts/cost_demo.py --out cost_demo

# postmortem demo: at SELDON_TPU_TRACE_SAMPLE=0.01 an injected +30 ms
# dispatch outlier must be KEPT by the tail-sampled recorder
# (utils/postmortem.py) with the explainer naming the guilty phase,
# while SELDON_TPU_POSTMORTEM=0 keeps nothing and restores the plain
# traceparent flags byte.  Artifact postmortem_demo/postmortem.json
# (scripts/postmortem_demo.py; docs/operations.md "Reading a
# postmortem")
postmortem-demo:
	JAX_PLATFORMS=cpu python scripts/postmortem_demo.py --out postmortem_demo

# perf-corpus demo: restart warm-start off the durable dispatch ledger
# (utils/perfcorpus.py) — a freshly-booted engine must price
# previously-seen shapes BEFORE its first dispatch (autopilot keys > 0
# at boot), and the SELDON_TPU_CORPUS=0 arm must boot cold.  Artifact
# corpus_demo/corpus.json + the GET /corpus page (scripts/corpus_demo.py;
# docs/operations.md "Fleet-truth burn and the perf corpus")
corpus-demo:
	JAX_PLATFORMS=cpu python scripts/corpus_demo.py --out corpus_demo

bench:
	python bench.py

# telemetry overhead budget gate: the span probe with EVERY observatory
# enabled must keep span_framework_p50_ms within
# SELDON_TPU_OVERHEAD_BUDGET_MS (default 1.0).  Fails loudly on breach;
# prove it gates with SELDON_TPU_TELEMETRY_TEST_DELAY_MS=2.
# CPU-friendly — no TPU required (docs/operations.md runbook).
# Relative A/B mode: when the absolute budget is breached, the baseline
# ref (OVERHEAD_BASELINE, default HEAD — set it to origin/main when the
# working tree IS HEAD, or empty for the pure absolute gate) is measured
# in a clean worktree ON THE SAME BOX and the gate fails only if this
# tree exceeds SELDON_TPU_OVERHEAD_REL_TOLERANCE (1.25x) of it — slow
# containers read as "parity", regressions still go red.
OVERHEAD_BASELINE ?= HEAD
overhead-gate:
	JAX_PLATFORMS=cpu python bench.py --overhead-gate $(if $(OVERHEAD_BASELINE),--overhead-gate-baseline $(OVERHEAD_BASELINE),)

# continuous-batching TTFT gate: the concurrent-stream probe (staggered
# arrivals into an already-decoding batch) must keep TTFT p50 within
# SELDON_TPU_TTFT_BUDGET_MS (default 400).  A scheduler change that lets
# prefill block co-batched decode — the r05 regression (305 -> 2012 ms)
# — turns this lane red.  CPU-friendly (docs/operations.md runbook).
ttft-gate:
	JAX_PLATFORMS=cpu python bench.py --ttft-gate --smoke

# multi-tenant fairness gate: a victim tenant's p99 under a 10x-share
# hog must stay within SELDON_TPU_FAIRNESS_BOUND (default 1.5) x its
# solo baseline with zero victim failures — the runtime/qos.py token
# bucket + weighted-fair-queue admission contract, best-of-3.
# CPU-friendly (docs/operations.md "Surviving overload" runbook).
fairness-gate:
	JAX_PLATFORMS=cpu python bench.py --fairness-gate

# binary wire contract gate: JSON vs application/x-seldon-tensor over
# the same socket/engine (bench.py --wire-gate, best-of-3).  Fails when
# the binary-lane floor exceeds SELDON_TPU_WIRE_FLOOR_REL (default
# 0.6) x the JSON floor AND bytes-copied-per-request dropped < 4x (the
# host-bound-container escape hatch; SELDON_TPU_WIRE_GATE_STRICT=1
# disables it).  CPU-friendly (docs/benchmarking.md "binary wire A/B").
wire-gate:
	JAX_PLATFORMS=cpu python bench.py --wire-gate --smoke

# served-decode flight-recorder gate: drives the REAL continuous-
# batching scheduler at saturation (bench.py --decode-gate, best-of-3)
# and holds the bubble ledger to SELDON_TPU_DECODE_BUBBLE_MAX (default
# 0.25) and served/kernel decode throughput to
# SELDON_TPU_SERVED_DECODE_REL (default 0.25), with a >=95% ledger-
# integrity floor and a host-bound escape hatch
# (SELDON_TPU_DECODE_GATE_STRICT=1 disables it).  CPU-friendly
# (docs/benchmarking.md "served decode MFU").
decode-gate:
	JAX_PLATFORMS=cpu python bench.py --decode-gate --smoke

# decode flight-recorder demo: saturated genserver run that prints the
# per-tick timeline (kind, host/device split, bubbles by cause) and the
# bubble-ledger breakdown, checks host+device+bubble accounts for >=95%
# of scheduler wall, and writes the /genperf document.  Artifact
# decode_demo/genperf.json (scripts/decode_demo.py; docs/operations.md
# "Reading the /genperf page")
decode-demo:
	JAX_PLATFORMS=cpu python scripts/decode_demo.py --out decode_demo

# binary-wire demo: sequential bit-exact JSON-vs-binary parity through
# gateway->relay->engine, a coalesced burst (N requests, fewer relay
# frames), the floor/copy A/B, and the SELDON_TPU_WIRE=0 kill switch.
# Artifact wire_demo/wire.json (scripts/wire_demo.py; docs/
# external-api.md "binary tensor wire contract")
wire-demo:
	JAX_PLATFORMS=cpu python scripts/wire_demo.py --out wire_demo

# whole-graph fusion gate: the fused dispatch path (graph/fuse.py) must
# stay bit-identical to the interpreter on the probe graphs AND keep the
# fused 4-node-chain p50 <= SELDON_TPU_FUSION_REL (default 0.7) x the
# interpreted p50, best-of-3.  Escape hatch for host-core-bound runners:
# relax SELDON_TPU_FUSION_REL toward 1.0 — equivalence and the
# graph_hops_eliminated N->1 accounting still gate.  CPU-friendly
# (docs/operations.md "The fused graph path").
fusion-gate:
	JAX_PLATFORMS=cpu python bench.py --fusion-gate --smoke

# whole-graph fusion demo: fused-vs-interpreter equivalence on a served
# graph, the fusion plan off /stats, the /perf per-node phase
# decomposition, and the SELDON_TPU_GRAPH_FUSE=0 kill switch.  Artifact
# fusion_demo/fusion.json (scripts/fusion_demo.py; docs/operations.md
# "The fused graph path")
fusion-demo:
	JAX_PLATFORMS=cpu python scripts/fusion_demo.py --out fusion_demo

# regenerate every artifact-quoted doc figure from the committed round
# snapshot / fail when the docs drift from it (CI runs docs-check)
docs-sync:
	python scripts/docs_sync.py

docs-check:
	python scripts/docs_sync.py --check

# guided end-to-end walkthroughs (the reference's notebooks role):
# canary shift, 8-member ensemble, epsilon-greedy feedback, SSE streaming
demos:
	python examples/demos.py all

# full model lifecycle: train -> checkpoint -> serve -> verify over REST
train-demo:
	python examples/train_then_serve.py

stack:
	python examples/local_stack.py

bundle:
	python -m seldon_core_tpu.operator.bundle

# component images (ci/docker/Dockerfile multi-stage; the reference's
# per-service Jenkinsfile build stages)
images:
	for t in $(IMAGES); do \
	  docker build -f ci/docker/Dockerfile --target $$t \
	    -t seldon-core-tpu/$$t:$(VERSION) . || exit 1 ; \
	done

publish: images
	for t in $(IMAGES); do \
	  docker push seldon-core-tpu/$$t:$(VERSION) || exit 1 ; \
	done

release-dryrun:
	@test "$(VERSION)" != "latest" || \
	  { echo "usage: make release-dryrun VERSION=X.Y.Z"; exit 2; }
	python release/release.py --version $(VERSION)

.PHONY: proto native test chaos trace-demo perf-demo quality-demo scale-demo autopilot-demo canary-demo overload-demo disagg-demo fleet-demo corpus-demo cost-demo postmortem-demo bench overhead-gate ttft-gate fairness-gate wire-gate wire-demo decode-gate decode-demo fusion-gate fusion-demo demos train-demo stack bundle images publish release-dryrun
