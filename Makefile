proto:
	protoc -I proto --python_out=seldon_core_tpu/proto_gen proto/prediction.proto proto/seldon_deployment.proto

test:
	python -m pytest tests/ -q

bench:
	python bench.py

.PHONY: proto test bench
