"""Tail-sampled postmortem recorder (utils/postmortem.py).

Covers: every retention trigger (error / shed / SLO-by-tier /
autopilot-excess / preemption / breaker / out-of-band notes /
reservoir baseline), the explainer's phase-excess math against
hand-computed numbers, pending-buffer and reservoir bounds, the
copy-out-at-keep-time immutability contract (pin vs a 1-entry ring),
the traceparent pm bit (bit 0x02) end-to-end including old-peer
degradation, the gateway-federated worst-of-fleet merge, and both kill
switches (``SELDON_TPU_POSTMORTEM=0`` and a disabled tracer)."""

import asyncio
import json
import random
from types import SimpleNamespace

from seldon_core_tpu.utils.postmortem import (
    POSTMORTEM,
    PostmortemRecorder,
    postmortem_enabled,
)
from seldon_core_tpu.utils.tracing import (
    Span,
    TraceContext,
    Tracer,
    parse_traceparent,
    trace_scope,
    traceparent_header_value,
)


def _span(tid, *, span_id, puid="", name="engine", kind="request",
          method="predict", start=1000.0, dur=10.0, parent="",
          attrs=None, events=None):
    return Span(puid=puid or f"p-{tid}", name=name, kind=kind,
                method=method, start_s=start, duration_ms=dur,
                attrs=dict(attrs or {}), trace_id=tid, span_id=span_id,
                parent_span_id=parent, events=list(events or ()))


def _request(rec, tid, *, root_ms=10.0, root_attrs=None, child=None):
    """Feed one synthetic request (optional child first, root last —
    fold order) and return the root span."""
    if child is not None:
        rec.offer(child)
    root = _span(tid, span_id="r" + tid, dur=root_ms, attrs=root_attrs)
    rec.offer(root)
    return root


def _recorder(**kw):
    kw.setdefault("enabled", True)
    kw.setdefault("slo_ms", 0.0)
    return PostmortemRecorder(**kw)


# -- retention triggers ----------------------------------------------------


def test_healthy_request_is_dropped():
    rec = _recorder(baseline=0)
    _request(rec, "t1")
    assert rec.document()["kept"] == []
    assert rec.completed_total == 1


def test_error_trigger_5xx_status():
    rec = _recorder()
    _request(rec, "t1", root_attrs={"status": 500})
    kept = rec.document()["kept"]
    assert [k["reason"] for k in kept] == ["error"]


def test_error_trigger_typed_error_attr():
    rec = _recorder()
    _request(rec, "t1", root_attrs={"error": "TimeoutError"})
    assert rec.document()["kept"][0]["reason"] == "error"


def test_shed_trigger_outranks_error():
    # a policy shed is flow control, not a failure — it must label the
    # exemplar "shed" even though it travels as a 503
    rec = _recorder()
    _request(rec, "t1", root_attrs={"shed": True, "status": 503})
    assert rec.document()["kept"][0]["reason"] == "shed"


def test_slo_trigger_respects_tier_budgets():
    rec = _recorder(slo_ms=100.0)
    # interactive (default tier): budget 100 ms -> 150 ms is anomalous
    _request(rec, "t1", root_ms=150.0)
    # batch: budget 4x -> the same 150 ms is fine, 450 ms is not
    _request(rec, "t2", root_ms=150.0, root_attrs={"tier": "batch"})
    _request(rec, "t3", root_ms=450.0, root_attrs={"tier": "batch"})
    kept = {k["trace_id"]: k for k in rec.document()["kept"]}
    assert "t1" in kept and kept["t1"]["reason"] == "slo"
    assert "t2" not in kept
    assert "t3" in kept and kept["t3"]["reason"] == "slo"


def test_slo_zero_is_inert():
    rec = _recorder(slo_ms=0.0, baseline=0)
    _request(rec, "t1", root_ms=10_000.0)
    assert rec.document()["kept"] == []


def test_autopilot_excess_trigger():
    rec = _recorder(excess_x=3.0)
    slow = _span("t1", span_id="d1", name="dispatch", kind="dispatch",
                 parent="rt1", dur=40.0,
                 attrs={"autopilot_predicted_ms": 10.0})
    _request(rec, "t1", child=slow)
    kept = rec.document()["kept"]
    assert kept and kept[0]["reason"] == "autopilot_excess"
    # within 3x of predicted: not anomalous
    rec2 = _recorder(excess_x=3.0, baseline=0)
    ok = _span("t2", span_id="d2", name="dispatch", kind="dispatch",
               parent="rt2", dur=25.0,
               attrs={"autopilot_predicted_ms": 10.0})
    _request(rec2, "t2", child=ok)
    assert rec2.document()["kept"] == []


def test_preemption_trigger_via_gen_seq_event():
    rec = _recorder()
    seq = _span("t1", span_id="g1", name="gen_sequence", kind="gen_seq",
                parent="rt1", dur=50.0,
                events=[{"name": "preempt", "ts": 1000.01}])
    _request(rec, "t1", child=seq)
    assert rec.document()["kept"][0]["reason"] == "preemption"


def test_breaker_trigger_via_span_event():
    rec = _recorder()
    hop = _span("t1", span_id="c1", name="engine", kind="client",
                parent="rt1", dur=5.0,
                events=[{"name": "breaker_open", "ts": 1000.0}])
    _request(rec, "t1", child=hop)
    assert rec.document()["kept"][0]["reason"] == "breaker"


def test_native_plane_batch_is_a_completable_unit():
    """The native C++ data plane never surfaces request boundaries to
    Python — its per-BATCH "plane" root span must still complete the
    trace, so a failed or over-SLO native dispatch is retained instead
    of TTL-rotting in the pending buffer forever."""
    rec = _recorder(slo_ms=50.0, baseline=0)
    # healthy batch: judged and dropped
    rec.offer(_span("b1", span_id="pb1", name="plane_batch", kind="plane",
                    dur=5.0))
    assert rec.completed_total == 1 and rec.document()["kept"] == []
    # dispatch blew up inside the batch: typed error on the plane root
    rec.offer(_span("b2", span_id="pb2", name="plane_batch", kind="plane",
                    dur=7.0, attrs={"status": 500,
                                    "error": "XlaRuntimeError"}))
    # cold-compile batch: over the SLO budget
    rec.offer(_span("b3", span_id="pb3", name="plane_batch", kind="plane",
                    dur=400.0))
    kept = {k["trace_id"]: k["reason"] for k in rec.document()["kept"]}
    assert kept == {"b2": "error", "b3": "slo"}


def test_note_rescues_an_already_dropped_trace():
    # the pending buffer is TTL-evicted, NOT cleared on a drop verdict,
    # exactly so a late failover signal can still rescue the trace
    rec = _recorder(baseline=0)
    _request(rec, "t1")
    assert rec.document()["kept"] == []
    rec.note("t1", "failover", lane="unary", recovered=True)
    kept = rec.document()["kept"]
    assert kept and kept[0]["reason"] == "failover"
    detail = rec.document(puid="p-t1")["postmortem"]
    assert detail["explain"]["notes"][0]["attrs"]["recovered"] is True


def test_traceless_note_becomes_bounded_synthetic_exemplar():
    rec = _recorder()
    for i in range(20):
        rec.note("", "lease", endpoint=f"http://e{i}",
                 transition="live->dead")
    doc = rec.document()
    assert doc["kept"] == []  # a lease flap must not evict real exemplars
    assert 0 < len(doc["synthetic"]) <= 8
    assert doc["synthetic"][0]["synthetic"] is True
    assert rec.kept_total["lease"] == 20


def test_reservoir_baseline_bounds():
    rec = _recorder(baseline=4)
    rec._rng = random.Random(7)
    for i in range(200):
        _request(rec, f"t{i}")
    doc = rec.document()
    assert len(doc["baseline"]) == 4  # full, never over
    assert all(b["reason"] == "baseline" for b in doc["baseline"])
    assert rec.completed_total == 200
    assert doc["kept"] == []  # healthy traffic never lands in kept


# -- explainer math --------------------------------------------------------


def _tree(tid, root_ms, disp_ms, attrs=None):
    root = _span(tid, span_id="r" + tid, dur=root_ms, attrs=attrs)
    disp = _span(tid, span_id="d" + tid, name="dispatch", kind="dispatch",
                 parent="r" + tid, start=1000.010, dur=disp_ms)
    return root, disp


def test_phase_excess_hand_computed():
    """3 healthy requests establish dispatch p50 = 40 ms / other = 60 ms;
    the outlier (90 ms dispatch inside a 160 ms root) must be blamed on
    dispatch with EXACTLY 90 - 40 = 50 ms of excess — the baselines fold
    AFTER judgement, so the outlier is measured against its
    predecessors, never softened by its own contribution."""
    rec = _recorder(slo_ms=120.0, baseline=0)
    for i in range(3):
        root, disp = _tree(f"t{i}", 100.0, 40.0)
        rec.offer(disp)
        rec.offer(root)
    root, disp = _tree("tx", 160.0, 90.0)
    rec.offer(disp)
    rec.offer(root)
    pm = rec.document(puid="p-tx")["postmortem"]
    assert pm["reasons"] == ["slo"]
    assert pm["phases"]["dispatch_ms"] == 90.0
    assert pm["phases"]["other_ms"] == 70.0
    ex = pm["explain"]
    assert ex["guilty_phase"] == "dispatch_ms"
    assert ex["excess_ms"] == 50.0
    assert ex["phase_excess_ms"]["dispatch_ms"] == 50.0
    assert ex["phase_excess_ms"]["other_ms"] == 10.0
    assert ex["baseline_p50_ms"]["dispatch_ms"] == 40.0
    assert ex["baseline_p50_ms"]["other_ms"] == 60.0


def test_fast_fail_names_biggest_phase():
    # an instant error beats every baseline — no positive excess, so the
    # explainer falls back to the biggest phase instead of None
    rec = _recorder()
    for i in range(2):
        root, disp = _tree(f"t{i}", 100.0, 40.0)
        rec.offer(disp)
        rec.offer(root)
    _request(rec, "te", root_ms=1.0, root_attrs={"status": 500})
    pm = rec.document(puid="p-te")["postmortem"]
    assert pm["reason"] == "error"
    assert pm["explain"]["guilty_phase"] == "other_ms"
    assert pm["explain"]["excess_ms"] <= 0.0


def test_explainer_carries_autopilot_p2c_and_gen_ledger():
    rec = _recorder()
    root = _span("t1", span_id="rt1", dur=90.0, attrs={
        "status": 500, "replica": "rep-2",
        "p2c_candidates": "rep-1,rep-2", "p2c_scores": "5.0,2.0",
    })
    disp = _span("t1", span_id="d1", name="dispatch", kind="dispatch",
                 parent="rt1", dur=80.0,
                 attrs={"autopilot_predicted_ms": 20.0})
    seq = _span("t1", span_id="g1", name="gen_sequence", kind="gen_seq",
                parent="rt1", dur=70.0,
                events=[{"name": "admitted", "ts": 1000.0}])
    rec.offer(disp)
    rec.offer(seq)
    rec.offer(root)
    ex = rec.document(puid="p-t1")["postmortem"]["explain"]
    assert ex["autopilot"] == [{
        "name": "dispatch", "kind": "dispatch",
        "predicted_ms": 20.0, "actual_ms": 80.0, "ratio": 4.0,
    }]
    assert ex["p2c"]["replica"] == "rep-2"
    assert ex["p2c"]["p2c_scores"] == "5.0,2.0"
    assert ex["gen_ledger"][0]["name"] == "gen_sequence"
    assert ex["gen_ledger"][0]["events"][0]["name"] == "admitted"


# -- bounds ----------------------------------------------------------------


def test_pending_buffer_bounded_lru():
    rec = _recorder(pending_traces=4)
    for i in range(10):
        rec.offer(_span(f"t{i}", span_id=f"q{i}", kind="queue",
                        name="batch_queue"))
    doc = rec.document()
    assert doc["pending"]["traces"] == 4
    assert rec.dropped_total == 6


def test_per_trace_span_cap_truncates_and_reports():
    rec = _recorder(pending_spans=3, slo_ms=1.0)
    for i in range(5):
        rec.offer(_span("t1", span_id=f"c{i}", kind="dispatch",
                        name="dispatch", parent="rt1"))
    _request(rec, "t1", root_ms=50.0)  # over the 1 ms budget -> kept
    pm = rec.document(puid="p-t1")["postmortem"]
    assert pm["pinned_spans"] == 3
    assert pm["truncated_spans"] == 3  # 2 extra children + the root
    assert pm["partial"] is True  # the assembler flags the missing root


def test_kept_ring_bounded_worst_first():
    rec = _recorder(keep=2)
    for i in range(5):
        _request(rec, f"t{i}", root_attrs={"status": 500})
    doc = rec.document()
    assert len(doc["kept"]) == 2
    assert sum(rec.kept_total.values()) == 5


# -- pin vs eviction (copy-out immutability) -------------------------------


def test_kept_document_survives_one_entry_ring_eviction():
    """Regression for kept-exemplar decay: with a 1-entry tracer ring,
    the spans of a kept trace are evicted from the ring almost
    immediately — the postmortem document must NOT degrade, because it
    copied the spans out at keep time."""
    tracer = Tracer(capacity=1, enabled=True, sample=1.0)
    rec = _recorder(slo_ms=1.0)
    tracer.pm_hook = rec.offer
    with tracer.span("p-slow", "engine", kind="request", method="predict",
                     status=500):
        with tracer.span("p-slow", "dispatch", kind="dispatch",
                         method="predict"):
            pass
    pm1 = rec.document(puid="p-slow")["postmortem"]
    assert pm1 is not None and pm1["pinned_spans"] == 2
    frozen = json.dumps(pm1, sort_keys=True, default=str)
    # churn the 1-entry ring until nothing of the original trace remains
    for i in range(10):
        with tracer.span(f"p-noise-{i}", "engine", kind="node"):
            pass
    assert tracer.snapshot()["spans"] == 1
    pm2 = rec.document(puid="p-slow")["postmortem"]
    assert json.dumps(pm2, sort_keys=True, default=str) == frozen
    assert pm2["partial"] is False


# -- the pm flags bit (head-sampling blind spot, end to end) ---------------


def test_traceparent_pm_bit_roundtrip():
    ctx = TraceContext(trace_id="ab" * 16, span_id="cd" * 8,
                       sampled=False, pm=True)
    with trace_scope(ctx):
        hdr = traceparent_header_value()
    assert hdr.endswith("-02")
    back = parse_traceparent(hdr)
    assert back.sampled is False and back.pm is True
    # sampled + pm -> 03; plain sampled stays exactly the old 01
    ctx2 = TraceContext(trace_id="ab" * 16, span_id="cd" * 8,
                        sampled=True, pm=True)
    with trace_scope(ctx2):
        assert traceparent_header_value().endswith("-03")
    ctx3 = TraceContext(trace_id="ab" * 16, span_id="cd" * 8, sampled=True)
    with trace_scope(ctx3):
        assert traceparent_header_value().endswith("-01")


def test_unsampled_root_feeds_child_process_pending_buffer():
    """The blind-spot fix end to end: a sampled-OUT root in the gateway
    process still produces a postmortem in the engine process — the pm
    bit rides the flags byte, the child records pm_only spans into its
    own pending buffer, and its ring stays empty."""
    gw_tracer = Tracer(enabled=True, sample=0.0)
    gw_rec = _recorder()
    gw_tracer.pm_hook = gw_rec.offer
    hdr = [None]
    with gw_tracer.span("p-x", "gateway", kind="request", method="predict",
                        status=500):
        hdr[0] = traceparent_header_value()
    assert hdr[0].endswith("-02")
    assert gw_rec.document()["kept"][0]["reason"] == "error"

    # engine process: adopts the remote context off the wire
    eng_tracer = Tracer(enabled=True, sample=1.0)
    eng_rec = _recorder()
    eng_tracer.pm_hook = eng_rec.offer
    with trace_scope(parse_traceparent(hdr[0])):
        with eng_tracer.span("p-x", "engine", kind="request",
                             method="predict", status=500):
            pass
    assert eng_tracer.snapshot()["spans"] == 0  # ring untouched
    assert eng_rec.document()["kept"][0]["reason"] == "error"


def test_old_peer_degrades_to_local_only():
    """A peer that predates the pm bit forwards flags 00 for an
    unsampled trace — the downstream process records nothing (local-only
    postmortems at the old peer's callers), and an old peer RECEIVING
    02 reads bit 0x01 only and stays silent too."""
    ctx = parse_traceparent("00-" + "ab" * 16 + "-" + "cd" * 8 + "-00")
    assert ctx.sampled is False and ctx.pm is False
    tracer = Tracer(enabled=True, sample=1.0)
    rec = _recorder()
    tracer.pm_hook = rec.offer
    with trace_scope(ctx):
        with tracer.span("p-x", "engine", kind="request"):
            pass
    assert rec.document()["kept"] == []
    assert rec.offer_total == 0
    # the 02 byte keeps bit 0x01 clear: an old peer parses it unsampled
    assert parse_traceparent(
        "00-" + "ab" * 16 + "-" + "cd" * 8 + "-02").sampled is False


# -- kill switches ---------------------------------------------------------


def test_killswitch_postmortem_env(monkeypatch):
    monkeypatch.setenv("SELDON_TPU_POSTMORTEM", "0")
    assert postmortem_enabled() is False
    rec = PostmortemRecorder()
    assert rec.enabled is False
    rec.offer(_span("t1", span_id="r1"))
    rec.note("", "lease")
    doc = rec.document()
    assert doc["enabled"] is False
    assert doc["kept"] == [] and doc["synthetic"] == []
    assert rec.offer_total == 0 and rec.noted_total == 0


def test_killswitch_no_hook_is_bit_for_bit():
    # pm_hook None (what SELDON_TPU_POSTMORTEM=0 leaves behind): a
    # sampled-out root records nothing anywhere and forwards plain 00
    tracer = Tracer(enabled=True, sample=0.0)
    flags = [None]
    with tracer.span("p-x", "engine", kind="request") as h:
        assert h is None  # the pre-postmortem unsampled null handle
        flags[0] = traceparent_header_value()
    assert flags[0].endswith("-00")
    assert tracer.snapshot()["spans"] == 0
    assert tracer.sampled_out_total == 1


def test_killswitch_tracer_disabled():
    tracer = Tracer(enabled=False)
    offered = []
    tracer.pm_hook = offered.append
    with tracer.span("p-x", "engine", kind="request"):
        pass
    assert offered == []


# -- federated merge -------------------------------------------------------


def _fleet_summary(name, excess):
    return {
        "enabled": True,
        "kept": [{
            "puid": f"p-{name}", "trace_id": f"t-{name}",
            "reason": "slo", "reasons": ["slo"], "duration_ms": 100.0,
            "guilty_phase": "dispatch_ms", "excess_ms": excess,
            "kept_at_s": 1.0, "pinned_spans": 2, "synthetic": False,
        }],
        "synthetic": [],
        "counters": {"completed": 10, "kept": {"slo": 1}, "dropped": 2,
                     "noted": 0, "offers": 20, "truncated_spans": 0},
    }


def test_federated_merge_worst_of_fleet(monkeypatch):
    from seldon_core_tpu.gateway import fleet

    POSTMORTEM.reset()
    try:
        # local exemplar with a modest excess
        root, disp = _tree("tl", 160.0, 90.0, attrs={"status": 500})
        POSTMORTEM.offer(disp)
        POSTMORTEM.offer(root)
        ep = SimpleNamespace(fleet_docs={
            "postmortems": _fleet_summary("remote", 500.0), "ts": 1.0})
        src = fleet.FleetSource(name="rep-1", set_name="d/p", lane="http",
                                base_url="http://x", endpoint=ep)
        monkeypatch.setattr(fleet, "gather_sources", lambda gw: [src])
        doc = asyncio.run(fleet.postmortems_document(object()))
    finally:
        POSTMORTEM.reset()
    assert doc["federated"] is True
    assert {s["source"] for s in doc["sources"]} == {"gateway", "rep-1"}
    # worst first: the remote 500 ms excess beats the local exemplar
    assert doc["kept"][0]["puid"] == "p-remote"
    assert doc["kept"][0]["source"] == "rep-1"
    assert any(k["source"] == "gateway" for k in doc["kept"])
    # counters sum across the fleet
    assert doc["counters"]["completed"] >= 11
    assert doc["counters"]["kept"]["slo"] >= 1


def test_federated_puid_chase_falls_through_to_replicas(monkeypatch):
    from seldon_core_tpu.gateway import fleet

    POSTMORTEM.reset()
    src = fleet.FleetSource(name="rep-2", set_name="d/p", lane="http",
                            base_url="http://x")
    monkeypatch.setattr(fleet, "gather_sources", lambda gw: [src])

    async def fake_fetch(gw, url):
        assert url == "http://x/postmortems?puid=p-far"
        return {"found": True, "puid": "p-far",
                "postmortem": {"puid": "p-far", "reason": "slo"}}

    monkeypatch.setattr(fleet, "_fetch_json", fake_fetch)
    doc = asyncio.run(fleet.postmortems_document(object(), puid="p-far"))
    assert doc["found"] is True and doc["source"] == "rep-2"
    assert doc["postmortem"]["reason"] == "slo"


def test_federated_killswitch_local_only(monkeypatch):
    from seldon_core_tpu.gateway import fleet

    monkeypatch.setenv("SELDON_TPU_FLEET", "0")
    POSTMORTEM.reset()
    try:
        monkeypatch.setattr(
            fleet, "gather_sources",
            lambda gw: (_ for _ in ()).throw(AssertionError("fanned out")))
        doc = asyncio.run(fleet.postmortems_document(object()))
    finally:
        POSTMORTEM.reset()
    assert doc["federated"] is False
    assert [s["source"] for s in doc["sources"]] == ["gateway"]


# -- metrics + evidence ----------------------------------------------------


def test_metric_mirrors_flow():
    from seldon_core_tpu.utils.telemetry import RECORDER

    before = RECORDER.snapshot()["postmortem"]
    RECORDER.record_postmortem_kept("slo")
    RECORDER.record_postmortem_dropped(3)
    RECORDER.set_postmortem_pinned(17)
    after = RECORDER.snapshot()["postmortem"]
    assert after["kept"].get("slo", 0) == before["kept"].get("slo", 0) + 1
    assert after["dropped"] == before["dropped"] + 3
    assert after["pinned_spans"] == 17


def test_exemplar_puids_prefer_deployment():
    rec = _recorder()
    _request(rec, "t1", root_attrs={"status": 500, "deployment": "dep-a"})
    _request(rec, "t2", root_attrs={"status": 500, "deployment": "dep-b"})
    _request(rec, "t3", root_attrs={"status": 500, "deployment": "dep-b"})
    assert rec.exemplar_puids(deployment="dep-a") == ["p-t1"]
    assert rec.exemplar_puids(deployment="dep-b") == ["p-t3", "p-t2"]
    # no match -> most recent anomalies, bounded
    assert rec.exemplar_puids(deployment="dep-z", limit=2) == \
        ["p-t3", "p-t2"]


def test_snapshot_axes_null_guarded():
    rec = _recorder()
    snap = rec.snapshot()
    assert snap["enabled"] is True
    assert snap["offer_p50_ms"] is None  # no offers measured yet
    off = PostmortemRecorder(enabled=False)
    assert off.snapshot()["enabled"] is False
