"""Deployment resource proto round-trip: JSON spec -> dataclasses -> proto
-> dataclasses -> JSON must be lossless (the control-plane analogue of the
data-plane codec fidelity tests)."""

from seldon_core_tpu.deployproto import deployment_from_proto, deployment_to_proto
from seldon_core_tpu.graph.spec import SeldonDeploymentSpec
from seldon_core_tpu.proto_gen import seldon_deployment_pb2 as pb

SPEC_JSON = {
    "apiVersion": "machinelearning.seldon.io/v1alpha2",
    "kind": "SeldonDeployment",
    "metadata": {"name": "mnist-canary", "labels": {"team": "serving"}},
    "spec": {
        "name": "mnist-canary",
        "oauth_key": "key1",
        "oauth_secret": "sec1",
        "annotations": {"project_name": "demo"},
        "predictors": [
            {
                "name": "main",
                "replicas": 3,
                "labels": {"version": "v1"},
                "graph": {
                    "name": "ab",
                    "type": "ROUTER",
                    "implementation": "RANDOM_ABTEST",
                    "parameters": [
                        {"name": "ratioA", "value": "0.8", "type": "FLOAT"}
                    ],
                    "children": [
                        {"name": "a", "type": "MODEL",
                         "endpoint": {"service_host": "h1",
                                      "service_port": 9000, "type": "GRPC"}},
                        {"name": "b", "type": "MODEL",
                         "methods": ["TRANSFORM_INPUT"]},
                    ],
                },
                "components": [
                    {"name": "a", "runtime": "inprocess",
                     "class_path": "MnistClassifier",
                     "mesh_axes": {"tp": 2, "sp": 2},
                     "parameters": [{"name": "hidden", "value": "64",
                                     "type": "INT"}]},
                    {"name": "b", "runtime": "grpc", "image": "img:1",
                     "host": "b-host", "port": 9001,
                     "env": {"FOO": "bar"}},
                ],
            }
        ],
    },
}


def test_roundtrip_spec_proto_spec():
    spec = SeldonDeploymentSpec.from_json_dict(SPEC_JSON)
    proto = deployment_to_proto(spec)
    back = deployment_from_proto(proto)
    assert back.to_json_dict() == spec.to_json_dict()


def test_proto_wire_roundtrip():
    spec = SeldonDeploymentSpec.from_json_dict(SPEC_JSON)
    wire = deployment_to_proto(spec).SerializeToString()
    parsed = pb.SeldonDeployment.FromString(wire)
    back = deployment_from_proto(parsed)
    assert back.predictor("main").graph.find("a").endpoint.service_port == 9000
    assert back.predictor("main").component_map()["a"].mesh_axes == {
        "tp": 2, "sp": 2,
    }
    assert back.oauth_key == "key1"
    # typed parameter survives with its type tag
    ps = back.predictor("main").graph.parameters
    assert ps[0].typed_value() == 0.8


def test_enum_name_parity_with_spec_enums():
    """Every spec enum value must exist in the proto (schema drift guard)."""
    from seldon_core_tpu.graph.spec import (
        UnitImplementation,
        UnitMethod,
        UnitType,
    )

    for t in UnitType:
        assert pb.PredictiveUnit.PredictiveUnitType.Value(t.value) is not None
    for i in UnitImplementation:
        assert (
            pb.PredictiveUnit.PredictiveUnitImplementation.Value(i.value)
            is not None
        )
    for m in UnitMethod:
        assert pb.PredictiveUnit.PredictiveUnitMethod.Value(m.value) is not None


def test_status_message_shape():
    st = pb.DeploymentStatus(state="Available")
    st.predictor_status.add(name="main", status="Available", replicas=3,
                            replicas_available=3)
    parsed = pb.DeploymentStatus.FromString(st.SerializeToString())
    assert parsed.state == "Available"
    assert parsed.predictor_status[0].replicas_available == 3
