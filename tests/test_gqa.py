"""Grouped-query attention: cache shapes shrink by the group factor, the
GQA formulation matches head-repeated MHA numerics exactly, generation and
training run end-to-end, and invalid head configs fail at config time."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from seldon_core_tpu.models.generate import (
    TransformerGenerator,
    generate,
    init_cache,
)
from seldon_core_tpu.models.transformer import (
    LMConfig,
    gqa_attention,
    lm_apply,
    lm_init,
    lm_train_step,
)


def test_gqa_matches_repeated_mha_numerics():
    """gqa_attention == plain attention with K/V heads explicitly
    repeated — the formulation only changes the dataflow, not the math."""
    rng = np.random.default_rng(0)
    B, H, KV, S, hd = 2, 8, 2, 16, 32
    q = jnp.asarray(rng.normal(size=(B, H, S, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, KV, S, hd)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, KV, S, hd)), jnp.float32)
    got = np.asarray(gqa_attention(q, k, v, causal=True))

    krep = jnp.repeat(k, H // KV, axis=1)
    vrep = jnp.repeat(v, H // KV, axis=1)
    scale = 1.0 / np.sqrt(hd)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, krep) * scale
    qpos = jnp.arange(S)[:, None]
    kpos = jnp.arange(S)[None, :]
    s = jnp.where(qpos >= kpos, s, -1e30)
    want = np.asarray(jnp.einsum(
        "bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), vrep
    ))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_gqa_cache_shrinks_by_group_factor():
    cfg_mha = LMConfig(vocab=64, d_model=64, n_heads=8, n_layers=2, d_ff=128)
    cfg_gqa = LMConfig(vocab=64, d_model=64, n_heads=8, n_layers=2, d_ff=128,
                       n_kv_heads=2)
    c_mha = init_cache(cfg_mha, batch=4, max_len=32)
    c_gqa = init_cache(cfg_gqa, batch=4, max_len=32)
    assert c_mha["l0"]["k"].shape == (4, 8, 32, 8)
    assert c_gqa["l0"]["k"].shape == (4, 2, 32, 8)
    # wqkv output shrinks too: q (64) + k/v (2 heads x 8 dim each)
    p = lm_init(jax.random.key(0), cfg_gqa)
    assert p["l0"]["wqkv"].shape == (64, 64 + 2 * 2 * 8)


def test_gqa_generate_prefill_decode_consistency():
    """generate() (prefill + cached decode scan) must agree with teacher
    forcing through lm_apply: greedy tokens re-fed through the full forward
    reproduce the same argmax chain."""
    cfg = LMConfig(vocab=64, d_model=64, n_heads=8, n_layers=2, d_ff=128,
                   n_kv_heads=2, dtype=jnp.float32)
    params = lm_init(jax.random.key(0), cfg)
    prompt = jnp.asarray(
        np.random.default_rng(1).integers(0, 64, size=(2, 8)), jnp.int32
    )
    toks = np.asarray(generate(params, prompt, cfg, max_new_tokens=6))
    full = np.asarray(prompt)
    for i in range(6):
        logits = np.asarray(lm_apply(params, jnp.asarray(full), cfg))
        nxt = logits[:, -1, :].argmax(-1)
        np.testing.assert_array_equal(nxt, toks[:, i])
        full = np.concatenate([full, nxt[:, None].astype(np.int32)], axis=1)


def test_gqa_unit_serves_and_int8_composes():
    gen = TransformerGenerator(vocab=64, d_model=64, n_heads=8, n_kv_heads=2,
                               n_layers=2, d_ff=128, max_new_tokens=8,
                               dtype="float32", quant="int8")
    state = gen.init_state(jax.random.key(0))
    y = np.asarray(gen.predict(state, jnp.zeros((2, 4), jnp.float32)))
    assert y.shape == (2, 8)
    assert ((y >= 0) & (y < 64)).all()


def test_gqa_trains():
    import optax

    cfg = LMConfig(vocab=64, d_model=64, n_heads=8, n_layers=2, d_ff=128,
                   n_kv_heads=4, dtype=jnp.float32)
    params = lm_init(jax.random.key(0), cfg)
    opt = optax.adam(1e-3)
    batch = {"tokens": jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(4, 17)), jnp.int32
    )}
    params, _, loss = lm_train_step(
        params, opt.init(params), batch, opt, cfg, use_flash=False
    )
    assert np.isfinite(float(loss))


def test_gqa_invalid_heads_rejected():
    with pytest.raises(ValueError, match="not divisible"):
        LMConfig(n_heads=4, n_kv_heads=3)


def test_rope_properties():
    """RoPE: norm-preserving rotation; relative-position invariance of
    attention scores (the property that makes position-relative behavior
    learnable); disabled via cfg.rope=False."""
    from seldon_core_tpu.models.transformer import LMConfig, apply_rope

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(1, 2, 8, 16)), jnp.float32)
    pos = jnp.arange(8)
    r = apply_rope(x, pos)
    # rotation preserves per-vector norm
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(r), axis=-1),
        np.linalg.norm(np.asarray(x), axis=-1), rtol=1e-5,
    )
    # score depends only on RELATIVE offset: <R(p)q, R(p+d)k> equal for
    # all p at fixed d
    q = jnp.asarray(rng.normal(size=(1, 1, 1, 16)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 1, 1, 16)), jnp.float32)
    scores = []
    for p in (0, 3, 11):
        qr = apply_rope(q, jnp.asarray([p]))
        kr = apply_rope(k, jnp.asarray([p + 5]))
        scores.append(float(np.asarray(qr * kr).sum()))
    np.testing.assert_allclose(scores, scores[0], rtol=1e-4)
    # odd head dim rejected at config time when rope is on
    with pytest.raises(ValueError, match="even head dim"):
        LMConfig(d_model=12, n_heads=4)  # hd=3
    LMConfig(d_model=12, n_heads=4, rope=False)  # fine without rope


def test_rope_matmul_form_equals_concat_form():
    """apply_rope computes the rotate-half as x @ [[0,I],[-I,0]] (the
    concat form lowered to unfusable lane-pad fusions on TPU — round-5
    profile); the signed-permutation matmul must reproduce the textbook
    [x1*cos - x2*sin, x2*cos + x1*sin] EXACTLY in f32, and accept
    per-row [B, S] position arrays."""
    from seldon_core_tpu.models.transformer import apply_rope

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(2, 3, 5, 16)), jnp.float32)
    base = 10000.0

    def concat_form(x, positions):
        hd = x.shape[-1]
        half = hd // 2
        freqs = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
        ang = positions.astype(jnp.float32)[..., None] * freqs
        cos = (jnp.cos(ang)[None, None] if ang.ndim == 2
               else jnp.cos(ang)[:, None])
        sin = (jnp.sin(ang)[None, None] if ang.ndim == 2
               else jnp.sin(ang)[:, None])
        x1 = x[..., :half]
        x2 = x[..., half:]
        return jnp.concatenate(
            [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)

    pos = jnp.arange(5) + 7
    np.testing.assert_allclose(
        np.asarray(apply_rope(x, pos)),
        np.asarray(concat_form(x, pos)), rtol=0, atol=1e-6)
    # per-row positions (batched speculative decoding)
    pos2 = jnp.asarray(rng.integers(0, 100, size=(2, 5)), jnp.int32)
    np.testing.assert_allclose(
        np.asarray(apply_rope(x, pos2)),
        np.asarray(concat_form(x, pos2)), rtol=0, atol=1e-6)


def test_weights_path_roundtrip_and_validation(tmp_path):
    """save_lm_weights -> weights_path serves the EXACT checkpoint;
    wrong-architecture or state-format checkpoints fail at load time."""
    from seldon_core_tpu.models.generate import TransformerGenerator
    from seldon_core_tpu.models.transformer import (
        LMConfig, lm_init, load_lm_weights, save_lm_weights,
    )

    cfg = LMConfig(vocab=64, d_model=64, n_heads=4, n_layers=2, d_ff=128,
                   dtype=jnp.float32)
    params = lm_init(jax.random.key(7), cfg)
    path = str(tmp_path / "w.npz")
    save_lm_weights(params, path)

    gen = TransformerGenerator(vocab=64, d_model=64, n_heads=4, n_layers=2,
                               d_ff=128, max_new_tokens=4, dtype="float32",
                               weights_path=path, seed=123)
    state = gen.init_state(jax.random.key(99))
    np.testing.assert_array_equal(
        np.asarray(state["params"]["l0"]["wqkv"]),
        np.asarray(params["l0"]["wqkv"]),
    )

    # missing file
    with pytest.raises(FileNotFoundError):
        load_lm_weights(params, str(tmp_path / "nope.npz"))
    # layer-count mismatch -> missing leaves
    big = lm_init(jax.random.key(0), LMConfig(
        vocab=64, d_model=64, n_heads=4, n_layers=4, d_ff=128,
        dtype=jnp.float32))
    with pytest.raises(ValueError, match="missing leaves"):
        load_lm_weights(big, path)
    # shape mismatch (different d_ff)
    wide = lm_init(jax.random.key(0), LMConfig(
        vocab=64, d_model=64, n_heads=4, n_layers=2, d_ff=256,
        dtype=jnp.float32))
    with pytest.raises(ValueError, match="shape mismatch"):
        load_lm_weights(wide, path)
