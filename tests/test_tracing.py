"""Tracing subsystem: puid-correlated spans across the graph, admin API.

The reference's only per-request observability is hop-latency logs keyed by
puid (engine InternalPredictionService.java:267-268, PredictionService.java:
52-58); here the same correlation id drives a real span store.
"""

import asyncio
import json

import numpy as np
import pytest

from seldon_core_tpu.graph.spec import SeldonDeploymentSpec
from seldon_core_tpu.messages import Feedback, SeldonMessage
from seldon_core_tpu.runtime.engine import EngineService
from seldon_core_tpu.utils.tracing import TRACER, Span, Tracer


def deployment(graph, components=None):
    return SeldonDeploymentSpec.from_json_dict(
        {
            "spec": {
                "name": "d",
                "predictors": [
                    {"name": "p", "graph": graph, "components": components or []}
                ],
            }
        }
    )


@pytest.fixture(autouse=True)
def _clean_tracer():
    TRACER.clear()
    TRACER.disable()
    yield
    TRACER.clear()
    TRACER.disable()


def test_tracer_disabled_is_nullcontext():
    t = Tracer(enabled=False)
    with t.span("p1", "n1") as sp:
        assert sp is None  # shared null context, no span recorded
    assert t.recent() == []


def test_tracer_records_and_queries_by_puid():
    t = Tracer(enabled=True)
    with t.span("p1", "node_a", method="predict") as sp:
        sp["rows"] = 4
    with t.span("p2", "node_b", method="route"):
        pass
    with t.span("p1", "node_c", method="aggregate"):
        pass
    spans = t.trace("p1")
    assert [s.name for s in spans] == ["node_a", "node_c"]
    assert spans[0].attrs == {"rows": 4}
    assert spans[0].duration_ms >= 0
    assert len(t.recent()) == 3
    d = spans[0].to_json_dict()
    json.dumps(d)  # JSON-safe


def test_tracer_capacity_bounded():
    t = Tracer(capacity=10, enabled=True)
    for i in range(50):
        t.add(Span(puid=f"p{i}", name="n", kind="node", method="m",
                   start_s=float(i), duration_ms=1.0))
    assert len(t.recent(1000)) == 10


def test_host_graph_records_node_spans():
    """Host-mode execution: one span per node method, all sharing the puid."""
    spec = deployment(
        {
            "name": "r",
            "implementation": "RANDOM_ABTEST",
            "type": "ROUTER",
            "parameters": [{"name": "ratioA", "value": "0.5", "type": "FLOAT"}],
            "children": [
                {"name": "a", "implementation": "SIMPLE_MODEL", "type": "MODEL"},
                {"name": "b", "implementation": "SIMPLE_MODEL", "type": "MODEL"},
            ],
        }
    )

    async def run():
        TRACER.enable()
        engine = EngineService(spec, force_host=True)
        msg = SeldonMessage.from_array(np.ones((1, 3), np.float32))
        resp = await engine.predict(msg)
        spans = TRACER.trace(resp.meta.puid)
        kinds = {(s.name, s.method) for s in spans}
        assert ("request", "predict") in kinds
        assert ("r", "route") in kinds
        # exactly one branch served
        assert (("a", "predict") in kinds) != (("b", "predict") in kinds)
        route_span = next(s for s in spans if s.method == "route")
        assert route_span.attrs.get("branch") in (0, 1)

    asyncio.run(run())


def test_compiled_engine_records_request_and_dispatch_spans():
    spec = deployment(
        {"name": "m", "implementation": "SIMPLE_MODEL", "type": "MODEL"}
    )

    async def run():
        TRACER.enable()
        engine = EngineService(spec)
        assert engine.mode == "compiled"
        msg = SeldonMessage.from_array(np.ones((2, 3), np.float32))
        resp = await engine.predict(msg)
        spans = TRACER.trace(resp.meta.puid)
        assert any(s.kind == "request" for s in spans)
        # batched dispatch spans are per-stack (no puid)
        dispatches = [s for s in TRACER.recent(100) if s.kind == "dispatch"]
        assert dispatches and dispatches[0].attrs.get("rows") >= 1

    asyncio.run(run())


def test_feedback_span_uses_response_puid():
    spec = deployment(
        {"name": "m", "implementation": "SIMPLE_MODEL", "type": "MODEL"}
    )

    async def run():
        TRACER.enable()
        engine = EngineService(spec)
        fb = Feedback(
            response=SeldonMessage.from_json(
                json.dumps({"meta": {"puid": "fbpuid"}})
            ),
            reward=1.0,
        )
        await engine.send_feedback(fb)
        spans = TRACER.trace("fbpuid")
        assert any(s.method == "feedback" for s in spans)

    asyncio.run(run())


def test_rest_trace_endpoints():
    from seldon_core_tpu.runtime.rest import make_engine_app

    spec = deployment(
        {"name": "m", "implementation": "SIMPLE_MODEL", "type": "MODEL"}
    )

    async def run():
        from aiohttp.test_utils import TestClient, TestServer

        engine = EngineService(spec)
        app = make_engine_app(engine)
        async with TestClient(TestServer(app)) as client:
            r = await client.post("/trace/enable")
            assert r.status == 200
            body = json.dumps({"meta": {"puid": "restpuid"},
                               "data": {"ndarray": [[1.0, 2.0, 3.0]]}})
            r = await client.post("/api/v0.1/predictions", data=body,
                                  headers={"Content-Type": "application/json"})
            assert r.status == 200
            r = await client.get("/trace", params={"puid": "restpuid"})
            doc = await r.json()
            assert doc["enabled"] is True
            assert any(s["puid"] == "restpuid" for s in doc["spans"])
            r = await client.post("/trace/disable")
            assert r.status == 200

    asyncio.run(run())
