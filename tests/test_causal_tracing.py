"""Causal distributed tracing: span trees, W3C propagation, sampling,
critical path, export.

The acceptance scenario (ISSUE 3): a request through a COMBINER graph
with one injected transient failure yields ONE trace tree containing the
root request span, per-node child spans, a retry-attempt event with
backoff + ``deadline_remaining_ms``, a batching queue-wait span, and a
critical path whose summed durations are within 10% of the root span
duration; ``/trace/export`` validates as Chrome trace-event JSON.
"""

import asyncio
import json

import numpy as np
import pytest

from seldon_core_tpu.graph.spec import ComponentBinding, SeldonDeploymentSpec
from seldon_core_tpu.messages import Feedback, SeldonMessage
from seldon_core_tpu.runtime.engine import EngineService
from seldon_core_tpu.utils.tracing import (
    TRACER,
    TraceContext,
    Tracer,
    chrome_trace,
    critical_path,
    current_trace_context,
    export_document,
    parse_traceparent,
    trace_document,
    trace_scope,
    traceparent_header_value,
)


def deployment(graph, components=None):
    return SeldonDeploymentSpec.from_json_dict(
        {
            "spec": {
                "name": "d",
                "predictors": [
                    {"name": "p", "graph": graph, "components": components or []}
                ],
            }
        }
    )


@pytest.fixture(autouse=True)
def _clean_tracer():
    TRACER.clear()
    TRACER.disable()
    TRACER.sample = 1.0
    yield
    TRACER.clear()
    TRACER.disable()
    TRACER.sample = 1.0


# ---------------------------------------------------------------------------
# Core tracer semantics
# ---------------------------------------------------------------------------


def test_nested_spans_build_a_tree():
    t = Tracer(enabled=True)
    with t.span("p1", "outer", kind="request", method="predict"):
        with t.span("p1", "middle", method="predict"):
            with t.span("p1", "leaf", kind="client", method="predict"):
                pass
        with t.span("p1", "sibling", method="route"):
            pass
    spans = {s.name: s for s in t.trace("p1")}
    assert len(spans) == 4
    outer = spans["outer"]
    assert outer.parent_span_id == ""
    assert {s.trace_id for s in spans.values()} == {outer.trace_id}
    assert spans["middle"].parent_span_id == outer.span_id
    assert spans["leaf"].parent_span_id == spans["middle"].span_id
    assert spans["sibling"].parent_span_id == outer.span_id
    # by_trace returns the same set, via the trace_id index
    assert len(t.by_trace(outer.trace_id)) == 4


def test_traceparent_roundtrip_and_malformed():
    t = Tracer(enabled=True)
    with t.span("p1", "root"):
        hdr = traceparent_header_value()
        ctx = current_trace_context()
        assert hdr == f"00-{ctx.trace_id}-{ctx.span_id}-01"
        parsed = parse_traceparent(hdr)
        assert parsed.trace_id == ctx.trace_id
        assert parsed.span_id == ctx.span_id
        assert parsed.sampled is True
    assert traceparent_header_value() is None  # no active trace
    for bad in (None, "", "garbage", "00-short-span-01",
                "00-" + "0" * 32 + "-" + "1" * 16 + "-01",  # zero trace id
                "ff-" + "1" * 32 + "-" + "1" * 16 + "-01",  # forbidden version
                "00-" + "x" * 32 + "-" + "1" * 16 + "-01"):  # non-hex
        assert parse_traceparent(bad) is None
    off = parse_traceparent("00-" + "a" * 32 + "-" + "b" * 16 + "-00")
    assert off is not None and off.sampled is False


def test_remote_context_adoption_parents_next_span():
    t = Tracer(enabled=True)
    remote = TraceContext(trace_id="c" * 32, span_id="d" * 16, puid="pX")
    with trace_scope(remote):
        with t.span("", "local", kind="server"):
            pass
    (span,) = t.by_trace("c" * 32)
    assert span.parent_span_id == "d" * 16
    assert span.puid == "pX"  # inherited from the adopted context


def test_head_sampling_zero_records_nothing_including_children():
    t = Tracer(enabled=True, sample=0.0)
    with t.span("p1", "root", kind="request") as sp:
        assert sp is None
        ctx = current_trace_context()
        assert ctx is not None and ctx.sampled is False
        with t.span("p1", "child"):
            pass
        # the decision also rides the wire: a remote hop adopting this
        # context records nothing either
        hdr = traceparent_header_value()
        assert hdr is not None and hdr.endswith("-00")
        with trace_scope(parse_traceparent(hdr)):
            with t.span("p1", "remote-side"):
                pass
    assert t.recent(100) == []
    assert t.sampled_out_total == 1


def test_events_attach_to_active_span():
    t = Tracer(enabled=True)
    assert t.event("orphan") is False  # no active span
    with t.span("p1", "call", kind="client"):
        assert t.event("retry", attempt=1, backoff_ms=5.0) is True
    (span,) = t.trace("p1")
    assert span.events[0]["name"] == "retry"
    assert span.events[0]["attrs"]["backoff_ms"] == 5.0
    json.dumps(span.to_json_dict())  # events stay JSON-safe


def test_index_stays_correct_across_eviction():
    t = Tracer(capacity=10, enabled=True)
    for i in range(50):
        with t.span(f"p{i % 4}", "n"):
            pass
    assert len(t.recent(1000)) == 10
    # index agrees with the ring exactly (no stale evicted entries)
    by_scan = {}
    for s in t.recent(1000):
        by_scan.setdefault(s.puid, []).append(s)
    for puid in ("p0", "p1", "p2", "p3"):
        assert t.trace(puid) == sorted(
            by_scan.get(puid, []), key=lambda s: s.start_s
        )
    # internal: no index key holds more than the ring can
    assert sum(len(v) for v in t._by_puid.values()) == 10
    assert sum(len(v) for v in t._by_trace.values()) == 10


def test_critical_path_sums_to_root_and_clips_children():
    t = Tracer(enabled=True)
    t0 = 1000.0
    root = _span(t, "root", "request", t0, 100.0)
    a = _span(t, "a", "node", t0 + 0.010, 30.0, parent=root)
    _span(t, "a-call", "client", t0 + 0.015, 20.0, parent=a)
    _span(t, "b", "node", t0 + 0.050, 45.0, parent=root)
    spans = t.by_trace(root.trace_id)
    r, segments = critical_path(spans)
    assert r.span_id == root.span_id
    total = sum(ms for _, ms in segments)
    assert total == pytest.approx(100.0, rel=1e-6)
    names_on_path = {sp.name for sp, _ in segments}
    assert "b" in names_on_path  # latest-ending child gates the root


def _span(t, name, kind, start_s, duration_ms, parent=None):
    from seldon_core_tpu.utils.tracing import Span, new_span_id, new_trace_id

    s = Span(
        puid="pc", name=name, kind=kind, method="m",
        start_s=start_s, duration_ms=duration_ms,
        trace_id=parent.trace_id if parent else new_trace_id(),
        span_id=new_span_id(),
        parent_span_id=parent.span_id if parent else "",
    )
    t.add(s)
    return s


def test_critical_path_clips_skewed_child_to_parent_window():
    """A child whose clock-skewed start precedes its parent's must not
    leak time outside the root duration — segments still sum exactly."""
    t = Tracer(enabled=True)
    t0 = 1000.0
    root = _span(t, "root", "request", t0, 100.0)
    # starts 50ms BEFORE the root (cross-host skew), ends inside it
    _span(t, "skewed", "client", t0 - 0.050, 90.0, parent=root)
    _, segments = critical_path(t.by_trace(root.trace_id))
    total = sum(ms for _, ms in segments)
    assert total == pytest.approx(100.0, rel=1e-6)


def test_chrome_trace_export_shape():
    t = Tracer(enabled=True)
    with t.span("p1", "root", kind="request"):
        t.event("retry", attempt=1)
    doc = chrome_trace(t.trace("p1"))
    json.loads(json.dumps(doc))  # serializable
    events = doc["traceEvents"]
    assert any(e["ph"] == "X" for e in events)
    assert any(e["ph"] == "i" and e["name"] == "retry" for e in events)
    assert any(e["ph"] == "M" for e in events)  # lane names
    for e in events:
        assert "name" in e and "ph" in e and "pid" in e
        if e["ph"] == "X":
            assert e["dur"] >= 0 and e["ts"] >= 0


# ---------------------------------------------------------------------------
# Engine integration: queue-wait spans, audit trace ids
# ---------------------------------------------------------------------------


def test_queue_wait_span_parented_under_request():
    spec = deployment(
        {"name": "m", "implementation": "SIMPLE_MODEL", "type": "MODEL"}
    )

    async def run():
        TRACER.enable()
        engine = EngineService(spec)
        assert engine.batcher is not None
        msg = SeldonMessage.from_array(np.ones((2, 3), np.float64))
        resp = await engine.predict(msg)
        spans = TRACER.trace(resp.meta.puid)
        request = next(s for s in spans if s.kind == "request")
        queue = next(s for s in spans if s.kind == "queue")
        assert queue.trace_id == request.trace_id
        assert queue.parent_span_id == request.span_id
        assert queue.attrs["rows"] == 2
        # the stacked flush span exists but stands alone (multi-request)
        assert any(s.kind == "batch" for s in TRACER.recent(200))

    asyncio.run(run())


def test_audit_records_carry_trace_id():
    from seldon_core_tpu.utils.telemetry import AuditLog

    spec = deployment(
        {"name": "m", "implementation": "SIMPLE_MODEL", "type": "MODEL"}
    )
    events = []

    async def run():
        TRACER.enable()
        engine = EngineService(spec, audit=AuditLog(sink=events.append))
        msg = SeldonMessage.from_array(np.ones((1, 3), np.float64))
        resp = await engine.predict(msg)
        await engine.audit.flush()
        return resp

    resp = asyncio.run(run())
    assert events, "audit sink saw no events"
    (ev,) = [e for e in events if e["puid"] == resp.meta.puid]
    spans = TRACER.trace(resp.meta.puid)
    assert ev["trace_id"] == spans[0].trace_id


# ---------------------------------------------------------------------------
# Cross-process propagation + acceptance scenario
# ---------------------------------------------------------------------------


def _flaky(times: int):
    """aiohttp middleware: first ``times`` /predict calls answer a
    retryable 503 — the injected transient failure."""
    from aiohttp import web

    left = {"n": times}

    @web.middleware
    async def mw(request, handler):
        if request.path == "/predict" and left["n"] > 0:
            left["n"] -= 1
            return web.Response(status=503, text="injected transient")
        return await handler(request)

    return mw


def test_combiner_trace_acceptance_rest():
    """The ISSUE 3 acceptance criterion, REST lane: host-mode COMBINER
    over two remote engines (served as MODEL leaves via /predict), one
    transient 503 injected, under a request deadline."""
    from aiohttp.test_utils import TestServer

    from seldon_core_tpu.runtime.client import RestNodeRuntime
    from seldon_core_tpu.runtime.resilience import deadline_scope
    from seldon_core_tpu.runtime.rest import make_engine_app

    leaf_spec = deployment(
        {"name": "m", "implementation": "SIMPLE_MODEL", "type": "MODEL"}
    )
    outer_spec = deployment(
        {
            "name": "ens",
            "implementation": "AVERAGE_COMBINER",
            "type": "COMBINER",
            "children": [
                {"name": "a", "type": "MODEL"},
                {"name": "b", "type": "MODEL"},
            ],
        }
    )

    async def run():
        TRACER.enable()
        inner_a = EngineService(leaf_spec)
        inner_b = EngineService(leaf_spec)
        assert inner_a.batcher is not None  # queue-wait spans exist
        app_a = make_engine_app(inner_a)
        app_a.middlewares.append(_flaky(1))
        srv_a, srv_b = TestServer(app_a), TestServer(make_engine_app(inner_b))
        await srv_a.start_server()
        await srv_b.start_server()
        try:
            nodes = {
                n.name: n
                for n in outer_spec.predictor("p").graph.walk()
            }
            outer = EngineService(
                outer_spec,
                force_host=True,
                extra_runtimes={
                    "a": RestNodeRuntime(
                        nodes["a"],
                        ComponentBinding(name="a", runtime="rest",
                                         host="127.0.0.1", port=srv_a.port),
                    ),
                    "b": RestNodeRuntime(
                        nodes["b"],
                        ComponentBinding(name="b", runtime="rest",
                                         host="127.0.0.1", port=srv_b.port),
                    ),
                },
            )
            msg = SeldonMessage.from_array(np.ones((1, 3), np.float64))
            msg.meta.puid = "acceptance-puid"
            with deadline_scope(10.0):
                resp = await outer.predict(msg)
            assert resp.status is None or resp.status.status == "SUCCESS"
            await outer.close()
        finally:
            await srv_a.close()
            await srv_b.close()

    asyncio.run(run())

    doc = trace_document(TRACER, puid="acceptance-puid")
    spans = doc["spans"]
    # ONE trace id across the outer engine, both node clients, and both
    # inner engines' request + queue spans
    trace_ids = {s["trace_id"] for s in spans if s.get("trace_id")}
    assert len(trace_ids) == 1, f"expected one trace, got {trace_ids}"
    kinds = {(s["kind"], s["name"]) for s in spans}
    assert ("request", "request") in kinds          # root + inner engines
    assert ("client", "a") in kinds and ("client", "b") in kinds
    assert ("queue", "batch_queue") in kinds        # micro-batch wait
    # the injected 503 shows up as a retry event with backoff and the
    # remaining deadline budget
    retry_events = [
        e
        for s in spans
        for e in s.get("events", [])
        if e["name"] == "retry"
    ]
    assert retry_events, "no retry event recorded for the injected 503"
    attrs = retry_events[0]["attrs"]
    assert attrs["backoff_ms"] >= 0
    assert attrs["deadline_remaining_ms"] > 0
    # assembled tree: a single root whose subtree covers the client hops
    roots = doc["tree"]
    root_nodes = [r for r in roots if r["kind"] == "request"]
    assert root_nodes, "no request root in the assembled tree"
    # critical path accounts for the root's duration within 10%
    total = sum(seg["self_ms"] for seg in doc["critical_path"])
    assert total == pytest.approx(doc["root_duration_ms"], rel=0.10)
    # per-phase decomposition covers the same wall clock
    assert doc["phases"]["total_ms"] == pytest.approx(total, abs=0.05)
    assert doc["phases"]["network_ms"] > 0
    # export validates as Chrome trace-event JSON
    export = export_document(TRACER, puid="acceptance-puid")
    parsed = json.loads(json.dumps(export))
    assert isinstance(parsed["traceEvents"], list) and parsed["traceEvents"]
    for e in parsed["traceEvents"]:
        assert {"name", "ph", "pid", "tid"} <= set(e)
        if e["ph"] == "X":
            assert "ts" in e and "dur" in e


def test_grpc_lane_propagates_trace_context():
    """Engine-as-MODEL-leaf over gRPC: the client span and the remote
    engine's request/queue spans share one trace id via traceparent
    metadata."""
    grpc = pytest.importorskip("grpc")  # noqa: F841

    from seldon_core_tpu.runtime.client import GrpcNodeRuntime
    from seldon_core_tpu.runtime.grpc_server import make_engine_grpc_server

    leaf_spec = deployment(
        {"name": "m", "implementation": "SIMPLE_MODEL", "type": "MODEL"}
    )

    async def run():
        TRACER.enable()
        inner = EngineService(leaf_spec)
        server = make_engine_grpc_server(inner, "127.0.0.1", 0)
        port = server.add_insecure_port("127.0.0.1:0")
        await server.start()
        try:
            node = leaf_spec.predictor("p").graph
            rt = GrpcNodeRuntime(
                node,
                ComponentBinding(name="m", runtime="grpc",
                                 host="127.0.0.1", port=port),
            )
            msg = SeldonMessage.from_array(np.ones((1, 3), np.float64))
            msg.meta.puid = "grpc-trace-puid"
            with TRACER.span("grpc-trace-puid", "caller", kind="request",
                             method="predict"):
                resp = await rt.predict(msg)
            assert resp.status is None or resp.status.status == "SUCCESS"
            await rt.close()
        finally:
            await server.stop(None)

    asyncio.run(run())
    spans = TRACER.trace("grpc-trace-puid")
    trace_ids = {s.trace_id for s in spans if s.trace_id}
    assert len(trace_ids) == 1
    kinds = {s.kind for s in spans}
    assert "client" in kinds, "gRPC client span missing (REST parity)"
    assert "request" in kinds
    client = next(s for s in spans if s.kind == "client")
    remote_request = next(
        s for s in spans if s.kind == "request" and s.name == "request"
    )
    assert remote_request.parent_span_id == client.span_id


def test_client_feedback_and_aggregate_puid_correlation():
    """Satellite: feedback spans fall back to the request's puid when the
    response is absent; aggregate uses the active trace context instead
    of guessing from msgs[0]."""
    from aiohttp.test_utils import TestServer

    from seldon_core_tpu.runtime.client import RestNodeRuntime
    from seldon_core_tpu.runtime.microservice import build_runtime
    from seldon_core_tpu.runtime.rest import make_unit_app

    async def run():
        TRACER.enable()
        runtime = build_runtime("AVERAGE_COMBINER", "COMBINER", unit_name="u")
        srv = TestServer(make_unit_app(runtime))
        await srv.start_server()
        try:
            node = runtime.node
            rt = RestNodeRuntime(
                node,
                ComponentBinding(name="u", runtime="rest",
                                 host="127.0.0.1", port=srv.port),
            )
            # feedback with NO response message but a request puid
            req = SeldonMessage.from_array(np.ones((1, 2), np.float64))
            req.meta.puid = "fb-req-puid"
            await rt.send_feedback(Feedback(request=req, reward=1.0), -1)
            # aggregate inside an active trace: ctx puid wins over msgs[0]
            m1 = SeldonMessage.from_array(np.ones((1, 2), np.float64))
            m2 = SeldonMessage.from_array(np.ones((1, 2), np.float64))
            with TRACER.span("ctx-puid", "request", kind="request"):
                await rt.aggregate([m1, m2])
            await rt.close()
        finally:
            await srv.close()

    asyncio.run(run())
    fb_spans = TRACER.trace("fb-req-puid")
    assert any(
        s.kind == "client" and s.method == "send-feedback" for s in fb_spans
    )
    agg_spans = TRACER.trace("ctx-puid")
    assert any(
        s.kind == "client" and s.method == "aggregate" for s in agg_spans
    )


# ---------------------------------------------------------------------------
# Admin surface
# ---------------------------------------------------------------------------


def test_trace_admin_is_post_only():
    """The PR-3 GET-alias deprecation window is closed: mutation via GET
    now answers 405 (the POST route exists, the GET does not) and flips
    nothing."""
    from seldon_core_tpu.runtime.rest import make_engine_app

    spec = deployment(
        {"name": "m", "implementation": "SIMPLE_MODEL", "type": "MODEL"}
    )

    async def run():
        from aiohttp.test_utils import TestClient, TestServer

        engine = EngineService(spec)
        async with TestClient(TestServer(make_engine_app(engine))) as client:
            r = await client.post("/trace/enable")
            assert r.status == 200 and "Deprecation" not in r.headers
            assert TRACER.enabled
            r = await client.post("/trace/disable")
            assert r.status == 200
            assert not TRACER.enabled
            # deprecation window closed: GET mutation is gone
            r = await client.get("/trace/enable")
            assert r.status in (404, 405)
            assert not TRACER.enabled
            await client.post("/trace/enable")
            r = await client.get("/trace/disable")
            assert r.status in (404, 405)
            assert TRACER.enabled
            await client.post("/trace/disable")

    asyncio.run(run())


def test_rest_trace_export_endpoint():
    from seldon_core_tpu.runtime.rest import make_engine_app

    spec = deployment(
        {"name": "m", "implementation": "SIMPLE_MODEL", "type": "MODEL"}
    )

    async def run():
        from aiohttp.test_utils import TestClient, TestServer

        engine = EngineService(spec)
        async with TestClient(TestServer(make_engine_app(engine))) as client:
            await client.post("/trace/enable")
            body = json.dumps({"meta": {"puid": "exp-puid"},
                               "data": {"ndarray": [[1.0, 2.0, 3.0]]}})
            r = await client.post(
                "/api/v0.1/predictions", data=body,
                headers={"Content-Type": "application/json"},
            )
            assert r.status == 200
            r = await client.get("/trace/export", params={"puid": "exp-puid"})
            doc = await r.json()
            assert doc["traceEvents"]
            # /stats carries the tracer health block
            r = await client.get("/stats")
            stats = await r.json()
            assert stats["tracer"]["enabled"] is True
            assert stats["tracer"]["sample"] == 1.0
            assert stats["tracer"]["spans"] >= 1

    asyncio.run(run())


def test_httpfast_trace_routes_and_post_admin():
    from seldon_core_tpu.runtime.httpfast import serve_fast

    spec = deployment(
        {"name": "m", "implementation": "SIMPLE_MODEL", "type": "MODEL"}
    )

    async def run():
        import aiohttp

        engine = EngineService(spec)
        server = await serve_fast(engine, "127.0.0.1", 0)
        base = f"http://127.0.0.1:{server.port}"
        try:
            async with aiohttp.ClientSession() as sess:
                async with sess.post(base + "/trace/enable") as r:
                    assert r.status == 200
                body = json.dumps({"meta": {"puid": "fast-puid"},
                                   "data": {"ndarray": [[1.0, 2.0, 3.0]]}})
                # traceparent adoption on the fast lane
                parent = "00-" + "a" * 32 + "-" + "b" * 16 + "-01"
                async with sess.post(
                    base + "/api/v0.1/predictions", data=body,
                    headers={"Content-Type": "application/json",
                             "traceparent": parent},
                ) as r:
                    assert r.status == 200
                async with sess.get(
                    base + "/trace", params={"puid": "fast-puid"}
                ) as r:
                    doc = await r.json()
                assert any(
                    s.get("trace_id") == "a" * 32 for s in doc["spans"]
                ), "fast lane did not adopt the traceparent"
                async with sess.get(
                    base + "/trace/export", params={"puid": "fast-puid"}
                ) as r:
                    export = await r.json()
                assert export["traceEvents"]
                async with sess.post(base + "/trace/disable") as r:
                    assert r.status == 200
                    assert "Deprecation" not in r.headers
                # deprecation window closed: GET mutation gone (lane
                # parity with the aiohttp app's 405)
                async with sess.get(base + "/trace/enable") as r:
                    assert r.status in (404, 405)
                assert not TRACER.enabled
                async with sess.get(base + "/trace/disable") as r:
                    assert r.status in (404, 405)
        finally:
            await server.stop()

    asyncio.run(run())


def test_gateway_feedback_adopts_traceparent():
    from aiohttp.test_utils import TestClient, TestServer

    from seldon_core_tpu.gateway.apife import ApiGateway, make_gateway_app

    spec = deployment(
        {"name": "m", "implementation": "SIMPLE_MODEL", "type": "MODEL"}
    )

    async def run():
        TRACER.enable()
        engine = EngineService(spec)
        gw = ApiGateway(require_auth=False)
        gw.store.register(spec, {"p": engine})
        parent = "00-" + "e" * 32 + "-" + "f" * 16 + "-01"
        fb = {"reward": 1.0,
              "response": {"meta": {"puid": "gw-fb-puid"}}}
        async with TestClient(TestServer(make_gateway_app(gw))) as client:
            r = await client.post(
                "/api/v0.1/feedback", data=json.dumps(fb),
                headers={"Content-Type": "application/json",
                         "traceparent": parent},
            )
            assert r.status == 200
        await engine.close()

    asyncio.run(run())
    spans = TRACER.by_trace("e" * 32)
    assert spans, "gateway feedback did not join the caller's trace"
    gw_span = next(s for s in spans if s.name == "gateway")
    assert gw_span.parent_span_id == "f" * 16
    assert gw_span.puid == "gw-fb-puid"


# ---------------------------------------------------------------------------
# Device profile re-entrancy
# ---------------------------------------------------------------------------


def test_device_profile_reentrancy_is_noop_not_error(monkeypatch, tmp_path):
    import seldon_core_tpu.utils.tracing as tracing

    calls = {"start": 0, "stop": 0}

    class _FakeProfiler:
        @staticmethod
        def start_trace(logdir):
            if calls["start"] > calls["stop"]:
                raise RuntimeError("profiler already active")
            calls["start"] += 1

        @staticmethod
        def stop_trace():
            calls["stop"] += 1

    import jax

    monkeypatch.setattr(jax, "profiler", _FakeProfiler)
    TRACER.enable()
    with TRACER.span("prof-puid", "work", kind="request"):
        with tracing.device_profile(str(tmp_path)):
            # nested: must not raise, must not call start_trace again
            with tracing.device_profile(str(tmp_path)):
                pass
    assert calls == {"start": 1, "stop": 1}
    (span,) = TRACER.trace("prof-puid")
    assert any(e["name"] == "device_profile_skipped" for e in span.events)


def test_device_profile_skip_without_open_span_records_span(monkeypatch, tmp_path):
    import seldon_core_tpu.utils.tracing as tracing

    class _FakeProfiler:
        @staticmethod
        def start_trace(logdir):
            pass

        @staticmethod
        def stop_trace():
            pass

    import jax

    monkeypatch.setattr(jax, "profiler", _FakeProfiler)
    TRACER.enable()
    with tracing.device_profile(str(tmp_path)):
        with tracing.device_profile(str(tmp_path)):
            pass
    assert any(
        s.name == "device_profile_skipped" for s in TRACER.recent(10)
    )
