"""Graph spec + defaulting/validation tests — the reference operator's
golden-file tests re-imagined (cluster-manager SeldonDeploymentDefaultingTest.java,
SeldonDeploymentValidationTest.java)."""

import json
import pathlib

import pytest

from seldon_core_tpu.graph.defaulting import (
    ENV_PARAMETERS,
    ENV_SERVICE_PORT,
    PU_PORT_BASE,
    default_and_validate,
    defaulting,
    validate,
)
from seldon_core_tpu.graph.spec import (
    EndpointType,
    GraphSpecError,
    Parameter,
    PredictiveUnit,
    SeldonDeploymentSpec,
    UnitImplementation,
    UnitType,
)

RES = pathlib.Path(__file__).parent / "resources"


def load(name):
    return SeldonDeploymentSpec.from_json((RES / name).read_text())


def test_parse_reference_style_deployment():
    spec = load("model_simple.json")
    assert spec.name == "simple-deployment"
    assert spec.metadata_name == "simple-example"
    assert spec.oauth_key == "key"
    p = spec.predictor()
    assert p.name == "main"
    assert p.graph.type is UnitType.MODEL
    assert p.components[0].image == "myorg/classifier:0.1"
    assert p.components[0].runtime == "rest"  # reference containers default to REST


def test_defaulting_assigns_ports_and_endpoints():
    """Port-from-base + endpoint back-fill (SeldonDeploymentOperatorImpl.java:346-387)."""
    spec = default_and_validate(load("model_simple.json"))
    binding = spec.predictor().components[0]
    unit = spec.predictor().graph
    assert binding.port == PU_PORT_BASE
    assert unit.endpoint.service_port == PU_PORT_BASE
    assert unit.endpoint.type is EndpointType.REST
    assert binding.env[ENV_SERVICE_PORT] == str(PU_PORT_BASE)
    assert binding.env["PREDICTIVE_UNIT_ID"] == "classifier"
    assert binding.env["SELDON_DEPLOYMENT_ID"] == "simple-deployment"


def test_defaulting_tpu_graph():
    spec = default_and_validate(load("model_tpu_abtest.json"))
    p = spec.predictor()
    a, b = p.component_map()["mnist-a"], p.component_map()["mnist-b"]
    # inprocess unit: no port, endpoint cleared (compiled into the engine program)
    assert a.port == 0
    assert p.graph.children[0].endpoint is None
    # grpc unit: unique port, GRPC endpoint
    assert b.port == PU_PORT_BASE
    assert p.graph.children[1].endpoint.type is EndpointType.GRPC
    # router params propagate as JSON env on bindings with params
    ab = p.graph
    assert ab.implementation is UnitImplementation.RANDOM_ABTEST
    assert ab.parameters[0].typed_value() == 0.5


def test_unique_ports_across_predictors():
    spec = load("model_simple.json")
    # clone the predictor to simulate a canary (two predictors, same graph)
    import copy

    p2 = copy.deepcopy(spec.predictors[0])
    p2.name = "canary"
    spec.predictors.append(p2)
    defaulting(spec)
    ports = [
        c.port for p in spec.predictors for c in p.components if c.runtime != "inprocess"
    ]
    assert len(ports) == len(set(ports)) == 2


def test_validation_rejects_unbound_model():
    """MODEL leaf with no binding and no hardcoded impl is invalid
    (SeldonDeploymentOperatorImpl.java:390-413)."""
    with pytest.raises(GraphSpecError, match="no matching component binding"):
        default_and_validate(load("model_invalid_graph.json"))


def test_validation_rejects_unit_with_nothing_declared():
    spec = load("model_simple.json")
    g = spec.predictor().graph
    g.type = None
    g.methods = None
    with pytest.raises(GraphSpecError, match="must declare type"):
        validate(spec)


def test_validation_abtest_structure():
    spec = load("model_tpu_abtest.json")
    spec.predictor().graph.children.pop()  # only 1 child now
    with pytest.raises(GraphSpecError, match="exactly 2 children"):
        default_and_validate(spec)
    spec2 = load("model_tpu_abtest.json")
    spec2.predictor().graph.parameters = []
    with pytest.raises(GraphSpecError, match="ratioA"):
        default_and_validate(spec2)


def test_validation_duplicate_unit_names():
    spec = load("model_tpu_abtest.json")
    spec.predictor().graph.children[1].name = "mnist-a"
    with pytest.raises(GraphSpecError, match="duplicate unit names"):
        validate(spec)


def test_validation_inprocess_needs_class_path():
    spec = load("model_tpu_abtest.json")
    spec.predictor().component_map()["mnist-a"].class_path = ""
    with pytest.raises(GraphSpecError, match="class_path"):
        default_and_validate(spec)


def test_roundtrip_spec_json():
    spec = default_and_validate(load("model_tpu_abtest.json"))
    back = SeldonDeploymentSpec.from_json(spec.to_json())
    assert back.name == spec.name
    assert back.predictor().graph.to_json_dict() == spec.predictor().graph.to_json_dict()


def test_parameter_typing():
    assert Parameter("x", "3", "INT").typed_value() == 3
    assert Parameter("x", "0.25", "FLOAT").typed_value() == 0.25
    assert Parameter("x", "true", "BOOL").typed_value() is True
    assert Parameter("x", "False", "BOOL").typed_value() is False
    assert Parameter("x", "s", "STRING").typed_value() == "s"
    with pytest.raises(GraphSpecError):
        Parameter("x", "abc", "INT").typed_value()
    with pytest.raises(GraphSpecError):
        Parameter("x", "1", "WAT").typed_value()


def test_graph_walk_and_find():
    spec = load("model_tpu_abtest.json")
    g = spec.predictor().graph
    assert [u.name for u in g.walk()] == ["ab", "mnist-a", "mnist-b"]
    assert g.find("mnist-b").type is UnitType.MODEL
    assert g.find("nope") is None
