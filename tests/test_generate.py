"""KV-cache decoding: cached generation must equal naive re-forward
decoding, and the generator unit must serve through the engine."""

import asyncio
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from seldon_core_tpu.graph.spec import SeldonDeploymentSpec
from seldon_core_tpu.models.generate import TransformerGenerator, generate
from seldon_core_tpu.models.transformer import LMConfig, lm_apply, lm_init
from seldon_core_tpu.runtime.engine import EngineService

CFG = LMConfig(vocab=48, d_model=32, n_heads=4, n_layers=2, d_ff=64,
               dtype=jnp.float32)


def _naive_greedy(params, prompt, max_new):
    """Recompute the full forward every step — the no-cache reference."""
    tokens = prompt
    out = []
    for _ in range(max_new):
        logits = lm_apply(params, tokens, CFG)
        nxt = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
        out.append(nxt)
        tokens = jnp.concatenate([tokens, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)


@pytest.mark.slow  # heavyweight equivalence check: full-suite/CI-shard coverage; excluded from the tier-1 time budget
def test_cached_generation_matches_naive():
    params = lm_init(jax.random.key(0), CFG)
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, 48, size=(2, 7)), jnp.int32
    )
    got = np.asarray(jax.jit(
        lambda p, t: generate(p, t, CFG, max_new_tokens=12)
    )(params, prompt))
    ref = np.asarray(_naive_greedy(params, prompt, 12))
    np.testing.assert_array_equal(got, ref)


def test_chunked_generation_merge_path_matches(monkeypatch):
    """Force the chunk-merge path (GEN_CHUNK_CAP smaller than max_new):
    tokens must match the single-chunk result exactly — merging relocates
    K/V between tiers without changing the attended set."""
    import seldon_core_tpu.models.generate as gen_mod

    params = lm_init(jax.random.key(5), CFG)
    prompt = jnp.asarray(
        np.random.default_rng(7).integers(0, 48, size=(2, 6)), jnp.int32
    )
    ref = np.asarray(generate(params, prompt, CFG, max_new_tokens=13))
    monkeypatch.setattr(gen_mod, "GEN_CHUNK_CAP", 4)
    got = np.asarray(generate(params, prompt, CFG, max_new_tokens=13))
    np.testing.assert_array_equal(got, ref)


def test_stream_merge_path_matches_generate(monkeypatch):
    """Streams that outgrow STREAM_CHUNK_CAP merge mid-stream; the
    concatenated tokens still equal generate()'s."""
    import seldon_core_tpu.models.generate as gen_mod
    from seldon_core_tpu.models.generate import stream_chunks

    monkeypatch.setattr(gen_mod, "STREAM_CHUNK_CAP", 5)
    params = lm_init(jax.random.key(6), CFG)
    prompt = jnp.asarray(
        np.random.default_rng(8).integers(0, 48, size=(2, 6)), jnp.int32
    )
    ref = np.asarray(generate(params, prompt, CFG, max_new_tokens=14))
    chunks = [np.asarray(c) for c in stream_chunks(
        params, prompt, CFG, max_new_tokens=14, chunk=3
    )]
    np.testing.assert_array_equal(np.concatenate(chunks, axis=1), ref)


def test_stream_chunk_larger_than_cap_clamped(monkeypatch):
    """A requested chunk bigger than STREAM_CHUNK_CAP must be clamped,
    not dus'd past the buffer (which would silently corrupt K/V)."""
    import seldon_core_tpu.models.generate as gen_mod
    from seldon_core_tpu.models.generate import stream_chunks

    monkeypatch.setattr(gen_mod, "STREAM_CHUNK_CAP", 4)
    params = lm_init(jax.random.key(9), CFG)
    prompt = jnp.asarray(
        np.random.default_rng(10).integers(0, 48, size=(1, 5)), jnp.int32
    )
    ref = np.asarray(generate(params, prompt, CFG, max_new_tokens=11))
    chunks = [np.asarray(c) for c in stream_chunks(
        params, prompt, CFG, max_new_tokens=11, chunk=9  # > cap
    )]
    np.testing.assert_array_equal(np.concatenate(chunks, axis=1), ref)


def test_int8_kv_attention_close_to_float():
    """Int8 cached attention vs the float formulation: per-token absmax
    rounding bounds the relative error at a few percent."""
    from seldon_core_tpu.models.generate import _attend_cached, _quantize_kv

    rng = np.random.default_rng(3)
    B, KV, g, hd, L = 2, 2, 4, 64, 96
    q = jnp.asarray(rng.normal(size=(B, KV * g, 1, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, KV, L, hd)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, KV, L, hd)), jnp.float32)
    want = np.asarray(_attend_cached(q, {"k": k, "v": v}, 80))
    k_q, k_s = _quantize_kv(k)
    v_q, v_s = _quantize_kv(v)
    got = np.asarray(_attend_cached(
        q, {"k": k_q, "v": v_q, "k_s": k_s, "v_s": v_s}, 80
    ))
    rel = np.abs(got - want).max() / np.abs(want).max()
    assert rel < 0.03, f"int8 KV attention rel err {rel:.4f}"


def test_int8_kv_generate_wiring_and_logit_fidelity():
    """kv_quant='int8' end to end: prefill stays exact (attends the
    pre-quantization k/v), decode logits track the float path closely,
    and generate() runs the full scan with the quantized cache."""
    import dataclasses

    from seldon_core_tpu.models.generate import (
        decode_step, init_cache, prefill,
    )

    cfg_q = dataclasses.replace(CFG, kv_quant="int8")
    params = lm_init(jax.random.key(2), CFG)
    prompt = jnp.asarray(
        np.random.default_rng(4).integers(0, 48, size=(2, 9)), jnp.int32
    )
    outs = {}
    for name, cfg in (("f32", CFG), ("int8", cfg_q)):
        cache = init_cache(cfg, 2, 16)
        logits, cache = prefill(params, prompt, cache, cfg)
        first = jnp.argmax(logits, -1).astype(jnp.int32)
        step_logits, _ = decode_step(params, first, cache, 9, cfg)
        outs[name] = (np.asarray(logits), np.asarray(step_logits))
    # prefill logits are EXACT (same float attention path)
    np.testing.assert_array_equal(outs["f32"][0], outs["int8"][0])
    # decode logits: only KV rounding error separates them
    np.testing.assert_allclose(
        outs["int8"][1], outs["f32"][1], rtol=0.1, atol=0.05
    )
    toks = np.asarray(generate(params, prompt, cfg_q, max_new_tokens=8))
    assert toks.shape == (2, 8)
    assert (toks >= 0).all() and (toks < CFG.vocab).all()


def test_int8_kv_generator_unit_parameter():
    unit = TransformerGenerator(
        vocab=48, d_model=32, n_heads=4, n_layers=1, d_ff=64,
        max_new_tokens=4, dtype="float32", kv_quant="int8",
    )
    state = unit.init_state(None)
    y = np.asarray(unit.predict(state, jnp.zeros((1, 5), jnp.float32)))
    assert y.shape == (1, 4)


def test_sampled_generation_valid_and_seeded():
    params = lm_init(jax.random.key(1), CFG)
    prompt = jnp.zeros((3, 4), jnp.int32)
    a = np.asarray(generate(params, prompt, CFG, max_new_tokens=8,
                            temperature=1.0, rng=jax.random.key(5)))
    b = np.asarray(generate(params, prompt, CFG, max_new_tokens=8,
                            temperature=1.0, rng=jax.random.key(5)))
    c = np.asarray(generate(params, prompt, CFG, max_new_tokens=8,
                            temperature=1.0, rng=jax.random.key(6)))
    np.testing.assert_array_equal(a, b)  # same key -> same sample
    assert (a != c).any()                # different key -> different path
    assert a.shape == (3, 8)
    assert (0 <= a).all() and (a < 48).all()


def test_generator_unit_serves_through_engine():
    spec = SeldonDeploymentSpec.from_json_dict({
        "spec": {"name": "gen", "predictors": [{
            "name": "p",
            "graph": {"name": "g", "type": "MODEL"},
            "components": [{
                "name": "g", "runtime": "inprocess",
                "class_path": "TransformerGenerator",
                "parameters": [
                    {"name": "vocab", "value": "48", "type": "INT"},
                    {"name": "d_model", "value": "32", "type": "INT"},
                    {"name": "n_layers", "value": "1", "type": "INT"},
                    {"name": "d_ff", "value": "64", "type": "INT"},
                    {"name": "max_new_tokens", "value": "6", "type": "INT"},
                    {"name": "dtype", "value": "float32", "type": "STRING"},
                ],
            }],
        }]}
    })
    engine = EngineService(spec)
    from seldon_core_tpu.messages import SeldonMessage

    prompt = np.zeros((2, 5), dtype=np.int64).tolist()
    msg = SeldonMessage.from_json(json.dumps({"data": {"ndarray": prompt}}))
    resp = asyncio.run(engine.predict(msg))
    toks = np.asarray(resp.data.array)
    assert toks.shape == (2, 6)
    assert np.isfinite(toks).all()
    assert ((0 <= toks) & (toks < 48)).all()


def test_single_token_generation():
    params = lm_init(jax.random.key(2), CFG)
    prompt = jnp.zeros((2, 3), jnp.int32)
    y = np.asarray(generate(params, prompt, CFG, max_new_tokens=1))
    ref = np.asarray(_naive_greedy(params, prompt, 1))
    np.testing.assert_array_equal(y, ref)


def test_sampled_generator_declares_batch_coupling():
    """temperature>0 samples depend on row position in the stacked batch,
    so the unit must opt its graphs out of cross-request coalescing."""
    greedy = TransformerGenerator(temperature=0.0)
    sampled = TransformerGenerator(temperature=1.0)
    assert greedy.batch_coupled is False
    assert sampled.batch_coupled is True


def test_sampled_unit_varies_across_requests():
    """temperature>0 must not replay the same continuation for repeated
    identical prompts: the request counter in state varies the key."""
    u = TransformerGenerator(vocab=48, d_model=32, n_heads=4, n_layers=1,
                             d_ff=64, max_new_tokens=8, temperature=1.0,
                             dtype="float32")
    st = u.init_state(jax.random.key(0))
    X = jnp.zeros((2, 4), jnp.float32)
    from seldon_core_tpu.graph.units import normalize_output

    y1, st1, _ = normalize_output(u.predict(st, X), st)
    y2, st2, _ = normalize_output(u.predict(st1, X), st1)
    assert int(st2["requests"]) == 2
    assert (np.asarray(y1) != np.asarray(y2)).any()


def test_out_of_range_prompt_tokens_clamped():
    u = TransformerGenerator(vocab=48, d_model=32, n_heads=4, n_layers=1,
                             d_ff=64, max_new_tokens=4, dtype="float32")
    st = u.init_state(jax.random.key(0))
    wild = jnp.asarray([[-5.0, 3.2, 999.0, 47.0]], jnp.float32)
    tame = jnp.asarray([[0.0, 3.0, 47.0, 47.0]], jnp.float32)
    y_wild = np.asarray(u.predict(st, wild))
    y_tame = np.asarray(u.predict(st, tame))
    np.testing.assert_array_equal(y_wild, y_tame)  # clamp contract
    assert ((0 <= y_wild) & (y_wild < 48)).all()


def test_sample_token_top_k_and_top_p_truncation():
    """top-k must never sample outside the k highest logits; top-p must
    never sample outside the smallest prefix reaching mass p (and always
    keeps at least one token)."""
    from seldon_core_tpu.models.generate import sample_token

    logits = jnp.asarray([[5.0, 4.0, 3.0, -2.0, -3.0, -9.0]] * 4)
    for i in range(8):
        k = jax.random.key(i)
        tk = np.asarray(sample_token(logits, k, temperature=1.0, top_k=2))
        assert set(tk.tolist()) <= {0, 1}, tk
        tp = np.asarray(sample_token(logits, k, temperature=1.0,
                                     top_p=0.5))
        assert set(tp.tolist()) <= {0}, tp  # token 0 alone has mass >0.5
    # extreme top_p still yields a valid token
    t = np.asarray(sample_token(logits, jax.random.key(0),
                                temperature=1.0, top_p=1e-9))
    assert set(t.tolist()) <= {0}
    # greedy path ignores truncation knobs entirely
    g = np.asarray(sample_token(logits, jax.random.key(0)))
    np.testing.assert_array_equal(g, [0, 0, 0, 0])


def test_mask_after_eos_and_generate_eos_contract():
    """Positions strictly after a row's first eos become eos; rows
    without eos are untouched; generate() and stream_chunks() apply the
    same padding, and a stream whose rows have ALL stopped pads from
    the host (the early-stop branch is exercised, not just declared)."""
    from seldon_core_tpu.models.generate import (
        mask_after_eos, stream_chunks,
    )

    toks = jnp.asarray([[3, 7, 7, 5], [1, 2, 3, 4], [7, 1, 7, 2]])
    got = np.asarray(mask_after_eos(toks, 7))
    np.testing.assert_array_equal(
        got, [[3, 7, 7, 7], [1, 2, 3, 4], [7, 7, 7, 7]])
    # disabled sentinel is a no-op
    np.testing.assert_array_equal(np.asarray(mask_after_eos(toks, -1)),
                                  np.asarray(toks))

    # B=1 so one row stopping means ALL rows stopped: pick an eos whose
    # FIRST occurrence in the baseline is mid-sequence, so (a) masking
    # changes real tokens and (b) the stream's host-padding branch runs
    params = lm_init(jax.random.key(0), CFG)
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, 48, size=(1, 7)), jnp.int32
    )
    # SAMPLED with a fixed key: the untrained greedy baseline is a
    # constant token (eos at position 0 masks nothing); sampling gives a
    # varied, still-deterministic sequence with a usable mid-stream eos
    kw = dict(temperature=1.0, rng=jax.random.key(5))
    base = np.asarray(generate(params, prompt, CFG, max_new_tokens=12,
                               **kw))[0]
    eos = first_at = None
    for j in range(1, 9):
        tok = int(base[j])
        if tok not in base[:j].tolist() and (base[j + 1:] != tok).any():
            eos, first_at = tok, j
            break
    assert eos is not None, f"no usable eos in baseline {base}"
    ref = np.asarray(generate(params, prompt[:1], CFG, max_new_tokens=12,
                              eos_token=eos, **kw))[0]
    np.testing.assert_array_equal(ref[:first_at + 1], base[:first_at + 1])
    assert (ref[first_at:] == eos).all()
    assert (base[first_at + 1:] != eos).any()  # masking changed tokens
    # stream == generate under eos padding, including host-padded chunks
    chunks = [np.asarray(c) for c in stream_chunks(
        params, prompt[:1], CFG, max_new_tokens=12, chunk=3,
        eos_token=eos, **kw)]
    np.testing.assert_array_equal(np.concatenate(chunks, axis=1)[0], ref)
    # the final chunk(s) past the stop are pure eos padding
    assert (chunks[-1] == eos).all()


def test_prefix_cache_equals_full_prefill():
    """A shared-prefix KV cache built once at B=1 must reproduce the
    full-prompt generation EXACTLY (f32 greedy) for batched suffixes,
    through both generate() and stream_chunks()."""
    from seldon_core_tpu.models.generate import (
        init_cache, prefill, stream_chunks,
    )

    params = lm_init(jax.random.key(3), CFG)
    rng = np.random.default_rng(11)
    prefix_ids = rng.integers(0, 48, size=(6,)).tolist()
    sufs = jnp.asarray(rng.integers(0, 48, size=(3, 5)), jnp.int32)
    full = jnp.concatenate(
        [jnp.broadcast_to(jnp.asarray(prefix_ids, jnp.int32), (3, 6)),
         sufs], axis=1)
    ref = np.asarray(generate(params, full, CFG, max_new_tokens=10))

    pc = init_cache(CFG, 1, len(prefix_ids))
    _, pc = prefill(params, jnp.asarray([prefix_ids], jnp.int32), pc, CFG)
    got = np.asarray(generate(
        params, sufs, CFG, max_new_tokens=10, prefix=pc))
    np.testing.assert_array_equal(got, ref)

    chunks = [np.asarray(c) for c in stream_chunks(
        params, sufs, CFG, max_new_tokens=10, chunk=4, prefix=pc)]
    np.testing.assert_array_equal(np.concatenate(chunks, axis=1), ref)


def _prefix_chunked_roundtrip(cfg, monkeypatch, cap=4, max_new=13):
    """Shared body: chunked (max_new > GEN_CHUNK_CAP) prefix-path equality
    — the zero-pad-to-main_len + merge_chunk-into-padded-region lane,
    including int8 k_s/v_s scale buffers when cfg quantizes the cache."""
    from seldon_core_tpu.models.generate import init_cache, prefill
    import seldon_core_tpu.models.generate as gen_mod

    params = lm_init(jax.random.key(3), cfg)
    rng = np.random.default_rng(21)
    prefix_ids = rng.integers(0, 48, size=(6,)).tolist()
    sufs = jnp.asarray(rng.integers(0, 48, size=(2, 5)), jnp.int32)

    pc = init_cache(cfg, 1, len(prefix_ids))
    _, pc = prefill(params, jnp.asarray([prefix_ids], jnp.int32), pc, cfg)
    # single-chunk reference FIRST (no cap patch): same prefix cache, so
    # any mismatch isolates the chunked merge path itself
    ref = np.asarray(generate(
        params, sufs, cfg, max_new_tokens=max_new, prefix=pc))
    monkeypatch.setattr(gen_mod, "GEN_CHUNK_CAP", cap)
    got = np.asarray(generate(
        params, sufs, cfg, max_new_tokens=max_new, prefix=pc))
    np.testing.assert_array_equal(got, ref)


def test_prefix_cache_chunked_merge_matches(monkeypatch):
    """Float cache: prefix + chunked decode (merge_chunk into the padded
    main region) must equal the single-chunk prefix result exactly."""
    _prefix_chunked_roundtrip(CFG, monkeypatch)


def test_prefix_cache_chunked_merge_matches_int8(monkeypatch):
    """int8 KV cache variant: the padded main carries k_s/v_s scale
    buffers that merge_chunk must relocate alongside the quantized K/V."""
    cfg = LMConfig(vocab=48, d_model=32, n_heads=4, n_layers=2, d_ff=64,
                   dtype=jnp.float32, kv_quant="int8")
    _prefix_chunked_roundtrip(cfg, monkeypatch)


def test_prefix_cache_unit_serves():
    """prefix_tokens as a deployment parameter: the unit builds the
    prefix cache once in init_state and every predict equals the
    no-prefix unit fed the concatenated prompt."""
    plain = TransformerGenerator(
        vocab=48, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_new_tokens=6, dtype="float32")
    pref = TransformerGenerator(
        vocab=48, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_new_tokens=6, dtype="float32", prefix_tokens="4, 9, 2")
    sp, s2 = plain.init_state(None), pref.init_state(None)
    assert "prefix_cache" in s2
    suf = jnp.asarray([[7, 8, 20, 1]], jnp.float32)
    full = jnp.asarray([[4, 9, 2, 7, 8, 20, 1]], jnp.float32)
    np.testing.assert_array_equal(
        np.asarray(pref.predict(s2, suf)),
        np.asarray(plain.predict(sp, full)))
    import pytest as _pytest

    with _pytest.raises(ValueError, match="outside vocab"):
        TransformerGenerator(vocab=48, prefix_tokens="99")


def test_sampled_state_writeback_preserves_prefix_cache():
    """temperature>0 writes state back (request counter); the write-back
    must carry EVERY state key — dropping prefix_cache silently turned
    every later request prefix-less."""
    unit = TransformerGenerator(
        vocab=48, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_new_tokens=4, dtype="float32", temperature=0.8,
        prefix_tokens="4,9,2")
    state = unit.init_state(None)
    y, aux = unit.predict(state, jnp.asarray([[7, 8]], jnp.float32))
    assert "prefix_cache" in aux.state
    y2 = unit.predict(aux.state, jnp.asarray([[7, 8]], jnp.float32))[0]
    assert np.asarray(y2).shape == (1, 4)


def test_prefix_cache_with_int8_kv_serves():
    """prefix caching composes with kv_quant='int8' (scales broadcast
    and concatenate like K/V): outputs are valid tokens — exactness is
    deliberately NOT claimed here (the prefix reads back quantized; see
    generate()'s docstring)."""
    import dataclasses

    from seldon_core_tpu.models.generate import init_cache, prefill

    cfg_q = dataclasses.replace(CFG, kv_quant="int8")
    params = lm_init(jax.random.key(3), CFG)
    prefix_ids = [4, 9, 2, 30]
    pc = init_cache(cfg_q, 1, len(prefix_ids))
    _, pc = prefill(params, jnp.asarray([prefix_ids], jnp.int32), pc,
                    cfg_q)
    sufs = jnp.asarray([[7, 8, 20], [1, 2, 3]], jnp.int32)
    got = np.asarray(generate(params, sufs, cfg_q, max_new_tokens=6,
                              prefix=pc))
    assert got.shape == (2, 6)
    assert (got >= 0).all() and (got < CFG.vocab).all()
