"""Learned cost-model autopilot (runtime/autopilot.py): hand-computed
predictor updates (seed-from-cost, online correction, outlier
robustness), goodput-optimal flush sizing, deadline-aware admission
shedding, p2c score blending, router branch demotion, the kill switch,
and the seldon_tpu_autopilot_* metric families."""

import asyncio
import json
from collections import deque

import jax
import numpy as np
import pytest

from seldon_core_tpu.gateway.balancer import ReplicaEndpoint, ReplicaSet
from seldon_core_tpu.graph.interpreter import GraphExecutor
from seldon_core_tpu.graph.spec import SeldonDeploymentSpec
from seldon_core_tpu.messages import SeldonMessage
from seldon_core_tpu.runtime.autopilot import (
    AUTOPILOT,
    Autopilot,
    autopilot_enabled,
    branch_key,
    pad_bucket,
)
from seldon_core_tpu.runtime.batching import MicroBatcher
from seldon_core_tpu.runtime.engine import EngineService
from seldon_core_tpu.runtime.resilience import Deadline, deadline_scope
from seldon_core_tpu.utils.perf import PerfObservatory, executable_key
from seldon_core_tpu.utils.telemetry import RECORDER, TPU_METRIC_FAMILIES


def deployment(graph, components=None):
    return SeldonDeploymentSpec.from_json_dict(
        {
            "spec": {
                "name": "d",
                "predictors": [
                    {"name": "p", "graph": graph,
                     "components": components or []}
                ],
            }
        }
    )


def run(coro):
    return asyncio.get_event_loop_policy().new_event_loop().run_until_complete(
        coro
    )


# ---------------------------------------------------------------------------
# predictor: seed-from-cost, online correction, outlier robustness
# ---------------------------------------------------------------------------


def test_pad_bucket_and_branch_key():
    assert [pad_bucket(n) for n in (1, 2, 3, 4, 5, 127, 128)] == [
        1, 2, 4, 4, 8, 128, 128,
    ]
    assert branch_key("r", 1, 5) == "branch:r/1[8]"
    assert branch_key("r", 0, None) == "branch:r/0[1]"


def test_seed_prior_is_overhead_adjusted_roofline():
    """Before any measurement the prediction is the perf observatory's
    overhead-adjusted roofline — hand-computed from the cost features."""
    obs = PerfObservatory(enabled=True)
    key = executable_key("predict", (8, 16), np.float32)
    flops, nbytes = 2.0 * 8 * 16 * 4, 4.0 * (8 * 16 + 16 * 4)
    obs.record_compile(
        key, {"flops": flops, "bytes_accessed": nbytes}, None
    )
    peaks = obs.peaks()
    roofline = max(
        flops / (peaks["peak_bf16_tflops"] * 1e12),
        nbytes / (peaks["peak_hbm_gbs"] * 1e9),
    )
    ap = Autopilot(lr=0.3, min_samples=4)
    ap.seed_fn = obs.seed_predicted_s
    assert ap.predict_s(key) == pytest.approx(roofline * obs.overhead_x)
    # one measured dispatch calibrates the seed: the adjusted roofline
    # scaled by the key's measured calibration ratio equals the wall
    obs.observe_dispatch(key, 0.004)
    assert obs.seed_predicted_s(key) == pytest.approx(0.004, rel=1e-3)


def test_online_correction_blends_seed_then_trusts_measurements():
    ap = Autopilot(lr=0.5, min_samples=4)
    ap.seed_fn = lambda key: 0.1  # a (bad) 100 ms prior
    key = "predict[4x8/float32]"
    assert ap.predict_s(key) == pytest.approx(0.1)  # pure seed, no samples
    ap.observe(key, 0.02)
    # 1 of 4 samples: w=0.25 toward the learned 20 ms estimate
    assert ap.predict_s(key) == pytest.approx(0.25 * 0.02 + 0.75 * 0.1)
    for _ in range(3):
        ap.observe(key, 0.02)
    # min_samples reached: the learned estimate stands alone
    assert ap.predict_s(key) == pytest.approx(0.02)
    # sustained shift converges at the learning rate: est += lr*resid
    # (resid clipped at 4 scales; scale here is 10 ms, so 40 ms passes)
    before = ap.predict_s(key)
    ap.observe(key, 0.04)
    m = ap._models[key]
    assert m.est_s > before  # moved toward the new regime


def test_outlier_robustness_hand_computed():
    """One 10 s straggler among 10 ms dispatches moves the estimate by at
    most lr * OUTLIER_K * scale — the model cannot be yanked."""
    ap = Autopilot(lr=0.5, min_samples=3)
    key = "predict[8x8/float32]"
    for _ in range(10):
        ap.observe(key, 0.010)
    m = ap._models[key]
    scale_before = m.scale_s  # decayed toward 0 on identical samples
    est_before = m.est_s
    ap.observe(key, 10.0)  # 1000x straggler
    max_step = ap.lr * ap.OUTLIER_K * max(scale_before, 1e-9)
    assert m.est_s - est_before <= max_step + 1e-12
    assert ap.predict_s(key) == pytest.approx(0.010, rel=0.05)
    # and the misprediction landed in the auditing reservoir
    assert ap.mispredict_pct.snapshot()["max"] > 1000.0


def test_bounded_model_table():
    ap = Autopilot()
    for i in range(ap.MAX_KEYS + 50):
        ap.observe(f"k{i}", 0.001)
    assert len(ap._models) == ap.MAX_KEYS


# ---------------------------------------------------------------------------
# predictive micro-batch sizing
# ---------------------------------------------------------------------------


def _entry(rows, width=3, deadline=None):
    return (np.zeros((rows, width)), None, 0.0, None, deadline)


async def _noop_batch(stacked):
    return stacked, {}


def test_flush_plan_picks_goodput_optimal_pad_bucket():
    """Constructed workload: 3+1 rows fill the 4-bucket exactly
    (predicted 10 ms -> 400 rows/s); adding a 5th row pads to the
    8-bucket (predicted 40 ms -> 125 rows/s).  The planner flushes the
    zero-waste prefix and leaves the tail for the next slot."""
    costs = {4: 0.010, 8: 0.040}
    mb = MicroBatcher(
        _noop_batch, max_batch=64,
        predict_s_fn=lambda padded, x: costs.get(padded),
    )
    bucket = deque([_entry(3), _entry(1), _entry(1)])
    k, predicted = mb._plan_flush(bucket)
    assert k == 2
    assert predicted == pytest.approx(0.010)

    # flat predicted cost: bigger is always better goodput -> take all
    mb2 = MicroBatcher(
        _noop_batch, max_batch=64, predict_s_fn=lambda p, x: 0.010,
    )
    k, predicted = mb2._plan_flush(deque([_entry(3), _entry(1), _entry(1)]))
    assert k == 3
    assert predicted == pytest.approx(0.010)


def test_flush_plan_respects_tightest_deadline():
    """A candidate whose predicted wall blows the included requests'
    tightest remaining deadline is dropped when a smaller prefix fits:
    16 rows at 25 ms is the better goodput (640 > 400 rows/s) and wins
    without deadline pressure, but under a 22 ms budget the planner
    flushes the 8-row prefix that can still answer in time."""
    costs = {8: 0.020, 16: 0.025}
    mb = MicroBatcher(
        _noop_batch, max_batch=64,
        predict_s_fn=lambda padded, x: costs.get(padded),
    )
    # no deadline pressure: the 16-bucket's higher goodput wins
    bucket = deque([_entry(8), _entry(8)])
    k, predicted = mb._plan_flush(bucket)
    assert k == 2
    assert predicted == pytest.approx(0.025)
    clock = [100.0]
    tight = Deadline(100.0 + 0.022, clock=lambda: clock[0])
    bucket = deque([_entry(8, deadline=tight), _entry(8, deadline=tight)])
    k, predicted = mb._plan_flush(bucket)
    assert k == 1
    assert predicted == pytest.approx(0.020)


def test_flush_plan_kill_switch_and_no_model_restore_legacy(monkeypatch):
    costs = {4: 0.010, 8: 0.040}
    mb = MicroBatcher(
        _noop_batch, max_batch=64,
        predict_s_fn=lambda padded, x: costs.get(padded),
    )
    bucket = deque([_entry(3), _entry(1), _entry(1)])
    monkeypatch.setenv("SELDON_TPU_AUTOPILOT", "0")
    assert mb._plan_flush(bucket) == (3, None)  # legacy take-all
    monkeypatch.delenv("SELDON_TPU_AUTOPILOT")
    # an unmodelled pad bucket anywhere in the candidate set: legacy
    mb.predict_s_fn = lambda padded, x: None
    assert mb._plan_flush(bucket) == (3, None)
    # no hook at all (engines without compiled graphs): legacy
    mb.predict_s_fn = None
    assert mb._plan_flush(bucket) == (3, None)


def test_predicted_latency_s_hand_computed():
    mb = MicroBatcher(
        _noop_batch, max_batch=64, coalesce_ms=0.5, max_wait_ms=2.0,
        predict_s_fn=lambda padded, x: {1: 0.004, 4: 0.007}.get(padded),
    )
    x = np.zeros((1, 3))
    # idle batcher: dispatch + coalesce window, no slot wait
    assert mb.predicted_latency_s(x) == pytest.approx(0.004 + 0.0005)
    # with 3 rows already queued the request lands in the 4-bucket
    # (bucket keys carry the latency tier — runtime/qos.py — and
    # default traffic is all-interactive)
    from seldon_core_tpu.runtime.qos import TIER_INTERACTIVE

    mb._buckets[(x.shape[1:], x.dtype, TIER_INTERACTIVE)] = \
        deque([_entry(3)])
    assert mb.predicted_latency_s(x) == pytest.approx(0.007 + 0.0005)


# ---------------------------------------------------------------------------
# deadline-aware admission control
# ---------------------------------------------------------------------------


def _model_engine(**kw):
    spec = deployment(
        {"name": "m", "implementation": "SIMPLE_MODEL", "type": "MODEL"}
    )
    return EngineService(spec, **kw)


def _prime_slow_model(engine, rows=1, width=4, seconds=5.0):
    """Teach the autopilot that this engine's pad bucket is slow."""
    x = np.zeros((rows, width))
    key = executable_key(
        "predict", (pad_bucket(rows),) + x.shape[1:], x.dtype
    )
    for _ in range(AUTOPILOT.min_samples + 1):
        AUTOPILOT.observe(key, seconds)
    return key


def test_admission_sheds_on_exhausted_predicted_budget():
    AUTOPILOT.reset()
    engine = _model_engine()
    assert engine.batcher is not None
    _prime_slow_model(engine, seconds=5.0)
    before = dict(RECORDER.autopilot_sheds)
    payload = json.dumps({"data": {"ndarray": [[0.0] * 4]}})

    async def go():
        # 50 ms of budget against a predicted ~5 s dispatch: typed 503
        # BEFORE any dispatch happens
        with deadline_scope(0.05):
            return await engine.predict_json(payload)

    text, status = run(go())
    assert status == 503
    doc = json.loads(text)
    assert doc["status"]["status"] == "FAILURE"
    assert "load shed" in doc["status"]["info"]
    got = RECORDER.autopilot_sheds.get("admission", 0)
    assert got == before.get("admission", 0) + 1
    AUTOPILOT.reset()


def test_admission_does_not_shed_when_budget_suffices():
    AUTOPILOT.reset()
    engine = _model_engine()
    _prime_slow_model(engine, seconds=0.001)  # predicted ~1 ms

    async def go():
        with deadline_scope(30.0):
            return await engine.predict_json(
                json.dumps({"data": {"ndarray": [[0.0] * 4]}})
            )

    text, status = run(go())
    assert status == 200, text
    AUTOPILOT.reset()


def test_admission_kill_switch_restores_prior_behavior(monkeypatch):
    """SELDON_TPU_AUTOPILOT=0: a doomed-looking request is NOT shed —
    exactly the pre-autopilot reactive path (and on this fast CPU model
    the dispatch actually makes the deadline, proving a shed would have
    been wrong to force)."""
    AUTOPILOT.reset()
    engine = _model_engine()
    _prime_slow_model(engine, seconds=5.0)  # model CLAIMS 5 s
    monkeypatch.setenv("SELDON_TPU_AUTOPILOT", "0")

    async def go():
        with deadline_scope(5.0):
            return await engine.predict_json(
                json.dumps({"data": {"ndarray": [[0.0] * 4]}})
            )

    text, status = run(go())
    assert status == 200, text
    AUTOPILOT.reset()


# ---------------------------------------------------------------------------
# cost-aware routing: p2c score blending + router branch demotion
# ---------------------------------------------------------------------------


def test_p2c_score_blends_shape_aware_latency():
    ep = ReplicaEndpoint("http://e1:1")
    # global EWMA says 5 ms; the 128-bucket has learned 50 ms
    ep.ewma_ms = 5.0
    for _ in range(ReplicaEndpoint.SHAPE_MIN_SAMPLES):
        ep.inflight += 1
        ep.complete(0.050, ok=True, rows=100)
    # unknown shape / no rows: the shape-blind EWMA (which the 50 ms
    # completions also fed) — bit-for-bit the legacy input
    assert ep.predicted_ms(None) == ep.ewma_ms
    # the 100-row request prices at its own bucket, not the blind EWMA
    assert ep.predicted_ms(100) == pytest.approx(50.0, rel=1e-6)
    assert ep.predicted_ms(1) == ep.ewma_ms  # no 1-bucket model yet
    now = 0.0
    assert ep.score(now, 1e9, rows=100) == pytest.approx(
        (ep.inflight + ep.scraped_inflight + 1) * 50.0
    )


def test_p2c_blend_below_min_samples_hand_computed():
    ep = ReplicaEndpoint("http://e1:1")
    ep.ewma_ms = 5.0
    for _ in range(2):  # 2 of 5 samples at 50 ms
        ep.inflight += 1
        ep.complete(0.050, ok=True, rows=100)
    ewma_after = ep.ewma_ms  # the completions moved the global EWMA too
    w = 2 / ReplicaEndpoint.SHAPE_MIN_SAMPLES
    assert ep.predicted_ms(100) == pytest.approx(
        w * 50.0 + (1 - w) * ewma_after
    )


def test_p2c_pick_steers_by_request_shape():
    """Replica A is fast for small rows, B for big ones: the same set
    routes a 1-row request to A and a 128-row request to B."""
    import random

    rs = ReplicaSet(["http://a:1", "http://b:1"], rng=random.Random(0))
    a, b = rs.endpoints
    a.ewma_ms = b.ewma_ms = 10.0
    a.shape_ms = {1: [1.0, 9], 128: [80.0, 9]}
    b.shape_ms = {1: [30.0, 9], 128: [8.0, 9]}
    picks_small = {rs.pick(rows=1)[0].name for _ in range(8)}
    picks_big = {rs.pick(rows=128)[0].name for _ in range(8)}
    assert picks_small == {"http://a:1"}
    assert picks_big == {"http://b:1"}


def test_p2c_kill_switch_restores_blind_ewma(monkeypatch):
    ep = ReplicaEndpoint("http://e1:1")
    ep.ewma_ms = 5.0
    ep.shape_ms = {128: [50.0, 9]}
    monkeypatch.setenv("SELDON_TPU_AUTOPILOT", "0")
    assert ep.predicted_ms(100) == 5.0
    assert ep.score(0.0, 1e9, rows=100) == ep.score(0.0, 1e9)


def test_router_branch_demotion_under_deadline():
    """The router picks branch 0 (argmax of rewards); the autopilot has
    learned branch 0 takes ~5 s and branch 1 ~1 ms.  Under a 100 ms
    budget the request is demoted to branch 1 — recorded in
    meta.routing (feedback trains the branch that served) and tagged.
    Without a deadline the router's choice stands."""
    AUTOPILOT.reset()
    g = {
        "name": "r",
        "type": "ROUTER",
        "children": [
            {"name": "s1", "type": "MODEL"},
            {"name": "s2", "type": "MODEL"},
        ],
    }
    comps = [
        {"name": "r", "runtime": "inprocess",
         "class_path": "test.CountingRouter"},
        {"name": "s1", "runtime": "inprocess", "class_path": "test.Scale"},
        {"name": "s2", "runtime": "inprocess", "class_path": "test.Scale"},
    ]
    import tests.test_graph_exec  # noqa: F401 - registers test.* units

    ex = GraphExecutor(deployment(g, comps).predictor())
    for _ in range(AUTOPILOT.min_samples + 1):
        AUTOPILOT.observe(branch_key("r", 0, 1), 5.0)
        AUTOPILOT.observe(branch_key("r", 1, 1), 0.001)

    resp = run(ex.predict(SeldonMessage.from_array(np.ones((1, 2)))))
    assert resp.meta.routing["r"] == 0  # no deadline: untouched

    async def bounded():
        with deadline_scope(0.1):
            return await ex.predict(SeldonMessage.from_array(np.ones((1, 2))))

    resp = run(bounded())
    assert resp.meta.routing["r"] == 1
    assert resp.meta.tags["seldon.autopilot.reroute.r"] == 1
    AUTOPILOT.reset()


# ---------------------------------------------------------------------------
# learning rides the telemetry spine; surfaces; metric families
# ---------------------------------------------------------------------------


def test_dispatches_train_model_through_spine_and_autopilot_page():
    AUTOPILOT.reset()
    engine = _model_engine()
    payload = json.dumps({"data": {"ndarray": [[0.0] * 4]}})

    async def go():
        for _ in range(8):
            text, status = await engine.predict_json(payload)
            assert status == 200, text

    run(go())
    doc = engine.autopilot_document()
    assert doc["engine"]["deployment"] == "d"
    trained = [k for k in doc["keys"] if k["samples"] > 0]
    assert trained, doc
    assert trained[0]["predicted_ms"] > 0
    assert doc["knobs"]["kill_switch"] == "SELDON_TPU_AUTOPILOT"
    # /stats carries the compact health block
    assert engine.stats()["autopilot"]["keys"] >= 1
    AUTOPILOT.reset()


def test_gateway_does_not_blame_replicas_for_sheds():
    """A predictive shed is the engine deciding, not the replica dying:
    the gateway must neither feed fail-degradation (a shedding replica
    would blackhole) nor the latency EWMA (a ~1 ms refusal would make
    it look fast) — while real transport 503s still count as faults."""
    from seldon_core_tpu.gateway.apife import ApiGateway
    from seldon_core_tpu.messages import LoadShedError
    from seldon_core_tpu.runtime.autopilot import SHED_INFO_PREFIX

    shed = SeldonMessage.failure(
        f"{SHED_INFO_PREFIX}: predicted 12.0 ms exceeds 4.0 ms", code=503
    )
    transport = SeldonMessage.failure("bad gateway", code=503)
    assert ApiGateway._is_autopilot_shed(shed)
    assert not ApiGateway._is_autopilot_shed(transport)
    assert not ApiGateway._replica_fault(shed)
    assert ApiGateway._replica_fault(transport)
    # the engine's raise site really does produce the recognized prefix
    assert str(LoadShedError(f"{SHED_INFO_PREFIX}: x")).startswith(
        SHED_INFO_PREFIX
    )


def test_flush_plan_shorter_prefix_same_bucket_feasible():
    """Two prefixes landing in the SAME pad bucket differ only in their
    tightest deadline — the shorter, feasible one must not be shadowed
    by the longer, infeasible one (both pad to 8; the second request's
    5 ms budget cannot fit the 20 ms wall, the first alone can)."""
    mb = MicroBatcher(
        _noop_batch, max_batch=64, predict_s_fn=lambda p, x: 0.020,
    )
    clock = [0.0]
    wide = Deadline(0.050, clock=lambda: clock[0])
    tight = Deadline(0.005, clock=lambda: clock[0])
    bucket = deque([
        _entry(5, deadline=wide), _entry(2, deadline=tight),
    ])
    k, predicted = mb._plan_flush(bucket)
    assert k == 1
    assert predicted == pytest.approx(0.020)


def test_autopilot_metric_families_exported():
    for family in (
        "seldon_tpu_autopilot_decisions_total",
        "seldon_tpu_autopilot_shed_total",
        "seldon_tpu_autopilot_mispredict_pct",
        "seldon_tpu_autopilot_keys",
    ):
        assert family in TPU_METRIC_FAMILIES
    before_shed = dict(RECORDER.autopilot_sheds)
    before_dec = dict(RECORDER.autopilot_decisions)
    RECORDER.record_autopilot_shed("admission")
    RECORDER.record_autopilot_decision("flush")
    RECORDER.set_autopilot_model(mispredict_p50_pct=12.5, keys=3)
    snap = RECORDER.snapshot()["autopilot"]
    assert snap["sheds"]["admission"] == before_shed.get("admission", 0) + 1
    assert snap["decisions"]["flush"] == before_dec.get("flush", 0) + 1
    assert snap["mispredict_p50_pct"] == 12.5
    assert snap["keys"] == 3
    if RECORDER.registry is not None:
        from seldon_core_tpu.utils.metrics import MetricsRegistry

        text = MetricsRegistry(deployment_name="t").exposition().decode()
        for family in (
            "seldon_tpu_autopilot_shed_total",
            "seldon_tpu_autopilot_mispredict_pct",
            "seldon_tpu_autopilot_keys",
        ):
            assert family in text
