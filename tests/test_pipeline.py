"""Pipeline parallelism (pp axis): GPipe schedule numerics vs the dense
single-device forward, and a full pipelined train step over dp x pp.

The reference has no model parallelism (SURVEY.md §2.7); these tests pin the
TPU-native pipeline layer the framework adds on top of parity.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from seldon_core_tpu.models.transformer import (
    LMConfig,
    lm_apply,
    lm_init,
    lm_loss,
    lm_pipeline_apply,
    lm_pipeline_loss,
    lm_pipeline_params,
    lm_pipeline_train_step,
)
from seldon_core_tpu.parallel.mesh import build_mesh
from seldon_core_tpu.parallel.pipeline import (
    merge_microbatches,
    pipeline_apply,
    split_microbatches,
    stack_stage_params,
    stage_param_shardings,
)

CFG = LMConfig(vocab=32, d_model=16, n_heads=2, n_layers=4, d_ff=32,
               dtype=jnp.float32)


def _tokens(rng, b, s):
    return jnp.asarray(rng.integers(0, CFG.vocab, size=(b, s)), jnp.int32)


def test_generic_pipeline_matches_sequential(devices8):
    """A 4-stage elementwise-affine pipeline == composing the stages."""
    mesh = build_mesh({"pp": 4}, devices=devices8[:4])
    rng = np.random.default_rng(0)
    per_stage = [
        {"w": jnp.asarray(rng.normal(size=(8,)), jnp.float32),
         "b": jnp.asarray(rng.normal(size=(8,)), jnp.float32)}
        for _ in range(4)
    ]
    stacked = stack_stage_params(per_stage)
    stacked = jax.device_put(stacked, stage_param_shardings(mesh, stacked))

    def stage_fn(p, x):
        return jnp.tanh(x * p["w"] + p["b"])

    x = jnp.asarray(rng.normal(size=(6, 3, 8)), jnp.float32)  # [n_micro,mb,F]
    y = jax.jit(
        lambda s, xm: pipeline_apply(stage_fn, s, xm, mesh=mesh,
                                     batch_axis=None)
    )(stacked, x)

    expect = x
    for p in per_stage:
        expect = stage_fn(p, expect)
    np.testing.assert_allclose(np.asarray(y), np.asarray(expect), atol=1e-6)


def test_micro_split_merge_roundtrip():
    x = jnp.arange(24.0).reshape(12, 2)
    m = split_microbatches(x, 4)
    assert m.shape == (4, 3, 2)
    np.testing.assert_array_equal(np.asarray(merge_microbatches(m)),
                                  np.asarray(x))
    with pytest.raises(ValueError):
        split_microbatches(x, 5)


def test_pipelined_lm_forward_matches_dense(devices8):
    mesh = build_mesh({"dp": 2, "pp": 4}, devices=devices8)
    rng = np.random.default_rng(1)
    params = lm_init(jax.random.key(0), CFG)
    tokens = _tokens(rng, 8, 12)

    ref = lm_apply(params, tokens, CFG)
    pp_params = lm_pipeline_params(params, CFG, 4, mesh)
    got = jax.jit(
        lambda p, t: lm_pipeline_apply(p, t, CFG, mesh, n_micro=4)
    )(pp_params, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


@pytest.mark.slow  # heavyweight equivalence check: full-suite/CI-shard coverage; excluded from the tier-1 time budget
def test_pipelined_train_step_matches_dense_loss(devices8):
    mesh = build_mesh({"dp": 2, "pp": 2}, devices=devices8[:4])
    rng = np.random.default_rng(2)
    params = lm_init(jax.random.key(3), CFG)
    batch = {"tokens": _tokens(rng, 4, 13)}

    dense_loss = float(lm_loss(params, batch, CFG))
    pp_params = lm_pipeline_params(params, CFG, 2, mesh)
    assert float(lm_pipeline_loss(pp_params, batch, CFG, mesh,
                                  n_micro=2)) == pytest.approx(
        dense_loss, abs=1e-4
    )

    opt = optax.adam(1e-3)
    opt_state = opt.init(pp_params)
    step = jax.jit(
        lambda p, o, b: lm_pipeline_train_step(p, o, b, opt, CFG, mesh,
                                               n_micro=2)
    )
    p1, _, loss1 = step(pp_params, opt_state, batch)
    assert np.isfinite(float(loss1))
    # a second step on the updated params must change the loss (grads flowed
    # through the ppermute schedule into every stage's weights)
    _, _, loss2 = step(p1, opt.init(p1), batch)
    assert float(loss2) < float(loss1)


def test_stage_count_mesh_mismatch_rejected(devices8):
    """4 stacked stages on a pp=2 mesh must fail loudly, not drop stages."""
    mesh = build_mesh({"pp": 2}, devices=devices8[:2])
    params = lm_init(jax.random.key(5), CFG)
    with pytest.raises(ValueError, match="stacked stage dim"):
        pp_params = lm_pipeline_params(params, CFG, 4, mesh)
        lm_pipeline_apply(pp_params, _tokens(np.random.default_rng(4), 4, 8),
                          CFG, mesh, n_micro=2)


def test_single_stage_degenerate():
    params = lm_init(jax.random.key(4), CFG)
    mesh = build_mesh({"pp": 1}, devices=jax.devices()[:1])
    rng = np.random.default_rng(3)
    tokens = _tokens(rng, 4, 8)
    pp_params = lm_pipeline_params(params, CFG, 1, mesh)
    got = lm_pipeline_apply(pp_params, tokens, CFG, mesh, n_micro=2)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(lm_apply(params, tokens, CFG)),
        atol=2e-4, rtol=2e-4,
    )
