"""Flash-attention kernel numerics vs plain XLA attention (interpret mode on
the CPU test platform; the TPU path compiles the same kernel)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from seldon_core_tpu.ops.flash_attention import flash_attention


def _xla_attention(q, k, v, causal):
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        qpos = jnp.arange(q.shape[2])[:, None]
        kpos = jnp.arange(k.shape[2])[None, :]
        s = jnp.where(qpos >= kpos, s, -1e30)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)


def _rand(shape, seed):
    return jnp.asarray(
        np.random.default_rng(seed).normal(size=shape), jnp.float32
    )


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_xla(causal):
    B, H, S, D = 2, 2, 256, 64
    q, k, v = (_rand((B, H, S, D), i) for i in range(3))
    got = flash_attention(q, k, v, causal=causal, interpret=True)
    ref = _xla_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_multiblock_online_softmax():
    """S = 3 blocks forces cross-block max/normaliser carries."""
    B, H, S, D = 1, 1, 384, 32
    q, k, v = (_rand((B, H, S, D), 10 + i) for i in range(3))
    got = flash_attention(q, k, v, causal=True, interpret=True)
    ref = _xla_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_constraint_errors():
    q = jnp.zeros((1, 1, 100, 64))  # S not multiple of 128
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, q, q)
    q = jnp.zeros((1, 1, 128, 512))  # head dim too large
    with pytest.raises(ValueError, match="head dim"):
        flash_attention(q, q, q)
    with pytest.raises(ValueError, match="shapes differ"):
        flash_attention(
            jnp.zeros((1, 1, 128, 64)), jnp.zeros((1, 1, 128, 32)),
            jnp.zeros((1, 1, 128, 64)),
        )
