"""Flash-attention kernel numerics vs plain XLA attention (interpret mode on
the CPU test platform; the TPU path compiles the same kernel)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from seldon_core_tpu.ops.flash_attention import flash_attention


def _xla_attention(q, k, v, causal):
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        qpos = jnp.arange(q.shape[2])[:, None]
        kpos = jnp.arange(k.shape[2])[None, :]
        s = jnp.where(qpos >= kpos, s, -1e30)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)


def _rand(shape, seed):
    return jnp.asarray(
        np.random.default_rng(seed).normal(size=shape), jnp.float32
    )


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_xla(causal):
    B, H, S, D = 2, 2, 256, 64
    q, k, v = (_rand((B, H, S, D), i) for i in range(3))
    got = flash_attention(q, k, v, causal=causal, interpret=True)
    ref = _xla_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_multiblock_online_softmax():
    """S = 3 blocks forces cross-block max/normaliser carries."""
    B, H, S, D = 1, 1, 384, 32
    q, k, v = (_rand((B, H, S, D), 10 + i) for i in range(3))
    got = flash_attention(q, k, v, causal=True, interpret=True)
    ref = _xla_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_constraint_errors():
    q = jnp.zeros((1, 1, 100, 64))  # S not multiple of 128
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, q, q)
    q = jnp.zeros((1, 1, 128, 512))  # head dim too large
    with pytest.raises(ValueError, match="head dim"):
        flash_attention(q, q, q)
    with pytest.raises(ValueError, match="shapes differ"):
        flash_attention(
            jnp.zeros((1, 1, 128, 64)), jnp.zeros((1, 1, 128, 32)),
            jnp.zeros((1, 1, 128, 64)),
        )


@pytest.mark.parametrize("causal", [True, False])
def test_flash_vjp_matches_xla(causal):
    """Flash backward (recompute-from-lse, two-pass dQ / dKV) vs the XLA
    attention VJP, f32, multi-block so accumulator carries are exercised."""
    B, H, S, D = 1, 2, 384, 32
    q, k, v = (_rand((B, H, S, D), 20 + i) for i in range(3))
    co = _rand((B, H, S, D), 99)  # cotangent

    def flash_loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, interpret=True) * co)

    def xla_loss(q, k, v):
        return jnp.sum(_xla_attention(q, k, v, causal) * co)

    got = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    ref = jax.grad(xla_loss, argnums=(0, 1, 2))(q, k, v)
    for g, r, name in zip(got, ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(r), atol=3e-4, rtol=3e-4,
            err_msg=f"d{name} mismatch",
        )


def test_flash_vjp_under_jit_and_value_and_grad():
    """The custom VJP composes with jit and value_and_grad (the lm_train_step
    usage shape)."""
    B, H, S, D = 1, 1, 256, 64
    q, k, v = (_rand((B, H, S, D), 30 + i) for i in range(3))

    @jax.jit
    def f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, interpret=True) ** 2)

    val, grads = jax.value_and_grad(f, argnums=(0, 1, 2))(q, k, v)
    ref = jnp.sum(_xla_attention(q, k, v, True) ** 2)
    np.testing.assert_allclose(float(val), float(ref), rtol=1e-5)
    assert all(g.shape == q.shape for g in grads)


@pytest.mark.slow  # heavyweight equivalence check: full-suite/CI-shard coverage; excluded from the tier-1 time budget
def test_lm_train_step_with_flash_matches_xla_attention():
    """lm_train_step(use_flash=True) (flash VJP, interpret-mode pallas via
    monkeypatched interpret default is not available here, so call the loss
    directly) must produce the same gradients as the XLA attention path."""
    import optax

    from seldon_core_tpu.models.transformer import LMConfig, lm_init, lm_loss

    cfg = LMConfig(vocab=64, d_model=64, n_heads=2, n_layers=1, d_ff=128,
                   dtype=jnp.float32)
    params = lm_init(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 129), 0, 64)
    batch = {"tokens": tokens}

    # S=128 satisfies the flash constraint; interpret mode is selected inside
    # flash_attention only via the arg, so patch it through _attention by
    # running on CPU where pallas interpret is implied not available ->
    # instead compare the pure loss fns explicitly
    import seldon_core_tpu.models.transformer as T

    orig = T._attention

    def flash_forced(q, k, v, mesh, causal, use_flash=False):
        if use_flash:
            return flash_attention(q, k, v, causal=causal, interpret=True)
        return orig(q, k, v, mesh, causal, use_flash=False)

    T._attention = flash_forced
    try:
        g_flash = jax.grad(
            lambda p: lm_loss(p, batch, cfg, use_flash=True)
        )(params)
        g_xla = jax.grad(
            lambda p: lm_loss(p, batch, cfg, use_flash=False)
        )(params)
    finally:
        T._attention = orig
    flat_f, _ = jax.tree_util.tree_flatten(g_flash)
    flat_x, _ = jax.tree_util.tree_flatten(g_xla)
    for a, b in zip(flat_f, flat_x):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_auto_gate_thresholds_route_as_measured():
    """The auto-mode thresholds are measurement-pinned (v5e round 4):
    grouped K/V takes the kernel from FLASH_AUTO_MIN_S_GQA (512) up, MHA
    from FLASH_AUTO_MIN_S (4096); "force" overrides everywhere."""
    import sys

    import seldon_core_tpu.ops.flash_attention  # noqa: F401 - for sys.modules
    import seldon_core_tpu.models.transformer as T

    fa = sys.modules["seldon_core_tpu.ops.flash_attention"]
    calls = []
    real = fa.flash_attention

    def spy(q, k, v, causal=True, interpret=False):
        calls.append(tuple(q.shape))
        return real(q, k, v, causal, True)

    def route(B, H, KV, S, use_flash):
        calls.clear()
        q = jnp.zeros((B, H, S, 64), jnp.float32)
        k = jnp.zeros((B, KV, S, 64), jnp.float32)
        T._attention(q, k, k, None, causal=True, use_flash=use_flash)
        return bool(calls)

    fa.flash_attention = spy
    try:
        assert route(1, 8, 2, 512, True), "GQA S=512 -> kernel"
        assert not route(1, 8, 2, 256, True), "GQA S=256 -> XLA"
        assert not route(1, 4, 4, 2048, True), "MHA S=2048 -> XLA"
        assert route(1, 4, 4, 4096, True), "MHA S=4096 -> kernel"
        assert route(1, 4, 4, 256, "force"), "force overrides the gate"
    finally:
        fa.flash_attention = real
