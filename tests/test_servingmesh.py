"""Disaggregated prefill/decode serving mesh (runtime/servingmesh.py +
runtime/kvstream.py + the genserver role/import machinery).

The load-bearing contracts:

* disaggregated generation (prefill replica -> KV-block stream over the
  relay -> decode replica) is TOKEN-IDENTICAL to the unified scheduler
  for the same seeds — greedy f32 and the int8-KV arm;
* a torn handoff reclaims every reserved block (pool occupancy returns
  to baseline; the TTL reaper covers a sender that just vanishes);
* role misconfigs answer typed 503s (generation at a decode-only
  replica, a handoff at a non-decode replica, prefill with no peers);
* ``SELDON_TPU_DISAGG=0`` restores the unified path bit-for-bit;
* reserved import blocks can never be picked as eviction victims and
  pinned shared-prefix blocks can never be freed;
* tensor-parallel dispatch: the scheduler's compiled executables over a
  ≥2-device mesh produce the same tokens as the single-device path.
"""

import asyncio
import json
import os
import tempfile
import threading
import time
import uuid

import numpy as np
import pytest

from seldon_core_tpu.graph.spec import SeldonDeploymentSpec
from seldon_core_tpu.messages import LoadShedError
from seldon_core_tpu.models.generate import TransformerGenerator
from seldon_core_tpu.runtime import kvstream
from seldon_core_tpu.runtime.engine import EngineService
from seldon_core_tpu.runtime.genserver import BlockAllocator, GenServer
from seldon_core_tpu.runtime.servingmesh import (
    DisaggCoordinator,
    HandoffError,
    RoleMismatchError,
    resolve_gen_role,
)
from seldon_core_tpu.runtime.udsrelay import serve_uds


def _unit(**overrides):
    kw = dict(vocab=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
              max_new_tokens=16, dtype="float32", eos_token=-1)
    kw.update(overrides)
    return TransformerGenerator(**kw)


def _genserver(unit=None, role="unified", coordinator=None, **kw):
    unit = unit or _unit()
    state = unit.init_state(None)
    cs = unit.continuous_spec(state)
    defaults = dict(num_blocks=64, block_size=4, span=4, prefill_chunk=8)
    defaults.update(kw)
    return GenServer(**cs, role=role, coordinator=coordinator, **defaults)


class LoopbackCoordinator:
    """In-process handoff driver that still exercises the REAL wire
    format (serialize -> parse on every frame) against a decode
    GenServer — the relay minus the socket."""

    def __init__(self, decode_gs, chunk=2):
        self.decode = decode_gs
        self.chunk = chunk

    def submit(self, export, done_cb):
        threading.Thread(
            target=self._run, args=(export, done_cb), daemon=True
        ).start()

    def _run(self, export, done_cb):
        hid = uuid.uuid4().bytes
        try:
            _, h, body = kvstream.parse_frame(
                kvstream.begin_frame(export, hid))
            self.decode.kv_reserve(h, kvstream.parse_begin(body))
            for fr in kvstream.block_frames(export, hid, self.chunk):
                _, h2, b2 = kvstream.parse_frame(fr)
                imp = self.decode._imports[h2]
                first, layers = kvstream.parse_blocks(b2, imp.meta)
                self.decode.kv_receive(h2, first, layers)
            req = self.decode.kv_commit(h)
            done_cb(np.asarray(req.future.result(timeout=120))[0])
        except BaseException as e:  # noqa: BLE001 - surfaced per request
            done_cb(e)

    def close(self):
        pass

    def snapshot(self):
        return {"loopback": True}

    def chain_estimate_s(self):
        return None


_PROMPT = (np.arange(22) % 13 + 1).reshape(1, -1)


def _wait_blocks_freed(gs, timeout_s=10.0):
    """Assert every KV block recycles, tolerating the retire step that
    may run a scheduler tick after the request future resolves."""
    deadline = time.monotonic() + timeout_s
    while gs._allocator.used != 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert gs._allocator.used == 0


# -- export/import round trip -------------------------------------------

def test_disagg_token_identical_greedy_f32():
    unified = _genserver()
    decode = _genserver(role="decode")
    prefill = _genserver(role="prefill",
                         coordinator=LoopbackCoordinator(decode))
    try:
        y0 = unified.submit(_PROMPT).future.result(timeout=120)
        y1 = prefill.submit(_PROMPT).future.result(timeout=120)
        np.testing.assert_array_equal(y0, y1)
        assert prefill.retired_total.get("handoff") == 1
        assert decode.imports_committed_total == 1
        # block recycling: the client future can resolve from the
        # scheduler's per-step delivery a tick BEFORE _retire_finished
        # releases the sequence's blocks — poll briefly instead of
        # racing the scheduler thread (flaked under full-suite load)
        _wait_blocks_freed(prefill)
        _wait_blocks_freed(decode)  # retired decode freed them
    finally:
        unified.stop()
        prefill.stop()
        decode.stop()


def test_disagg_token_identical_int8_kv():
    unit_kw = dict(kv_quant="int8")
    unified = _genserver(_unit(**unit_kw))
    decode = _genserver(_unit(**unit_kw), role="decode")
    prefill = _genserver(
        _unit(**unit_kw), role="prefill",
        coordinator=LoopbackCoordinator(decode))
    try:
        y0 = unified.submit(_PROMPT).future.result(timeout=120)
        y1 = prefill.submit(_PROMPT).future.result(timeout=120)
        np.testing.assert_array_equal(y0, y1)
        assert decode.imports_committed_total == 1
    finally:
        unified.stop()
        prefill.stop()
        decode.stop()


def test_disagg_multi_request_streams_match_unified():
    """Several co-scheduled requests hand off independently and every
    stream concatenates to the unified answer."""
    unified = _genserver()
    decode = _genserver(role="decode")
    prefill = _genserver(role="prefill",
                         coordinator=LoopbackCoordinator(decode))
    try:
        prompts = [(np.arange(10 + 3 * i) % 17 + 1).reshape(1, -1)
                   for i in range(3)]
        want = [unified.submit(p).future.result(timeout=120)
                for p in prompts]
        reqs = [prefill.submit(p) for p in prompts]
        got = [r.future.result(timeout=120) for r in reqs]
        for w, g in zip(want, got):
            np.testing.assert_array_equal(w, g)
        assert prefill.retired_total.get("handoff") == 3
    finally:
        unified.stop()
        prefill.stop()
        decode.stop()


# -- torn handoffs -------------------------------------------------------

def _export_for(gs, prompt):
    """Run a prefill-role GenServer up to the export (capturing it
    instead of handing off) — gives tests a real KvExport plus the
    pending request and the completion callback."""
    captured = {}

    class Capture:
        def submit(self, export, done_cb):
            captured["export"] = export
            captured["done"] = done_cb

        def close(self):
            pass

        def snapshot(self):
            return {}

        def chain_estimate_s(self):
            return None

    gs.coordinator = Capture()
    captured["req"] = gs.submit(prompt)
    deadline = time.monotonic() + 60
    while "export" not in captured and time.monotonic() < deadline:
        time.sleep(0.01)
    assert "export" in captured, "prefill never exported"
    return captured


def test_torn_handoff_reclaims_all_blocks():
    decode = _genserver(role="decode")
    prefill = _genserver(role="prefill")
    try:
        captured = _export_for(prefill, _PROMPT)
        export = captured["export"]
        hid = uuid.uuid4().bytes
        decode.kv_reserve(hid, export.meta)
        snap = decode._allocator.snapshot()
        assert snap["reserved"] == export.meta.n_blocks
        baseline_used = snap["used"] - snap["reserved"]
        # stream ONE chunk, then tear the handoff
        frame = next(iter(kvstream.block_frames(export, hid, 2)))
        _, h2, b2 = kvstream.parse_frame(frame)
        first, layers = kvstream.parse_blocks(b2, export.meta)
        decode.kv_receive(hid, first, layers)
        assert decode.kv_abort(hid) is True
        snap = decode._allocator.snapshot()
        assert snap["reserved"] == 0
        assert snap["used"] == baseline_used  # zero leaked blocks
        assert decode.imports_reclaimed_total == 1
        # the abandoned prefill request fails typed + retryable once the
        # coordinator reports the tear back
        captured["done"](HandoffError("torn mid-stream"))
        with pytest.raises(HandoffError):
            captured["req"].future.result(timeout=60)
    finally:
        prefill.stop()
        decode.stop()


def test_commit_before_all_blocks_is_torn_and_reclaims():
    decode = _genserver(role="decode")
    prefill = _genserver(role="prefill")
    try:
        export = _export_for(prefill, _PROMPT)["export"]
        hid = uuid.uuid4().bytes
        decode.kv_reserve(hid, export.meta)
        with pytest.raises(kvstream.KvWireError, match="torn"):
            decode.kv_commit(hid)
        assert decode._allocator.snapshot()["reserved"] == 0
        assert decode.imports_reclaimed_total == 1
    finally:
        prefill.stop()
        decode.stop()


def test_ttl_reaper_reclaims_stale_import():
    decode = _genserver(role="decode")
    prefill = _genserver(role="prefill")
    try:
        export = _export_for(prefill, _PROMPT)["export"]
        hid = uuid.uuid4().bytes
        decode.kv_reserve(hid, export.meta)
        decode._import_ttl_s = 0.05  # shrink the TTL for the test
        baseline = decode._allocator.snapshot()
        assert baseline["reserved"] > 0
        time.sleep(0.1)
        # any traffic tick runs the reaper; poke the scheduler directly
        with decode._wake:
            decode._wake.notify_all()
        deadline = time.monotonic() + 10
        while decode.imports_reclaimed_total == 0 \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        snap = decode._allocator.snapshot()
        assert decode.imports_reclaimed_total == 1
        assert snap["reserved"] == 0
        assert snap["used"] == 0  # high-water only; occupancy back
    finally:
        prefill.stop()
        decode.stop()


def test_commit_racing_ttl_reap_answers_typed_not_corrupt():
    """A COMMIT landing after the reaper reclaimed the reservation must
    answer 'unknown or expired' — never admit a sequence onto blocks
    that went back to the free list (the claim is an atomic pop)."""
    decode = _genserver(role="decode")
    prefill = _genserver(role="prefill")
    try:
        export = _export_for(prefill, _PROMPT)["export"]
        hid = uuid.uuid4().bytes
        decode.kv_reserve(hid, export.meta)
        for fr in kvstream.block_frames(export, hid, 2):
            _, h2, b2 = kvstream.parse_frame(fr)
            first, layers = kvstream.parse_blocks(b2, export.meta)
            decode.kv_receive(h2, first, layers)
        # the reaper wins the race (simulated: same pop-first claim)
        imp = decode._imports.pop(hid)
        decode._allocator.release_reserved(imp.blocks)
        with pytest.raises(kvstream.KvWireError, match="unknown"):
            decode.kv_commit(hid)
        assert not decode._remote_arrivals
    finally:
        prefill.stop()
        decode.stop()


def test_stop_fails_requests_with_handoff_in_flight():
    """A request whose handoff sits at the coordinator when the
    scheduler stops must fail typed — not hang its awaiting client
    forever (it lives in no scheduler list)."""
    prefill = _genserver(role="prefill")
    captured = _export_for(prefill, _PROMPT)  # handoff parked, never done
    prefill.stop()
    with pytest.raises(RuntimeError, match="stopped"):
        captured["req"].future.result(timeout=30)


def test_fail_all_releases_committed_import_reservations():
    """A committed-but-not-yet-admitted import still holds RESERVED
    blocks; a scheduler failure between commit and admission must
    release them (a leak here shrinks the pool permanently)."""
    decode = _genserver(role="decode")
    prefill = _genserver(role="prefill")
    try:
        export = _export_for(prefill, _PROMPT)["export"]
        hid = uuid.uuid4().bytes
        decode.kv_reserve(hid, export.meta)
        for fr in kvstream.block_frames(export, hid, 2):
            _, h2, b2 = kvstream.parse_frame(fr)
            first, layers = kvstream.parse_blocks(b2, export.meta)
            decode.kv_receive(h2, first, layers)
        req = decode.kv_commit(hid)
        # simulate a tick failure before _import_admit ran: grab the
        # committed import back out of the arrivals queue first so the
        # scheduler can't admit it under us
        deadline = time.monotonic() + 30
        while decode._remote_arrivals and time.monotonic() < deadline:
            decode._fail_all(RuntimeError("boom"))
            break
        # whether _fail_all or admission won, no reservation may remain
        deadline = time.monotonic() + 30
        while (decode._allocator.snapshot()["reserved"] > 0
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert decode._allocator.snapshot()["reserved"] == 0
        # and the request surface resolved one way or the other
        try:
            req.future.result(timeout=60)
        except RuntimeError:
            pass
    finally:
        prefill.stop()
        decode.stop()


# -- role misconfig / kill switch ---------------------------------------

def test_generation_at_decode_replica_is_typed_503():
    decode = _genserver(role="decode")
    try:
        with pytest.raises(RoleMismatchError) as ei:
            decode.submit(_PROMPT)
        assert ei.value.http_code == 503
    finally:
        decode.stop()


def test_prefill_without_peers_fails_typed():
    prefill = _genserver(role="prefill")  # no coordinator
    try:
        req = prefill.submit(_PROMPT)
        with pytest.raises(HandoffError):
            req.future.result(timeout=60)
    finally:
        prefill.stop()


def test_kill_switch_forces_unified_role(monkeypatch):
    monkeypatch.setenv("SELDON_TPU_DISAGG", "0")
    assert resolve_gen_role("prefill") == "unified"
    assert resolve_gen_role("decode") == "unified"
    monkeypatch.delenv("SELDON_TPU_DISAGG")
    assert resolve_gen_role("prefill") == "prefill"


# -- allocator audit (satellite: pin vs eviction vs import) -------------

def test_reserved_blocks_refused_by_free_and_invisible_to_eviction():
    alloc = BlockAllocator(16)
    owned = alloc.alloc(5)
    reserved = alloc.reserve(4)
    # free() must refuse reserved ids — a confused caller cannot return
    # an in-flight import's blocks to the pool
    alloc.free(reserved)
    assert alloc.snapshot()["reserved"] == 4
    assert alloc.used == 9
    # a full drain of owned blocks leaves the reservation intact
    alloc.free(owned)
    assert alloc.used == 4
    got = alloc.alloc(11)
    assert got is not None and not set(got) & set(reserved)
    alloc.free(got)
    alloc.release_reserved(reserved)
    assert alloc.used == 0
    # double release is harmless
    alloc.release_reserved(reserved)
    assert alloc.used == 0


def test_pinned_blocks_never_freed():
    alloc = BlockAllocator(8)
    blocks = alloc.alloc(3)
    alloc.pin(blocks[:2])
    alloc.free(blocks)
    # the two pinned blocks stay resident forever
    assert alloc.used == 2
    assert alloc.snapshot()["pinned"] == 2


def test_eviction_pressure_never_touches_reserved_import():
    """A decode replica under pool pressure (local sequences evicting
    each other) must never reclaim an in-flight import's reservation —
    the committed sequence decodes token-identically afterwards."""
    unified = _genserver(num_blocks=20)
    decode = _genserver(role="decode", num_blocks=20, slots=2)
    prefill = _genserver(role="prefill", num_blocks=20)
    try:
        want = unified.submit(_PROMPT).future.result(timeout=120)
        export = _export_for(prefill, _PROMPT)["export"]
        hid = uuid.uuid4().bytes
        decode.kv_reserve(hid, export.meta)
        reserved = set(decode._imports[hid].blocks)
        # churn the decode replica's own pool around the reservation:
        # these long generations force eviction pressure in a 19-block
        # pool missing 6 reserved blocks
        churn = [(np.arange(12) % 7 + 1).reshape(1, -1) for _ in range(3)]
        churn_reqs = [decode_submit_local(decode, p) for p in churn]
        for r in churn_reqs:
            r.future.result(timeout=120)
        assert set(decode._imports[hid].blocks) == reserved
        assert decode._allocator.snapshot()["reserved"] == len(reserved)
        # now finish the import: content untouched => tokens identical
        for fr in kvstream.block_frames(export, hid, 2):
            _, h2, b2 = kvstream.parse_frame(fr)
            first, layers = kvstream.parse_blocks(b2, export.meta)
            decode.kv_receive(h2, first, layers)
        req = decode.kv_commit(hid)
        got = np.asarray(req.future.result(timeout=120))
        np.testing.assert_array_equal(want, got)
    finally:
        unified.stop()
        prefill.stop()
        decode.stop()


def decode_submit_local(decode_gs, prompt):
    """Bypass the decode-role guard for test churn traffic: the guard is
    a routing contract, not a scheduler limitation."""
    real_role = decode_gs.role
    decode_gs.role = "unified"
    try:
        return decode_gs.submit(prompt)
    finally:
        decode_gs.role = real_role


# -- the wire format -----------------------------------------------------

def test_wire_roundtrip_preserves_meta_and_tensors():
    meta = kvstream.KvBeginMeta(
        n_layers=2, block_size=4, kv_heads=2, head_dim=16,
        dtype="float32", n_blocks=3, n_valid=9, pending=42, max_new=16,
        prefix_len=0, prompt=np.arange(9, dtype=np.int32),
        emitted=[42], key_data=np.asarray([1, 2, 3, 4], np.uint32),
        tier="batch",
    )
    rng = np.random.default_rng(0)
    layers = [
        {"k": rng.normal(size=(3, 4, 2, 16)).astype(np.float32),
         "v": rng.normal(size=(3, 4, 2, 16)).astype(np.float32)}
        for _ in range(2)
    ]
    export = kvstream.KvExport(meta=meta, layers=layers)
    hid = uuid.uuid4().bytes
    sub, h, body = kvstream.parse_frame(kvstream.begin_frame(export, hid))
    assert (sub, h) == (kvstream.KV_BEGIN, hid)
    got = kvstream.parse_begin(body)
    assert (got.n_layers, got.block_size, got.kv_heads, got.head_dim,
            got.dtype, got.n_blocks, got.n_valid, got.pending,
            got.max_new, got.tier) == (
        2, 4, 2, 16, "float32", 3, 9, 42, 16, "batch")
    np.testing.assert_array_equal(got.prompt, meta.prompt)
    assert got.emitted == [42]
    np.testing.assert_array_equal(got.key_data, meta.key_data)
    frames = list(kvstream.block_frames(export, hid, 2))
    assert len(frames) == 2  # 3 blocks at chunk 2
    staged = [
        {"k": np.zeros((3, 4, 2, 16), np.float32),
         "v": np.zeros((3, 4, 2, 16), np.float32)}
        for _ in range(2)
    ]
    for fr in frames:
        _, _, b = kvstream.parse_frame(fr)
        first, chunk = kvstream.parse_blocks(b, got)
        for stage, lay in zip(staged, chunk):
            for name, arr in lay.items():
                stage[name][first:first + arr.shape[0]] = arr
    for stage, lay in zip(staged, layers):
        np.testing.assert_array_equal(stage["k"], lay["k"])
        np.testing.assert_array_equal(stage["v"], lay["v"])
    # tokens + stats helpers
    toks = np.arange(16, dtype=np.int32)
    np.testing.assert_array_equal(
        kvstream.unpack_tokens(kvstream.pack_tokens(toks)), toks)
    s = kvstream.unpack_stats(kvstream.pack_stats(10, 63, 1, 2))
    assert s == {"free": 10, "total": 63, "waiting": 1, "inflight": 2}


def test_geometry_mismatch_refused_typed():
    decode = _genserver(role="decode")
    prefill = _genserver(role="prefill")
    try:
        export = _export_for(prefill, _PROMPT)["export"]
        bad = kvstream.KvBeginMeta(
            **{**export.meta.__dict__, "kv_heads": 7})
        with pytest.raises(kvstream.KvWireError, match="geometry"):
            decode.kv_reserve(uuid.uuid4().bytes, bad)
        assert decode._allocator is not None
        assert decode._allocator.snapshot()["reserved"] == 0
    finally:
        prefill.stop()
        decode.stop()


def test_pool_full_reserve_sheds_typed_retryable():
    decode = _genserver(role="decode", num_blocks=4)  # 3 usable blocks
    prefill = _genserver(role="prefill")
    try:
        export = _export_for(prefill, _PROMPT)["export"]
        assert export.meta.n_blocks > 3
        with pytest.raises(LoadShedError):
            decode.kv_reserve(uuid.uuid4().bytes, export.meta)
    finally:
        prefill.stop()
        decode.stop()


# -- the full relay stack (engines + coordinator + UDS) ------------------

def _gen_spec():
    return SeldonDeploymentSpec.from_json_dict({
        "spec": {"name": "d", "predictors": [{
            "name": "p",
            "graph": {"name": "gen", "type": "MODEL"},
            "components": [{
                "name": "gen", "runtime": "inprocess",
                "class_path": "TransformerGenerator",
                "parameters": [
                    {"name": "vocab", "value": "64", "type": "INT"},
                    {"name": "d_model", "value": "32", "type": "INT"},
                    {"name": "n_heads", "value": "2", "type": "INT"},
                    {"name": "n_layers", "value": "2", "type": "INT"},
                    {"name": "d_ff", "value": "64", "type": "INT"},
                    {"name": "max_new_tokens", "value": "16",
                     "type": "INT"},
                    {"name": "dtype", "value": "float32",
                     "type": "STRING"},
                ],
            }],
        }]}
    })


def test_disagg_over_uds_relay_token_identical_and_kill_switch():
    """The acceptance path: 1 prefill + 1 decode EngineService over a
    real UDS relay produce byte-identical predictions to a unified
    engine, the /stats surfaces show the handoff, role misconfig
    answers 503, and SELDON_TPU_DISAGG=0 restores unified bit-for-bit."""
    sock = os.path.join(tempfile.mkdtemp(prefix="seldon-kv-"),
                        "decode.sock")
    decode_engine = EngineService(_gen_spec(), gen_role="decode")
    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()
    server = asyncio.run_coroutine_threadsafe(
        serve_uds(decode_engine, sock), loop).result(10)
    prefill_engine = EngineService(
        _gen_spec(), gen_role="prefill", decode_peers=[f"uds:{sock}"])
    unified_engine = EngineService(_gen_spec())
    payload = json.dumps({"data": {"ndarray": [list(range(1, 23))]}})
    try:
        t0, s0 = asyncio.run(unified_engine.predict_json(payload))
        t1, s1 = asyncio.run(prefill_engine.predict_json(payload))
        assert s0 == 200 and s1 == 200
        a0 = np.asarray(json.loads(t0)["data"]["ndarray"])
        a1 = np.asarray(json.loads(t1)["data"]["ndarray"])
        np.testing.assert_array_equal(a0, a1)
        # the handoff is visible on both /stats surfaces
        disagg = prefill_engine.genserver.snapshot()["disagg"]
        assert disagg["handoffs"].get("ok") == 1
        assert disagg["bytes_per_tok"] > 0
        assert disagg["handoff_ms_p50"] > 0
        imports = decode_engine.genserver.snapshot()["imports"]
        assert imports["committed_total"] == 1
        # role misconfig: a client generation at the decode replica
        t2, s2 = asyncio.run(decode_engine.predict_json(payload))
        assert s2 == 503 and "decode-only" in t2
        # a handoff BEGIN at a non-decode replica answers 503 typed
        export_frame = kvstream.begin_frame(
            kvstream.KvExport(meta=kvstream.KvBeginMeta(
                n_layers=2, block_size=4, kv_heads=2, head_dim=16,
                dtype="float32", n_blocks=1, n_valid=4, pending=1,
                max_new=4, prefix_len=0,
                prompt=np.arange(4, dtype=np.int32), emitted=[1],
                key_data=None), layers=[]),
            uuid.uuid4().bytes)
        status, body = asyncio.run(unified_engine.kv_frame(export_frame))
        assert status == 503 and b"role misconfig" in body
        # kill switch: bit-for-bit unified
        os.environ["SELDON_TPU_DISAGG"] = "0"
        try:
            killed = EngineService(
                _gen_spec(), gen_role="prefill",
                decode_peers=[f"uds:{sock}"])
            assert killed.gen_role == "unified"
            t3, s3 = asyncio.run(killed.predict_json(payload))
            assert s3 == 200
            np.testing.assert_array_equal(
                np.asarray(json.loads(t3)["data"]["ndarray"]), a0)
            asyncio.run(killed.close())
        finally:
            os.environ.pop("SELDON_TPU_DISAGG", None)
    finally:
        asyncio.run_coroutine_threadsafe(server.stop(), loop).result(10)
        loop.call_soon_threadsafe(loop.stop)
        for e in (decode_engine, prefill_engine, unified_engine):
            asyncio.run(e.close())


def test_coordinator_p2c_prefers_freer_peer_and_walks_on_refusal():
    """Two decode peers over real UDS relays: one with a pool too small
    to ever accept the handoff.  The coordinator's free-block p2c
    prefers the big pool, and when the order lands on the tiny one its
    typed refusal walks to the next candidate — the handoff still
    lands."""
    tmp = tempfile.mkdtemp(prefix="seldon-kv-")
    small_sock = os.path.join(tmp, "small.sock")
    big_sock = os.path.join(tmp, "big.sock")
    small = _genserver(role="decode", num_blocks=4)
    big = _genserver(role="decode")

    class _Shim:
        """Engine-shaped wrapper the relay server dispatches into."""

        def __init__(self, gs):
            self.genserver = gs
            self.gen_role = gs.role

        async def kv_frame(self, payload):
            eng = EngineService.__new__(EngineService)
            eng.genserver = self.genserver
            return await EngineService.kv_frame(eng, payload)

    loop = asyncio.new_event_loop()
    threading.Thread(target=loop.run_forever, daemon=True).start()
    s1 = asyncio.run_coroutine_threadsafe(
        serve_uds(_Shim(small), small_sock), loop).result(10)
    s2 = asyncio.run_coroutine_threadsafe(
        serve_uds(_Shim(big), big_sock), loop).result(10)
    prefill = _genserver(role="prefill")
    coord = DisaggCoordinator(
        [f"uds:{small_sock}", f"uds:{big_sock}"])
    prefill.coordinator = coord
    try:
        unified = _genserver()
        want = unified.submit(_PROMPT).future.result(timeout=120)
        unified.stop()
        got = prefill.submit(_PROMPT).future.result(timeout=120)
        np.testing.assert_array_equal(want, got)
        assert big.imports_committed_total == 1
        assert small.imports_committed_total == 0
        snap = coord.snapshot()
        assert snap["handoffs"].get("ok") == 1
        # the free-block scrape saw both peers
        assert f"uds:{big_sock}" in snap["peer_free_blocks"]
    finally:
        asyncio.run_coroutine_threadsafe(s1.stop(), loop).result(10)
        asyncio.run_coroutine_threadsafe(s2.stop(), loop).result(10)
        loop.call_soon_threadsafe(loop.stop)
        prefill.stop()
        small.stop()
        big.stop()


# -- phase-aware routing at the gateway ---------------------------------

def test_endpoint_spec_role_suffix_parses():
    from seldon_core_tpu.gateway.balancer import ReplicaEndpoint

    ep = ReplicaEndpoint("http://h:1+role:prefill")
    assert ep.role == "prefill" and ep.base_url == "http://h:1"
    ep = ReplicaEndpoint("http://h:1+uds:/x.sock+role:decode")
    assert ep.role == "decode" and ep.uds_path == "/x.sock"
    # order-insensitive: +role: before +uds: must keep BOTH
    ep = ReplicaEndpoint("http://h:1+role:decode+uds:/x.sock")
    assert ep.role == "decode" and ep.uds_path == "/x.sock"
    assert ep.base_url == "http://h:1"
    ep = ReplicaEndpoint("http://h:1")
    assert ep.role == "unified"
    assert ep.snapshot()["role"] == "unified"


def test_gateway_pick_excludes_decode_replicas():
    from seldon_core_tpu.gateway.apife import _not_decode
    from seldon_core_tpu.gateway.balancer import ReplicaSet

    rs = ReplicaSet([
        "http://prefill-0:1+role:prefill",
        "http://decode-0:1+role:decode",
        "http://decode-1:1+role:decode",
    ])
    for _ in range(32):
        ep, _decision = rs.pick(eligible=_not_decode)
        assert ep.role != "decode"


def test_inprocess_endpoint_reads_engine_role():
    from seldon_core_tpu.gateway.balancer import ReplicaEndpoint

    class FakeEngine:
        gen_role = "decode"

        async def predict(self, msg):
            return msg

    assert ReplicaEndpoint(FakeEngine()).role == "decode"


# -- tensor-parallel dispatch -------------------------------------------

def test_mesh_sharded_scheduler_token_identical(devices8):
    """The scheduler's compiled prefill/decode executables over a tp=2
    mesh (params sharded by the unit, pool sharded by shard_gen_pool)
    produce the same tokens as the single-device path."""
    from seldon_core_tpu.parallel.mesh import MeshSpec, build_mesh

    single = _genserver()
    mesh = build_mesh(MeshSpec({"tp": 2}), devices=devices8[:2])
    meshed_unit = _unit(mesh=mesh)
    meshed = _genserver(meshed_unit)
    try:
        assert meshed.mesh is mesh
        y0 = single.submit(_PROMPT).future.result(timeout=180)
        y1 = meshed.submit(_PROMPT).future.result(timeout=180)
        np.testing.assert_array_equal(y0, y1)
        # the pool actually landed sharded over both devices
        k0 = meshed._pool["l0"]["k"]
        assert len(k0.sharding.device_set) == 2
        assert meshed.snapshot()["mesh"] == {"tp": 2}
    finally:
        single.stop()
        meshed.stop()


def test_mesh_disagg_composes(devices8):
    """Disaggregation + tensor-parallel dispatch: a mesh-sharded decode
    replica imports a single-device prefill's handoff token-identically
    (the wire format is host arrays — device layout is a local
    concern)."""
    from seldon_core_tpu.parallel.mesh import MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec({"tp": 2}), devices=devices8[:2])
    unified = _genserver()
    decode = _genserver(_unit(mesh=mesh), role="decode")
    prefill = _genserver(role="prefill",
                         coordinator=LoopbackCoordinator(decode))
    try:
        want = unified.submit(_PROMPT).future.result(timeout=180)
        got = prefill.submit(_PROMPT).future.result(timeout=180)
        np.testing.assert_array_equal(want, got)
    finally:
        unified.stop()
        prefill.stop()
        decode.stop()
