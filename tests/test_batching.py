"""Micro-batching tests: coalescing correctness, per-row tag slicing,
router graphs excluded."""

import asyncio
import json

import numpy as np
import pytest

from seldon_core_tpu.graph.spec import SeldonDeploymentSpec
from seldon_core_tpu.messages import SeldonMessage
from seldon_core_tpu.runtime.batching import MicroBatcher, graph_is_batchable
from seldon_core_tpu.runtime.engine import EngineService


def deployment(graph, components=None):
    return SeldonDeploymentSpec.from_json_dict(
        {
            "spec": {
                "name": "d",
                "predictors": [
                    {"name": "p", "graph": graph, "components": components or []}
                ],
            }
        }
    )


def test_graph_is_batchable():
    from seldon_core_tpu.graph.spec import PredictiveUnit, UnitType

    model = deployment({"name": "m", "implementation": "SIMPLE_MODEL", "type": "MODEL"})
    assert graph_is_batchable(model.predictor().graph)
    router = deployment(
        {
            "name": "r",
            "implementation": "RANDOM_ABTEST",
            "type": "ROUTER",
            "parameters": [{"name": "ratioA", "value": "0.5", "type": "FLOAT"}],
            "children": [
                {"name": "a", "implementation": "SIMPLE_MODEL", "type": "MODEL"},
                {"name": "b", "implementation": "SIMPLE_MODEL", "type": "MODEL"},
            ],
        }
    )
    assert not graph_is_batchable(router.predictor().graph)
    assert EngineService(router).batcher is None  # routers never auto-batch


def test_microbatcher_coalesces_and_splits():
    calls = []

    async def batch_fn(stacked):
        calls.append(len(stacked))
        return stacked * 2.0, {"per_row": np.arange(len(stacked)), "shared": "x"}

    async def run():
        mb = MicroBatcher(batch_fn, max_batch=64, max_wait_ms=5.0)
        outs = await asyncio.gather(
            *[mb.submit(np.full((2, 3), i, np.float32)) for i in range(8)]
        )
        for i, (y, aux) in enumerate(outs):
            np.testing.assert_allclose(y, np.full((2, 3), 2.0 * i))
            assert aux["per_row"].shape == (2,)  # sliced to this caller's rows
            np.testing.assert_array_equal(aux["per_row"], [2 * i, 2 * i + 1])
            assert aux["shared"] == "x"

    asyncio.run(run())
    assert calls == [16]  # one coalesced dispatch for 8 concurrent callers


def test_microbatcher_max_batch_flush():
    calls = []

    async def batch_fn(stacked):
        calls.append(len(stacked))
        return stacked, {}

    async def run():
        mb = MicroBatcher(batch_fn, max_batch=4, max_wait_ms=1000.0)
        await asyncio.gather(*[mb.submit(np.ones((1, 2))) for _ in range(8)])

    asyncio.run(run())
    assert sum(calls) == 8
    assert all(c <= 4 for c in calls)


def test_microbatcher_error_propagates():
    async def batch_fn(stacked):
        raise ValueError("boom")

    async def run():
        mb = MicroBatcher(batch_fn, max_batch=4, max_wait_ms=1.0)
        with pytest.raises(ValueError, match="boom"):
            await mb.submit(np.ones((1, 2)))

    asyncio.run(run())


def test_microbatcher_pads_to_power_of_two():
    sizes = []

    async def batch_fn(stacked):
        sizes.append(len(stacked))
        return stacked, {"per_row": np.arange(len(stacked))}

    async def run():
        mb = MicroBatcher(batch_fn, max_batch=64, max_wait_ms=5.0)
        outs = await asyncio.gather(
            *[mb.submit(np.full((1, 3), i, np.float32)) for i in range(5)]
        )
        # 5 rows padded up to 8 (one jit shape, not one per row-count)
        assert sizes == [8]
        for i, (y, aux) in enumerate(outs):
            np.testing.assert_allclose(y, np.full((1, 3), float(i)))
            np.testing.assert_array_equal(aux["per_row"], [i])  # padding dropped

    asyncio.run(run())


def test_microbatcher_1d_payload_treated_as_one_sample():
    async def batch_fn(stacked):
        assert stacked.ndim == 2
        return stacked * 2.0, {}

    async def run():
        mb = MicroBatcher(batch_fn, max_batch=8, max_wait_ms=1.0, pad_to_buckets=False)
        y, _ = await mb.submit(np.array([1.0, 2.0, 3.0, 4.0]))
        np.testing.assert_allclose(y, [[2.0, 4.0, 6.0, 8.0]])

    asyncio.run(run())


def test_engine_disables_padding_for_streaming_stats():
    spec = deployment(
        {
            "name": "out",
            "type": "TRANSFORMER",
            "children": [{"name": "m0", "type": "MODEL"}],
        },
        [
            {
                "name": "out",
                "runtime": "inprocess",
                "class_path": "MahalanobisOutlier",
                "parameters": [{"name": "n_features", "value": "784", "type": "INT"}],
            },
            {
                "name": "m0",
                "runtime": "inprocess",
                "class_path": "MnistClassifier",
                "parameters": [{"name": "hidden", "value": "32", "type": "INT"}],
            },
        ],
    )
    engine = EngineService(spec)
    assert engine.batcher is not None
    assert engine.batcher.pad_to_buckets is False  # padding would corrupt stats
    plain = deployment(
        {"name": "m", "implementation": "SIMPLE_MODEL", "type": "MODEL"}
    )
    assert EngineService(plain).batcher.pad_to_buckets is True


def test_microbatcher_splits_oversized_requests():
    """Dispatch sizes stay bounded by max_batch even for one huge request."""
    sizes = []

    async def batch_fn(stacked):
        sizes.append(len(stacked))
        return stacked * 2.0, {"per_row": np.arange(len(stacked))}

    async def run():
        mb = MicroBatcher(batch_fn, max_batch=16, max_wait_ms=1.0)
        big = np.arange(50, dtype=np.float32).reshape(50, 1)
        y, aux = await mb.submit(big)
        np.testing.assert_allclose(y, big * 2.0)
        assert aux["per_row"].shape == (50,)
        assert max(sizes) <= 16 and sum(s for s in sizes) >= 50

    asyncio.run(run())


def test_engine_1d_payload_consistent_batched_and_not():
    """A 1-D wire payload is one sample in BOTH engine paths (review
    finding: unbatched path crashed where batched path succeeded)."""
    spec = deployment(
        {"name": "m0", "type": "MODEL"},
        [
            {
                "name": "m0",
                "runtime": "inprocess",
                "class_path": "MnistClassifier",
                "parameters": [{"name": "hidden", "value": "32", "type": "INT"}],
            }
        ],
    )

    async def run():
        msg_1d = SeldonMessage.from_json(
            json.dumps({"data": {"ndarray": [0.0] * 784}})
        )
        import copy

        batched = await EngineService(spec, max_wait_ms=2.0).predict(copy.deepcopy(msg_1d))
        unbatched = await EngineService(spec, batching=False).predict(copy.deepcopy(msg_1d))
        assert np.asarray(batched.array()).shape == (1, 10)
        np.testing.assert_allclose(
            batched.array(), unbatched.array(), atol=1e-5
        )
        assert (batched.status is None or batched.status.status == "SUCCESS")
        assert (unbatched.status is None or unbatched.status.status == "SUCCESS")

    asyncio.run(run())


def test_engine_batched_results_match_unbatched():
    spec = deployment(
        {"name": "m0", "type": "MODEL"},
        [
            {
                "name": "m0",
                "runtime": "inprocess",
                "class_path": "MnistClassifier",
                "parameters": [{"name": "hidden", "value": "32", "type": "INT"}],
            }
        ],
    )

    async def run():
        batched = EngineService(spec, max_wait_ms=5.0)
        unbatched = EngineService(spec, batching=False)
        assert batched.batcher is not None and unbatched.batcher is None
        rng = np.random.default_rng(0)
        reqs = [
            SeldonMessage.from_array(rng.normal(size=(1, 784)).astype(np.float32))
            for _ in range(6)
        ]
        got = await asyncio.gather(*[batched.predict(m) for m in reqs])
        want = [await unbatched.predict(m) for m in reqs]
        for g, w in zip(got, want):
            np.testing.assert_allclose(g.array(), w.array(), atol=1e-5)
            assert g.names() == w.names()
            assert g.meta.puid  # assigned per request

    asyncio.run(run())


def test_engine_batched_outlier_tags_per_request():
    """Per-row outlierScore tags are sliced to each caller's rows."""
    from seldon_core_tpu.graph.units import UNIT_REGISTRY, Unit, register_unit

    if "test.Scale" not in UNIT_REGISTRY:

        @register_unit("test.Scale")
        class ScaleUnit(Unit):
            def __init__(self, factor: float = 2.0):
                self.factor = factor

            def predict(self, state, X):
                return X * self.factor

    spec = deployment(
        {
            "name": "out",
            "type": "TRANSFORMER",
            "children": [{"name": "m0", "type": "MODEL"}],
        },
        [
            {
                "name": "out",
                "runtime": "inprocess",
                "class_path": "MahalanobisOutlier",
                "parameters": [{"name": "n_features", "value": "4", "type": "INT"}],
            },
            {"name": "m0", "runtime": "inprocess", "class_path": "test.Scale"},
        ],
    )

    async def run():
        engine = EngineService(spec, max_wait_ms=5.0)
        assert engine.batcher is not None
        reqs = [
            SeldonMessage.from_array(np.full((2, 4), float(i), np.float32))
            for i in range(4)
        ]
        resps = await asyncio.gather(*[engine.predict(m) for m in reqs])
        for r in resps:
            scores = np.asarray(r.meta.tags["outlierScore"])
            assert scores.shape == (2,)  # this caller's rows only

    asyncio.run(run())


def test_predict_json_matches_object_path():
    """Wire-to-wire fast path emits a document equivalent to
    from_json -> predict -> to_json (field-for-field after parsing)."""
    spec = deployment(
        {"name": "m0", "type": "MODEL"},
        [
            {
                "name": "m0",
                "runtime": "inprocess",
                "class_path": "MnistClassifier",
                "parameters": [{"name": "hidden", "value": "32", "type": "INT"}],
            }
        ],
    )

    async def run():
        rng = np.random.default_rng(1)
        x = rng.normal(size=(2, 784)).astype(np.float64)
        for wire in (
            json.dumps({"data": {"ndarray": x.tolist()}}),
            json.dumps(
                {
                    "meta": {"puid": "fixedpuid", "tags": {"k": "v"}},
                    "data": {"tensor": {"shape": [2, 784], "values": x.reshape(-1).tolist()}},
                }
            ),
        ):
            fast_engine = EngineService(spec, max_wait_ms=2.0)
            slow_engine = EngineService(spec, batching=False)
            text, status = await fast_engine.predict_json(wire)
            assert status == 200
            got = json.loads(text)
            want = json.loads(
                (await slow_engine.predict(SeldonMessage.from_json(wire))).to_json()
            )
            assert got["data"].keys() == want["data"].keys()
            assert got["data"].get("names") == want["data"].get("names")
            np.testing.assert_allclose(
                np.asarray(got["data"].get("ndarray", got["data"].get("tensor", {}).get("values"))),
                np.asarray(want["data"].get("ndarray", want["data"].get("tensor", {}).get("values"))),
                atol=1e-4,
            )
            if "tensor" in got["data"]:
                assert got["data"]["tensor"]["shape"] == want["data"]["tensor"]["shape"]
            assert got["status"]["status"] == "SUCCESS"
            assert got["meta"].get("tags") == want["meta"].get("tags")
            assert got["meta"]["puid"]
            if "fixedpuid" in wire:
                assert got["meta"]["puid"] == "fixedpuid"

    asyncio.run(run())


def test_predict_json_failure_and_fallbacks():
    spec = deployment(
        {"name": "m0", "type": "MODEL"},
        [
            {
                "name": "m0",
                "runtime": "inprocess",
                "class_path": "MnistClassifier",
                "parameters": [{"name": "hidden", "value": "32", "type": "INT"}],
            }
        ],
    )

    async def run():
        engine = EngineService(spec, max_wait_ms=2.0)
        # ragged payload -> 400 FAILURE document either path
        text, status = await engine.predict_json(
            json.dumps({"data": {"ndarray": [[1.0, 2.0], [3.0]]}})
        )
        assert status == 400
        assert json.loads(text)["status"]["status"] == "FAILURE"
        # strData (non-numeric) falls back to the object path cleanly
        text, status = await engine.predict_json(json.dumps({"strData": "hi"}))
        doc = json.loads(text)
        assert "meta" in doc

    asyncio.run(run())


def test_stateful_graph_rejects_oversized_request():
    """A request larger than max_batch on a stateful graph must fail
    loudly: splitting it into chunks would commit state per chunk, and a
    mid-request failure would leave it partially applied."""
    import asyncio
    import json

    import numpy as np

    from seldon_core_tpu.graph.spec import SeldonDeploymentSpec
    from seldon_core_tpu.runtime.engine import EngineService

    spec = SeldonDeploymentSpec.from_json_dict({
        "spec": {"name": "d", "predictors": [{
            "name": "p",
            "graph": {
                "name": "out", "type": "TRANSFORMER",
                "children": [{"name": "m", "type": "MODEL"}],
            },
            "components": [
                {"name": "out", "runtime": "inprocess",
                 "class_path": "MahalanobisOutlier",
                 "parameters": [{"name": "n_features", "value": "8",
                                 "type": "INT"}]},
                {"name": "m", "runtime": "inprocess",
                 "class_path": "MeanClassifier"},
            ],
        }]}
    })
    engine = EngineService(spec, max_batch=16)
    assert engine.batcher is not None
    assert engine.batcher.atomic_chunks  # streaming stats => stateful

    async def run():
        big = np.zeros((40, 8)).tolist()
        text, status = await engine.predict_json(
            json.dumps({"data": {"ndarray": big}})
        )
        assert status == 400
        assert "atomically" in json.loads(text)["status"]["info"]
        # within-limit requests still serve
        ok = np.zeros((8, 8)).tolist()
        text, status = await engine.predict_json(
            json.dumps({"data": {"ndarray": ok}})
        )
        assert status == 200

    asyncio.run(run())
