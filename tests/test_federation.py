"""Federated gateway tier + inflight-work recovery (gateway/federation.py,
gateway/state.py leases, gateway/balancer.py lease liveness, apife's
hedged unary re-dispatch and SSE stream failover).

The properties pinned here are the mesh's crash contract: any process is
killable under load without user-visible failure —

  * the shared sqlite store serializes concurrent replicas (IMMEDIATE
    transactions + SQLITE_BUSY retry): no lost updates, monotone revision;
  * the coordinator lease fails over within one TTL, and a paused-then-
    resumed ex-coordinator's writes are REJECTED by the fencing token
    inside the store's own transaction (the Chubby-style fence);
  * a rollout controller survives the handoff: the successor continues,
    the zombie's split write dies as a typed "fenced" decision;
  * a dead engine's inflight unary re-dispatches to a peer (zero failed
    unary), and a live SSE stream re-homes mid-generation via re-prefill
    (prompt + emitted-so-far) instead of 502ing;
  * ``SELDON_TPU_FEDERATION=0`` restores fail-to-caller bit-for-bit.
"""

import asyncio
import json
import threading
import time

import numpy as np
import pytest

from seldon_core_tpu.gateway.apife import ApiGateway, DeploymentStore
from seldon_core_tpu.gateway.balancer import ReplicaSet
from seldon_core_tpu.gateway.federation import (
    COORDINATOR_LEASE,
    GatewayFederation,
)
from seldon_core_tpu.gateway.state import SqliteDeploymentStore, StaleFenceError
from seldon_core_tpu.graph.spec import SeldonDeploymentSpec
from seldon_core_tpu.messages import SeldonMessage
from seldon_core_tpu.operator.rollouts import (
    RolloutController,
    RolloutGates,
    RolloutPlan,
)
from seldon_core_tpu.testing.faults import InjectedFault, PartitionedStore


def canary_spec(name="dep", key="key"):
    def predictor(pname, reps):
        return {"name": pname, "replicas": reps,
                "graph": {"name": "m", "type": "MODEL",
                          "implementation": "SIMPLE_MODEL"}}
    return SeldonDeploymentSpec.from_json_dict({
        "spec": {
            "name": name, "oauth_key": key, "oauth_secret": "s",
            "predictors": [predictor("baseline", 9),
                           predictor("candidate", 1)],
        }
    })


@pytest.fixture()
def db_path(tmp_path):
    return str(tmp_path / "gateway.db")


async def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _weights(store, key="key"):
    reg = store._registration(key)
    return {name: w for name, w, _ in reg.engines}


# ---------------------------------------------------------------------------
# shared-store concurrency: no lost updates, monotone revision
# ---------------------------------------------------------------------------


def test_two_store_instances_concurrent_writes_no_lost_updates(db_path):
    """Two store instances (two gateway replicas) hammering the same file
    with interleaved registrations + weight shifts: every write lands
    (revision advances exactly once per bump-carrying write) and no
    writer ever sees a raw SQLITE_BUSY."""
    a = SqliteDeploymentStore(db_path)
    b = SqliteDeploymentStore(db_path)
    a.register(canary_spec(), {"baseline": "http://b:8000",
                               "candidate": "http://c:8000"})
    base = a.revision()
    n, errors = 40, []

    def worker(store, flip):
        try:
            for i in range(n):
                pct = (i * 7) % 101 if flip else (100 - (i * 3) % 101)
                store.set_weights("dep", {"candidate": pct,
                                          "baseline": 100 - pct})
        except Exception as e:  # noqa: BLE001 — the assertion target
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(a, True)),
               threading.Thread(target=worker, args=(b, False))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, f"writers surfaced: {errors[:3]}"
    # every set_weights bumps revision inside ITS OWN transaction: two
    # replicas x n writes = exactly 2n bumps — a lost update would skip
    assert a.revision() == base + 2 * n
    w = _weights(a)
    assert w["candidate"] + w["baseline"] == 100


def test_busy_writer_retries_instead_of_raising(db_path):
    """A sibling replica holding the write lock past busy_timeout makes
    BEGIN IMMEDIATE fail SQLITE_BUSY — the _write retry loop must absorb
    it, not surface an OperationalError."""
    import sqlite3

    a = SqliteDeploymentStore(db_path)
    a.register(canary_spec(), {"baseline": "http://b:8000",
                               "candidate": "http://c:8000"})
    held = threading.Event()

    def hold_lock():
        # a rogue connection holding the write lock, released only after
        # busy_timeout has certainly elapsed — inside the retry window
        rogue = sqlite3.connect(db_path, isolation_level=None)
        rogue.execute("BEGIN IMMEDIATE")
        held.set()
        time.sleep(0.35)
        rogue.execute("COMMIT")
        rogue.close()

    t = threading.Thread(target=hold_lock)
    t.start()
    held.wait()
    a.set_weights("dep", {"candidate": 50, "baseline": 50})  # must not raise
    t.join()
    assert _weights(a)["candidate"] == 50


# ---------------------------------------------------------------------------
# coordinator lease + fencing
# ---------------------------------------------------------------------------


def test_lease_acquire_renew_takeover_token_semantics(db_path):
    s = SqliteDeploymentStore(db_path)
    assert s.acquire_lease("coord", "A", ttl_s=0.3) == 1
    # renewal by the live holder keeps the token
    assert s.acquire_lease("coord", "A", ttl_s=0.3) == 1
    # a live lease blocks other claimants
    assert s.acquire_lease("coord", "B", ttl_s=0.3) is None
    time.sleep(0.35)
    # expired: takeover bumps the token
    assert s.acquire_lease("coord", "B", ttl_s=0.3) == 2
    time.sleep(0.35)
    # even the SAME holder name re-claiming an expired lease bumps — a
    # restarted process must not inherit its dead predecessor's fence
    assert s.acquire_lease("coord", "B", ttl_s=0.3) == 3


def test_release_lease_is_conditional(db_path):
    s = SqliteDeploymentStore(db_path)
    s.acquire_lease("coord", "A", ttl_s=5.0)
    s.release_lease("coord", "A", token=99)  # wrong token: no-op
    assert s.lease("coord")["holder"] == "A"
    s.release_lease("coord", "A", token=1)
    assert s.lease("coord") is None


def test_paused_ex_coordinator_write_rejected_by_fence(db_path):
    """The tentpole fencing property: a coordinator paused past its TTL
    (GC stall, SIGSTOP) resumes and writes with its old token — the
    store rejects it inside the same transaction; the new coordinator's
    write with the current token lands."""
    s = SqliteDeploymentStore(db_path)
    s.register(canary_spec(), {"baseline": "http://b:8000",
                               "candidate": "http://c:8000"})
    old = s.acquire_lease("coord", "A", ttl_s=0.25)
    time.sleep(0.3)  # A is "paused" past its TTL
    new = s.acquire_lease("coord", "B", ttl_s=5.0)
    assert new == old + 1
    with pytest.raises(StaleFenceError):
        s.fenced_set_weights("dep", {"candidate": 90, "baseline": 10},
                             lease="coord", holder="A", token=old)
    assert _weights(s)["candidate"] == 1  # the zombie write never landed
    s.fenced_set_weights("dep", {"candidate": 25, "baseline": 75},
                         lease="coord", holder="B", token=new)
    assert _weights(s)["candidate"] == 25


def test_federation_election_and_failover_within_one_ttl(db_path):
    store_a = SqliteDeploymentStore(db_path)
    store_b = SqliteDeploymentStore(db_path)
    fed_a = GatewayFederation(store_a, "gw-a", ttl_s=0.3,
                              base_url="http://a:8080")
    fed_b = GatewayFederation(store_b, "gw-b", ttl_s=0.3,
                              base_url="http://b:8080")
    assert fed_a.tick() is True
    assert fed_b.tick() is False
    assert fed_a.is_coordinator and not fed_b.is_coordinator
    # the peer directory sees both replicas either way
    assert fed_a.peers() == [("gw-b", "http://b:8080")]
    # gw-a dies (stops ticking); gw-b takes over within one TTL
    deadline = time.time() + 0.3 + 0.2
    while not fed_b.tick() and time.time() < deadline:
        time.sleep(0.05)
    assert fed_b.is_coordinator, "failover exceeded one lease TTL"
    assert fed_b.fencing_token == fed_a.fencing_token + 1


def test_federation_demotes_on_store_error_and_recovers(db_path):
    inner = SqliteDeploymentStore(db_path)
    store = PartitionedStore(inner)
    fed = GatewayFederation(store, "gw-a", ttl_s=5.0)
    assert fed.tick() is True
    store.partition()
    # tenure can't be proven against a dead store: demote, keep serving
    assert fed.tick() is False
    assert not fed.is_coordinator
    assert "InjectedFault" in fed.snapshot().get("store_error", "")
    store.heal()
    assert fed.tick() is True


def test_kill_switch_makes_every_replica_coordinator(db_path, monkeypatch):
    monkeypatch.setenv("SELDON_TPU_FEDERATION", "0")
    fed = GatewayFederation(SqliteDeploymentStore(db_path), "gw-a")
    assert not fed.enabled
    assert fed.tick() is True and fed.is_coordinator
    # in-memory stores have no lease API: same degradation
    monkeypatch.delenv("SELDON_TPU_FEDERATION")
    fed2 = GatewayFederation(DeploymentStore(), "gw-b")
    assert not fed2.enabled and fed2.is_coordinator


# ---------------------------------------------------------------------------
# rollout controller: singleton duty surviving the handoff
# ---------------------------------------------------------------------------


def _fast_plan():
    return RolloutPlan(
        deployment="dep", candidate="candidate", baseline="baseline",
        stages=(10, 50, 100), hold_s=0.0,
        gates=RolloutGates(min_requests=0, max_drift=None,
                           max_burn_rate=None, max_error_rate=None,
                           max_shadow_disagreement=None),
        config_hash="h1",
    )


def test_rollout_controller_survives_coordinator_handoff(db_path):
    """Replica A's controller starts the rollout; A stalls past its TTL;
    replica B's controller continues the SAME rollout off the shared
    store, and A's zombie tick dies as a typed 'fenced' decision instead
    of clobbering B's split."""
    store_a = SqliteDeploymentStore(db_path)
    store_b = SqliteDeploymentStore(db_path)
    store_a.register(canary_spec(), {"baseline": "http://b:8000",
                                     "candidate": "http://c:8000"})
    fed_a = GatewayFederation(store_a, "gw-a", ttl_s=0.25)
    fed_b = GatewayFederation(store_b, "gw-b", ttl_s=0.25)
    signals = lambda plan: {"requests": 1000, "errors": 0}  # noqa: E731
    ctl_a = RolloutController(store_a, signals, federation=fed_a)
    ctl_b = RolloutController(store_b, signals, federation=fed_b)
    ctl_a.apply(_fast_plan())
    ctl_b.apply(_fast_plan())

    fed_a.tick()
    assert fed_b.tick() is False
    [d] = ctl_a.tick()
    assert d["decision"] == "advance" and d["percent"] == 10
    assert _weights(store_b)["candidate"] == 10
    # B is NOT coordinator: its controller must not tick at all
    assert ctl_b.tick() == []

    time.sleep(0.3)  # A stalls past its TTL (never ticks its federation)
    assert fed_b.tick() is True
    # the successor RESUMES at the predecessor's stage off the shared
    # store's live split, then continues — never snaps back to stage 0
    [d] = ctl_b.tick()
    assert d["decision"] == "resume" and d["percent"] == 10
    [d] = ctl_b.tick()
    assert d["decision"] == "advance" and d["percent"] == 50
    assert _weights(store_a)["candidate"] == 50

    # the zombie: A still believes in its stale token -> fenced decision,
    # split untouched
    assert fed_a.is_coordinator  # stale local view, by construction
    [d] = ctl_a.tick()
    assert d["decision"] == "fenced"
    assert _weights(store_a)["candidate"] == 50


# ---------------------------------------------------------------------------
# engine liveness leases -> balancer
# ---------------------------------------------------------------------------


def test_apply_leases_marks_lapsed_dead_and_resets_on_boot_id(db_path):
    s = SqliteDeploymentStore(db_path)
    rs = ReplicaSet(["http://a:1", "http://b:1"])
    a, b = rs.endpoints
    # never-leased endpoints keep scrape-based health untouched
    rs.apply_leases(s.engine_leases())
    assert a.lease_state is None and not a.degraded(time.monotonic(), 10.0)

    s.heartbeat_engine("http://a:1", "boot-1", ttl_s=0.25)
    rs.apply_leases(s.engine_leases())
    assert a.lease_state == "live" and a.boot_id == "boot-1"
    assert b.lease_state is None

    # a poisoned EWMA from the dead epoch must not outlive the process
    a.ewma_ms = 500.0
    a.consec_failures = 2
    time.sleep(0.3)  # lease lapses: the engine is dead
    rs.apply_leases(s.engine_leases())
    assert a.lease_state == "dead"
    assert a.degraded(time.monotonic(), 10.0)

    # same URL, new boot_id: restarted process — state resets, liveness
    # returns, the stale EWMA/failure streak dies with the old epoch
    s.heartbeat_engine("http://a:1", "boot-2", ttl_s=5.0)
    rs.apply_leases(s.engine_leases())
    assert a.lease_state == "live" and a.boot_id == "boot-2"
    assert a.ewma_ms == 0.0 and a.consec_failures == 0
    assert a.epoch_resets == 1

    # graceful deregistration: row deleted -> dead immediately
    s.drop_engine("http://a:1")
    rs.apply_leases(s.engine_leases())
    assert a.lease_state == "dead"


# ---------------------------------------------------------------------------
# hedged unary re-dispatch: zero failed unary across an engine death
# ---------------------------------------------------------------------------


def _remote_spec(urls):
    spec = SeldonDeploymentSpec.from_json_dict({
        "spec": {
            "name": "dep", "oauth_key": "key", "oauth_secret": "s",
            "predictors": [
                {"name": "p", "replicas": 1,
                 "graph": {"name": "m", "type": "MODEL",
                           "implementation": "SIMPLE_MODEL"}}
            ],
        }
    })
    store = DeploymentStore()
    store.register(spec, {"p": urls})
    return store


def test_dead_engine_unary_rehomed_to_peer():
    """A predict routed at a dead engine re-dispatches to the live peer:
    the caller sees SUCCESS, the failover counter ticks."""
    from aiohttp import web

    async def run():
        async def ok(request):
            return web.json_response(
                {"meta": {}, "data": {"ndarray": [[0.5]]}})

        app = web.Application()
        app.router.add_post("/api/v0.1/predictions", ok)
        runner = web.AppRunner(app)
        await runner.setup()
        live = await _free_port()
        await web.TCPSite(runner, "127.0.0.1", live).start()
        dead = await _free_port()  # nothing listens here

        store = _remote_spec([f"http://127.0.0.1:{dead}",
                              f"http://127.0.0.1:{live}"])
        gw = ApiGateway(store=store, require_auth=False)
        try:
            msg = SeldonMessage.from_array(np.zeros((1, 4), np.float64))
            await gw.predict(msg)  # builds the replica set
            [(_fp, rs)] = list(gw._replica_sets.values())
            d = next(ep for ep in rs.endpoints if str(dead) in ep.base_url)
            h = next(ep for ep in rs.endpoints if str(live) in ep.base_url)
            # steer the pick at the corpse: the dead replica looks FAST
            # (it never answered, so nothing poisoned its EWMA) — exactly
            # the window before fail-degradation kicks in
            d.ewma_ms, d.consec_failures, d.fail_degraded_until = 0.1, 0, 0.0
            h.ewma_ms = 1000.0
            before = gw.failovers.get("unary", 0)
            resp = await gw.predict(msg)
            st = resp.status
            assert st is None or st.status == "SUCCESS", st
            assert gw.failovers["unary"] == before + 1
        finally:
            await gw.close()
            await runner.cleanup()

    asyncio.run(run())


def test_kill_switch_restores_fail_to_caller(monkeypatch):
    monkeypatch.setenv("SELDON_TPU_FEDERATION", "0")
    from aiohttp import web

    async def run():
        async def ok(request):
            return web.json_response(
                {"meta": {}, "data": {"ndarray": [[0.5]]}})

        app = web.Application()
        app.router.add_post("/api/v0.1/predictions", ok)
        runner = web.AppRunner(app)
        await runner.setup()
        live = await _free_port()
        await web.TCPSite(runner, "127.0.0.1", live).start()
        dead = await _free_port()

        store = _remote_spec([f"http://127.0.0.1:{dead}",
                              f"http://127.0.0.1:{live}"])
        gw = ApiGateway(store=store, require_auth=False)
        try:
            msg = SeldonMessage.from_array(np.zeros((1, 4), np.float64))
            await gw.predict(msg)
            [(_fp, rs)] = list(gw._replica_sets.values())
            d = next(ep for ep in rs.endpoints if str(dead) in ep.base_url)
            h = next(ep for ep in rs.endpoints if str(live) in ep.base_url)
            d.ewma_ms, d.consec_failures, d.fail_degraded_until = 0.1, 0, 0.0
            h.ewma_ms = 1000.0
            resp = await gw.predict(msg)
            # pre-federation behavior bit-for-bit: the failure surfaces
            assert resp.status is not None
            assert resp.status.status == "FAILURE"
            assert gw.failovers.get("unary", 0) == 0
        finally:
            await gw.close()
            await runner.cleanup()

    asyncio.run(run())


# ---------------------------------------------------------------------------
# SSE stream failover: resume mid-generation via re-prefill
# ---------------------------------------------------------------------------


def test_stream_rehomed_mid_generation_resumes_via_reprefill():
    """An engine dies two tokens into a five-token stream.  The gateway
    re-homes the live SSE stream to the peer with prompt+emitted as the
    new prompt and the budget reduced by what was served; the client
    sees tokens 10..14 exactly once plus the terminal event — never a
    502, never a duplicate."""
    import aiohttp
    from aiohttp import web

    from seldon_core_tpu.gateway.apife import make_gateway_app
    from seldon_core_tpu.runtime.rest import serve_app

    resume_bodies = []

    async def stream_handler(request):
        doc = json.loads(await request.text())
        prompt = doc["data"]["ndarray"][0]
        resp = web.StreamResponse(
            status=200, headers={"Content-Type": "text/event-stream"})
        await resp.prepare(request)
        if len(prompt) == 3:  # the fresh stream: serve 2 tokens, then die
            await resp.write(b'data: {"tokens": [[10.0]]}\n\n')
            await resp.write(b'data: {"tokens": [[11.0]]}\n\n')
            return resp  # abrupt end, no terminal event
        # the resume: prompt must be original + emitted, budget reduced
        resume_bodies.append(doc)
        for tok in prompt[3:]:  # re-emit nothing: continue AFTER emitted
            pass
        for t in (12.0, 13.0, 14.0):
            await resp.write(
                b'data: {"tokens": [[%.1f]]}\n\n' % t)
        await resp.write(b'data: {"done": true}\n\n')
        return resp

    async def make_engine():
        app = web.Application()
        app.router.add_post("/api/v0.1/generate/stream", stream_handler)
        runner = web.AppRunner(app)
        await runner.setup()
        port = await _free_port()
        await web.TCPSite(runner, "127.0.0.1", port).start()
        return runner, port

    async def run():
        r1, p1 = await make_engine()
        r2, p2 = await make_engine()
        store = _remote_spec([f"http://127.0.0.1:{p1}",
                              f"http://127.0.0.1:{p2}"])
        gw = ApiGateway(store=store, require_auth=False)
        gport = await _free_port()
        grunner = await serve_app(make_gateway_app(gw), "127.0.0.1", gport)
        try:
            async with aiohttp.ClientSession() as s:
                async with s.post(
                    f"http://127.0.0.1:{gport}/api/v0.1/generate/stream",
                    json={"data": {"ndarray": [[1.0, 2.0, 3.0]]},
                          "max_new": 5},
                ) as r:
                    assert r.status == 200
                    raw = await r.read()
            events = [json.loads(e.partition(b"data:")[2])
                      for e in raw.split(b"\n\n") if e.strip()]
            toks = [e["tokens"][0][0] for e in events if "tokens" in e]
            assert toks == [10.0, 11.0, 12.0, 13.0, 14.0]
            assert any(e.get("done") for e in events)
            assert not any("error" in e for e in events)
            assert gw.failovers.get("stream", 0) == 1
            # the re-prefill contract: prompt + emitted, budget shrunk
            [body] = resume_bodies
            assert body["data"]["ndarray"] == [[1.0, 2.0, 3.0, 10.0, 11.0]]
            assert body["max_new"] == 3
        finally:
            await grunner.cleanup()
            await gw.close()
            await r1.cleanup()
            await r2.cleanup()

    asyncio.run(run())


def test_stream_kill_switch_keeps_raw_proxy(monkeypatch):
    """SELDON_TPU_FEDERATION=0: a mid-stream death surfaces as the
    in-band terminal error event (the pre-federation contract), with no
    resume attempt reaching the peer."""
    monkeypatch.setenv("SELDON_TPU_FEDERATION", "0")
    import aiohttp
    from aiohttp import web

    from seldon_core_tpu.gateway.apife import make_gateway_app
    from seldon_core_tpu.runtime.rest import serve_app

    calls = []

    async def stream_handler(request):
        calls.append(request.remote)
        resp = web.StreamResponse(
            status=200, headers={"Content-Type": "text/event-stream"})
        await resp.prepare(request)
        await resp.write(b'data: {"tokens": [[10.0]]}\n\n')
        return resp  # dies without a terminal event

    async def run():
        app = web.Application()
        app.router.add_post("/api/v0.1/generate/stream", stream_handler)
        runner = web.AppRunner(app)
        await runner.setup()
        p1 = await _free_port()
        await web.TCPSite(runner, "127.0.0.1", p1).start()
        store = _remote_spec([f"http://127.0.0.1:{p1}"])
        gw = ApiGateway(store=store, require_auth=False)
        gport = await _free_port()
        grunner = await serve_app(make_gateway_app(gw), "127.0.0.1", gport)
        try:
            async with aiohttp.ClientSession() as s:
                async with s.post(
                    f"http://127.0.0.1:{gport}/api/v0.1/generate/stream",
                    json={"data": {"ndarray": [[1.0, 2.0, 3.0]]},
                          "max_new": 5},
                ) as r:
                    assert r.status == 200
                    raw = await r.read()
            assert len(calls) == 1  # no resume attempt
            assert gw.failovers.get("stream", 0) == 0
        finally:
            await grunner.cleanup()
            await gw.close()
            await runner.cleanup()

    asyncio.run(run())


# ---------------------------------------------------------------------------
# fault harness: PartitionedStore semantics
# ---------------------------------------------------------------------------


def test_partitioned_store_read_write_asymmetry(db_path):
    inner = SqliteDeploymentStore(db_path)
    store = PartitionedStore(inner)
    store.heartbeat_engine("http://a:1", "b1", 5.0)  # healthy passthrough
    assert "http://a:1" in store.engine_leases()

    store.partition(reads=True, writes=False)
    store.heartbeat_engine("http://a:1", "b1", 5.0)  # writes still up
    with pytest.raises(InjectedFault):
        store.engine_leases()

    store.partition(reads=False, writes=True)
    assert "http://a:1" in store.engine_leases()
    with pytest.raises(InjectedFault):
        store.acquire_lease("coord", "A", 1.0)

    store.heal()
    store.fail_next(2)  # deterministic flap: exactly two calls fail
    with pytest.raises(InjectedFault):
        store.engine_leases()
    with pytest.raises(InjectedFault):
        store.engine_leases()
    assert "http://a:1" in store.engine_leases()
    assert store.faults_injected == 4


def test_kill_engine_sends_sigkill():
    import signal
    import subprocess
    import sys

    from seldon_core_tpu.testing.faults import kill_engine

    proc = subprocess.Popen([sys.executable, "-c",
                             "import time; time.sleep(30)"])
    kill_engine(proc)
    assert proc.wait(timeout=5) == -signal.SIGKILL


# ---------------------------------------------------------------------------
# /stats surfaces
# ---------------------------------------------------------------------------


def test_gateway_stats_federation_block(db_path):
    async def run():
        store = SqliteDeploymentStore(db_path)
        store.register(canary_spec(), {"baseline": "http://b:8000",
                                       "candidate": "http://c:8000"})
        gw = ApiGateway(store=store, require_auth=False)
        fed = GatewayFederation(store, "gw-a", ttl_s=5.0,
                                base_url="http://a:8080")
        gw.federation = fed
        fed.tick()
        try:
            doc = gw.stats()["federation"]
            assert doc["replica_id"] == "gw-a"
            assert doc["coordinator"] is True
            assert doc["fencing_token"] == 1
            assert doc["lease"]["holder"] == "gw-a"
            assert doc["failovers"] == {}
        finally:
            fed.resign()
            await gw.close()
        assert store.lease(COORDINATOR_LEASE) is None  # resigned cleanly

    asyncio.run(run())
