"""Engine replica sets + power-of-two-choices balancing
(gateway/balancer.py), the gateway integration (apife._pick_engine
weight math, decision span attrs, dispatch accounting), and the shared
sqlite store's multi-engine registrations.

The hand-computed cases pin the score function — expected wait =
(inflight + scraped + 1) x max(ewma, floor), plus the degraded penalty —
because a broken score silently turns p2c into random choice and only
shows up as tail latency much later."""

import asyncio
import json
import random

import numpy as np
import pytest

from seldon_core_tpu.gateway.apife import ApiGateway, DeploymentStore
from seldon_core_tpu.gateway.balancer import (
    _EWMA_ALPHA,
    _EWMA_FLOOR_MS,
    _UNHEALTHY_PENALTY,
    PickDecision,
    ReplicaEndpoint,
    ReplicaSet,
    parse_endpoint_spec,
)
from seldon_core_tpu.gateway.state import SqliteDeploymentStore
from seldon_core_tpu.graph.spec import SeldonDeploymentSpec
from seldon_core_tpu.messages import SeldonMessage
from seldon_core_tpu.runtime.engine import EngineService
from seldon_core_tpu.testing.faults import FaultSpec, FaultyEngine
from seldon_core_tpu.utils.telemetry import RECORDER


def sigmoid_spec(name="rs-dep", replicas=2, n_predictors=1):
    def predictor(pname, seed, reps):
        return {
            "name": pname,
            "replicas": reps,
            "graph": {"name": "m", "type": "MODEL"},
            "components": [{
                "name": "m", "runtime": "inprocess",
                "class_path": "SigmoidPredictor",
                "parameters": [
                    {"name": "n_features", "value": "4", "type": "INT"},
                    {"name": "seed", "value": str(seed), "type": "INT"},
                ],
            }],
        }
    return SeldonDeploymentSpec.from_json_dict({
        "spec": {
            "name": name,
            "oauth_key": "k", "oauth_secret": "s",
            "predictors": [
                predictor(f"p{i}" if n_predictors > 1 else "p", i, replicas)
                for i in range(n_predictors)
            ],
        }
    })


def msg4():
    return SeldonMessage.from_array(np.zeros((1, 4), np.float32))


# -- endpoint spec parsing -------------------------------------------------


def test_parse_endpoint_spec_three_forms():
    assert parse_endpoint_spec("http://h:8000") == ("http://h:8000", None)
    assert parse_endpoint_spec("http://h:8000/") == ("http://h:8000", None)
    assert parse_endpoint_spec("uds:/run/e.sock") == (None, "/run/e.sock")
    assert parse_endpoint_spec("http://h:8000+uds:/run/e.sock") == (
        "http://h:8000", "/run/e.sock"
    )


# -- score function, hand-computed ----------------------------------------


def test_score_is_expected_wait():
    ep = ReplicaEndpoint("http://a:1")
    # no samples yet: floor keeps the score finite and non-zero
    assert ep.score(0.0, 10.0) == pytest.approx(1 * _EWMA_FLOOR_MS)
    ep.ewma_ms = 8.0
    ep.inflight = 2
    ep.scraped_inflight = 3
    # (2 gateway-side + 3 scraped + 1) * 8 ms
    assert ep.score(0.0, 10.0) == pytest.approx(6 * 8.0)


def test_ewma_update_and_failure_counting():
    ep = ReplicaEndpoint("http://a:1")
    ep.begin()
    ep.complete(0.010)  # first sample seeds the EWMA directly
    assert ep.ewma_ms == pytest.approx(10.0)
    ep.begin()
    ep.complete(0.020)
    assert ep.ewma_ms == pytest.approx(
        (1 - _EWMA_ALPHA) * 10.0 + _EWMA_ALPHA * 20.0
    )
    before = ep.ewma_ms
    ep.begin()
    ep.complete(5.0, ok=False)  # failures don't poison the latency signal
    assert ep.ewma_ms == before
    assert ep.failures == 1
    assert ep.inflight == 0


def test_degraded_penalty_orders_below_healthy():
    rs = ReplicaSet(["http://a:1", "http://b:1"], rng=random.Random(0))
    a, b = rs.endpoints
    a.ewma_ms = 100.0   # slow but healthy
    b.ewma_ms = 1.0     # fast but breaker-open
    b.breaker_open = True
    now = 0.0
    assert a.score(now, rs.stale_after_s) < b.score(now, rs.stale_after_s)
    assert b.score(now, rs.stale_after_s) >= _UNHEALTHY_PENALTY


def test_fast_failing_replica_degrades_instead_of_magnetizing():
    """Failures drain inflight instantly and never raise the EWMA, so
    without failure-degradation a dead replica scores at the floor and
    WINS every pick — the black-hole shape.  Three consecutive failures
    must flip it degraded; cooldown expiry is the passive half-open and
    one success clears the streak."""
    import time as _time

    rs = ReplicaSet(["uds:/run/a.sock", "http://b:1"],
                    rng=random.Random(0))
    a, b = rs.endpoints
    b.ewma_ms = 50.0  # healthy but slow
    for _ in range(2):
        a.begin()
        a.complete(0.0001, ok=False)
    now = _time.monotonic()
    assert not a.degraded(now, rs.stale_after_s)  # streak too short
    a.begin()
    a.complete(0.0001, ok=False)  # third consecutive failure
    now = _time.monotonic()
    assert a.degraded(now, rs.stale_after_s)
    # the dead-fast replica must now LOSE to the slow healthy one
    assert a.score(now, rs.stale_after_s) > b.score(now, rs.stale_after_s)
    # cooldown expired -> sampled again (passive half-open probe)
    a.fail_degraded_until = now - 0.001
    assert not a.degraded(_time.monotonic(), rs.stale_after_s)
    # one success clears the streak entirely
    a.begin()
    a.complete(0.001, ok=True)
    assert a.consec_failures == 0 and a.fail_degraded_until == 0.0


def test_stale_scrape_degrades_only_after_first_success():
    ep = ReplicaEndpoint("http://a:1")
    # never scraped (tests, single-shot benches): not degraded
    assert not ep.degraded(1000.0, 6.0)
    ep.scrape_ts = 1.0
    assert ep.degraded(1000.0, 6.0)       # stale now
    assert not ep.degraded(5.0, 6.0)      # fresh enough


# -- p2c pick -------------------------------------------------------------


def test_p2c_picks_lower_score_and_records_decision():
    RECORDER.reset()
    rs = ReplicaSet(["http://a:1", "http://b:1"], rng=random.Random(3))
    a, b = rs.endpoints
    a.ewma_ms, b.ewma_ms = 50.0, 2.0
    chosen, decision = rs.pick()
    assert chosen is b
    assert decision is not None
    assert decision.replica == "http://b:1"
    assert set(decision.candidates) == {"http://a:1", "http://b:1"}
    assert len(decision.scores) == 2
    assert decision.loser_ewma_ms == pytest.approx(50.0)
    snap = RECORDER.snapshot()["replicas"]
    assert snap["picks"]["default"]["http://b:1"] == 1


def test_single_endpoint_and_kill_switch_bypass_p2c(monkeypatch):
    solo = ReplicaSet(["http://a:1"])
    ep, decision = solo.pick()
    assert ep.name == "http://a:1" and decision is None

    rs = ReplicaSet(["http://a:1", "http://b:1"], rng=random.Random(0))
    rs.endpoints[0].ewma_ms = 1e6  # would never win a scored pick
    monkeypatch.setenv("SELDON_TPU_REPLICAS", "0")
    for _ in range(8):
        ep, decision = rs.pick()
        assert ep is rs.endpoints[0] and decision is None
    assert rs.endpoints[0].picks == 0  # no p2c accounting either


def test_mispick_hindsight_accounting():
    RECORDER.reset()
    rs = ReplicaSet(["http://a:1", "http://b:1"], rng=random.Random(0))
    ep = rs.endpoints[0]
    decision = PickDecision(
        replica=ep.name, candidates=[ep.name, "http://b:1"],
        scores=[1.0, 2.0], loser_ewma_ms=5.0,
    )
    ep.begin()
    rs.complete(ep, decision, latency_s=0.050)  # 50 ms > loser's 5 ms
    assert rs.mispicks == 1
    ep.begin()
    rs.complete(ep, decision, latency_s=0.001)  # 1 ms < 5 ms: good pick
    assert rs.mispicks == 1
    # failures are not mispicks (the loser might have failed too)
    ep.begin()
    rs.complete(ep, decision, latency_s=9.9, ok=False)
    assert rs.mispicks == 1
    assert RECORDER.snapshot()["replicas"]["mispicks"] == 1


def test_degraded_loser_never_judges_the_pick():
    """Beating a sick replica's historical EWMA is not a prediction
    error: a pick whose loser was degraded carries loser_ewma_ms=0, so
    steering around an open breaker can't pin the mispick ratio at 1."""
    rs = ReplicaSet(["http://a:1", "http://b:1"], rng=random.Random(3))
    a, b = rs.endpoints
    a.ewma_ms = 50.0          # healthy, slow
    b.ewma_ms = 2.0           # was fast...
    b.breaker_open = True     # ...but is now sick
    chosen, decision = rs.pick()
    assert chosen is a        # steered around the degraded fast one
    assert decision.loser_ewma_ms == 0.0
    a.begin()
    rs.complete(a, decision, latency_s=0.050)  # 50ms >> b's old 2ms
    assert rs.mispicks == 0


def test_gateway_prunes_replica_sets_on_unregister():
    spec = sigmoid_spec()
    store = DeploymentStore()
    store.register(spec, {"p": ["http://a:1", "http://b:1"]})
    gw = ApiGateway(store, require_auth=False)
    reg = store._by_key["k"]
    gw._pick_engine(reg)
    assert ("rs-dep", "p") in gw._replica_sets
    store.unregister("k")
    assert gw.stats()["replicas"] == {}  # stats() prunes the stale set
    assert gw._replica_sets == {}


def test_replica_set_snapshot_imbalance():
    rs = ReplicaSet(["http://a:1", "http://b:1"])
    rs.endpoints[0].inflight = 3
    rs.endpoints[1].inflight = 1
    snap = rs.snapshot()
    assert snap["inflight_max_over_mean"] == pytest.approx(1.5)
    assert [e["endpoint"] for e in snap["endpoints"]] == [
        "http://a:1", "http://b:1"
    ]


# -- _pick_engine weight math ---------------------------------------------


def test_pick_engine_weighted_split_and_named_predictor():
    spec = sigmoid_spec(n_predictors=2)
    # weights 3:1 ride the predictors' replicas into the registration
    spec.predictors[0].replicas = 3
    spec.predictors[1].replicas = 1
    store = DeploymentStore()
    store.register(spec, {"p0": "http://p0:1", "p1": "http://p1:1"})
    gw = ApiGateway(store, require_auth=False, seed=11)
    reg = store._by_key["k"]
    served = [gw._pick_engine(reg)[0] for _ in range(200)]
    counts = {p: served.count(p) for p in set(served)}
    # 3:1 expectation with slack: p0 must dominate, p1 must get traffic
    assert counts["p0"] > counts["p1"] > 0
    assert counts["p0"] > 100
    # a named predictor bypasses the weighted draw entirely
    name, rs, ep, _ = gw._pick_engine(reg, predictor="p1")
    assert name == "p1" and ep.base_url == "http://p1:1"


def test_pick_engine_all_zero_replicas_uniform():
    """The replicas=0 edge: zero total weight falls back to uniform
    instead of dividing by zero."""
    spec = sigmoid_spec(n_predictors=2)
    for p in spec.predictors:
        p.replicas = 0
    store = DeploymentStore()
    store.register(spec, {"p0": "http://p0:1", "p1": "http://p1:1"})
    gw = ApiGateway(store, require_auth=False, seed=5)
    reg = store._by_key["k"]
    served = [gw._pick_engine(reg)[0] for _ in range(100)]
    counts = {p: served.count(p) for p in set(served)}
    assert counts.get("p0", 0) > 20 and counts.get("p1", 0) > 20


def test_replica_set_cache_rebuilds_on_reregistration():
    spec = sigmoid_spec()
    store = DeploymentStore()
    store.register(spec, {"p": ["http://a:1", "http://b:1"]})
    gw = ApiGateway(store, require_auth=False)
    reg = store._by_key["k"]
    _, rs1, _, _ = gw._pick_engine(reg)
    _, rs2, _, _ = gw._pick_engine(reg)
    assert rs1 is rs2  # cached between picks
    store.register(spec, {"p": ["http://a:1", "http://c:1"]})
    reg = store._by_key["k"]
    _, rs3, _, _ = gw._pick_engine(reg)
    assert rs3 is not rs1
    assert [e.name for e in rs3.endpoints] == ["http://a:1", "http://c:1"]


def test_decision_attrs_shape():
    assert ApiGateway._decision_attrs(None) == {}
    attrs = ApiGateway._decision_attrs(PickDecision(
        replica="http://b:1", candidates=["http://a:1", "http://b:1"],
        scores=[3.2, 1.1], loser_ewma_ms=4.0,
    ))
    assert attrs == {
        "replica": "http://b:1",
        "p2c_candidates": "http://a:1,http://b:1",
        "p2c_scores": "3.2,1.1",
    }


# -- gateway end-to-end: p2c steers around a slow replica ------------------


def test_gateway_steers_around_slow_inprocess_replica():
    RECORDER.reset()

    async def run():
        spec = sigmoid_spec()
        fast = EngineService(spec, max_batch=8, max_wait_ms=0.5)
        slow = FaultyEngine(
            EngineService(spec, max_batch=8, max_wait_ms=0.5),
            FaultSpec(delay_s=0.03),
        )
        store = DeploymentStore()
        store.register(spec, {"p": [fast, slow]})
        gw = ApiGateway(store, require_auth=False)

        # warm BOTH engines' jit caches outside the measured window:
        # this test pins STEADY-STATE p2c steering, and the one-off
        # compile otherwise poisons a coin-flip endpoint's first EWMA
        # sample (the stale-EWMA re-probe recovers it, but recovery
        # costs wall this short run can't always spare)
        await fast.predict(msg4())
        await slow.inner.predict(msg4())

        async def worker(n):
            for _ in range(n):
                resp = await gw.predict(msg4())
                assert resp.status is None or \
                    resp.status.status != "FAILURE"

        await asyncio.gather(*(worker(24) for _ in range(6)))
        snap = gw.stats()["replicas"]["rs-dep/p"]
        picks = [ep["picks"] for ep in snap["endpoints"]]
        assert sum(picks) == 144
        # blind rotation gives the slow replica half; p2c must starve it
        assert picks[1] / sum(picks) < 0.3
        # lane accounting says both dispatches rode the in-process lane
        lanes = RECORDER.snapshot()["replicas"]["lanes"]
        assert lanes.get("inprocess", 0) >= 144
        await gw.close()
        await fast.close()
        await slow.inner.close()

    asyncio.run(run())


def test_gateway_replicas_kill_switch_single_path(monkeypatch):
    """SELDON_TPU_REPLICAS=0 restores today's single-engine behavior:
    first endpoint, no decision attrs, no pick accounting."""
    RECORDER.reset()

    async def run():
        spec = sigmoid_spec()
        e0 = EngineService(spec, max_batch=8, max_wait_ms=0.5)
        e1 = EngineService(spec, max_batch=8, max_wait_ms=0.5)
        store = DeploymentStore()
        store.register(spec, {"p": [e0, e1]})
        gw = ApiGateway(store, require_auth=False)
        monkeypatch.setenv("SELDON_TPU_REPLICAS", "0")
        for _ in range(6):
            resp = await gw.predict(msg4())
            assert resp.status is None or resp.status.status != "FAILURE"
        snap = gw.stats()["replicas"]["rs-dep/p"]
        assert [ep["picks"] for ep in snap["endpoints"]] == [0, 0]
        assert RECORDER.snapshot()["replicas"]["picks"] == {}
        await gw.close()
        await e0.close()
        await e1.close()

    asyncio.run(run())


# -- sqlite store multi-engine registrations -------------------------------


def test_sqlite_store_replica_list_roundtrip(tmp_path):
    db = str(tmp_path / "gw.db")
    spec = sigmoid_spec(replicas=3)
    store = SqliteDeploymentStore(db)
    store.register(spec, {
        "p": ["http://e0:8000", "http://e1:8000+uds:/run/e1.sock"],
    })
    # a SECOND store over the same file (another gateway replica) sees
    # the same weighted replica set
    other = SqliteDeploymentStore(db)
    reg = other._registration("k")
    assert reg.engines == [
        ("p", 3, ["http://e0:8000", "http://e1:8000+uds:/run/e1.sock"]),
    ]
    store.close()
    other.close()


def test_sqlite_store_weight_clamps_and_rejections(tmp_path):
    db = str(tmp_path / "gw.db")
    spec = sigmoid_spec()
    spec.predictors[0].replicas = -2  # clamped to 0, not carried negative
    store = SqliteDeploymentStore(db)
    store.register(spec, {"p": "http://e0:8000"})
    assert store._registration("k").engines == [("p", 0, "http://e0:8000")]
    with pytest.raises(TypeError, match="non-empty list"):
        store.register(spec, {"p": []})
    with pytest.raises(TypeError, match="non-empty list"):
        store.register(spec, {"p": [object()]})
    with pytest.raises(TypeError, match="in-process"):
        store.register(spec, {"p": object()})
    store.close()


def test_gateway_main_replica_template_expansion(monkeypatch):
    from seldon_core_tpu.gateway.gateway_main import (
        _engine_replicas,
        _engine_url_map,
        _render_endpoints,
    )

    monkeypatch.setenv("GATEWAY_ENGINE_REPLICAS", "3")
    assert _engine_replicas() == 3
    monkeypatch.setenv("GATEWAY_ENGINE_REPLICAS", "0")
    with pytest.raises(SystemExit):
        _engine_replicas()
    monkeypatch.setenv("GATEWAY_ENGINE_REPLICAS", "x")
    with pytest.raises(SystemExit):
        _engine_replicas()

    tpl = "http://{name}-{predictor}-{replica}:8000"
    assert _render_endpoints(tpl, "d", "p", 2) == [
        "http://d-p-0:8000", "http://d-p-1:8000",
    ]
    # replicas>1 with no {replica} placeholder is fatal at boot — the
    # scale-out must never silently not exist
    from seldon_core_tpu.gateway.gateway_main import _check_replica_template

    assert _check_replica_template(4, tpl) == 4
    assert _check_replica_template(1, "http://{name}:8000") == 1
    with pytest.raises(SystemExit, match="needs a .replica."):
        _check_replica_template(4, "http://{name}:8000")

    monkeypatch.setenv(
        "GATEWAY_ENGINE_URL_MAP",
        json.dumps({"d/p": ["http://a:1", "uds:/run/a.sock"]}),
    )
    assert _engine_url_map() == {"d/p": ["http://a:1", "uds:/run/a.sock"]}
    monkeypatch.setenv("GATEWAY_ENGINE_URL_MAP", json.dumps({"d/p": []}))
    with pytest.raises(SystemExit, match="non-empty"):
        _engine_url_map()


# -- review-pass regressions ----------------------------------------------


def test_gateway_prunes_on_same_deployment_reregistration():
    # a re-registration that DROPS a predictor must prune its replica
    # set even though the deployment-ID list is unchanged — otherwise
    # the stale set is scraped (and its relay clients pooled) forever
    spec = sigmoid_spec(n_predictors=2)
    store = DeploymentStore()
    store.register(spec, {"p0": ["http://a:1", "http://b:1"],
                          "p1": ["http://c:1", "http://d:1"]})
    gw = ApiGateway(store, require_auth=False)
    reg = store._by_key["k"]
    gw._pick_engine(reg, "p0")
    gw._pick_engine(reg, "p1")
    assert ("rs-dep", "p0") in gw._replica_sets
    assert ("rs-dep", "p1") in gw._replica_sets
    store.register(spec, {"p0": ["http://a:1", "http://b:1"]})
    gw.stats()  # stats() runs the prune
    assert ("rs-dep", "p0") in gw._replica_sets
    assert ("rs-dep", "p1") not in gw._replica_sets


def test_sqlite_store_revision_bumps_on_every_write(tmp_path):
    store = SqliteDeploymentStore(str(tmp_path / "gw.db"))
    spec = sigmoid_spec()
    assert store.revision() == 0
    store.register(spec, {"p": ["http://a:1"]})
    r1 = store.revision()
    store.register(spec, {"p": ["http://a:1", "http://b:1"]})  # same dep
    r2 = store.revision()
    store.unregister("k")
    r3 = store.revision()
    assert r1 < r2 < r3
    # a second gateway replica on the same file sees the bumps
    other = SqliteDeploymentStore(str(tmp_path / "gw.db"))
    assert other.revision() == r3
    store.close()
    other.close()


def test_batcher_inflight_tracks_only_batcher_dispatches():
    ep = ReplicaEndpoint("http://a:1")
    ep.begin()                 # unary predict: rides the MicroBatcher
    ep.begin(batcher=False)    # stream/feedback: engine-side, unbatched
    assert (ep.inflight, ep.batcher_inflight) == (2, 1)
    ep.release()               # stream end
    assert (ep.inflight, ep.batcher_inflight) == (1, 1)
    ep.complete(0.01)          # unary end
    assert (ep.inflight, ep.batcher_inflight) == (0, 0)
    ep.begin()
    ep.release(batcher=True)   # neutral-accounting unary close
    assert (ep.inflight, ep.batcher_inflight) == (0, 0)


def test_scrape_subtracts_only_own_batcher_inflight():
    # the engine-side inflight_dispatches figure contains only batcher
    # work: subtracting streams/feedback (which never enter the batcher)
    # would erase OTHER gateways' real load from the score signal
    class _Resp:
        def __init__(self, doc):
            self._doc = doc

        async def json(self, content_type=None):
            return self._doc

        async def __aenter__(self):
            return self

        async def __aexit__(self, *a):
            return False

    class _Session:
        def get(self, url, timeout=None):
            return _Resp({"telemetry": {"batch": {"inflight_dispatches": 5}}})

    rs = ReplicaSet(["http://a:1"])
    ep = rs.endpoints[0]
    ep.begin()                 # 1 own unary in the engine's figure
    ep.begin(batcher=False)    # own stream: NOT in the engine's figure
    ep.begin(batcher=False)
    assert asyncio.run(rs.scrape_once(_Session())) == 1
    # 5 engine-side - 1 own batcher-bound = 4 (other gateways' load kept)
    assert ep.scraped_inflight == 4


def test_pick_eligibility_filter_lands_picks_on_capable_endpoint():
    rs = ReplicaSet(["uds:/run/a.sock", "http://b:1"],
                    rng=random.Random(7))
    streamable = lambda ep: ep.base_url is not None
    for _ in range(8):
        ep, decision = rs.pick(streamable)
        assert ep.base_url == "http://b:1"
        assert decision is not None and decision.replica == ep.name
    # picks (and their metrics) land on the endpoint that serves
    assert rs.endpoints[1].picks == 8
    assert rs.endpoints[0].picks == 0
    # an impossible filter falls back to the full pool — the caller
    # handles the capability miss (e.g. streams answer 503)
    ep, _ = rs.pick(lambda _ep: False)
    assert ep in rs.endpoints


def test_cancelled_predict_is_neutral_for_replica_health():
    # a client hanging up (handler cancellation) says nothing about the
    # replica: it must not feed the failure streak that fail-degrades,
    # and it must release the inflight it began
    class Wedged:
        def __init__(self):
            self.gate = asyncio.Event()

        async def predict(self, msg):
            await self.gate.wait()
            return msg

    async def run():
        spec = sigmoid_spec()
        store = DeploymentStore()
        wedged = Wedged()
        store.register(spec, {"p": [wedged]})
        gw = ApiGateway(store, require_auth=False)
        task = asyncio.create_task(gw.predict(msg4()))
        await asyncio.sleep(0.05)
        ep = gw._replica_sets[("rs-dep", "p")][1].endpoints[0]
        assert (ep.inflight, ep.batcher_inflight) == (1, 1)
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task
        assert (ep.inflight, ep.batcher_inflight) == (0, 0)
        assert ep.consec_failures == 0
        assert ep.failures == 0
        assert ep.fail_degraded_until == 0.0
        await gw.close()

    asyncio.run(run())


# -- stale-EWMA re-probe: a poisoned endpoint cannot be starved forever ----


def test_stale_ewma_reprobe_floors_score_and_reseeds(monkeypatch):
    """An idle, healthy endpoint whose only sample ate a one-off cost
    (jit compile) used to keep losing p2c forever — its EWMA never got
    a correcting sample.  Pin the escape hatch: past the re-probe
    window the score floors to attract ONE probe (not while one is
    already out), and a probe that contradicts the stale history beyond
    the trust region RESEEDS the EWMA instead of blending."""
    import time as _time

    ep = ReplicaEndpoint("http://a:1")
    ep.begin()
    ep.complete(0.400)  # compile-poisoned first sample
    assert ep.ewma_ms == pytest.approx(400.0)
    now = _time.monotonic()
    # fresh sample: full price
    assert ep.score(now, 10.0) == pytest.approx(400.0)
    # idle past the window: floor-priced so p2c sends a probe
    ep.last_sample_ts = now - 1.0
    assert ep.score(now, 10.0) == pytest.approx(_EWMA_FLOOR_MS)
    # ...but not while the probe is in flight (no pile-on)
    ep.begin()
    assert ep.score(now, 10.0) == pytest.approx(2 * 400.0)
    # the probe lands 200x below the stale EWMA: reseed, not blend
    ep.complete(0.002)
    assert ep.ewma_ms == pytest.approx(2.0)
    assert ep.ewma_reseeds == 1
    # a stale probe WITHIN the trust region keeps the smoothing blend
    ep.last_sample_ts = _time.monotonic() - 1.0
    ep.begin()
    ep.complete(0.003)
    assert ep.ewma_ms == pytest.approx(
        (1 - _EWMA_ALPHA) * 2.0 + _EWMA_ALPHA * 3.0)
    assert ep.ewma_reseeds == 1
    # rapid traffic (fresh samples) blends no matter how far off
    before = ep.ewma_ms
    ep.begin()
    ep.complete(1.0)
    assert ep.ewma_ms == pytest.approx(
        (1 - _EWMA_ALPHA) * before + _EWMA_ALPHA * 1000.0)
    assert ep.ewma_reseeds == 1
    # SELDON_TPU_REPROBE_S=0 disables the hatch entirely
    monkeypatch.setenv("SELDON_TPU_REPROBE_S", "0")
    ep.last_sample_ts = _time.monotonic() - 99.0
    assert ep.score(_time.monotonic(), 10.0) == pytest.approx(ep.ewma_ms)


def test_reprobe_never_rescues_a_degraded_endpoint():
    """The floor price is an exploration grant for HEALTHY endpoints —
    a degraded one keeps its penalty no matter how stale its EWMA."""
    import time as _time

    ep = ReplicaEndpoint("http://a:1")
    ep.begin()
    ep.complete(0.400)
    now = _time.monotonic()
    ep.last_sample_ts = now - 99.0
    ep.fail_degraded_until = now + 60.0
    assert ep.score(now, 10.0) > _UNHEALTHY_PENALTY
