"""Cross-language conformance: the SAME suite drives every non-Python
model-server lane through the engine's remote REST runtime — the
guarantee that the internal API (docs/internal-api.md) admits any
language, the way the reference's R and Java wrappers did
(wrappers/s2i/R/microservice.R).

Lanes (each skipped when its toolchain is absent; the CI image installs
all three toolchains so CI skips none):
  * cpp  — zero-dependency C++ server (examples/cpp_model/model_server.cpp)
  * r    — zero-package base-R server (wrappers/R/microservice.R)
  * java — zero-dependency JDK server (wrappers/java/ModelServer.java)

All implement the conformance semantics: scale features by the `scale`
FLOAT parameter, output name "scaled", kind preservation, /send-feedback.
"""

import asyncio
import json
import os
import shutil
import socket
import subprocess
import time

import numpy as np
import pytest

from seldon_core_tpu.graph.spec import SeldonDeploymentSpec
from seldon_core_tpu.runtime.engine import EngineService

ROOT = os.path.join(os.path.dirname(__file__), "..")
SRC = os.path.join(ROOT, "examples", "cpp_model", "model_server.cpp")
R_SERVER = os.path.join(ROOT, "wrappers", "R", "microservice.R")
R_MODEL = os.path.join(ROOT, "wrappers", "R", "example_model.R")
JAVA_SRC = os.path.join(ROOT, "wrappers", "java", "ModelServer.java")

PARAMS = json.dumps([{"name": "scale", "value": "2.0", "type": "FLOAT"}])

LANES = ["cpp", "r", "java"]


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_lane(lane, tmp_path_factory):
    port = free_port()
    env = dict(
        os.environ,
        PREDICTIVE_UNIT_SERVICE_PORT=str(port),
        PREDICTIVE_UNIT_PARAMETERS=PARAMS,
    )
    if lane == "cpp":
        if shutil.which("g++") is None:
            pytest.skip("no C++ toolchain")
        binary = str(tmp_path_factory.mktemp("cpp") / "model_server")
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-pthread", "-o", binary, SRC],
            check=True,
        )
        cmd = [binary]
    elif lane == "r":
        if shutil.which("Rscript") is None:
            pytest.skip("no R toolchain (scripts/toolchain_probe.py "
                        "records what this host has)")
        cmd = ["Rscript", R_SERVER, "--model", R_MODEL, "--service", "MODEL"]
    elif lane == "java":
        if shutil.which("javac") is None or shutil.which("java") is None:
            pytest.skip("no JVM toolchain (scripts/toolchain_probe.py "
                        "records what this host has — bazel's embedded "
                        "JRE lacks jdk.compiler)")
        outdir = str(tmp_path_factory.mktemp("java"))
        subprocess.run(["javac", "-d", outdir, JAVA_SRC], check=True)
        cmd = ["java", "-cp", outdir, "ModelServer"]
    else:  # pragma: no cover
        raise ValueError(lane)
    proc = subprocess.Popen(cmd, env=env, stderr=subprocess.PIPE)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port), 0.2).close()
            break
        except OSError:
            if proc.poll() is not None:
                pytest.fail(
                    f"{lane} model server exited: "
                    f"{proc.stderr.read().decode()[-2000:]}"
                )
            time.sleep(0.1)
    else:
        proc.kill()
        pytest.fail(f"{lane} model server did not come up")
    return proc, port


@pytest.fixture(scope="module", params=LANES)
def server(request, tmp_path_factory):
    proc, port = _spawn_lane(request.param, tmp_path_factory)
    yield port
    proc.kill()
    proc.wait()


def engine_for(port):
    spec = SeldonDeploymentSpec.from_json_dict({
        "spec": {
            "name": "cpp-conformance",
            "predictors": [{
                "name": "p",
                "graph": {"name": "scaler", "type": "MODEL"},
                "components": [{
                    "name": "scaler",
                    "runtime": "rest",
                    "host": "127.0.0.1",
                    "port": port,
                }],
            }],
        }
    })
    return EngineService(spec)


def test_predict_through_engine_ndarray(server):
    engine = engine_for(server)
    assert engine.mode == "host"  # remote node: interpreter + pooled client

    async def run():
        text, status = await engine.predict_json(
            '{"data":{"ndarray":[[1.0, 2.5], [3.0, -4.0]]}}'
        )
        assert status == 200
        doc = json.loads(text)
        np.testing.assert_allclose(
            doc["data"]["ndarray"], [[2.0, 5.0], [6.0, -8.0]]
        )
        assert doc["data"]["names"] == ["scaled"]
        assert doc["meta"]["puid"]  # engine-assigned correlation id

    asyncio.run(run())


def test_predict_preserves_tensor_kind(server):
    engine = engine_for(server)

    async def run():
        text, status = await engine.predict_json(
            '{"data":{"tensor":{"shape":[1,3],"values":[1.0,2.0,3.0]}}}'
        )
        assert status == 200
        doc = json.loads(text)
        assert doc["data"]["tensor"]["shape"] == [1, 3]
        np.testing.assert_allclose(
            doc["data"]["tensor"]["values"], [2.0, 4.0, 6.0]
        )

    asyncio.run(run())


def test_feedback_through_engine(server):
    engine = engine_for(server)

    async def run():
        from seldon_core_tpu.messages import Feedback

        fb = Feedback.from_json(json.dumps({
            "request": {"data": {"ndarray": [[1.0]]}},
            "response": {"data": {"ndarray": [[2.0]]}},
            "reward": 1.0,
        }))
        ack = await engine.send_feedback(fb)
        assert ack.status is None or ack.status.status == "SUCCESS"

    asyncio.run(run())


def test_contract_validates_cpp_server_responses(server):
    """Contract-driven conformance against the internal /predict route:
    generated requests in, responses validated against the declared
    targets (the language-independent check any new wrapper must pass)."""
    import aiohttp

    from seldon_core_tpu.messages import SeldonMessage
    from seldon_core_tpu.testing.contract import (
        Contract,
        generate_batch,
        validate_response,
    )

    contract = Contract(
        features=[
            {"name": "x", "dtype": "FLOAT", "ftype": "continuous",
             "range": [0, 1], "repeat": 2}
        ],
        targets=[
            {"name": "scaled", "dtype": "FLOAT", "ftype": "continuous",
             "range": [0, 2], "repeat": 2}
        ],
    )

    async def run():
        async with aiohttp.ClientSession() as session:
            for seed in range(4):
                msg = generate_batch(contract, 2, seed=seed)
                async with session.post(
                    f"http://127.0.0.1:{server}/predict",
                    data=msg.to_json(),
                ) as r:
                    assert r.status == 200
                    resp = SeldonMessage.from_json(await r.text())
                problems = validate_response(contract, resp)
                assert problems == [], problems

    asyncio.run(run())


def test_concurrent_requests_through_engine(server):
    engine = engine_for(server)

    async def run():
        async def one(i):
            text, status = await engine.predict_json(
                json.dumps({"data": {"ndarray": [[float(i)]]}})
            )
            assert status == 200
            assert json.loads(text)["data"]["ndarray"] == [[2.0 * i]]

        await asyncio.gather(*[one(i) for i in range(16)])

    asyncio.run(run())
