"""Int8 quantized serving: weight/activation quantization numerics, argmax
stability vs the f32 MLP, and the quantized unit through the engine."""

import asyncio
import json

import jax
import jax.numpy as jnp
import numpy as np

from seldon_core_tpu.models.mnist import MnistClassifier, QuantizedMnistClassifier
from seldon_core_tpu.ops.quant import (
    QuantizedMLP,
    quant_matmul,
    quantize_mlp_params,
    quantize_weight,
)


def test_quantize_weight_roundtrip_error_bounded():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(64, 32)), jnp.float32)
    w_q, s = quantize_weight(w)
    assert w_q.dtype == jnp.int8 and s.shape == (32,)
    deq = np.asarray(w_q, np.float32) * np.asarray(s)[None, :]
    err = np.abs(deq - np.asarray(w)).max()
    # per-channel symmetric: error bounded by half a quantization step
    assert err <= float(np.asarray(s).max()) * 0.5 + 1e-6


def test_dequant_matmul_preserves_input_rank():
    from seldon_core_tpu.ops.quant import dequant_matmul

    rng = np.random.default_rng(2)
    w = jnp.asarray(rng.normal(size=(64, 32)), jnp.float32)
    w_q, s = quantize_weight(w)
    # rank-1 input -> rank-1 [out] output, same as a plain matmul
    x1 = jnp.asarray(rng.normal(size=(64,)), jnp.float32)
    y1 = dequant_matmul(x1, w_q, s)
    assert y1.shape == (32,)
    # rank-3 leading dims pass through
    x3 = jnp.asarray(rng.normal(size=(2, 3, 64)), jnp.float32)
    assert dequant_matmul(x3, w_q, s).shape == (2, 3, 32)
    ref = np.asarray(x1 @ w)
    assert np.abs(np.asarray(y1) - ref).max() / np.abs(ref).max() < 0.02


def test_quant_matmul_close_to_f32():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(8, 64)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(64, 32)), jnp.float32)
    w_q, s = quantize_weight(w)
    got = np.asarray(quant_matmul(x, w_q, s))
    ref = np.asarray(x @ w)
    # relative error ~1% for int8 dynamic quantization
    assert np.abs(got - ref).max() / np.abs(ref).max() < 0.02


def test_quantized_mlp_argmax_agrees_with_f32():
    f32_unit = MnistClassifier(hidden=64, depth=2, dtype="float32",
                               use_pallas="never")
    q_unit = QuantizedMnistClassifier(hidden=64, depth=2, dtype="float32",
                                      use_pallas="never")
    f32_state = f32_unit.init_state(jax.random.key(0))
    q_state = q_unit.init_state(jax.random.key(0))
    X = jnp.asarray(np.random.default_rng(2).normal(size=(256, 784)),
                    jnp.float32)
    p_f32 = np.asarray(f32_unit.predict(f32_state, X))
    p_q = np.asarray(q_unit.predict(q_state, X))
    np.testing.assert_allclose(p_q.sum(axis=1), 1.0, atol=1e-5)
    # an untrained random MLP is the WORST case for argmax stability (its
    # logits are near-uniform, so borderline rows flip on tiny noise);
    # probabilities must still be close and agreement high
    assert np.abs(p_f32 - p_q).max() < 0.05
    agree = (p_f32.argmax(1) == p_q.argmax(1)).mean()
    assert agree >= 0.95, f"argmax agreement {agree}"


def test_quantized_unit_serves_through_engine():
    from seldon_core_tpu.graph.spec import SeldonDeploymentSpec
    from seldon_core_tpu.runtime.engine import EngineService

    spec = SeldonDeploymentSpec.from_json_dict({
        "spec": {"name": "q", "predictors": [{
            "name": "p",
            "graph": {"name": "m", "type": "MODEL"},
            "components": [{
                "name": "m", "runtime": "inprocess",
                "class_path": "QuantizedMnistClassifier",
                "parameters": [{"name": "hidden", "value": "32",
                                "type": "INT"}],
            }],
        }]}
    })
    engine = EngineService(spec)
    assert engine.mode == "compiled" and engine.batcher is not None

    async def run():
        text, status = await engine.predict_json(
            json.dumps({"data": {"ndarray": np.zeros((2, 784)).tolist()}})
        )
        assert status == 200
        probs = np.asarray(json.loads(text)["data"]["ndarray"])
        assert probs.shape == (2, 10)
        np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-4)

    asyncio.run(run())


def test_quantized_lm_generate_matches_shapes_and_quality():
    """Int8 transformer serving (quantize_lm_params + lm_matmul): the
    quantized generator produces valid token ids and the quantized
    TransformerLM's logits track the bf16 model's argmax closely."""
    from seldon_core_tpu.models.generate import TransformerGenerator
    from seldon_core_tpu.models.transformer import (
        LMConfig, TransformerLM, lm_apply, lm_init,
    )
    from seldon_core_tpu.ops.quant import quantize_lm_params

    cfg = LMConfig(vocab=64, d_model=64, n_heads=4, n_layers=2, d_ff=128,
                   dtype=jnp.float32)
    params = lm_init(jax.random.key(0), cfg)
    qparams = quantize_lm_params(params)
    # every layer weight replaced by _q/_s; embed and norms untouched
    assert "wqkv_q" in qparams["l0"] and "wqkv" not in qparams["l0"]
    assert qparams["l0"]["wqkv_q"].dtype == jnp.int8
    assert qparams["embed"] is params["embed"]

    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, size=(4, 16)), jnp.int32
    )
    logits = np.asarray(lm_apply(params, tokens, cfg))
    cfg_q = LMConfig(vocab=64, d_model=64, n_heads=4, n_layers=2, d_ff=128,
                     dtype=jnp.float32, quant="int8")
    qlogits = np.asarray(lm_apply(qparams, tokens, cfg_q))
    assert qlogits.shape == logits.shape
    agree = (logits.argmax(-1) == qlogits.argmax(-1)).mean()
    assert agree >= 0.9, f"argmax agreement {agree}"

    # the full serving unit: quantized weights, cached decode loop
    gen = TransformerGenerator(vocab=64, d_model=64, n_heads=4, n_layers=2,
                               d_ff=128, max_new_tokens=8, dtype="float32",
                               quant="int8")
    state = gen.init_state(jax.random.key(1))
    y = np.asarray(gen.predict(state, jnp.zeros((2, 4), jnp.float32)))
    assert y.shape == (2, 8)
    assert ((y >= 0) & (y < 64)).all()

    # the quantized LM unit serves logits too
    lm = TransformerLM(vocab=64, d_model=64, n_heads=4, n_layers=2,
                       d_ff=128, dtype="float32", quant="int8")
    lstate = lm.init_state(jax.random.key(2))
    out = np.asarray(lm.predict(lstate, jnp.zeros((2, 4), jnp.float32)))
    assert out.shape == (2, 4, 64)


def test_attention_parameter_modes():
    """attention=xla|flash|auto resolve to a static flash decision;
    invalid values fail at construction (graph-load time)."""
    import pytest

    from seldon_core_tpu.models.transformer import TransformerLM, resolve_flash

    assert resolve_flash("xla", None) is False
    assert isinstance(resolve_flash("auto", None), bool)
    # 'flash' prefers the kernel but still degrades on unsupported
    # runtimes instead of crash-looping the pod
    assert resolve_flash("flash", None) == resolve_flash("auto", None)
    with pytest.raises(ValueError):
        TransformerLM(attention="nope")
    with pytest.raises(ValueError):
        TransformerLM(quant="fp4")


def test_quant_lm_training_guarded():
    import optax
    import pytest

    from seldon_core_tpu.models.transformer import LMConfig, lm_train_step

    cfg = LMConfig(quant="int8")
    with pytest.raises(ValueError):
        lm_train_step({}, {}, {}, optax.sgd(1e-2), cfg)
