"""Reconciler vs a HOSTILE API server — the real k8s API's failure modes
the reference controller hardens against: create races and 404-create vs
conflict-update (SeldonDeploymentControllerImpl.java:69-111), stale
resourceVersions (SeldonDeploymentWatcher.java:89-153), mid-reconcile CR
deletion, status-patch conflicts, and transient API errors.

Every scenario asserts two properties: the loop never crashes, and the
cluster CONVERGES (possibly on the next tick) with no orphaned resources.
"""

import copy
import json
import os

import pytest

from seldon_core_tpu.operator.reconciler import (
    HASH_ANNOTATION,
    HostileKubeApi,
    KubeConflict,
    OWNER_LABEL,
    Reconciler,
)

EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples")


def load_cr(name="iris", example="iris_deployment.json"):
    with open(os.path.join(EXAMPLES, example)) as f:
        cr = json.load(f)
    md = cr.setdefault("metadata", {})
    md["name"] = name
    md.setdefault("namespace", "default")
    cr.setdefault("kind", "SeldonDeployment")
    return cr


@pytest.fixture()
def api():
    return HostileKubeApi()


@pytest.fixture()
def rec(api):
    return Reconciler(api)


def converged(api, rec, name):
    """Steady-state check: a reconcile tick issues zero resource writes and
    every desired resource exists with a non-phantom hash."""
    api.clear_ops()
    rec.run_once()
    writes = [op for op in api.ops
              if op[0] in ("create", "replace", "delete")]
    assert writes == [], f"not converged: {writes}"
    for obj in api.list("Deployment", "default", {OWNER_LABEL: name}):
        h = obj["metadata"]["annotations"].get(HASH_ANNOTATION)
        assert h and h != "phantom"


# -- failure mode 1: create race -------------------------------------------

def test_create_race_converges_same_pass(api, rec):
    """Another actor creates the engine Deployment between the reconciler's
    GET miss and its POST: the create's AlreadyExists must fall back to
    replace, converging in the SAME pass (no failed tick)."""
    api.create(load_cr())
    # discover the first rendered names with a throwaway reconcile on a
    # pristine clone, then arm the race for every one of them
    probe_api = HostileKubeApi()
    probe_api.create(load_cr())
    Reconciler(probe_api).run_once()
    for (kind, _, name) in list(probe_api.objects):
        if kind in ("Deployment", "Service"):
            api.race_on_get_miss.append((kind, name))

    results = rec.run_once()
    assert results["iris"].get("failed", 0) == 0
    # every racer's phantom got replaced by the real rendering
    assert results["iris"]["updates"] >= 1
    converged(api, rec, "iris")


# -- failure mode 2: stale-resourceVersion conflict on replace -------------

def test_stale_rv_conflict_retried(api, rec):
    api.create(load_cr())
    rec.run_once()
    # change the spec so the next tick must replace, and inject a 409 on
    # the engine Deployment write
    cr = api.get("SeldonDeployment", "default", "iris")
    cr["spec"]["predictors"][0]["replicas"] = 3
    api.replace(cr)
    api.fail_queue.append(("replace", "Deployment/", KubeConflict("409")))
    results = rec.run_once()
    assert results["iris"].get("failed", 0) == 0
    dep = api.list("Deployment", "default", {OWNER_LABEL: "iris"})[0]
    assert dep["spec"]["replicas"] == 3
    converged(api, rec, "iris")


def test_persistent_conflict_isolates_cr_then_recovers(api, rec):
    """A conflict that outlives the retry budget fails the CR's tick
    without crashing the loop; the next clean tick converges."""
    api.create(load_cr())
    rec.run_once()
    cr = api.get("SeldonDeployment", "default", "iris")
    cr["spec"]["predictors"][0]["replicas"] = 5
    api.replace(cr)
    for _ in range(4):  # more than the retry budget
        api.fail_queue.append(("replace", "Deployment/", KubeConflict("409")))
    results = rec.run_once()
    assert results["iris"].get("failed", 0) == 1
    api.fail_queue.clear()
    results = rec.run_once()
    assert results["iris"].get("failed", 0) == 0
    dep = api.list("Deployment", "default", {OWNER_LABEL: "iris"})[0]
    assert dep["spec"]["replicas"] == 5
    converged(api, rec, "iris")


# -- failure mode 3: resource deleted under the reconciler -----------------

def test_resource_deleted_mid_pass_recreated(api, rec):
    """replace() hits NotFound because a hostile actor deleted the live
    object after the GET: the reconciler must fall back to create."""
    api.create(load_cr())
    rec.run_once()
    cr = api.get("SeldonDeployment", "default", "iris")
    cr["spec"]["predictors"][0]["replicas"] = 2
    api.replace(cr)
    dep_name = api.list(
        "Deployment", "default", {OWNER_LABEL: "iris"}
    )[0]["metadata"]["name"]

    # delete the Deployment between the reconciler's GET and its replace:
    # emulate by a fail hook that deletes then raises KeyError (NotFound)
    class Vanish(KeyError):
        pass

    del api.objects[("Deployment", "default", dep_name)]
    api.fail_queue.append(("replace", f"Deployment/{dep_name}",
                           Vanish("not found")))
    results = rec.run_once()
    assert results["iris"].get("failed", 0) == 0
    dep = api.get("Deployment", "default", dep_name)
    assert dep is not None and dep["spec"]["replicas"] == 2
    converged(api, rec, "iris")


# -- failure mode 4: CR deleted mid-reconcile ------------------------------

def test_cr_deleted_mid_reconcile_no_crash_then_prune(api, rec):
    """The CR vanishes after rendering but before the status write-back:
    the tick must not crash, and the NEXT tick prunes every orphan."""
    api.create(load_cr())
    api.delete_crs_after_writes = 1  # vanish after the first created child
    results = rec.run_once()
    assert "iris" in results  # tick completed
    # owned resources exist but the CR is gone
    assert api.get("SeldonDeployment", "default", "iris") is None
    results = rec.run_once()
    assert results["iris"]["deletes"] >= 1
    assert api.list("Deployment", "default", {OWNER_LABEL: "iris"}) == []
    assert api.list("Service", "default", {OWNER_LABEL: "iris"}) == []


# -- failure mode 5: status-patch conflict ---------------------------------

def test_status_patch_conflict_retried(api, rec):
    api.create(load_cr())
    api.fail_queue.append(
        ("patch_status", "SeldonDeployment/iris", KubeConflict("409"))
    )
    results = rec.run_once()
    assert results["iris"].get("failed", 0) == 0
    status = api.get("SeldonDeployment", "default", "iris").get("status")
    assert status and status["state"] in ("Creating", "Available")


# -- failure mode 6: transient API 500s ------------------------------------

def test_transient_api_error_isolates_cr_and_recovers(api, rec):
    """An API flake mid-reconcile fails only that CR's tick (run_once's
    isolation contract) and the next tick converges with no orphans."""
    api.create(load_cr("a"))
    api.create(load_cr("b", "mnist_deployment.json"))
    api.fail_queue.append(("create", "Deployment/", RuntimeError("API 500")))
    results = rec.run_once()
    failed = [n for n, r in results.items() if r.get("failed")]
    ok = [n for n, r in results.items() if not r.get("failed")]
    assert len(failed) == 1 and len(ok) == 1  # isolation
    results = rec.run_once()
    assert all(not r.get("failed") for r in results.values())
    for name in ("a", "b"):
        assert api.list("Deployment", "default", {OWNER_LABEL: name})
        converged(api, rec, name)


# -- steady state stays zero-write under RV bookkeeping --------------------

def test_steady_state_zero_writes_including_status(api, rec):
    """With resourceVersions live, an unchanged status must NOT be patched
    every tick (each patch bumps the CR RV and would retrigger watchers) —
    total write silence at steady state."""
    api.create(load_cr())
    rec.run_once()
    api.mark_deployments_ready()
    rec.run_once()
    api.clear_ops()
    rec.run_once()
    writes = [op for op in api.ops
              if op[0] in ("create", "replace", "delete", "patch_status")]
    assert writes == []
