"""Continuous-batching generation scheduler (runtime/genserver.py): the
block allocator's alloc/free/reuse arithmetic, admission/retirement
ordering, pool-exhaustion queueing (never crashing), and the defining
equivalence — scheduler output token-identical to one-shot ``generate()``
for the same prompts/seeds, through chunked prefill, the paged decode
round, int8 KV pools, shared-prefix block reuse, and speculative
draft/verify rounds."""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from seldon_core_tpu.models.generate import generate, init_cache, prefill
from seldon_core_tpu.models.transformer import LMConfig, lm_init
from seldon_core_tpu.runtime.genserver import BlockAllocator, GenServer

CFG = LMConfig(vocab=48, d_model=32, n_heads=4, n_layers=2, d_ff=64,
               dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return lm_init(jax.random.key(3), CFG)


def _server(params, **kw):
    kw.setdefault("max_new_tokens", 10)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 64)
    kw.setdefault("slots", 8)
    kw.setdefault("span", 3)
    kw.setdefault("prefill_chunk", 4)
    return GenServer(params, kw.pop("cfg", CFG), **kw)


def _settle(srv, timeout=10.0):
    """Wait until the scheduler drained (retirement runs a beat after the
    last token is delivered)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        s = srv.snapshot()
        if not s["inflight_sequences"] and not s["waiting_sequences"]:
            return s
        time.sleep(0.01)
    raise AssertionError("scheduler did not settle")


# -- block allocator ---------------------------------------------------------


def test_allocator_alloc_free_reuse():
    a = BlockAllocator(8)          # block 0 is scratch
    assert a.capacity == 7
    x = a.alloc(3)
    y = a.alloc(2)
    assert x == [1, 2, 3] and y == [4, 5] and a.used == 5
    assert a.high_water == 5
    a.free(x)
    assert a.used == 2
    # freed ids are reused (FIFO through the free list): no fragmentation
    # is possible by construction — any free block serves any sequence
    z = a.alloc(4)
    assert z == [6, 7, 1, 2] and a.used == 6  # remaining, then freed ids
    assert a.high_water == 6


def test_allocator_exhaustion_returns_none():
    a = BlockAllocator(4)
    assert a.alloc(3) is not None
    assert a.alloc(1) is None      # exhausted: caller queues, no throw
    assert not a.can_alloc(1)


def test_allocator_pinned_blocks_never_freed():
    a = BlockAllocator(6)
    shared = a.alloc(2)
    a.pin(shared)
    a.free(shared)                 # a retiring sequence "frees" its table
    assert a.used == 2             # shared prefix blocks stay resident
    assert not any(b in (a.alloc(3) or []) for b in shared)


# -- the defining equivalence ------------------------------------------------


def test_scheduler_tokens_identical_to_generate(params):
    """Chunked prefill (prompt 7 through chunk-4 pieces) + paged decode
    rounds must reproduce one-shot generate() token-for-token (greedy,
    f32) — including across co-scheduled requests."""
    prompts = np.random.default_rng(0).integers(0, 48, size=(3, 7))
    ref = np.asarray(generate(params, jnp.asarray(prompts, jnp.int32),
                              CFG, max_new_tokens=10))
    srv = _server(params)
    try:
        # two requests in flight at once: rows co-batch in the decode
        # round, outputs stay per-row identical
        r1 = srv.submit(prompts[:2].astype(float))
        r2 = srv.submit(prompts[2:].astype(float))
        got = np.concatenate(
            [r1.future.result(timeout=180), r2.future.result(timeout=180)]
        )
        np.testing.assert_array_equal(got, ref)
    finally:
        srv.stop()


def test_scheduler_stream_matches_unary(params):
    prompts = np.random.default_rng(1).integers(0, 48, size=(2, 5))
    ref = np.asarray(generate(params, jnp.asarray(prompts, jnp.int32),
                              CFG, max_new_tokens=10))
    srv = _server(params)
    try:
        chunks = [c for c in srv.stream(prompts.astype(float), chunk=4)]
        assert [c.shape[1] for c in chunks] == [4, 4, 2]
        np.testing.assert_array_equal(np.concatenate(chunks, axis=1), ref)
    finally:
        srv.stop()


def test_scheduler_eos_contract(params):
    """Rows that emit eos retire early; output is eos-padded exactly like
    generate(eos_token=...) + mask_after_eos."""
    prompt = np.random.default_rng(0).integers(0, 48, size=(1, 7))
    base = np.asarray(generate(params, jnp.asarray(prompt, jnp.int32),
                               CFG, max_new_tokens=10))[0]
    eos = int(base[0])  # greedy untrained models repeat: position 0 works
    ref = np.asarray(generate(params, jnp.asarray(prompt, jnp.int32),
                              CFG, max_new_tokens=10, eos_token=eos))
    srv = _server(params, eos_token=eos)
    try:
        got = srv.submit(prompt.astype(float)).future.result(timeout=180)
        np.testing.assert_array_equal(got, ref)
        s = _settle(srv)
        assert s["retired_total"].get("eos", 0) == 1
        assert s["kv_blocks"]["used"] == 0  # retirement freed the blocks
    finally:
        srv.stop()


def test_scheduler_int8_kv_pool(params):
    """kv_quant='int8' pools: quantized scatter + scale-plane gather
    through the whole scheduler path — valid tokens (exactness is not
    claimed, same class as every int8-KV read-back)."""
    import dataclasses

    cfg_q = dataclasses.replace(CFG, kv_quant="int8")
    prompts = np.random.default_rng(2).integers(0, 48, size=(2, 5))
    srv = _server(params, cfg=cfg_q, max_new_tokens=8)
    try:
        got = srv.submit(prompts.astype(float)).future.result(timeout=180)
        assert got.shape == (2, 8)
        assert (got >= 0).all() and (got < 48).all()
    finally:
        srv.stop()


def test_scheduler_prefix_cache_shared_blocks(params):
    """A shared B=1 prefix cache: full blocks written once and pinned,
    per-sequence tail copy, outputs equal full-prompt generate()."""
    rng = np.random.default_rng(11)
    prefix_ids = rng.integers(0, 48, size=(6,)).tolist()
    sufs = rng.integers(0, 48, size=(3, 5))
    full = np.concatenate(
        [np.broadcast_to(np.asarray(prefix_ids), (3, 6)), sufs], axis=1)
    ref = np.asarray(generate(params, jnp.asarray(full, jnp.int32), CFG,
                              max_new_tokens=10))
    pc = init_cache(CFG, 1, len(prefix_ids))
    _, pc = prefill(params, jnp.asarray([prefix_ids], jnp.int32), pc, CFG)
    srv = _server(params, prefix_cache=pc)
    try:
        got = srv.submit(sufs.astype(float)).future.result(timeout=180)
        np.testing.assert_array_equal(got, ref)
        snap = _settle(srv)
        # prefix len 6, block 4: one full block pinned + shared, the
        # 2-token tail copied per-sequence into private blocks
        assert snap["kv_blocks"]["pinned"] == 1
        assert snap["kv_blocks"]["used"] == 1  # only the pinned block stays
    finally:
        srv.stop()


def test_scheduler_speculative_rounds():
    """Speculative mode: draft k+1 paged steps + one verify per round;
    output equals vanilla greedy decode of the target (the
    speculative_generate contract), now on the serving path."""
    from seldon_core_tpu.models.speculative import SpeculativeGenerator

    unit = SpeculativeGenerator(
        vocab=48, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_new_tokens=10, k=3, dtype="float32")
    st = unit.init_state(jax.random.key(0))
    prompts = np.random.default_rng(4).integers(0, 48, size=(2, 6))
    ref = np.asarray(generate(
        st["target"], jnp.asarray(prompts, jnp.int32), unit.target_cfg,
        max_new_tokens=10))
    srv = GenServer(**unit.continuous_spec(st), block_size=4,
                    num_blocks=64, slots=4, span=3, prefill_chunk=4)
    try:
        got = srv.submit(prompts.astype(float)).future.result(timeout=240)
        np.testing.assert_array_equal(got, ref)
        assert srv.snapshot()["mode"] == "speculative"
        assert srv.snapshot()["steps_total"].get("spec", 0) > 0
    finally:
        srv.stop()


# -- admission / retirement / exhaustion -------------------------------------


def test_admission_is_fifo_and_respects_slots(params):
    """With one slot, requests serve strictly in arrival order."""
    prompts = np.random.default_rng(5).integers(0, 48, size=(3, 4))
    ref = np.asarray(generate(params, jnp.asarray(prompts, jnp.int32),
                              CFG, max_new_tokens=6))
    srv = _server(params, slots=1, max_new_tokens=6)
    try:
        reqs = [srv.submit(prompts[i:i + 1].astype(float))
                for i in range(3)]
        done_order = []
        for i, r in enumerate(reqs):
            r.future.result(timeout=180)
            done_order.append(i)
        assert done_order == [0, 1, 2]
        for i, r in enumerate(reqs):
            np.testing.assert_array_equal(
                r.future.result(), ref[i:i + 1])
        assert srv.snapshot()["admitted_total"] == 3
    finally:
        srv.stop()


def test_pool_exhaustion_queues_not_crashes(params):
    """A pool that can hold ~one sequence: the second request WAITS for
    the first retirement's freed blocks, then serves correctly."""
    prompts = np.random.default_rng(6).integers(0, 48, size=(2, 5))
    ref = np.asarray(generate(params, jnp.asarray(prompts, jnp.int32),
                              CFG, max_new_tokens=8))
    srv = _server(params, num_blocks=8, max_new_tokens=8)  # 7 usable
    try:
        r1 = srv.submit(prompts[:1].astype(float))
        r2 = srv.submit(prompts[1:].astype(float))
        np.testing.assert_array_equal(
            r1.future.result(timeout=180), ref[:1])
        np.testing.assert_array_equal(
            r2.future.result(timeout=180), ref[1:])
        s = _settle(srv)
        assert s["kv_blocks"]["used"] == 0
    finally:
        srv.stop()


def test_preemption_under_pressure_recomputes_and_leaks_nothing(params):
    """A pool too small for two full sequences forces decode-round
    eviction (preempt-youngest, recompute-on-readmit).  The preempted
    sequence must resume EXACTLY where it stopped (outputs still equal
    one-shot generate), and — the regression this test pins — the
    capacity pass must not touch sequences an earlier row's eviction
    already removed from the batch: that stale iteration used to
    allocate blocks onto the WAITING victim, which _admit later
    overwrote, leaking pool blocks permanently (used > 0 with zero live
    sequences)."""
    prompts = np.random.default_rng(13).integers(0, 48, size=(2, 4))
    ref = np.asarray(generate(params, jnp.asarray(prompts, jnp.int32),
                              CFG, max_new_tokens=8))
    # each sequence eventually needs 6 blocks of 2; capacity 8 holds
    # both admissions but not both full lengths -> eviction mid-decode
    srv = _server(params, block_size=2, num_blocks=9, span=4,
                  prefill_chunk=4, max_new_tokens=8)
    try:
        r1 = srv.submit(prompts[:1].astype(float))
        r2 = srv.submit(prompts[1:].astype(float))
        np.testing.assert_array_equal(
            r1.future.result(timeout=180), ref[:1])
        np.testing.assert_array_equal(
            r2.future.result(timeout=180), ref[1:])
        s = _settle(srv)
        assert s["preempted_total"] >= 1   # the pressure was real
        assert s["kv_blocks"]["used"] == 0  # nothing leaked
    finally:
        srv.stop()


def test_double_preemption_does_not_duplicate_context(params):
    """_preempt rebuilds the recompute prompt from the ORIGINAL prompt +
    emitted tokens: folding into the already-folded prompt would
    duplicate context the second time the same sequence is evicted
    (preempt-youngest keeps picking the freshest readmission, so double
    preemption is the common case under sustained pressure)."""
    from seldon_core_tpu.runtime.genserver import GenRequest, _Sequence

    srv = _server(params)
    try:
        req = GenRequest(1, None, 10)
        seq = _Sequence(0, req, 0, np.arange(5, dtype=np.int32), 10)
        srv._active.append(seq)
        seq.emitted = [7, 8]
        srv._preempt(seq)
        np.testing.assert_array_equal(seq.prompt, [0, 1, 2, 3, 4, 7])
        assert seq.pending == 8
        srv._waiting.remove(seq)      # "readmit" and emit one more token
        srv._active.append(seq)
        seq.emitted = [7, 8, 9]
        srv._preempt(seq)
        np.testing.assert_array_equal(seq.prompt, [0, 1, 2, 3, 4, 7, 8])
        assert seq.pending == 9
        srv._waiting.remove(seq)
        assert srv.snapshot()["retired_total"].get("preempted", 0) == 2
    finally:
        srv.stop()


def test_impossible_request_fails_typed_not_deadlocks(params):
    """A request whose FIRST prefill chunk cannot ever fit fails with a
    clear error instead of deadlocking the queue."""
    srv = _server(params, num_blocks=2, prefill_chunk=8)  # 1 usable block
    try:
        req = srv.submit(np.zeros((1, 8)))
        with pytest.raises(RuntimeError, match="KV pool"):
            req.future.result(timeout=60)
    finally:
        srv.stop()


def test_overlong_prompt_fails_typed_not_livelocks(params):
    """A prompt whose FIRST chunk fits (so admission succeeds) but whose
    full length exceeds the whole pool must fail typed once prefill runs
    out of victims to evict — not loop admit -> prefill -> requeue
    forever at full device utilization (a client-controlled hot-spin)."""
    srv = _server(params, num_blocks=4)   # 3 usable blocks = 12 positions
    try:
        req = srv.submit(np.zeros((1, 20)))
        with pytest.raises(RuntimeError, match="KV pool"):
            req.future.result(timeout=60)
        s = _settle(srv)
        assert s["kv_blocks"]["used"] == 0
    finally:
        srv.stop()


def test_sampled_uses_per_sequence_keys(params):
    """temperature>0: valid tokens, repeated identical prompts draw
    different continuations (per-sequence keys), co-batching cannot
    couple requests."""
    prompt = np.random.default_rng(7).integers(0, 48, size=(1, 5))
    srv = _server(params, temperature=1.0, max_new_tokens=8)
    try:
        a = srv.submit(prompt.astype(float)).future.result(timeout=180)
        b = srv.submit(prompt.astype(float)).future.result(timeout=180)
        for t in (a, b):
            assert (t >= 0).all() and (t < 48).all()
        assert (a != b).any()
    finally:
        srv.stop()


def test_stream_cancel_frees_blocks(params):
    """Abandoning a stream mid-flight retires its sequences and frees
    their KV blocks (the SSE-disconnect path)."""
    prompt = np.random.default_rng(8).integers(0, 48, size=(1, 5))
    srv = _server(params, max_new_tokens=64, span=2)
    try:
        it = srv.stream(prompt.astype(float), chunk=2)
        next(it)          # first chunk arrived — stream is live
        it.close()        # client went away
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            s = srv.snapshot()
            if s["retired_total"].get("cancelled", 0) and (
                s["kv_blocks"]["used"] == 0
            ):
                break
            time.sleep(0.02)
        s = srv.snapshot()
        assert s["retired_total"].get("cancelled", 0) == 1
        assert s["kv_blocks"]["used"] == 0
    finally:
        srv.stop()


# -- batched prefill / adaptive chunk ----------------------------------------


def test_prefill_batches_across_sequences(params):
    """Co-arriving long prompts prefill TOGETHER: one batched dispatch
    advances every prefilling sequence each tick, so N prompts of ~c
    chunks cost ~c ticks, not N*c serialized dispatches (16 co-arriving
    512-token prompts at chunk 128 are 4 ticks, not 64 — on a
    dispatch-latency relay that difference IS the TTFT p50)."""
    rng = np.random.default_rng(9)
    long_p = rng.integers(0, 48, size=(2, 16))
    short_p = rng.integers(0, 48, size=(2, 13))
    ref_l = np.asarray(generate(params, jnp.asarray(long_p, jnp.int32),
                                CFG, max_new_tokens=6))
    ref_s = np.asarray(generate(params, jnp.asarray(short_p, jnp.int32),
                                CFG, max_new_tokens=6))
    srv = _server(params, max_new_tokens=6)
    try:
        reqs = [srv.submit(p[None].astype(float))
                for p in (long_p[0], long_p[1], short_p[0], short_p[1])]
        outs = [r.future.result(timeout=180) for r in reqs]
        np.testing.assert_array_equal(np.concatenate(outs[:2]), ref_l)
        np.testing.assert_array_equal(np.concatenate(outs[2:]), ref_s)
        s = _settle(srv)
        # rows enter/leave the prefill batch at different ticks (13- vs
        # 16-token prompts at chunk 4) and per-row start/width diverge —
        # the batched program must stay per-row exact (asserted above)
        # while the tick count stays ~the LONGEST prompt's chunk count:
        # 4 chunks + admission-stagger slack.  One-sequence-per-tick
        # serialization would need 16.
        pf_ticks = (s["steps_total"].get("prefill", 0)
                    + s["steps_total"].get("mixed", 0))
        assert pf_ticks <= 8, s["steps_total"]
    finally:
        srv.stop()


def test_adaptive_chunk_probe_and_latch(params, monkeypatch):
    """The dispatch-latency-aware chunk policy, deterministically: probe
    upward while doubling the width leaves the tick wall <1.6x (the
    relay's round-trip dominates, so wider chunks are ~free TTFT), shrink
    back and LATCH the first time compute dominates; floor is the
    configured interleave grain, ceiling is PREFILL_CHUNK_MAX."""
    monkeypatch.setenv("SELDON_TPU_GEN_PREFILL_CHUNK_MAX", "32")
    srv = _server(params, prefill_chunk=4)
    try:
        assert srv.prefill_chunk_max == 32
        srv._adapt_chunk(4, 0.100)     # evidence rule: >=2 ticks at a
        assert srv._chunk_eff == 4     # width before any move
        srv._adapt_chunk(4, 0.100)
        assert srv._chunk_eff == 8     # dispatch-bound: probe up
        srv._adapt_chunk(8, 0.105)
        srv._adapt_chunk(8, 0.105)
        assert srv._chunk_eff == 16    # doubling was ~free: keep probing
        srv._adapt_chunk(16, 0.400)
        srv._adapt_chunk(16, 0.400)    # >1.6x the width-8 wall: compute
        assert srv._chunk_eff == 8     # dominates — shrink and latch
        assert srv._chunk_latched
        srv._adapt_chunk(8, 0.050)
        assert srv._chunk_eff == 8     # latched: no further probing
        assert srv.snapshot()["prefill_chunk_effective"] == 8
    finally:
        srv.stop()


def test_unsaturated_ticks_never_adapt(params, monkeypatch):
    """Prompts narrower than the current chunk say nothing about width-C
    compute and would compile wider executables for nothing — only
    SATURATED ticks feed the adaptive policy."""
    monkeypatch.setenv("SELDON_TPU_GEN_PREFILL_CHUNK_MAX", "32")
    srv = _server(params, prefill_chunk=8, max_new_tokens=4)
    try:
        prompt = np.random.default_rng(10).integers(0, 48, size=(1, 5))
        srv.submit(prompt.astype(float)).future.result(timeout=180)
        assert srv._chunk_wall == {}   # no saturated tick was recorded
        assert srv._chunk_eff == 8
    finally:
        srv.stop()


def test_chunk_growth_midflight_stays_exact(params, monkeypatch):
    """The effective chunk can widen BETWEEN ticks of one prompt's
    prefill (two saturated chunk-4 ticks probe to 8 mid-prompt);
    per-row start/width keep the output token-identical."""
    monkeypatch.setenv("SELDON_TPU_GEN_PREFILL_CHUNK_MAX", "8")
    prompt = np.random.default_rng(12).integers(0, 48, size=(1, 32))
    ref = np.asarray(generate(params, jnp.asarray(prompt, jnp.int32),
                              CFG, max_new_tokens=6))
    srv = _server(params, prefill_chunk=4, max_new_tokens=6)
    try:
        got = srv.submit(prompt.astype(float)).future.result(timeout=180)
        np.testing.assert_array_equal(got, ref)
        # grew to 8 while dispatch-bound, or latched back to the floor if
        # this box's width-8 compute dominated — either way exactness held
        assert srv.snapshot()["prefill_chunk_effective"] in (4, 8)
    finally:
        srv.stop()


# -- engine integration ------------------------------------------------------


def _gen_spec(max_new=8):
    from seldon_core_tpu.graph.spec import SeldonDeploymentSpec

    return SeldonDeploymentSpec.from_json_dict({
        "spec": {"name": "cg", "predictors": [{
            "name": "p",
            "graph": {"name": "g", "type": "MODEL"},
            "components": [{
                "name": "g", "runtime": "inprocess",
                "class_path": "TransformerGenerator",
                "parameters": [
                    {"name": "vocab", "value": "48", "type": "INT"},
                    {"name": "d_model", "value": "32", "type": "INT"},
                    {"name": "n_heads", "value": "4", "type": "INT"},
                    {"name": "n_layers", "value": "2", "type": "INT"},
                    {"name": "d_ff", "value": "64", "type": "INT"},
                    {"name": "max_new_tokens", "value": str(max_new),
                     "type": "INT"},
                    {"name": "dtype", "value": "float32", "type": "STRING"},
                ],
            }],
        }]}
    })


def test_engine_serves_through_genserver():
    """Default-on: a generator engine routes unary predict through the
    GenLane (continuous scheduler), /stats exposes the scheduler block,
    and streams concatenate to the unary output."""
    import asyncio

    from seldon_core_tpu.runtime.engine import EngineService

    engine = EngineService(_gen_spec())
    assert engine.genserver is not None
    assert engine.can_stream()
    payload = json.dumps({"data": {"ndarray": [[3, 1, 4, 1, 5]]}})

    async def run():
        text, status = await engine.predict_json(payload)
        assert status == 200
        full = np.asarray(json.loads(text)["data"]["ndarray"])
        chunks = []
        async for event in engine.generate_stream(payload, chunk=3):
            doc = json.loads(event)
            if doc["done"]:
                break
            chunks.append(np.asarray(doc["tokens"]))
        np.testing.assert_array_equal(
            np.concatenate(chunks, axis=1), full)
        stats = engine.stats()
        assert stats["genserver"]["admitted_total"] >= 2
        assert stats["batcher"]["mode"] == "genserver"
        await engine.close()

    asyncio.run(run())


def test_kill_switch_restores_static_path(monkeypatch):
    """SELDON_TPU_GEN_CONTINUOUS=0: no scheduler, the MicroBatcher path
    serves exactly as before."""
    import asyncio

    from seldon_core_tpu.runtime.batching import MicroBatcher
    from seldon_core_tpu.runtime.engine import EngineService

    monkeypatch.setenv("SELDON_TPU_GEN_CONTINUOUS", "0")
    engine = EngineService(_gen_spec())
    assert engine.genserver is None
    assert isinstance(engine.batcher, MicroBatcher)
    assert engine.can_stream()  # stream_tokens static path

    async def run():
        text, status = await engine.predict_json(
            json.dumps({"data": {"ndarray": [[3, 1, 4, 1, 5]]}}))
        assert status == 200
        assert np.asarray(
            json.loads(text)["data"]["ndarray"]).shape == (1, 8)

    asyncio.run(run())


def test_gen_metric_families_exported():
    """The seldon_tpu_gen_* families are real exported metrics (the
    grafana/alert honesty test resolves names through the same table)."""
    from seldon_core_tpu.utils.telemetry import (
        RECORDER,
        TPU_METRIC_FAMILIES,
    )

    for fam in (
        "seldon_tpu_gen_inflight_sequences",
        "seldon_tpu_gen_waiting_sequences",
        "seldon_tpu_gen_kv_blocks",
        "seldon_tpu_gen_admitted_total",
        "seldon_tpu_gen_retired_total",
        "seldon_tpu_gen_steps_total",
    ):
        assert fam in TPU_METRIC_FAMILIES
    RECORDER.set_gen_scheduler(inflight=2, waiting=1, blocks_used=5,
                               blocks_total=63, blocks_high_water=9)
    RECORDER.record_gen_step("decode")
    snap = RECORDER.snapshot()["generation"]["continuous"]
    assert snap["scheduler"]["blocks_used"] == 5
    assert snap["steps"].get("decode", 0) >= 1
    if RECORDER.registry is not None:
        text = RECORDER.exposition().decode()
        assert "seldon_tpu_gen_kv_blocks" in text
        assert 'state="high_water"' in text
