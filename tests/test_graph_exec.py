"""Graph execution tests — host interpreter + compiled executor.

Mirrors the reference's engine unit tests (AverageCombinerTest.java,
RandomABTestUnitTest.java, SimpleModelUnitTest.java) plus compiled/host
parity checks the reference has no equivalent of."""

import asyncio
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from seldon_core_tpu.graph.compiled import CompiledGraph
from seldon_core_tpu.graph.interpreter import GraphExecutor
from seldon_core_tpu.graph.spec import (
    GraphSpecError,
    SeldonDeploymentSpec,
)
from seldon_core_tpu.graph.units import Unit, UnitAux, register_unit
from seldon_core_tpu.messages import Feedback, SeldonMessage


# ---------------------------------------------------------------------------
# test fixtures: tiny pure units
# ---------------------------------------------------------------------------


@register_unit("test.Scale")
class ScaleUnit(Unit):
    def __init__(self, factor: float = 2.0):
        self.factor = factor

    def predict(self, state, X):
        return X * self.factor


@register_unit("test.AddTag")
class AddTagUnit(Unit):
    """Transformer that tags the batch mean (outlier-detector shape)."""

    def transform_input(self, state, X):
        return X, UnitAux(tags={"batch_mean": jnp.mean(X)})


@register_unit("test.CountingRouter")
class CountingRouter(Unit):
    """Feedback-counting router: routes to argmax of per-branch reward."""

    def __init__(self, n_branches: int = 2):
        self.n = n_branches

    def init_state(self, rng):
        return {
            "rewards": jnp.zeros((self.n,), jnp.float32),
            "counts": jnp.zeros((self.n,), jnp.float32),
        }

    def route(self, state, X):
        return jnp.argmax(state["rewards"]).astype(jnp.int32)

    def send_feedback(self, state, X, branch, reward, truth):
        onehot = jax.nn.one_hot(branch, self.n, dtype=jnp.float32)
        return {
            "rewards": state["rewards"] + onehot * reward,
            "counts": state["counts"] + onehot,
        }


def graph_json(graph, components=None):
    spec = {
        "spec": {
            "name": "t",
            "predictors": [
                {"name": "p", "graph": graph, "components": components or []}
            ],
        }
    }
    return SeldonDeploymentSpec.from_json_dict(spec)


def run(coro):
    return asyncio.get_event_loop_policy().new_event_loop().run_until_complete(coro)


# ---------------------------------------------------------------------------
# host interpreter
# ---------------------------------------------------------------------------


def test_simple_model_host():
    """SIMPLE_MODEL stub returns [0.1, 0.9, 0.5] / class0..2
    (engine SimpleModelUnitTest.java:43-119)."""
    spec = graph_json({"name": "m", "implementation": "SIMPLE_MODEL", "type": "MODEL"})
    ex = GraphExecutor(spec.predictor())
    req = SeldonMessage.from_array(np.zeros((2, 4)))
    req.meta.puid = "pp"
    resp = run(ex.predict(req))
    np.testing.assert_allclose(resp.array(), [[0.1, 0.9, 0.5]] * 2, atol=1e-6)
    assert resp.names() == ["class0", "class1", "class2"]
    assert resp.meta.puid == "pp"
    assert resp.status.status == "SUCCESS"


def test_average_combiner_host():
    """Mean over children (engine AverageCombinerTest.java:41-228)."""
    g = {
        "name": "comb",
        "type": "COMBINER",
        "implementation": "AVERAGE_COMBINER",
        "children": [
            {"name": "s1", "type": "MODEL"},
            {"name": "s2", "type": "MODEL"},
        ],
    }
    comps = [
        {"name": "s1", "runtime": "inprocess", "class_path": "test.Scale",
         "parameters": [{"name": "factor", "value": "2.0", "type": "FLOAT"}]},
        {"name": "s2", "runtime": "inprocess", "class_path": "test.Scale",
         "parameters": [{"name": "factor", "value": "4.0", "type": "FLOAT"}]},
    ]
    ex = GraphExecutor(graph_json(g, comps).predictor())
    resp = run(ex.predict(SeldonMessage.from_array(np.ones((1, 3)))))
    np.testing.assert_allclose(resp.array(), [[3.0, 3.0, 3.0]], atol=1e-6)


def test_abtest_routing_deterministic_host():
    """Seeded AB test is deterministic and records meta.routing
    (engine RandomABTestUnitTest.java:42-103)."""
    g = {
        "name": "ab",
        "implementation": "RANDOM_ABTEST",
        "type": "ROUTER",
        "parameters": [{"name": "ratioA", "value": "0.5", "type": "FLOAT"}],
        "children": [
            {"name": "s1", "type": "MODEL"},
            {"name": "s2", "type": "MODEL"},
        ],
    }
    comps = [
        {"name": "s1", "runtime": "inprocess", "class_path": "test.Scale",
         "parameters": [{"name": "factor", "value": "1.0", "type": "FLOAT"}]},
        {"name": "s2", "runtime": "inprocess", "class_path": "test.Scale",
         "parameters": [{"name": "factor", "value": "-1.0", "type": "FLOAT"}]},
    ]

    def route_seq(seed):
        ex = GraphExecutor(graph_json(g, comps).predictor(), rng=jax.random.key(seed))
        seq = []
        for _ in range(20):
            resp = run(ex.predict(SeldonMessage.from_array(np.ones((1, 2)))))
            seq.append(resp.meta.routing["ab"])
        return seq

    s_a, s_b = route_seq(7), route_seq(7)
    assert s_a == s_b  # deterministic for fixed seed
    assert set(s_a) == {0, 1}  # both branches exercised at ratio 0.5


def test_tags_merge_host():
    g = {
        "name": "outlier",
        "type": "TRANSFORMER",
        "children": [{"name": "m", "type": "MODEL"}],
    }
    comps = [
        {"name": "outlier", "runtime": "inprocess", "class_path": "test.AddTag"},
        {"name": "m", "runtime": "inprocess", "class_path": "test.Scale"},
    ]
    ex = GraphExecutor(graph_json(g, comps).predictor())
    resp = run(ex.predict(SeldonMessage.from_array(np.full((1, 2), 3.0))))
    assert resp.meta.tags["batch_mean"] == pytest.approx(3.0)
    np.testing.assert_allclose(resp.array(), [[6.0, 6.0]], atol=1e-6)


def test_feedback_routed_branch_only_host():
    """Feedback replays meta.routing: only the serving branch trains
    (engine PredictiveUnitBean.java:141-149)."""
    g = {
        "name": "r",
        "type": "ROUTER",
        "children": [
            {"name": "s1", "type": "MODEL"},
            {"name": "s2", "type": "MODEL"},
        ],
    }
    comps = [
        {"name": "r", "runtime": "inprocess", "class_path": "test.CountingRouter"},
        {"name": "s1", "runtime": "inprocess", "class_path": "test.Scale"},
        {"name": "s2", "runtime": "inprocess", "class_path": "test.Scale"},
    ]
    ex = GraphExecutor(graph_json(g, comps).predictor())
    req = SeldonMessage.from_array(np.ones((1, 2)))
    resp = run(ex.predict(req))
    assert resp.meta.routing["r"] == 0  # argmax of zeros -> 0

    fb = Feedback(request=req, response=resp, reward=5.0)
    run(ex.send_feedback(fb))
    state = ex.states()["r"]
    np.testing.assert_allclose(state["rewards"], [5.0, 0.0])
    np.testing.assert_allclose(state["counts"], [1.0, 0.0])

    # reward on branch 0 keeps routing there; feedback with routing=1 trains s2
    resp.meta.routing["r"] = 1
    run(ex.send_feedback(Feedback(request=req, response=resp, reward=9.0)))
    state = ex.states()["r"]
    np.testing.assert_allclose(state["rewards"], [5.0, 9.0])
    resp2 = run(ex.predict(req))
    assert resp2.meta.routing["r"] == 1  # learned preference


def test_mismatched_combiner_shapes_host():
    g = {
        "name": "comb",
        "implementation": "AVERAGE_COMBINER",
        "type": "COMBINER",
        "children": [
            {"name": "s1", "type": "MODEL"},
            {"name": "sm", "implementation": "SIMPLE_MODEL", "type": "MODEL"},
        ],
    }
    comps = [{"name": "s1", "runtime": "inprocess", "class_path": "test.Scale"}]
    ex = GraphExecutor(graph_json(g, comps).predictor())
    with pytest.raises(GraphSpecError, match="shapes differ"):
        run(ex.predict(SeldonMessage.from_array(np.ones((1, 2)))))


# ---------------------------------------------------------------------------
# compiled executor
# ---------------------------------------------------------------------------


def test_compiled_combiner_matches_host():
    g = {
        "name": "comb",
        "implementation": "AVERAGE_COMBINER",
        "type": "COMBINER",
        "children": [
            {"name": "s1", "type": "MODEL"},
            {"name": "s2", "type": "MODEL"},
        ],
    }
    comps = [
        {"name": "s1", "runtime": "inprocess", "class_path": "test.Scale",
         "parameters": [{"name": "factor", "value": "2.0", "type": "FLOAT"}]},
        {"name": "s2", "runtime": "inprocess", "class_path": "test.Scale",
         "parameters": [{"name": "factor", "value": "4.0", "type": "FLOAT"}]},
    ]
    pred = graph_json(g, comps).predictor()
    cg = CompiledGraph(pred)
    x = np.random.default_rng(0).normal(size=(8, 16)).astype(np.float32)
    y, routing, tags = cg.predict_arrays(x)
    np.testing.assert_allclose(np.asarray(y), x * 3.0, rtol=1e-6)
    assert routing == {} and tags == {}

    host = GraphExecutor(pred)
    resp = run(host.predict(SeldonMessage.from_array(x)))
    np.testing.assert_allclose(np.asarray(y), resp.array(), rtol=1e-6)


def test_compiled_abtest_parity_with_host():
    """Same seed => identical routing decisions in compiled and host mode."""
    g = {
        "name": "ab",
        "implementation": "RANDOM_ABTEST",
        "type": "ROUTER",
        "parameters": [{"name": "ratioA", "value": "0.4", "type": "FLOAT"}],
        "children": [
            {"name": "s1", "type": "MODEL"},
            {"name": "s2", "type": "MODEL"},
        ],
    }
    comps = [
        {"name": "s1", "runtime": "inprocess", "class_path": "test.Scale",
         "parameters": [{"name": "factor", "value": "1.0", "type": "FLOAT"}]},
        {"name": "s2", "runtime": "inprocess", "class_path": "test.Scale",
         "parameters": [{"name": "factor", "value": "-1.0", "type": "FLOAT"}]},
    ]
    pred = graph_json(g, comps).predictor()
    x = np.ones((1, 2), np.float32)

    cg = CompiledGraph(pred, rng=jax.random.key(3))
    compiled_seq = [cg.predict_arrays(x)[1]["ab"] for _ in range(12)]

    host = GraphExecutor(pred, rng=jax.random.key(3))
    host_seq = [
        run(host.predict(SeldonMessage.from_array(x))).meta.routing["ab"]
        for _ in range(12)
    ]
    assert compiled_seq == host_seq
    assert set(compiled_seq) == {0, 1}


def test_compiled_routing_executes_single_branch():
    """Outputs match the routed child exactly (lax.switch semantics)."""
    g = {
        "name": "r",
        "type": "ROUTER",
        "children": [
            {"name": "s1", "type": "MODEL"},
            {"name": "s2", "type": "MODEL"},
        ],
    }
    comps = [
        {"name": "r", "runtime": "inprocess", "class_path": "test.CountingRouter"},
        {"name": "s1", "runtime": "inprocess", "class_path": "test.Scale",
         "parameters": [{"name": "factor", "value": "10.0", "type": "FLOAT"}]},
        {"name": "s2", "runtime": "inprocess", "class_path": "test.Scale",
         "parameters": [{"name": "factor", "value": "-10.0", "type": "FLOAT"}]},
    ]
    cg = CompiledGraph(graph_json(g, comps).predictor())
    x = np.ones((2, 2), np.float32)
    y, routing, _ = cg.predict_arrays(x)
    assert routing == {"r": 0}
    np.testing.assert_allclose(np.asarray(y), x * 10.0)

    # on-device feedback flips the learned preference to branch 1
    cg.feedback_arrays(x, {"r": 1}, reward=7.0)
    y2, routing2, _ = cg.predict_arrays(x)
    assert routing2 == {"r": 1}
    np.testing.assert_allclose(np.asarray(y2), x * -10.0)
    np.testing.assert_allclose(np.asarray(cg.states["r"]["rewards"]), [0.0, 7.0])


def test_compiled_tags_flow():
    g = {
        "name": "outlier",
        "type": "TRANSFORMER",
        "children": [{"name": "m", "type": "MODEL"}],
    }
    comps = [
        {"name": "outlier", "runtime": "inprocess", "class_path": "test.AddTag"},
        {"name": "m", "runtime": "inprocess", "class_path": "test.Scale"},
    ]
    cg = CompiledGraph(graph_json(g, comps).predictor())
    y, _, tags = cg.predict_arrays(np.full((1, 4), 2.0, np.float32))
    assert float(tags["batch_mean"]) == pytest.approx(2.0)
    np.testing.assert_allclose(np.asarray(y), [[4.0] * 4])


def test_compiled_message_api():
    spec = graph_json({"name": "m", "implementation": "SIMPLE_MODEL", "type": "MODEL"})
    cg = CompiledGraph(spec.predictor())
    req = SeldonMessage.from_json('{"data":{"ndarray":[[0,0]]},"meta":{"puid":"x"}}')
    resp = cg.predict(req)
    assert resp.meta.puid == "x"
    assert resp.names() == ["class0", "class1", "class2"]
    d = json.loads(resp.to_json())
    assert d["data"]["ndarray"] == [[pytest.approx(0.1), pytest.approx(0.9), pytest.approx(0.5)]]


@register_unit("test.BadRouter")
class BadRouter(Unit):
    """Returns an out-of-range branch."""

    def __init__(self, branch: int = 5):
        self.branch = branch

    def route(self, state, X):
        return jnp.int32(self.branch)


@pytest.mark.parametrize("bad_branch", [5, -2])
def test_invalid_branch_raises_both_modes(bad_branch):
    """Out-of-range and negative (non-broadcast) branches raise in BOTH
    execution modes instead of silently picking a child."""
    g = {
        "name": "r",
        "type": "ROUTER",
        "children": [
            {"name": "s1", "type": "MODEL"},
            {"name": "s2", "type": "MODEL"},
        ],
    }
    comps = [
        {"name": "r", "runtime": "inprocess", "class_path": "test.BadRouter",
         "parameters": [{"name": "branch", "value": str(bad_branch), "type": "INT"}]},
        {"name": "s1", "runtime": "inprocess", "class_path": "test.Scale"},
        {"name": "s2", "runtime": "inprocess", "class_path": "test.Scale"},
    ]
    pred = graph_json(g, comps).predictor()
    with pytest.raises(GraphSpecError, match="children"):
        run(GraphExecutor(pred).predict(SeldonMessage.from_array(np.ones((1, 2)))))
    with pytest.raises(GraphSpecError, match="children"):
        CompiledGraph(pred).predict_arrays(np.ones((1, 2), np.float32))


@register_unit("test.NamedModel")
class NamedModel(Unit):
    def __init__(self, label: str = "x", factor: float = 1.0):
        self.class_names = [f"{label}:0", f"{label}:1"]
        self.factor = factor

    def predict(self, state, X):
        return X[:, :2] * self.factor


def test_compiled_output_names_follow_routing():
    """data.names come from the unit that served the request, per-mode parity
    (review finding: first-in-walk-order names mislabel routed responses)."""
    g = {
        "name": "r",
        "type": "ROUTER",
        "children": [
            {"name": "a", "type": "MODEL"},
            {"name": "b", "type": "MODEL"},
        ],
    }
    comps = [
        {"name": "r", "runtime": "inprocess", "class_path": "test.CountingRouter"},
        {"name": "a", "runtime": "inprocess", "class_path": "test.NamedModel",
         "parameters": [{"name": "label", "value": "a", "type": "STRING"}]},
        {"name": "b", "runtime": "inprocess", "class_path": "test.NamedModel",
         "parameters": [{"name": "label", "value": "b", "type": "STRING"}]},
    ]
    pred = graph_json(g, comps).predictor()
    cg = CompiledGraph(pred)
    x = np.ones((1, 2), np.float32)
    resp = cg.predict(SeldonMessage.from_array(x))
    assert resp.names() == ["a:0", "a:1"]  # routed to branch 0
    cg.feedback_arrays(x, {"r": 1}, reward=5.0)
    resp = cg.predict(SeldonMessage.from_array(x))
    assert resp.names() == ["b:0", "b:1"]  # now routed to branch 1


def test_compiled_rejects_remote_nodes():
    g = {"name": "m", "type": "MODEL"}
    comps = [{"name": "m", "runtime": "grpc", "image": "x:1"}]
    with pytest.raises(GraphSpecError, match="in-process"):
        CompiledGraph(graph_json(g, comps).predictor())
