"""Round-5 measurement/docs infrastructure: the docs-sync drift gate
must actually gate, the toolchain ledger must mirror the conformance
skip conditions, and the timing fence must fetch the smallest leaf."""

import importlib.util
import os
import shutil
import subprocess
import sys

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name):
    """Import a scripts/*.py module hermetically (no cwd / sys.path
    dependence — scripts/ is not a package)."""
    spec = importlib.util.spec_from_file_location(
        f"_r5_{name}", os.path.join(ROOT, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_docs_sync_check_passes_then_catches_drift(tmp_path):
    """--check is clean on the committed tree; corrupting a generated
    figure must flip it to a non-zero exit (the CI gate's contract)."""
    out = subprocess.run(
        [sys.executable, "scripts/docs_sync.py", "--check"],
        capture_output=True, text=True, cwd=ROOT,
    )
    assert out.returncode == 0, out.stderr
    # drift in a scratch copy of the repo docs
    work = tmp_path / "repo"
    (work / "docs").mkdir(parents=True)
    (work / "scripts").mkdir()
    for rel in ("docs/benchmarking.md", "PARITY.md", "scripts/docs_sync.py"):
        shutil.copy(os.path.join(ROOT, rel), work / rel)
    art = sorted(
        p for p in os.listdir(ROOT)
        if p.startswith("BENCH_r") and p.endswith("_full.json")
    )[-1]
    shutil.copy(os.path.join(ROOT, art), work / art)
    doc = (work / "docs" / "benchmarking.md").read_text()
    # corrupt a digit INSIDE the generated block
    start = doc.index("BEGIN GENERATED")
    end = doc.index("END GENERATED")
    block = doc[start:end]
    for ch in "0123456789":
        if ch in block:
            block2 = block.replace(ch, "9" if ch != "9" else "8", 1)
            break
    (work / "docs" / "benchmarking.md").write_text(
        doc[:start] + block2 + doc[end:])
    out = subprocess.run(
        [sys.executable, "scripts/docs_sync.py", "--check"],
        capture_output=True, text=True, cwd=work,
    )
    assert out.returncode == 1
    assert "DRIFT" in out.stderr


def test_docs_sync_artifact_numeric_round_order(tmp_path, monkeypatch):
    """BENCH_r9 vs BENCH_r10: the newest round must win numerically,
    not lexicographically."""
    docs_sync = _load_script("docs_sync")
    for name in ("BENCH_r9_full.json", "BENCH_r10_full.json"):
        (tmp_path / name).write_text("{}")
    monkeypatch.setattr(docs_sync, "ROOT", str(tmp_path))
    assert docs_sync._artifact().endswith("BENCH_r10_full.json")


def test_toolchain_probe_mirrors_conformance_gate(monkeypatch):
    """java_lane_runnable must equal the test gate's condition (javac AND
    java on PATH), so the ledger never misattributes a skip.  Every
    scenario stubs _run and the bazel-JRE glob so no subprocess spawns
    and no host state leaks in."""
    tp = _load_script("toolchain_probe")
    monkeypatch.setattr(
        tp, "_run", lambda cmd, timeout=30: (0, "openjdk 21\njava.base"))
    monkeypatch.setattr(tp.glob, "glob", lambda pat: [])

    def which(names):
        return lambda exe: f"/usr/bin/{exe}" if exe in names else None

    monkeypatch.setattr(tp.shutil, "which", which({"javac"}))
    doc = tp.probe()
    assert doc["java_lane_runnable"] is False
    assert "java" in doc["conformance_expected_skips"]

    monkeypatch.setattr(tp.shutil, "which", which({"javac", "java"}))
    doc = tp.probe()
    assert doc["java_lane_runnable"] is True
    assert "java" not in doc["conformance_expected_skips"]

    monkeypatch.setattr(tp.shutil, "which", which({"Rscript", "R"}))
    doc = tp.probe()
    assert doc["r_lane_runnable"] is True
    assert "r" not in doc["conformance_expected_skips"]


def test_fence_fetches_smallest_leaf_only():
    """fetch_sync must MATERIALIZE exactly the smallest leaf (the cheap
    fence) — observed through recording leaves, so a regression to
    max(), to no-fetch, or to fetch-everything fails here."""
    from seldon_core_tpu.utils.fence import fetch_sync

    fetched = []

    class FakeLeaf:
        def __init__(self, name, size):
            self.name = name
            self.size = size
            self.dtype = np.float32  # looks array-like to np.asarray

        def __array__(self, dtype=None, copy=None):
            fetched.append(self.name)
            return np.zeros((1,), np.float32)

    big = FakeLeaf("big", 4096)
    small = FakeLeaf("small", 2)
    out = fetch_sync({"a": big, "b": (small, big)})
    assert fetched == ["small"]
    assert out["b"][0] is small
