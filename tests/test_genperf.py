"""Generation-lane flight recorder (utils/genperf.py): bubble-ledger
arithmetic on a hand-timed fake clock, the host+device+bubble ≈ wall
accounting identity, phase-split residuals, the served-decode null
guards, ``GET /genperf`` on both REST lanes, the per-sequence lifecycle
timeline joining the causal trace, tick-error visibility, and the
kill-switch contract (all observatories off => ZERO ring writes from a
full scheduler run)."""

import asyncio
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from seldon_core_tpu.graph.spec import SeldonDeploymentSpec
from seldon_core_tpu.models.transformer import LMConfig, lm_init
from seldon_core_tpu.runtime.engine import EngineService
from seldon_core_tpu.runtime.genserver import GenServer
from seldon_core_tpu.utils.genperf import BUBBLE_CAUSES, GENPERF
from seldon_core_tpu.utils.hotrecord import SPINE
from seldon_core_tpu.utils.perf import OBSERVATORY
from seldon_core_tpu.utils.quality import QUALITY
from seldon_core_tpu.utils.telemetry import RECORDER, TPU_METRIC_FAMILIES
from seldon_core_tpu.utils.tracing import (
    TRACER,
    TraceContext,
    new_span_id,
    new_trace_id,
    trace_scope,
)

CFG = LMConfig(vocab=48, d_model=32, n_heads=4, n_layers=2, d_ff=64,
               dtype=jnp.float32)


@pytest.fixture(scope="module")
def params():
    return lm_init(jax.random.key(3), CFG)


@pytest.fixture(autouse=True)
def _clean():
    SPINE.drain()
    SPINE.reset()
    GENPERF.reset()
    TRACER.clear()
    yield
    SPINE.drain()
    SPINE.reset()
    GENPERF.reset()
    TRACER.clear()


def _server(params, **kw):
    kw.setdefault("max_new_tokens", 10)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_blocks", 64)
    kw.setdefault("slots", 8)
    kw.setdefault("span", 3)
    kw.setdefault("prefill_chunk", 4)
    return GenServer(params, kw.pop("cfg", CFG), **kw)


def _settle(srv, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        s = srv.snapshot()
        if not s["inflight_sequences"] and not s["waiting_sequences"]:
            return s
        time.sleep(0.01)
    raise AssertionError("scheduler did not settle")


def deployment():
    return SeldonDeploymentSpec.from_json_dict(
        {"spec": {"name": "genperf-dep", "predictors": [{
            "name": "p",
            "graph": {"name": "m", "implementation": "SIMPLE_MODEL",
                      "type": "MODEL"},
        }]}}
    )


# -- bubble-ledger arithmetic (hand-timed fake clock) ------------------------


def test_bubble_ledger_arithmetic_fake_clock():
    """Four hand-timed ticks, one bubble per cause: the ledger must
    reproduce the exact per-cause sums and the exact bubble fraction —
    no measurement noise, pure arithmetic."""
    GENPERF.observe_tick("decode", {
        "wall_s": 0.010, "device_s": 0.006,
        "bubble_s": 0.002, "bubble_cause": "host"})
    GENPERF.observe_tick("decode", {
        "wall_s": 0.010, "device_s": 0.004,
        "bubble_s": 0.003, "bubble_cause": "admission_stall"})
    GENPERF.observe_tick("prefill", {
        "wall_s": 0.020, "device_s": 0.015,
        "bubble_s": 0.001, "bubble_cause": "pool_exhaustion"})
    GENPERF.observe_tick("idle", {
        "wall_s": 0.005, "bubble_s": 0.004, "bubble_cause": "idle"})
    doc = GENPERF.document()
    by_cause = doc["bubbles"]["by_cause_s"]
    assert by_cause == {"host": 0.002, "admission_stall": 0.003,
                        "pool_exhaustion": 0.001, "idle": 0.004}
    assert doc["bubbles"]["by_cause_ticks"] == {
        "host": 1, "admission_stall": 1, "pool_exhaustion": 1, "idle": 1}
    assert set(by_cause) <= set(BUBBLE_CAUSES)
    # wall 0.045, bubble 0.010 -> fraction 0.010 / 0.055
    assert doc["bubbles"]["fraction"] == round(0.010 / 0.055, 4)
    assert doc["ticks"] == {"decode": 2, "prefill": 1, "idle": 1}
    # idle duty cycle: 0.005 of the 0.055 total scheduler wall
    assert doc["idle"]["ticks"] == 1
    assert doc["idle"]["duty_cycle"] == round(0.005 / 0.055, 4)


def test_accounting_identity_host_device_bubble_covers_wall():
    """host := wall - device and bubble := inter-tick gap, so the
    ledger accounts for scheduler wall BY CONSTRUCTION — the demo
    artifact's >= 95 % criterion checks the wiring, not luck."""
    GENPERF.observe_tick("decode", {
        "wall_s": 0.012, "device_s": 0.009,
        "bubble_s": 0.001, "bubble_cause": "host"})
    GENPERF.observe_tick("mixed", {"wall_s": 0.030, "device_s": 0.022})
    acct = GENPERF.document()["accounting"]
    assert acct["scheduler_wall_s"] == round(0.012 + 0.030 + 0.001, 4)
    assert acct["host_s"] == round(0.003 + 0.008, 4)
    assert acct["device_s"] == round(0.009 + 0.022, 4)
    assert acct["bubble_s"] == 0.001
    assert acct["accounted_fraction"] == 1.0


def test_phase_split_residual_lands_in_host_other():
    """Named phases get their fenced device time subtracted; tick wall
    not covered by any named phase shows up as host_other, never
    disappears."""
    GENPERF.observe_tick("decode", {
        "wall_s": 0.010, "device_s": 0.005,
        "phases": {"admit": 0.002, "decode": 0.006},
        "device_phases": {"decode": 0.005}})
    ph = GENPERF.document()["phases"]
    assert ph["host_s"]["decode/admit"] == 0.002
    assert ph["host_s"]["decode/decode"] == round(0.006 - 0.005, 4)
    assert ph["device_s"]["decode/decode"] == 0.005
    assert ph["host_s"]["decode/host_other"] == round(0.010 - 0.008, 4)


def test_served_decode_null_guard_without_cost_features(monkeypatch):
    """No registered decode-step cost features (perf observatory off or
    scheduler never initialized a device): the MFU/BW figures are None,
    never a KeyError — but the raw token/throughput accounting stays."""
    monkeypatch.setattr(OBSERVATORY, "enabled", False)
    GENPERF.observe_tick("decode", {
        "wall_s": 0.010, "device_s": 0.004, "tokens": 8, "steps": 3,
        "real_rows": 2, "rows": 4,
        "device_phases": {"decode": 0.004}, "phases": {"decode": 0.004}})
    served = GENPERF.document()["served_decode"]
    assert served["served_decode_mfu_pct"] is None
    assert served["served_decode_hbm_bw_util_pct"] is None
    assert served["real_tokens"] == 8
    assert served["served_decode_tok_s_device"] == round(8 / 0.004, 1)


def test_tick_error_counter_and_family():
    assert "seldon_tpu_gen_tick_errors_total" in TPU_METRIC_FAMILIES
    before = RECORDER.gen_tick_errors
    GENPERF.observe_tick_error()
    RECORDER.record_gen_tick_error()
    assert GENPERF.document()["tick_errors_total"] == 1
    assert RECORDER.gen_tick_errors == before + 1


# -- the real scheduler feeding the recorder ---------------------------------


def test_scheduler_run_accounts_for_wall(params):
    """A real (CPU) scheduler run: every tick lands in the recorder via
    the spine's off-path drainer, the accounting identity holds, and
    KV block ages appear at retirement."""
    srv = _server(params)
    try:
        reqs = [srv.submit(np.full((1, 5), i + 1.0)) for i in range(4)]
        for r in reqs:
            r.future.result(timeout=30)
        _settle(srv)
    finally:
        srv.stop()
    SPINE.drain()
    doc = GENPERF.document()
    assert sum(doc["ticks"].values()) > 0
    assert doc["accounting"]["accounted_fraction"] >= 0.95
    assert doc["rows"]["real_total"] > 0
    assert doc["rows"]["real_fraction"] <= 1.0
    assert doc["kv"]["blocks_released_total"] > 0
    assert doc["kv"]["block_age_s"]["count"] > 0
    # the scheduler registered analytic decode-step costs at device init
    assert OBSERVATORY.cost_features("gen_decode_step") is not None
    assert doc["served_decode"]["real_tokens"] > 0


def test_idle_ticks_accounted(params):
    """Satellite: idle spins are explicit — a tick that wakes but runs
    no prefill/decode work (here: the cancel-drop path) lands in
    steps_total['idle'] and /genperf carries an idle duty-cycle
    figure instead of silence."""
    srv = _server(params)
    try:
        req = srv.submit(np.full((1, 5), 3.0))
        req.cancel()        # dropped at the next tick's _drop_cancelled
        try:
            req.future.result(timeout=30)
        except Exception:
            pass            # cancellation may resolve or fail the future
        deadline = time.monotonic() + 10
        while srv.snapshot()["steps_total"].get("idle", 0) == 0 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        snap = srv.snapshot()
    finally:
        srv.stop()
    SPINE.drain()
    assert snap["steps_total"].get("idle", 0) > 0
    doc = GENPERF.document()
    assert doc["idle"]["ticks"] > 0
    assert doc["idle"]["duty_cycle"] is not None


def test_tick_error_path_visible(params, monkeypatch):
    """Satellite: a raising tick is COUNTED (snapshot + recorder +
    /genperf), not silently retried forever."""
    srv = _server(params)
    before = RECORDER.gen_tick_errors

    def boom():
        raise RuntimeError("injected tick failure")

    try:
        monkeypatch.setattr(srv, "_admit", boom)
        req = srv.submit(np.full((1, 5), 2.0))
        with pytest.raises(Exception):
            req.future.result(timeout=30)
        deadline = time.monotonic() + 10
        while srv.snapshot()["tick_errors_total"] == 0 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        snap = srv.snapshot()
    finally:
        srv.stop()
    assert snap["tick_errors_total"] >= 1
    assert RECORDER.gen_tick_errors > before
    assert GENPERF.document()["tick_errors_total"] >= 1


def test_sequence_timeline_joins_causal_trace(params, monkeypatch):
    """Per-sequence lifecycle (enqueue -> admit -> prefill chunks ->
    decode rounds -> retire) is emitted as ONE gen_sequence span into
    the SAME trace tree as the submitting request."""
    monkeypatch.setattr(TRACER, "enabled", True)
    ctx = TraceContext(trace_id=new_trace_id(), span_id=new_span_id(),
                       sampled=True, puid="p-genperf")
    srv = _server(params)
    try:
        with trace_scope(ctx):
            req = srv.submit(np.full((1, 6), 4.0))
        req.future.result(timeout=30)
        _settle(srv)
    finally:
        srv.stop()
    SPINE.drain()
    spans = TRACER.by_trace(ctx.trace_id)
    seq_spans = [s for s in spans if s.name == "gen_sequence"]
    assert len(seq_spans) == 1, [s.name for s in spans]
    span = seq_spans[0]
    assert span.kind == "gen_seq"
    assert span.parent_span_id == ctx.span_id
    assert span.puid == "p-genperf"
    names = [e["name"] for e in span.events]
    assert names[0] == "enqueue"
    assert "admit" in names
    assert "prefill_chunk" in names
    assert "decode_round" in names
    assert names[-1] == "retire"
    # events are monotonically timestamped — a timeline, not a bag
    stamps = [e["ts"] for e in span.events]
    assert stamps == sorted(stamps)


def test_kill_switches_leave_zero_ring_writes(params, monkeypatch):
    """SELDON_TPU_TELEMETRY=0 + trace/perf/quality off: a FULL scheduler
    run performs ZERO ring writes and the recorder sees ZERO ticks —
    the flight recorder costs nothing when turned off."""
    monkeypatch.setattr(SPINE, "telemetry_enabled", False)
    monkeypatch.setattr(TRACER, "enabled", False)
    monkeypatch.setattr(OBSERVATORY, "enabled", False)
    monkeypatch.setattr(QUALITY, "enabled", False)
    # the cost ledger (on by default) rides the same tick records —
    # cut it too or its WANT_COST payloads keep the ring warm
    monkeypatch.setenv("SELDON_TPU_COSTLEDGER", "0")
    writes = {"n": 0}
    real_append = SPINE._append

    def counting_append(rec):
        writes["n"] += 1
        return real_append(rec)

    monkeypatch.setattr(SPINE, "_append", counting_append)
    srv = _server(params)
    try:
        srv.submit(np.full((1, 5), 5.0)).future.result(timeout=30)
        _settle(srv)
    finally:
        srv.stop()
    SPINE.drain()
    assert writes["n"] == 0
    assert GENPERF.document()["ticks"] == {}


def test_gen_continuous_kill_switch_keeps_genperf_empty(monkeypatch):
    """SELDON_TPU_GEN_CONTINUOUS=0: no scheduler exists, so /genperf
    reports scheduler: null and an empty recorder — and serving still
    works (the static-path contract lives in test_genserver)."""
    monkeypatch.setenv("SELDON_TPU_GEN_CONTINUOUS", "0")
    engine = EngineService(deployment())
    doc = engine.genperf_document()
    assert doc["scheduler"] is None
    assert doc["adaptive_chunk"] is None
    assert doc["ticks"] == {}
    assert doc["served_decode"]["served_decode_mfu_pct"] is None


# -- the REST surfaces -------------------------------------------------------


def test_genperf_endpoint_on_both_lanes():
    from aiohttp.test_utils import TestClient, TestServer

    from seldon_core_tpu.runtime.rest import make_engine_app

    engine = EngineService(deployment())

    async def run():
        async with TestClient(TestServer(make_engine_app(engine))) as client:
            r = await client.get("/genperf")
            assert r.status == 200
            doc = await r.json()
            assert doc["engine"]["deployment"] == "genperf-dep"
            assert "accounting" in doc and "bubbles" in doc
            assert "served_decode" in doc

    asyncio.run(run())

    from seldon_core_tpu.runtime.httpfast import serve_fast

    async def run_fast():
        import aiohttp

        server = await serve_fast(engine, "127.0.0.1", 0)
        try:
            async with aiohttp.ClientSession() as sess:
                async with sess.get(
                    f"http://127.0.0.1:{server.port}/genperf"
                ) as r:
                    assert r.status == 200
                    doc = await r.json()
                    assert "accounting" in doc and "bubbles" in doc
        finally:
            await server.stop()

    asyncio.run(run_fast())


def test_new_metric_families_registered():
    for fam in (
        "seldon_tpu_gen_step_seconds",
        "seldon_tpu_gen_bubble_seconds_total",
        "seldon_tpu_gen_served_mfu",
        "seldon_tpu_gen_kv_block_age_seconds",
        "seldon_tpu_gen_tick_errors_total",
    ):
        assert fam in TPU_METRIC_FAMILIES
    RECORDER.record_gen_step_seconds("decode", "decode", 0.004)
    RECORDER.record_gen_bubble("host", 0.002)
    RECORDER.record_gen_kv_block_age(1.5)
    RECORDER.set_gen_served_mfu(0.12)
    snap = RECORDER.snapshot()["generation"]["continuous"]
    assert snap["bubble_seconds"].get("host", 0) >= 0.002
    assert snap["served_mfu"] == 0.12
    if RECORDER.registry is not None:
        text = RECORDER.exposition().decode()
        assert "seldon_tpu_gen_bubble_seconds_total" in text
        assert "seldon_tpu_gen_served_mfu" in text
