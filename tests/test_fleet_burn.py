"""Fleet-truth SLO/QoS burn accounting (utils/quality.py FLEET_BURN +
gateway/federation.py burn deltas over the shared sqlite store).

The acceptance properties pinned here (ISSUE PR-18):

  * **fleet-truth engages the ladder** — two gateway replicas each burn
    below the brownout enter threshold, but their SUMMED counts exceed
    it: with federation on, every replica's brownout ladder engages off
    the fleet aggregate; with ``SELDON_TPU_FLEET_BURN=0`` nothing
    publishes, nothing folds, and each replica judges only its own ring
    (PR-17-and-earlier behaviour bit-for-bit);
  * **rollout burn gates judge the same aggregate** — GatewaySignals
    reads ``effective_burn_rate``, so a canary cannot pass on a 1/N
    slice of the fleet's burn;
  * **no burn amnesia on failover** (satellite) — the coordinator dies
    mid-burn; the successor's fold still sums the dead replica's last
    published deltas until the window they measured ages out;
  * **fail-closed staleness** — a stale fold (wedged federation loop)
    makes consumers fall back to their per-replica rings, never freeze
    a stale fleet number into decisions.
"""

import time

import pytest

from seldon_core_tpu.gateway.federation import GatewayFederation
from seldon_core_tpu.gateway.state import SqliteDeploymentStore
from seldon_core_tpu.runtime.brownout import BrownoutController
from seldon_core_tpu.utils.quality import (
    FLEET_BURN,
    QUALITY,
    SloTracker,
    effective_burn_rate,
    fleet_burn_enabled,
)


@pytest.fixture()
def db_path(tmp_path):
    return str(tmp_path / "gateway.db")


@pytest.fixture(autouse=True)
def _slo(monkeypatch):
    """A configured latency SLO on the process-global tracker, restored
    (with clean rings) afterwards."""
    saved_p99, saved_err = QUALITY.slo.p99_ms, QUALITY.slo.error_rate
    QUALITY.slo.p99_ms = 10.0
    QUALITY.slo.error_rate = None
    yield
    QUALITY.slo.p99_ms, QUALITY.slo.error_rate = saved_p99, saved_err
    QUALITY.slo.reset_events()
    FLEET_BURN.clear()


def _burn_locally(slow_fraction, total=100, now=None):
    """Feed the process-global SLO ring a window with the given slow
    fraction (p99 objective 10ms => slow = latency > 10ms)."""
    now = now if now is not None else time.time()
    slow = int(total * slow_fraction)
    for i in range(total):
        QUALITY.slo.record(
            0.050 if i < slow else 0.001, now=now)


class _Gov:
    """Stand-in TenantGovernor: cumulative throttle/shed counters."""

    def __init__(self, throttled=0, shed=0):
        self._t, self._s = throttled, shed

    def burn_totals(self):
        return {"acme": {"throttled": self._t, "shed": self._s}}


# ---------------------------------------------------------------------------
# the delta table (gateway/state.py)
# ---------------------------------------------------------------------------


def test_publish_burn_upserts_and_burn_rows_reads_all_replicas(db_path):
    s = SqliteDeploymentStore(db_path)
    s.publish_burn("gw-a", [("_global", "5m", 100, 5, 0, 2, 1)])
    s.publish_burn("gw-b", [("_global", "5m", 50, 0, 0, 0, 0)])
    s.publish_burn("gw-a", [("_global", "5m", 120, 6, 0, 2, 1)])  # upsert
    rows = s.burn_rows()
    assert len(rows) == 2
    by_replica = {r["replica_id"]: r for r in rows}
    assert by_replica["gw-a"]["total"] == 120  # absolute, not summed
    assert by_replica["gw-a"]["slow"] == 6
    assert by_replica["gw-b"]["total"] == 50


def test_burn_rows_age_filter(db_path):
    s = SqliteDeploymentStore(db_path)
    s.publish_burn("gw-a", [("_global", "5m", 10, 1, 0, 0, 0)])
    assert len(s.burn_rows(max_age_s=60.0)) == 1
    assert len(s.burn_rows(max_age_s=0.0)) == 0


# ---------------------------------------------------------------------------
# the 2-replica acceptance: fleet aggregate engages, per-replica does not
# ---------------------------------------------------------------------------


def _two_replicas(db_path):
    store_a = SqliteDeploymentStore(db_path)
    store_b = SqliteDeploymentStore(db_path)
    fed_a = GatewayFederation(store_a, "gw-a", ttl_s=5.0)
    fed_b = GatewayFederation(store_b, "gw-b", ttl_s=5.0)
    fed_a.governor = _Gov(throttled=3, shed=1)
    fed_b.governor = _Gov()
    return fed_a, fed_b


def test_fleet_aggregate_exceeds_what_each_replica_sees(db_path):
    """Each replica's local 5m burn is ~1.2x (12% slow over a 1% budget
    ... scaled: 1.2% slow / 0.01 budget = 1.2) — below the ladder's
    enter threshold of 2.0.  The sum (2.4% slow over the combined
    total... same fraction) — the REAL fleet case is replicas burning
    on DIFFERENT requests: here replica B publishes counts from its own
    (simulated) ring, so the fold sums 1.2% + strictly more slow
    traffic and the aggregate crosses 2.0 while each local view reads
    1.2."""
    fed_a, fed_b = _two_replicas(db_path)
    # replica A's local ring: 1.2% slow of 1000 => burn 1.2 (< 2.0)
    _burn_locally(0.012, total=1000)
    assert QUALITY.slo.burn_rates()["5m"]["burn_rate"] == pytest.approx(
        1.2, abs=0.05)
    # replica B published heavier counts (its own process's ring — we
    # inject the delta directly, as its tick would)
    fed_b.store.publish_burn(
        "gw-b", [("_global", "5m", 1000, 40, 0, 0, 0)])
    fed_a.tick()   # publishes A's delta, folds both
    assert fed_a._burn_publishes == 1 and fed_a._burn_folds == 1
    snap = FLEET_BURN.snapshot()
    assert snap["fresh"]
    view = snap["view"]
    assert set(view["replicas"]) == {"gw-a", "gw-b"}
    # fleet: (12 + 40) slow / 2000 total = 2.6% over 1% budget = 2.6
    assert view["windows"]["5m"]["burn_rate"] == pytest.approx(
        2.6, abs=0.1)
    assert view["windows"]["5m"]["throttled"] == 3  # A's QoS totals
    assert view["windows"]["5m"]["shed"] == 1
    # effective = max(local 1.2, fleet 2.6)
    assert effective_burn_rate("5m") == pytest.approx(2.6, abs=0.1)


def test_brownout_ladder_engages_on_fleet_not_on_local(db_path):
    fed_a, fed_b = _two_replicas(db_path)
    _burn_locally(0.012, total=1000)
    fed_b.store.publish_burn(
        "gw-b", [("_global", "5m", 1000, 40, 0, 0, 0)])

    ladder = BrownoutController(enter_burn=2.0, enter_depth=0.0,
                                dwell_s=0.0)
    # before any fold: local burn 1.2 / enter 2.0 => pressure < 1, calm
    ladder.tick()
    assert ladder.stage() == 0
    fed_a.tick()
    ladder.tick()
    assert ladder.stage() == 1   # fleet 2.6 / 2.0 => severity 1
    assert ladder.snapshot()["signals"]["burn_5m"] == pytest.approx(
        2.6, abs=0.1)


def test_kill_switch_restores_per_replica_behaviour(db_path, monkeypatch):
    monkeypatch.setenv("SELDON_TPU_FLEET_BURN", "0")
    assert not fleet_burn_enabled()
    fed_a, fed_b = _two_replicas(db_path)
    _burn_locally(0.012, total=1000)
    fed_b.store.publish_burn(
        "gw-b", [("_global", "5m", 1000, 40, 0, 0, 0)])
    fed_a.tick()
    assert fed_a._burn_publishes == 0   # kill switch: no publish, no fold
    assert fed_a.store.burn_rows() == [
        r for r in fed_a.store.burn_rows() if r["replica_id"] == "gw-b"
    ]
    # consumers read the local ring only
    assert effective_burn_rate("5m") == pytest.approx(1.2, abs=0.05)
    ladder = BrownoutController(enter_burn=2.0, enter_depth=0.0,
                                dwell_s=0.0)
    ladder.tick()
    assert ladder.stage() == 0


def test_rollout_burn_gate_reads_the_same_aggregate(db_path):
    """GatewaySignals' burn figure IS effective_burn_rate — with a
    fresh fleet fold the canary gate judges 2.6, not its local 1.2."""
    from seldon_core_tpu.operator.rollouts import GatewaySignals

    fed_a, fed_b = _two_replicas(db_path)
    _burn_locally(0.012, total=1000)
    fed_b.store.publish_burn(
        "gw-b", [("_global", "5m", 1000, 40, 0, 0, 0)])
    fed_a.tick()

    class _Shadow:
        def disagreement_rate(self, _):
            return None

    class _Gateway:
        shadow = _Shadow()

        def predictor_traffic(self, _dep, _pred):
            return 100, 0

    class _Plan:
        deployment, candidate = "dep", "candidate"

    out = GatewaySignals(_Gateway())(_Plan())
    assert out["burn_rate"] == pytest.approx(2.6, abs=0.1)


# ---------------------------------------------------------------------------
# failover continuity (satellite): no burn amnesia
# ---------------------------------------------------------------------------


def test_successor_fold_keeps_dead_replicas_last_deltas(db_path):
    """Kill the coordinator mid-burn: its last published counts keep
    feeding every successor's fold until the 5m window they measured
    has fully aged out — burned budget cannot be amnesia'd away by a
    crash."""
    fed_a, fed_b = _two_replicas(db_path)
    _burn_locally(0.012, total=1000)
    assert fed_a.tick()   # A is coordinator and published its delta
    # A dies. Nothing removes its burn_deltas row. B folds regardless of
    # who holds the coordinator lease — burn is not a singleton duty.
    fed_b.tick()
    view = FLEET_BURN.snapshot()["view"]
    assert "gw-a" in view["replicas"]     # the dead replica still counts
    assert view["folded_by"] == "gw-b"
    # B's fold sums A's last counts: 12 slow / 1000 = 1.2% => burn 1.2
    # PLUS B's own (empty governor, shared process ring also 1.2% — the
    # rows are per-replica in the STORE, so A's and B's both sum)
    assert view["windows"]["5m"]["requests"] >= 1000
    assert view["windows"]["5m"]["burn_rate"] >= 1.0


def test_aged_out_deltas_stop_counting(db_path):
    """A replica dead longer than the window span no longer feeds the
    fold — stale history must not pin the fleet at a burn it has
    outlived."""
    s = SqliteDeploymentStore(db_path)
    fed = GatewayFederation(s, "gw-live", ttl_s=5.0)
    _burn_locally(0.001, total=1000)   # live replica: calm
    # a dead replica's row, stamped 10 minutes ago (past the 5m span)
    s.publish_burn("gw-dead", [("_global", "5m", 1000, 500, 0, 0, 0)])
    import sqlite3

    with sqlite3.connect(db_path) as conn:
        conn.execute("UPDATE burn_deltas SET updated = updated - 600 "
                     "WHERE replica_id = 'gw-dead'")
    fed.tick()
    view = FLEET_BURN.snapshot()["view"]
    assert "gw-dead" not in view["replicas"]
    assert view["windows"]["5m"]["burn_rate"] < 1.0


# ---------------------------------------------------------------------------
# staleness fail-closed
# ---------------------------------------------------------------------------


def test_stale_fold_degrades_to_local_ring(db_path, monkeypatch):
    fed_a, fed_b = _two_replicas(db_path)
    _burn_locally(0.012, total=1000)
    fed_b.store.publish_burn(
        "gw-b", [("_global", "5m", 1000, 40, 0, 0, 0)])
    fed_a.tick()
    assert effective_burn_rate("5m") == pytest.approx(2.6, abs=0.1)
    # the federation loop wedges: the last fold ages past the bound
    monkeypatch.setenv("SELDON_TPU_FLEET_BURN_STALE_S", "0.05")
    time.sleep(0.06)
    assert not FLEET_BURN.fresh()
    assert effective_burn_rate("5m") == pytest.approx(1.2, abs=0.05)


def test_per_tenant_deltas_publish_and_fold(db_path):
    fed_a, _fed_b = _two_replicas(db_path)
    _burn_locally(0.012, total=200)
    QUALITY.record_tenant_request("acme", 0.050, now=time.time())
    try:
        fed_a.tick()
        view = FLEET_BURN.snapshot()["view"]
        assert "acme" in view["tenants"]
        entry = view["tenants"]["acme"]["5m"]
        assert entry["requests"] == 1
        assert entry["throttled"] == 3 and entry["shed"] == 1
    finally:
        QUALITY._tenant_slo.clear()


def test_no_slo_configured_means_no_slo_burn_rows(db_path):
    """No SLO objective => no SLO burn rows and no burn-rate signal
    (the local tracker's contract) — but per-tenant ADMISSION rows
    (synthetic "admission" window, PR-19) still publish while a
    governor is live: admission truth does not require an SLO."""
    QUALITY.slo.p99_ms = None
    QUALITY.slo.error_rate = None
    fed_a, _ = _two_replicas(db_path)
    fed_a.tick()
    rows = fed_a.store.burn_rows()
    assert rows and all(r["window"] == "admission" for r in rows)
    assert effective_burn_rate("5m") is None
    # admission folds into the fleet view without any SLO math
    view = FLEET_BURN.snapshot()["view"]
    assert view["windows"] == {}
    assert view["tenants"]["acme"]["admission"]["throttled"] == 3


def test_no_governor_and_no_slo_publishes_nothing(db_path):
    QUALITY.slo.p99_ms = None
    QUALITY.slo.error_rate = None
    store = SqliteDeploymentStore(db_path)
    fed = GatewayFederation(store, "gw-solo", ttl_s=5.0)
    fed.tick()
    assert fed._burn_publishes == 0
    assert fed.store.burn_rows() == []


def test_admission_rows_fold_fleet_wide(db_path):
    """Two replicas admitting the same tenant: /fleet's admission view
    sums requests/throttled/shed across replicas — the fleet-wide
    per-tenant admission rate ROADMAP's QoS tail asked for."""
    fed_a, fed_b = _two_replicas(db_path)
    fed_b.governor = _Gov(throttled=2, shed=0)
    _burn_locally(0.012, total=200)
    fed_a.tick()
    fed_b.tick()
    adm = FLEET_BURN.snapshot()["view"]["tenants"]["acme"]["admission"]
    assert adm["throttled"] == 3 + 2
    assert adm["shed"] == 1 + 0
    # _Gov publishes no request counter; real governors do
    assert adm["requests"] == 0
