"""Tabular model families (mean classifier, sigmoid predictor, min-max
transformer, boosted oblivious trees) + a smoke test that every example
deployment JSON in examples/ parses and serves a prediction."""

import asyncio
import json
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from seldon_core_tpu.graph.compiled import CompiledGraph
from seldon_core_tpu.graph.spec import SeldonDeploymentSpec
from seldon_core_tpu.messages import SeldonMessage
from seldon_core_tpu.models.tabular import (
    MeanClassifier,
    MeanTransformer,
    ObliviousTreeEnsemble,
    SigmoidPredictor,
)
from seldon_core_tpu.runtime.engine import EngineService

EXAMPLES = pathlib.Path(__file__).resolve().parent.parent / "examples"


def test_mean_classifier_semantics():
    u = MeanClassifier(threshold=1.0)
    st = u.init_state(None)
    X = jnp.asarray([[1.0, 1.0], [3.0, 5.0]])
    y = np.asarray(u.predict(st, X))
    assert y.shape == (2, 1)
    assert y[0, 0] == pytest.approx(0.5)          # mean 1.0 == threshold
    assert y[1, 0] == pytest.approx(1 / (1 + np.exp(-3.0)))  # mean 4.0


def test_sigmoid_predictor_learns_task():
    u = SigmoidPredictor(train_steps=300, seed=0)
    st = u.init_state(jax.random.key(0))
    rng = np.random.default_rng(42)
    X = rng.normal(size=(512, 10)).astype(np.float32)
    y_true = (1 / (1 + np.exp(-X[:, 0] * X[:, 1])) >= 0.5).astype(int)
    probs = np.asarray(u.predict(st, jnp.asarray(X)))
    assert probs.shape == (512, 2)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-5)
    acc = ((probs[:, 1] > 0.5).astype(int) == y_true).mean()
    assert acc > 0.8, f"sigmoid predictor failed to learn: acc={acc}"


def test_mean_transformer_minmax_and_constant_batch():
    u = MeanTransformer()
    X = jnp.asarray([[0.0, 5.0], [10.0, 2.5]])
    out = np.asarray(u.transform_input(None, X))
    np.testing.assert_allclose(out, [[0.0, 0.5], [1.0, 0.25]], atol=1e-6)
    const = np.asarray(u.transform_input(None, jnp.full((3, 4), 7.0)))
    np.testing.assert_array_equal(const, np.zeros((3, 4)))


def test_oblivious_trees_beat_base_predictor():
    u = ObliviousTreeEnsemble(n_trees=16, depth=3, seed=0)
    st = u.init_state(None)
    # held-out sample of the same synthetic task
    rng = np.random.default_rng(99)
    X = rng.normal(size=(512, 8))
    y = np.sin(X[:, 0]) + 0.5 * X[:, 1] * (X[:, 2] > 0)
    pred = np.asarray(jax.jit(u.predict)(st, jnp.asarray(X)))[:, 0]
    mse_model = float(np.mean((pred - y) ** 2))
    mse_base = float(np.mean((float(st["base"]) - y) ** 2))
    assert pred.shape == (512,)
    assert mse_model < 0.5 * mse_base, (mse_model, mse_base)


def test_batch_coupled_unit_disables_request_coalescing():
    """A graph containing MeanTransformer must not micro-batch: one
    caller's rows would shift another caller's min/max."""
    spec = SeldonDeploymentSpec.from_json(
        (EXAMPLES / "mean_transformer_deployment.json").read_text()
    )
    engine = EngineService(spec)
    assert engine.batcher is None
    # and a plain model graph still batches
    mnist = SeldonDeploymentSpec.from_json(
        (EXAMPLES / "mnist_deployment.json").read_text()
    )
    assert EngineService(mnist).batcher is not None


def test_oblivious_trees_compile_into_graph():
    g = {"name": "gbm", "type": "MODEL"}
    comps = [{
        "name": "gbm", "runtime": "inprocess",
        "class_path": "ObliviousTreeEnsemble",
        "parameters": [{"name": "n_trees", "value": "8", "type": "INT"}],
    }]
    spec = SeldonDeploymentSpec.from_json_dict(
        {"spec": {"name": "g", "predictors": [
            {"name": "p", "graph": g, "components": comps}]}}
    )
    cg = CompiledGraph(spec.predictor())
    y, _, _ = cg.predict_arrays(np.zeros((4, 8), np.float32))
    assert np.asarray(y).shape == (4, 1)


_EXAMPLE_FEATURES = {
    "iris_deployment.json": 4,
    "mnist_deployment.json": 784,
    "epsilon_greedy_deployment.json": 784,
    "ensemble4_deployment.json": 784,
    "outlier_pipeline_deployment.json": 784,
    "canary_deployment.json": 784,
    "mean_transformer_deployment.json": 6,
    "gbm_deployment.json": 8,
    "generator_deployment.json": 5,  # 5-token prompts -> generated tokens
    "stub_deployment.json": 1,  # the reference's max-throughput stub graph
    "generator_tp_deployment.json": 5,  # tp=4 mesh-sharded LM generator
    "generator_ep_deployment.json": 5,  # ep=4 MoE expert-parallel generator
    "generator_int8_deployment.json": 4,  # int8 + GQA + flash opt-ins
    "speculative_deployment.json": 5,  # draft/verify generation opt-in
    # shared-prefix KV cache + eos stop handling opt-ins
    "generator_prefix_deployment.json": 4,
}


@pytest.mark.parametrize("fname", sorted(_EXAMPLE_FEATURES))
def test_every_example_deployment_serves(fname):
    path = EXAMPLES / fname
    assert path.exists(), f"example listed but missing: {fname}"
    spec = SeldonDeploymentSpec.from_json(path.read_text())
    n = _EXAMPLE_FEATURES[fname]
    x = np.random.default_rng(0).normal(size=(2, n)).tolist()
    msg = SeldonMessage.from_json(json.dumps({"data": {"ndarray": x}}))
    for p in spec.predictors:
        engine = EngineService(spec, p.name)
        resp = asyncio.run(engine.predict(msg))
        assert resp.status is None or resp.status.status != "FAILURE", (
            fname, p.name, resp.status)
        arr = np.asarray(resp.data.array)
        assert arr.shape[0] == 2 and np.isfinite(arr).all(), (fname, p.name)


def test_example_dir_has_no_untested_deployments():
    on_disk = {p.name for p in EXAMPLES.glob("*_deployment.json")}
    assert on_disk == set(_EXAMPLE_FEATURES), (
        "keep _EXAMPLE_FEATURES in sync with examples/"
    )
