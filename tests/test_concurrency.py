"""Concurrency stress: the reference's only flagged race was bandit-state
ordering (RandomABTestUnit.java:49 FIXME); here state lives in device
buffers updated through the engine's lock/pipeline discipline, and these
tests pin that concurrent traffic cannot lose updates or corrupt state.

  * feedback vs feedback: N concurrent send_feedback calls must all land
    (tries counts sum to N — lost-update check).
  * predict vs feedback: pipelined predict dispatches skip their state
    write-back, so a slow in-flight predict must not clobber a feedback
    update that raced past it.
  * drain: /pause flips readiness while in-flight requests complete.
"""

import asyncio
import json

import numpy as np

from seldon_core_tpu.graph.spec import SeldonDeploymentSpec
from seldon_core_tpu.messages import Feedback, SeldonMessage
from seldon_core_tpu.runtime.engine import EngineService


def _bandit_spec():
    return SeldonDeploymentSpec.from_json_dict({
        "spec": {"name": "d", "predictors": [{
            "name": "p",
            "graph": {
                "name": "eg", "type": "ROUTER",
                "children": [{"name": "m0", "type": "MODEL"},
                             {"name": "m1", "type": "MODEL"}],
            },
            "components": [
                {"name": "eg", "runtime": "inprocess",
                 "class_path": "EpsilonGreedyRouter",
                 "parameters": [{"name": "n_branches", "value": "2",
                                 "type": "INT"}]},
                {"name": "m0", "runtime": "inprocess",
                 "class_path": "MnistClassifier",
                 "parameters": [{"name": "hidden", "value": "16",
                                 "type": "INT"}]},
                {"name": "m1", "runtime": "inprocess",
                 "class_path": "MnistClassifier",
                 "parameters": [{"name": "hidden", "value": "16",
                                 "type": "INT"}, {"name": "seed",
                                                  "value": "1",
                                                  "type": "INT"}]},
            ],
        }]}
    })


def _feedback(branch: int, reward: float) -> Feedback:
    fb = Feedback(
        request=SeldonMessage.from_json(
            json.dumps({"data": {"ndarray": [[0.0] * 784]}})
        ),
        response=SeldonMessage.from_json(
            json.dumps({"meta": {"routing": {"eg": branch}}})
        ),
        reward=reward,
    )
    return fb


def test_concurrent_feedback_no_lost_updates():
    engine = EngineService(_bandit_spec())
    N = 40

    async def run():
        await asyncio.gather(*[
            engine.send_feedback(_feedback(i % 2, 1.0)) for i in range(N)
        ])

    asyncio.run(run())
    tries = np.asarray(engine.compiled.states["eg"]["tries"])
    assert tries.sum() == N, tries
    np.testing.assert_allclose(tries, [N / 2, N / 2])


def test_predict_feedback_interleaving_keeps_state():
    """Pipelined predicts racing with feedback must not clobber bandit
    state (predict_arrays skips its state write-back when pipelined)."""
    engine = EngineService(_bandit_spec())
    payload = json.dumps({"data": {"ndarray": [[0.0] * 784]}})
    N = 30

    async def run():
        async def pred():
            text, status = await engine.predict_json(payload)
            assert status == 200

        async def fb(i):
            await engine.send_feedback(_feedback(i % 2, 1.0))

        await asyncio.gather(*(
            [pred() for _ in range(N)] + [fb(i) for i in range(N)]
        ))

    asyncio.run(run())
    tries = np.asarray(engine.compiled.states["eg"]["tries"])
    assert tries.sum() == N, f"lost feedback updates: {tries}"


def test_pause_drains_inflight():
    """Pre-stop drain: requests genuinely in flight when /pause lands must
    complete with 200 while /ready flips to 503 (the k8s pre-stop contract:
    curl /pause && sleep — SeldonDeploymentOperatorImpl.java:130-134)."""
    import aiohttp
    from seldon_core_tpu.runtime.rest import make_engine_app, serve_app

    engine = EngineService(_bandit_spec())
    payload = json.dumps({"data": {"ndarray": [[0.0] * 784]}})

    async def run():
        runner = await serve_app(make_engine_app(engine), "127.0.0.1", 0)
        port = runner.addresses[0][1]
        base = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as s:
                tasks = [
                    asyncio.create_task(s.post(
                        f"{base}/api/v0.1/predictions", data=payload
                    ))
                    for _ in range(8)
                ]
                await asyncio.sleep(0)  # let the requests actually start
                async with s.get(f"{base}/pause") as r:
                    assert r.status == 200
                async with s.get(f"{base}/ready") as r:
                    assert r.status == 503  # readiness gate flipped
                responses = await asyncio.gather(*tasks)
                assert all(r.status == 200 for r in responses), [
                    r.status for r in responses
                ]  # in-flight work drained, not dropped
                for r in responses:
                    r.release()
        finally:
            await runner.cleanup()

    asyncio.run(run())
